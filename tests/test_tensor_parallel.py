"""Tensor parallelism: dp×tp BERT train step vs single-device math."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.data.text import mlm_dataset, mlm_feed_tokens
from sparknet_tpu.models.bert import BertConfig, BertMLM
from sparknet_tpu.parallel.mesh import make_mesh
from sparknet_tpu.parallel.tensor import bert_param_pspecs, make_tp_train_step
from sparknet_tpu.proto.caffe_pb import SolverParameter
from sparknet_tpu.solver.caffe_solver import init_opt_state


def _cfg(dropout=0.0):
    c = BertConfig.bert_tiny(vocab_size=64)
    return type(c)(**{
        **c.__dict__, "hidden_dropout": dropout, "attention_dropout": dropout,
        "num_heads": 4,  # tp shards heads; tp=4 needs 4 of them
    })


def _solver_param():
    return SolverParameter(
        base_lr=1e-3, lr_policy="fixed", solver_type="ADAMW",
        momentum=0.9, weight_decay=0.01, max_iter=100,
    )


def _batch(b, s, vocab=64, seed=0):
    ds, vsize = mlm_dataset(vocab_size=vocab, n_tokens=8192, seq_len=s,
                            seed=seed)
    feed = mlm_feed_tokens(ds, b, vsize, seed=seed)
    return feed


def test_tp_step_matches_single_device():
    """One dp=2×tp=4 step == one single-device step on the same global
    batch (dropout off)."""
    b, s = 4, 32
    cfg = _cfg(dropout=0.0)
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    # SGD: updates are linear in grads, so sharded-vs-dense reduction
    # order can't be amplified the way Adam's rsqrt(v) does
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", solver_type="SGD",
                         momentum=0.9, weight_decay=1e-4, max_iter=100)

    # single-device baseline via token loss
    model0 = BertMLM(cfg, shapes)
    params, _ = model0.init(jax.random.PRNGKey(0))
    opt0 = init_opt_state(sp, params)
    feed = _batch(b, s)
    batch = {k: jnp.asarray(v) for k, v in next(feed).items()}

    from sparknet_tpu.solver.caffe_solver import make_update_fn, mults_for_params

    def baseline_step(params, opt, batch, it):
        def loss_fn(p):
            nll, w, corr = model0.token_loss_sums(p, {}, batch, train=True,
                                                  rng=None)
            return nll / jnp.maximum(w, 1.0), (nll, w)

        grads, _ = jax.grad(loss_fn, has_aux=True)(params)
        lr_m, dec_m = mults_for_params(params, model0.param_specs())
        return make_update_fn(sp, lr_m, dec_m)(params, grads, opt, it)

    p_base, _ = jax.jit(baseline_step)(params, opt0, batch,
                                       jnp.asarray(0, jnp.int32))

    # dp=2 x tp=4
    mesh = make_mesh({"dp": 2, "tp": 4}, jax.devices()[:8])
    model_tp = BertMLM(cfg, shapes, tp_axis="tp")
    step = make_tp_train_step(model_tp, sp, mesh, dp_axis="dp", tp_axis="tp")
    opt1 = init_opt_state(sp, params)
    p_tp, _, m = step(params, opt1, batch, jnp.asarray(0, jnp.int32),
                      jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss"]))
    for layer in p_base:
        for name in p_base[layer]:
            np.testing.assert_allclose(
                np.asarray(p_tp[layer][name]),
                np.asarray(p_base[layer][name]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"{layer}/{name}",
            )


@pytest.mark.slow
def test_tp_sp_combined_trains():
    """3-D mesh dp=2×tp=2×sp=2: ring attention on tp-sharded heads."""
    b, s = 4, 64
    cfg = _cfg(dropout=0.1)
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2}, jax.devices()[:8])
    model = BertMLM(cfg, shapes, attention_impl="ring", tp_axis="tp",
                    sp_axis="sp")
    params, _ = model.init(jax.random.PRNGKey(0))
    sp = _solver_param()
    opt = init_opt_state(sp, params)
    step = make_tp_train_step(model, sp, mesh, dp_axis="dp", tp_axis="tp",
                              sp_axis="sp")
    feed = _batch(b, s)
    # fixed batch: memorisation is a deterministic learning signal
    batch = {k: jnp.asarray(v) for k, v in next(feed).items()}
    losses = []
    rng = jax.random.PRNGKey(2)
    for it in range(10):
        rng, srng = jax.random.split(rng)
        params, opt, m = step(params, opt, batch,
                              jnp.asarray(it, jnp.int32), srng)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses


def test_param_pspecs_cover_all_params():
    cfg = _cfg()
    model = BertMLM(cfg, {"input_ids": (2, 32), "mlm_positions": (2, 4)})
    params, _ = model.init(jax.random.PRNGKey(0))
    specs = bert_param_pspecs(model)
    assert jax.tree_util.tree_structure(
        jax.tree_util.tree_map(lambda _: 0, params)
    ) == jax.tree_util.tree_structure(
        jax.tree_util.tree_map(lambda _: 0, specs,
                               is_leaf=lambda x: not isinstance(x, dict))
    )
