"""data/pipeline.py: the multiprocess input pipeline's contracts.

Determinism (the acceptance proof): the parallel feed's batch stream is
bit-identical to the serial ``ds.batches`` stream for 1, 2, and 4
workers, and ``skip(n)``-then-iterate equals iterate-then-slice.
Shutdown: close() leaves no child processes and no /dev/shm segments
(the session fixture in conftest.py re-asserts this globally after the
whole run). Errors surface at their serial stream position.
"""

import glob
import multiprocessing
import os
import time

import numpy as np
import pytest

from sparknet_tpu.data.pipeline import (
    ParallelBatchPipeline,
    PipelineMetrics,
    SHM_PREFIX,
    default_data_workers,
    resolve_data_workers,
)
from sparknet_tpu.data.rdd import ShardedDataset

pytestmark = pytest.mark.skipif(
    "fork" not in multiprocessing.get_all_start_methods(),
    reason="pipeline workers require the fork start method",
)


def _ds(n=96, parts=4):
    rng = np.random.default_rng(0)
    return ShardedDataset.from_arrays(
        {
            "data": rng.normal(size=(n, 8, 8, 3)).astype(np.float32),
            "label": np.arange(n, dtype=np.int32),
        },
        parts,
    )


def _aug(batch, r):
    # draws from the per-batch rng: catches any transform-RNG drift
    # between the serial path and a worker's
    return {
        "data": batch["data"]
        + r.normal(size=batch["data"].shape).astype(np.float32),
        "label": batch["label"],
    }


def _assert_same_stream(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


def _assert_no_leaks():
    """No stray pipeline children or shm segments right now (close()
    joins before returning, so no settling loop is needed)."""
    stray = [
        p for p in multiprocessing.active_children()
        if p.name.startswith(SHM_PREFIX)
    ]
    assert not stray, f"leaked pipeline workers: {stray}"
    if os.path.isdir("/dev/shm"):
        segs = glob.glob(f"/dev/shm/{SHM_PREFIX}_*")
        assert not segs, f"leaked shm segments: {segs}"


@pytest.mark.parametrize("workers", [1, 2, 4])
def test_parallel_feed_bit_identical_to_serial(workers):
    ds = _ds()
    serial = list(
        ds.batches(8, shuffle=True, seed=3, epochs=2, transform=_aug)
    )
    with ParallelBatchPipeline(
        ds, 8, workers=workers, shuffle=True, seed=3, epochs=2,
        transform=_aug,
    ) as pipe:
        got = list(pipe)
    _assert_same_stream(serial, got)
    _assert_no_leaks()


def test_skip_then_iterate_equals_iterate_then_slice():
    ds = _ds()
    serial = list(
        ds.batches(8, shuffle=True, seed=3, epochs=2, transform=_aug)
    )
    with ParallelBatchPipeline(
        ds, 8, workers=3, shuffle=True, seed=3, epochs=2, transform=_aug
    ) as pipe:
        pipe.skip(7)  # pre-start skip: O(1), offsets every worker
        got = [next(pipe) for _ in range(5)]
    _assert_same_stream(serial[7:12], got)

    # post-start skip degrades to consume-and-discard but stays correct
    with ParallelBatchPipeline(
        ds, 8, workers=3, shuffle=True, seed=3, epochs=2, transform=_aug
    ) as pipe:
        first = next(pipe)
        pipe.skip(4)
        after = next(pipe)
    _assert_same_stream([serial[0], serial[5]], [first, after])


def test_infinite_stream_early_close_no_leaks():
    ds = _ds()
    serial_it = ds.batches(8, shuffle=True, seed=3, transform=_aug)
    serial = [next(serial_it) for _ in range(10)]
    pipe = ParallelBatchPipeline(
        ds, 8, workers=4, shuffle=True, seed=3, transform=_aug
    )
    got = [next(pipe) for _ in range(10)]
    pipe.close()
    _assert_same_stream(serial, got)
    _assert_no_leaks()
    with pytest.raises(StopIteration):
        next(pipe)  # closed pipelines don't resurrect workers


def test_worker_error_surfaces_at_serial_position():
    ds = _ds(n=40, parts=2)

    def boom(batch, r):
        if batch["label"][0] >= 20:
            raise RuntimeError("late explosion")
        return batch

    serial_n = 0
    try:
        for _ in ds.batches(4, shuffle=False, seed=0, transform=boom):
            serial_n += 1
    except RuntimeError:
        pass

    pipe = ParallelBatchPipeline(
        ds, 4, workers=2, shuffle=False, seed=0, transform=boom
    )
    n = 0
    with pytest.raises(RuntimeError, match="late explosion"):
        for _ in pipe:
            n += 1
    assert n == serial_n  # every batch before the failure was yielded
    _assert_no_leaks()


def test_slot_overflow_falls_back_to_pickle():
    ds = _ds(n=32, parts=2)
    serial = list(
        ds.batches(8, shuffle=False, seed=0, epochs=1, transform=_aug)
    )
    # slots too small for any batch: every worker batch takes the
    # pickled-queue fallback; the stream must not change
    with ParallelBatchPipeline(
        ds, 8, workers=2, shuffle=False, seed=0, epochs=1,
        transform=_aug, slot_bytes=8,
    ) as pipe:
        got = list(pipe)
        fallbacks = pipe.metrics.shm_fallbacks
    _assert_same_stream(serial, got)
    assert fallbacks == len(serial) - 1  # all but the serial probe batch


def test_metrics_snapshot_shape_and_occupancy():
    ds = _ds()
    with ParallelBatchPipeline(
        ds, 8, workers=2, shuffle=True, seed=0, epochs=1, transform=_aug
    ) as pipe:
        n = len(list(pipe))
        snap = pipe.metrics.snapshot()
    assert snap["batches"] == n
    assert snap["rows"] == n * 8
    assert snap["shm_fallbacks"] == 0
    for stage in ("produce", "worker_wait", "consumer_wait"):
        assert set(snap[stage]) == {
            "count", "mean_ms", "p50_ms", "p95_ms", "p99_ms"
        }
    # backpressure: the reorder buffer can never exceed the slot count
    # (slots release at in-order consumption, so workers*depth bounds it)
    assert snap["reorder_depth"]["max"] <= 2 * 2
    assert isinstance(pipe.metrics.json_line(), str)


def _straggler_aug(batch, r):
    # batches owned by one residue class stall: the OTHER workers must
    # not run unboundedly ahead while the sequence waits on them
    if int(batch["label"][0]) % 3 == 0:
        time.sleep(0.05)
    return {"data": batch["data"], "label": batch["label"]}


def test_backpressure_bounded_under_straggler():
    ds = ShardedDataset.from_arrays(
        {
            "data": np.zeros((240, 4), np.float32),
            "label": np.arange(240, dtype=np.int32),
        },
        2,
    )
    with ParallelBatchPipeline(
        ds, 8, workers=3, depth=2, shuffle=False, seed=0, epochs=1,
        transform=_straggler_aug,
    ) as pipe:
        n = len(list(pipe))
        depth_max = pipe.metrics.reorder_depth.max
    assert n == 30
    assert depth_max <= 3 * 2, depth_max


def test_training_through_pipeline_bit_identical():
    """Weights after training on the parallel feed == weights after the
    serial feed (the end-to-end determinism the resume/A-B contract
    rides on); composes with prefetch_to_device like the apps do."""
    import jax

    from sparknet_tpu.data.prefetch import prefetch_to_device
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "pipe"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""
    sp_txt = 'base_lr: 0.1\nlr_policy: "fixed"\nmomentum: 0.9\nmax_iter: 6\n'
    rng = np.random.default_rng(11)
    ds = ShardedDataset.from_arrays(
        {
            "data": rng.normal(size=(48, 6)).astype(np.float32),
            "label": rng.integers(0, 3, 48).astype(np.int32),
        },
        3,
    )

    def feed(workers):
        if workers:
            return ParallelBatchPipeline(
                ds, 8, workers=workers, shuffle=True, seed=5
            )
        return ds.batches(8, shuffle=True, seed=5)

    results = []
    for workers in (0, 2):
        sp = caffe_pb.load_solver(sp_txt, is_path=False)
        sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
        solver = Solver(sp, {"data": (8, 6), "label": (8,)})
        raw = feed(workers)
        solver.step(prefetch_to_device(raw, size=2), 6)
        getattr(raw, "close", lambda: None)()
        results.append(jax.device_get(solver.params))
    a, b = results
    for layer in a:
        for name in a[layer]:
            np.testing.assert_array_equal(a[layer][name], b[layer][name])
    _assert_no_leaks()


def test_worker_count_resolution():
    assert resolve_data_workers(0) == 0
    assert resolve_data_workers(3) == 3
    env = os.environ.get("SPARKNET_DATA_WORKERS")
    try:
        os.environ["SPARKNET_DATA_WORKERS"] = "5"
        assert default_data_workers() == 5
        assert resolve_data_workers(-1) == 5
        assert resolve_data_workers(None) == 5
        os.environ["SPARKNET_DATA_WORKERS"] = "0"
        assert default_data_workers() == 0
        del os.environ["SPARKNET_DATA_WORKERS"]
        # cpu-count aware: bounded, serial on tiny hosts
        assert 0 <= default_data_workers() <= 4
    finally:
        if env is None:
            os.environ.pop("SPARKNET_DATA_WORKERS", None)
        else:
            os.environ["SPARKNET_DATA_WORKERS"] = env
    with pytest.raises(ValueError):
        ParallelBatchPipeline(_ds(), 8, workers=0)


def test_app_feed_constructor_uses_pipeline():
    """The apps' make_feed(workers=N) returns the pipeline and the
    stream equals the serial make_feed stream (the --data-workers /
    SPARKNET_DATA_WORKERS wiring, without running a whole app)."""
    from sparknet_tpu.apps.imagenet_app import make_feed
    from sparknet_tpu.data.preprocess import Transformer

    rng = np.random.default_rng(2)
    ds = ShardedDataset.from_arrays(
        {
            "data": rng.integers(0, 255, (40, 12, 12, 3)).astype(np.uint8),
            "label": np.arange(40, dtype=np.int32),
        },
        2,
    )
    tf = Transformer(crop_size=8, mirror=True, train=True, mean_values=[3.0])
    serial = make_feed(ds, tf, 8, seed=4)
    par = make_feed(ds, tf, 8, seed=4, workers=2)
    assert isinstance(par, ParallelBatchPipeline)
    try:
        a = [next(serial) for _ in range(6)]
        b = [next(par) for _ in range(6)]
    finally:
        par.close()
    _assert_same_stream(a, b)


@pytest.mark.slow
def test_bench_input_pipeline_record():
    """BENCH_MODEL=input_pipeline emits the serial-vs-parallel A/B
    record (slow: subprocess + real AlexNet-shaped preprocessing)."""
    import json
    import subprocess
    import sys

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_MODEL="input_pipeline",
        BENCH_BATCH="16",
        BENCH_ITERS="6",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=here,
    )
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "input_pipeline_images_per_sec", rec
    assert rec["value"] > 0, rec
    assert rec["serial_img_per_sec"] > 0
    assert rec["input_pipeline_workers"] >= 1
    assert "speedup_vs_serial" in rec and "pipeline_metrics" in rec
