"""Pipeline parallelism: pp (×dp) BERT training on the CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.data.text import mlm_dataset, mlm_feed_tokens
from sparknet_tpu.models.bert import BertConfig, BertMLM
from sparknet_tpu.parallel.mesh import make_mesh
from sparknet_tpu.parallel.pipeline import (
    make_pp_train_step,
    stack_layer_params,
    unstack_layer_params,
)
from sparknet_tpu.proto.caffe_pb import SolverParameter
from sparknet_tpu.solver.caffe_solver import (
    init_opt_state,
    make_update_fn,
    mults_for_params,
)


def _cfg(layers=4, dropout=0.0):
    c = BertConfig.bert_tiny(vocab_size=64)
    return type(c)(**{
        **c.__dict__, "num_layers": layers,
        "hidden_dropout": dropout, "attention_dropout": dropout,
    })


def test_stack_roundtrip():
    cfg = _cfg()
    model = BertMLM(cfg, {"input_ids": (2, 32), "mlm_positions": (2, 4)})
    params, _ = model.init(jax.random.PRNGKey(0))
    stacked, rest = stack_layer_params(params, cfg.num_layers)
    assert stacked["q_w"].shape[0] == cfg.num_layers
    back = unstack_layer_params(stacked, rest, cfg.num_layers)
    for layer in params:
        for n in params[layer]:
            np.testing.assert_array_equal(
                np.asarray(back[layer][n]), np.asarray(params[layer][n])
            )


def test_pp_step_matches_single_device():
    """pp=4 pipelined step == unpipelined step (SGD, dropout off)."""
    b, s = 4, 32
    cfg = _cfg(layers=4)
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    model = BertMLM(cfg, shapes)
    params, _ = model.init(jax.random.PRNGKey(0))
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", solver_type="SGD",
                         momentum=0.9, weight_decay=1e-4, max_iter=100)

    ds, vs = mlm_dataset(vocab_size=64, n_tokens=8192, seq_len=s)
    feed = mlm_feed_tokens(ds, b, vs, seed=0)
    batch = {k: jnp.asarray(v) for k, v in next(feed).items()}

    # baseline
    opt0 = init_opt_state(sp, params)

    def baseline_step(params, opt, batch, it):
        def loss_fn(p):
            nll, w, _ = model.token_loss_sums(p, {}, batch, train=True,
                                              rng=None)
            return nll / jnp.maximum(w, 1.0)

        grads = jax.grad(loss_fn)(params)
        lr_m, dec_m = mults_for_params(params, model.param_specs())
        return make_update_fn(sp, lr_m, dec_m)(params, grads, opt, it)

    p_base, _ = jax.jit(baseline_step)(params, opt0, batch,
                                       jnp.asarray(0, jnp.int32))

    # pipelined: pp=4, 2 microbatches
    mesh = make_mesh({"pp": 4}, jax.devices()[:4])
    stacked, rest = stack_layer_params(params, cfg.num_layers)
    pp_params = {"layers": stacked, "rest": rest}
    opt1 = init_opt_state(sp, pp_params)
    step = make_pp_train_step(model, sp, mesh, n_micro=2, pp_axis="pp")
    p_pp, _, m = step(pp_params, opt1, batch, jnp.asarray(0, jnp.int32),
                      jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss"]))

    back = unstack_layer_params(p_pp["layers"], p_pp["rest"], cfg.num_layers)
    for layer in p_base:
        for name in p_base[layer]:
            np.testing.assert_allclose(
                np.asarray(back[layer][name]),
                np.asarray(p_base[layer][name]),
                rtol=2e-4, atol=2e-5, err_msg=f"{layer}/{name}",
            )


@pytest.mark.slow
def test_pp_dp_combined_trains():
    """dp=2 × pp=4 with dropout on: loss decreases."""
    b, s = 8, 32
    cfg = _cfg(layers=4, dropout=0.1)
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    model = BertMLM(cfg, shapes)
    params, _ = model.init(jax.random.PRNGKey(0))
    sp = SolverParameter(base_lr=1e-3, lr_policy="fixed", solver_type="ADAMW",
                         momentum=0.9, weight_decay=0.01, max_iter=100)
    mesh = make_mesh({"dp": 2, "pp": 4}, jax.devices()[:8])
    stacked, rest = stack_layer_params(params, cfg.num_layers)
    pp_params = {"layers": stacked, "rest": rest}
    opt = init_opt_state(sp, pp_params)
    step = make_pp_train_step(model, sp, mesh, n_micro=2, dp_axis="dp")
    ds, vs = mlm_dataset(vocab_size=64, n_tokens=8192, seq_len=s)
    feed = mlm_feed_tokens(ds, b, vs, seed=0)
    rng = jax.random.PRNGKey(2)
    losses = []
    for it in range(30):
        batch = {k: jnp.asarray(v) for k, v in next(feed).items()}
        rng, srng = jax.random.split(rng)
        pp_params, opt, m = step(pp_params, opt, batch,
                                 jnp.asarray(it, jnp.int32), srng)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def _moe_cfg(layers=2, experts=4):
    c = BertConfig.bert_tiny(vocab_size=64)
    return type(c)(**{
        **c.__dict__, "num_layers": layers, "num_heads": 4,
        "hidden_dropout": 0.0, "attention_dropout": 0.0,
        "moe_num_experts": experts, "moe_capacity_factor": 2.0,
        "moe_dispatch": "sort",
    })


@pytest.mark.slow
def test_pp_ep_moe_step_matches_single_device():
    """pp=2 × ep=2 MoE pipelined step == a single-device step computing
    the identical per-microbatch objective (nll/w + aux_weight * mean
    over microbatches of the layer-summed router aux)."""
    b, s, n_micro = 4, 32, 2
    cfg = _moe_cfg(layers=2, experts=4)
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", solver_type="SGD",
                         momentum=0.9, weight_decay=1e-4, max_iter=100)

    ds, vs = mlm_dataset(vocab_size=64, n_tokens=8192, seq_len=s)
    feed = mlm_feed_tokens(ds, b, vs, seed=0)
    batch = {k: jnp.asarray(v) for k, v in next(feed).items()}

    # oracle: unsharded model, explicit microbatch loop
    model0 = BertMLM(cfg, shapes)
    params, _ = model0.init(jax.random.PRNGKey(0))
    mb = b // n_micro

    def baseline_step(params, opt, batch, it):
        def loss_fn(p):
            nll_t = w_t = 0.0
            aux_t = 0.0
            for mi in range(n_micro):
                sub = {
                    k: v[mi * mb:(mi + 1) * mb] for k, v in batch.items()
                }
                nll, w, _, aux = model0.token_loss_sums_with_aux(
                    p, {}, sub, train=True, rng=None
                )
                nll_t, w_t, aux_t = nll_t + nll, w_t + w, aux_t + aux
            return (
                nll_t / jnp.maximum(w_t, 1.0)
                + cfg.moe_aux_weight * aux_t / n_micro
            )

        grads = jax.grad(loss_fn)(params)
        lr_m, dec_m = mults_for_params(params, model0.param_specs())
        return make_update_fn(sp, lr_m, dec_m)(params, grads, opt, it)

    p_base, _ = jax.jit(baseline_step)(
        params, init_opt_state(sp, params), batch, jnp.asarray(0, jnp.int32)
    )

    # pipelined + expert-parallel
    mesh = make_mesh({"pp": 2, "ep": 2}, jax.devices()[:4])
    model1 = BertMLM(cfg, shapes, ep_axis="ep")
    stacked, rest = stack_layer_params(params, cfg.num_layers)
    pp_params = {"layers": stacked, "rest": rest}
    step = make_pp_train_step(model1, sp, mesh, n_micro=n_micro,
                              pp_axis="pp", ep_axis="ep")
    p_pp, _, m = step(pp_params, init_opt_state(sp, pp_params), batch,
                      jnp.asarray(0, jnp.int32), jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss"]))
    assert np.isfinite(float(m["moe_aux"]))

    back = unstack_layer_params(p_pp["layers"], p_pp["rest"], cfg.num_layers)
    for layer in p_base:
        for name in p_base[layer]:
            np.testing.assert_allclose(
                np.asarray(back[layer][name]),
                np.asarray(p_base[layer][name]),
                rtol=2e-4, atol=2e-5, err_msg=f"{layer}/{name}",
            )


@pytest.mark.slow
def test_pp_dp_ep_moe_trains():
    """dp=2 × pp=2 × ep=2 MoE with dropout on: loss decreases."""
    b, s = 8, 32
    cfg = _moe_cfg(layers=2, experts=4)
    cfg = type(cfg)(**{
        **cfg.__dict__, "hidden_dropout": 0.1, "attention_dropout": 0.1,
    })
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    model = BertMLM(cfg, shapes, ep_axis="ep")
    params, _ = model.init(jax.random.PRNGKey(0))
    sp = SolverParameter(base_lr=1e-3, lr_policy="fixed", solver_type="ADAMW",
                         momentum=0.9, weight_decay=0.01, max_iter=100)
    mesh = make_mesh({"dp": 2, "pp": 2, "ep": 2}, jax.devices()[:8])
    stacked, rest = stack_layer_params(params, cfg.num_layers)
    pp_params = {"layers": stacked, "rest": rest}
    opt = init_opt_state(sp, pp_params)
    step = make_pp_train_step(model, sp, mesh, n_micro=2, dp_axis="dp",
                              ep_axis="ep")
    ds, vs = mlm_dataset(vocab_size=64, n_tokens=8192, seq_len=s)
    feed = mlm_feed_tokens(ds, b, vs, seed=0)
    rng = jax.random.PRNGKey(2)
    losses = []
    for it in range(30):
        batch = {k: jnp.asarray(v) for k, v in next(feed).items()}
        rng, srng = jax.random.split(rng)
        pp_params, opt, m = step(pp_params, opt, batch,
                                 jnp.asarray(it, jnp.int32), srng)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_pp_ep_requires_matching_model_axis():
    cfg = _moe_cfg()
    model = BertMLM(cfg, {"input_ids": (2, 32), "mlm_positions": (2, 4)})
    mesh = make_mesh({"pp": 2, "ep": 2}, jax.devices()[:4])
    sp = SolverParameter()
    with pytest.raises(ValueError, match="ep_axis"):
        make_pp_train_step(model, sp, mesh, n_micro=2, ep_axis="ep")


def test_pp_rejects_indivisible_layers():
    cfg = _cfg(layers=3)
    model = BertMLM(cfg, {"input_ids": (2, 32), "mlm_positions": (2, 4)})
    mesh = make_mesh({"pp": 4}, jax.devices()[:4])
    sp = SolverParameter()
    with pytest.raises(ValueError):
        make_pp_train_step(model, sp, mesh, n_micro=2)
