"""Caffe tool-chain twins: convert_imageset -> compute_image_mean ->
train from the produced LMDB -> classify with exported weights."""

import os

import numpy as np
import pytest

from sparknet_tpu.tools import classify as classify_mod
from sparknet_tpu.tools.compute_image_mean import (
    compute_mean,
    write_binaryproto,
)
from sparknet_tpu.tools.convert_imageset import convert


@pytest.fixture()
def image_list(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    lines = []
    for i in range(12):
        arr = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
        p = tmp_path / f"img{i}.png"
        Image.fromarray(arr).save(p)
        lines.append(f"img{i}.png {i % 4}")
    listfile = tmp_path / "list.txt"
    listfile.write_text("\n".join(lines) + "\n")
    return tmp_path, str(listfile)


def test_convert_imageset_and_mean(image_list, tmp_path):
    root, listfile = image_list
    db = str(tmp_path / "imgs_lmdb")
    n = convert(listfile, db, root=str(root), resize_height=32, resize_width=32)
    assert n == 12

    from sparknet_tpu.data.caffe_layers import lmdb_dataset

    ds = lmdb_dataset(db, num_partitions=2)
    batch = next(ds.batches(12, shuffle=False))
    assert batch["data"].shape == (12, 32, 32, 3)
    np.testing.assert_array_equal(np.sort(batch["label"]), np.repeat([0, 1, 2, 3], 3))

    mean = compute_mean(db)
    assert mean.shape == (32, 32, 3)
    np.testing.assert_allclose(
        mean, batch["data"].astype(np.float64).mean(0), rtol=1e-5
    )

    # binaryproto round-trip through the transform layer loader
    bp = str(tmp_path / "mean.binaryproto")
    write_binaryproto(bp, mean)
    from sparknet_tpu.proto.caffemodel import load_binaryproto_mean

    np.testing.assert_allclose(load_binaryproto_mean(bp), mean, rtol=1e-6)


def test_train_from_toolchain_lmdb_and_classify(image_list, tmp_path):
    """Full reference workflow: build LMDB + mean with the tools, train
    CifarApp-style from the prototxt, export .caffemodel, classify."""
    root, listfile = image_list
    db = str(tmp_path / "train_lmdb")
    convert(listfile, db, root=str(root), resize_height=32, resize_width=32)
    bp = str(tmp_path / "mean.binaryproto")
    write_binaryproto(bp, compute_mean(db))

    net_txt = tmp_path / "net.prototxt"
    net_txt.write_text(f"""
name: "toolnet"
layer {{ name: "d" type: "Data" top: "data" top: "label"
        transform_param {{ mean_file: "{bp}" }}
        data_param {{ source: "{db}" batch_size: 6 backend: LMDB }} }}
layer {{ name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param {{ num_output: 4
          weight_filler {{ type: "gaussian" std: 0.01 }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip1" bottom: "label" top: "loss" }}
""")
    solver_txt = tmp_path / "solver.prototxt"
    solver_txt.write_text(f"""
net: "{net_txt}"
base_lr: 0.0001
momentum: 0.9
lr_policy: "fixed"
display: 2
max_iter: 4
""")
    from sparknet_tpu.apps import cifar_app

    # train through the app's own build/train_loop, then export the
    # TRAINED solver's weights (memorise the tiny set first)
    solver, train_feed, test_feed = cifar_app.build(
        cifar_app_args(str(solver_txt), str(tmp_path))
    )
    solver.sp.base_lr = 0.01
    solver.sp.max_iter = 60
    cifar_app.train_loop(solver, train_feed, test_feed, log=lambda *a: None)
    assert solver.iter == 60
    import jax

    fresh_ip1 = np.asarray(
        cifar_app.build(cifar_app_args(str(solver_txt), str(tmp_path)))[0]
        .params["ip1"]["weight"]
    )
    trained_ip1 = np.asarray(solver.params["ip1"]["weight"])
    assert not np.allclose(fresh_ip1, trained_ip1)  # training moved them

    cm_path = str(tmp_path / "tool.caffemodel")
    solver.export_weights(cm_path)

    deploy = tmp_path / "deploy.prototxt"
    deploy.write_text("""
name: "toolnet"
input: "data"
input_shape { dim: 1 dim: 3 dim: 32 dim: 32 }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 4 } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
""")
    net, params, state = classify_mod.load_model(str(deploy), cm_path)
    np.testing.assert_allclose(
        np.asarray(params["ip1"]["weight"]), trained_ip1, rtol=1e-6
    )  # the deploy net really carries the trained weights
    imgs = [str(root / f"img{i}.png") for i in range(8)]
    from sparknet_tpu.proto.caffemodel import load_binaryproto_mean

    batch = classify_mod.preprocess(imgs, 32, load_binaryproto_mean(bp))
    idx, probs = classify_mod.classify(net, params, state, batch, top_k=3)
    assert idx.shape == (8, 3) and probs.shape == (8, 3)
    assert np.all(probs >= 0) and np.all(probs[:, 0] >= probs[:, 1])
    # the trained net must actually classify its memorised training
    # images: top-1 should match the true label for most of them
    truth = np.asarray([i % 4 for i in range(8)])
    assert (idx[:, 0] == truth).mean() >= 0.75


def test_time_net_reports(tmp_path):
    from sparknet_tpu.tools import time_net

    out = time_net.main([
        "--solver",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "sparknet_tpu", "models", "prototxt",
            "cifar10_quick_solver.prototxt",
        ),
        "--batch-size", "8", "--iters", "3",
    ])
    assert out["train_step_ms"] > 0 and out["forward_ms"] > 0
    assert out["items_per_sec"] > 0


def cifar_app_args(solver_path, data_dir):
    import argparse

    return argparse.Namespace(
        solver=solver_path, data_dir=data_dir, synthetic=False,
        synthetic_n=10000, max_iter=4, batch_size=0, native_loader=False,
        parallel="none", tau=10, restore=None, auto_resume=False,
        weights=None, profile_dir=None, seed=0,
    )
