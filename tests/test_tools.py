"""Caffe tool-chain twins: convert_imageset -> compute_image_mean ->
train from the produced LMDB -> classify with exported weights."""

import os

import numpy as np
import pytest

from sparknet_tpu.tools import classify as classify_mod
from sparknet_tpu.tools.compute_image_mean import (
    compute_mean,
    write_binaryproto,
)
from sparknet_tpu.tools.convert_imageset import convert


@pytest.fixture()
def image_list(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    lines = []
    for i in range(12):
        arr = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
        p = tmp_path / f"img{i}.png"
        Image.fromarray(arr).save(p)
        lines.append(f"img{i}.png {i % 4}")
    listfile = tmp_path / "list.txt"
    listfile.write_text("\n".join(lines) + "\n")
    return tmp_path, str(listfile)


def test_convert_imageset_and_mean(image_list, tmp_path):
    root, listfile = image_list
    db = str(tmp_path / "imgs_lmdb")
    n = convert(listfile, db, root=str(root), resize_height=32, resize_width=32)
    assert n == 12

    from sparknet_tpu.data.caffe_layers import lmdb_dataset

    ds = lmdb_dataset(db, num_partitions=2)
    batch = next(ds.batches(12, shuffle=False))
    assert batch["data"].shape == (12, 32, 32, 3)
    np.testing.assert_array_equal(np.sort(batch["label"]), np.repeat([0, 1, 2, 3], 3))

    mean = compute_mean(db)
    assert mean.shape == (32, 32, 3)
    np.testing.assert_allclose(
        mean, batch["data"].astype(np.float64).mean(0), rtol=1e-5
    )

    # binaryproto round-trip through the transform layer loader
    bp = str(tmp_path / "mean.binaryproto")
    write_binaryproto(bp, mean)
    from sparknet_tpu.proto.caffemodel import load_binaryproto_mean

    np.testing.assert_allclose(load_binaryproto_mean(bp), mean, rtol=1e-6)


def test_train_from_toolchain_lmdb_and_classify(image_list, tmp_path):
    """Full reference workflow: build LMDB + mean with the tools, train
    CifarApp-style from the prototxt, export .caffemodel, classify."""
    root, listfile = image_list
    db = str(tmp_path / "train_lmdb")
    convert(listfile, db, root=str(root), resize_height=32, resize_width=32)
    bp = str(tmp_path / "mean.binaryproto")
    write_binaryproto(bp, compute_mean(db))

    net_txt = tmp_path / "net.prototxt"
    net_txt.write_text(f"""
name: "toolnet"
layer {{ name: "d" type: "Data" top: "data" top: "label"
        transform_param {{ mean_file: "{bp}" }}
        data_param {{ source: "{db}" batch_size: 6 backend: LMDB }} }}
layer {{ name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param {{ num_output: 4
          weight_filler {{ type: "gaussian" std: 0.01 }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip1" bottom: "label" top: "loss" }}
""")
    solver_txt = tmp_path / "solver.prototxt"
    solver_txt.write_text(f"""
net: "{net_txt}"
base_lr: 0.0001
momentum: 0.9
lr_policy: "fixed"
display: 2
max_iter: 4
""")
    from sparknet_tpu.apps import cifar_app

    # train through the app's own build/train_loop, then export the
    # TRAINED solver's weights (memorise the tiny set first)
    solver, train_feed, test_feed = cifar_app.build(
        cifar_app_args(str(solver_txt), str(tmp_path))
    )
    solver.sp.base_lr = 0.01
    solver.sp.max_iter = 60
    cifar_app.train_loop(solver, train_feed, test_feed, log=lambda *a: None)
    assert solver.iter == 60
    import jax

    fresh_ip1 = np.asarray(
        cifar_app.build(cifar_app_args(str(solver_txt), str(tmp_path)))[0]
        .params["ip1"]["weight"]
    )
    trained_ip1 = np.asarray(solver.params["ip1"]["weight"])
    assert not np.allclose(fresh_ip1, trained_ip1)  # training moved them

    cm_path = str(tmp_path / "tool.caffemodel")
    solver.export_weights(cm_path)

    deploy = tmp_path / "deploy.prototxt"
    deploy.write_text("""
name: "toolnet"
input: "data"
input_shape { dim: 1 dim: 3 dim: 32 dim: 32 }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 4 } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
""")
    net, params, state = classify_mod.load_model(str(deploy), cm_path)
    np.testing.assert_allclose(
        np.asarray(params["ip1"]["weight"]), trained_ip1, rtol=1e-6
    )  # the deploy net really carries the trained weights
    imgs = [str(root / f"img{i}.png") for i in range(8)]
    from sparknet_tpu.proto.caffemodel import load_binaryproto_mean

    batch = classify_mod.preprocess(imgs, 32, load_binaryproto_mean(bp))
    idx, probs = classify_mod.classify(net, params, state, batch, top_k=3)
    assert idx.shape == (8, 3) and probs.shape == (8, 3)
    assert np.all(probs >= 0) and np.all(probs[:, 0] >= probs[:, 1])
    # the trained net must actually classify its memorised training
    # images: top-1 should match the true label for most of them
    truth = np.asarray([i % 4 for i in range(8)])
    assert (idx[:, 0] == truth).mean() >= 0.75


def test_time_net_reports(tmp_path):
    from sparknet_tpu.tools import time_net

    out = time_net.main([
        "--solver",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "sparknet_tpu", "models", "prototxt",
            "cifar10_quick_solver.prototxt",
        ),
        "--batch-size", "8", "--iters", "3",
    ])
    assert out["train_step_ms"] > 0 and out["forward_ms"] > 0
    assert out["items_per_sec"] > 0


def cifar_app_args(solver_path, data_dir):
    import argparse

    return argparse.Namespace(
        solver=solver_path, data_dir=data_dir, synthetic=False,
        synthetic_n=10000, max_iter=4, batch_size=0, native_loader=False,
        parallel="none", tau=10, restore=None, auto_resume=False,
        weights=None, profile_dir=None, seed=0,
    )


@pytest.mark.slow
def test_convert_mnist_to_lenet_training(tmp_path):
    """idx files -> convert_mnist_data -> LMDB -> LeNet via the caffe
    CLI: the full published MNIST workflow on synthetic digits."""
    import struct

    from sparknet_tpu.tools.convert_mnist_data import convert as mnist_convert

    rng = np.random.default_rng(0)

    def write_idx(n, name_img, name_lab):
        imgs = rng.integers(0, 256, (n, 28, 28), dtype=np.uint8)
        labs = rng.integers(0, 10, n).astype(np.uint8)
        with open(tmp_path / name_img, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(imgs.tobytes())
        with open(tmp_path / name_lab, "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(labs.tobytes())

    write_idx(64, "train-images", "train-labels")
    write_idx(128, "t10k-images", "t10k-labels")  # >= TEST batch_size 100
    n = mnist_convert(
        str(tmp_path / "train-images"),
        str(tmp_path / "train-labels"),
        str(tmp_path / "mnist_train_lmdb"),
    )
    assert n == 64
    mnist_convert(
        str(tmp_path / "t10k-images"),
        str(tmp_path / "t10k-labels"),
        str(tmp_path / "mnist_test_lmdb"),
    )

    # stage the zoo LeNet files next to the LMDBs (data_param sources
    # are relative, exactly like the published example)
    zoo = os.path.join(
        os.path.dirname(__file__), "..", "sparknet_tpu", "models", "prototxt"
    )
    for f in ("lenet_train_test.prototxt", "lenet_solver.prototxt"):
        with open(os.path.join(zoo, f)) as src:
            (tmp_path / f).write_text(src.read())

    from sparknet_tpu.tools import caffe as caffe_cli

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        result = caffe_cli.main(
            [
                "train",
                f"--solver={tmp_path}/lenet_solver.prototxt",
                "--max-iter", "2",
            ]
        )
    finally:
        os.chdir(cwd)
    assert "accuracy" in result


def test_extract_features(tmp_path):
    """extract_features dumps a named blob to a float-Datum LMDB that
    decodes back to the right shapes and labels."""
    from sparknet_tpu.data.caffe_layers import encode_datum, lmdb_dataset
    from sparknet_tpu.data.lmdb_io import write_lmdb
    from sparknet_tpu.tools.extract_features import extract

    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (48, 12, 12, 3), dtype=np.uint8)
    labels = rng.integers(0, 5, 48)
    os.makedirs(tmp_path / "db")
    write_lmdb(
        str(tmp_path / "db"),
        [
            (f"{i:08d}".encode(), encode_datum(imgs[i], int(labels[i])))
            for i in range(48)
        ],
    )
    net = tmp_path / "net.prototxt"
    net.write_text(f"""
name: "feat"
layer {{ name: "d" type: "Data" top: "data" top: "label"
        include {{ phase: TEST }}
        data_param {{ source: "{tmp_path}/db" batch_size: 8 }} }}
layer {{ name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param {{ num_output: 7
          weight_filler {{ type: "gaussian" std: 0.1 }} }} }}
layer {{ name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }}
""")
    n = extract(
        str(net), "ip1", str(tmp_path / "feats_lmdb"), iterations=3
    )
    assert n == 24
    feats = lmdb_dataset(str(tmp_path / "feats_lmdb"), num_partitions=1)
    part = feats.collect_partition(0)
    assert part["data"].shape == (24, 1, 1, 7)
    assert set(np.unique(part["label"])) <= set(range(5))


def test_caffe_device_query(capsys):
    from sparknet_tpu.tools import caffe as caffe_cli

    devices = caffe_cli.main(["device_query"])
    outp = capsys.readouterr().out
    assert len(devices) >= 1 and "Device id:" in outp


def test_check_determinism_tool():
    """Two fresh replays of the same schedule must match bitwise (the
    framework's race-detector analog); a perturbed tree must not."""
    from sparknet_tpu.tools import check_determinism as cd

    args = [
        "--synthetic", "--synthetic-n", "256", "--iters", "2",
        "--batch-size", "8",
    ]
    assert cd.main(args) == 0

    a = {"l": {"w": np.zeros((2, 2), np.float32)}}
    b = {"l": {"w": np.full((2, 2), 1e-7, np.float32)}}
    bad = cd.compare_trees(a, b)
    assert bad and bad[0][0] == "l/w"
    assert cd.compare_trees(a, {"l": {"w": np.zeros((2, 2), np.float32)}}) == []


def test_time_net_per_layer(tmp_path):
    from sparknet_tpu.tools import time_net

    out = time_net.main([
        "--solver",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "sparknet_tpu", "models", "prototxt",
            "cifar10_quick_solver.prototxt",
        ),
        "--batch-size", "4", "--iters", "3", "--per-layer",
    ])
    rows = out["per_layer"]
    by_type = {r["type"] for r in rows}
    assert {"Convolution", "Pooling", "ReLU", "InnerProduct"} <= by_type
    assert all(r["forward_ms"] > 0 for r in rows)
    conv = next(r for r in rows if r["type"] == "Convolution")
    assert conv["backward_ms"] and conv["backward_ms"] > 0


def test_draw_net_dot_output(tmp_path):
    from sparknet_tpu.tools import draw_net

    zoo = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "sparknet_tpu", "models", "prototxt",
    )
    out = str(tmp_path / "net.dot")
    dot = draw_net.main(
        [os.path.join(zoo, "cifar10_quick_train_test.prototxt"), out,
         "--phase", "TRAIN"]
    )
    assert dot.startswith("digraph net {") and dot.rstrip().endswith("}")
    assert "conv1" in dot and "SoftmaxWithLoss" in dot
    assert os.path.getsize(out) > 0
    import re

    # every layer bottom must have produced exactly one edge: count
    # edges == total bottoms across drawn layers
    from sparknet_tpu.proto import caffe_pb

    npm = caffe_pb.load_net(
        os.path.join(zoo, "cifar10_quick_train_test.prototxt")
    )
    n_bottoms = sum(
        len(l.bottom) for l in npm.layers_for_phase("TRAIN")
    )
    edges = re.findall(r"(\w+) -> (l\d+) \[label=\"(\w+)\"\]", dot)
    assert len(edges) == n_bottoms
    assert not re.search(r"dangling_", dot)  # nothing unresolved
    # in-place ReLU: the conv1 blob edge into relu1 must leave conv1's
    # node, and the edge into conv2 must leave relu1's node (the LAST
    # writer), proving in-place chaining
    node_of = {
        m.group(2): m.group(1)
        for m in re.finditer(r'^\s*(l\d+) \[label="(\w+)', dot, re.M)
    }
    # cifar10_quick pools before relu: relu1 runs in place on pool1's
    # blob, so conv2's edge must leave relu1 (the LAST writer), proving
    # in-place chaining
    into_relu1 = [a for a, b_, lbl in edges if b_ == node_of["relu1"]]
    assert into_relu1 == [node_of["pool1"]]
    into_conv2 = [a for a, b_, lbl in edges if b_ == node_of["conv2"]]
    assert into_conv2 == [node_of["relu1"]]


def test_draw_net_deploy_inputs_and_dangling(tmp_path):
    """Deploy-style net-level inputs get producer nodes; a typo'd
    bottom surfaces as a marked dangling node, not a silent drop."""
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.tools.draw_net import net_to_dot

    deploy = caffe_pb.load_net("""
name: "d"
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 2 kernel_size: 3 } }
layer { name: "oops" type: "ReLU" bottom: "typo_blob" top: "oops" }
""", is_path=False)
    dot = net_to_dot(deploy)
    assert 'in0 [label="data"' in dot
    assert "in0 -> l0" in dot  # deploy input feeds conv1
    assert "dangling_" in dot and "typo_blob??" in dot
