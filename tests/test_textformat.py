from pathlib import Path

import pytest

from sparknet_tpu.proto.textformat import parse, ParseError
from sparknet_tpu.proto import caffe_pb

REPO = Path(__file__).resolve().parents[1]
ZOO = REPO / "sparknet_tpu" / "models" / "prototxt"


def test_scalars_and_types():
    m = parse('name: "net" n: 3 f: 0.5 b: true b2: false e: MAX neg: -2')
    assert m.get("name") == "net"
    assert m.get("n") == 3 and isinstance(m.get("n"), int)
    assert m.get("f") == 0.5
    assert m.get("b") is True and m.get("b2") is False
    assert m.get("e") == "MAX"
    assert m.get("neg") == -2


def test_nested_and_repeated():
    m = parse(
        """
        layer { name: "a" bottom: "x" bottom: "y" }
        layer { name: "b" }
        """
    )
    layers = m.get_all("layer")
    assert len(layers) == 2
    assert layers[0].get_all("bottom") == ["x", "y"]


def test_colon_brace_and_comments():
    m = parse('sub: { k: 1 } # trailing comment\n# full line\nv: 2')
    assert m.get("sub").get("k") == 1
    assert m.get("v") == 2


def test_string_escapes_and_scientific():
    m = parse(r's: "a\"b" lr: 1e-3')
    assert m.get("s") == 'a"b'
    assert m.get("lr") == 1e-3


def test_parse_error():
    with pytest.raises(ParseError):
        parse("layer { name: ")
    with pytest.raises(ParseError):
        parse("} oops")


def test_cifar10_quick_net_roundtrip():
    net = caffe_pb.load_net(str(ZOO / "cifar10_quick_train_test.prototxt"))
    assert net.name == "CIFAR10_quick"
    names = [l.name for l in net.layers]
    assert "conv1" in names and "ip2" in names and "loss" in names
    conv1 = next(l for l in net.layers if l.name == "conv1")
    assert conv1.type == "Convolution"
    assert conv1.convolution_param.get("num_output") == 32
    assert conv1.convolution_param.get("pad") == 2
    assert [p.lr_mult for p in conv1.params] == [1.0, 2.0]
    # phase filtering: two Data layers, one per phase
    train_layers = net.layers_for_phase("TRAIN")
    test_layers = net.layers_for_phase("TEST")
    assert sum(1 for l in train_layers if l.type == "Data") == 1
    assert any(l.type == "Accuracy" for l in test_layers)
    assert not any(l.type == "Accuracy" for l in train_layers)


def test_cifar10_quick_solver():
    s = caffe_pb.load_solver(str(ZOO / "cifar10_quick_solver.prototxt"))
    assert s.base_lr == 0.001
    assert s.momentum == 0.9
    assert s.weight_decay == 0.004
    assert s.lr_policy == "fixed"
    assert s.max_iter == 4000
    assert s.net.endswith("cifar10_quick_train_test.prototxt")


def test_v1_layer_upgrade():
    net = caffe_pb.load_net(
        """
        name: "v1net"
        layers { name: "c" type: CONVOLUTION blobs_lr: 1 blobs_lr: 2
                 convolution_param { num_output: 4 kernel_size: 3 } }
        layers { name: "r" type: RELU }
        """,
        is_path=False,
    )
    assert net.layers[0].type == "Convolution"
    assert net.layers[1].type == "ReLU"
    assert [p.lr_mult for p in net.layers[0].params] == [1.0, 2.0]


def test_last_wins_and_lists_and_concat():
    m = parse('base_lr: 0.1 base_lr: 0.01')
    assert m.get("base_lr") == 0.01  # protobuf singular semantics
    m = parse('stepvalue: [1000, 2000, 3000]')
    assert m.get_all("stepvalue") == [1000, 2000, 3000]
    m = parse('s: "a" "b" t: 1')
    assert m.get("s") == "ab" and m.get("t") == 1
    m = parse('display: 100# abutting comment\nv: 2')
    assert m.get("display") == 100 and m.get("v") == 2
    m = parse('nested: [{ k: 1 }, { k: 2 }]')
    assert [x.get("k") for x in m.get_all("nested")] == [1, 2]
    assert parse('b: 1 b: 2 b: 3').to_dict() == {"b": [1, 2, 3]}
    assert parse('s: "caf\\xc3\\xa9"').get("s") == "caf\xc3\xa9"


def test_input_shape_parsing():
    net = caffe_pb.load_net(
        """
        name: "deploy"
        input: "data"
        input_dim: 1 input_dim: 3 input_dim: 227 input_dim: 227
        """,
        is_path=False,
    )
    assert net.inputs == ["data"]
    assert net.input_shapes == [[1, 3, 227, 227]]
