"""Expert parallelism: dp×ep BERT-MoE train step vs single-device math."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.data.text import mlm_dataset, mlm_feed_tokens
from sparknet_tpu.models.bert import BertConfig, BertMLM
from sparknet_tpu.parallel.expert import bert_moe_pspecs, make_ep_train_step
from sparknet_tpu.parallel.mesh import make_mesh
from sparknet_tpu.proto.caffe_pb import SolverParameter
from sparknet_tpu.solver.caffe_solver import (
    init_opt_state,
    make_update_fn,
    mults_for_params,
)


def _cfg(experts=4, dispatch="dense"):
    return dataclasses.replace(
        BertConfig.bert_tiny(vocab_size=64),
        hidden_dropout=0.0, attention_dropout=0.0,
        moe_num_experts=experts, moe_capacity_factor=2.0,
        moe_dispatch=dispatch,
    )


def _batch(b, s, seed=0):
    ds, vsize = mlm_dataset(vocab_size=64, n_tokens=8192, seq_len=s, seed=seed)
    feed = mlm_feed_tokens(ds, b, vsize, seed=seed)
    return {k: jnp.asarray(v) for k, v in next(feed).items()}


@pytest.mark.parametrize("dispatch", ["dense", "sort"])
def test_ep_step_matches_single_device(dispatch):
    """One dp=2×ep=4 step == one single-device step on the same global
    batch (dropout off, SGD so reduction order can't amplify)."""
    b, s = 4, 32
    cfg = _cfg(dispatch=dispatch)
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", solver_type="SGD",
                         momentum=0.9, weight_decay=1e-4, max_iter=100)

    model0 = BertMLM(cfg, shapes)
    params, _ = model0.init(jax.random.PRNGKey(0))
    batch = _batch(b, s)

    def baseline_step(params, opt, batch, it):
        # the sharded step computes routing + aux loss PER dp SHARD
        # (GShard's per-device load balance); mirror that by scoring
        # each dp half separately and averaging the aux terms
        def loss_fn(p):
            halves = [
                {k: v[: b // 2] for k, v in batch.items()},
                {k: v[b // 2 :] for k, v in batch.items()},
            ]
            nll = w = aux = 0.0
            for half in halves:
                nll_i, w_i, _, aux_i = model0.token_loss_sums_with_aux(
                    p, {}, half, train=True, rng=None
                )
                nll, w, aux = nll + nll_i, w + w_i, aux + aux_i
            return (
                nll / jnp.maximum(w, 1.0) + cfg.moe_aux_weight * aux / 2.0,
                (nll, w),
            )

        grads, _ = jax.grad(loss_fn, has_aux=True)(params)
        lr_m, dec_m = mults_for_params(params, model0.param_specs())
        return make_update_fn(sp, lr_m, dec_m)(params, grads, opt, it)

    p_base, _ = jax.jit(baseline_step)(
        params, init_opt_state(sp, params), batch, jnp.asarray(0, jnp.int32)
    )

    mesh = make_mesh({"dp": 2, "ep": 4}, jax.devices()[:8])
    model_ep = BertMLM(cfg, shapes, ep_axis="ep")
    step = make_ep_train_step(model_ep, sp, mesh, dp_axis="dp", ep_axis="ep")
    p_ep, _, m = step(
        params, init_opt_state(sp, params), batch,
        jnp.asarray(0, jnp.int32), jax.random.PRNGKey(1),
    )
    assert np.isfinite(float(m["loss"]))
    for layer in p_base:
        for name in p_base[layer]:
            np.testing.assert_allclose(
                np.asarray(p_ep[layer][name]), np.asarray(p_base[layer][name]),
                rtol=2e-4, atol=2e-6, err_msg=f"{layer}.{name}",
            )


def test_ep_pspecs_cover_params():
    cfg = _cfg()
    shapes = {"input_ids": (2, 32), "mlm_positions": (2, 4)}
    model = BertMLM(cfg, shapes, ep_axis="ep")
    params, _ = model.init(jax.random.PRNGKey(0))
    specs = bert_moe_pspecs(model)
    assert set(specs) == set(params)
    for layer in params:
        assert set(specs[layer]) == set(params[layer]), layer


def test_ep_step_rejects_mismatches():
    cfg = _cfg(experts=4)
    shapes = {"input_ids": (2, 32), "mlm_positions": (2, 4)}
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", solver_type="SGD",
                         momentum=0.9, max_iter=10)
    mesh = make_mesh({"dp": 1, "ep": 8}, jax.devices()[:8])
    with pytest.raises(ValueError):  # 8 does not divide 4 experts
        make_ep_train_step(BertMLM(cfg, shapes, ep_axis="ep"), sp, mesh)
    mesh2 = make_mesh({"dp": 2, "ep": 4}, jax.devices()[:8])
    with pytest.raises(ValueError):  # model built without the ep hook
        make_ep_train_step(BertMLM(cfg, shapes), sp, mesh2)
    dense = dataclasses.replace(cfg, moe_num_experts=0)
    with pytest.raises(ValueError):  # dense config has no experts
        make_ep_train_step(BertMLM(dense, shapes, ep_axis="ep"), sp, mesh2)
