"""Communication layer (parallel/comm.py + tau_controller.py) tests.

Key contracts (ISSUE 6):
- lossless bucketed reduction is BITWISE identical to the monolithic
  per-leaf pmean it replaces (and so is the trained result);
- int8 runs are deterministic per seed;
- error-feedback residuals re-inject quantization error (the
  cumulative mean converges where no-feedback stays biased);
- the tau controller widens when sync-bound, narrows on divergence,
  and never leaves [tau_min, tau_max];
- residuals ride opt state through snapshot save/restore.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from sparknet_tpu.parallel import CommConfig, ParallelSolver, comm, make_mesh
from sparknet_tpu.parallel.local_sgd import RESIDUAL_KEY, RoundBuffer
from sparknet_tpu.parallel.tau_controller import TauController, parse_tau
from sparknet_tpu.proto import caffe_pb

TINY_NET = """
name: "tiny"
layer { name: "d" type: "Input" top: "data" top: "label" }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss" }
"""

SOLVER_TXT = "base_lr: 0.1 momentum: 0.9 lr_policy: 'fixed' weight_decay: 0.001"
SHAPES = {"data": (16, 8), "label": (16,)}


def tiny_net():
    return caffe_pb.load_net(TINY_NET, is_path=False)


def tiny_solver():
    return caffe_pb.load_solver(SOLVER_TXT, is_path=False)


def batch(seed, n=16):
    rng = np.random.default_rng(seed)
    return {
        "data": jnp.asarray(rng.normal(size=(n, 8)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 4, size=(n,)), jnp.int32),
    }


def make_local(cc, tau=2, seed=7, net=None):
    return ParallelSolver(
        tiny_solver(), SHAPES, net_param=net or tiny_net(), seed=seed,
        mesh=make_mesh(), mode="local", tau=tau, comm_config=cc,
    )


def run_local(cc, tau=2, n=6, seed=7):
    s = make_local(cc, tau=tau, seed=seed)
    s.step(iter([batch(i) for i in range(n)]), n)
    return jax.device_get(s.params), s


def assert_trees_equal(a, b, exact=True, rtol=0.0, atol=0.0):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (pa, xa), (_, xb) in zip(la, lb):
        xa, xb = np.asarray(xa), np.asarray(xb)
        if exact:
            assert np.array_equal(xa, xb), (pa, np.max(np.abs(xa - xb)))
        else:
            np.testing.assert_allclose(xa, xb, rtol=rtol, atol=atol,
                                       err_msg=str(pa))


# ------------------------------------------------------------ planning

def test_plan_buckets_bounds_order_and_coverage():
    leaves = [
        np.zeros(s, np.float32)
        for s in ((100,), (200,), (50,), (500,), (10,))
    ]
    plan = comm.plan_buckets(leaves, 1000)  # 250 floats per bucket
    covered = sorted(i for b in plan for i in b)
    assert covered == list(range(len(leaves)))  # every leaf exactly once
    # reverse flatten order: first bucket starts from the LAST leaf
    assert plan[0][0] == len(leaves) - 1
    for b in plan:
        nbytes = sum(leaves[i].nbytes for i in b)
        assert nbytes <= 1000 or len(b) == 1  # oversized leaf = own bucket
    # a leaf above the bound still lands somewhere, alone
    assert any(len(b) == 1 and 3 in b for b in plan)


def test_plan_buckets_never_mixes_dtypes():
    leaves = [np.zeros(4, np.float32), np.zeros(4, np.int32),
              np.zeros(4, np.float32)]
    plan = comm.plan_buckets(leaves, 1 << 20)
    for b in plan:
        assert len({np.asarray(leaves[i]).dtype for i in b}) == 1


def test_wire_bytes_and_histogram():
    leaves = [np.zeros(256, np.float32), np.zeros(64, np.float32)]
    plan = comm.plan_buckets(leaves, 1 << 20)
    h = comm.bucket_histogram(plan, leaves)
    assert h["buckets"] == 1 and h["total_bytes"] == 320 * 4
    assert comm.wire_bytes(plan, leaves, "none") == 320 * 4
    assert comm.wire_bytes(plan, leaves, "bf16") == 320 * 2
    assert comm.wire_bytes(plan, leaves, "int8") == 320 * 2 + 4  # int16 acc


def test_config_resolution_and_validation(monkeypatch):
    monkeypatch.setenv(comm.COMM_ENV, "monolithic")
    monkeypatch.setenv(comm.COMPRESS_ENV, "")
    assert comm.resolve_config().mode == "monolithic"
    monkeypatch.setenv(comm.COMM_ENV, "")
    monkeypatch.setenv(comm.COMPRESS_ENV, "int8")
    cfg = comm.resolve_config()
    assert cfg.compress == "int8" and cfg.for_sync() == "bucketed"
    assert cfg.for_local() == "bucketed"
    with pytest.raises(ValueError):
        CommConfig(mode="monolithic", compress="bf16")
    with pytest.raises(ValueError):
        CommConfig(mode="nope")
    with pytest.raises(ValueError):
        CommConfig(bucket_mb=0)
    # lossless auto: sync keeps the implicit program
    assert CommConfig().for_sync() == "monolithic"


# ----------------------------------------------------- in-mesh reduction

def test_bucketed_none_reduce_is_bitwise_per_leaf_pmean():
    mesh = make_mesh()
    tree = {
        "a": {"w": jnp.arange(300, dtype=jnp.float32).reshape(30, 10) / 7.0,
              "b": jnp.linspace(-1, 1, 10, dtype=jnp.float32)},
        "z": {"w": jnp.full((128,), 2.5, jnp.float32)},
    }
    cc = CommConfig(mode="bucketed", bucket_mb=0.0005)  # force >1 bucket

    def vary(t):
        widx = lax.axis_index("dp").astype(jnp.float32)
        t = comm.pcast_varying(t, "dp")
        return jax.tree_util.tree_map(lambda x: x * (1.0 + 0.1 * widx), t)

    def bucketed(t):
        r, _ = comm.reduce_bucketed(vary(t), "dp", 8, cc)
        return r

    def per_leaf(t):
        return jax.tree_util.tree_map(
            lambda x: lax.pmean(x, "dp"), vary(t)
        )

    f1 = jax.jit(comm.shard_map(
        bucketed, mesh=mesh, in_specs=(P(),), out_specs=P()))
    f2 = jax.jit(comm.shard_map(
        per_leaf, mesh=mesh, in_specs=(P(),), out_specs=P()))
    assert_trees_equal(f1(tree), f2(tree), exact=True)


@pytest.mark.parametrize("compress", ["bf16", "int8"])
def test_error_feedback_converges_where_biased_would_not(compress):
    """Reducing the SAME per-worker values round after round: with
    error feedback the cumulative mean of the reduced outputs converges
    to the exact mean (residual re-injection cancels quantization
    error); without residuals the same error repeats every round."""
    mesh = make_mesh()
    cc = CommConfig(compress=compress, bucket_mb=1.0)
    val = {"w": jnp.linspace(0.1, 1.7, 64, dtype=jnp.float32)}

    def worker(res):
        widx = lax.axis_index("dp").astype(jnp.float32)
        t = jax.tree_util.tree_map(
            lambda x: comm.pcast_varying(x, "dp") * (1.0 + 0.013 * widx),
            val,
        )
        red, new_res = comm.reduce_bucketed(t, "dp", 8, cc, residual=res)
        return red, new_res

    f = jax.jit(comm.shard_map(
        worker, mesh=mesh, in_specs=(P("dp"),), out_specs=(P(), P("dp"))))
    exact = np.asarray(val["w"]) * (1.0 + 0.013 * np.mean(np.arange(8)))
    res = jax.device_put(
        jax.tree_util.tree_map(
            lambda x: jnp.zeros((8,) + x.shape, jnp.float32), val
        ),
        jax.sharding.NamedSharding(make_mesh(), P("dp")),
    )
    total = np.zeros_like(exact)
    rounds = 8
    first_err = None
    for i in range(rounds):
        red, res = f(res)
        if first_err is None:
            first_err = np.max(np.abs(np.asarray(red["w"]) - exact))
        total += np.asarray(red["w"])
    ef_err = np.max(np.abs(total / rounds - exact))
    # repeating the round-1 output (no feedback) keeps the round-1
    # error; the EF cumulative mean must beat it clearly
    assert first_err > 0  # quantization really is lossy here
    assert ef_err < 0.35 * first_err, (ef_err, first_err)


# ------------------------------------------------- local-SGD end to end

def test_local_bucketed_none_bitwise_matches_monolithic():
    mono, _ = run_local(CommConfig(mode="monolithic"))
    buck, _ = run_local(CommConfig(mode="bucketed", bucket_mb=0.01))
    assert_trees_equal(mono, buck, exact=True)


def test_local_compressed_tracks_exact_average():
    exact, _ = run_local(CommConfig(mode="monolithic"))
    for compress in ("bf16", "int8"):
        got, s = run_local(CommConfig(compress=compress, bucket_mb=0.01))
        assert RESIDUAL_KEY in s.opt_state
        assert_trees_equal(exact, got, exact=False, rtol=0.02, atol=5e-3)


def test_local_int8_deterministic_per_seed():
    a, _ = run_local(CommConfig(compress="int8", bucket_mb=0.01))
    b, _ = run_local(CommConfig(compress="int8", bucket_mb=0.01))
    assert_trees_equal(a, b, exact=True)


def test_grad_allreduce_phase_attributed():
    from sparknet_tpu.telemetry import timeline as ttl

    s = make_local(CommConfig(mode="bucketed"))
    tl = ttl.Timeline(fence=True)
    s.timeline = tl
    tl.start()
    s.step(iter([batch(i) for i in range(4)]), 4)
    tl.stop()
    ph = tl.phase_seconds()
    assert "grad_allreduce" in ph and ph["grad_allreduce"] > 0
    assert "grad_allreduce" in tl.table()


# ------------------------------------------------------ sync DP bucketed

def test_sync_bucketed_matches_implicit():
    net = tiny_net()
    imp = ParallelSolver(
        tiny_solver(), SHAPES, net_param=net, seed=7, mesh=make_mesh(),
        mode="sync", comm_config=CommConfig(mode="monolithic"),
    )
    exp = ParallelSolver(
        tiny_solver(), SHAPES, net_param=net, seed=7, mesh=make_mesh(),
        mode="sync", comm_config=CommConfig(mode="bucketed", bucket_mb=0.01),
    )
    feed = [batch(i) for i in range(3)]
    imp.step(iter(list(feed)), 3)
    exp.step(iter(list(feed)), 3)
    assert_trees_equal(
        jax.device_get(imp.params), jax.device_get(exp.params),
        exact=False, rtol=2e-5, atol=1e-6,
    )


def test_sync_compressed_residual_lives_in_opt_state():
    s = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7,
        mesh=make_mesh(), mode="sync",
        comm_config=CommConfig(compress="int8", bucket_mb=0.01),
    )
    assert RESIDUAL_KEY in s.opt_state
    lead = jax.tree_util.tree_leaves(s.opt_state[RESIDUAL_KEY])[0]
    assert lead.shape[0] == 8  # per-worker residual stack
    s.step(iter([batch(i) for i in range(2)]), 2)
    # after a step some worker quantized something away
    resid_mag = sum(
        float(jnp.sum(jnp.abs(x)))
        for x in jax.tree_util.tree_leaves(s.opt_state[RESIDUAL_KEY])
    )
    assert np.isfinite(resid_mag)


# -------------------------------------------------- snapshots + residual

def test_snapshot_roundtrip_carries_residual(tmp_path):
    cc = CommConfig(compress="bf16", bucket_mb=0.01)
    feed = [batch(i) for i in range(6)]
    a = make_local(cc)
    a.step(iter(list(feed[:2])), 2)
    path = str(tmp_path / "comm.solverstate.npz")
    a.save(path)
    b = make_local(cc, seed=11)  # different init: restore must win
    b.restore(path)
    assert RESIDUAL_KEY in b.opt_state
    a.step(iter(list(feed[2:])), 4)
    b.step(iter(list(feed[2:])), 4)
    assert_trees_equal(
        jax.device_get(a.params), jax.device_get(b.params), exact=True
    )


def test_restore_reconciles_residual_mismatch(tmp_path, capsys):
    # snapshot WITHOUT residuals -> restored into a compressed run
    plain = make_local(CommConfig(mode="bucketed"))
    plain.step(iter([batch(0), batch(1)]), 2)
    path = str(tmp_path / "plain.solverstate.npz")
    plain.save(path)
    lossy = make_local(CommConfig(compress="int8", bucket_mb=0.01))
    lossy.restore(path)
    assert RESIDUAL_KEY in lossy.opt_state  # injected zeros
    lossy.step(iter([batch(2)]), 1)  # and the compiled step accepts them
    # snapshot WITH residuals -> restored into a lossless run
    path2 = str(tmp_path / "lossy.solverstate.npz")
    lossy.save(path2)
    plain2 = make_local(CommConfig(mode="bucketed"))
    plain2.restore(path2)
    assert RESIDUAL_KEY not in plain2.opt_state  # dropped
    plain2.step(iter([batch(3)]), 1)


# ------------------------------------------------------- tau controller

def _snap(round_s=1.0, sync_s=0.0, loss=1.0):
    return dict(round_s=round_s, sync_s=sync_s, loss=loss)


def test_tau_controller_widens_when_sync_bound():
    c = TauController(tau=4, tau_min=1, tau_max=32, cooldown_rounds=0)
    taus = [c.observe_round(**_snap(sync_s=0.5, loss=1.0)) for _ in range(4)]
    assert taus == [8, 16, 32, 32]  # doubles, then pins at tau_max
    assert all(d["action"] in ("widen", "hold") for d in c.decisions)
    assert c.decisions[0]["reason"].startswith("sync share")


def test_tau_controller_narrows_on_divergence():
    c = TauController(tau=16, tau_min=2, tau_max=32, cooldown_rounds=0)
    c.observe_round(**_snap(sync_s=0.0, loss=1.0))  # establishes the EMA
    taus = [
        c.observe_round(**_snap(sync_s=0.0, loss=1.0 + 0.5 * k))
        for k in range(1, 5)
    ]
    assert taus[0] == 8 and min(taus) >= 2  # halves, floor respected
    assert any(d["action"] == "narrow" for d in c.decisions)
    # divergence wins even when also sync-bound
    c2 = TauController(tau=8, tau_min=1, tau_max=64, cooldown_rounds=0)
    c2.observe_round(**_snap(loss=1.0))
    assert c2.observe_round(**_snap(sync_s=0.9, loss=2.0)) == 4


def test_tau_controller_cooldown_and_bounds():
    c = TauController(tau=4, tau_min=4, tau_max=4)
    for k in range(5):
        t = c.observe_round(**_snap(sync_s=0.9, loss=1.0 + k))
        assert t == 4  # bounds pin tau regardless of signals
    c = TauController(tau=2, tau_min=1, tau_max=64, cooldown_rounds=2)
    assert c.observe_round(**_snap(sync_s=0.9, loss=1.0)) == 4
    # two cooldown rounds hold even though still sync-bound
    assert c.observe_round(**_snap(sync_s=0.9, loss=1.0)) == 4
    assert c.observe_round(**_snap(sync_s=0.9, loss=1.0)) == 4
    assert c.observe_round(**_snap(sync_s=0.9, loss=1.0)) == 8


def test_parse_tau():
    assert parse_tau(5) == (5, False)
    assert parse_tau("12") == (12, False)
    tau0, auto = parse_tau("auto")
    assert auto and tau0 >= 1
    with pytest.raises(ValueError):
        parse_tau("fast")


def test_tau_auto_end_to_end_records_decisions(tmp_path):
    s = make_local(CommConfig(mode="bucketed"), tau="auto")
    assert s.tau_controller is not None
    s.step(iter([batch(i) for i in range(64)]), 3 * s.tau)
    snap = s.tau_controller.snapshot()
    assert snap["rounds"] >= 2 and snap["decisions"]
    assert all(
        snap["tau_min"] <= d["next_tau"] <= snap["tau_max"]
        for d in snap["decisions"]
    )
    path = s.tau_controller.write_report(str(tmp_path / "run"))
    import json

    with open(path) as f:
        assert json.load(f)["decisions"]
    report = s.comm_report()
    assert report["tau_controller"]["rounds"] == snap["rounds"]
    assert report["buckets"]["buckets"] >= 1


# --------------------------------------------------------- round buffer

def test_round_buffer_bit_identical_and_counted():
    from sparknet_tpu.telemetry import REGISTRY

    buf = RoundBuffer()
    reuse0 = REGISTRY.counter("round_buffer", event="reuse").snapshot()
    alloc0 = REGISTRY.counter("round_buffer", event="alloc").snapshot()
    rounds = []
    for r in range(5):
        bl = [batch(10 * r + i) for i in range(3)]
        from sparknet_tpu.parallel import stack_round_batches

        want = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *bl
        )
        got = stack_round_batches(bl, buffer=buf)
        for k in want:
            assert np.array_equal(want[k], np.asarray(got[k])), (r, k)
        rounds.append(got)
    reuse = REGISTRY.counter("round_buffer", event="reuse").snapshot() - reuse0
    alloc = REGISTRY.counter("round_buffer", event="alloc").snapshot() - alloc0
    # depth-3 rotation per key, 2 keys (data/label), 5 rounds
    assert alloc == 2 * RoundBuffer.DEPTH
    assert reuse == 2 * (5 - RoundBuffer.DEPTH)
    # rotation depth really protects the last DEPTH-1 rounds: the last
    # three rounds' buffers are distinct objects
    assert len({id(rounds[r]["data"]) for r in (2, 3, 4)}) == 3
