"""Closed-loop deploy pipeline (ISSUE 18): traffic tee, incremental
trainer, eval gate, gated rolls, burn/regression auto-rollback.

The full e2e (real tier, seeded traffic, gated roll, chaos regression,
auto-rollback with zero failed requests) lives in
scripts/closed_loop_smoke.py (check.sh); these tests pin each
contract fast and CPU-only:

- ``TeeWriter.offer`` never blocks and never raises — a stalled drain
  drops (counted), the request path pays O(1);
- a crashed tee leaves a torn tail that :func:`recover_log`
  quarantines (the ``data.torn_shard`` discipline) while intact
  orphans are adopted;
- trainer restart == continuous training, bitwise, via shard-level
  ``skip(n)`` log-head resume;
- the gate passes agreeing candidates, quarantines poisoned/regressed
  ones with machine-readable verdicts, and the ineligibility ledger
  keeps a rolled-back digest out forever;
- with ``SPARKNET_DEPLOY_GATE`` on, an ungated snapshot is refused at
  every layer: engine (DeployGateError), server /reload (409), router
  roll (409), snapshot watcher (skipped);
- ``engine.rollback()`` restores the resident previous generation
  bitwise and is one-deep (double rollback -> error / 409);
- :class:`RollbackWatch` fires exactly once per armed window.
"""

import json
import os
import time

import numpy as np
import pytest
import jax

from sparknet_tpu import chaos
from sparknet_tpu.chaos.plan import FaultPlan
from sparknet_tpu.data import records as rec
from sparknet_tpu.deploy import gate
from sparknet_tpu.deploy.controller import DeployController
from sparknet_tpu.deploy.rollback import RollbackWatch
from sparknet_tpu.deploy.tee import TeeWriter, recover_log
from sparknet_tpu.deploy.trainer import IncrementalTrainer
from sparknet_tpu.serve.engine import InferenceEngine
from sparknet_tpu.serve.server import InferenceServer
from sparknet_tpu.solver.snapshot import save_state

TRAIN_NET = """
name: "tiny"
layer { name: "d" type: "Input" top: "data" top: "label" }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2"
        bottom: "label" top: "loss" }
"""

DEPLOY_NET = """
name: "tiny"
input: "data"
input_shape { dim: 1 dim: 8 }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "gaussian" std: 0.5 } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "gaussian" std: 0.5 } } }
layer { name: "prob" type: "Softmax" bottom: "ip2" top: "prob" }
"""


@pytest.fixture(autouse=True)
def _chaos_isolation():
    chaos.clear()
    yield
    chaos.clear()


def _rows(n, seed=0):
    return np.random.default_rng(seed).normal(size=(n, 8)).astype(
        np.float32
    )


def _sample(i, seed=0):
    rng = np.random.default_rng(seed + i)
    return {
        "data": rng.normal(size=(8,)).astype(np.float32),
        "label": np.int32(rng.integers(0, 4)),
    }


def _write_nets(tmp_path):
    train = str(tmp_path / "train.prototxt")
    deploy = str(tmp_path / "deploy.prototxt")
    with open(train, "w") as fh:
        fh.write(TRAIN_NET)
    with open(deploy, "w") as fh:
        fh.write(DEPLOY_NET)
    return train, deploy


def _tiny_engine(deploy, seed=7):
    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.proto import caffe_pb

    net = XLANet(caffe_pb.load_net(DEPLOY_NET, is_path=False), "TEST")
    params, state = net.init(jax.random.PRNGKey(seed))
    return InferenceEngine(net, params, state, buckets=(4,))


def _solverstate(tmp_path, name, engine):
    path = str(tmp_path / name)
    save_state(
        path,
        params=jax.device_get(engine.params),
        state=jax.device_get(engine.state),
    )
    return path


# ------------------------------------------------------------ chaos grammar
def test_deploy_fault_points_parse():
    p = FaultPlan(
        "deploy.poison_snapshot@iter=4:frac=0.3,"
        "deploy.regressed_weights@index=1:frac=8"
    )
    assert p.points() == [
        "deploy.poison_snapshot", "deploy.regressed_weights"
    ]
    rule = p.match("deploy.poison_snapshot", index=0, iter=4)
    assert rule is not None and rule.params["frac"] == 0.3
    rule = p.match("deploy.regressed_weights", index=1)
    assert rule is not None and rule.params["frac"] == 8
    assert p.match("deploy.regressed_weights", index=0) is None


# ------------------------------------------------------------------- tee
def test_tee_offer_never_blocks_and_drops_are_counted(tmp_path):
    tee = TeeWriter(str(tmp_path), capacity=64, interval_s=60.0)
    try:
        with tee._io_lock:  # stall the drain: worst case for offer()
            t0 = time.monotonic()
            results = [tee.offer(_sample(i)) for i in range(200)]
            dt = time.monotonic() - t0
        assert results.count(True) == 64
        assert results.count(False) == 136
        assert tee.offered == 64 and tee.dropped == 136
        # the request path pays deque-append + counter, nothing else:
        # 200 offers against a stalled drain finish in well under the
        # <=2% latency budget of any real request
        assert dt / 200 < 1e-3
        tee.flush()
        ds = rec.PackedDataset(str(tmp_path))
        assert ds.num_records == 64
    finally:
        tee.stop()


def test_tee_log_survives_torn_tail_and_adopts_orphans(tmp_path):
    # an intact shard missing from the manifest (crash between finish
    # and manifest publish) is adopted; a torn tail is quarantined
    w = rec.ShardWriter(str(tmp_path / f"shard-{os.getpid()}-00000.snpk"))
    for i in range(6):
        w.add(_sample(i))
    w.finish()
    torn = str(tmp_path / f"shard-{os.getpid()}-00001.snpk")
    w2 = rec.ShardWriter(torn)
    for i in range(6):
        w2.add(_sample(i))
    w2.finish()
    with open(torn, "rb+") as fh:
        fh.truncate(os.path.getsize(torn) // 2)
    summary = recover_log(str(tmp_path))
    assert len(summary["adopted"]) == 1
    assert summary["quarantined"] == [os.path.basename(torn)]
    assert not os.path.exists(torn)
    assert os.path.exists(torn + ".quarantined")
    assert rec.PackedDataset(str(tmp_path)).num_records == 6
    # idempotent: a second recovery changes nothing
    again = recover_log(str(tmp_path))
    assert not again["adopted"] and not again["quarantined"]


def test_multiple_tee_writers_share_one_log(tmp_path):
    # pid-scoped shard names + merge-on-publish manifests: two writers
    # in one process stand in for two replica processes
    a = TeeWriter(str(tmp_path), interval_s=60.0)
    b = TeeWriter(str(tmp_path), interval_s=60.0)
    try:
        for i in range(4):
            a.offer(_sample(i))
        a.flush()
        for i in range(4, 8):
            b.offer(_sample(i))
        b.flush()
        for i in range(8, 12):
            a.offer(_sample(i))
        a.flush()
    finally:
        a.stop()
        b.stop()
    recover_log(str(tmp_path))
    assert rec.PackedDataset(str(tmp_path)).num_records == 12


# ------------------------------------------------------- trainer resume
def test_trainer_restart_is_bitwise_equal_to_continuous(tmp_path):
    train, _ = _write_nets(tmp_path)
    log = str(tmp_path / "log")
    tee = TeeWriter(log, interval_s=60.0)
    try:
        for i in range(16):
            tee.offer(_sample(i))
        tee.flush()

        out_ab = str(tmp_path / "cand_ab")
        tr_a = IncrementalTrainer(log, train, out_ab, batch_size=4, seed=0)
        first = tr_a.run_once()
        assert first and first.endswith("_iter_4.solverstate.npz")
        assert tr_a.run_once() is None  # at the head: nothing new

        # the log grows while the trainer is "down"
        for i in range(16, 32):
            tee.offer(_sample(i))
        tee.flush()
    finally:
        tee.stop()

    # restart: a NEW trainer restores iter 4 and trains to the head
    tr_b = IncrementalTrainer(log, train, out_ab, batch_size=4, seed=0)
    second = tr_b.run_once()
    assert second and second.endswith("_iter_8.solverstate.npz")

    # continuous reference: one trainer sees the full log at once
    out_c = str(tmp_path / "cand_c")
    tr_c = IncrementalTrainer(log, train, out_c, batch_size=4, seed=0)
    ref = tr_c.run_once()
    assert ref and ref.endswith("_iter_8.solverstate.npz")

    from sparknet_tpu.solver.snapshot import load_state

    sa, sc = load_state(second), load_state(ref)
    assert int(np.asarray(sa["it"])) == int(np.asarray(sc["it"])) == 8
    la = jax.tree_util.tree_leaves(sa["params"])
    lc = jax.tree_util.tree_leaves(sc["params"])
    assert la and len(la) == len(lc)
    for x, y in zip(la, lc):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_trainer_first_generation_inits_from_serving_solverstate(tmp_path):
    # the controller hands the trainer the serving baseline (a full
    # .solverstate.npz) as --init-weights; Solver.load_weights must
    # overlay its params rather than choke on a non-caffemodel file
    train, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy, seed=99)
    boot = _solverstate(tmp_path, "boot_iter_1.solverstate.npz", eng)
    log = str(tmp_path / "log")
    tee = TeeWriter(log, interval_s=60.0)
    try:
        for i in range(2):  # < batch_size: solver builds, zero steps
            tee.offer(_sample(i))
        tee.flush()
    finally:
        tee.stop()
    tr = IncrementalTrainer(
        log, train, str(tmp_path / "cand"),
        batch_size=4, seed=0, init_weights=boot,
    )
    assert tr.run_once() is None  # no full batch yet
    from sparknet_tpu.solver.snapshot import load_state

    want = jax.tree_util.tree_leaves(load_state(boot)["params"])
    leaves = jax.tree_util.tree_leaves(tr._solver.params)
    assert leaves and len(leaves) == len(want)
    for x, y in zip(leaves, want):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert tr._solver.iter == 0  # iteration IS the log position


# ---------------------------------------------------------------- gate
def test_gate_passes_agreeing_candidate_and_saves_probe(tmp_path):
    _, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy)
    baseline = _solverstate(tmp_path, "base_iter_1.solverstate.npz", eng)
    cand = _solverstate(tmp_path, "inc_iter_2.solverstate.npz", eng)
    v = gate.evaluate(
        cand, model=deploy, baseline_weights=baseline, probe=_rows(4)
    )
    assert v["verdict"] == "pass" and v["disagree_pct"] == 0.0
    ok, reason = gate.check_eligible(cand)
    assert ok, reason
    saved = gate.load_probe(cand)
    assert saved is not None and len(saved["expected_top1"]) == 4


def test_gate_rejects_disagreeing_candidate_and_quarantines(tmp_path):
    _, deploy = _write_nets(tmp_path)
    baseline = _solverstate(
        tmp_path, "base_iter_1.solverstate.npz", _tiny_engine(deploy, 7)
    )
    cand = _solverstate(
        tmp_path, "inc_iter_2.solverstate.npz", _tiny_engine(deploy, 99)
    )
    v = gate.evaluate(
        cand, model=deploy, baseline_weights=baseline, probe=_rows(16)
    )
    assert v["verdict"] == "fail" and "disagreement" in v["reason"]
    assert not os.path.exists(cand)  # quarantined out of the glob
    assert os.path.exists(cand + gate.QUARANTINE_SUFFIX)
    # the verdict record survives at the original name for the audit
    assert gate.read_verdict(cand)["verdict"] == "fail"
    assert not gate.check_eligible(cand)[0]


def test_poisoned_candidate_is_quarantined_never_served(tmp_path):
    """deploy.poison_snapshot chaos: the candidate is corrupted before
    the gate looks — manifest verification catches it, the file is
    quarantined, and nothing could ever roll it."""
    _, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy)
    baseline = _solverstate(tmp_path, "base_iter_1.solverstate.npz", eng)
    cand = _solverstate(tmp_path, "inc_iter_2.solverstate.npz", eng)
    chaos.install_from("deploy.poison_snapshot@times=1:frac=0.5")
    v = gate.evaluate(
        cand, model=deploy, baseline_weights=baseline, probe=_rows(4)
    )
    assert v["verdict"] == "fail"
    assert "manifest verify failed" in v["reason"]
    assert "chaos poisoned" in v["reason"]
    assert not os.path.exists(cand)
    assert os.path.exists(cand + gate.QUARANTINE_SUFFIX)
    assert gate.check_eligible(cand)[0] is False


def test_ineligibility_ledger_blocks_redeploy(tmp_path):
    _, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy)
    baseline = _solverstate(tmp_path, "base_iter_1.solverstate.npz", eng)
    cand = _solverstate(tmp_path, "inc_iter_2.solverstate.npz", eng)
    v = gate.evaluate(
        cand, model=deploy, baseline_weights=baseline, probe=_rows(4)
    )
    assert v["verdict"] == "pass"
    digest = gate.mark_ineligible(cand, reason="slo_burn")
    assert digest == v["digest"]
    ok, reason = gate.check_eligible(cand)
    assert not ok and "ineligible" in reason
    # machine-checkable: the ledger carries the digest + reason
    ledger = gate.load_ledger(str(tmp_path))
    assert ledger["ineligible"][digest]["reason"] == "slo_burn"
    # re-gating the same bytes refuses too — only a NEW snapshot can
    v2 = gate.evaluate(
        cand, model=deploy, baseline_weights=baseline, probe=_rows(4),
        do_quarantine=False,
    )
    assert v2["verdict"] == "fail" and "ineligible" in v2["reason"]


# ------------------------------------------------ gate enforcement layers
def test_ungated_snapshot_refused_at_engine_server_and_watcher(
    tmp_path, monkeypatch
):
    """ISSUE 18 satellite fix: the verdict is threaded through
    swap_from_file — with gating on, an unverified-or-ungated snapshot
    is a DeployGateError at the engine and a 409 at the server, and
    the watcher skips it instead of parking."""
    from sparknet_tpu.serve import hotswap

    _, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy).warmup()
    baseline = _solverstate(tmp_path, "base_iter_1.solverstate.npz", eng)
    gated = _solverstate(tmp_path, "inc_iter_2.solverstate.npz", eng)
    ungated = _solverstate(tmp_path, "inc_iter_9.solverstate.npz", eng)
    assert gate.evaluate(
        gated, model=deploy, baseline_weights=baseline, probe=_rows(4)
    )["verdict"] == "pass"

    monkeypatch.setenv("SPARKNET_DEPLOY_GATE", "require")
    with pytest.raises(gate.DeployGateError, match="ungated"):
        eng.swap_from_file(ungated)
    assert eng.generation == 0  # the old weights keep serving

    srv = InferenceServer(eng, port=0)
    code, doc = srv.reload(ungated)
    assert code == 409 and "deploy gate" in doc["error"]
    code, doc = srv.reload(gated)
    assert code == 200 and doc["generation"] == 1

    # the watcher falls through the ungated newest to the gated one
    got = hotswap.newest_verified(
        str(tmp_path), eligible=hotswap.gate_eligible_filter()
    )
    assert got is not None and got[1] == gated
    monkeypatch.delenv("SPARKNET_DEPLOY_GATE")
    got = hotswap.newest_verified(
        str(tmp_path), eligible=hotswap.gate_eligible_filter()
    )
    assert got is not None and got[1] == ungated  # gate off: no filter


def test_router_roll_refuses_ungated_snapshot_with_409(
    tmp_path, monkeypatch
):
    from sparknet_tpu.serve.router import Router

    _, deploy = _write_nets(tmp_path)
    ungated = _solverstate(
        tmp_path, "inc_iter_3.solverstate.npz", _tiny_engine(deploy)
    )
    monkeypatch.setenv("SPARKNET_DEPLOY_GATE", "1")
    router = Router([("127.0.0.1", 1)], health_interval_s=9999.0)
    try:
        code, doc = router.roll(ungated)
        assert code == 409
        assert "deploy gate" in doc["error"] and "ungated" in doc["error"]
    finally:
        router.stop()


# ------------------------------------------------------------- rollback
def test_engine_rollback_restores_previous_generation_bitwise(tmp_path):
    _, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy, seed=7).warmup()
    rows = _rows(4)
    out0 = np.asarray(eng.infer(rows))
    other = _tiny_engine(deploy, seed=99)
    eng.swap(other.params, other.state)
    out1 = np.asarray(eng.infer(rows))
    assert not np.array_equal(out0, out1)
    gen = eng.generation
    assert eng.rollback() == gen + 1  # a rollback is still a new gen
    np.testing.assert_array_equal(np.asarray(eng.infer(rows)), out0)
    # one-deep: the consumed previous cannot be rolled back to twice
    with pytest.raises(ValueError, match="no previous generation"):
        eng.rollback()


def test_server_reload_rollback_maps_to_409_when_spent(tmp_path):
    _, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy).warmup()
    other = _tiny_engine(deploy, seed=42)
    eng.swap(other.params, other.state)
    srv = InferenceServer(eng, port=0)
    code, doc = srv.reload(rollback=True)
    assert code == 200 and doc["rolled_back"]
    code, doc = srv.reload(rollback=True)
    assert code == 409


def test_regressed_weights_chaos_fires_after_the_gate(tmp_path):
    """deploy.regressed_weights scales the installed weights AFTER
    load: the gate saw clean bytes, the served generation disagrees —
    exactly the post-gate regression the watch must catch."""
    _, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy).warmup()
    snap_path = _solverstate(
        tmp_path, "inc_iter_2.solverstate.npz", eng
    )
    clean = _tiny_engine(deploy).warmup()
    clean.swap_from_file(snap_path)
    chaos.install_from("deploy.regressed_weights@index=0:frac=64")
    eng.swap_from_file(snap_path)
    probe = _rows(16, seed=3)
    clean_top1 = np.argmax(np.asarray(clean.infer(probe)), axis=-1)
    hot_top1 = np.argmax(np.asarray(eng.infer(probe)), axis=-1)
    assert not np.array_equal(clean_top1, hot_top1)


def test_rollback_watch_fires_exactly_once_per_window():
    t = [0.0]
    w = RollbackWatch(window_s=10.0, regress_pct=2.0, now=lambda: t[0])
    assert w.tick(probe_fn=None, burn_active=True) is None  # unarmed
    w.arm(source="s", previous="p")
    assert w.tick(probe_fn=None, burn_active=True) == "slo_burn"
    # the double burn-fire: disarmed before reporting, so the second
    # tick of the same window must NOT request a second rollback
    assert w.tick(probe_fn=None, burn_active=True) is None
    assert not w.armed and w.fired_reason == "slo_burn"

    # surviving the window disarms with no reason (generation accepted)
    w.arm(source="s2", previous="p")
    t[0] += 11.0
    assert w.tick(probe_fn=None, burn_active=True) is None
    assert not w.armed and w.fired_reason is None

    # live agreement regression past the bar fires; transient probe
    # failures never do
    w.arm(
        source="s3", previous="p",
        probe=np.zeros((4, 8), np.float32),
        expected_top1=np.array([0, 1, 2, 3]),
    )
    assert w.tick(probe_fn=lambda p: None, burn_active=False) is None
    assert w.probe_errors == 1
    assert w.tick(
        probe_fn=lambda p: np.array([0, 1, 2, 3]), burn_active=False
    ) is None
    reason = w.tick(
        probe_fn=lambda p: np.array([3, 2, 1, 0]), burn_active=False
    )
    assert reason is not None and reason.startswith("agreement_regressed")
    assert w.last_disagree_pct == 100.0


# ----------------------------------------------------------- controller
class _StubTier:
    host, port = "127.0.0.1", 1  # never contacted (burn fires first)

    def __init__(self):
        self.rolled, self.rolled_back = [], []

    def roll(self, weights):
        self.rolled.append(weights)
        return 200, {"rolled": [{"replica": 0}, {"replica": 1}]}

    def roll_back(self, reason=""):
        self.rolled_back.append(reason)
        return 200, {"rolled_back": [{"replica": 0}, {"replica": 1}]}


def test_controller_gates_rolls_and_rolls_back_once(tmp_path, monkeypatch):
    train, deploy = _write_nets(tmp_path)
    eng = _tiny_engine(deploy)
    baseline = _solverstate(tmp_path, "boot_iter_0.solverstate.npz", eng)
    tier = _StubTier()
    ctl = DeployController(
        tier,
        deploy_dir=str(tmp_path / "dep"),
        model=deploy,
        train_net=train,
        boot_weights=baseline,
        window_s=60.0,
        probe_n=4,
        min_new_records=4,
        run_trainer=False,
    )
    # seed the log (the probe source) and a candidate
    tee = TeeWriter(ctl.log_dir, interval_s=60.0)
    try:
        for i in range(8):
            tee.offer(_sample(i))
        tee.flush()
    finally:
        tee.stop()
    cand = os.path.join(ctl.candidate_dir, "inc_iter_4.solverstate.npz")
    save_state(
        cand,
        params=jax.device_get(eng.params),
        state=jax.device_get(eng.state),
    )

    assert ctl.tick() is None  # gate + roll + arm
    assert tier.rolled == [cand]
    assert ctl.watch.armed and ctl.rolls == 1
    assert [e["action"] for e in ctl.events] == ["roll"]

    monkeypatch.setattr(
        "sparknet_tpu.telemetry.anomaly.active", lambda kind=None: ["p99"]
    )
    assert ctl.tick() == "slo_burn"  # burn inside the window
    assert tier.rolled_back == ["slo_burn"]
    assert ctl.rollbacks == 1 and ctl.last_rollback_ms is not None
    # idempotent: the burn keeps burning, the tier rolls back ONCE
    assert ctl.tick() is None
    assert len(tier.rolled_back) == 1
    # the rolled-back generation is ledger-ineligible: the controller
    # will not re-gate it and the gate would refuse it anyway
    ok, reason = gate.check_eligible(cand)
    assert not ok and "ineligible" in reason
    snap = ctl.snapshot()
    assert snap["rollbacks"] == 1
    assert [e["action"] for e in snap["events"]] == ["roll", "rollback"]
    assert ctl.baseline == baseline  # never promoted to the bad gen


# ------------------------------------------------- respawn generation re-sync
def test_router_resyncs_respawned_replica_to_serving_generation():
    """A replica respawned after a roll boots on its spawn-time argv
    weights — the router must bring it onto the serving generation
    BEFORE it becomes dispatchable again, or the tier serves mixed
    generations until the next roll (and, post-rollback, could even
    resurrect the exact weights the watch rolled back)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from sparknet_tpu.serve.router import Router

    class _Stub:
        def __init__(self):
            self.generation = 0
            self.weights_source = None
            self.reloads = []
            self.reload_status = 200
            outer = self

            class Handler(BaseHTTPRequestHandler):
                def log_message(self, *a):
                    pass

                def _reply(self, code, payload):
                    body = json.dumps(payload).encode()
                    self.send_response(code)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):
                    self._reply(200, {
                        "status": "ok",
                        "generation": outer.generation,
                        "weights_source": outer.weights_source,
                        "warmup_s": 0.1, "pid": None,
                    })

                def do_POST(self):
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    outer.reloads.append(req.get("weights"))
                    if outer.reload_status != 200:
                        self._reply(outer.reload_status,
                                    {"error": "scripted"})
                        return
                    outer.generation += 1
                    outer.weights_source = req.get("weights")
                    self._reply(200, {
                        "generation": outer.generation,
                        "source": req.get("weights"),
                    })

            self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
            self.httpd.daemon_threads = True
            self.host, self.port = self.httpd.server_address[:2]
            threading.Thread(
                target=self.httpd.serve_forever, daemon=True
            ).start()

        def stop(self):
            self.httpd.shutdown()
            self.httpd.server_close()

    a, b = _Stub(), _Stub()
    router = Router(
        [(a.host, a.port), (b.host, b.port)],
        model_name="stub", health_interval_s=30.0,
    )
    try:
        router.health_tick()
        code, doc = router.roll("/fake/w_iter_2.caffemodel")
        assert code == 200, doc
        assert router._serving_weights == "/fake/w_iter_2.caffemodel"

        # simulate replica 0's respawn: fresh process on boot weights
        a.generation, a.weights_source, a.reloads = 0, None, []
        rep = router.replicas[0]
        rep.healthy = False
        rep.needs_resync = True

        # resync failure (e.g. gate 409): stays OUT of dispatch
        a.reload_status = 409
        router.health_tick()
        assert not rep.healthy and rep.needs_resync
        assert a.reloads == ["/fake/w_iter_2.caffemodel"]

        # resync success: reloaded onto the serving weights, THEN
        # healthy — never dispatchable on the stale generation
        a.reload_status = 200
        router.health_tick()
        assert rep.healthy and not rep.needs_resync
        assert a.weights_source == "/fake/w_iter_2.caffemodel"
        assert rep.generation == 1

        # rollback retargets the resync at what the tier serves NOW —
        # this stub restores boot weights (source None), so re-sync
        # disarms entirely: a respawn boots on those same weights
        code, doc = router.roll_back("agreement_regressed")
        assert code == 200, doc
        assert router._serving_weights is None
    finally:
        router.stop()
        a.stop()
        b.stop()
