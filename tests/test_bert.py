"""BERT MLM family: model numerics, solver integration, app E2E,
text/MLM data layer."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.data.text import (
    MASK,
    NUM_SPECIAL,
    PAD,
    Vocab,
    mlm_dataset,
    mlm_feed,
    mlm_mask,
    synthetic_token_stream,
)
from sparknet_tpu.models.bert import BertConfig, BertMLM


def tiny_model(b=2, s=64, vocab=64):
    cfg = BertConfig.bert_tiny(vocab_size=vocab)
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 8)}
    return BertMLM(cfg, shapes), cfg


def test_bert_base_param_count():
    cfg = BertConfig.bert_base()
    model = BertMLM(cfg, {"input_ids": (1, 128), "mlm_positions": (1, 20)})
    params, _ = model.init(jax.random.PRNGKey(0))
    n = model.num_params(params)
    # published BERT-base: ~110M; ours = 109.51M (encoder+embeddings)
    # + MLM transform head (~0.62M) with tied decoder
    assert 109_000_000 < n < 112_000_000


def test_bert_forward_and_loss():
    model, cfg = tiny_model()
    params, state = model.init(jax.random.PRNGKey(0))
    batch = model.dummy_batch()
    blobs, _ = model.apply(params, state, batch, train=False)
    loss, metrics = model.loss_and_metrics(blobs)
    # untrained loss near ln(vocab)
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0
    assert 0.0 <= float(metrics["mlm_acc"]) <= 1.0


def test_bert_mask_invariance():
    """Padding keys must not influence outputs at valid positions."""
    model, _ = tiny_model(b=1, s=32)
    params, state = model.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(0)
    ids = rng.integers(NUM_SPECIAL, 64, (1, 32)).astype(np.int32)
    mask = np.ones((1, 32), np.int32)
    mask[:, 24:] = 0
    batch = model.dummy_batch()
    batch["input_ids"] = jnp.asarray(ids)
    batch["attention_mask"] = jnp.asarray(mask)
    x1 = model.encode(params, batch, train=False, rng=None)
    # garbage in the padded tail
    ids2 = ids.copy()
    ids2[:, 24:] = (ids2[:, 24:] + 7) % 60 + NUM_SPECIAL
    batch["input_ids"] = jnp.asarray(ids2)
    x2 = model.encode(params, batch, train=False, rng=None)
    np.testing.assert_allclose(
        np.asarray(x1[:, :24]), np.asarray(x2[:, :24]), rtol=1e-4, atol=1e-5
    )


def test_bert_solver_training_reduces_loss():
    from sparknet_tpu.apps import bert_app

    solver, feed, cfg = bert_app.build(
        bert_app.make_args(
            config="tiny", vocab_size=64, seq_len=32, batch_size=8,
            max_iter=30, lr=3e-3, synthetic_tokens=4096,
        )
    )
    m0 = {k: float(v) for k, v in solver.step(feed, 5).items()}
    m1 = {k: float(v) for k, v in solver.step(feed, 25).items()}
    assert m1["loss"] < m0["loss"], (m0, m1)


def test_bert_parallel_sync_and_local():
    from sparknet_tpu.apps import bert_app

    for mode in ("sync", "local"):
        solver, feed, _ = bert_app.build(
            bert_app.make_args(
                config="tiny", vocab_size=64, seq_len=32, batch_size=8,
                max_iter=4, parallel=mode, tau=2, synthetic_tokens=4096,
            )
        )
        m = solver.step(feed, 4)
        assert np.isfinite(float(m["loss"]))
        assert solver.iter == 4


def test_bert_flash_vs_reference_attention():
    """Same params, same batch: flash (interpret) and reference attention
    paths must agree."""
    cfg = BertConfig.bert_tiny(vocab_size=64)
    shapes = {"input_ids": (1, 128), "mlm_positions": (1, 8)}
    m_ref = BertMLM(cfg, shapes, attention_impl="reference")
    params, state = m_ref.init(jax.random.PRNGKey(2))
    batch = m_ref.dummy_batch()
    rng = np.random.default_rng(1)
    batch["input_ids"] = jnp.asarray(
        rng.integers(NUM_SPECIAL, 64, (1, 128)), jnp.int32
    )
    out_ref, _ = m_ref.apply(params, state, batch, train=False)

    import sparknet_tpu.models.bert as B
    from sparknet_tpu.ops import attention as A

    m_flash = BertMLM(cfg, shapes, attention_impl="flash")
    orig = A.flash_attention

    def interp_flash(*a, **kw):
        kw["interpret"] = True
        return orig(*a, **kw)

    B.attention.__globals__["flash_attention"] = interp_flash
    try:
        out_flash, _ = m_flash.apply(params, state, batch, train=False)
    finally:
        B.attention.__globals__["flash_attention"] = orig
    np.testing.assert_allclose(
        float(out_ref["loss"]), float(out_flash["loss"]), rtol=1e-4
    )


# -- text data layer --------------------------------------------------------

def test_vocab_roundtrip():
    v = Vocab.from_corpus(["the cat sat on the mat", "the dog"])
    assert v.encode(["the"]) == [NUM_SPECIAL]  # most frequent first
    assert v.encode(["zebra"]) == [1]  # UNK


def test_synthetic_stream_learnable_structure():
    s = synthetic_token_stream(1000, 64, seed=0)
    assert s.min() >= NUM_SPECIAL and s.max() < 64
    # 80% transitions follow the deterministic successor table
    succ = (np.arange(59) * 17 + 3) % 59
    follows = np.mean(succ[s[:-1] - NUM_SPECIAL] + NUM_SPECIAL == s[1:])
    assert follows > 0.7


def test_mlm_mask_properties():
    rng = np.random.default_rng(0)
    toks = np.full(64, 10, np.int64)
    toks[0] = 2  # CLS never maskable
    out, pos, labels, w = mlm_mask(toks, rng, 64, max_preds=12)
    n = int(w.sum())
    assert 1 <= n <= 12
    assert (pos[:n] != 0).all()  # CLS at 0 never chosen
    assert (labels[:n] == 10).all()
    # masked positions changed to MASK/random mostly
    changed = sum(out[p] != toks[p] for p in pos[:n])
    assert changed >= n // 2


def test_mlm_feed_shapes():
    ds, vsize = mlm_dataset(vocab_size=64, n_tokens=4096, seq_len=32)
    feed = mlm_feed(ds, 8, vsize, max_preds=5, seed=0)
    b = next(feed)
    assert b["input_ids"].shape == (8, 32)
    assert b["input_ids"][0, 0] == 2  # CLS
    assert b["mlm_positions"].shape == (8, 5)
    assert b["attention_mask"].dtype == np.int32
    assert (b["mlm_weights"].sum(1) >= 1).all()


def test_bert_app_long_context_max_position():
    """--max-position grows the position table past BERT's 512 so long
    sequences train; an overlong --seq-len without it errors clearly."""
    import pytest

    from sparknet_tpu.apps import bert_app

    solver, feed, cfg = bert_app.build(
        bert_app.make_args(
            config="tiny", seq_len=256, max_position=256, batch_size=2,
            max_iter=1,
        )
    )
    assert cfg.max_position == 256
    m = solver.step(feed, 1)
    assert float(m["loss"]) > 0

    with pytest.raises(ValueError, match="max_position"):
        bert_app.build(
            bert_app.make_args(config="tiny", seq_len=512, batch_size=2)
        )


@pytest.mark.parametrize(
    "mode,extra",
    [
        ("sp", ["--mesh", "dp=2,sp=4"]),
        ("tp", ["--mesh", "dp=2,tp=2,sp=2"]),
        ("pp", ["--mesh", "dp=2,pp=2", "--pp-microbatches", "2"]),
        ("ep", ["--mesh", "dp=2,ep=4", "--moe-experts", "4"]),
    ],
)
@pytest.mark.slow
def test_bert_app_model_parallel_modes(mode, extra):
    """Every model-parallel axis is reachable from the app CLI (the
    same step factories the driver dryrun exercises)."""
    from sparknet_tpu.apps import bert_app

    metrics = bert_app.main(
        [
            "--config", "tiny", "--parallel", mode, "--batch-size", "4",
            "--seq-len", "64", "--max-iter", "2", "--display", "2",
        ]
        + extra
    )
    assert np.isfinite(metrics["loss"])
