"""Test env: force an 8-device virtual CPU mesh before jax import.

Multi-chip hardware is unavailable in CI; all sharding tests run on
``xla_force_host_platform_device_count=8`` CPU devices, mirroring how the
driver dry-runs the multi-chip path.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
