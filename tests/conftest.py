"""Test env: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding tests run on
``xla_force_host_platform_device_count=8`` CPU devices, mirroring how the
driver dry-runs the multi-chip path. Note: this environment pins
``JAX_PLATFORMS=axon`` (the TPU tunnel) and re-asserts it over the env
var, so we must force CPU through ``jax.config`` — the env var alone is
not honored.

``SPARKNET_TEST_TPU=1`` keeps the real backend instead, for the
hardware-gated tests (scripts/tpu_measure.sh runs them that way).
"""

import os

if os.environ.get("SPARKNET_TEST_TPU", "") not in ("", "0"):
    pass  # real accelerator: leave the backend alone
else:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
