"""Test env: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding tests run on
``xla_force_host_platform_device_count=8`` CPU devices, mirroring how the
driver dry-runs the multi-chip path. Note: this environment pins
``JAX_PLATFORMS=axon`` (the TPU tunnel) and re-asserts it over the env
var, so we must force CPU through ``jax.config`` — the env var alone is
not honored.

``SPARKNET_TEST_TPU=1`` keeps the real backend instead, for the
hardware-gated tests (scripts/tpu_measure.sh runs them that way).

The suite is compile-bound (every jit traces + XLA-compiles), so a
persistent compilation cache (``.jax_cache/``, gitignored) is enabled
for all backends: a warm run skips recompilation entirely, keeping
``pytest -m "not slow"`` inside a CI round's budget. Delete the dir to
force cold compiles; ``SPARKNET_TEST_NO_CACHE=1`` disables it.
"""

import os

if os.environ.get("SPARKNET_TEST_TPU", "") not in ("", "0"):
    import jax  # real accelerator: leave the backend alone
else:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

if os.environ.get("SPARKNET_TEST_NO_CACHE", "") in ("", "0"):
    _cache_dir = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    )
    # through BOTH the config (this process) and the env (so the many
    # subprocess-spawning tests — app CLIs, multi-host clusters, bench
    # invocations — share the same cache; jax reads these at init)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
    # min compile time 1s, NOT 0: persisting the near-instant compiles
    # deterministically segfaults this jaxlib's cache serialization
    # (reproduced on test_snapshot's resume tests — the crash that was
    # truncating every tier-1 run at ~60% since the seed; 2026-08-04).
    # Sub-second compiles are cheaper to redo than the crash costs.
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    # config mirrors the POST-setdefault env values, so a user-provided
    # JAX_COMPILATION_CACHE_DIR keeps parent and subprocess tests in the
    # SAME cache (the whole point) instead of splitting them
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        int(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
    )
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes",
        int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]),
    )


import glob
import multiprocessing

import pytest


@pytest.fixture(autouse=True, scope="session")
def assert_no_pipeline_leaks(tmp_path_factory):
    """Tier-1 runs on CPU and must stay leak-free: after the whole
    session, no input-pipeline worker process may still be alive — the
    originals AND the chaos-era *respawned* replacements (named
    ``{SHM_PREFIX}-worker-{r}-r{n}``; a supervisor that forgets its
    respawns would pass a naive check) — and no shared-memory slot may
    survive in /dev/shm, including the replacement slots respawns add
    (``..._r{n}`` names).  data/pipeline.py names everything with the
    SHM_PREFIX, so stray ones are attributable.

    The cross-job decoded-batch cache (data/cache.py) persists named
    ``{SHM_CACHE_PREFIX}_*`` segments ON PURPOSE across jobs — but a
    test run is a closed world: every test that opens a cache namespace
    must ``clear()`` it, and any segment that survives the session is
    an orphan this fixture names."""
    yield
    import re

    from sparknet_tpu.data.cache import SHM_CACHE_PREFIX
    from sparknet_tpu.data.pipeline import SHM_PREFIX

    stray = [
        p for p in multiprocessing.active_children()
        if p.name.startswith(SHM_PREFIX)
    ]
    respawned = [p for p in stray if re.search(r"-r\d+$", p.name)]
    assert not stray, (
        f"input-pipeline workers leaked past tests: {stray}"
        + (f" (orphaned respawned workers: {respawned})" if respawned else "")
    )
    if os.path.isdir("/dev/shm"):
        segs = glob.glob(f"/dev/shm/{SHM_PREFIX}_*")
        assert not segs, f"shared-memory segments leaked past tests: {segs}"
        cache_segs = glob.glob(f"/dev/shm/{SHM_CACHE_PREFIX}_*")
        assert not cache_segs, (
            f"decoded-batch cache segments leaked past tests (a test "
            f"opened a cache namespace without clear()): {cache_segs}"
        )
    # storage-fault hygiene (utils/safeio.py): every atomic writer
    # must either publish (rename) or unlink its staging file, even
    # under injected ENOSPC/EIO, and an abandoned tee shard must be
    # renamed ``.writing.quarantined`` — so NO bare ``*.tmp*`` or
    # ``*.writing`` file may survive the suite anywhere under pytest's
    # session temp root.
    base = str(tmp_path_factory.getbasetemp())
    stale = []
    for root, _dirs, files in os.walk(base):
        for name in files:
            if name.endswith(".writing") or ".tmp" in name:
                stale.append(os.path.join(root, name))
    assert not stale, (
        f"staging files leaked past tests (a writer failed without "
        f"cleaning up its tmp, or a torn tee shard was not "
        f"quarantined): {stale[:20]}"
    )
