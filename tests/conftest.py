"""Test env: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding tests run on
``xla_force_host_platform_device_count=8`` CPU devices, mirroring how the
driver dry-runs the multi-chip path. Note: this environment pins
``JAX_PLATFORMS=axon`` (the TPU tunnel) and re-asserts it over the env
var, so we must force CPU through ``jax.config`` — the env var alone is
not honored.

``SPARKNET_TEST_TPU=1`` keeps the real backend instead, for the
hardware-gated tests (scripts/tpu_measure.sh runs them that way).

The suite is compile-bound (every jit traces + XLA-compiles), so a
persistent compilation cache (``.jax_cache/``, gitignored) is enabled
for all backends: a warm run skips recompilation entirely, keeping
``pytest -m "not slow"`` inside a CI round's budget. Delete the dir to
force cold compiles; ``SPARKNET_TEST_NO_CACHE=1`` disables it.
"""

import os

if os.environ.get("SPARKNET_TEST_TPU", "") not in ("", "0"):
    import jax  # real accelerator: leave the backend alone
else:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

if os.environ.get("SPARKNET_TEST_NO_CACHE", "") in ("", "0"):
    _cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.abspath(_cache_dir))
    # cache every entry, however small/fast — the suite's cost is many
    # medium compiles, not a few giant ones
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
