"""Solver math (lr policies, update rules) and end-to-end training."""

import numpy as np
import jax
import jax.numpy as jnp

from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.solver.caffe_solver import (
    init_opt_state,
    learning_rate,
    make_update_fn,
)
from sparknet_tpu.solver.trainer import Solver

from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
ZOO = REPO / "sparknet_tpu" / "models" / "prototxt"


def sp_from(text: str) -> caffe_pb.SolverParameter:
    return caffe_pb.load_solver(text, is_path=False)


def test_lr_policies():
    it = jnp.asarray(1000, jnp.int32)
    np.testing.assert_allclose(
        float(learning_rate(sp_from("base_lr: 0.1 lr_policy: 'fixed'"), it)), 0.1, rtol=1e-6
    )
    lr = learning_rate(
        sp_from("base_lr: 0.1 lr_policy: 'step' gamma: 0.5 stepsize: 400"), it
    )
    np.testing.assert_allclose(float(lr), 0.1 * 0.5**2, rtol=1e-6)
    lr = learning_rate(
        sp_from("base_lr: 0.1 lr_policy: 'inv' gamma: 0.0001 power: 0.75"), it
    )
    np.testing.assert_allclose(float(lr), 0.1 * (1 + 0.0001 * 1000) ** -0.75, rtol=1e-6)
    lr = learning_rate(
        sp_from(
            "base_lr: 0.1 lr_policy: 'multistep' gamma: 0.1 stepvalue: 500 stepvalue: 2000"
        ),
        it,
    )
    np.testing.assert_allclose(float(lr), 0.01, rtol=1e-6)
    lr = learning_rate(
        sp_from("base_lr: 0.1 lr_policy: 'poly' power: 2 max_iter: 2000"), it
    )
    np.testing.assert_allclose(float(lr), 0.1 * 0.25, rtol=1e-6)


def test_sgd_momentum_update_matches_caffe_formula():
    sp = sp_from("base_lr: 0.1 momentum: 0.9 weight_decay: 0.01 lr_policy: 'fixed'")
    params = {"l": {"weight": jnp.asarray([1.0, -2.0])}}
    grads = {"l": {"weight": jnp.asarray([0.5, 0.25])}}
    opt = init_opt_state(sp, params)
    update = make_update_fn(sp)
    it = jnp.asarray(0, jnp.int32)

    # v1 = 0.9*0 + 0.1*(g + 0.01*w); w1 = w - v1
    g_reg = np.array([0.5 + 0.01 * 1.0, 0.25 + 0.01 * -2.0])
    v1 = 0.1 * g_reg
    p1, opt = update(params, grads, opt, it)
    np.testing.assert_allclose(np.asarray(p1["l"]["weight"]), [1.0, -2.0] - v1, rtol=1e-6)
    # second step accumulates momentum
    p2, opt = update(p1, grads, opt, it)
    g_reg2 = np.array(
        [0.5 + 0.01 * float(p1["l"]["weight"][0]), 0.25 + 0.01 * float(p1["l"]["weight"][1])]
    )
    v2 = 0.9 * v1 + 0.1 * g_reg2
    np.testing.assert_allclose(
        np.asarray(p2["l"]["weight"]), np.asarray(p1["l"]["weight"]) - v2, rtol=1e-6
    )


def test_lr_mult_and_clip():
    sp = sp_from("base_lr: 1.0 momentum: 0.0 lr_policy: 'fixed' clip_gradients: 1.0")
    params = {"l": {"weight": jnp.asarray([0.0]), "bias": jnp.asarray([0.0])}}
    grads = {"l": {"weight": jnp.asarray([3.0]), "bias": jnp.asarray([4.0])}}
    lr_m = {"l": {"weight": 1.0, "bias": 2.0}}
    dec_m = {"l": {"weight": 1.0, "bias": 0.0}}
    update = make_update_fn(sp, lr_m, dec_m)
    opt = init_opt_state(sp, params)
    p, _ = update(params, grads, opt, jnp.asarray(0, jnp.int32))
    # ||g|| = 5 -> scale 0.2; bias lr_mult 2 -> step 2*0.8
    np.testing.assert_allclose(float(p["l"]["weight"][0]), -0.6, rtol=1e-6)
    np.testing.assert_allclose(float(p["l"]["bias"][0]), -1.6, rtol=1e-6)


def test_adam_first_step_magnitude():
    sp = sp_from("base_lr: 0.001 type: 'Adam' momentum: 0.9 momentum2: 0.999 lr_policy: 'fixed'")
    params = {"l": {"w": jnp.asarray([1.0])}}
    grads = {"l": {"w": jnp.asarray([10.0])}}
    opt = init_opt_state(sp, params)
    update = make_update_fn(sp)
    p, _ = update(params, grads, opt, jnp.asarray(0, jnp.int32))
    # Adam's first step is ~lr regardless of grad magnitude
    np.testing.assert_allclose(float(p["l"]["w"][0]), 1.0 - 0.001, rtol=1e-3)


def test_end_to_end_memorize():
    """cifar10_quick with a higher LR memorizes a fixed 8-sample batch:
    loss must drop below 0.1 — exercises forward, backward, and update."""
    sp = caffe_pb.load_solver(str(ZOO / "cifar10_quick_solver.prototxt"))
    sp.base_lr = 0.01
    shapes = {"data": (8, 32, 32, 3), "label": (8,)}
    s = Solver(sp, shapes, solver_dir=str(REPO))
    rng = np.random.default_rng(0)
    batch = {
        "data": jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32),
        "label": jnp.asarray(np.arange(8) % 10, jnp.int32),
    }

    def batches():
        while True:
            yield batch

    m = s.step(batches(), 150)
    assert float(m["loss"]) < 0.1, f"did not memorize: loss={float(m['loss'])}"
    acc = s.test(batches(), 1)
    assert acc["accuracy"] == 1.0


def test_iter_size_accumulation_matches_full_batch():
    """iter_size=2 over two half-batches == one full batch (mean losses)."""
    net_text = """
    name: "tiny"
    layer { name: "d" type: "Input" top: "data" top: "label" }
    layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
            inner_product_param { num_output: 3
              weight_filler { type: "gaussian" std: 0.1 } } }
    layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
    """
    net_param = caffe_pb.load_net(net_text, is_path=False)
    rng = np.random.default_rng(0)
    data = rng.normal(size=(8, 5)).astype(np.float32)
    labels = (np.arange(8) % 3).astype(np.int32)

    def run(iter_size, shapes, feed):
        sp = sp_from(f"base_lr: 0.5 momentum: 0.9 lr_policy: 'fixed' iter_size: {iter_size}")
        s = Solver(sp, shapes, net_param=net_param, seed=3)
        s.step(iter(feed), 1)
        return np.asarray(s.params["ip"]["weight"])

    full = run(
        1,
        {"data": (8, 5), "label": (8,)},
        [{"data": jnp.asarray(data), "label": jnp.asarray(labels)}],
    )
    halves = run(
        2,
        {"data": (4, 5), "label": (4,)},
        [
            {"data": jnp.asarray(data[:4]), "label": jnp.asarray(labels[:4])},
            {"data": jnp.asarray(data[4:]), "label": jnp.asarray(labels[4:])},
        ],
    )
    np.testing.assert_allclose(full, halves, rtol=1e-5, atol=1e-6)


def test_solver_without_net_raises():
    import pytest

    with pytest.raises(ValueError, match="no net"):
        Solver(sp_from("base_lr: 0.1 lr_policy: 'fixed'"), {})


def test_average_loss_and_test_initialization():
    """average_loss smooths displayed losses over the window; the
    parsed test_initialization/average_loss fields carry defaults."""
    from sparknet_tpu.proto import caffe_pb

    sp = caffe_pb.load_solver(
        "net: \"x\"\nbase_lr: 0.1\nlr_policy: \"fixed\"\n"
        "average_loss: 3\ntest_initialization: false\nmax_iter: 6\n"
        "display: 1\n",
        is_path=False,
    )
    assert sp.average_loss == 3 and sp.test_initialization is False
    # defaults (Caffe: test_initialization true, average_loss 1)
    sp2 = caffe_pb.load_solver(
        "net: \"x\"\nbase_lr: 0.1\nlr_policy: \"fixed\"\n", is_path=False
    )
    assert sp2.test_initialization is True and sp2.average_loss == 1

    import numpy as np

    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "t"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 2
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""
    sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
    sp.net = sp.train_net = None
    solver = Solver(sp, {"data": (4, 8), "label": (4,)})
    rng = np.random.default_rng(0)

    def feed():
        while True:
            yield {
                "data": rng.normal(size=(4, 8)).astype(np.float32),
                "label": rng.integers(0, 2, 4).astype(np.int32),
            }

    logged = []
    solver.step(feed(), 6, log_fn=lambda it, m: logged.append((it, m["loss"])))
    assert len(logged) == 6
    # the 3rd displayed loss must equal the mean of the first 3 raw
    # losses — recompute from a replay with average_loss=1
    sp_raw = caffe_pb.load_solver(
        "base_lr: 0.1\nlr_policy: \"fixed\"\nmax_iter: 6\ndisplay: 1\n",
        is_path=False,
    )
    sp_raw.net_param = sp.net_param
    solver2 = Solver(sp_raw, {"data": (4, 8), "label": (4,)})
    rng = np.random.default_rng(0)
    raw = []
    solver2.step(feed(), 6, log_fn=lambda it, m: raw.append(m["loss"]))
    np.testing.assert_allclose(
        logged[2][1], np.mean(raw[:3]), rtol=1e-6
    )
    np.testing.assert_allclose(
        logged[5][1], np.mean(raw[3:6]), rtol=1e-6
    )


def test_stop_requested_cooperative_stop():
    """stop_requested (the preemption-grace hook) must stop BOTH solver
    types at an iteration boundary and leave the solver reusable once
    the flag is cleared."""
    from sparknet_tpu.parallel import ParallelSolver, make_mesh

    sp = sp_from(
        "base_lr: 0.01 lr_policy: 'fixed' max_iter: 100\n"
        "net_param { name: 'n'\n"
        "  layer { name: 'data' type: 'Input' top: 'data'\n"
        "          input_param { shape { dim: 8 dim: 4 } } }\n"
        "  layer { name: 'label' type: 'Input' top: 'label'\n"
        "          input_param { shape { dim: 8 } } }\n"
        "  layer { name: 'ip' type: 'InnerProduct' bottom: 'data' top: 'ip'\n"
        "          inner_product_param { num_output: 3\n"
        "            weight_filler { type: 'xavier' } } }\n"
        "  layer { name: 'loss' type: 'SoftmaxWithLoss'\n"
        "          bottom: 'ip' bottom: 'label' top: 'loss' } }"
    )
    import itertools

    def feed():
        batch = {
            "data": jnp.ones((8, 4), jnp.float32),
            "label": jnp.zeros((8,), jnp.int32),
        }
        return itertools.repeat(batch)

    shapes = {"data": (8, 4), "label": (8,)}
    for make in (
        lambda: Solver(sp, shapes),
        lambda: ParallelSolver(
            sp, shapes, mesh=make_mesh({"dp": 2}, jax.devices()[:2]),
            mode="local", tau=2,
        ),
    ):
        solver = make()
        solver.step(feed(), 4)
        assert solver.iter == 4
        solver.stop_requested = True
        solver.step(feed(), 10)
        assert solver.iter == 4  # stopped at the boundary, no progress
        solver.stop_requested = False  # consumed -> reusable
        solver.step(feed(), 2)
        assert solver.iter == 6


def test_remat_matches_no_remat():
    """Per-layer rematerialization must be numerically transparent: the
    same seed and batches give (near-)identical params after training,
    including through BatchNorm state and PRNG-keyed dropout (masks
    recompute from the same fold_in key, not from saved buffers)."""
    import itertools

    net_txt = """
    net_param { name: 'remat'
      layer { name: 'data' type: 'Input' top: 'data'
              input_param { shape { dim: 4 dim: 8 dim: 8 dim: 3 } } }
      layer { name: 'label' type: 'Input' top: 'label'
              input_param { shape { dim: 4 } } }
      layer { name: 'conv' type: 'Convolution' bottom: 'data' top: 'conv'
              convolution_param { num_output: 6 kernel_size: 3 pad: 1
                weight_filler { type: 'xavier' } } }
      layer { name: 'bn' type: 'BatchNorm' bottom: 'conv' top: 'bn' }
      layer { name: 'relu' type: 'ReLU' bottom: 'bn' top: 'bn' }
      layer { name: 'drop' type: 'Dropout' bottom: 'bn' top: 'bn'
              dropout_param { dropout_ratio: 0.3 } }
      layer { name: 'ip' type: 'InnerProduct' bottom: 'bn' top: 'ip'
              inner_product_param { num_output: 5
                weight_filler { type: 'xavier' } } }
      layer { name: 'loss' type: 'SoftmaxWithLoss'
              bottom: 'ip' bottom: 'label' top: 'loss' } }
    base_lr: 0.05 lr_policy: 'fixed' momentum: 0.9 max_iter: 20
    """
    sp = sp_from(net_txt)
    shapes = {"data": (4, 8, 8, 3), "label": (4,)}
    rng = np.random.default_rng(5)
    batch = {
        "data": jnp.asarray(rng.normal(size=shapes["data"]), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 5, 4), jnp.int32),
    }

    def train(remat):
        s = Solver(sp, shapes, seed=11, remat=remat)
        s.step(itertools.repeat(batch), 5)
        return jax.device_get(s.params), jax.device_get(s.state)

    p0, st0 = train(False)
    p1, st1 = train(True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6),
        (p0, st0), (p1, st1),
    )


def test_step_compiler_options_env_contract(monkeypatch):
    """The SPARKNET_SCOPED_VMEM_KIB knob: default on TPU, 0/blank (and
    padded spellings) disable, garbage fails fast, CPU always off."""
    from sparknet_tpu.solver import trainer as T

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("SPARKNET_SCOPED_VMEM_KIB", raising=False)
    assert T._step_compiler_options() == {
        "xla_tpu_scoped_vmem_limit_kib": "32768"
    }
    for off in ("0", " 0 ", ""):
        monkeypatch.setenv("SPARKNET_SCOPED_VMEM_KIB", off)
        assert T._step_compiler_options() is None
    monkeypatch.setenv("SPARKNET_SCOPED_VMEM_KIB", "49152")
    assert T._step_compiler_options() == {
        "xla_tpu_scoped_vmem_limit_kib": "49152"
    }
    monkeypatch.setenv("SPARKNET_SCOPED_VMEM_KIB", "32M")
    try:
        T._step_compiler_options()
    except ValueError as e:
        assert "SPARKNET_SCOPED_VMEM_KIB" in str(e)
    else:
        raise AssertionError("garbage value must fail fast")
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    monkeypatch.delenv("SPARKNET_SCOPED_VMEM_KIB", raising=False)
    assert T._step_compiler_options() is None


def test_step_compile_kw_forwards_to_jit(monkeypatch):
    """The option dict must actually reach jax.jit as
    ``compiler_options`` (the kwarg name is load-bearing: a typo would
    silently compile without the option on TPU while every CPU test
    stays green). On CPU the kw is empty; forwarding is asserted by
    building a Solver under a faked TPU backend with jit intercepted."""
    from sparknet_tpu.solver import trainer as T

    seen = []
    real_jit = jax.jit

    def spy_jit(fn, **kw):
        seen.append(kw.get("compiler_options"))
        kw.pop("compiler_options", None)  # CPU jit would reject it
        return real_jit(fn, **kw)

    monkeypatch.setattr(T.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(T.jax, "jit", spy_jit)
    monkeypatch.delenv("SPARKNET_SCOPED_VMEM_KIB", raising=False)
    sp = sp_from("base_lr: 0.1 lr_policy: 'fixed'")
    net = caffe_pb.load_net(
        """layer { name: "d" type: "Input" top: "data" top: "label" }
           layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
             inner_product_param { num_output: 3 } }
           layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip"
             bottom: "label" top: "loss" }""",
        is_path=False,
    )
    Solver(sp, {"data": (4, 5), "label": (4,)}, net_param=net)
    assert {"xla_tpu_scoped_vmem_limit_kib": "32768"} in seen

    # bench's per-arch override relies on Solver evaluating the env AT
    # CONSTRUCTION (eager jit in _finish_init): inside _arch_env the
    # build must see the override, and the env must restore after. A
    # refactor deferring jit creation would silently void ARCH_ENV —
    # this pins the ordering contract.
    import os

    import bench

    seen.clear()
    with bench._arch_env("resnet50"):
        Solver(sp, {"data": (4, 5), "label": (4,)}, net_param=net)
    assert seen and all(o is None for o in seen), seen
    assert "SPARKNET_SCOPED_VMEM_KIB" not in os.environ


def test_scan_steps_trains_like_step_loop():
    """scan_steps(batch, n) — the tunnel-proof bench primitive — runs n
    real iterations in one dispatch: iter advances by n, the loss
    descends like the equivalent step() loop (rng streams differ, so
    trajectories are compared loosely, not bitwise), and iter_size>1
    micro-batch stacking compiles through the scan."""
    sp = sp_from("base_lr: 0.5 momentum: 0.9 lr_policy: 'fixed'")
    net_text = """
    name: "tiny"
    layer { name: "d" type: "Input" top: "data" top: "label" }
    layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
            inner_product_param { num_output: 3
              weight_filler { type: "gaussian" std: 0.1 } } }
    layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
    """
    net_param = caffe_pb.load_net(net_text, is_path=False)
    rng = np.random.default_rng(0)
    batch = {
        "data": jnp.asarray(rng.normal(size=(8, 5)), jnp.float32),
        "label": jnp.asarray(np.arange(8) % 3, jnp.int32),
    }
    shapes = {"data": (8, 5), "label": (8,)}

    scan = Solver(sp, shapes, net_param=net_param, seed=3)
    m = scan.scan_steps(batch, 30)
    assert scan.iter == 30
    scanned_loss = float(m["loss"])

    loop = Solver(sp, shapes, net_param=net_param, seed=3)

    def batches():
        while True:
            yield batch

    loop_loss = float(loop.step(batches(), 30)["loss"])
    assert scanned_loss < 0.25 and loop_loss < 0.25, (scanned_loss, loop_loss)
    # same work per iteration: the two trainings land in the same basin
    assert abs(scanned_loss - loop_loss) < 0.15, (scanned_loss, loop_loss)

    # iter_size>1: one micro-batch stacks iter_size-fold through the scan
    sp2 = sp_from("base_lr: 0.1 momentum: 0.9 lr_policy: 'fixed' iter_size: 2")
    s2 = Solver(sp2, shapes, net_param=net_param, seed=3)
    m2 = s2.scan_steps(batch, 3)
    assert s2.iter == 3 and np.isfinite(float(m2["loss"]))
