"""Model-zoo coverage: GoogLeNet and ResNet-50 (BASELINE.json configs
3 and 4). Param counts are checked against the published totals — an
exact match means every conv/fc/BN in the generated prototxts has the
canonical geometry."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.nets.xlanet import XLANet
from sparknet_tpu.solver.trainer import Solver

ZOO = os.path.join(
    os.path.dirname(__file__), "..", "sparknet_tpu", "models", "prototxt"
)


def _count(params):
    return sum(int(np.prod(v.shape)) for lp in params.values() for v in lp.values())


@pytest.mark.parametrize(
    "proto,total",
    [
        # published totals: GoogLeNet 13.38M incl. both aux heads,
        # ResNet-50 25.557M
        ("bvlc_googlenet_train_val.prototxt", 13_378_280),
        ("resnet50_train_val.prototxt", 25_557_032),
        # VGG-16 configuration D published total
        ("vgg16_train_val.prototxt", 138_357_544),
    ],
)
def test_zoo_shapes_and_param_counts(proto, total):
    npm = caffe_pb.load_net(os.path.join(ZOO, proto))
    for phase in ("TRAIN", "TEST"):
        net = XLANet(npm, phase, {"data": (2, 224, 224, 3), "label": (2,)})
        assert net.blob_shapes["label"] == (2,)
    params, _ = net.init(jax.random.PRNGKey(0))
    assert _count(params) == total


def test_zoo_regen_is_stable(tmp_path):
    """zoo_gen output matches the files checked into the zoo."""
    from sparknet_tpu.models import zoo_gen

    for fname, gen in zoo_gen.GENERATED.items():
        with open(os.path.join(ZOO, fname)) as f:
            assert f.read() == gen(), f"{fname} drifted from generator"


def _one_step(solver_file, crop=224, bs=2, n=1):
    sp = caffe_pb.load_solver(os.path.join(ZOO, solver_file))
    shapes = {"data": (bs, crop, crop, 3), "label": (bs,)}
    s = Solver(sp, shapes, solver_dir=ZOO)
    rng = np.random.default_rng(0)
    batch = {
        "data": jnp.asarray(rng.normal(size=shapes["data"]), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 1000, (bs,)), jnp.int32),
    }

    def feed():
        while True:
            yield batch

    return s, feed


@pytest.mark.slow
def test_resnet50_trains():
    s, feed = _one_step("resnet50_solver.prototxt")
    m0 = {k: float(v) for k, v in s.step(feed(), 1).items()}
    assert np.isfinite(m0["loss/loss"])
    # BatchNorm running stats must update in TRAIN phase
    bn = s.state["bn_conv1"]
    assert float(jnp.abs(bn["mean"]).sum()) > 0
    m5 = {k: float(v) for k, v in s.step(feed(), 5).items()}
    assert m5["loss/loss"] < m0["loss/loss"]  # memorizes the fixed batch


@pytest.mark.slow
def test_googlenet_trains():
    s, feed = _one_step("bvlc_googlenet_quick_solver.prototxt")
    m = {k: float(v) for k, v in s.step(feed(), 1).items()}
    # three heads, aux weighted 0.3 (weighting applied in the loss sum,
    # metrics report the raw per-head values)
    for k in ("loss1/loss", "loss2/loss", "loss3/loss"):
        assert np.isfinite(m[k])
    # initial CE should be near ln(1000)
    assert abs(m["loss3/loss"] - np.log(1000.0)) < 1.5


def test_lenet_param_count_and_train():
    """The classic MNIST LeNet: published total 431,080 params
    (20·1·5·5+20 + 50·20·5·5+50 + 500·800+500 + 10·500+10); grayscale
    28x28 inputs flow through with 1 channel."""
    npm = caffe_pb.load_net(os.path.join(ZOO, "lenet_train_test.prototxt"))
    net = XLANet(npm, "TRAIN", {"data": (4, 28, 28, 1), "label": (4,)})
    params, _ = net.init(jax.random.PRNGKey(0))
    assert _count(params) == 431_080
    sp = caffe_pb.load_solver(os.path.join(ZOO, "lenet_solver.prototxt"))
    sp.max_iter = 2
    solver = Solver(sp, {"data": (4, 28, 28, 1), "label": (4,)}, solver_dir=ZOO)
    rng = np.random.default_rng(0)
    batch = {
        "data": jnp.asarray(rng.normal(size=(4, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, 4), jnp.int32),
    }

    def feed():
        while True:
            yield batch

    m = solver.step(feed(), 2)
    assert np.isfinite(float(m["loss"]))
