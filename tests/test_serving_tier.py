"""Serving tier (ISSUE 9): router semantics, continuous batching,
zero-downtime hot-swap, the child pool, and the persistent compile
cache's keying.

The expensive chaos e2e (subprocess replicas, SIGKILL + rolling swap +
cache-hit respawn) lives in scripts/serving_smoke.py (check.sh); these
tests pin the same semantics fast: stub HTTP replicas for router
behavior (no jax in the backend), the toy deploy net for real-engine
swaps, stub engines for batch-composition proofs."""

import json
import os
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest
import jax

from sparknet_tpu.serve.batcher import MicroBatcher
from sparknet_tpu.serve.compile_cache import cache_entries, net_fingerprint
from sparknet_tpu.serve.engine import InferenceEngine
from sparknet_tpu.serve.metrics import ServeMetrics
from sparknet_tpu.serve.router import Router
from sparknet_tpu.serve.server import InferenceServer

TOY_DEPLOY = """
name: "toy"
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 5
          weight_filler { type: "gaussian" std: 0.2 } } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""


def toy_net(seed=7):
    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.proto import caffe_pb

    net = XLANet(caffe_pb.load_net(TOY_DEPLOY, is_path=False), "TEST")
    params, state = net.init(jax.random.PRNGKey(seed))
    return net, params, state


def toy_rows(n, seed=0):
    return (
        np.random.default_rng(seed)
        .normal(size=(n, 8, 8, 3))
        .astype(np.float32)
    )


# ------------------------------------------------------- stub replicas
class _StubReplica:
    """A scriptable replica: echoes the first row value back as the
    top-1 index, so the test can match answers to requests exactly.
    ``die_next`` drops one /classify connection with no response (the
    kill-mid-request shape); ``sick`` fails /healthz."""

    def __init__(self):
        self.generation = 0
        self.reloads = []
        self.served = []
        self.die_next = False
        self.sick = False
        self.reload_status = 200
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz" and not outer.sick:
                    self._reply(200, {
                        "status": "ok", "generation": outer.generation,
                        "warmup_s": 0.1, "pid": None,
                    })
                else:
                    self._reply(500, {"error": "sick"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
                if self.path == "/reload":
                    outer.reloads.append(req.get("weights"))
                    if outer.reload_status != 200:
                        self._reply(outer.reload_status,
                                    {"error": "scripted failure"})
                        return
                    outer.generation += 1
                    self._reply(200, {"generation": outer.generation,
                                      "source": req.get("weights")})
                    return
                if outer.die_next:
                    outer.die_next = False
                    self.connection.close()  # vanish mid-request
                    return
                rid = int(req["rows"][0][0])
                outer.served.append(rid)
                self._reply(200, {
                    "indices": [[rid]], "probs": [[1.0]],
                    "gen": outer.generation,
                })

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def stub_pair():
    a, b = _StubReplica(), _StubReplica()
    router = Router(
        [(a.host, a.port), (b.host, b.port)],
        model_name="stub", health_interval_s=0.1,
    )
    assert router.wait_healthy(timeout_s=10)
    yield a, b, router
    router.stop()
    a.stop()
    b.stop()


def _classify(router, rid):
    code, payload, _ = router.dispatch(
        json.dumps({"rows": [[float(rid)]]}).encode()
    )
    return code, json.loads(payload)


# ---------------------------------------------------------- router core
def test_router_retries_killed_replica_on_peer(stub_pair):
    """ISSUE 9 satellite: a replica dying mid-request costs latency,
    never answers — every request answered exactly once, correctly."""
    a, b, router = stub_pair
    a.die_next = True
    b.die_next = False
    rids = list(range(20))
    answers = []
    for rid in rids:
        code, doc = _classify(router, rid)
        assert code == 200, doc
        answers.append(doc["indices"][0][0])
    # zero dropped, zero duplicated: the echoed ids are exactly the
    # requested ids, and the one dropped connection was retried
    assert answers == rids
    assert sorted(a.served + b.served) == rids
    assert router.metrics.snapshot()["retries"] >= 1


def test_router_least_outstanding_spreads_load(stub_pair):
    a, b, router = stub_pair
    for rid in range(30):
        code, _ = _classify(router, rid)
        assert code == 200
    # both replicas served (ties round-robin; outstanding always 0 in
    # this serial loop, so the spread must come from rotation)
    assert a.served and b.served
    assert len(a.served) + len(b.served) == 30


def test_router_ejects_sick_replica_and_rejoins(stub_pair):
    a, b, router = stub_pair
    a.sick = True
    for _ in range(4):
        router.health_tick()
    hz = router.healthz()
    assert hz["replicas_healthy"] == 1 and hz["status"] == "degraded"
    # traffic flows around the ejected replica
    before = len(a.served)
    for rid in range(10):
        code, _ = _classify(router, rid)
        assert code == 200
    assert len(a.served) == before  # nothing routed to the sick one
    a.sick = False
    for _ in range(2):
        router.health_tick()
    assert router.healthz()["replicas_healthy"] == 2
    snap = router.metrics.snapshot()
    assert snap["ejects"] >= 1 and snap["rejoins"] >= 1


def test_router_rolling_reload_one_at_a_time(stub_pair):
    a, b, router = stub_pair
    code, doc = router.roll("/fake/w_iter_20.solverstate.npz")
    assert code == 200, doc
    assert [r["replica"] for r in doc["rolled"]] == [0, 1]
    assert a.reloads == ["/fake/w_iter_20.solverstate.npz"]
    assert b.reloads == ["/fake/w_iter_20.solverstate.npz"]
    assert router.healthz()["generations"] == [1]


def test_router_roll_stops_at_first_failure(stub_pair):
    """A bad snapshot fails on replica 0 and never reaches replica 1 —
    the tier keeps a serving majority on the old generation."""
    a, b, router = stub_pair
    a.reload_status = 409
    code, doc = router.roll("/fake/torn.solverstate.npz")
    assert code == 502
    assert doc["errors"] and not doc["rolled"]
    assert b.reloads == []  # the roll never advanced past the failure


def test_router_all_replicas_down_returns_503():
    a = _StubReplica()
    router = Router([(a.host, a.port)], health_interval_s=0.1)
    assert router.wait_healthy(timeout_s=10)
    a.stop()
    for _ in range(4):
        router.health_tick()
    code, payload, headers = router.dispatch(
        json.dumps({"rows": [[1.0]]}).encode()
    )
    assert code == 503
    assert dict(headers).get("Retry-After")
    router.stop()


# --------------------------------------------------- continuous batching
class _RecordingEngine:
    """Duck-typed engine: first call blocks until released (so tests
    can saturate the queue deterministically), every call's batch
    composition is recorded."""

    buckets = (1, 8)

    def __init__(self):
        self.calls = []
        self.release = threading.Event()
        self.started = threading.Event()

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def infer(self, rows):
        self.started.set()
        assert self.release.wait(10)
        self.calls.append(np.asarray(rows).copy())
        return np.asarray(rows)


def _composition_run(mode):
    """Sentinel request (absorbs the cold start), then 16 two-row
    requests queued while the engine is blocked — from the release on,
    the batcher is saturated."""
    eng = _RecordingEngine()
    b = MicroBatcher(
        eng, max_batch=8, max_latency_us=500_000, max_queue=999,
        mode=mode,
    )
    sentinel = b.submit(np.full((1, 1), -1.0, np.float32))
    assert eng.started.wait(10)
    futs = [
        b.submit(np.full((2, 1), float(i), np.float32))
        for i in range(16)
    ]
    eng.release.set()
    assert sentinel.result(timeout=10) is not None
    for f in futs:
        f.result(timeout=10)
    b.drain()
    # compositions after the sentinel batch: the saturated phase
    return [tuple(c[:, 0].astype(int)) for c in eng.calls[1:]]


def test_continuous_equals_fill_at_saturation():
    """ISSUE 9 satellite: at saturation the continuous admitter is
    batch-for-batch identical to fill-then-flush — same compositions,
    same order (outputs are then trivially bit-equal)."""
    fill = _composition_run("fill")
    cont = _composition_run("continuous")
    assert fill == cont
    assert len(fill) == 4  # 16 requests x 2 rows in 8-row batches
    assert all(len(c) == 8 for c in fill)


def test_continuous_dispatches_small_bucket_at_low_rate():
    """A lone request must NOT wait out the co-rider window: with no
    predicted arrivals, waiting buys padding, not throughput."""

    class _Instant:
        buckets = (1, 8)

        def bucket_for(self, n):
            return 1 if n <= 1 else 8

        def infer(self, rows):
            return np.asarray(rows)

    window_s = 0.4
    b = MicroBatcher(
        _Instant(), max_batch=8, max_latency_us=int(window_s * 1e6),
        mode="continuous",
    )
    t0 = time.perf_counter()
    b.submit(np.zeros((1, 1), np.float32)).result(timeout=10)
    dt = time.perf_counter() - t0
    b.drain()
    assert dt < window_s / 2, (
        f"continuous admitter waited the window ({dt:.3f}s)"
    )


def test_fill_waits_window_baseline():
    """The contrast case: fill-then-flush DOES wait the window for a
    lone request — the p99 cost the continuous admitter removes."""

    class _Instant:
        buckets = (8,)

        def infer(self, rows):
            return np.asarray(rows)

    window_s = 0.3
    b = MicroBatcher(
        _Instant(), max_batch=8, max_latency_us=int(window_s * 1e6),
        mode="fill",
    )
    t0 = time.perf_counter()
    b.submit(np.zeros((1, 1), np.float32)).result(timeout=10)
    dt = time.perf_counter() - t0
    b.drain()
    assert dt >= window_s * 0.8


def test_batcher_rejects_unknown_mode():
    with pytest.raises(ValueError, match="fill|continuous"):
        MicroBatcher(_RecordingEngine(), mode="bogus")


# ------------------------------------------------------- engine hot-swap
def test_engine_swap_same_arch_no_recompile_new_outputs():
    net, params, state = toy_net(seed=1)
    eng = InferenceEngine(net, params, state, buckets=(4,)).warmup()
    rows = toy_rows(3)
    out0 = eng.infer(rows)
    n_exec = len(eng._cache)
    _, params2, state2 = toy_net(seed=2)
    gen = eng.swap(params2, state2, source="seed2")
    assert gen == 1 and eng.generation == 1
    assert len(eng._cache) == n_exec  # weights are arguments: no compile
    out1, tag = eng.infer_tagged(rows)
    assert tag == 1
    assert not np.array_equal(out0, out1)
    # bit-identical to a direct apply with the new weights
    import jax.numpy as jnp

    ref = net.apply(
        jax.tree_util.tree_map(jnp.asarray, params2),
        jax.tree_util.tree_map(jnp.asarray, state2),
        {"data": jnp.asarray(rows)}, train=False, rng=None,
    )[0]["prob"]
    np.testing.assert_array_equal(out1, np.asarray(ref))


def test_engine_generation_monotonic_across_swaps():
    net, params, state = toy_net()
    eng = InferenceEngine(net, params, state, buckets=(2,)).warmup()
    seen = []
    for i in range(4):
        _, gen = eng.infer_tagged(toy_rows(1))
        seen.append(gen)
        _, p, s = toy_net(seed=10 + i)
        eng.swap(p, s)
    _, gen = eng.infer_tagged(toy_rows(1))
    seen.append(gen)
    assert seen == sorted(seen) == [0, 1, 2, 3, 4]


def test_engine_swap_from_torn_snapshot_keeps_old_generation(tmp_path):
    from sparknet_tpu.solver.snapshot import SnapshotError, save_state

    net, params, state = toy_net()
    eng = InferenceEngine(net, params, state, buckets=(2,)).warmup()
    path = str(tmp_path / "w_iter_5.solverstate.npz")
    save_state(path, params=jax.device_get(eng.params),
               state=jax.device_get(eng.state))
    with open(path, "rb+") as fh:  # tear it
        fh.truncate(os.path.getsize(path) // 2)
    out0 = eng.infer(toy_rows(2))
    with pytest.raises(SnapshotError):
        eng.swap_from_file(path)
    assert eng.generation == 0  # the old weights keep serving
    np.testing.assert_array_equal(out0, eng.infer(toy_rows(2)))


def test_fingerprint_keys_arch_not_weights():
    """ISSUE 9 satellite (the stale-executable fix): the executable
    cache key carries the net/params fingerprint — same arch with new
    weights shares it, a different arch can never collide."""
    net, params, state = toy_net(seed=1)
    _, params2, state2 = toy_net(seed=2)
    fp1 = net_fingerprint(net, params, state)
    fp2 = net_fingerprint(net, params2, state2)
    assert fp1 == fp2  # weights are not part of the executable identity

    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.proto import caffe_pb

    other_proto = TOY_DEPLOY.replace("num_output: 5", "num_output: 6")
    net_b = XLANet(caffe_pb.load_net(other_proto, is_path=False), "TEST")
    params_b, state_b = net_b.init(jax.random.PRNGKey(1))
    assert net_fingerprint(net_b, params_b, state_b) != fp1

    eng = InferenceEngine(net, params, state, buckets=(2,)).warmup()
    # the executable cache is keyed by the engine's (dtype-qualified)
    # fingerprint — and a weights swap leaves that key unchanged
    assert all(key[0] == eng.fingerprint for key in eng._cache)
    eng.swap(params2, state2)
    assert all(key[0] == eng.fingerprint for key in eng._cache)
    # dtype still distinguishes entries for the same arch
    assert net_fingerprint(net, params, state, "bfloat16") != (
        net_fingerprint(net, params, state, "float32")
    )


def test_cache_entries_counts_files(tmp_path):
    assert cache_entries(str(tmp_path)) == 0
    assert cache_entries(str(tmp_path / "missing")) == 0
    (tmp_path / "jit_x-cache").write_bytes(b"x")
    (tmp_path / ".hidden").write_bytes(b"x")
    assert cache_entries(str(tmp_path)) == 1


# -------------------------------------------------------- snapshot watch
def test_snapshot_watcher_fires_on_newer_verified_only(tmp_path):
    from sparknet_tpu.serve.hotswap import SnapshotWatcher, newest_verified
    from sparknet_tpu.solver.snapshot import save_state

    prefix = str(tmp_path / "run" / "snap")
    tree = {"w": np.arange(4.0)}
    save_state(f"{prefix}_iter_10.solverstate.npz", params=tree)
    fired = []
    w = SnapshotWatcher(str(tmp_path / "run"), lambda it, p: fired.append(it))
    assert w.poll_once() == (10, f"{prefix}_iter_10.solverstate.npz")
    assert w.poll_once() is None  # nothing newer
    # a torn newest file is skipped, never swapped to
    torn = f"{prefix}_iter_20.solverstate.npz"
    save_state(torn, params=tree)
    with open(torn, "rb+") as fh:
        fh.truncate(os.path.getsize(torn) // 2)
    assert w.poll_once() is None
    assert w.torn_seen >= 1
    assert newest_verified(str(tmp_path / "run"))[0] == 10
    # an intact newer one fires
    save_state(f"{prefix}_iter_30.solverstate.npz", params=tree)
    assert w.poll_once()[0] == 30
    assert fired == [10, 30]


def test_snapshot_watcher_start_iter_suppresses_boot_snapshot(tmp_path):
    from sparknet_tpu.serve.hotswap import SnapshotWatcher
    from sparknet_tpu.solver.snapshot import save_state

    prefix = str(tmp_path / "snap")
    save_state(f"{prefix}_iter_10.solverstate.npz",
               params={"w": np.zeros(2)})
    w = SnapshotWatcher(prefix, lambda it, p: None, start_iter=10)
    assert w.poll_once() is None  # already serving iter 10


# ----------------------------------------------------------- child pool
def _fast_cfg(**kw):
    from sparknet_tpu.supervise.policy import Config

    kw.setdefault("backoff_s", 0.01)
    kw.setdefault("max_backoff_s", 0.02)
    kw.setdefault("flap_window_s", 9999.0)
    kw.setdefault("healthy_s", 9999.0)
    return Config(**kw)


def test_child_pool_respawns_then_gives_up():
    from sparknet_tpu.supervise.pool import GIVEN_UP, ChildPool

    pool = ChildPool(
        lambda i, s: [sys.executable, "-c", "import sys; sys.exit(3)"],
        1, config=_fast_cfg(max_restarts=2, flap_limit=99),
    ).start()
    events = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        events += pool.tick()
        if pool.children[0].state == GIVEN_UP:
            break
        time.sleep(0.02)
    child = pool.children[0]
    assert child.state == GIVEN_UP
    assert child.spawn_count == 3  # initial + 2 budgeted respawns
    kinds = [e["event"] for e in events]
    assert kinds.count("give_up") == 1
    assert "restart budget spent" in child.give_up_reason
    pool.stop()


def test_child_pool_clean_exit_stays_down():
    from sparknet_tpu.supervise.pool import STOPPED, ChildPool

    pool = ChildPool(
        lambda i, s: [sys.executable, "-c", "pass"], 1,
        config=_fast_cfg(),
    ).start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        pool.tick()
        if pool.children[0].state == STOPPED:
            break
        time.sleep(0.02)
    assert pool.children[0].state == STOPPED
    assert pool.children[0].spawn_count == 1  # never respawned
    pool.stop()


def test_child_pool_kill_and_respawn_flow():
    from sparknet_tpu.supervise.pool import RUNNING, ChildPool

    pool = ChildPool(
        lambda i, s: [sys.executable, "-c", "import time; time.sleep(60)"],
        2, config=_fast_cfg(max_restarts=5),
    ).start()
    try:
        first_pid = pool.children[0].pid
        assert pool.kill(0)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pool.tick()
            c = pool.children[0]
            if c.state == RUNNING and c.pid != first_pid:
                break
            time.sleep(0.02)
        assert pool.children[0].pid != first_pid
        # the peer never flinched
        assert pool.children[1].spawn_count == 1
        assert len(pool.alive()) == 2
    finally:
        pool.stop()


def test_replica_kill_chaos_point_registered():
    from sparknet_tpu.chaos.plan import FAULT_POINTS, FaultPlan

    assert "serve.replica_kill" in FAULT_POINTS
    plan = FaultPlan("serve.replica_kill@tick=3:worker=1", seed=0)
    assert plan.match("serve.replica_kill", tick=3, worker=1) is not None
    assert plan.match("serve.replica_kill", tick=3, worker=0) is None
    assert plan.match("serve.replica_kill", tick=2, worker=1) is None


# --------------------------------------- real engine behind the router
def test_router_over_real_servers_swap_generations():
    """End-to-end in-process: two real engine replicas, HTTP loadgen
    through the router, a rolling swap mid-life — zero failures and
    monotone generations."""
    from sparknet_tpu.serve.loadgen import run_http_loadgen

    servers, engines = [], []
    for seed in (1, 2):
        net, params, state = toy_net(seed)
        m = ServeMetrics((4,))
        eng = InferenceEngine(
            net, params, state, buckets=(4,), metrics=m
        ).warmup()
        srv = InferenceServer(
            eng, metrics=m, port=0, model_name="toy",
            batcher=MicroBatcher(eng, max_latency_us=2000, metrics=m,
                                 mode="continuous"),
        ).start()
        servers.append(srv)
        engines.append(eng)
    router = Router(
        [(s.host, s.port) for s in servers],
        model_name="toy", health_interval_s=0.1,
    ).start()
    try:
        assert router.wait_healthy(timeout_s=10)
        rec = run_http_loadgen(
            router.host, router.port, (8, 8, 3),
            n_requests=30, sizes=(1, 2, 3), concurrency=3,
        )
        assert rec["failed_requests"] == 0
        assert rec["served_generations"] == [0]

        import tempfile

        from sparknet_tpu.solver.snapshot import save_state

        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "w_iter_9.solverstate.npz")
            save_state(path,
                       params=jax.device_get(engines[0].params),
                       state=jax.device_get(engines[0].state))
            code, doc = router.roll(path)
            assert code == 200 and len(doc["rolled"]) == 2
        rec2 = run_http_loadgen(
            router.host, router.port, (8, 8, 3),
            n_requests=20, sizes=(1, 2), concurrency=2,
        )
        assert rec2["failed_requests"] == 0
        assert rec2["served_generations"] == [1]
        assert router.healthz()["generations"] == [1]
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_server_reload_route_and_classify_gen(tmp_path):
    """Single replica surface: /reload swaps (manifest-verified), the
    response and /healthz carry the generation, torn files 409."""
    from sparknet_tpu.solver.snapshot import save_state

    net, params, state = toy_net()
    m = ServeMetrics((2,))
    eng = InferenceEngine(net, params, state, buckets=(2,),
                          metrics=m).warmup()
    srv = InferenceServer(
        eng, metrics=m, port=0,
        batcher=MicroBatcher(eng, metrics=m),
    ).start()
    try:
        c = srv.client()
        st, resp = c.classify(toy_rows(1))
        assert st == 200 and resp["gen"] == 0
        path = str(tmp_path / "w_iter_3.solverstate.npz")
        save_state(path, params=jax.device_get(eng.params),
                   state=jax.device_get(eng.state))
        st, resp = c.reload(path)
        assert st == 200 and resp["generation"] == 1
        st, hz = c.healthz()
        assert hz["generation"] == 1
        assert hz["weights_source"] == path
        st, resp = c.classify(toy_rows(1))
        assert resp["gen"] == 1
        # torn file -> 409, generation unchanged
        with open(path, "rb+") as fh:
            fh.truncate(os.path.getsize(path) // 2)
        st, resp = c.reload(path)
        assert st == 409 and "torn" in resp["error"]
        assert c.healthz()[1]["generation"] == 1
        snap = m.snapshot()
        assert snap["hot_swaps"] == 1 and snap["generation"] == 1
    finally:
        srv.stop()


def test_classify_from_decoded_batch_cache():
    """ISSUE 9 satellite: a replica attached read-only to the PR 8
    decoded-batch cache classifies by cache_key — the rows never cross
    the wire — and the data_cache counters surface in /healthz and
    /metrics."""
    from sparknet_tpu.data.cache import ShmBatchCache

    ns = f"servetier-{os.getpid()}"
    writer = ShmBatchCache(namespace=ns, max_bytes=int(8e6))
    reader = ShmBatchCache(namespace=ns, readonly=True)
    try:
        rows = toy_rows(2, seed=5)
        assert writer.put("batch-0", {"data": rows})
        assert not reader.put("nope", {"data": rows})  # readonly no-op

        net, params, state = toy_net()
        m = ServeMetrics((2,))
        eng = InferenceEngine(net, params, state, buckets=(2,),
                              metrics=m).warmup()
        srv = InferenceServer(
            eng, metrics=m, port=0, data_cache=reader,
            batcher=MicroBatcher(eng, metrics=m),
        ).start()
        try:
            c = srv.client()
            st, via_cache = c.classify_cached("batch-0", top_k=3)
            assert st == 200
            st, via_wire = c.classify(rows, top_k=3)
            assert via_cache["indices"] == via_wire["indices"]
            st, missing = c.classify_cached("no-such-batch")
            assert st == 404
            st, hz = c.healthz()
            assert hz["data_cache"]["hits"] >= 1
            # the counters also ride the Prometheus scrape via the
            # registry's data_cache source
            import urllib.request

            text = urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/metrics"
            ).read().decode()
            assert "data_cache" in text
        finally:
            srv.stop()
    finally:
        writer.clear()


# ------------------------------------------------------- dash + bench_diff
def test_dash_renders_router_section():
    from sparknet_tpu.telemetry.dash import render_html

    router_snap = {
        "replicas_healthy": 1, "replicas_total": 2,
        "generations": [3],
        "router": {
            "retries": 5, "failed": 0, "replica_deaths": 1,
            "respawns": 1, "rolls": 2,
            "request_latency": {"p99_ms": 12.5},
        },
        "replicas": [
            {"index": 0, "healthy": True, "addr": "h:1",
             "outstanding": 2, "generation": 3, "forwarded": 10,
             "latency": {"p50_ms": 4.0, "p99_ms": 9.0}},
            {"index": 1, "healthy": False, "addr": "h:2",
             "outstanding": 0, "generation": 2, "forwarded": 7,
             "latency": {}},
        ],
    }
    html = render_html({"uptime_s": 1.0}, router=router_snap)
    assert "Serving tier" in html
    assert "replica 0" in html and "replica 1" in html
    assert "ejected" in html and "1/2" in html
    # without a router snapshot the section is absent
    assert "Serving tier" not in render_html({"uptime_s": 1.0})


def test_bench_diff_learns_serving_fields(tmp_path):
    old = {
        "metric": "serving_tier_p99_ms_continuous", "value": 50.0,
        "p50_ms": 20.0, "p99_ms": 50.0, "p99_improvement": 1.5,
        "warm_restart_speedup": 4.0,
        "tier": {"failed_requests": 0, "served_generations": [0, 1]},
    }
    good = dict(old, p99_ms=48.0,
                tier={"failed_requests": 0,
                      "served_generations": [0, 1]})
    bad = dict(old, p99_ms=90.0,
               tier={"failed_requests": 2,
                     "served_generations": [0]})
    pa, pb, pc = (str(tmp_path / f"{n}.json") for n in "abc")
    for p, doc in ((pa, old), (pb, good), (pc, bad)):
        with open(p, "w") as fh:
            json.dump(doc, fh)
    script = os.path.join(
        os.path.dirname(__file__), "..", "scripts", "bench_diff.py"
    )
    ok = subprocess.run(
        [sys.executable, script, pa, pb],
        capture_output=True, text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad_run = subprocess.run(
        [sys.executable, script, pa, pc],
        capture_output=True, text=True,
    )
    assert bad_run.returncode == 1
    assert "failed_requests" in bad_run.stdout
    assert "ZERO is the bar" in bad_run.stdout
    assert "p99_ms" in bad_run.stdout
    assert "served_generations" in bad_run.stdout
