"""Realistic-shape parallelism steps (VERDICT r03 weak #5): the toy
dryrun shapes (bert_tiny, S=64) can hide pspec/memory logic that only
trips at size — e.g. a block size that divides 64 but not 512, a
capacity computation that overflows a shard, a reshape that silently
assumes seq == hidden. One 8-device CPU step per axis at
bert_small/S=512 catches that class.

Slow-marked (each step is a real fwd+bwd compile at size on CPU);
deselect with ``-m 'not slow'`` for quick iteration.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.data.text import mlm_dataset, mlm_feed_tokens
from sparknet_tpu.models.bert import BertConfig, BertMLM
from sparknet_tpu.parallel.mesh import make_mesh
from sparknet_tpu.proto.caffe_pb import SolverParameter
from sparknet_tpu.solver.caffe_solver import init_opt_state

B, S, VOCAB = 8, 512, 4096


def _sp():
    return SolverParameter(
        base_lr=1e-4, lr_policy="fixed", solver_type="ADAMW",
        momentum=0.9, weight_decay=0.01, max_iter=10,
    )


def _cfg(**overrides):
    c = BertConfig.bert_small()
    return dataclasses.replace(c, vocab_size=VOCAB, max_position=S,
                               **overrides)


def _batch(seq=S):
    ds, vs = mlm_dataset(vocab_size=VOCAB, n_tokens=B * seq * 2, seq_len=seq)
    feed = mlm_feed_tokens(ds, B, vs, seed=0)
    return {k: jnp.asarray(v) for k, v in next(feed).items()}


def _assert_step(step, params, batch):
    p, _, m = step(params, init_opt_state(_sp(), params), batch,
                   jnp.asarray(0, jnp.int32), jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss"])), m
    return p


@pytest.mark.slow
def test_tp_sp_bert_small_s512():
    """dp2 x tp2 x sp2 at bert_small/S=512 (ring attention shards)."""
    cfg = _cfg()
    shapes = {"input_ids": (B, S), "mlm_positions": (B, 8)}
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2}, jax.devices()[:8])
    model = BertMLM(cfg, shapes, attention_impl="ring", tp_axis="tp",
                    sp_axis="sp")
    params, _ = model.init(jax.random.PRNGKey(0))
    from sparknet_tpu.parallel.tensor import make_tp_train_step

    step = make_tp_train_step(model, _sp(), mesh, dp_axis="dp",
                              tp_axis="tp", sp_axis="sp")
    _assert_step(step, params, _batch())


@pytest.mark.slow
def test_pp_bert_small_s512():
    """dp2 x pp4 at bert_small/S=512, 2 microbatches."""
    cfg = _cfg()
    shapes = {"input_ids": (B, S), "mlm_positions": (B, 8)}
    mesh = make_mesh({"dp": 2, "pp": 4}, jax.devices()[:8])
    model = BertMLM(cfg, shapes)
    params, _ = model.init(jax.random.PRNGKey(0))
    from sparknet_tpu.parallel.pipeline import (
        make_pp_train_step,
        stack_layer_params,
    )

    stacked, rest = stack_layer_params(params, cfg.num_layers)
    step = make_pp_train_step(model, _sp(), mesh, n_micro=2, dp_axis="dp")
    _assert_step(step, {"layers": stacked, "rest": rest}, _batch())


@pytest.mark.slow
def test_ep_bert_small_s512():
    """dp2 x ep4 at bert_small/S=512 with 8 experts, sort dispatch."""
    cfg = _cfg(moe_num_experts=8, moe_dispatch="sort",
               moe_capacity_factor=1.25, moe_top_k=2)
    shapes = {"input_ids": (B, S), "mlm_positions": (B, 8)}
    mesh = make_mesh({"dp": 2, "ep": 4}, jax.devices()[:8])
    model = BertMLM(cfg, shapes, ep_axis="ep")
    params, _ = model.init(jax.random.PRNGKey(0))
    from sparknet_tpu.parallel.expert import make_ep_train_step

    step = make_ep_train_step(model, _sp(), mesh, dp_axis="dp",
                              ep_axis="ep")
    _assert_step(step, params, _batch())
