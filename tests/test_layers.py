"""Layer numerics vs torch-CPU oracle (NCHW<->NHWC adapted)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.proto.caffe_pb import LayerParameter
from sparknet_tpu.proto.textformat import parse
from sparknet_tpu.nets import layers as L

torch = pytest.importorskip("torch")
import torch.nn.functional as F


def lp_from(text: str) -> LayerParameter:
    return LayerParameter.from_message(parse(text))


def nhwc(x_nchw: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1)))


def to_nchw(y: jnp.ndarray) -> np.ndarray:
    return np.transpose(np.asarray(y), (0, 3, 1, 2))


CTX = L.ApplyCtx(train=False, rng=None)


@pytest.mark.parametrize(
    "cin,cout,k,s,p,d,g",
    [
        (3, 8, 3, 1, 1, 1, 1),
        (4, 6, 5, 2, 2, 1, 2),
        (3, 8, 3, 1, 2, 2, 1),
        (8, 8, 1, 1, 0, 1, 8),  # depthwise
    ],
)
def test_convolution_vs_torch(cin, cout, k, s, p, d, g):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, cin, 13, 11)).astype(np.float32)
    w = rng.normal(size=(cout, cin // g, k, k)).astype(np.float32)
    b = rng.normal(size=(cout,)).astype(np.float32)

    lp = lp_from(
        f'name: "c" type: "Convolution" convolution_param {{ '
        f"num_output: {cout} kernel_size: {k} stride: {s} pad: {p} "
        f"dilation: {d} group: {g} }}"
    )
    params = {"weight": jnp.asarray(np.transpose(w, (2, 3, 1, 0))), "bias": jnp.asarray(b)}
    (y,), _ = L.Convolution.apply(lp, params, None, [nhwc(x)], CTX)
    ref = F.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
        stride=s, padding=p, dilation=d, groups=g,
    ).numpy()
    np.testing.assert_allclose(to_nchw(y), ref, rtol=2e-5, atol=2e-5)


def test_max_pool_ceil_mode_vs_torch():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 4, 11, 11)).astype(np.float32)
    lp = lp_from('name: "p" type: "Pooling" pooling_param { pool: MAX kernel_size: 3 stride: 2 }')
    (y,), _ = L.Pooling.apply(lp, {}, None, [nhwc(x)], CTX)
    ref = F.max_pool2d(torch.from_numpy(x), 3, 2, 0, ceil_mode=True).numpy()
    assert to_nchw(y).shape == ref.shape
    np.testing.assert_allclose(to_nchw(y), ref, rtol=1e-6)


def test_ave_pool_caffe_divisor():
    # Caffe AVE pooling: window clipped to padded region; divisor counts
    # padding. Construct the reference directly.
    rng = np.random.default_rng(2)
    H = W = 5
    k, s, p = 3, 2, 1
    x = rng.normal(size=(1, 1, H, W)).astype(np.float32)
    lp = lp_from(
        'name: "p" type: "Pooling" pooling_param { pool: AVE kernel_size: 3 stride: 2 pad: 1 }'
    )
    (y,), _ = L.Pooling.apply(lp, {}, None, [nhwc(x)], CTX)
    y = to_nchw(y)[0, 0]

    oh = L._pool_out(H, k, s, p)
    ref = np.zeros((oh, oh), np.float32)
    for i in range(oh):
        for j in range(oh):
            hs, ws = i * s - p, j * s - p
            he, we = min(hs + k, H + p), min(ws + k, W + p)
            pool_size = (he - hs) * (we - ws)
            hs0, ws0 = max(hs, 0), max(ws, 0)
            he0, we0 = min(he, H), min(we, W)
            ref[i, j] = x[0, 0, hs0:he0, ws0:we0].sum() / pool_size
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


def test_lrn_across_channels_vs_torch():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 8, 6, 6)).astype(np.float32)
    size, alpha, beta, k = 5, 1e-4, 0.75, 1.0
    lp = lp_from(
        f'name: "n" type: "LRN" lrn_param {{ local_size: {size} alpha: {alpha} beta: {beta} }}'
    )
    (y,), _ = L.LRN.apply(lp, {}, None, [nhwc(x)], CTX)
    ref = torch.nn.LocalResponseNorm(size, alpha, beta, k)(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(to_nchw(y), ref, rtol=1e-5, atol=1e-6)


def test_softmax_with_loss_vs_torch():
    rng = np.random.default_rng(4)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)
    lp = lp_from('name: "l" type: "SoftmaxWithLoss"')
    (loss,), _ = L.SoftmaxWithLoss.apply(
        lp, {}, None, [jnp.asarray(logits), jnp.asarray(labels)], CTX
    )
    ref = F.cross_entropy(torch.from_numpy(logits), torch.from_numpy(labels)).item()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-6)


def test_inner_product_and_accuracy():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(4, 7)).astype(np.float32)
    w = rng.normal(size=(7, 3)).astype(np.float32)
    lp = lp_from('name: "ip" type: "InnerProduct" inner_product_param { num_output: 3 }')
    (y,), _ = L.InnerProduct.apply(lp, {"weight": jnp.asarray(w)}, None, [jnp.asarray(x)], CTX)
    # bias_term defaults true but params lack bias -> apply() must honor param presence
    np.testing.assert_allclose(np.asarray(y), x @ w, rtol=1e-5)

    logits = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32)
    labels = np.array([1, 0, 0])
    alp = lp_from('name: "a" type: "Accuracy" top: "accuracy"')
    (acc,), _ = L.Accuracy.apply(alp, {}, None, [jnp.asarray(logits), jnp.asarray(labels)], CTX)
    np.testing.assert_allclose(float(acc), 2.0 / 3.0, rtol=1e-6)


def test_batchnorm_train_then_eval():
    rng = np.random.default_rng(6)
    x = rng.normal(loc=3.0, scale=2.0, size=(8, 5, 5, 4)).astype(np.float32)
    lp = lp_from('name: "bn" type: "BatchNorm" batch_norm_param { moving_average_fraction: 0.0 }')
    state = L.BatchNorm.init_state(lp, [x.shape])
    ctx_tr = L.ApplyCtx(train=True, rng=None)
    (y,), new_state = L.BatchNorm.apply(lp, {}, state, [jnp.asarray(x)], ctx_tr)
    # normalized output: per-channel mean ~0, var ~1
    np.testing.assert_allclose(np.asarray(y).mean((0, 1, 2)), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).var((0, 1, 2)), 1.0, atol=1e-3)
    # mavf=0 -> running stats equal batch stats; eval reproduces train output
    (y2,), _ = L.BatchNorm.apply(lp, {}, new_state, [jnp.asarray(x)], CTX)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=2e-5, atol=2e-5)


def test_dropout_train_eval():
    x = jnp.ones((1000,))
    lp = lp_from('name: "d" type: "Dropout" dropout_param { dropout_ratio: 0.4 }')
    (y_eval,), _ = L.Dropout.apply(lp, {}, None, [x], CTX)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    ctx = L.ApplyCtx(train=True, rng=jax.random.PRNGKey(0))
    (y_tr,), _ = L.Dropout.apply(lp, {}, None, [x], ctx)
    y_tr = np.asarray(y_tr)
    assert abs((y_tr == 0).mean() - 0.4) < 0.06  # drop rate
    nz = y_tr[y_tr != 0]
    np.testing.assert_allclose(nz, 1.0 / 0.6, rtol=1e-5)  # inverted scaling


def test_eltwise_concat_slice():
    a = jnp.asarray(np.arange(12, dtype=np.float32).reshape(1, 1, 2, 6))
    b = a + 1
    lp = lp_from('name: "e" type: "Eltwise" eltwise_param { operation: SUM coeff: 1 coeff: -1 }')
    (y,), _ = L.Eltwise.apply(lp, {}, None, [a, b], CTX)
    np.testing.assert_allclose(np.asarray(y), -1.0)

    lp = lp_from('name: "c" type: "Concat"')  # default caffe axis 1 -> NHWC last
    (y,), _ = L.Concat.apply(lp, {}, None, [a, b], CTX)
    assert y.shape == (1, 1, 2, 12)

    lp = lp_from('name: "s" type: "Slice" top: "x" top: "y" slice_param { slice_point: 4 }')
    outs, _ = L.Slice.apply(lp, {}, None, [a], CTX)
    assert outs[0].shape == (1, 1, 2, 4) and outs[1].shape == (1, 1, 2, 2)


def test_grouped_deconvolution_shape_and_upsample():
    # FCN-style grouped upsampling must trace and double spatial dims
    lp = lp_from(
        'name: "up" type: "Deconvolution" convolution_param { '
        "num_output: 6 group: 6 kernel_size: 4 stride: 2 pad: 1 bias_term: false "
        'weight_filler { type: "constant" value: 0.25 } }'
    )
    x = jnp.ones((1, 5, 5, 6))
    [out_shape] = L.Deconvolution.infer(lp, [x.shape])
    params = L.Deconvolution.init(lp, jax.random.PRNGKey(0), [x.shape])
    (y,), _ = L.Deconvolution.apply(lp, params, None, [x], CTX)
    assert y.shape == out_shape == (1, 10, 10, 6)


def test_lrn_within_channel_scale():
    # constant input: denom = (1 + alpha/size^2 * sum(window))^beta with
    # full interior windows -> y = x / (1 + alpha*x^2)^beta
    size, alpha, beta = 3, 2.0, 0.75
    lp = lp_from(
        f'name: "n" type: "LRN" lrn_param {{ local_size: {size} alpha: {alpha} '
        f"beta: {beta} norm_region: WITHIN_CHANNEL k: 5.0 }}"
    )
    x = 2.0 * jnp.ones((1, 7, 7, 1))
    (y,), _ = L.LRN.apply(lp, {}, None, [x], CTX)
    interior = np.asarray(y)[0, 3, 3, 0]
    expected = 2.0 / (1.0 + alpha * 4.0) ** beta  # k ignored within-channel
    np.testing.assert_allclose(interior, expected, rtol=1e-6)


def test_bf16_compute_grad_path():
    """bfloat16 compute (the TPU matmul dtype): forward + grad through a
    conv->IP->softmax net must produce finite f32 loss and grads — guards
    the conv transpose rule against mixed-dtype regressions."""
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.nets.xlanet import XLANet

    npm = caffe_pb.load_net(
        """
        name: "tiny"
        layer { name: "data" type: "Input" top: "data" top: "label" }
        layer {
          name: "conv" type: "Convolution" bottom: "data" top: "conv"
          convolution_param { num_output: 4 kernel_size: 3 pad: 1
            weight_filler { type: "xavier" } }
        }
        layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" }
        layer {
          name: "ip" type: "InnerProduct" bottom: "conv" top: "ip"
          inner_product_param { num_output: 3
            weight_filler { type: "gaussian" std: 0.1 } }
        }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip"
                bottom: "label" top: "loss" }
        """,
        is_path=False,
    )
    shapes = {"data": (2, 8, 8, 3), "label": (2,)}
    net = XLANet(npm, "TRAIN", shapes, compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {
        "data": jnp.asarray(np.random.default_rng(0).normal(size=shapes["data"]),
                            jnp.float32),
        "label": jnp.asarray([0, 2], jnp.int32),
    }

    def loss_fn(p):
        blobs, _ = net.apply(p, state, batch, train=True, rng=None)
        loss, _ = net.loss_and_metrics(blobs)
        return loss

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert loss.dtype == jnp.float32 and np.isfinite(float(loss))
    flat = jax.tree_util.tree_leaves(grads)
    assert flat and all(np.all(np.isfinite(np.asarray(g, np.float32))) for g in flat)
    # params stay f32 master copies; grads match param dtype
    assert all(g.dtype == jnp.float32 for g in flat)
