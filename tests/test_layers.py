"""Layer numerics vs torch-CPU oracle (NCHW<->NHWC adapted)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.proto.caffe_pb import LayerParameter
from sparknet_tpu.proto.textformat import parse
from sparknet_tpu.nets import layers as L

torch = pytest.importorskip("torch")
import torch.nn.functional as F


def lp_from(text: str) -> LayerParameter:
    return LayerParameter.from_message(parse(text))


def nhwc(x_nchw: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1)))


def to_nchw(y: jnp.ndarray) -> np.ndarray:
    return np.transpose(np.asarray(y), (0, 3, 1, 2))


CTX = L.ApplyCtx(train=False, rng=None)


@pytest.mark.parametrize(
    "cin,cout,k,s,p,d,g",
    [
        (3, 8, 3, 1, 1, 1, 1),
        (4, 6, 5, 2, 2, 1, 2),
        (3, 8, 3, 1, 2, 2, 1),
        (8, 8, 1, 1, 0, 1, 8),  # depthwise
    ],
)
def test_convolution_vs_torch(cin, cout, k, s, p, d, g):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, cin, 13, 11)).astype(np.float32)
    w = rng.normal(size=(cout, cin // g, k, k)).astype(np.float32)
    b = rng.normal(size=(cout,)).astype(np.float32)

    lp = lp_from(
        f'name: "c" type: "Convolution" convolution_param {{ '
        f"num_output: {cout} kernel_size: {k} stride: {s} pad: {p} "
        f"dilation: {d} group: {g} }}"
    )
    params = {"weight": jnp.asarray(np.transpose(w, (2, 3, 1, 0))), "bias": jnp.asarray(b)}
    (y,), _ = L.Convolution.apply(lp, params, None, [nhwc(x)], CTX)
    ref = F.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
        stride=s, padding=p, dilation=d, groups=g,
    ).numpy()
    np.testing.assert_allclose(to_nchw(y), ref, rtol=2e-5, atol=2e-5)


def test_max_pool_ceil_mode_vs_torch():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 4, 11, 11)).astype(np.float32)
    lp = lp_from('name: "p" type: "Pooling" pooling_param { pool: MAX kernel_size: 3 stride: 2 }')
    (y,), _ = L.Pooling.apply(lp, {}, None, [nhwc(x)], CTX)
    ref = F.max_pool2d(torch.from_numpy(x), 3, 2, 0, ceil_mode=True).numpy()
    assert to_nchw(y).shape == ref.shape
    np.testing.assert_allclose(to_nchw(y), ref, rtol=1e-6)


def test_ave_pool_caffe_divisor():
    # Caffe AVE pooling: window clipped to padded region; divisor counts
    # padding. Construct the reference directly.
    rng = np.random.default_rng(2)
    H = W = 5
    k, s, p = 3, 2, 1
    x = rng.normal(size=(1, 1, H, W)).astype(np.float32)
    lp = lp_from(
        'name: "p" type: "Pooling" pooling_param { pool: AVE kernel_size: 3 stride: 2 pad: 1 }'
    )
    (y,), _ = L.Pooling.apply(lp, {}, None, [nhwc(x)], CTX)
    y = to_nchw(y)[0, 0]

    oh = L._pool_out(H, k, s, p)
    ref = np.zeros((oh, oh), np.float32)
    for i in range(oh):
        for j in range(oh):
            hs, ws = i * s - p, j * s - p
            he, we = min(hs + k, H + p), min(ws + k, W + p)
            pool_size = (he - hs) * (we - ws)
            hs0, ws0 = max(hs, 0), max(ws, 0)
            he0, we0 = min(he, H), min(we, W)
            ref[i, j] = x[0, 0, hs0:he0, ws0:we0].sum() / pool_size
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


def test_lrn_bf16_temps_track_f32():
    """Under a bf16 compute dtype the LRN temp chain runs bf16 (the
    round-5 bandwidth win); its output must stay within ordinary bf16
    rounding of the f32 math it replaces."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=(2, 6, 6, 96)).astype(np.float32)
    # alpha ~1 so d deviates far from 1 and the normalization actually
    # bites — at the zoo's 1e-4 a broken identity path would pass any
    # loose-tolerance comparison
    lp = lp_from(
        'name: "n" type: "LRN" lrn_param { local_size: 5 alpha: 1.0 beta: 0.75 }'
    )
    (y32,), _ = L.LRN.apply(lp, {}, None, [jnp.asarray(x)], CTX)
    (y16,), _ = L.LRN.apply(
        lp, {}, None, [jnp.asarray(x, jnp.bfloat16)], CTX
    )
    assert y16.dtype == jnp.bfloat16
    # the transform must be a real normalization, not identity
    assert float(jnp.max(jnp.abs(y32 - jnp.asarray(x)))) > 0.5
    np.testing.assert_allclose(
        np.asarray(y16, np.float32), np.asarray(y32), rtol=3e-2, atol=3e-2
    )


def test_lrn_across_channels_vs_torch():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 8, 6, 6)).astype(np.float32)
    size, alpha, beta, k = 5, 1e-4, 0.75, 1.0
    lp = lp_from(
        f'name: "n" type: "LRN" lrn_param {{ local_size: {size} alpha: {alpha} beta: {beta} }}'
    )
    (y,), _ = L.LRN.apply(lp, {}, None, [nhwc(x)], CTX)
    ref = torch.nn.LocalResponseNorm(size, alpha, beta, k)(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(to_nchw(y), ref, rtol=1e-5, atol=1e-6)


def test_softmax_with_loss_vs_torch():
    rng = np.random.default_rng(4)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)
    lp = lp_from('name: "l" type: "SoftmaxWithLoss"')
    (loss,), _ = L.SoftmaxWithLoss.apply(
        lp, {}, None, [jnp.asarray(logits), jnp.asarray(labels)], CTX
    )
    ref = F.cross_entropy(torch.from_numpy(logits), torch.from_numpy(labels)).item()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-6)


def test_inner_product_and_accuracy():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(4, 7)).astype(np.float32)
    w = rng.normal(size=(7, 3)).astype(np.float32)
    lp = lp_from('name: "ip" type: "InnerProduct" inner_product_param { num_output: 3 }')
    (y,), _ = L.InnerProduct.apply(lp, {"weight": jnp.asarray(w)}, None, [jnp.asarray(x)], CTX)
    # bias_term defaults true but params lack bias -> apply() must honor param presence
    np.testing.assert_allclose(np.asarray(y), x @ w, rtol=1e-5)

    logits = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32)
    labels = np.array([1, 0, 0])
    alp = lp_from('name: "a" type: "Accuracy" top: "accuracy"')
    (acc,), _ = L.Accuracy.apply(alp, {}, None, [jnp.asarray(logits), jnp.asarray(labels)], CTX)
    np.testing.assert_allclose(float(acc), 2.0 / 3.0, rtol=1e-6)


def test_batchnorm_train_then_eval():
    rng = np.random.default_rng(6)
    x = rng.normal(loc=3.0, scale=2.0, size=(8, 5, 5, 4)).astype(np.float32)
    lp = lp_from('name: "bn" type: "BatchNorm" batch_norm_param { moving_average_fraction: 0.0 }')
    state = L.BatchNorm.init_state(lp, [x.shape])
    ctx_tr = L.ApplyCtx(train=True, rng=None)
    (y,), new_state = L.BatchNorm.apply(lp, {}, state, [jnp.asarray(x)], ctx_tr)
    # normalized output: per-channel mean ~0, var ~1
    np.testing.assert_allclose(np.asarray(y).mean((0, 1, 2)), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).var((0, 1, 2)), 1.0, atol=1e-3)
    # mavf=0 -> running stats equal batch stats; eval reproduces train output
    (y2,), _ = L.BatchNorm.apply(lp, {}, new_state, [jnp.asarray(x)], CTX)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=2e-5, atol=2e-5)


def test_dropout_train_eval():
    x = jnp.ones((1000,))
    lp = lp_from('name: "d" type: "Dropout" dropout_param { dropout_ratio: 0.4 }')
    (y_eval,), _ = L.Dropout.apply(lp, {}, None, [x], CTX)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    ctx = L.ApplyCtx(train=True, rng=jax.random.PRNGKey(0))
    (y_tr,), _ = L.Dropout.apply(lp, {}, None, [x], ctx)
    y_tr = np.asarray(y_tr)
    assert abs((y_tr == 0).mean() - 0.4) < 0.06  # drop rate
    nz = y_tr[y_tr != 0]
    np.testing.assert_allclose(nz, 1.0 / 0.6, rtol=1e-5)  # inverted scaling


def test_eltwise_concat_slice():
    a = jnp.asarray(np.arange(12, dtype=np.float32).reshape(1, 1, 2, 6))
    b = a + 1
    lp = lp_from('name: "e" type: "Eltwise" eltwise_param { operation: SUM coeff: 1 coeff: -1 }')
    (y,), _ = L.Eltwise.apply(lp, {}, None, [a, b], CTX)
    np.testing.assert_allclose(np.asarray(y), -1.0)

    lp = lp_from('name: "c" type: "Concat"')  # default caffe axis 1 -> NHWC last
    (y,), _ = L.Concat.apply(lp, {}, None, [a, b], CTX)
    assert y.shape == (1, 1, 2, 12)

    lp = lp_from('name: "s" type: "Slice" top: "x" top: "y" slice_param { slice_point: 4 }')
    outs, _ = L.Slice.apply(lp, {}, None, [a], CTX)
    assert outs[0].shape == (1, 1, 2, 4) and outs[1].shape == (1, 1, 2, 2)


def test_grouped_deconvolution_shape_and_upsample():
    # FCN-style grouped upsampling must trace and double spatial dims
    lp = lp_from(
        'name: "up" type: "Deconvolution" convolution_param { '
        "num_output: 6 group: 6 kernel_size: 4 stride: 2 pad: 1 bias_term: false "
        'weight_filler { type: "constant" value: 0.25 } }'
    )
    x = jnp.ones((1, 5, 5, 6))
    [out_shape] = L.Deconvolution.infer(lp, [x.shape])
    params = L.Deconvolution.init(lp, jax.random.PRNGKey(0), [x.shape])
    (y,), _ = L.Deconvolution.apply(lp, params, None, [x], CTX)
    assert y.shape == out_shape == (1, 10, 10, 6)


def test_lrn_within_channel_scale():
    # constant input: denom = (1 + alpha/size^2 * sum(window))^beta with
    # full interior windows -> y = x / (1 + alpha*x^2)^beta
    size, alpha, beta = 3, 2.0, 0.75
    lp = lp_from(
        f'name: "n" type: "LRN" lrn_param {{ local_size: {size} alpha: {alpha} '
        f"beta: {beta} norm_region: WITHIN_CHANNEL k: 5.0 }}"
    )
    x = 2.0 * jnp.ones((1, 7, 7, 1))
    (y,), _ = L.LRN.apply(lp, {}, None, [x], CTX)
    interior = np.asarray(y)[0, 3, 3, 0]
    expected = 2.0 / (1.0 + alpha * 4.0) ** beta  # k ignored within-channel
    np.testing.assert_allclose(interior, expected, rtol=1e-6)


def test_bf16_compute_grad_path():
    """bfloat16 compute (the TPU matmul dtype): forward + grad through a
    conv->IP->softmax net must produce finite f32 loss and grads — guards
    the conv transpose rule against mixed-dtype regressions."""
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.nets.xlanet import XLANet

    npm = caffe_pb.load_net(
        """
        name: "tiny"
        layer { name: "data" type: "Input" top: "data" top: "label" }
        layer {
          name: "conv" type: "Convolution" bottom: "data" top: "conv"
          convolution_param { num_output: 4 kernel_size: 3 pad: 1
            weight_filler { type: "xavier" } }
        }
        layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" }
        layer {
          name: "ip" type: "InnerProduct" bottom: "conv" top: "ip"
          inner_product_param { num_output: 3
            weight_filler { type: "gaussian" std: 0.1 } }
        }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip"
                bottom: "label" top: "loss" }
        """,
        is_path=False,
    )
    shapes = {"data": (2, 8, 8, 3), "label": (2,)}
    net = XLANet(npm, "TRAIN", shapes, compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {
        "data": jnp.asarray(np.random.default_rng(0).normal(size=shapes["data"]),
                            jnp.float32),
        "label": jnp.asarray([0, 2], jnp.int32),
    }

    def loss_fn(p):
        blobs, _ = net.apply(p, state, batch, train=True, rng=None)
        loss, _ = net.loss_and_metrics(blobs)
        return loss

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert loss.dtype == jnp.float32 and np.isfinite(float(loss))
    flat = jax.tree_util.tree_leaves(grads)
    assert flat and all(np.all(np.isfinite(np.asarray(g, np.float32))) for g in flat)
    # params stay f32 master copies; grads match param dtype
    assert all(g.dtype == jnp.float32 for g in flat)


def test_prelu_vs_torch():
    rng = np.random.default_rng(8)
    x = rng.normal(size=(2, 5, 7, 7)).astype(np.float32)
    slope = rng.uniform(0.1, 0.4, size=(5,)).astype(np.float32)
    lp = lp_from('name: "pr" type: "PReLU"')
    (y,), _ = L.PReLU.apply(lp, {"slope": jnp.asarray(slope)}, None, [nhwc(x)], CTX)
    ref = F.prelu(torch.from_numpy(x), torch.from_numpy(slope)).numpy()
    np.testing.assert_allclose(to_nchw(y), ref, rtol=1e-6)
    # channel_shared init -> single slope at Caffe's 0.25 default
    lp2 = lp_from(
        'name: "pr" type: "PReLU" prelu_param { channel_shared: true }'
    )
    p = L.PReLU.init(lp2, jax.random.PRNGKey(0), [(2, 7, 7, 5)])
    assert p["slope"].shape == (1,) and float(p["slope"][0]) == 0.25


def test_threshold_tile_mvn():
    rng = np.random.default_rng(9)
    x = rng.normal(size=(2, 3, 4, 4)).astype(np.float32)
    lp = lp_from('name: "t" type: "Threshold" threshold_param { threshold: 0.2 }')
    (y,), _ = L.Threshold.apply(lp, {}, None, [nhwc(x)], CTX)
    np.testing.assert_array_equal(to_nchw(y), (x > 0.2).astype(np.float32))

    # Tile along the channel axis (Caffe axis 1 -> NHWC trailing)
    lp = lp_from('name: "ti" type: "Tile" tile_param { axis: 1 tiles: 3 }')
    xin = nhwc(x)
    assert L.Tile.infer(lp, [xin.shape]) == [(2, 4, 4, 9)]
    (y,), _ = L.Tile.apply(lp, {}, None, [xin], CTX)
    np.testing.assert_allclose(
        to_nchw(y), np.tile(x, (1, 3, 1, 1)), rtol=1e-6
    )

    # MVN per channel: zero mean, unit variance over H,W
    lp = lp_from('name: "m" type: "MVN"')
    (y,), _ = L.MVN.apply(lp, {}, None, [nhwc(x)], CTX)
    yn = to_nchw(y)
    np.testing.assert_allclose(yn.mean((2, 3)), 0.0, atol=1e-5)
    np.testing.assert_allclose(yn.std((2, 3)), 1.0, atol=1e-3)
    # across_channels without variance: mean over C,H,W removed only
    lp = lp_from(
        'name: "m" type: "MVN" mvn_param { across_channels: true '
        "normalize_variance: false }"
    )
    (y,), _ = L.MVN.apply(lp, {}, None, [nhwc(x)], CTX)
    np.testing.assert_allclose(to_nchw(y).mean((1, 2, 3)), 0.0, atol=1e-5)


def test_argmax_embed_reduction():
    rng = np.random.default_rng(10)
    x = rng.normal(size=(3, 7)).astype(np.float32)
    lp = lp_from(
        'name: "a" type: "ArgMax" argmax_param { top_k: 2 out_max_val: true }'
    )
    (y,), _ = L.ArgMax.apply(lp, {}, None, [jnp.asarray(x)], CTX)
    assert y.shape == (3, 2, 2)
    ref_v, ref_i = torch.topk(torch.from_numpy(x), 2)
    np.testing.assert_allclose(np.asarray(y)[:, 0], ref_i.numpy(), rtol=0)
    np.testing.assert_allclose(np.asarray(y)[:, 1], ref_v.numpy(), rtol=1e-6)

    lp = lp_from(
        'name: "e" type: "Embed" embed_param { num_output: 6 input_dim: 11 '
        'bias_term: true weight_filler { type: "gaussian" std: 1.0 } }'
    )
    params = L.Embed.init(lp, jax.random.PRNGKey(1), [(4,)])
    ids = jnp.asarray([0, 3, 10, 3], jnp.int32)
    (y,), _ = L.Embed.apply(lp, params, None, [ids], CTX)
    assert y.shape == (4, 6)
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(params["weight"])[np.asarray(ids)] + np.asarray(params["bias"]),
        rtol=1e-6,
    )

    x4 = rng.normal(size=(2, 3, 4, 5)).astype(np.float32)  # NCHW view
    lp = lp_from(
        'name: "r" type: "Reduction" reduction_param { operation: SUMSQ '
        "axis: 2 coeff: 0.5 }"
    )
    xin = nhwc(x4)
    assert L.Reduction.infer(lp, [xin.shape]) == [(2, 3)]
    (y,), _ = L.Reduction.apply(lp, {}, None, [xin], CTX)
    np.testing.assert_allclose(
        np.asarray(y), 0.5 * np.square(x4).sum((2, 3)), rtol=1e-5
    )


def test_crop_matches_fcn_semantics():
    rng = np.random.default_rng(11)
    x = rng.normal(size=(2, 3, 10, 12)).astype(np.float32)
    ref = np.zeros((2, 3, 6, 7), np.float32)
    lp = lp_from(
        'name: "c" type: "Crop" crop_param { axis: 2 offset: 2 offset: 3 }'
    )
    shapes = L.Crop.infer(lp, [nhwc(x).shape, nhwc(ref).shape])
    assert shapes == [(2, 6, 7, 3)]
    (y,), _ = L.Crop.apply(lp, {}, None, [nhwc(x), nhwc(ref)], CTX)
    np.testing.assert_allclose(
        to_nchw(y), x[:, :, 2:8, 3:10], rtol=1e-6
    )
    # single offset broadcast to all cropped axes
    lp = lp_from('name: "c" type: "Crop" crop_param { axis: 2 offset: 1 }')
    (y,), _ = L.Crop.apply(lp, {}, None, [nhwc(x), nhwc(ref)], CTX)
    np.testing.assert_allclose(to_nchw(y), x[:, :, 1:7, 1:8], rtol=1e-6)


def test_hinge_and_contrastive_losses_vs_torch():
    rng = np.random.default_rng(12)
    x = rng.normal(size=(6, 5)).astype(np.float32)
    labels = rng.integers(0, 5, size=(6,))
    lp = lp_from('name: "h" type: "HingeLoss"')
    (l1,), _ = L.HingeLoss.apply(
        lp, {}, None, [jnp.asarray(x), jnp.asarray(labels)], CTX
    )
    t = -np.ones_like(x)
    t[np.arange(6), labels] = 1.0
    ref = np.maximum(0, 1 - t * x).sum() / 6
    np.testing.assert_allclose(float(l1), ref, rtol=1e-5)
    lp = lp_from(
        'name: "h" type: "HingeLoss" hinge_loss_param { norm: L2 }'
    )
    (l2,), _ = L.HingeLoss.apply(
        lp, {}, None, [jnp.asarray(x), jnp.asarray(labels)], CTX
    )
    np.testing.assert_allclose(
        float(l2), np.square(np.maximum(0, 1 - t * x)).sum() / 6, rtol=1e-5
    )

    a = rng.normal(size=(6, 4)).astype(np.float32)
    b = rng.normal(size=(6, 4)).astype(np.float32)
    y = rng.integers(0, 2, size=(6,)).astype(np.float32)
    lp = lp_from(
        'name: "cl" type: "ContrastiveLoss" '
        "contrastive_loss_param { margin: 1.5 }"
    )
    (lc,), _ = L.ContrastiveLoss.apply(
        lp, {}, None, [jnp.asarray(a), jnp.asarray(b), jnp.asarray(y)], CTX
    )
    d = np.linalg.norm(a - b, axis=1)
    ref = (y * d**2 + (1 - y) * np.maximum(1.5 - d, 0) ** 2).sum() / (2 * 6)
    np.testing.assert_allclose(float(lc), ref, rtol=1e-5)


def test_silence_produces_nothing():
    lp = lp_from('name: "s" type: "Silence"')
    assert L.Silence.infer(lp, [(2, 3)]) == []
    outs, _ = L.Silence.apply(lp, {}, None, [jnp.zeros((2, 3))], CTX)
    assert outs == []


def test_argmax_axis_out_max_val_and_embed_bias_default():
    rng = np.random.default_rng(13)
    x = rng.normal(size=(3, 7)).astype(np.float32)
    # axis + out_max_val -> Caffe emits the top-k VALUES along the axis
    lp = lp_from(
        'name: "a" type: "ArgMax" argmax_param { axis: 1 top_k: 2 '
        "out_max_val: true }"
    )
    (y,), _ = L.ArgMax.apply(lp, {}, None, [jnp.asarray(x)], CTX)
    ref_v, _ = torch.topk(torch.from_numpy(x), 2)
    np.testing.assert_allclose(np.asarray(y), ref_v.numpy(), rtol=1e-6)

    # caffe.proto EmbedParameter: bias_term defaults TRUE
    lp = lp_from(
        'name: "e" type: "Embed" embed_param { num_output: 4 input_dim: 9 '
        'weight_filler { type: "gaussian" std: 1.0 } }'
    )
    params = L.Embed.init(lp, jax.random.PRNGKey(2), [(3,)])
    assert "bias" in params and params["bias"].shape == (4,)


def test_prelu_param_spec_maps_to_slope():
    """prototxt param{} spec 0 on a PReLU layer must govern the SLOPE
    blob (regression: specs were keyed weight/bias for every layer, so
    PReLU's decay_mult was silently dropped)."""
    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.caffe_solver import mults_for_params

    net = caffe_pb.NetParameter.from_message(parse("""
name: "p"
layer { name: "data" type: "Input" top: "data" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 4
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "act" type: "PReLU" bottom: "ip" top: "act"
        param { lr_mult: 3 decay_mult: 0 } }
"""))
    xnet = XLANet(net, "TRAIN", {"data": (2, 8)})
    params, _ = xnet.init(jax.random.PRNGKey(0))
    lr, dec = mults_for_params(params, xnet.param_specs())
    assert lr["act"]["slope"] == 3.0
    assert dec["act"]["slope"] == 0.0


def test_lstm_vs_torch():
    """Caffe-gate-order LSTM vs torch.nn.LSTM (torch packs gates
    i,f,g,o; Caffe i,f,o,g — remap and compare the full sequence)."""
    rng = np.random.default_rng(20)
    T, N, C, H = 6, 3, 5, 4
    x = rng.normal(size=(T, N, C)).astype(np.float32)
    wx = rng.normal(size=(C, 4 * H)).astype(np.float32) * 0.5
    wh = rng.normal(size=(H, 4 * H)).astype(np.float32) * 0.5
    b = rng.normal(size=(4 * H,)).astype(np.float32) * 0.1

    lp = lp_from(
        'name: "l" type: "LSTM" recurrent_param { num_output: %d }' % H
    )
    cont = np.ones((T, N), np.float32)
    cont[0] = 0.0  # sequence start
    params = {
        "weight": jnp.asarray(wx),
        "bias": jnp.asarray(b),
        "hidden_weight": jnp.asarray(wh),
    }
    (y,), _ = L.LSTM.apply(
        lp, params, None, [jnp.asarray(x), jnp.asarray(cont)], CTX
    )

    m = torch.nn.LSTM(C, H)
    # ours (in, 4H) caffe order [i,f,o,g] -> torch (4H, in) order [i,f,g,o]
    def reorder(w4h):  # (.., 4H) caffe -> torch gate order
        i, f, o, g = np.split(w4h, 4, axis=-1)
        return np.concatenate([i, f, g, o], axis=-1)

    with torch.no_grad():
        m.weight_ih_l0.copy_(torch.from_numpy(reorder(wx).T))
        m.weight_hh_l0.copy_(torch.from_numpy(reorder(wh).T))
        m.bias_ih_l0.copy_(torch.from_numpy(reorder(b)))
        m.bias_hh_l0.zero_()
        ref, _ = m(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=2e-5, atol=2e-5)


def test_lstm_cont_resets_state():
    """cont=0 mid-sequence must equal restarting the net from zero
    state at that step."""
    rng = np.random.default_rng(21)
    T, N, C, H = 8, 2, 3, 4
    x = rng.normal(size=(T, N, C)).astype(np.float32)
    lp = lp_from(
        'name: "l" type: "LSTM" recurrent_param { num_output: %d '
        'weight_filler { type: "gaussian" std: 0.5 } }' % H
    )
    params = L.LSTM.init(lp, jax.random.PRNGKey(3), [(T, N, C)])
    cont = np.ones((T, N), np.float32)
    cont[0] = 0.0
    cont[5] = 0.0  # reset mid-stream
    (y,), _ = L.LSTM.apply(
        lp, params, None, [jnp.asarray(x), jnp.asarray(cont)], CTX
    )
    # restarted run over the tail only
    cont_tail = np.ones((3, N), np.float32)
    cont_tail[0] = 0.0
    (y_tail,), _ = L.LSTM.apply(
        lp, params, None, [jnp.asarray(x[5:]), jnp.asarray(cont_tail)], CTX
    )
    np.testing.assert_allclose(
        np.asarray(y)[5:], np.asarray(y_tail), rtol=1e-5, atol=1e-6
    )


def test_rnn_shapes_and_determinism():
    rng = np.random.default_rng(22)
    T, N, C, H = 5, 2, 3, 6
    x = rng.normal(size=(T, N, C)).astype(np.float32)
    lp = lp_from(
        'name: "r" type: "RNN" recurrent_param { num_output: %d '
        'weight_filler { type: "xavier" } }' % H
    )
    assert L.RNN.infer(lp, [(T, N, C)]) == [(T, N, H)]
    params = L.RNN.init(lp, jax.random.PRNGKey(4), [(T, N, C)])
    assert set(params) == {
        "weight", "bias", "hidden_weight", "out_weight", "out_bias"
    }
    (y1,), _ = L.RNN.apply(lp, params, None, [jnp.asarray(x)], CTX)
    (y2,), _ = L.RNN.apply(lp, params, None, [jnp.asarray(x)], CTX)
    assert y1.shape == (T, N, H)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    assert np.abs(np.asarray(y1)).max() <= 1.0  # tanh output


def test_multinomial_and_infogain_losses():
    rng = np.random.default_rng(23)
    probs = rng.dirichlet(np.ones(5), size=6).astype(np.float32)
    labels = rng.integers(0, 5, 6)
    lp = lp_from('name: "m" type: "MultinomialLogisticLoss"')
    (l,), _ = L.MultinomialLogisticLoss.apply(
        lp, {}, None, [jnp.asarray(probs), jnp.asarray(labels)], CTX
    )
    ref = -np.mean(np.log(probs[np.arange(6), labels]))
    np.testing.assert_allclose(float(l), ref, rtol=1e-5)

    # identity infogain == multinomial logistic
    lp = lp_from('name: "i" type: "InfogainLoss"')
    (li,), _ = L.InfogainLoss.apply(
        lp, {}, None,
        [jnp.asarray(probs), jnp.asarray(labels), jnp.eye(5)], CTX
    )
    np.testing.assert_allclose(float(li), ref, rtol=1e-5)
    # a weighted H changes the loss accordingly
    h = np.eye(5, dtype=np.float32) * 2.0
    (l2,), _ = L.InfogainLoss.apply(
        lp, {}, None,
        [jnp.asarray(probs), jnp.asarray(labels), jnp.asarray(h)], CTX
    )
    np.testing.assert_allclose(float(l2), 2 * ref, rtol=1e-5)


def test_accuracy_ignore_label():
    logits = jnp.asarray(
        [[2.0, 0.0], [0.0, 2.0], [2.0, 0.0], [0.0, 2.0]], jnp.float32
    )
    labels = jnp.asarray([0, 1, 1, 9], jnp.int32)  # 9 = ignored
    lp = lp_from(
        'name: "a" type: "Accuracy" accuracy_param { ignore_label: 9 }'
    )
    (acc,), _ = L.Accuracy.apply(lp, {}, None, [logits, labels], CTX)
    # rows 0,1 correct; row 2 wrong; row 3 ignored -> 2/3
    np.testing.assert_allclose(float(acc), 2.0 / 3.0, rtol=1e-6)


def test_lstm_net_trains_through_xlanet():
    """An LSTM net compiles and trains end-to-end through the XLANet
    compiler + solver (time-major blobs flow through the DAG)."""
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "seq"
layer { name: "x" type: "Input" top: "x" }
layer { name: "cont" type: "Input" top: "cont" }
layer { name: "target" type: "Input" top: "target" }
layer { name: "lstm" type: "LSTM" bottom: "x" bottom: "cont" top: "lstm"
        recurrent_param { num_output: 8
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "lstm" bottom: "target" top: "loss" }
"""
    sp = caffe_pb.load_solver(
        "base_lr: 0.05\nlr_policy: \"fixed\"\nmomentum: 0.9\nmax_iter: 20\n",
        is_path=False,
    )
    sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
    T, N, C = 6, 4, 5
    shapes = {"x": (T, N, C), "cont": (T, N), "target": (T, N, 8)}
    solver = Solver(sp, shapes)
    rng = np.random.default_rng(5)
    cont = np.ones((T, N), np.float32)
    cont[0] = 0.0
    batch = {
        "x": jnp.asarray(rng.normal(size=(T, N, C)), jnp.float32),
        "cont": jnp.asarray(cont),
        "target": jnp.asarray(rng.normal(size=(T, N, 8)) * 0.1, jnp.float32),
    }

    def feed():
        while True:
            yield batch

    first = float(solver.step(feed(), 1)["loss"])
    last = float(solver.step(feed(), 19)["loss"])
    assert np.isfinite(last) and last < first  # it learns the mapping


def test_spp_vs_torch_adaptive_and_shapes():
    """SPP level geometry vs torch max_pool2d with explicit ceil-kernel
    windows; fixed-length output from two different input sizes."""
    rng = np.random.default_rng(30)
    lp = lp_from('name: "s" type: "SPP" spp_param { pyramid_height: 3 }')
    for h, w in ((13, 13), (9, 11)):
        x = rng.normal(size=(2, 4, h, w)).astype(np.float32)
        assert L.SPP.infer(lp, [(2, h, w, 4)]) == [(2, 4 * (1 + 4 + 16))]
        (y,), _ = L.SPP.apply(lp, {}, None, [nhwc(x)], CTX)
        assert y.shape == (2, 84)
        # level 0 (1x1 bin) is a global max over each channel map
        np.testing.assert_allclose(
            np.asarray(y)[:, :4], x.max((2, 3)), rtol=1e-6
        )
        # level 1 (2x2) matches torch pooling with the same ceil
        # kernel and centered padding
        bins = 2
        kh, ph = L.SPP._level(h, bins)
        kw, pw = L.SPP._level(w, bins)
        ref = torch.nn.functional.max_pool2d(
            torch.nn.functional.pad(
                torch.from_numpy(x),
                (pw, kw * bins - w - pw, ph, kh * bins - h - ph),
                value=float("-inf"),
            ),
            (kh, kw), (kh, kw),
        ).numpy()
        np.testing.assert_allclose(
            np.asarray(y)[:, 4:20], ref.reshape(2, -1), rtol=1e-6
        )


def test_spp_rejects_too_deep_pyramid_and_missing_param():
    lp = lp_from('name: "s" type: "SPP" spp_param { pyramid_height: 4 }')
    with pytest.raises(ValueError, match="bins per side"):
        L.SPP.infer(lp, [(1, 7, 7, 2)])  # level 3 wants 8 bins on 7px
    with pytest.raises(ValueError, match="pyramid_height"):
        L.SPP.infer(lp_from('name: "s" type: "SPP"'), [(1, 8, 8, 2)])


def test_batch_reindex_gather_and_grad():
    x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(4, 6))
    idx = jnp.asarray([2, 0, 2, 3, 1])
    lp = lp_from('name: "r" type: "BatchReindex"')
    assert L.BatchReindex.infer(lp, [(4, 6), (5,)]) == [(5, 6)]
    (y,), _ = L.BatchReindex.apply(lp, {}, None, [x, idx], CTX)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x)[[2, 0, 2, 3, 1]])
    # backward is scatter-add: row 2 selected twice gets gradient 2
    g = jax.grad(
        lambda x_: jnp.sum(L.BatchReindex.apply(lp, {}, None, [x_, idx], CTX)[0][0])
    )(x)
    np.testing.assert_allclose(np.asarray(g)[:, 0], [1.0, 1.0, 2.0, 1.0])


def test_parameter_layer_exposes_blob():
    lp = lp_from(
        'name: "p" type: "Parameter" '
        "parameter_param { shape { dim: 3 dim: 5 } }"
    )
    assert L.Parameter.infer(lp, []) == [(3, 5)]
    params = L.Parameter.init(lp, jax.random.PRNGKey(0), [])
    assert params["weight"].shape == (3, 5)
    np.testing.assert_array_equal(np.asarray(params["weight"]), 0.0)
    (y,), _ = L.Parameter.apply(lp, params, None, [], CTX)
    assert y.dtype == CTX.compute_dtype
    np.testing.assert_array_equal(np.asarray(y), np.asarray(params["weight"]))


@pytest.mark.parametrize("k,s,p,d", [(3, 1, 1, 1), (2, 2, 0, 1), (3, 1, 2, 2)])
def test_im2col_vs_torch_unfold(k, s, p, d):
    rng = np.random.default_rng(3)
    n, c, h, w = 2, 3, 9, 7
    x = rng.normal(size=(n, c, h, w)).astype(np.float32)
    lp = lp_from(
        f'name: "i" type: "Im2col" convolution_param {{ '
        f"kernel_size: {k} stride: {s} pad: {p} dilation: {d} }}"
    )
    (y,), _ = L.Im2col.apply(lp, {}, None, [nhwc(x)], CTX)
    ho, wo = y.shape[1], y.shape[2]
    assert L.Im2col.infer(lp, [(n, h, w, c)]) == [(n, ho, wo, c * k * k)]
    # torch unfold: (N, C*kh*kw, L) with c-major columns — the same
    # feature order this layer documents
    ref = F.unfold(
        torch.from_numpy(x), kernel_size=k, stride=s, padding=p, dilation=d
    ).numpy()  # (N, C*k*k, Ho*Wo)
    got = np.asarray(y).reshape(n, ho * wo, c * k * k).transpose(0, 2, 1)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
