"""serve/ subsystem: bucket-padding correctness, micro-batching
semantics, HTTP surface, load generator, and parity with the one-shot
classify tool (which now routes through the same engine)."""

import os
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sparknet_tpu.serve.batcher import Backpressure, MicroBatcher
from sparknet_tpu.serve.engine import InferenceEngine
from sparknet_tpu.serve.loadgen import run_loadgen
from sparknet_tpu.serve.metrics import LatencyHistogram, ServeMetrics
from sparknet_tpu.serve.server import InferenceServer

ZOO = os.path.join(
    os.path.dirname(__file__), "..", "sparknet_tpu", "models", "prototxt"
)
CIFAR_DEPLOY = os.path.join(ZOO, "cifar10_quick_deploy.prototxt")

# a tiny deploy net: fast compiles, still exercises conv/pool/fc/softmax
TOY_DEPLOY = """
name: "toy"
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 4 kernel_size: 3 pad: 1
          weight_filler { type: "gaussian" std: 0.2 } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
        pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
        inner_product_param { num_output: 5
          weight_filler { type: "gaussian" std: 0.2 } } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""


def toy_engine(buckets=(4, 8), metrics=None, warm=True):
    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.proto import caffe_pb

    net = XLANet(caffe_pb.load_net(TOY_DEPLOY, is_path=False), "TEST")
    params, state = net.init(jax.random.PRNGKey(7))
    eng = InferenceEngine(
        net, params, state, buckets=buckets, metrics=metrics
    )
    return eng.warmup() if warm else eng


def toy_rows(n, seed=0, shape=(8, 8, 3)):
    return (
        np.random.default_rng(seed).normal(size=(n,) + shape)
        .astype(np.float32)
    )


# ---------------------------------------------------------------- engine
def test_bucket_padding_bit_identical():
    """Padded-bucket outputs must be BIT-identical per-row to a direct
    unpadded XLANet.apply on the same rows (the acceptance bar)."""
    eng = toy_engine(buckets=(4,))
    rows = toy_rows(3)
    out = eng.infer(rows)  # 3 rows padded up to the 4-bucket
    direct_blobs, _ = eng.net.apply(
        eng.params, eng.state, {"data": jnp.asarray(rows)},
        train=False, rng=None,
    )
    np.testing.assert_array_equal(out, np.asarray(direct_blobs["prob"]))


def test_engine_buckets_and_chunking():
    m = ServeMetrics((2, 4))
    eng = toy_engine(buckets=(2, 4), metrics=m)
    assert eng.bucket_for(1) == 2 and eng.bucket_for(3) == 4
    assert eng.bucket_for(99) == 4  # beyond the ladder -> chunked
    rows = toy_rows(11)
    out = eng.infer(rows)
    assert out.shape == (11, 5)
    # chunked run must equal one unchunked reference row-for-row
    ref = eng.net.apply(
        eng.params, eng.state, {"data": jnp.asarray(rows)},
        train=False, rng=None,
    )[0]["prob"]
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-6, atol=1e-7)
    snap = m.snapshot()
    # 11 rows = 4 + 4 + 3(padded to 4): all batches in the 4-bucket
    assert snap["per_bucket"]["4"]["batches"] == 3
    assert snap["per_bucket"]["4"]["padded_rows"] == 1
    assert snap["per_bucket"]["4"]["padding_waste"] > 0


def test_engine_rejects_bad_shapes_and_empty():
    eng = toy_engine(buckets=(2,))
    with pytest.raises(ValueError, match="net wants"):
        eng.infer(toy_rows(2, shape=(4, 4, 3)))
    with pytest.raises(ValueError, match="empty"):
        eng.infer(np.zeros((0, 8, 8, 3), np.float32))


def test_engine_executable_cache_is_per_bucket():
    eng = toy_engine(buckets=(2, 4), warm=False)
    assert not eng._cache
    eng.infer(toy_rows(1))
    assert len(eng._cache) == 1  # only the 2-bucket compiled
    eng.infer(toy_rows(1))
    assert len(eng._cache) == 1  # cache hit, no recompile
    eng.warmup()
    assert len(eng._cache) == 2


def test_engine_topk_postprocess():
    eng = toy_engine(buckets=(4,))
    idx, probs = eng.topk(toy_rows(3), top_k=3)
    assert idx.shape == (3, 3) and probs.shape == (3, 3)
    assert np.all(probs >= 0) and np.all(probs[:, 0] >= probs[:, 1])
    # output blob is a Softmax: postprocess must not re-softmax
    out = eng.infer(toy_rows(3))
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)
    top = np.sort(np.asarray(out, np.float64), -1)[:, ::-1][:, :3]
    np.testing.assert_allclose(np.sort(probs, -1)[:, ::-1], top, rtol=1e-6)


# --------------------------------------------------------------- batcher
def test_batcher_max_latency_flush():
    """A lone request must come back after ~max_latency even when the
    batch never fills."""
    m = ServeMetrics()
    eng = toy_engine(buckets=(2, 4), metrics=m)
    b = MicroBatcher(eng, max_batch=4, max_latency_us=30_000, metrics=m)
    t0 = time.perf_counter()
    out = b.submit(toy_rows(1)).result(timeout=10)
    dt = time.perf_counter() - t0
    assert out.shape == (1, 5)
    assert dt < 5.0  # flushed by the latency knob, not stuck
    snap = m.snapshot()
    assert snap["requests"] == 1 and snap["errors"] == 0
    b.drain()


def test_batcher_coalesces_to_max_batch():
    """max_batch concurrent 1-row requests must ride ONE engine batch
    (and flush immediately on filling, not wait out the window)."""
    m = ServeMetrics()
    eng = toy_engine(buckets=(4,), metrics=m)
    b = MicroBatcher(eng, max_batch=4, max_latency_us=2_000_000, metrics=m)
    t0 = time.perf_counter()
    futs = [b.submit(toy_rows(1, seed=i)) for i in range(4)]
    outs = [f.result(timeout=10) for f in futs]
    dt = time.perf_counter() - t0
    assert all(o.shape == (1, 5) for o in outs)
    assert dt < 1.5  # full batch flushed without waiting the 2s window
    snap = m.snapshot()
    assert snap["requests"] == 4
    assert snap["per_bucket"]["4"]["batches"] == 1  # coalesced
    # each rider's rows come back in submit order
    for i, o in enumerate(outs):
        ref = eng.infer(toy_rows(1, seed=i))
        np.testing.assert_array_equal(o, ref)
    b.drain()


class _StubEngine:
    """Duck-typed engine whose infer blocks until released — makes
    backpressure deterministic without timing races."""

    buckets = (8,)

    def __init__(self):
        self.release = threading.Event()
        self.started = threading.Event()

    def infer(self, rows):
        self.started.set()
        assert self.release.wait(10)
        return np.asarray(rows)


def test_batcher_backpressure_bounded_queue():
    stub = _StubEngine()
    b = MicroBatcher(stub, max_batch=1, max_latency_us=0, max_queue=2)
    first = b.submit(np.zeros((1, 3), np.float32))
    assert stub.started.wait(10)  # worker is busy inside infer
    q1 = b.submit(np.zeros((1, 3), np.float32))
    q2 = b.submit(np.zeros((1, 3), np.float32))
    with pytest.raises(Backpressure):
        b.submit(np.zeros((1, 3), np.float32))
    stub.release.set()
    for f in (first, q1, q2):
        assert f.result(timeout=10).shape == (1, 3)
    b.drain()
    with pytest.raises(RuntimeError, match="drained"):
        b.submit(np.zeros((1, 3), np.float32))


def test_batcher_engine_error_propagates_to_future():
    m = ServeMetrics()
    eng = toy_engine(buckets=(2,), metrics=m)
    b = MicroBatcher(eng, metrics=m)
    fut = b.submit(toy_rows(1, shape=(2, 2, 3)))  # wrong input shape
    with pytest.raises(ValueError, match="net wants"):
        fut.result(timeout=10)
    assert m.snapshot()["errors"] == 1
    b.drain()


# --------------------------------------------------------------- metrics
def test_latency_histogram_percentiles():
    h = LatencyHistogram()
    for ms in range(1, 101):  # 1..100 ms uniform
        h.observe(ms / 1000)
    snap = h.snapshot()
    assert snap["count"] == 100
    # log-binned: percentile is exact to bin resolution (<47% up-error)
    assert 45 <= snap["p50_ms"] <= 75
    assert 90 <= snap["p99_ms"] <= 150
    assert snap["p50_ms"] <= snap["p95_ms"] <= snap["p99_ms"]
    assert LatencyHistogram().snapshot()["p50_ms"] is None


def test_metrics_json_line_roundtrip():
    import json

    m = ServeMetrics((1, 8))
    m.record_batch(8, rows=5, padded_rows=3, device_s=0.004)
    m.record_request(0.01, rows=5)
    rec = json.loads(m.json_line())
    assert rec["requests"] == 1 and rec["rows"] == 5
    assert rec["per_bucket"]["8"]["padding_waste"] == 0.375
    assert rec["per_bucket"]["8"]["device_latency"]["count"] == 1


# ---------------------------------------------------------------- server
def test_server_healthz_metrics_classify_roundtrip():
    m = ServeMetrics((4,))
    eng = toy_engine(buckets=(4,), metrics=m)
    srv = InferenceServer(
        eng, metrics=m, port=0, model_name="toy",
        batcher=MicroBatcher(eng, max_latency_us=5_000, metrics=m),
    ).start()
    try:
        c = srv.client()
        st, health = c.healthz()
        assert st == 200 and health["status"] == "ok"
        assert health["model"] == "toy" and health["buckets"] == [4]

        st, resp = c.classify(toy_rows(2), top_k=3)
        assert st == 200
        assert np.asarray(resp["indices"]).shape == (2, 3)
        probs = np.asarray(resp["probs"])
        assert np.all(probs[:, 0] >= probs[:, 1])

        st, resp = c.classify(toy_rows(1, shape=(2, 2, 3)))
        assert st == 400 and "net wants" in resp["error"]

        st, missing = c._request("GET", "/nope")
        assert st == 404

        st, met = c.metrics()
        assert st == 200
        assert met["requests"] == 1  # the good classify
        assert met["errors"] == 1  # the bad-shape classify
        assert met["per_bucket"]["4"]["batches"] == 1
        assert met["request_latency"]["count"] == 1
    finally:
        srv.stop()


def test_retry_after_accepts_both_rfc7231_forms():
    """ISSUE 4 satellite: Retry-After may be delta-seconds OR an
    HTTP-date; both parse, a past date clamps to 0, and garbage is
    ignored instead of crashing the retry loop."""
    from email.utils import formatdate

    from sparknet_tpu.serve.server import _retry_after_seconds

    assert _retry_after_seconds("2") == 2.0
    assert _retry_after_seconds("0") == 0.0
    assert _retry_after_seconds("-3") == 0.0  # bogus negative clamps
    # HTTP-date 3 seconds out -> roughly that many seconds
    future = _retry_after_seconds(formatdate(time.time() + 3, usegmt=True))
    assert future is not None and 1.0 <= future <= 4.0
    # a date already past means "retry now", not a crash
    past = _retry_after_seconds(formatdate(time.time() - 60, usegmt=True))
    assert past == 0.0
    assert _retry_after_seconds("soonish") is None
    assert _retry_after_seconds("") is None


def test_client_honors_http_date_retry_after_within_cap(monkeypatch):
    """A 503 carrying an HTTP-date Retry-After far in the future must
    delay the retry by at most max_backoff_s — and still retry."""
    from email.utils import formatdate

    from sparknet_tpu.serve.server import Client

    c = Client("h", 1, retries=2, backoff_s=0.01, max_backoff_s=0.05)
    calls = []

    def fake_once(method, path, payload=None):
        calls.append(method)
        if len(calls) == 1:
            return 503, {"error": "busy"}, formatdate(
                time.time() + 3600, usegmt=True
            )
        return 200, {"ok": True}, None

    monkeypatch.setattr(c, "_once", fake_once)
    t0 = time.perf_counter()
    status, data = c._request("GET", "/healthz")
    elapsed = time.perf_counter() - t0
    assert status == 200 and data == {"ok": True}
    assert len(calls) == 2
    assert elapsed < 1.0  # the 1-hour date was clamped to the cap


# ------------------------------------------------- classify-tool parity
def test_engine_matches_classify_tool_on_zoo_net():
    """classify (one-shot tool) and a bucketed serving engine over the
    zoo cifar10_quick deploy net must produce identical top-k."""
    from sparknet_tpu.tools import classify as classify_mod

    net, params, state = classify_mod.load_model(CIFAR_DEPLOY)
    # random weights: zero-init would softmax to uniform rows where
    # top-k ordering is meaningless
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(jax.random.PRNGKey(3), len(leaves))
    params = jax.tree_util.tree_unflatten(
        treedef,
        [
            jax.random.normal(k, l.shape, l.dtype) * 0.05
            for k, l in zip(keys, leaves)
        ],
    )
    rows = toy_rows(5, seed=2, shape=(32, 32, 3))
    idx_tool, probs_tool = classify_mod.classify(
        net, params, state, rows, top_k=4
    )
    eng = InferenceEngine(net, params, state, buckets=(8,))
    idx_srv, probs_srv = eng.topk(rows, top_k=4)
    np.testing.assert_array_equal(idx_tool, idx_srv)
    np.testing.assert_allclose(probs_tool, probs_srv, rtol=1e-6)


# --------------------------------------------------------------- loadgen
def test_loadgen_closed_loop_record():
    m = ServeMetrics((2, 4))
    eng = toy_engine(buckets=(2, 4), metrics=m)
    rec = run_loadgen(
        eng, n_requests=40, sizes=(1, 2, 5), concurrency=3, metrics=m
    )
    assert rec["metric"] == "serve_requests_per_sec"
    assert rec["value"] > 0 and rec["errors"] == 0
    assert rec["requests"] == 40
    assert rec["rows"] == sum((1, 2, 5)[i % 3] for i in range(40))
    assert rec["metrics"]["requests"] == 40
    assert rec["p99_ms"] is not None and rec["p99_ms"] >= rec["p50_ms"]
    # mixed sizes must exercise more than one bucket
    used = {
        b
        for b, e in rec["metrics"]["per_bucket"].items()
        if e["batches"] > 0
    }
    assert len(used) >= 2, rec["metrics"]["per_bucket"]


# -------------------------------------------------------------- CLI e2e
def test_serve_cli_bench_toy(tmp_path, capsys):
    """The acceptance flow in miniature: serve CLI loads a deploy net +
    .npz weights, the closed-loop generator pushes mixed-size requests,
    and the final record shows zero errors + per-bucket histograms."""
    import json

    from sparknet_tpu.nets.weights import save_npz
    from sparknet_tpu.tools import serve as serve_cli

    deploy = tmp_path / "toy_deploy.prototxt"
    deploy.write_text(TOY_DEPLOY)
    eng0 = toy_engine(buckets=(1,), warm=False)
    npz = str(tmp_path / "toy.npz")
    save_npz(npz, jax.device_get(eng0.params))

    rec = serve_cli.main(
        [
            "--model", str(deploy), "--weights", npz,
            "--buckets", "1,4", "--max-latency-us", "1000",
            "--bench", "60", "--bench-sizes", "1,3,6",
            "--bench-concurrency", "3",
        ]
    )
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["requests"] == 60  # the printed JSON record
    assert rec["errors"] == 0 and rec["requests"] == 60
    assert rec["metrics"]["errors"] == 0
    hist = rec["metrics"]["per_bucket"]
    assert sum(e["batches"] for e in hist.values()) > 0
    assert all(
        e["device_latency"]["count"] == e["batches"] for e in hist.values()
    )


@pytest.mark.slow
def test_serve_cli_bench_cifar10_quick(capsys, tmp_path):
    """Full acceptance run: cifar10_quick deploy + npz snapshot, >= 500
    mixed-size requests, zero errors, correct counts, per-bucket
    latency histograms (the ISSUE 1 acceptance criteria verbatim)."""
    import json

    from sparknet_tpu.nets.weights import save_npz
    from sparknet_tpu.tools import classify as classify_mod
    from sparknet_tpu.tools import serve as serve_cli

    net, params, state = classify_mod.load_model(CIFAR_DEPLOY)
    npz = str(tmp_path / "cifar10_quick.npz")
    save_npz(npz, jax.device_get(params))

    rec = serve_cli.main(
        [
            "--model", CIFAR_DEPLOY, "--weights", npz,
            "--buckets", "1,8,32", "--bench", "500",
            "--bench-sizes", "1,2,8,17,5", "--bench-concurrency", "8",
        ]
    )
    assert rec["requests"] == 500 and rec["errors"] == 0
    assert rec["metrics"]["errors"] == 0
    assert rec["metrics"]["requests"] == 500
    hist = rec["metrics"]["per_bucket"]
    used = {b for b, e in hist.items() if e["batches"] > 0}
    assert len(used) >= 2  # mixed sizes crossed buckets
    for e in hist.values():
        if e["batches"]:
            assert e["device_latency"]["p50_ms"] is not None
