"""BERT with MoE FFN layers and layer rematerialisation."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from sparknet_tpu.models.bert import BertConfig, BertMLM
from sparknet_tpu.parallel.mesh import make_mesh
from sparknet_tpu.proto.caffe_pb import SolverParameter
from sparknet_tpu.solver.trainer import Solver


def moe_model(b=2, s=32, experts=4, top_k=1, dispatch="dense", remat=False):
    cfg = dataclasses.replace(
        BertConfig.bert_tiny(vocab_size=64),
        moe_num_experts=experts, moe_top_k=top_k, moe_dispatch=dispatch,
        moe_capacity_factor=2.0, remat=remat,
    )
    shapes = {"input_ids": (b, s), "mlm_positions": (b, 4)}
    return BertMLM(cfg, shapes), cfg


def test_moe_bert_params_and_forward():
    model, cfg = moe_model()
    params, state = model.init(jax.random.PRNGKey(0))
    lp = params["layer_00"]
    assert "router_w" in lp and "ffn_in_w" not in lp
    assert lp["w_in"].shape == (4, cfg.hidden_size, cfg.intermediate_size)
    blobs, _ = model.apply(params, state, model.dummy_batch(), train=False)
    loss, metrics = model.loss_and_metrics(blobs)
    assert np.isfinite(float(loss))
    # aux loss contributes: near-uniform routing at init keeps it small
    # but nonzero relative to a dense model
    assert float(loss) > 0


@pytest.mark.parametrize("top_k,dispatch", [(1, "dense"), (2, "sort")])
def test_moe_bert_trains(top_k, dispatch):
    model, _ = moe_model(top_k=top_k, dispatch=dispatch)
    sp = SolverParameter(
        base_lr=5e-3, lr_policy="fixed", solver_type="ADAMW",
        momentum=0.9, weight_decay=0.01, max_iter=20,
    )
    shapes = {"input_ids": (2, 32), "mlm_positions": (2, 4)}
    solver = Solver(sp, shapes, model=model)
    batch = model.dummy_batch()

    def feed():
        while True:
            yield batch

    m0 = solver.step(feed(), 1)
    l0 = float(m0["loss"])
    m = solver.step(feed(), 19)
    assert np.isfinite(float(m["loss"]))
    assert float(m["loss"]) < l0  # memorising the fixed batch


def test_moe_bert_grads_flow_to_experts():
    model, _ = moe_model()
    params, state = model.init(jax.random.PRNGKey(0))
    batch = model.dummy_batch()

    def loss_fn(p):
        blobs, _ = model.apply(p, state, batch, train=False)
        return model.loss_and_metrics(blobs)[0]

    g = jax.grad(loss_fn)(params)
    for name in ("router_w", "w_in", "w_out"):
        gn = float(
            jnp.sum(jnp.abs(g["layer_00"][name]))
            + jnp.sum(jnp.abs(g["layer_01"][name]))
        )
        assert gn > 0, name


def test_remat_matches_no_remat():
    """jax.checkpoint must not change the math — loss and grads equal."""
    model_a, _ = moe_model(remat=False)
    model_b, _ = moe_model(remat=True)
    params, state = model_a.init(jax.random.PRNGKey(0))
    batch = model_a.dummy_batch()

    def loss(model, p):
        blobs, _ = model.apply(p, state, batch, train=False)
        return model.loss_and_metrics(blobs)[0]

    la = float(jax.jit(lambda p: loss(model_a, p))(params))
    lb = float(jax.jit(lambda p: loss(model_b, p))(params))
    np.testing.assert_allclose(lb, la, rtol=1e-6)
    ga = jax.grad(lambda p: loss(model_a, p))(params)
    gb = jax.grad(lambda p: loss(model_b, p))(params)
    for (pa, xa), (_, xb) in zip(
        jax.tree_util.tree_leaves_with_path(ga),
        jax.tree_util.tree_leaves_with_path(gb),
    ):
        np.testing.assert_allclose(
            np.asarray(xb), np.asarray(xa), rtol=1e-5, atol=1e-7,
            err_msg=str(pa),
        )


def test_moe_param_specs_no_decay_on_expert_biases():
    model, _ = moe_model()
    specs = model.param_specs()
    layer = specs["layer_00"]
    assert layer["b_in"] == (1.0, 0.0)
    assert layer["b_out"] == (1.0, 0.0)
    assert layer["router_w"] == (1.0, 1.0)
    assert layer["w_in"] == (1.0, 1.0)
    # spec names must cover exactly the real param names
    params, _ = model.init(jax.random.PRNGKey(0))
    assert set(layer) == set(params["layer_00"])


def test_moe_bert_pipeline_needs_matching_ep_axis():
    """MoE now composes with pp (tests/test_pipeline_parallel.py); the
    guard that remains is ep-axis consistency between model and step."""
    from sparknet_tpu.parallel.mesh import make_mesh
    from sparknet_tpu.parallel.pipeline import make_pp_train_step

    model, _ = moe_model()  # built without ep_axis
    mesh = make_mesh({"pp": 2, "ep": 2}, jax.devices()[:4])
    with pytest.raises(ValueError, match="ep_axis"):
        make_pp_train_step(model, None, mesh, n_micro=2, ep_axis="ep")


def test_moe_bert_rejects_tp_and_sp():
    cfg = dataclasses.replace(
        BertConfig.bert_tiny(vocab_size=64), moe_num_experts=4
    )
    shapes = {"input_ids": (2, 32), "mlm_positions": (2, 4)}
    with pytest.raises(NotImplementedError):
        BertMLM(cfg, shapes, tp_axis="tp")
    with pytest.raises(NotImplementedError):
        BertMLM(cfg, shapes, attention_impl="ring")


def test_bert_app_moe_cli():
    from sparknet_tpu.apps import bert_app

    metrics = bert_app.main([
        "--config", "tiny", "--vocab-size", "64", "--seq-len", "32",
        "--batch-size", "2", "--max-iter", "2", "--display", "1",
        "--moe-experts", "4", "--remat",
    ])
    assert np.isfinite(metrics["loss"])
