"""Continuous token-level batched decode (ISSUE 17): K sessions per
compiled step dispatch through ``engine.decode_batch``, the batcher's
``submit_decode`` windowing, coalescing, deadline sheds, accounting,
and the satellites (server A/B flag, healthz/dash surfaces, bench_diff
gates).

The expensive chaos e2e (subprocess tier, mid-burst SIGKILL of the
session holder) lives in scripts/decode_batch_smoke.py (check.sh);
these tests pin the same semantics fast with the toy char decoder from
tests/test_session.py.  The load-bearing numeric fact, pinned below:
rows are bitwise independent across the batched widths (4/8/16) —
slot position, batch width and batch-mates never change a row's
answer — which is exactly why the width ladder floors at 4 instead
of 1 (XLA CPU fuses the width-1 step differently, at the ulp level).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from tests.test_session import char_engine

from sparknet_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    decode_batching_enabled,
)
from sparknet_tpu.serve.engine import (
    DECODE_BUCKETS_DEFAULT,
    decode_buckets_from_env,
)
from sparknet_tpu.serve.metrics import ServeMetrics


@pytest.fixture(scope="module")
def eng():
    return char_engine(seed=3)


def _ok(results):
    bad = [r for r in results if isinstance(r, Exception)]
    assert not bad, bad
    return results


# ------------------------------------------------------- core equality
def test_decode_batch_matches_serial_bitwise(eng):
    """THE acceptance bar: a multi-row batched window returns, per
    row, exactly what a one-at-a-time replay through the same loop
    returns — tokens, probs, indices, accounting."""
    reqs = [
        {"tokens": [1 + i, 2, 3 + i], "steps": 2 + (i % 2)}
        for i in range(5)
    ]
    batched = _ok(eng.decode_batch([dict(r) for r in reqs]))
    serial = [
        _ok(eng.decode_batch([dict(r)]))[0] for r in reqs
    ]
    for b, s in zip(batched, serial):
        assert b["tokens"] == s["tokens"]
        assert b["probs"] == s["probs"]
        assert b["indices"] == s["indices"]
        assert b["steps_run"] == s["steps_run"]
        assert b["session_tokens"] == s["session_tokens"]


def test_decode_batch_sessions_match_serial(eng):
    """Same bar with live session state: batched rows put/take cache
    entries exactly like the serial path."""
    reqs = [
        {"tokens": [2 * i + 1, 4, 5], "steps": 2, "session": f"sb{i}"}
        for i in range(3)
    ]
    batched = _ok(eng.decode_batch([dict(r) for r in reqs]))
    for i in range(3):
        eng.session_cache.drop(eng.fingerprint, f"sb{i}")
    serial = [_ok(eng.decode_batch([dict(r)]))[0] for r in reqs]
    for b, s in zip(batched, serial):
        assert b["tokens"] == s["tokens"] and b["probs"] == s["probs"]
        assert b["cache_state"] == s["cache_state"] == "cold"
    for i in range(3):
        eng.session_cache.drop(eng.fingerprint, f"sb{i}")


def test_decode_rows_independent_of_width_and_slot(eng):
    """The width-4-floor rationale: a row's answer is bitwise
    identical whether it compiles at width 4, 8 or 16, and whatever
    slot or batch-mates it rides with."""
    req = {"tokens": [3, 1, 4], "steps": 3}
    other = {"tokens": [5, 9, 2], "steps": 3}
    saved = eng.decode_buckets
    try:
        outs = []
        for buckets in ((4,), (8,), (16,)):
            eng.decode_buckets = buckets
            outs.append(_ok(eng.decode_batch([dict(req)]))[0])
        eng.decode_buckets = saved
        # slot 0 vs slot 1, alone vs with a batch-mate
        outs.append(_ok(eng.decode_batch([dict(req), dict(other)]))[0])
        outs.append(_ok(eng.decode_batch([dict(other), dict(req)]))[1])
        ref = outs[0]
        for o in outs[1:]:
            assert o["tokens"] == ref["tokens"]
            assert o["probs"] == ref["probs"]
            assert o["indices"] == ref["indices"]
    finally:
        eng.decode_buckets = saved


def test_decode_batch_matches_generate(eng):
    """The single-session ``generate`` path stays the A/B baseline:
    identical greedy continuations, allclose distributions (width 1
    vs width >= 4 differ at the ulp level on CPU — same fusion story
    the docstring pins)."""
    req = {"tokens": [7, 8, 9], "steps": 4}
    b = _ok(eng.decode_batch([dict(req)]))[0]
    g = eng.generate([7, 8, 9], steps=4)
    assert b["tokens"] == g["tokens"]
    assert b["indices"] == g["indices"]
    assert np.allclose(b["probs"], g["probs"], rtol=1e-6, atol=1e-8)
    assert b["steps_run"] == g["steps_run"]


# --------------------------------------------------------- accounting
def test_decode_accounting_padded_steps_dont_count(eng):
    """Satellite 2 regression: padded/masked slots are never rows —
    ``steps_run``/``session_tokens`` stay exact per request, and the
    metrics ledger splits real rows from padding."""
    m = ServeMetrics()
    eng.metrics = m
    try:
        sid = "acct"
        cold = _ok(eng.decode_batch(
            [{"tokens": [1, 2, 3], "steps": 2, "session": sid}]
        ))[0]
        # a lone row padded to width 4 still ran exactly 5 steps
        assert cold["cache_state"] == "cold"
        assert cold["steps_run"] == 5 and cold["session_tokens"] == 5
        hist = [1, 2, 3] + cold["tokens"]
        hit = _ok(eng.decode_batch(
            [{"tokens": hist, "steps": 2, "session": sid}]
        ))[0]
        assert hit["cache_state"] == "hit"
        assert hit["steps_run"] == 2, (
            "hit must step only its NEW tokens — padded dispatches "
            f"leaked into steps_run: {hit}"
        )
        assert hit["session_tokens"] == len(hist) + 2
        snap = m.snapshot()["decode"]
        assert snap["rows"] == cold["steps_run"] + hit["steps_run"]
        assert snap["dispatches"] == 7
        assert snap["padded_rows"] == 7 * 4 - snap["rows"]
        assert snap["retired"] == 2 and snap["occupancy"] == 0.25
        assert snap["per_width"]["4"]["dispatches"] == 7
    finally:
        eng.metrics = None
        eng.session_cache.drop(eng.fingerprint, "acct")


def test_decode_full_prefix_hit_retires_without_dispatch(eng):
    """A request a cache hit already fully covers (steps=0, prefix
    resident) retires at admission: zero batched steps run."""
    m = ServeMetrics()
    eng.metrics = m
    try:
        sid = "instant"
        first = _ok(eng.decode_batch(
            [{"tokens": [4, 5, 6], "steps": 0, "session": sid}]
        ))[0]
        before = m.snapshot()["decode"]["dispatches"]
        again = _ok(eng.decode_batch(
            [{"tokens": [4, 5, 6], "steps": 0, "session": sid}]
        ))[0]
        assert again["cache_state"] == "hit" and again["steps_run"] == 0
        assert again["probs"] == first["probs"]
        assert m.snapshot()["decode"]["dispatches"] == before
    finally:
        eng.metrics = None
        eng.session_cache.drop(eng.fingerprint, "instant")


# --------------------------------------------------------- coalescing
def test_decode_same_session_rows_coalesce(eng):
    """Two rows for ONE session in a window would race one carry:
    the second defers until the first retires, then takes a HIT on
    the state the first just published — and the cache counts it."""
    sid = "co"
    before = eng.session_cache.snapshot()["coalesced"]
    out = _ok(eng.decode_batch([
        {"tokens": [1, 2, 3], "steps": 0, "session": sid},
        {"tokens": [1, 2, 3, 7], "steps": 0, "session": sid},
    ]))
    assert out[0]["cache_state"] == "cold" and out[0]["steps_run"] == 3
    assert out[1]["cache_state"] == "hit", (
        f"coalesced row must hit the freshly put carry: {out[1]}"
    )
    assert out[1]["steps_run"] == 1  # only the one new token
    assert eng.session_cache.snapshot()["coalesced"] == before + 1
    # equal to the uncontended answer
    eng.session_cache.drop(eng.fingerprint, sid)
    solo = _ok(eng.decode_batch([{"tokens": [1, 2, 3, 7], "steps": 0}]))
    assert out[1]["probs"] == solo[0]["probs"]
    eng.session_cache.drop(eng.fingerprint, sid)


# ---------------------------------------------------- shed + admission
def test_decode_per_token_deadline_shed(eng):
    """An expired row sheds AT A STEP BOUNDARY without disturbing its
    batch-mates; the shed is a DeadlineExceeded and counted."""
    m = ServeMetrics()
    eng.metrics = m
    try:
        out = eng.decode_batch([
            {"tokens": [1, 2, 3], "steps": 2},
            {"tokens": [4, 5, 6], "steps": 2,
             "deadline": time.perf_counter() - 1.0},
        ])
        assert isinstance(out[1], DeadlineExceeded)
        assert not isinstance(out[0], Exception)
        solo = _ok(eng.decode_batch([{"tokens": [1, 2, 3], "steps": 2}]))
        assert out[0]["probs"] == solo[0]["probs"]
        d = m.snapshot()["decode"]
        assert d["shed"] == 1 and d["retired"] == 2
        assert m.health() == "degraded"
    finally:
        eng.metrics = None


def test_decode_admit_hook_joins_running_window(eng):
    """Step-boundary admission: a request arriving mid-window becomes
    a fresh batch row and returns exactly its serial answer."""
    late = {"tokens": [9, 8, 7], "steps": 2}
    handed = []

    def admit(free_slots):
        assert free_slots > 0
        if not handed:
            handed.append(1)
            return [dict(late)]
        return None

    results = {}
    out = _ok(eng.decode_batch(
        [{"tokens": [1, 2, 3], "steps": 3}],
        admit=admit,
        on_result=lambda tag, v: results.setdefault(tag, v),
    ))
    assert len(out) == 2 and handed
    solo = _ok(eng.decode_batch([dict(late)]))[0]
    assert out[1]["probs"] == solo["probs"]
    assert out[1]["tokens"] == solo["tokens"]
    # on_result fired once per row, keyed by slot-default tags
    assert set(results) == {0, 1} and results[1]["probs"] == solo["probs"]


def test_decode_per_row_validation(eng):
    """A bad request fails ITS slot only — batch-mates answer."""
    out = eng.decode_batch([
        {"tokens": [1, 2], "steps": 1},
        {"tokens": [10**6], "steps": 0},
        {"tokens": [], "steps": 0},
    ])
    assert not isinstance(out[0], Exception)
    assert isinstance(out[1], ValueError) and "out of range" in str(out[1])
    assert isinstance(out[2], ValueError)


# ------------------------------------------------- batcher integration
def test_submit_decode_shares_windows_and_keeps_fifo(eng):
    """Concurrent submit_decode futures resolve with serial-equal
    answers; interleaved submit_call work still runs in FIFO order;
    the decode metrics block sees multi-row windows."""
    m = ServeMetrics()
    eng.metrics = m
    b = MicroBatcher(eng, metrics=m)
    try:
        reqs = [
            {"tokens": [i + 1, 5, 3], "steps": 2, "session": f"mb{i}"}
            for i in range(4)
        ]
        futs = [b.submit_decode(dict(r), block=True) for r in reqs]
        calls = [b.submit_call(lambda i=i: i) for i in range(2)]
        got = [f.result(60) for f in futs]
        assert [c.result(60) for c in calls] == [0, 1]
        for i in range(4):
            eng.session_cache.drop(eng.fingerprint, f"mb{i}")
        for g, r in zip(got, reqs):
            solo = _ok(eng.decode_batch([dict(r)]))[0]
            assert g["tokens"] == solo["tokens"]
            assert g["probs"] == solo["probs"]
        d = m.snapshot()["decode"]
        assert d["retired"] >= 4 and d["dispatches"] > 0
    finally:
        b.drain()
        eng.metrics = None
        for i in range(4):
            eng.session_cache.drop(eng.fingerprint, f"mb{i}")


def test_decode_flag_and_bucket_env(monkeypatch):
    """The A/B switch and the width-ladder override parse exactly."""
    monkeypatch.delenv("SPARKNET_DECODE_BATCH", raising=False)
    assert decode_batching_enabled() is True
    for off in ("0", "off", "OFF", "false", "no"):
        monkeypatch.setenv("SPARKNET_DECODE_BATCH", off)
        assert decode_batching_enabled() is False
    monkeypatch.setenv("SPARKNET_DECODE_BATCH", "1")
    assert decode_batching_enabled() is True

    monkeypatch.delenv("SPARKNET_DECODE_BUCKETS", raising=False)
    assert decode_buckets_from_env() == DECODE_BUCKETS_DEFAULT == (4, 8, 16)
    monkeypatch.setenv("SPARKNET_DECODE_BUCKETS", "8, 4,32")
    assert decode_buckets_from_env() == (4, 8, 32)
    monkeypatch.setenv("SPARKNET_DECODE_BUCKETS", "2")
    with pytest.raises(ValueError):
        decode_buckets_from_env()


# ------------------------------------------------------ server surface
@pytest.fixture(scope="module")
def char_server():
    from sparknet_tpu.serve.server import InferenceServer

    server = InferenceServer(char_engine(seed=3), port=0).start()
    yield server
    try:
        server.stop()
    except Exception:
        pass


def test_server_generate_batched_and_flag_off(char_server, monkeypatch):
    """/generate rides the batched decode loop by default (healthz
    decode block proves it ran); SPARKNET_DECODE_BATCH=0 falls back to
    the serial submit_call path live, with equal answers."""
    monkeypatch.delenv("SPARKNET_DECODE_BATCH", raising=False)
    c = char_server.client()
    st, on = c.generate([1, 2, 3], steps=2)
    assert st == 200 and len(on["tokens"]) == 2
    st, hz = c.healthz()
    dec = hz["decode"]
    assert dec["batching"] is True and dec["buckets"] == [4, 8, 16]
    assert dec["dispatches"] > 0 and dec["rows"] > 0
    before = dec["dispatches"]
    monkeypatch.setenv("SPARKNET_DECODE_BATCH", "0")
    st, off = c.generate([1, 2, 3], steps=2)
    assert st == 200 and off["tokens"] == on["tokens"]
    assert off["indices"] == on["indices"]
    st, hz = c.healthz()
    assert hz["decode"]["batching"] is False
    assert hz["decode"]["dispatches"] == before, (
        "flag-off generate still ran the batched loop"
    )
    # error mapping holds on the batched path
    monkeypatch.delenv("SPARKNET_DECODE_BATCH", raising=False)
    st, err = c.generate([10**6], steps=0)
    assert st == 400 and "out of range" in err["error"]


def test_dash_decode_tiles(char_server):
    """/dash Sessions panel gains the occupancy + tokens/sec +
    coalesced tiles once batched decode has run."""
    import urllib.request

    c = char_server.client()
    c.generate([2, 3, 4], session="dash-dec", steps=1)
    page = urllib.request.urlopen(
        f"http://{char_server.host}:{char_server.port}/dash"
    ).read().decode()
    assert "batch occupancy" in page
    assert "decode tokens/s" in page
    assert "coalesced" in page


# ------------------------------------------------------ bench_diff gate
def test_bench_diff_decode_gates(tmp_path):
    """session_serving records gate the batched arm: the >=3x WALL
    tokens/sec floor on accelerator records only (CPU records carry
    speedup_gate=informational-on-cpu), the >=3x DEVICE-side ratio
    (overhead-immune) and the batched-vs-serial token match absolutely
    everywhere."""
    import sys

    sys.path.insert(0, "scripts")
    try:
        import bench_diff
    finally:
        sys.path.pop(0)

    def rec(speedup, gate="gated", match=True, device=4.5):
        return {
            "metric": "session_serving_cached_speedup",
            "value": 8.0,
            "cached_speedup": 8.0,
            "bit_identical": True,
            "session_failed_requests": 0,
            "batched_tokens_per_sec_speedup": speedup,
            "batched_device_speedup": device,
            "batched_tokens_match": match,
            "speedup_gate": gate,
        }

    def run(old, new):
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(new))
        return bench_diff.main([str(a), str(b)])

    assert run(rec(4.0), rec(3.5)) == 0
    assert run(rec(4.0), rec(1.2)) == 1                # below 3x floor
    assert run(rec(4.0), rec(1.2, "informational-on-cpu")) == 0
    assert run(rec(4.0), rec(4.0, match=False)) == 1   # absolute bar
    assert run(
        rec(4.0), rec(1.2, "informational-on-cpu", match=False)
    ) == 1
    # the device-side ratio gates even on CPU records
    assert run(
        rec(4.0), rec(1.2, "informational-on-cpu", device=2.1)
    ) == 1
    assert run(
        rec(4.0), rec(1.2, "informational-on-cpu", device=3.4)
    ) == 0
