"""End-to-end request tracing for the serving tier (ISSUE 11).

Pins: trace-context header round-trip, the allocation-free disabled
path (the PR 5 tracer discipline), bounded span storage, the inline
``X-Sparknet-Spans`` replica batch, the router's cross-process stitch
(>=5 spans, >=90% wall attribution), chaos forensics for a SIGKILLed
replica (failed hop + retry hop on one waterfall), the structured
``retry:`` line + ``router_events{event="retry_hop"}``, the SLO
burn-rate detector (deterministic on a synthetic series; surfaces in
``/healthz``), OpenMetrics exemplars, the loadgen's failed/slow trace
ids, and the bench_diff ``reqtrace_overhead_pct`` gate.
"""

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest
import jax

from sparknet_tpu.serve.batcher import MicroBatcher
from sparknet_tpu.serve.engine import InferenceEngine
from sparknet_tpu.serve.metrics import ServeMetrics
from sparknet_tpu.serve.router import Router
from sparknet_tpu.serve.server import InferenceServer
from sparknet_tpu.telemetry import anomaly, reqtrace
from sparknet_tpu.telemetry.registry import REGISTRY, LatencyHistogram

TOY_DEPLOY = """
name: "toy"
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 5
          weight_filler { type: "gaussian" std: 0.2 } } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""


@pytest.fixture(autouse=True)
def _isolate():
    reqtrace.reset()
    reqtrace.enable()
    anomaly.clear()
    anomaly.reset_detectors()
    yield
    reqtrace.reset()
    reqtrace.configure_from_env()
    anomaly.clear()
    anomaly.reset_detectors()


def toy_net(seed=7):
    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.proto import caffe_pb

    net = XLANet(caffe_pb.load_net(TOY_DEPLOY, is_path=False), "TEST")
    params, state = net.init(jax.random.PRNGKey(seed))
    return net, params, state


def toy_rows(n, seed=0):
    return (
        np.random.default_rng(seed)
        .normal(size=(n, 8, 8, 3))
        .astype(np.float32)
    )


def toy_server(seed=7, buckets=(4,), **kw):
    net, params, state = toy_net(seed)
    m = ServeMetrics(buckets)
    eng = InferenceEngine(
        net, params, state, buckets=buckets, metrics=m
    ).warmup()
    srv = InferenceServer(
        eng, metrics=m, port=0, model_name="toy",
        batcher=MicroBatcher(eng, max_latency_us=2000, metrics=m,
                             mode="continuous"),
        **kw,
    ).start()
    return srv, eng, m


# ---------------------------------------------------------- primitives
def test_context_header_round_trip():
    ctx = reqtrace.mint()
    assert ctx.root and len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    back = reqtrace.parse(reqtrace.to_header(ctx))
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled == ctx.sampled
    assert not back.root  # a parsed context is never the stitch root
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id
    # garbage headers never raise — they just don't parse
    for bad in (None, "", "zz", "a-b-c", "0" * 32, f"{'x' * 32}-{'y' * 16}-01"):
        assert reqtrace.parse(bad) is None


def test_disabled_mode_is_allocation_free_noop():
    reqtrace.disable()
    try:
        assert reqtrace.mint() is None
        # ONE shared no-op object each — nothing allocated per call
        assert reqtrace.span(None, "x") is reqtrace.span(None, "y")
        assert reqtrace.span(None, "x") is reqtrace._NULL
        assert reqtrace.hop(None, "x") is reqtrace._NULL_HOP
        assert reqtrace.hop(None, "x").finish() is None
        assert reqtrace.record(None, "x", 0, 0.0) is None
        assert reqtrace.record_interval(None, "x", 0.0) is None
        assert reqtrace.parse("a" * 32 + "-" + "b" * 16 + "-00") is None
        assert reqtrace.finish(None, 0.0) is None
        assert reqtrace.completed() == []
    finally:
        reqtrace.enable()


def test_store_bounds_evict_and_count():
    before = REGISTRY.counter("reqtrace_dropped_spans").snapshot()
    # spans-per-trace cap
    ctx = reqtrace.mint()
    for i in range(reqtrace.MAX_SPANS_PER_TRACE + 10):
        reqtrace.record(ctx, f"s{i}", i, 1.0)
    assert len(reqtrace.take(ctx.trace_id)) == reqtrace.MAX_SPANS_PER_TRACE
    # open-trace cap: the oldest trace is evicted, newest survive
    first = reqtrace.mint()
    reqtrace.record(first, "old", 0, 1.0)
    for _ in range(reqtrace.MAX_TRACES):
        reqtrace.record(reqtrace.mint(), "fill", 0, 1.0)
    assert reqtrace.take(first.trace_id) == []
    assert REGISTRY.counter("reqtrace_dropped_spans").snapshot() > before


def test_spans_header_round_trip_and_truncation():
    spans = [{"name": f"s{i}", "span": "a" * 16, "parent": "b" * 16,
              "ts": i, "dur": 1.0, "pid": 1} for i in range(5)]
    val = reqtrace.spans_header_value(spans)
    assert "\n" not in val
    assert reqtrace.parse_spans_header(val) == spans
    assert reqtrace.parse_spans_header("not json") == []
    assert reqtrace.parse_spans_header(None) == []
    # oversized batches drop newest spans rather than breaking the wire
    big = [dict(s, name="x" * 4096) for s in spans] * 4
    val = reqtrace.spans_header_value(big)
    assert len(val) <= reqtrace.MAX_HEADER_BYTES
    assert len(reqtrace.parse_spans_header(val)) < len(big)


# --------------------------------------------------- single-process hop
def test_single_server_roots_and_completes_trace():
    srv, eng, m = toy_server()
    try:
        c = srv.client()
        st, resp = c.classify(toy_rows(2))
        assert st == 200 and "gen" in resp
        recs = reqtrace.completed()
        assert recs, "root server never completed its trace"
        rec = max(recs, key=lambda r: len(r["spans"]))
        names = {s["name"] for s in rec["spans"]}
        assert {"server.request", "batcher.wait", "engine.compute",
                "serve.serialize"} <= names
        assert reqtrace.coverage(rec) >= 0.9
        # parent chain: batcher/engine/serialize spans hang off the
        # server.request hop span
        server_span = next(
            s for s in rec["spans"] if s["name"] == "server.request"
        )
        for s in rec["spans"]:
            if s["name"] != "server.request":
                assert s["parent"] == server_span["span"]
    finally:
        srv.stop()


def test_replica_returns_span_batch_inline_when_not_root():
    """A replica under a router (= incoming trace header) hands its
    spans back in ``X-Sparknet-Spans`` instead of stitching locally."""
    srv, eng, m = toy_server()
    try:
        ctx = reqtrace.mint()
        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=30)
        conn.request(
            "POST", "/classify",
            body=json.dumps({"rows": toy_rows(1).tolist()}),
            headers={"Content-Type": "application/json",
                     reqtrace.HEADER: reqtrace.to_header(ctx)},
        )
        resp = conn.getresponse()
        spans_hdr = resp.getheader(reqtrace.SPANS_HEADER)
        echo = resp.getheader(reqtrace.HEADER)
        assert resp.status == 200
        resp.read()
        conn.close()
        assert echo and echo.startswith(ctx.trace_id)
        spans = reqtrace.parse_spans_header(spans_hdr)
        names = {s["name"] for s in spans}
        assert {"server.request", "batcher.wait", "engine.compute",
                "serve.serialize"} <= names
        # the server hop parents onto the caller's span id — the
        # cross-process link the router stitches on
        server_span = next(
            s for s in spans if s["name"] == "server.request"
        )
        assert server_span["parent"] == ctx.span_id
        # not the root: nothing stitched locally for this trace
        assert all(
            r["trace"] != ctx.trace_id for r in reqtrace.completed()
        )
    finally:
        srv.stop()


def test_disabled_tracing_serves_without_trace_headers():
    reqtrace.disable()
    srv, eng, m = toy_server()
    try:
        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=30)
        conn.request(
            "POST", "/classify",
            body=json.dumps({"rows": toy_rows(1).tolist()}),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader(reqtrace.HEADER) is None
        assert resp.getheader(reqtrace.SPANS_HEADER) is None
        resp.read()
        conn.close()
        assert reqtrace.completed() == []
    finally:
        srv.stop()
        reqtrace.enable()


# ------------------------------------------------------- stitched tier
def test_router_stitches_cross_hop_waterfall():
    """The acceptance bar: one classify through a 2-replica tier ->
    ONE stitched waterfall with >=5 spans attributing >=90% of wall
    latency, exported as Perfetto-loadable Chrome JSON."""
    servers = [toy_server(seed)[0] for seed in (1, 2)]
    router = Router(
        [(s.host, s.port) for s in servers],
        model_name="toy", health_interval_s=0.1,
    )
    try:
        assert router.wait_healthy(timeout_s=20)
        code, payload, headers = router.dispatch(
            json.dumps({"rows": toy_rows(2).tolist()}).encode()
        )
        assert code == 200
        hdr = dict(headers)
        assert reqtrace.HEADER in hdr  # the trace id reaches the client
        recs = [
            r for r in reqtrace.completed()
            if r["trace"] == reqtrace.parse(hdr[reqtrace.HEADER]).trace_id
        ]
        rec = max(recs, key=lambda r: len(r["spans"]))
        names = {s["name"] for s in rec["spans"]}
        assert len(rec["spans"]) >= 5
        assert {"router.dispatch", "server.request", "batcher.wait",
                "engine.compute", "serve.serialize"} <= names
        assert reqtrace.coverage(rec) >= 0.9
        # the replica's spans kept their origin pid; the dispatch hop
        # is the router's — two processes... here one process, but the
        # PARENT chain must cross the hop: server.request hangs off
        # the dispatch attempt's span id
        disp = next(s for s in rec["spans"] if s["name"] == "router.dispatch")
        serv = next(s for s in rec["spans"] if s["name"] == "server.request")
        assert serv["parent"] == disp["span"]
        # Perfetto-loadable export: X events with ts/dur/pid/tid + the
        # trace id in args
        doc = reqtrace.export_chrome([rec])
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(evs) == len(rec["spans"])
        for e in evs:
            assert e["ph"] in ("X", "M")
            for k in ("name", "ts", "dur", "pid", "tid"):
                assert k in e, e
            assert e["args"]["trace"] == rec["trace"]
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_sigkilled_replica_leaves_forensic_trace(tmp_path, capsys):
    """ISSUE 11 satellite (chaos forensics): SIGKILL a real replica
    subprocess (the ``serve.replica_kill`` chaos surface,
    ``pool.kill``) and assert the survivor-answered request's stitched
    trace holds the failed hop span, the retry hop span, and >=90%
    wall-latency attribution — plus the structured ``retry:`` line and
    the ``router_events{event="retry_hop"}`` increment."""
    from sparknet_tpu.supervise.pool import ChildPool

    model = tmp_path / "toy.prototxt"
    model.write_text(TOY_DEPLOY)

    def argv(i, spawn):
        return [
            sys.executable, "-m", "sparknet_tpu.serve.replica",
            "--model", str(model), "--buckets", "1,4", "--port", "0",
            "--portfile", str(tmp_path / f"replica-{i}-s{spawn}.json"),
        ]

    pool = ChildPool(argv, 2, name="reqtrace-replica")
    router = Router(
        2, pool=pool,
        portfile_for=lambda i, s: str(tmp_path / f"replica-{i}-s{s}.json"),
        health_interval_s=0.2,
    )
    pool.start()
    try:
        assert router.wait_healthy(timeout_s=180)
        retry_before = REGISTRY.counter(
            "router_events", event="retry_hop"
        ).snapshot()
        # SIGKILL replica 0 through the pool — the serve.replica_kill
        # chaos point's kill surface — and dispatch before any health
        # sweep can eject it: the router discovers the death
        # mid-request and retries on the peer
        assert pool.kill(0, signal.SIGKILL)
        time.sleep(0.2)  # let the process die so the port refuses
        body = json.dumps({"rows": toy_rows(1).tolist()}).encode()
        stitched = None
        for _ in range(4):  # rr tie-break: within 2 picks one lands on 0
            code, payload, headers = router.dispatch(body)
            assert code == 200, payload  # a kill costs latency, never answers
            tid = reqtrace.parse(dict(headers)[reqtrace.HEADER]).trace_id
            rec = next(
                r for r in reqtrace.completed() if r["trace"] == tid
            )
            if any(s["name"] == "router.retry" for s in rec["spans"]):
                stitched = rec
                break
        assert stitched is not None, "no dispatch ever hit the dead replica"
        failed = [
            s for s in stitched["spans"]
            if s["name"] == "router.dispatch"
            and s.get("args", {}).get("outcome") == "error"
        ]
        retried = [
            s for s in stitched["spans"] if s["name"] == "router.retry"
        ]
        assert failed and failed[0]["args"]["error"]
        assert retried and retried[0]["args"]["outcome"] == "ok"
        assert retried[0]["args"]["retry_of"] == failed[0]["args"]["replica"]
        # the survivor's replica spans stitched in from another PROCESS
        assert any(
            s["name"] == "server.request" and s["pid"] != os.getpid()
            for s in stitched["spans"]
        )
        assert reqtrace.coverage(stitched) >= 0.9
        # structured retry record at the moment of re-dispatch
        assert REGISTRY.counter(
            "router_events", event="retry_hop"
        ).snapshot() > retry_before
        retry_lines = [
            json.loads(line[len("retry: "):])
            for line in capsys.readouterr().out.splitlines()
            if line.startswith("retry: ")
        ]
        assert any(
            r["trace"] == stitched["trace"] and r["reason"]
            and r["from"] != r["to"]
            for r in retry_lines
        )
    finally:
        router.stop()


def test_retry_line_on_stub_replica_death(capsys):
    """The cheap (stub) version of the retry record: a connection
    dropped mid-request leaves the ``retry:`` JSON line and a
    ``retry_hop`` event even without real replica processes."""
    from tests.test_serving_tier import _StubReplica

    a, b = _StubReplica(), _StubReplica()
    router = Router(
        [(a.host, a.port), (b.host, b.port)], health_interval_s=0.1
    )
    try:
        assert router.wait_healthy(timeout_s=10)
        a.die_next = b.die_next = True  # whichever is picked first dies
        code, payload, _ = router.dispatch(
            json.dumps({"rows": [[1.0]]}).encode()
        )
        assert code == 200
        lines = [
            json.loads(ln[len("retry: "):])
            for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("retry: ")
        ]
        assert lines and lines[0]["reason"]
        assert {"trace", "from", "to", "reason"} <= set(lines[0])
    finally:
        router.stop()
        a.stop()
        b.stop()


# ------------------------------------------------------- SLO burn rate
def test_slo_burn_detector_fires_on_sustained_violation_only():
    clock = {"t": 0.0}
    det = anomaly.SloBurnRateDetector(
        slo_ms=100.0, emit=lambda *_: None, now=lambda: clock["t"]
    )
    # 20 min of healthy scrapes: silence
    for _ in range(40):
        clock["t"] += 30
        assert det.observe(50.0) is None
    # sustained violation: fires exactly when BOTH windows burn (fast
    # 5m window saturates quickly; the slow 1h window crosses 25% at
    # the 14th violating sample: 14/54)
    events = []
    for _ in range(14):
        clock["t"] += 30
        got = det.observe(500.0)
        if got:
            events.append(got)
    assert len(events) == 1
    ev = events[0]
    assert ev["kind"] == "slo_burn" and ev["severity"] == "critical"
    assert ev["fast_burn"] >= 0.5 and ev["slow_burn"] >= 0.25
    assert anomaly.active("slo_burn")
    # recovery resets the episode; a later breach fires anew
    for _ in range(60):
        clock["t"] += 30
        det.observe(50.0)
    assert det._last_fire is None


def test_slo_burn_needs_both_windows():
    """A brief spike saturating only the fast window must NOT fire —
    the slow window is the 'error budget is really burning' gate."""
    clock = {"t": 0.0}
    det = anomaly.SloBurnRateDetector(
        slo_ms=100.0, emit=lambda *_: None, now=lambda: clock["t"]
    )
    for _ in range(100):
        clock["t"] += 30
        det.observe(50.0)
    for _ in range(8):  # 4 min of violation: fast burn 0.8, slow ~0.07
        clock["t"] += 30
        assert det.observe(500.0) is None


def test_healthz_degrades_on_slo_burn(monkeypatch):
    monkeypatch.setenv("SPARKNET_SLO_P99_MS", "0.0001")
    anomaly.reset_detectors()
    srv, eng, m = toy_server()
    try:
        c = srv.client()
        st, _ = c.classify(toy_rows(1))
        assert st == 200  # any real request's p99 >> 0.0001 ms
        for _ in range(6):  # scrapes feed the burn windows (min 5)
            st, hz = c.healthz()
        assert st == 200
        kinds = {a["kind"] for a in hz["anomalies"]}
        assert "slo_burn" in kinds
        assert hz["status"] == "degraded"
    finally:
        srv.stop()


# ------------------------------------------------- exemplars + loadgen
def test_sampled_traces_become_prometheus_exemplars():
    from sparknet_tpu.telemetry.exporter import render_prometheus
    from sparknet_tpu.telemetry.registry import Registry

    reg = Registry()
    h = reg.histogram("serve_request_latency_seconds")
    h.observe(0.010)  # no exemplar: plain bucket line
    h.observe(0.012, exemplar=("cafe" * 8, 0.012))
    text = render_prometheus(registry=reg)
    assert f'# {{trace_id="{"cafe" * 8}"}} 0.012' in text
    # exactly one exemplar (one bin), not one per bucket line
    assert text.count("trace_id=") == 1


def test_every_nth_mint_is_sampled():
    n = reqtrace._SAMPLE_N
    flags = [reqtrace.mint().sampled for _ in range(2 * n)]
    assert sum(flags) == 2
    assert flags[0]  # the counter was reset by the fixture


def test_loadgen_records_failed_and_slow_trace_ids():
    from sparknet_tpu.serve.loadgen import run_http_loadgen

    srv, eng, m = toy_server()
    try:
        rec = run_http_loadgen(
            srv.host, srv.port, (8, 8, 3),
            n_requests=30, sizes=(1, 2, 3), concurrency=3,
        )
        assert rec["failed_requests"] == 0
        assert rec["failed_request_traces"] == []
        assert rec["p50_exact_ms"] is not None
        assert rec["p99_exact_ms"] >= rec["p50_exact_ms"]
        # the >p99 stragglers are named by trace id, slowest first
        assert isinstance(rec["slow_request_traces"], list)
        for entry in rec["slow_request_traces"]:
            assert set(entry) == {"req", "trace", "ms"}
            assert len(entry["trace"]) == 32
            assert entry["ms"] > rec["p99_exact_ms"]
    finally:
        srv.stop()


# ------------------------------------------------------- dash + gates
def test_dash_renders_slow_request_panel():
    from sparknet_tpu.telemetry.dash import render_html

    recs = [{
        "trace": "ab" * 16, "wall_ms": 42.5, "t": 0.0, "sampled": True,
        "spans": [
            {"name": "router.dispatch", "span": "s1", "parent": "r",
             "ts": 0, "dur": 900.0, "pid": 1,
             "args": {"outcome": "error", "error": "ConnectionRefused"}},
            {"name": "router.retry", "span": "s2", "parent": "r",
             "ts": 1000, "dur": 41000.0, "pid": 1,
             "args": {"outcome": "ok"}},
            {"name": "server.request", "span": "s3", "parent": "s2",
             "ts": 1200, "dur": 40000.0, "pid": 2, "args": {}},
        ],
    }]
    html = render_html({"uptime_s": 1.0}, reqtrace=recs)
    assert "Slow requests" in html and "42.5 ms" in html
    assert "⟳ retried" in html  # retry hops flagged, not color alone
    assert 'data-hop="router.retry"' in html
    # absent records -> absent panel
    assert "Slow requests" not in render_html({"uptime_s": 1.0})


def test_bench_diff_gates_reqtrace_overhead(tmp_path):
    base = {"metric": "serving_tier_p99_ms_continuous", "value": 50.0,
            "reqtrace_overhead_pct": 0.5}
    good = dict(base, reqtrace_overhead_pct=1.4)
    bad = dict(base, reqtrace_overhead_pct=3.7)
    paths = {}
    for name, doc in (("a", base), ("b", good), ("c", bad)):
        paths[name] = str(tmp_path / f"{name}.json")
        with open(paths[name], "w") as fh:
            json.dump(doc, fh)
    script = os.path.join(
        os.path.dirname(__file__), "..", "scripts", "bench_diff.py"
    )
    ok = subprocess.run(
        [sys.executable, script, paths["a"], paths["b"]],
        capture_output=True, text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad_run = subprocess.run(
        [sys.executable, script, paths["a"], paths["c"]],
        capture_output=True, text=True,
    )
    assert bad_run.returncode == 1
    assert "reqtrace_overhead_pct" in bad_run.stdout
    assert "≤2% is the bar" in bad_run.stdout
