"""Synthetic convergence smokes for the big zoo nets (VERDICT r04 #7).

The env ships no datasets (SURVEY.md §0), so these memorize a small
deterministic batch cycle — the same oracle tau_sweep.py uses: a net
whose loss falls markedly on memorisable data has working forward,
backward, and update paths end-to-end. GoogLeNet additionally pins the
train_val's three-head loss weighting (aux heads 0.3 + main 1.0);
ResNet-50 checks BatchNorm's moving stats stay sane while training.

Both are CPU-minutes heavy -> @slow (the nightly tier; `-m "not slow"`
skips them).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.solver.trainer import Solver

ZOO = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "sparknet_tpu", "models", "prototxt",
)


def _memorisable_feed(bs, size, classes, n_distinct=2, seed=0):
    rng = np.random.default_rng(seed)
    batches = [
        {
            "data": rng.normal(size=(bs, size, size, 3)).astype(np.float32),
            "label": rng.integers(0, classes, bs).astype(np.int32),
        }
        for _ in range(n_distinct)
    ]
    while True:
        yield from batches


def _smoke_solver(proto, size, bs, iters, lr=0.01):
    sp = caffe_pb.load_solver(os.path.join(ZOO, proto))
    sp.base_lr = lr
    sp.lr_policy = "fixed"
    sp.max_iter = iters + 10
    sp.display = 0
    sp.snapshot = 0
    sp.test_interval = 0
    shapes = {"data": (bs, size, size, 3), "label": (bs,)}
    return Solver(sp, shapes, solver_dir=ZOO)


@pytest.mark.slow
def test_googlenet_synthetic_convergence():
    solver = _smoke_solver("bvlc_googlenet_quick_solver.prototxt", 224,
                           bs=4, iters=24)
    # the 1,310-line train_val's three loss heads, aux-weighted 0.3
    heads = {
        lp.top[0]: (lp.loss_weight[0] if lp.loss_weight else 1.0)
        for lp in solver.train_net.layers
        if lp.type == "SoftmaxWithLoss"
    }
    assert heads == {
        "loss1/loss": pytest.approx(0.3),
        "loss2/loss": pytest.approx(0.3),
        "loss3/loss": pytest.approx(1.0),
    }

    feed = _memorisable_feed(4, 224, classes=8)
    m0 = solver.step(feed, 2)
    first = {k: float(v) for k, v in m0.items() if "loss" in k}
    m1 = solver.step(feed, 22)
    last = {k: float(v) for k, v in m1.items() if "loss" in k}
    # every head must be finite and the main head clearly descending
    assert all(np.isfinite(v) for v in last.values()), last
    assert last["loss3/loss"] < first["loss3/loss"] * 0.85, (first, last)


@pytest.mark.slow
def test_resnet50_synthetic_convergence_and_bn_stats():
    solver = _smoke_solver("resnet50_solver.prototxt", 224, bs=2, iters=16)
    feed = _memorisable_feed(2, 224, classes=4, seed=1)
    m0 = solver.step(feed, 2)
    l0 = float(next(v for k, v in m0.items() if "loss" in k))
    m1 = solver.step(feed, 14)
    l1 = float(next(v for k, v in m1.items() if "loss" in k))
    assert np.isfinite(l1) and l1 < l0 * 0.9, (l0, l1)

    # BatchNorm moving stats: finite everywhere, variances positive
    bn_layers = 0
    for name, st in jax.device_get(solver.state).items():
        if not isinstance(st, dict) or "mean" not in st:
            continue
        bn_layers += 1
        assert np.all(np.isfinite(st["mean"])), name
        assert np.all(np.isfinite(st["var"])), name
        assert np.all(np.asarray(st["var"]) >= 0.0), name
    assert bn_layers >= 49, f"ResNet-50 should carry >=49 BN layers, saw {bn_layers}"
