"""data/records.py + data/cache.py: the packed data plane's contracts.

The acceptance spine (ISSUE 8): the packed streaming readers are
bit-identical to the legacy in-memory ``ShardedDataset`` feed (so
``--data-format packed`` can never change training results), ``skip(n)``
extends PR 2's O(1) resume to the shard level (equal to
iterate-then-slice, across shard boundaries, under 0/2/4 pipeline
workers), the global shuffle is deterministic per ``(seed, epoch)``,
and the cross-job decoded-batch cache serves bit-identical batches —
including after torn segments, evictions, and ``data.torn_shard``
chaos, none of which may poison it.  Every cache namespace opened here
is cleared; the session leak fixture asserts no ``snkc_*`` shm segment
survives the suite.
"""

import glob
import json
import multiprocessing
import os
import uuid

import numpy as np
import pytest

from sparknet_tpu.data.cache import SHM_CACHE_PREFIX, ShmBatchCache
from sparknet_tpu.data.records import (
    PackedDataset,
    PackedShardReader,
    ShardError,
    decode_record,
    encode_record,
    pack_arrays,
    packed_dataset,
)
from sparknet_tpu.data.rdd import ShardedDataset


def _arrays(n=97, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "data": rng.integers(0, 255, (n, 8, 8, 3)).astype(np.uint8),
        "label": np.arange(n, dtype=np.int32),
    }


def _aug(batch, r):
    return {
        "data": batch["data"].astype(np.float32)
        + r.normal(size=batch["data"].shape).astype(np.float32),
        "label": batch["label"],
    }


def _assert_same_stream(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


@pytest.fixture
def packed_dir(tmp_path):
    arrays = _arrays()
    d = str(tmp_path / "packed")
    pack_arrays(d, arrays, 4)
    return d, arrays


@pytest.fixture
def cache():
    c = ShmBatchCache(f"t-{uuid.uuid4().hex[:8]}", max_bytes=32_000_000)
    yield c
    c.clear()
    assert not glob.glob(f"/dev/shm/{SHM_CACHE_PREFIX}_{c._ns}_*")


# ---------------------------------------------------------------------------
# format layer
# ---------------------------------------------------------------------------

def test_record_codec_roundtrip():
    sample = {
        "data": np.arange(24, dtype=np.uint8).reshape(2, 4, 3),
        "label": np.asarray(np.int32(7)),  # 0-d labels must stay 0-d
        "weight": np.asarray([1.5, -2.0], np.float32),
    }
    cache = {}
    payload = encode_record(sample)
    for _ in range(2):  # second pass exercises the header cache
        out = decode_record(payload, cache)
        assert sorted(out) == sorted(sample)
        for k in sample:
            assert out[k].shape == np.asarray(sample[k]).shape
            np.testing.assert_array_equal(out[k], sample[k])


def test_shard_roundtrip_index_and_torn_trailer(tmp_path, packed_dir):
    d, arrays = packed_dir
    manifest = json.load(open(os.path.join(d, "MANIFEST.json")))
    assert manifest["record_count"] == len(arrays["label"])
    shard0 = manifest["shards"][0]
    r = PackedShardReader(os.path.join(d, shard0["file"]))
    assert len(r) == shard0["records"]
    rec = r.record(3)
    np.testing.assert_array_equal(rec["data"], arrays["data"][3])
    assert int(rec["label"]) == 3
    # the bulk fast path: uniform layout + verified region checksum
    assert r.region_sum() == int(shard0["region_sum"])
    mat, cols = r.uniform_matrix()
    assert mat.shape[0] == len(r)
    r.close()
    # a truncated shard (torn trailer) must fail loudly at open
    path = str(tmp_path / "torn.snpk")
    with open(os.path.join(d, shard0["file"]), "rb") as fh:
        blob = fh.read()
    with open(path, "wb") as fh:
        fh.write(blob[: len(blob) - 7])
    with pytest.raises(ShardError):
        PackedShardReader(path)


def test_crc_failing_record_skipped_with_counter(tmp_path):
    from sparknet_tpu.telemetry.registry import REGISTRY

    d = str(tmp_path / "p")
    pack_arrays(d, _arrays(20), 1)
    ds = PackedDataset(d)
    manifest = json.load(open(os.path.join(d, "MANIFEST.json")))
    # flip one byte inside record 5's payload: region checksum breaks
    # (bulk path refuses the shard) and record 5's CRC fails (the
    # per-record path skips it with a counter and substitutes a
    # healthy neighbor — shapes hold, the stream keeps going)
    path = os.path.join(d, manifest["shards"][0]["file"])
    r = PackedShardReader(path)
    off = int(r.offsets[5]) + 8 + 40
    r.close()
    blob = bytearray(open(path, "rb").read())
    blob[off] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    before = REGISTRY.counter("packed_reader", event="crc_skipped").snapshot()
    got = list(ds.batches(5, shuffle=False, epochs=1))
    after = REGISTRY.counter("packed_reader", event="crc_skipped").snapshot()
    assert after - before == 1
    assert len(got) == 4 and all(len(b["label"]) == 5 for b in got)


# ---------------------------------------------------------------------------
# streaming readers: legacy equivalence, shuffle, skip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("drop_remainder", [True, False])
def test_packed_stream_bit_identical_to_legacy(packed_dir, drop_remainder):
    d, arrays = packed_dir
    legacy = ShardedDataset.from_arrays(arrays, 4)
    pds = PackedDataset(d)
    a = list(legacy.batches(8, shuffle=True, seed=3, epochs=2,
                            drop_remainder=drop_remainder, transform=_aug))
    b = list(pds.batches(8, shuffle=True, seed=3, epochs=2,
                         drop_remainder=drop_remainder, transform=_aug))
    _assert_same_stream(a, b)


@pytest.mark.parametrize("workers", [0, 2, 4])
def test_skip_across_shards_equals_iterate_then_slice(packed_dir, workers):
    """Shard-level O(1) resume: skip(13) crosses shard boundaries (4
    shards x ~24 records, batches of 8) and must equal slicing the
    uninterrupted stream — serially and through the multiprocess
    pipeline (whose pre-start skip offsets every worker)."""
    d, _ = packed_dir
    pds = PackedDataset(d)
    ref = list(pds.batches(8, shuffle=True, seed=3, epochs=2,
                           transform=_aug))[13:18]
    if workers == 0:
        it = pds.batches(8, shuffle=True, seed=3, epochs=2, transform=_aug)
        it.skip(13)
        got = [next(it) for _ in range(5)]
        it.close()
    else:
        from sparknet_tpu.data.pipeline import ParallelBatchPipeline

        with ParallelBatchPipeline(
            pds, 8, workers=workers, shuffle=True, seed=3, epochs=2,
            transform=_aug,
        ) as pipe:
            pipe.skip(13)
            got = [next(pipe) for _ in range(5)]
    _assert_same_stream(ref, got)


def test_global_shuffle_deterministic_per_seed_epoch(packed_dir):
    d, _ = packed_dir
    for window in (0, 16):  # full mode and streaming-window mode
        pds = PackedDataset(d, shuffle_window=window)
        one = [b["label"] for b in pds.batches(8, shuffle=True, seed=5,
                                               epochs=2)]
        two = [b["label"] for b in pds.batches(8, shuffle=True, seed=5,
                                               epochs=2)]
        for x, y in zip(one, two):
            np.testing.assert_array_equal(x, y)
        other = [b["label"] for b in pds.batches(8, shuffle=True, seed=6,
                                                 epochs=2)]
        assert any((x != y).any() for x, y in zip(one, other))
        # epochs reshuffle (epoch is part of the RNG key)
        per_epoch = np.array_split(np.concatenate(one), 2)
        assert (per_epoch[0] != per_epoch[1]).any()
        # every record appears exactly once per epoch
        seen = np.sort(np.concatenate(
            [b["label"] for b in pds.batches(8, shuffle=True, seed=5,
                                             epochs=1,
                                             drop_remainder=False)]
        ))
        np.testing.assert_array_equal(seen, np.arange(97))


def test_host_shard_partitions_records(packed_dir):
    d, _ = packed_dir
    pds = PackedDataset(d)
    s0, s1 = pds.shard(0, 2), pds.shard(1, 2)
    assert s0.num_records + s1.num_records == pds.num_records
    assert {s0.fingerprint, s1.fingerprint, pds.fingerprint}.__len__() == 3
    got = np.sort(np.concatenate(
        [b["label"] for s in (s0, s1)
         for b in s.batches(8, shuffle=False, epochs=1,
                            drop_remainder=False)]
    ))
    np.testing.assert_array_equal(got, np.arange(97))


# ---------------------------------------------------------------------------
# decoded-batch cache
# ---------------------------------------------------------------------------

def test_cache_hits_are_bit_identical(packed_dir, cache):
    d, _ = packed_dir
    pds = PackedDataset(d, cache=cache)
    cold = list(pds.batches(8, shuffle=True, seed=3, epochs=1,
                            transform=_aug))
    snap = cache.metrics.snapshot()
    assert snap["puts"] == len(cold) and snap["hits"] == 0
    warm = list(pds.batches(8, shuffle=True, seed=3, epochs=1,
                            transform=_aug))
    assert cache.metrics.snapshot()["hits"] == len(warm)
    _assert_same_stream(cold, warm)
    # a different stream (other seed) shares nothing
    list(pds.batches(8, shuffle=True, seed=4, epochs=1))
    assert cache.metrics.snapshot()["hits"] == len(warm)


def test_cache_cross_process(packed_dir, cache):
    """The cross-job story: a forked child (a co-located job) fills the
    cache; the parent's fresh reader serves from it."""
    d, _ = packed_dir

    def child():
        pds = PackedDataset(d, cache=ShmBatchCache(
            cache.namespace, max_bytes=cache.max_bytes
        ))
        list(pds.batches(8, shuffle=True, seed=3, epochs=1))

    p = multiprocessing.get_context("fork").Process(target=child)
    p.start()
    p.join(timeout=60)
    assert p.exitcode == 0
    pds = PackedDataset(d, cache=cache)
    got = list(pds.batches(8, shuffle=True, seed=3, epochs=1))
    snap = cache.metrics.snapshot()
    assert snap["hits"] == len(got) and snap["puts"] == 0
    ref = list(PackedDataset(d).batches(8, shuffle=True, seed=3, epochs=1))
    _assert_same_stream(ref, got)


def test_cache_eviction_respects_budget(packed_dir):
    d, _ = packed_dir
    c = ShmBatchCache(f"t-{uuid.uuid4().hex[:8]}", max_bytes=3 * 4096)
    try:
        pds = PackedDataset(d, cache=c)
        list(pds.batches(8, shuffle=True, seed=3, epochs=1))
        snap = c.metrics.snapshot()
        assert snap["evictions"] > 0
        assert c.total_bytes() <= c.max_bytes
    finally:
        c.clear()


def test_torn_cache_segment_falls_back_to_decode(packed_dir, cache):
    from multiprocessing import shared_memory

    d, _ = packed_dir
    pds = PackedDataset(d, cache=cache)
    cold = list(pds.batches(8, shuffle=True, seed=3, epochs=1))
    seg = glob.glob(f"/dev/shm/{SHM_CACHE_PREFIX}_{cache._ns}_*")[0]
    s = shared_memory.SharedMemory(name=os.path.basename(seg))
    s.buf[s.size - 1] = (s.buf[s.size - 1] + 1) % 256  # payload bit rot
    s.close()
    warm = list(pds.batches(8, shuffle=True, seed=3, epochs=1))
    snap = cache.metrics.snapshot()
    assert snap["torn"] == 1  # detected, unlinked, re-decoded
    _assert_same_stream(cold, warm)


def test_chaos_torn_shard_never_poisons_cache(packed_dir, cache):
    from sparknet_tpu import chaos
    from sparknet_tpu.telemetry.registry import REGISTRY

    d, _ = packed_dir
    clean = list(PackedDataset(d).batches(8, shuffle=False, epochs=1))
    before = REGISTRY.counter("packed_reader", event="crc_skipped").snapshot()
    chaos.install("data.torn_shard@shard=1:index=2")
    try:
        pds = PackedDataset(d, cache=cache)
        got = list(pds.batches(8, shuffle=False, epochs=1))
        after = REGISTRY.counter(
            "packed_reader", event="crc_skipped"
        ).snapshot()
        assert after - before == 1
        # the tainted batch (duplicated neighbor record) was NOT cached
        assert cache.metrics.snapshot()["puts"] == len(got) - 1
        assert sum(
            (x["label"] != y["label"]).any() for x, y in zip(clean, got)
        ) == 1
    finally:
        chaos.clear()
    # chaos off: the stream is clean again — nothing stale in the cache
    got2 = list(PackedDataset(d, cache=cache).batches(8, shuffle=False,
                                                      epochs=1))
    _assert_same_stream(clean, got2)


# ---------------------------------------------------------------------------
# training: bitwise equality + shard-level mid-epoch resume
# ---------------------------------------------------------------------------

_NET = """
name: "dp"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""
_SOLVER = 'base_lr: 0.1\nlr_policy: "fixed"\nmomentum: 0.9\nmax_iter: 6\n'


def _mlp_solver():
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.trainer import Solver

    sp = caffe_pb.load_solver(_SOLVER, is_path=False)
    sp.net_param = caffe_pb.load_net(_NET, is_path=False)
    return Solver(sp, {"data": (8, 6), "label": (8,)})


def _mlp_arrays():
    rng = np.random.default_rng(11)
    return {
        "data": rng.normal(size=(48, 6)).astype(np.float32),
        "label": rng.integers(0, 3, 48).astype(np.int32),
    }


def test_training_bitwise_equal_and_midepoch_resume(tmp_path, cache):
    """Weights after training on the packed feed — cold, cache-served,
    and after a mid-epoch save/restore whose align_feed fast-forwards
    via the shard-level skip — are all bitwise equal to the legacy
    in-memory feed's."""
    import jax

    arrays = _mlp_arrays()
    d = str(tmp_path / "p")
    pack_arrays(d, arrays, 3)
    legacy = ShardedDataset.from_arrays(arrays, 3)
    pds_cold = PackedDataset(d, cache=cache)

    def train(feed):
        s = _mlp_solver()
        s.step(feed, 6)
        return jax.device_get(s.params)

    ref = train(legacy.batches(8, shuffle=True, seed=5))
    results = {
        "packed_cold": train(pds_cold.batches(8, shuffle=True, seed=5)),
        "packed_cached": train(pds_cold.batches(8, shuffle=True, seed=5)),
    }
    assert cache.metrics.snapshot()["hits"] >= 6

    # mid-epoch resume: 3 iters, snapshot, fresh solver + fresh feed,
    # restore aligns the feed (skip crosses a shard boundary), 3 more
    path = str(tmp_path / "ck.solverstate.npz")
    s1 = _mlp_solver()
    feed = pds_cold.batches(8, shuffle=True, seed=5)
    s1.step(feed, 3)
    s1.save(path)
    s2 = _mlp_solver()
    feed2 = pds_cold.batches(8, shuffle=True, seed=5)
    s2.restore(path, feed2)
    assert s2.iter == 3
    s2.step(feed2, 3)
    results["resumed"] = jax.device_get(s2.params)

    for name, got in results.items():
        for layer in ref:
            for p in ref[layer]:
                np.testing.assert_array_equal(
                    ref[layer][p], got[layer][p],
                    err_msg=f"{name}: {layer}/{p}",
                )


# ---------------------------------------------------------------------------
# prefetch double-buffering + metrics surface
# ---------------------------------------------------------------------------

def test_reader_metrics_and_prefetch_counts(packed_dir):
    d, _ = packed_dir
    pds = PackedDataset(d)
    it = pds.batches(8, shuffle=True, seed=3, epochs=1)
    n = len(list(it))
    snap = it.metrics.snapshot()
    it.close()
    assert snap["batches"] == n and snap["rows"] == n * 8
    pf = snap["prefetch"]
    assert pf["hits"] + pf["misses"] >= 1  # shard opens went through it
    assert set(pf["wait"]) == {"count", "mean_ms", "p50_ms", "p95_ms",
                               "p99_ms"}


def test_double_buffer_hit_miss_and_errors():
    import time

    from sparknet_tpu.data.pipeline import PipelineMetrics
    from sparknet_tpu.data.prefetch import DoubleBuffer

    pm = PipelineMetrics(source_name="test_dbuf")
    calls = []

    def fetch(k):
        calls.append(k)
        if k == "boom":
            raise RuntimeError("staged failure")
        return f"v{k}"

    db = DoubleBuffer(fetch, metrics=pm)
    assert db.get(1) == "v1"          # nothing staged: miss
    db.stage(2)
    for _ in range(100):              # staged in a background thread
        time.sleep(0.01)
        if pm.prefetch_hits + pm.prefetch_misses >= 1 and 2 in calls:
            break
    assert db.get(2) == "v2"          # hit
    assert pm.prefetch_hits == 1 and pm.prefetch_misses == 1
    db.stage("boom")
    with pytest.raises(RuntimeError, match="staged failure"):
        db.get("boom")                # staged exception re-raises at get
    db.close()


def test_prefetch_to_device_reports_metrics(packed_dir):
    from sparknet_tpu.data.pipeline import PipelineMetrics
    from sparknet_tpu.data.prefetch import prefetch_to_device

    d, _ = packed_dir
    pds = PackedDataset(d)
    inner = pds.batches(8, shuffle=True, seed=0, epochs=1)
    pm = inner.metrics
    base = pm.prefetch_hits + pm.prefetch_misses
    feed = prefetch_to_device(inner, size=2, put=lambda b: b, metrics=pm)
    n = len(list(feed))
    assert n == 12
    assert pm.prefetch_hits + pm.prefetch_misses >= base + n
    inner.close()


# ---------------------------------------------------------------------------
# pack tool
# ---------------------------------------------------------------------------

def test_pack_tool_cli_roundtrip(tmp_path, capsys):
    from sparknet_tpu.tools import pack_records

    out = str(tmp_path / "out")
    rc = pack_records.main(
        ["--source", "synthetic-cifar", "--n", "64", "--out", out]
    )
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert [s["records"] for s in rec["packed"]] == [64, 12]
    train = packed_dataset(out, train=True)
    assert train.num_records == 64
    assert train.sample_shape() == (32, 32, 3)
    assert train.mean() is not None and train.mean().shape == (32, 32, 3)
    # bit-identical to the loader it packed from
    from sparknet_tpu.data.cifar import cifar10_dataset

    legacy, _ = cifar10_dataset(None, train=True, synthetic_n=64)
    _assert_same_stream(
        list(legacy.batches(8, shuffle=True, seed=1, epochs=1)),
        list(train.batches(8, shuffle=True, seed=1, epochs=1)),
    )
