"""Flash attention (interpret mode) vs the jnp reference oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.ops.attention import (
    attention,
    flash_attention,
    mha_reference,
)


def rand_qkv(rng, b=2, h=2, sq=128, sk=128, d=32):
    q = jnp.asarray(rng.normal(size=(b, h, sq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, sk, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, sk, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = rand_qkv(rng, sq=256, sk=256)
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_kv_mask():
    rng = np.random.default_rng(1)
    q, k, v = rand_qkv(rng, b=2, sq=128, sk=128)
    mask = np.ones((2, 128), bool)
    mask[0, 100:] = False  # pad tail of batch row 0
    mask[1, 64:] = False
    ref = mha_reference(q, k, v, kv_mask=jnp.asarray(mask))
    out = flash_attention(q, k, v, kv_mask=jnp.asarray(mask),
                          interpret=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_offsets_match_sliced_causal():
    """Ring-attention contract: running the kernel on a KV shard with
    kv_offset must equal the corresponding slice of full causal attention
    when merged — here checked in the single-shard degenerate case: query
    shard [128:256) of a 256-seq causal attention over full KV."""
    rng = np.random.default_rng(2)
    q, k, v = rand_qkv(rng, b=1, h=1, sq=256, sk=256, d=16)
    full = mha_reference(q, k, v, causal=True)
    out = flash_attention(
        q[:, :, 128:], k, v, causal=True, q_offset=128, kv_offset=0,
        interpret=True, block_q=64, block_k=64,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(full[:, :, 128:]), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    rng = np.random.default_rng(3)
    q, k, v = rand_qkv(rng, b=1, h=2, sq=128, sk=128, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True,
                            block_q=64, block_k=64)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=causal)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_grads_with_mask():
    rng = np.random.default_rng(4)
    q, k, v = rand_qkv(rng, b=2, h=1, sq=64, sk=64, d=16)
    mask = np.ones((2, 64), bool)
    mask[1, 32:] = False
    mask_j = jnp.asarray(mask)

    def lf(q, k, v):
        o = flash_attention(q, k, v, kv_mask=mask_j, interpret=True,
                            block_q=32, block_k=32)
        return jnp.sum(o * o)

    def lr(q, k, v):
        return jnp.sum(jnp.square(mha_reference(q, k, v, kv_mask=mask_j)))

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
    # grads w.r.t. masked-out V rows must be exactly zero
    assert np.abs(np.asarray(gf[2])[1, :, 32:]).max() == 0.0


def test_dispatcher_cpu_uses_reference():
    rng = np.random.default_rng(5)
    q, k, v = rand_qkv(rng, sq=64, sk=64)
    out = attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v)), rtol=1e-6
    )


def test_flash_ragged_seq_snaps_blocks():
    """Non-128-multiple seq lens work via gcd block snapping."""
    rng = np.random.default_rng(6)
    q, k, v = rand_qkv(rng, sq=96, sk=96)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v)),
        rtol=2e-5, atol=2e-5,
    )


def test_resolve_blocks_never_full_axis():
    """A non-conforming length must pad-and-mask, never silently snap
    to a full-axis block (the S=32k VMEM blowup the streamed kernel
    exists to avoid)."""
    from sparknet_tpu.ops.attention import _resolve_blocks

    # S = 32k + 8: an 8-multiple whose gcd with 128 is a sliver —
    # both axes pad to lane multiples and keep full-size blocks
    pad_q, pad_k, bq, bk = _resolve_blocks(32776, 32776, 128, 128)
    assert (pad_q, pad_k, bq, bk) == (120, 120, 128, 128)
    assert (32776 + pad_q) % bq == 0 and (32776 + pad_k) % bk == 0

    # odd length: both axes pad, blocks stay at granularity
    pad_q, pad_k, bq, bk = _resolve_blocks(13, 13, 128, 128)
    assert (13 + pad_q) % 8 == 0 and (13 + pad_k) % 128 == 0
    assert bq % 8 == 0 and bk % 128 == 0

    # conforming lengths: no padding, full-size blocks
    assert _resolve_blocks(4096, 4096, 128, 128) == (0, 0, 128, 128)

    # an under-lane block request is raised to one lane tile, not
    # bounced to the full axis
    pad_q, pad_k, bq, bk = _resolve_blocks(4096, 4096, 64, 64)
    assert (bq, bk) == (64, 128)

    # awkward block requests (coprime-ish with the padded axis) must
    # still come back sublane/lane legal
    for req_q in (129, 132):
        pad_q, pad_k, bq, bk = _resolve_blocks(32776, 32776, req_q, 128)
        assert bq % 8 == 0 and (32776 + pad_q) % bq == 0, (req_q, bq)
        assert bk % 128 == 0 and (32776 + pad_k) % bk == 0


@pytest.mark.parametrize("causal", [False, True])
def test_flash_padded_lengths_match_reference(causal):
    """Odd (sub-granularity) lengths run via pad-and-mask: forward and
    grads match the reference exactly on the unpadded region."""
    rng = np.random.default_rng(11)
    q, k, v = rand_qkv(rng, b=1, h=2, sq=100, sk=77, d=32)

    def f_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(jnp.cos(o)), o

    def f_ref(q, k, v):
        o = mha_reference(q, k, v, causal=causal)
        return jnp.sum(jnp.cos(o)), o

    (_, o1), g1 = jax.value_and_grad(f_flash, (0, 1, 2), has_aux=True)(
        q, k, v
    )
    (_, o2), g2 = jax.value_and_grad(f_ref, (0, 1, 2), has_aux=True)(
        q, k, v
    )
    assert o1.shape == (1, 2, 100, 32)
    np.testing.assert_allclose(
        np.asarray(o1), np.asarray(o2), rtol=2e-5, atol=2e-5
    )
    for a, b, name in zip(g1, g2, "qkv"):
        assert a.shape == b.shape, name
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
        )


def test_flash_fully_padded_row():
    """A batch row whose kv_mask is all zero: forward exactly 0, grads
    exactly 0 (the reference path shares this contract)."""
    rng = np.random.default_rng(7)
    q, k, v = rand_qkv(rng, b=2, h=1, sq=64, sk=64, d=16)
    mask = np.ones((2, 64), bool)
    mask[1, :] = False
    mask_j = jnp.asarray(mask)

    for impl in ("flash", "reference"):
        def loss(q, k, v):
            if impl == "flash":
                o = flash_attention(q, k, v, kv_mask=mask_j, interpret=True,
                                    block_q=32, block_k=32)
            else:
                o = mha_reference(q, k, v, kv_mask=mask_j)
            return jnp.sum(jnp.sin(o)), o

        (l, o), g = jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)(
            q, k, v
        )
        assert np.abs(np.asarray(o)[1]).max() == 0.0, impl
        for gi, name in zip(g, "qkv"):
            assert np.abs(np.asarray(gi)[1]).max() == 0.0, (impl, name)
            assert np.isfinite(np.asarray(gi)).all(), (impl, name)


@pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="in-kernel dropout PRNG only exists on real TPU hardware "
    "(interpret mode stubs prng_random_bits to 0)",
)
@pytest.mark.parametrize("rate", [0.1, 0.5])
def test_flash_dropout_keep_rate_on_hardware(rate):
    """Regression for the signed-compare keep-rate bug: with v=ones each
    output row is the (rescaled) kept attention mass, whose expectation
    is exactly 1.0 when the keep probability and 1/(1-rate) rescale are
    right.  The buggy unsigned threshold measured 0.44 at rate=0.1 and
    2.0 at rate=0.5 on v5e."""
    rng = np.random.default_rng(11)
    q, k, _ = rand_qkv(rng, b=2, h=4, sq=512, sk=512, d=64)
    v = jnp.ones_like(q)
    key = jax.random.PRNGKey(42)
    o = flash_attention(q, k, v, dropout_rate=rate, dropout_rng=key)
    mass = float(jnp.mean(o))
    assert abs(mass - 1.0) < 0.05, mass
    # determinism: same rng -> identical mask
    o2 = flash_attention(q, k, v, dropout_rate=rate, dropout_rng=key)
    assert bool(jnp.all(o == o2))
    # fwd/bwd mask consistency: dv row mass has the same expectation
    def loss(vv):
        return flash_attention(
            q, k, vv, dropout_rate=rate, dropout_rng=key
        ).astype(jnp.float32).sum()

    dv = jax.grad(loss)(jnp.asarray(rng.normal(size=q.shape), jnp.float32))
    assert abs(float(jnp.mean(dv)) - 1.0) < 0.05


@pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="in-kernel dropout PRNG only exists on real TPU hardware",
)
def test_flash_dropout_mask_identical_fwd_bwd_on_hardware():
    """fwd/bwd dropout-mask identity (the statistical keep-rate test
    cannot see a derivation mismatch — two different masks with the
    right rate still have the right expectations).  With v = I the
    forward output IS the dropped probability matrix p~, so dv must
    equal p~^T @ dO.  The comparison is statistical, not bitwise: the
    MXU's multi-pass bf16 f32 matmuls leave ~3e-3 noise, so the test
    asserts the dv error against the EXTRACTED mask is far below the
    error against the keep-all hypothesis (a mismatched derivation
    lands at the keep-all error scale).  S == d so the extraction
    works; h=2 exercises the head-folded path."""
    b, h, s = 1, 2, 256
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(b, h, s, s)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, s, s)), jnp.float32)
    eye = jnp.broadcast_to(jnp.eye(s, dtype=jnp.float32), (b, h, s, s))
    key = jax.random.PRNGKey(7)
    rate = 0.3

    p_dropped = np.asarray(
        flash_attention(q, k, eye, dropout_rate=rate, dropout_rng=key)
    )  # (b, h, s, s): row i = dropped+rescaled softmax probs of query i
    p_all = np.asarray(flash_attention(q, k, eye))  # undropped softmax

    g_out = jnp.asarray(rng.normal(size=(b, h, s, s)), jnp.float32)

    def loss(vv):
        return jnp.sum(
            flash_attention(q, k, vv, dropout_rate=rate, dropout_rng=key)
            * g_out
        )

    dv = np.asarray(jax.grad(loss)(eye))
    g_np = np.asarray(g_out)
    err_mask = np.abs(
        dv - np.einsum("bhqk,bhqd->bhkd", p_dropped, g_np)
    ).mean()
    err_keepall = np.abs(
        dv - np.einsum("bhqk,bhqd->bhkd", p_all, g_np)
    ).mean()
    # identical masks: only MXU noise remains; a derivation mismatch
    # would sit at (or above) the keep-all error scale
    assert err_mask < 1e-3, err_mask
    assert err_keepall > 5 * err_mask, (err_mask, err_keepall)
