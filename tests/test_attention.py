"""Flash attention (interpret mode) vs the jnp reference oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.ops.attention import (
    attention,
    flash_attention,
    mha_reference,
)


def rand_qkv(rng, b=2, h=2, sq=128, sk=128, d=32):
    q = jnp.asarray(rng.normal(size=(b, h, sq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, sk, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, sk, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = rand_qkv(rng, sq=256, sk=256)
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_kv_mask():
    rng = np.random.default_rng(1)
    q, k, v = rand_qkv(rng, b=2, sq=128, sk=128)
    mask = np.ones((2, 128), bool)
    mask[0, 100:] = False  # pad tail of batch row 0
    mask[1, 64:] = False
    ref = mha_reference(q, k, v, kv_mask=jnp.asarray(mask))
    out = flash_attention(q, k, v, kv_mask=jnp.asarray(mask),
                          interpret=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_offsets_match_sliced_causal():
    """Ring-attention contract: running the kernel on a KV shard with
    kv_offset must equal the corresponding slice of full causal attention
    when merged — here checked in the single-shard degenerate case: query
    shard [128:256) of a 256-seq causal attention over full KV."""
    rng = np.random.default_rng(2)
    q, k, v = rand_qkv(rng, b=1, h=1, sq=256, sk=256, d=16)
    full = mha_reference(q, k, v, causal=True)
    out = flash_attention(
        q[:, :, 128:], k, v, causal=True, q_offset=128, kv_offset=0,
        interpret=True, block_q=64, block_k=64,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(full[:, :, 128:]), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    rng = np.random.default_rng(3)
    q, k, v = rand_qkv(rng, b=1, h=2, sq=128, sk=128, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True,
                            block_q=64, block_k=64)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=causal)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_grads_with_mask():
    rng = np.random.default_rng(4)
    q, k, v = rand_qkv(rng, b=2, h=1, sq=64, sk=64, d=16)
    mask = np.ones((2, 64), bool)
    mask[1, 32:] = False
    mask_j = jnp.asarray(mask)

    def lf(q, k, v):
        o = flash_attention(q, k, v, kv_mask=mask_j, interpret=True,
                            block_q=32, block_k=32)
        return jnp.sum(o * o)

    def lr(q, k, v):
        return jnp.sum(jnp.square(mha_reference(q, k, v, kv_mask=mask_j)))

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
    # grads w.r.t. masked-out V rows must be exactly zero
    assert np.abs(np.asarray(gf[2])[1, :, 32:]).max() == 0.0


def test_dispatcher_cpu_uses_reference():
    rng = np.random.default_rng(5)
    q, k, v = rand_qkv(rng, sq=64, sk=64)
    out = attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v)), rtol=1e-6
    )


def test_flash_ragged_seq_snaps_blocks():
    """Non-128-multiple seq lens work via gcd block snapping."""
    rng = np.random.default_rng(6)
    q, k, v = rand_qkv(rng, sq=96, sk=96)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v)),
        rtol=2e-5, atol=2e-5,
    )


def test_flash_fully_padded_row():
    """A batch row whose kv_mask is all zero: forward exactly 0, grads
    exactly 0 (the reference path shares this contract)."""
    rng = np.random.default_rng(7)
    q, k, v = rand_qkv(rng, b=2, h=1, sq=64, sk=64, d=16)
    mask = np.ones((2, 64), bool)
    mask[1, :] = False
    mask_j = jnp.asarray(mask)

    for impl in ("flash", "reference"):
        def loss(q, k, v):
            if impl == "flash":
                o = flash_attention(q, k, v, kv_mask=mask_j, interpret=True,
                                    block_q=32, block_k=32)
            else:
                o = mha_reference(q, k, v, kv_mask=mask_j)
            return jnp.sum(jnp.sin(o)), o

        (l, o), g = jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)(
            q, k, v
        )
        assert np.abs(np.asarray(o)[1]).max() == 0.0, impl
        for gi, name in zip(g, "qkv"):
            assert np.abs(np.asarray(gi)[1]).max() == 0.0, (impl, name)
            assert np.isfinite(np.asarray(gi)).all(), (impl, name)


@pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="in-kernel dropout PRNG only exists on real TPU hardware "
    "(interpret mode stubs prng_random_bits to 0)",
)
@pytest.mark.parametrize("rate", [0.1, 0.5])
def test_flash_dropout_keep_rate_on_hardware(rate):
    """Regression for the signed-compare keep-rate bug: with v=ones each
    output row is the (rescaled) kept attention mass, whose expectation
    is exactly 1.0 when the keep probability and 1/(1-rate) rescale are
    right.  The buggy unsigned threshold measured 0.44 at rate=0.1 and
    2.0 at rate=0.5 on v5e."""
    rng = np.random.default_rng(11)
    q, k, _ = rand_qkv(rng, b=2, h=4, sq=512, sk=512, d=64)
    v = jnp.ones_like(q)
    key = jax.random.PRNGKey(42)
    o = flash_attention(q, k, v, dropout_rate=rate, dropout_rng=key)
    mass = float(jnp.mean(o))
    assert abs(mass - 1.0) < 0.05, mass
    # determinism: same rng -> identical mask
    o2 = flash_attention(q, k, v, dropout_rate=rate, dropout_rng=key)
    assert bool(jnp.all(o == o2))
    # fwd/bwd mask consistency: dv row mass has the same expectation
    def loss(vv):
        return flash_attention(
            q, k, vv, dropout_rate=rate, dropout_rng=key
        ).astype(jnp.float32).sum()

    dv = jax.grad(loss)(jnp.asarray(rng.normal(size=q.shape), jnp.float32))
    assert abs(float(jnp.mean(dv)) - 1.0) < 0.05
