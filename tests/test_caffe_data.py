"""Caffe-native data sources: LMDB/Datum, ImageData, HDF5Data."""

import os

import numpy as np
import pytest

from sparknet_tpu.data.caffe_layers import (
    dataset_from_layer,
    decode_datum,
    encode_datum,
    hdf5_dataset,
    image_data_dataset,
    lmdb_dataset,
)
from sparknet_tpu.data.lmdb_io import LMDBReader, write_lmdb
from sparknet_tpu.proto import caffe_pb


def test_lmdb_round_trip_small_values(tmp_path):
    items = [(f"{i:08d}".encode(), f"value-{i}".encode() * 3) for i in range(50)]
    path = str(tmp_path / "small.mdb")
    write_lmdb(path, items)
    got = list(LMDBReader(path).items())
    assert got == sorted(items)


def test_lmdb_round_trip_multi_leaf_and_overflow(tmp_path):
    rng = np.random.default_rng(0)
    items = []
    for i in range(40):
        if i % 5 == 0:  # big values -> overflow pages
            val = rng.integers(0, 256, 9000, dtype=np.uint8).tobytes()
        else:
            val = rng.integers(0, 256, 500, dtype=np.uint8).tobytes()
        items.append((f"k{i:06d}".encode(), val))
    path = str(tmp_path / "big.mdb")
    write_lmdb(path, items)
    reader = LMDBReader(path)
    assert len(reader) == 40
    got = list(reader.items())
    assert [k for k, _ in got] == [k for k, _ in sorted(items)]
    for (k1, v1), (k2, v2) in zip(got, sorted(items)):
        assert v1 == v2, k1


def test_lmdb_directory_layout(tmp_path):
    d = tmp_path / "db_dir"
    d.mkdir()
    write_lmdb(str(d), [(b"a", b"1")])
    assert os.path.exists(d / "data.mdb")
    assert list(LMDBReader(str(d)).items()) == [(b"a", b"1")]


def test_datum_round_trip_uint8_and_float():
    rng = np.random.default_rng(1)
    img = rng.integers(0, 256, (8, 6, 3), dtype=np.uint8)
    out, label = decode_datum(encode_datum(img, 7))
    assert label == 7
    np.testing.assert_array_equal(out, img)

    imgf = rng.normal(size=(4, 5, 3)).astype(np.float32)
    out, label = decode_datum(encode_datum(imgf, 2))
    assert label == 2
    np.testing.assert_allclose(out, imgf, rtol=1e-6)


def test_lmdb_dataset_batches(tmp_path):
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (30, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, 30)
    items = [
        (f"{i:08d}".encode(), encode_datum(imgs[i], int(labels[i])))
        for i in range(30)
    ]
    path = str(tmp_path / "cifar.mdb")
    write_lmdb(path, items)
    ds = lmdb_dataset(path, num_partitions=4)
    batch = next(ds.batches(8, shuffle=False))
    assert batch["data"].shape == (8, 32, 32, 3)
    np.testing.assert_array_equal(batch["data"], imgs[:8])
    np.testing.assert_array_equal(batch["label"], labels[:8])


def test_hdf5_dataset(tmp_path):
    import h5py

    rng = np.random.default_rng(3)
    data = rng.normal(size=(12, 3, 8, 8)).astype(np.float32)  # NCHW
    label = rng.integers(0, 5, 12)
    h5 = str(tmp_path / "part0.h5")
    with h5py.File(h5, "w") as f:
        f["data"] = data
        f["label"] = label
    src = tmp_path / "files.txt"
    src.write_text(h5 + "\n")
    ds = hdf5_dataset(str(src))
    part = ds.collect_partition(0)
    assert part["data"].shape == (12, 8, 8, 3)
    np.testing.assert_allclose(
        part["data"], np.transpose(data, (0, 2, 3, 1)), rtol=1e-6
    )
    np.testing.assert_array_equal(part["label"], label)


def test_image_data_dataset(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(4)
    lines = []
    for i in range(6):
        arr = rng.integers(0, 256, (10, 12, 3), dtype=np.uint8)
        p = tmp_path / f"img{i}.png"
        Image.fromarray(arr).save(p)
        lines.append(f"img{i}.png {i % 3}")
    src = tmp_path / "list.txt"
    src.write_text("\n".join(lines) + "\n")
    ds = image_data_dataset(
        str(src), root_folder=str(tmp_path), new_height=8, new_width=9
    )
    part = ds.collect_partition(0)
    assert part["data"].shape == (6, 8, 9, 3)
    np.testing.assert_array_equal(part["label"], [0, 1, 2, 0, 1, 2])


def test_dataset_from_layer_lmdb(tmp_path):
    rng = np.random.default_rng(5)
    imgs = rng.integers(0, 256, (10, 32, 32, 3), dtype=np.uint8)
    items = [
        (f"{i:08d}".encode(), encode_datum(imgs[i], i % 10)) for i in range(10)
    ]
    db = str(tmp_path / "train_lmdb")
    os.makedirs(db)
    write_lmdb(db, items)
    layer = caffe_pb.load_net(
        f"""
        name: "t"
        layer {{ name: "d" type: "Data" top: "data" top: "label"
                 data_param {{ source: "{db}" batch_size: 4 backend: LMDB }} }}
        """,
        is_path=False,
    ).layers[0]
    ds = dataset_from_layer(layer)
    assert ds is not None
    part = next(ds.batches(4, shuffle=False))
    np.testing.assert_array_equal(part["data"], imgs[:4])

    missing = caffe_pb.load_net(
        """
        name: "t"
        layer { name: "d" type: "Data" top: "data" top: "label"
                data_param { source: "/nonexistent_lmdb" batch_size: 4 } }
        """,
        is_path=False,
    ).layers[0]
    assert dataset_from_layer(missing) is None
