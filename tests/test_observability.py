"""Cluster observability plane (ISSUE 7): aggregation, anomaly
detection, flight recorder, dashboard.

The acceptance bar: 3-rank aggregation merges bounded, version-tagged
payloads; an injected slow rank trips the straggler detector
deterministically (pinned); a chaos-killed supervised child's failure
record references a readable flight-recorder dump; the ``/dash`` route
returns valid HTML with live numbers; and every disabled mode stays
the PR-5 allocation-free no-op.  CPU-only, tier-1.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.telemetry import (
    REGISTRY,
    aggregate,
    anomaly,
    dash,
    flight,
    timeline,
    trace,
)

SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)


@pytest.fixture(autouse=True)
def _isolation(monkeypatch):
    """No aggregator, advisory board, flight ring, tracer state, or
    supervision env may leak between tests."""
    for var in (
        "SPARKNET_SUPERVISE", "SPARKNET_SUPERVISE_DIR",
        "SPARKNET_SUPERVISE_GEN", "SPARKNET_FLIGHT",
        "SPARKNET_CLUSTER_TELEMETRY",
    ):
        monkeypatch.delenv(var, raising=False)
    anomaly.clear()
    anomaly.reset_detectors()
    aggregate.reset()
    flight.disable()
    yield
    anomaly.clear()
    anomaly.reset_detectors()
    aggregate.reset()
    flight.disable()
    trace.disable()
    timeline.set_current(None)
    os.environ.pop(trace.OWNER_PID_ENV, None)


def _payload(rank, seq, phases, wall, v=aggregate.PAYLOAD_VERSION, **extra):
    doc = {
        "v": v, "rank": rank, "seq": seq, "pid": 1000 + rank,
        "t": 0.0, "wall_s": wall,
        "phases": {k: list(tc) for k, tc in phases.items()},
        **extra,
    }
    return json.dumps(doc).encode()


_SILENT = lambda s: None  # detectors under test must not spam stdout


# -------------------------------------------------------------- payloads
def test_publisher_payload_is_bounded(monkeypatch):
    class HugeTimeline:
        enabled = True
        wall_s = 100.0

        def snapshot(self):
            return {
                "phases": {
                    f"phase_{i:04d}": {"total_s": 1.0, "count": i}
                    for i in range(2000)
                }
            }

    monkeypatch.setattr(timeline, "_current", HugeTimeline())
    before = REGISTRY.counter("cluster_payload_truncated").snapshot()
    raw = aggregate.RankPublisher(3).payload()
    assert len(raw) <= aggregate.MAX_PAYLOAD_BYTES
    doc = json.loads(raw)
    assert doc["v"] == aggregate.PAYLOAD_VERSION and doc["rank"] == 3
    # the 2000 synthetic phases could not fit: sections were shed (and
    # counted), the envelope survived
    assert len(doc["phases"]) < 2000
    assert REGISTRY.counter("cluster_payload_truncated").snapshot() > before


def test_three_rank_merge_and_version_skew():
    agg = aggregate.ClusterAggregator(
        detector=anomaly.StragglerDetector(emit=_SILENT)
    )
    for r in (0, 1, 2):
        assert agg.ingest(_payload(
            r, 1, {"compiled_step": [1.0 + r, 5], "input_wait": [0.5, 5]},
            wall=2.0 + r,
        ))
    snap = agg.snapshot()
    assert sorted(snap["ranks"]) == ["0", "1", "2"]
    assert snap["ranks"]["2"]["phases"]["compiled_step"]["total_s"] == 3.0
    # per-rank label series landed in the registry
    g = REGISTRY.gauge("cluster_phase_share_pct", rank=1, phase="compiled_step")
    assert g.snapshot()["value"] == pytest.approx(100 * 2.0 / 3.0, abs=0.1)
    # the cluster table renders one column per rank + skew
    table = agg.table()
    assert "r0" in table and "r1" in table and "r2" in table
    assert "compiled_step" in table and "max/med" in table

    # garbage and structurally-wrong payloads are counted, not fatal
    errors0 = REGISTRY.counter("cluster_payload_errors").snapshot()
    assert not agg.ingest(b"{torn json")
    assert not agg.ingest(b'["not an object"]')
    assert not agg.ingest(  # rank must be an integer
        json.dumps({"v": 1, "rank": "x", "phases": {}}).encode()
    )
    assert REGISTRY.counter("cluster_payload_errors").snapshot() >= errors0 + 3

    # version skew is tolerated: newer payload, unknown fields merged
    # past, known fields kept — and the skew counted
    skew0 = REGISTRY.counter("cluster_version_skew").snapshot()
    assert agg.ingest(_payload(
        1, 2, {"compiled_step": [2.5, 6]}, wall=3.5,
        v=aggregate.PAYLOAD_VERSION + 1, future_field={"x": 1},
    ))
    assert REGISTRY.counter("cluster_version_skew").snapshot() == skew0 + 1
    assert agg.snapshot()["ranks"]["1"]["phases"]["compiled_step"][
        "total_s"
    ] == 2.5


def test_ingest_never_raises_via_module_entry():
    assert aggregate.ingest(b"anything") is False  # no aggregator yet
    aggregate.init_aggregator()
    assert aggregate.ingest(b"\xff\xfe garbage") is False
    assert aggregate.ingest(_payload(1, 1, {"eval": [0.1, 1]}, 1.0))


# ------------------------------------------------------------ stragglers
def _round_payloads(agg, k, slow_rank=1, slow_factor=3.0):
    """One full aggregation round: every rank's cumulative phases."""
    for r in (0, 1, 2):
        factor = slow_factor if r == slow_rank else 1.0
        agg.ingest(_payload(
            r, k, {"compiled_step": [k * factor, 5 * k]}, wall=4.0 * k
        ))


def test_injected_slow_rank_trips_straggler_detector():
    """The acceptance pin: rank 1 runs compiled_step 3x the cluster
    median for 3 consecutive aggregation rounds -> exactly one
    straggler anomaly naming rank 1, counted + advisory raised."""
    lines = []
    det = anomaly.StragglerDetector(factor=2.0, rounds=3, emit=lines.append)
    agg = aggregate.ClusterAggregator(detector=det)
    fired0 = REGISTRY.counter("anomalies", kind="straggler").snapshot()
    # round 1 completes solo (ranks 1/2 unknown until they first
    # publish), so the 3-round streak needs 4 publish sweeps
    for k in (1, 2, 3, 4):
        _round_payloads(agg, k)
    assert agg.rounds == 4
    assert REGISTRY.counter("anomalies", kind="straggler").snapshot() == (
        fired0 + 1
    )
    (active,) = anomaly.active("straggler")
    assert active["rank"] == 1 and active["phase"] == "compiled_step"
    assert active["ratio"] == pytest.approx(3.0, abs=0.01)
    # the structured log line parses and names the rank
    (line,) = [ln for ln in lines if ln.startswith("anomaly: ")]
    doc = json.loads(line[len("anomaly: "):])
    assert doc["kind"] == "straggler" and doc["rank"] == 1
    # the cluster snapshot surfaces the advisory
    assert agg.snapshot()["stragglers"]


def test_straggler_streak_resets_below_threshold():
    det = anomaly.StragglerDetector(factor=2.0, rounds=3, emit=_SILENT)

    def round_of(slow):
        return {
            r: {"phases": {"compiled_step": (3.0 if r == 1 and slow else 1.0)},
                "wall_s": 4.0}
            for r in (0, 1, 2)
        }

    before = anomaly.fired_total()
    det.observe_round(round_of(True), 1)
    det.observe_round(round_of(True), 2)
    det.observe_round(round_of(False), 3)  # streak broken
    det.observe_round(round_of(True), 4)
    det.observe_round(round_of(True), 5)
    assert anomaly.fired_total() == before  # never reached 3 consecutive
    assert det.observe_round(round_of(True), 6)  # now it fires
    assert anomaly.fired_total() == before + 1


# --------------------------------------------------------------- outliers
def test_ema_mad_detector_is_deterministic():
    det = anomaly.EmaMadDetector(
        "step_time_spike", k=5.0, min_n=5, emit=_SILENT
    )
    # a mildly noisy plateau: no firings while the window warms up or after
    for x in (1.0, 1.1, 0.9, 1.0, 1.05, 0.95, 1.0, 1.02):
        assert det.observe(x) is None
    # a 10x spike deviates far past k * MAD
    ev = det.observe(10.0)
    assert ev is not None and ev["kind"] == "step_time_spike"
    assert ev["value"] == 10.0
    assert REGISTRY.counter("anomalies", kind="step_time_spike").snapshot() >= 1
    # same stream, fresh detector -> same single firing (determinism)
    det2 = anomaly.EmaMadDetector(
        "step_time_spike", k=5.0, min_n=5, emit=_SILENT
    )
    fires = [
        det2.observe(x) is not None
        for x in (1.0, 1.1, 0.9, 1.0, 1.05, 0.95, 1.0, 1.02, 10.0)
    ]
    assert fires == [False] * 8 + [True]


def test_ema_mad_min_samples_gate():
    det = anomaly.EmaMadDetector("loss_spike", k=5.0, min_n=5, emit=_SILENT)
    assert det.observe(1.0) is None
    assert det.observe(100.0) is None  # only 2 samples: never fires


# ------------------------------------------------------------ queue stalls
def test_queue_stall_detector_fires_and_resets():
    clock = [0.0]
    det = anomaly.QueueStallDetector(
        "serve", observations=3, min_interval_s=1.0,
        emit=_SILENT, now=lambda: clock[0],
    )

    def look(depth, progress):
        clock[0] += 1.0
        return det.observe(depth, progress)

    assert look(5, 10) is None  # first look: baseline
    assert look(5, 10) is None  # stall 1
    assert look(5, 10) is None  # stall 2
    ev = look(5, 10)            # stall 3 -> fire
    assert ev is not None and ev["kind"] == "queue_stall"
    assert ev["queue"] == "serve" and ev["depth"] == 5
    # progress resumes: streak resets, no refire
    assert look(5, 11) is None
    assert look(5, 11) is None and look(5, 11) is None
    # rapid-fire scrapes inside min_interval don't fake a stall
    det2 = anomaly.QueueStallDetector(
        "x", observations=2, min_interval_s=10.0,
        emit=_SILENT, now=lambda: clock[0],
    )
    assert det2.observe(1, 0) is None
    assert det2.observe(1, 0) is None  # same instant: not counted
    assert det2.observe(1, 0) is None


def test_pipeline_stall_poll_from_snapshot():
    # pre-seed the process-global detector with a zero min-interval so
    # the poll path is testable without real flush-cadence sleeps
    anomaly._pipeline_stall = anomaly.QueueStallDetector(
        "pipeline", observations=3, min_interval_s=0.0, emit=_SILENT
    )
    for _ in range(4):
        anomaly.observe_pipeline(
            {"reorder_depth": {"value": 2}, "batches": 7}
        )
    assert any(
        a["kind"] == "queue_stall" and a.get("queue") == "pipeline"
        for a in anomaly.active()
    )
    # malformed snapshots are ignored, never fatal
    anomaly.observe_pipeline({"nonsense": True})


# --------------------------------------------------------- advisory hook
def test_tau_controller_consumes_straggler_advisory():
    from sparknet_tpu.parallel.tau_controller import TauController

    # share 15% is below the normal 25% widen threshold...
    c = TauController(tau=4, tau_min=1, tau_max=64)
    assert c.observe_round(round_s=1.0, sync_s=0.15, loss=1.0) == 4
    # ...but above the halved threshold while a straggler is active
    c2 = TauController(tau=4, tau_min=1, tau_max=64)
    nxt = c2.observe_round(
        round_s=1.0, sync_s=0.15, loss=1.0,
        advisories=[{"kind": "straggler", "rank": 1}],
    )
    assert nxt == 8
    assert c2.decisions[-1]["action"] == "widen"
    assert c2.decisions[-1]["straggler_advisory"] is True


# ------------------------------------------------------ heartbeat piggyback
def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_heartbeat_piggyback_merges_worker_snapshots():
    """The tentpole socket path, in-process: a rank-1 heartbeat client
    publishes stats frames that rank 0's server merges — no
    jax.distributed, the fabric is plain TCP."""
    from sparknet_tpu.parallel.multihost import _Heartbeat

    tl = timeline.Timeline(fence=False)
    timeline.set_current(tl)
    tl.start()
    with tl.phase("compiled_step"):
        time.sleep(0.02)
    port = _free_port()
    hb0 = _Heartbeat("127.0.0.1", port, 0, 2, interval=0.05, timeout=10.0)
    hb1 = _Heartbeat("127.0.0.1", port, 1, 2, interval=0.05, timeout=10.0)
    try:
        agg = aggregate.get_aggregator()
        assert agg is not None  # rank 0's server created it
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            snap = agg.snapshot()
            if snap["ranks"].get("1", {}).get("phases"):
                break
            time.sleep(0.02)
        else:
            pytest.fail(f"rank 1 snapshot never merged: {agg.snapshot()}")
        assert "compiled_step" in snap["ranks"]["1"]["phases"]
        assert "r1" in agg.table()
    finally:
        hb1.close()
        hb0.close()


def test_piggyback_disabled_by_env(monkeypatch):
    monkeypatch.setenv("SPARKNET_CLUSTER_TELEMETRY", "0")
    from sparknet_tpu.parallel.multihost import _Heartbeat

    port = _free_port()
    hb0 = _Heartbeat("127.0.0.1", port, 0, 2, interval=0.05, timeout=5.0)
    hb1 = _Heartbeat("127.0.0.1", port, 1, 2, interval=0.05, timeout=5.0)
    try:
        assert aggregate.get_aggregator() is None
        assert hb1._publisher is None
        time.sleep(0.2)  # pings flow; no stats frames, no crash
    finally:
        hb1.close()
        hb0.close()


# --------------------------------------------------------- flight recorder
def test_flight_disabled_mode_is_allocation_free():
    assert not flight.enabled()
    f = print
    assert flight.tee_log(f) is f  # identity: nothing wrapped
    assert flight.note("x", a=1) is None
    assert flight.dump("/tmp", "t") is None
    assert flight.add_log("line") is None


def test_flight_rings_are_bounded_and_dump_round_trips(tmp_path):
    flight.enable(capacity=4, log_capacity=2)
    for i in range(10):
        flight.note("tick", i=i)
        flight.add_log(f"line {i}")
    snap = flight.snapshot()
    assert [e["i"] for e in snap["events"]] == [6, 7, 8, 9]
    assert snap["logs"] == ["line 8", "line 9"]
    path = flight.dump(str(tmp_path), tag="test")
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["version"] == 1 and len(doc["events"]) == 4
    assert "registry" in doc and "timeline" in doc


def test_flight_configure_from_env(tmp_path, monkeypatch):
    assert flight.configure_from_env() is False  # nothing armed
    monkeypatch.setenv("SPARKNET_SUPERVISE_DIR", str(tmp_path))
    assert flight.configure_from_env() is True  # supervised: armed
    flight.disable()
    monkeypatch.setenv("SPARKNET_FLIGHT", "0")
    assert flight.configure_from_env() is False  # explicit off wins


def test_failure_record_references_flight_dump(tmp_path, monkeypatch):
    from sparknet_tpu.supervise import records

    monkeypatch.setenv(records.RECORD_DIR_ENV, str(tmp_path))
    flight.enable()
    flight.add_log("about to die")
    flight.note("anomaly", anomaly_kind="loss_spike")
    path = records.write_failure_record(
        process_id=0, kind="exception", reason="test", exit_code=1
    )
    rec = json.load(open(path))
    assert rec["flight_recorder"] and os.path.exists(rec["flight_recorder"])
    dump = json.load(open(rec["flight_recorder"]))
    assert "about to die" in dump["logs"]
    assert any(e.get("kind") == "anomaly" for e in dump["events"])
    # dump sits next to the record, in failures/
    assert os.path.dirname(rec["flight_recorder"]) == os.path.dirname(path)


# ----------------------------------------------------------------- serve
class _StubEngine:
    buckets = (4,)
    output = "prob"
    metrics = None

    def infer(self, rows):
        rows = np.asarray(rows, np.float32)
        return rows.reshape(len(rows), -1)[:, :3]

    def postprocess(self, out, top_k):
        idx = np.argsort(-out, axis=-1)[:, :top_k]
        return idx, np.take_along_axis(out, idx, axis=-1)


def _get(host, port, path):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read().decode()
    ctype = resp.getheader("Content-Type") or ""
    conn.close()
    return resp.status, ctype, body


def test_healthz_anomalies_field_and_degraded_status():
    from sparknet_tpu.serve.metrics import ServeMetrics
    from sparknet_tpu.serve.server import InferenceServer

    srv = InferenceServer(
        _StubEngine(), metrics=ServeMetrics((4,)), port=0, model_name="stub"
    ).start()
    try:
        st, _, body = _get(srv.host, srv.port, "/healthz")
        doc = json.loads(body)
        assert st == 200 and doc["status"] == "ok"
        assert doc["anomalies"] == []
        # a live stall advisory degrades the status without touching
        # the shed/cancelled machinery
        anomaly.fire("queue_stall", key="serve", queue="serve", depth=3,
                     emit=_SILENT)
        st, _, body = _get(srv.host, srv.port, "/healthz")
        doc = json.loads(body)
        assert doc["status"] == "degraded"
        assert any(a["kind"] == "queue_stall" for a in doc["anomalies"])
        # non-degrading anomaly kinds report but don't degrade
        anomaly.clear()
        anomaly.fire("loss_spike", value=9.0, emit=_SILENT)
        st, _, body = _get(srv.host, srv.port, "/healthz")
        doc = json.loads(body)
        assert doc["status"] == "ok" and len(doc["anomalies"]) == 1
    finally:
        srv.stop()


def test_dash_route_serves_live_html():
    from sparknet_tpu.serve.metrics import ServeMetrics
    from sparknet_tpu.serve.server import InferenceServer

    srv = InferenceServer(
        _StubEngine(), metrics=ServeMetrics((4,)), port=0, model_name="stub"
    ).start()
    try:
        c = srv.client()
        st, _ = c.classify(np.ones((2, 3)), top_k=2)
        assert st == 200
        anomaly.fire("loss_spike", value=9.0, emit=_SILENT)
        st, ctype, body = _get(srv.host, srv.port, "/dash")
        assert st == 200 and ctype.startswith("text/html")
        assert body.startswith("<!doctype html>")
        assert "sparknet" in body and "stub" in body
        # live numbers: the one classify request shows in the tiles
        assert '<div class="value">1</div>' in body
        # the anomaly feed rendered the firing
        assert "loss_spike" in body
    finally:
        srv.stop()


def test_dash_renders_cluster_bars_from_snapshot():
    agg = aggregate.ClusterAggregator(
        detector=anomaly.StragglerDetector(emit=_SILENT)
    )
    for r in (0, 1):
        agg.ingest(_payload(
            r, 1,
            {"compiled_step": [3.0, 5], "input_wait": [1.0, 5]},
            wall=4.0,
        ))
    html_ = dash.render_html(
        REGISTRY.snapshot(), serve_metrics={}, cluster=agg.snapshot()
    )
    assert "rank 0" in html_ and "rank 1" in html_
    assert 'data-phase="compiled_step"' in html_
    assert "<table" in html_  # the accessibility table view exists
    assert "legend" in html_


# ------------------------------------------------------------ trace counters
def test_trace_ring_drops_are_counted():
    trace.enable(capacity=4)
    try:
        for i in range(10):
            with trace.span(f"s{i}"):
                pass
        assert trace.dropped_spans() == 6
        assert REGISTRY.counter("trace_dropped_spans").snapshot() >= 6
    finally:
        trace.disable()


def test_sidecar_merge_failures_are_counted(tmp_path):
    path = str(tmp_path / "t.json")
    trace.enable(path)
    try:
        with trace.span("work"):
            pass
        with open(f"{path}.part-999.json", "w") as fh:
            fh.write("{torn")  # unreadable sidecar
        assert trace.write() == path
        assert trace.sidecar_errors() == 1
        assert REGISTRY.counter("trace_sidecar_errors").snapshot() >= 1
        json.load(open(path))  # the merge itself survived
    finally:
        trace.disable()


# ------------------------------------------------------------- bench diff
def _bench_record(tmp_path, name, value, step_ms, compiled_share):
    rec = {
        "metric": "images_per_sec", "value": value, "step_ms": step_ms,
        "telemetry": {
            "timeline": {
                "wall_s": 1.0,
                "phases": {
                    "compiled_step": {"total_s": compiled_share, "count": 5},
                    "input_wait": {"total_s": 1.0 - compiled_share,
                                   "count": 5},
                },
            },
        },
        "comm": {"wire_bytes_per_reduction": 1000.0},
    }
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


def test_bench_diff_regression_table(tmp_path):
    old = _bench_record(tmp_path, "old.json", 100.0, 10.0, 0.8)
    new = _bench_record(tmp_path, "new.json", 60.0, 17.0, 0.5)
    r = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "bench_diff.py"), old, new],
        capture_output=True, text=True,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSED" in r.stdout
    assert "phase:input_wait" in r.stdout  # share grew 20% -> 50%
    # informational mode prints the same table but never gates
    r2 = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "bench_diff.py"), old, new,
         "--informational"],
        capture_output=True, text=True,
    )
    assert r2.returncode == 0 and "REGRESSED" in r2.stdout


def test_bench_diff_accepts_driver_wrapper(tmp_path):
    inner = {"metric": "m", "value": 10.0, "step_ms": 5.0}
    p1 = tmp_path / "a.json"
    p1.write_text(json.dumps({"n": 1, "parsed": inner}))
    p2 = tmp_path / "b.json"
    p2.write_text(json.dumps(inner))
    r = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "bench_diff.py"),
         str(p1), str(p2)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


# ------------------------------------------------------------------- e2e
NET_TXT = """
name: "tiny"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 10
          weight_filler { type: "gaussian" std: 0.05 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""


def test_chaos_killed_child_leaves_referenced_flight_dump(
    tmp_path, monkeypatch, capfd
):
    """THE postmortem acceptance run: ``caffe train --supervise`` with
    a ``supervisor.child_crash`` injection — the killed child's failure
    record must reference a readable flight-recorder dump whose log
    ring holds the loop's last lines, and the supervisor's report must
    surface the dump path."""
    from sparknet_tpu import chaos
    from sparknet_tpu.supervise import records
    from sparknet_tpu.supervise.supervisor import REPORT_NAME
    from sparknet_tpu.tools import caffe as caffe_cli

    chaos.clear()
    monkeypatch.setenv("SPARKNET_SUPERVISE_RESTARTS", "3")
    monkeypatch.setenv("SPARKNET_SUPERVISE_BACKOFF", "0.05")
    monkeypatch.setenv("SPARKNET_SUPERVISE_BACKOFF_CAP", "0.1")
    d = str(tmp_path / "run")
    os.makedirs(d)
    with open(os.path.join(d, "net.prototxt"), "w") as fh:
        fh.write(NET_TXT)
    with open(os.path.join(d, "solver.prototxt"), "w") as fh:
        fh.write(
            'net: "net.prototxt"\nbase_lr: 0.05\nlr_policy: "fixed"\n'
            'momentum: 0.9\nmax_iter: 8\nsnapshot: 4\n'
            f'snapshot_prefix: "{d}/snap"\ndisplay: 0\n'
        )
    try:
        caffe_cli.main([
            "train", "--supervise",
            "--chaos=supervisor.child_crash@after=4",
            f"--solver={d}/solver.prototxt", "--synthetic",
            "--synthetic-n=64", "--batch-size=8", "--seed=3",
            "--data-workers=0", "--native-loader=off",
        ])
    finally:
        chaos.clear()
    (rec,) = records.read_failure_records(d)
    assert rec["kind"] == "chaos.child_crash"
    fpath = rec["flight_recorder"]
    assert fpath and os.path.exists(fpath), rec
    dump = json.load(open(fpath))
    assert dump["version"] == 1
    # the loop's log ring made it into the dump (snapshot lines at
    # iteration 4 precede the injected crash)
    assert any("Snapshotting" in ln for ln in dump["logs"]), dump["logs"]
    with open(os.path.join(d, REPORT_NAME)) as fh:
        report = json.load(fh)
    assert report["final_status"] == "done"
    assert fpath in report["generations"][0]["flight_recorders"]
    out = capfd.readouterr().out
    assert "flight recorder dump:" in out
