"""Traffic-shaped autoscaling + SLO admission control (ISSUE 16).

Fast pins for the autoscale subsystem: deterministic open-loop traffic
schedules (same (script, seed) ⇒ byte-identical arrivals), the pure
scale policy replayed against synthetic (rate, p99, burn) series, the
per-class admission verdicts (batch sheds first, with trace headers on
the refusal), the child pool's elastic width (retire/rearm/add), the
router's drain path (held sessions migrate COUNTED, never silently),
and the control loop driven against a fake router.  The expensive
subprocess e2e (real replicas, a real 10x spike, scale-up + recovery +
scale-down) lives in scripts/autoscale_smoke.py (check.sh).
"""

import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from sparknet_tpu.autoscale.admission import AdmissionPolicy, normalize_class
from sparknet_tpu.autoscale.controller import AutoscaleController
from sparknet_tpu.autoscale.policy import AutoscalePolicy
from sparknet_tpu.autoscale.traffic import (
    arrivals,
    parse_script,
    rate_at,
    schedule,
)
from sparknet_tpu.serve.router import Router
from sparknet_tpu.telemetry import anomaly


def _silent(*a, **k):
    pass


@pytest.fixture(autouse=True)
def _clean_advisories():
    anomaly.clear()
    anomaly.reset_detectors()
    yield
    anomaly.clear()
    anomaly.reset_detectors()


# ------------------------------------------------------- traffic shapes
def test_traffic_script_shapes_and_rates():
    segs = parse_script("flat:rate=4,dur=10")
    assert len(segs) == 1 and segs[0].dur == 10 and segs[0].peak == 4
    # spike: base outside [warm, warm+burst), base*mult inside
    s = "spike:base=2,mult=10,warm=5,burst=3,cool=2"
    assert rate_at(s, 0.0) == 2 and rate_at(s, 4.99) == 2
    assert rate_at(s, 5.0) == 20 and rate_at(s, 7.99) == 20
    assert rate_at(s, 8.0) == 2 and rate_at(s, 99.0) == 0.0
    # ramp endpoints, sine floor at zero
    r = "ramp:lo=2,hi=12,dur=10"
    assert rate_at(r, 0.0) == 2 and abs(rate_at(r, 5.0) - 7.0) < 1e-9
    assert rate_at("sine:mean=1,amp=9,period=4,dur=8", 3.0) == 0.0
    # composed scripts run back to back on one absolute clock
    comp = "flat:rate=1,dur=2;flat:rate=7,dur=2"
    assert rate_at(comp, 1.0) == 1 and rate_at(comp, 3.0) == 7


def test_traffic_script_rejects_garbage():
    with pytest.raises(ValueError, match="unknown shape"):
        parse_script("sawtooth:rate=1,dur=1")
    with pytest.raises(ValueError, match="unknown key"):
        parse_script("flat:rte=1,dur=1")
    with pytest.raises(ValueError, match="must be a number"):
        parse_script("flat:rate=fast,dur=1")
    with pytest.raises(ValueError, match="dur must be > 0"):
        parse_script("flat:rate=1,dur=0")
    with pytest.raises(ValueError, match="empty script"):
        parse_script(" ; ")


def test_arrivals_deterministic_per_seed():
    """The satellite bar: two runs of the same traffic script produce
    IDENTICAL arrival timestamps; a different seed produces different
    ones; the realized count tracks the scripted volume."""
    s = "spike:base=20,mult=5,warm=2,burst=2,cool=1"
    t1, d1 = arrivals(s, seed=42)
    t2, d2 = arrivals(s, seed=42)
    assert t1 == t2 and d1 == d2 == 5.0
    t3, _ = arrivals(s, seed=43)
    assert t1 != t3
    assert all(0.0 <= t < 5.0 for t in t1)
    assert t1 == sorted(t1)
    # expected volume: 20*2 (warm) + 100*2 (burst) + 20*1 (cool) = 260
    assert 170 < len(t1) < 350


def test_schedule_classes_and_sessions_deterministic():
    s = "flat:rate=150,dur=2"
    p1 = schedule(s, seed=7, batch_frac=0.5, sessions=16, session_zipf=1.4)
    p2 = schedule(s, seed=7, batch_frac=0.5, sessions=16, session_zipf=1.4)
    assert p1.times == p2.times
    assert p1.classes == p2.classes
    assert p1.session_ids == p2.session_ids
    # classes/sessions ride a SECOND stream: adding them never perturbs
    # the arrival clock itself
    assert p1.times == arrivals(s, seed=7)[0]
    n_batch = p1.classes.count("batch")
    assert 0 < n_batch < len(p1)
    assert 0.3 < n_batch / len(p1) < 0.7
    # Zipf skew: rank-0 session is the hottest
    counts = [p1.session_ids.count(k) for k in range(16)]
    assert counts[0] == max(counts) and counts[0] > counts[-1]
    assert len(p1) > 0 and p1.offered_rate() > 0


# ------------------------------------------------------- scale policy
def _policy(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("slo_ms", 100.0)
    kw.setdefault("up_looks", 2)
    kw.setdefault("down_looks", 2)
    kw.setdefault("up_cooldown_s", 5.0)
    kw.setdefault("down_cooldown_s", 5.0)
    kw.setdefault("down_frac", 0.5)
    return AutoscalePolicy(**kw)


def test_policy_up_needs_streak_cooldown_and_ceiling():
    clock = [0.0]
    pol = _policy(now=lambda: clock[0])
    look = dict(rate_rps=50.0, p99_ms=400.0, healthy=1, width=1)
    d = pol.decide(**look)
    assert d["action"] == "hold" and "streak" in d["reason"]
    d = pol.decide(**look)
    assert d["action"] == "up"
    # the streak resets on fire and the cooldown blocks a re-fire
    d = pol.decide(**{**look, "width": 2})
    d = pol.decide(**{**look, "width": 2})
    assert d["action"] == "hold" and d["reason"] == "up cooldown"
    clock[0] += 6.0
    assert pol.decide(**{**look, "width": 2})["action"] == "up"
    # at the ceiling a breach can only hold
    clock[0] += 6.0
    pol.decide(**{**look, "width": 3})
    d = pol.decide(**{**look, "width": 3})
    assert d["action"] == "hold" and "max_replicas" in d["reason"]


def test_policy_burn_advisory_alone_scales_up():
    clock = [0.0]
    pol = _policy(now=lambda: clock[0])
    look = dict(rate_rps=10.0, p99_ms=20.0, healthy=1, width=1, burn=True)
    pol.decide(**look)
    d = pol.decide(**look)
    assert d["action"] == "up" and "slo_burn" in d["reason"]


def test_policy_down_needs_learned_capacity_calm_streak_and_floor():
    clock = [0.0]
    pol = _policy(now=lambda: clock[0])
    calm = dict(rate_rps=2.0, p99_ms=20.0, healthy=2, width=2)
    # no learned capacity yet: never down, no matter how calm
    for _ in range(5):
        assert pol.decide(**calm)["action"] == "hold"
    assert pol.per_replica_rps == 1.0  # rate/healthy observed so far
    # a busy-but-healthy look teaches real per-replica capacity
    pol.decide(rate_rps=20.0, p99_ms=60.0, healthy=2, width=2)
    assert pol.per_replica_rps == 10.0
    # rate 2 fits 0.5 * 10 * 1 = 5: two calm looks then down
    pol.decide(**calm)
    d = pol.decide(**calm)
    assert d["action"] == "down", d
    # at the floor the same calm series only holds
    at_floor = dict(rate_rps=2.0, p99_ms=20.0, healthy=1, width=1)
    clock[0] += 10.0
    for _ in range(4):
        assert pol.decide(**at_floor)["action"] == "hold"


def test_policy_idle_tier_shrinks_to_the_floor():
    """No traffic at all (rate 0, no latency samples) is calm — a tier
    left wide after a spike must come back down even when the traffic
    stops entirely (but never without learned capacity)."""
    clock = [0.0]
    pol = _policy(now=lambda: clock[0])
    idle = dict(rate_rps=0.0, p99_ms=None, healthy=2, width=2)
    for _ in range(4):
        assert pol.decide(**idle)["action"] == "hold"  # capacity unknown
    # the calm streak built during those looks; with capacity known
    # the very next idle look shrinks
    pol.per_replica_rps = 10.0
    assert pol.decide(**idle)["action"] == "down"


def test_policy_never_shrinks_on_the_heels_of_a_grow():
    clock = [0.0]
    pol = _policy(now=lambda: clock[0], down_cooldown_s=8.0)
    pol.per_replica_rps = 10.0
    breach = dict(rate_rps=30.0, p99_ms=400.0, healthy=1, width=1)
    pol.decide(**breach)
    assert pol.decide(**breach)["action"] == "up"
    calm = dict(rate_rps=1.0, p99_ms=10.0, healthy=2, width=2)
    clock[0] += 2.0
    pol.decide(**calm)
    d = pol.decide(**calm)
    assert d["action"] == "hold" and d["reason"] == "recent scale-up"
    clock[0] += 10.0  # past the post-up window: the streak is already
    # built, so the next calm look shrinks
    assert pol.decide(**calm)["action"] == "down"


def test_policy_rejects_bad_knobs():
    with pytest.raises(ValueError, match="down_frac"):
        AutoscalePolicy(down_frac=0.0)
    with pytest.raises(ValueError, match="> 0"):
        AdmissionPolicy(max_outstanding_per_replica=0)
    with pytest.raises(ValueError, match="hard_factor"):
        AdmissionPolicy(hard_factor=0.5)


# --------------------------------------------------- admission verdicts
def test_admission_batch_sheds_before_interactive():
    pol = AdmissionPolicy(max_outstanding_per_replica=4, hard_factor=2)
    assert normalize_class(None) == "interactive"
    assert normalize_class(" Batch ") == "batch"
    assert normalize_class("weird") == "interactive"
    # burn live: batch 429s while interactive is still admitted
    v = pol.check("batch", burn=True, outstanding=0, healthy=2)
    assert v == ("shed", 429, "slo_burn")
    v = pol.check("interactive", burn=True, outstanding=0, healthy=2)
    assert v == ("admit", None, None)
    # queue pressure (cap = 4*2 = 8): batch first, interactive only at
    # hard_factor x the cap
    v = pol.check("batch", burn=False, outstanding=8, healthy=2)
    assert v == ("shed", 429, "queue_pressure")
    v = pol.check("interactive", burn=False, outstanding=8, healthy=2)
    assert v == ("admit", None, None)
    v = pol.check("interactive", burn=False, outstanding=16, healthy=2)
    assert v == ("shed", 503, "overload")
    # nothing healthy: admit — dispatch owns the all-down 503
    v = pol.check("batch", burn=True, outstanding=99, healthy=0)
    assert v == ("admit", None, None)


# ------------------------------------------------------- stub replicas
class _Stub:
    """Scriptable replica speaking /classify, /generate and /healthz —
    enough surface for router-level admission and drain tests."""

    def __init__(self):
        self.served = []
        self.gen_sessions = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._reply(200, {"status": "ok", "generation": 0,
                                  "warmup_s": 0.1, "pid": None})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
                if self.path == "/generate":
                    sid = req.get("session")
                    outer.gen_sessions.append(sid)
                    steps = int(req.get("steps", 0))
                    self._reply(200, {
                        "tokens": [1] * steps, "probs": [[1.0]] * steps,
                        "session": sid, "cache_state": "hit", "gen": 0,
                    })
                    return
                rid = int(req["rows"][0][0])
                outer.served.append(rid)
                self._reply(200, {
                    "indices": [[rid]], "probs": [[1.0]], "gen": 0,
                })

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def stub_tier():
    a, b = _Stub(), _Stub()
    router = Router(
        [(a.host, a.port), (b.host, b.port)],
        model_name="stub", health_interval_s=0.1,
        admission=AdmissionPolicy(
            max_outstanding_per_replica=4, hard_factor=2
        ),
    )
    assert router.wait_healthy(timeout_s=10)
    yield a, b, router
    router.stop()
    a.stop()
    b.stop()


def test_router_sheds_batch_on_burn_with_trace_headers(stub_tier):
    """The admission-path satellite: a burn-rate trip sheds batch (429
    + Retry-After) while interactive still serves; the refusal carries
    ``X-Sparknet-Trace``; shed/admit counts land in the snapshot."""
    from sparknet_tpu.telemetry import reqtrace

    a, b, router = stub_tier
    reqtrace.reset()
    reqtrace.enable()
    try:
        anomaly.fire("slo_burn", key="p99", emit=_silent)
        body = json.dumps({"rows": [[3.0]]}).encode()
        code, payload, headers = router.dispatch(body, cls="batch")
        hdrs = dict(headers)
        assert code == 429
        doc = json.loads(payload)
        assert doc["reason"] == "slo_burn" and doc["class"] == "batch"
        assert hdrs.get("Retry-After")
        assert hdrs.get("X-Sparknet-Trace"), "shed lost its trace"
        # the shed request's trace completed, with the router.shed span
        done = reqtrace.completed(5)
        assert any(
            s["name"] == "router.shed"
            for rec in done for s in rec["spans"]
        )
        # interactive traffic flows regardless of the advisory
        code, payload, _ = router.dispatch(body, cls="interactive")
        assert code == 200
        code, payload, _ = router.dispatch(body)  # no class header
        assert code == 200
        adm = router.metrics.snapshot()["admission"]
        assert adm["batch"]["shed"] == 1
        assert adm["interactive"]["admitted"] == 2
        assert not a.served or not b.served or True  # served somewhere
    finally:
        reqtrace.reset()
        reqtrace.disable()


def test_router_admission_clears_with_the_advisory(stub_tier):
    a, b, router = stub_tier
    anomaly.fire("slo_burn", key="p99", ttl_s=0.05, emit=_silent)
    body = json.dumps({"rows": [[1.0]]}).encode()
    code, _, _ = router.dispatch(body, cls="batch")
    assert code == 429
    time.sleep(0.1)  # the advisory expires; batch flows again
    code, _, _ = router.dispatch(body, cls="batch")
    assert code == 200
    snap = router.metrics.snapshot()
    assert snap["admission"]["batch"] == {"admitted": 1, "shed": 1}


def test_router_windowed_metrics_track_rate_and_p99(stub_tier):
    a, b, router = stub_tier
    body = json.dumps({"rows": [[1.0]]}).encode()
    for _ in range(20):
        code, _, _ = router.dispatch(body)
        assert code == 200
    w = router.metrics.windowed(10.0)
    assert w["samples"] == 20
    assert w["rate_rps"] == pytest.approx(2.0, abs=0.01)
    assert w["p99_ms"] is not None and w["p99_ms"] > 0
    assert router.metrics.snapshot()["window"]["window_s"] == 5.0


def test_router_drain_migrates_sessions_counted(stub_tier):
    """The scale-down bar: draining the replica that holds sessions
    routes them to a peer through the COUNTED migration path — the
    response is stamped migrated, ``session_migrations`` increments,
    and the drained replica empties without ever going unhealthy."""
    a, b, router = stub_tier
    body = json.dumps(
        {"tokens": [1, 2], "steps": 1, "session": "hot"}
    ).encode()
    code, payload, _ = router.dispatch(
        body, path="/generate", session="hot"
    )
    assert code == 200
    holder = router._session_holder("hot")
    assert holder is not None
    # affinity holds while the holder is up
    for _ in range(3):
        code, payload, _ = router.dispatch(
            body, path="/generate", session="hot"
        )
        assert code == 200
        assert router._session_holder("hot") == holder
    before = router.metrics.snapshot()["session_migrations"]
    assert router.begin_drain(holder)
    assert not router.begin_drain(holder)  # idempotence guard
    code, payload, _ = router.dispatch(
        body, path="/generate", session="hot"
    )
    assert code == 200
    doc = json.loads(payload)
    assert doc.get("migrated") is True
    new_holder = router._session_holder("hot")
    assert new_holder is not None and new_holder != holder
    assert (
        router.metrics.snapshot()["session_migrations"] == before + 1
    )
    # the drained replica has no in-flight work: retire it
    assert router.replica_drained(holder)
    assert router.retire_replica(holder)
    assert router.active_width() == 1
    hz = router.healthz()
    assert hz["replicas_active"] == 1 and hz["replicas_draining"] == 0
    assert hz["replicas_total"] == 2  # the slot is parked, not deleted
    # classify traffic keeps flowing on the survivor
    code, _, _ = router.dispatch(json.dumps({"rows": [[9.0]]}).encode())
    assert code == 200


# --------------------------------------------------- elastic child pool
def _fast_cfg(**kw):
    from sparknet_tpu.supervise.policy import Config

    kw.setdefault("backoff_s", 0.01)
    kw.setdefault("max_backoff_s", 0.02)
    kw.setdefault("flap_window_s", 9999.0)
    kw.setdefault("healthy_s", 9999.0)
    return Config(**kw)


def _wait(pred, timeout=30.0, tick=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if tick is not None:
            tick()
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_pool_retire_rearm_and_add():
    from sparknet_tpu.supervise.pool import RUNNING, STOPPED, ChildPool

    pool = ChildPool(
        lambda i, s: [sys.executable, "-c", "import time; time.sleep(60)"],
        2, config=_fast_cfg(max_restarts=5),
    ).start()
    try:
        # retire: deliberate stop — state flips, the tick reaps the
        # exit quietly (no crash event, no respawn)
        assert pool.retire(1, grace_s=5.0)
        assert pool.children[1].state == STOPPED
        assert not pool.retire(1)  # already down
        assert _wait(
            lambda: pool.children[1].proc.poll() is not None,
            tick=lambda: pool.tick(),
        )
        events = pool.tick()
        assert pool.children[1].state == STOPPED
        assert all(e["event"] != "exit" or e["child"] != 1
                   for e in events)
        spawns_before = pool.children[1].spawn_count
        # rearm: the retired slot comes back with a FRESH budget
        assert pool.rearm(1)
        assert _wait(
            lambda: pool.children[1].state == RUNNING,
            tick=lambda: pool.tick(),
        )
        assert pool.children[1].spawn_count == spawns_before + 1
        assert not pool.rearm(1)  # running: nothing to re-arm
        # add: a third slot, spawned by the next tick
        child = pool.add_child()
        assert child.index == 2 and len(pool.children) == 3
        assert _wait(
            lambda: pool.children[2].state == RUNNING,
            tick=lambda: pool.tick(),
        )
        assert len(pool.alive()) == 3
    finally:
        pool.stop()


def test_pool_retire_escalates_to_kill_past_grace():
    """A child that ignores SIGTERM is SIGKILLed by the tick once the
    retire grace expires."""
    from sparknet_tpu.supervise.pool import ChildPool

    pool = ChildPool(
        lambda i, s: [
            sys.executable, "-c",
            "import signal, time; "
            "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
            "time.sleep(60)",
        ],
        1, config=_fast_cfg(),
    ).start()
    try:
        _wait(lambda: pool.children[0].proc is not None
              and pool.children[0].proc.poll() is None)
        time.sleep(0.2)  # let the child install its handler
        assert pool.retire(0, grace_s=0.3)
        assert _wait(
            lambda: pool.children[0].proc.poll() is not None,
            timeout=15.0, tick=lambda: pool.tick(),
        ), "retire never escalated past SIGTERM"
    finally:
        pool.stop()


# ------------------------------------------------------ the control loop
class _FakeRouter:
    """The router's scale surface as a scriptable fake: windowed
    metrics are set by the test, scale actions mutate plain counters."""

    class _M:
        def __init__(self):
            self.obs = {"window_s": 5.0, "rate_rps": 0.0,
                        "p99_ms": None, "samples": 0}

        def windowed(self, window_s):
            return dict(self.obs)

    def __init__(self, width=1):
        self.metrics = self._M()
        self.width = width
        self.draining = set()
        self.retired = []
        self.drained = set()   # indices whose outstanding hit zero

    def active_width(self):
        return self.width

    def healthy_count(self):
        return self.width - len(self.draining)

    def scale_up(self):
        self.width += 1
        return self.width - 1

    def pick_drain_victim(self):
        for i in reversed(range(self.width)):
            if i not in self.draining:
                return i
        return None

    def begin_drain(self, idx):
        self.draining.add(idx)
        return True

    def replica_drained(self, idx):
        return idx in self.drained

    def retire_replica(self, idx):
        self.draining.discard(idx)
        self.retired.append(idx)
        self.width -= 1
        return True


class _NoBurn:
    def observe(self, p99_ms):
        return None


def test_controller_scales_up_then_drains_down():
    clock = [100.0]
    router = _FakeRouter(width=1)
    pol = _policy(now=lambda: clock[0], up_cooldown_s=0.0,
                  down_cooldown_s=0.0)
    ctl = AutoscaleController(
        router, pol, interval_s=0.1, window_s=5.0, drain_timeout_s=30.0,
        burn_detector=_NoBurn(), emit=_silent, now=lambda: clock[0],
    )
    # breach series: p99 over SLO for two looks -> one scale-up
    router.metrics.obs.update(rate_rps=40.0, p99_ms=400.0, samples=50)
    ctl.look()
    d = ctl.look()
    assert d["action"] == "up" and router.width == 2
    assert ctl.scale_ups == 1
    # healthy + busy: capacity learned; then calm -> drain begins
    clock[0] += 1.0
    router.metrics.obs.update(rate_rps=40.0, p99_ms=50.0)
    ctl.look()
    assert pol.per_replica_rps == 20.0
    router.metrics.obs.update(rate_rps=3.0, p99_ms=20.0)
    ctl.look()
    d = ctl.look()
    assert d["action"] == "down"
    assert router.draining == {1} and router.width == 2
    assert ctl.snapshot()["draining"] == [1]
    # while draining, no second drain starts; once the replica is
    # empty the next look retires it
    d = ctl.look()
    assert router.draining == {1}
    router.drained.add(1)
    ctl.look()
    assert router.retired == [1] and router.width == 1
    assert ctl.scale_downs == 1 and ctl.drains_forced == 0


def test_controller_forces_a_stuck_drain_past_the_timeout():
    clock = [100.0]
    router = _FakeRouter(width=2)
    pol = _policy(now=lambda: clock[0], up_cooldown_s=0.0,
                  down_cooldown_s=0.0)
    pol.per_replica_rps = 20.0
    ctl = AutoscaleController(
        router, pol, interval_s=0.1, window_s=5.0, drain_timeout_s=2.0,
        burn_detector=_NoBurn(), emit=_silent, now=lambda: clock[0],
    )
    router.metrics.obs.update(rate_rps=2.0, p99_ms=10.0, samples=50)
    ctl.look()
    d = ctl.look()
    assert d["action"] == "down" and router.draining == {1}
    # the replica never empties: past the deadline it is retired anyway
    clock[0] += 1.0
    ctl.look()
    assert router.retired == []
    clock[0] += 2.0
    ctl.look()
    assert router.retired == [1]
    assert ctl.drains_forced == 1


def test_controller_burn_detector_drives_the_advisory():
    """End-to-end inside one process: a windowed p99 breach series
    fires ``slo_burn`` through the controller's own detector, and the
    advisory expires shortly after the series recovers (short ttl —
    the scale-down gate must be able to clear)."""
    clock = [500.0]
    router = _FakeRouter(width=4)
    pol = _policy(max_replicas=4, now=lambda: clock[0])
    ctl = AutoscaleController(
        router, pol, interval_s=1.0, window_s=5.0,
        emit=_silent, now=lambda: clock[0],
    )
    assert ctl._burn.ttl_s == 3.0  # 3x the refire cadence
    router.metrics.obs.update(rate_rps=10.0, p99_ms=999.0, samples=9)
    for _ in range(6):  # past min_samples on both burn windows
        ctl.look()
        clock[0] += 1.0
    assert anomaly.active("slo_burn"), "burn series never fired"
    # NOTE: the detector clock is real time.monotonic (the advisory
    # board's expiry is too) — recovery here is the *real* ttl elapsing
    router.metrics.obs.update(p99_ms=10.0)
    deadline = time.monotonic() + 10.0
    while anomaly.active("slo_burn") and time.monotonic() < deadline:
        ctl.look()
        time.sleep(0.2)
    assert not anomaly.active("slo_burn"), "advisory never cleared"


# ------------------------------------------------------ open-loop loadgen
def test_open_loadgen_fires_on_the_clock_and_counts_classes():
    from sparknet_tpu.serve.loadgen import run_open_loadgen

    stub = _Stub()
    try:
        rec = run_open_loadgen(
            stub.host, stub.port, (1,),
            script="flat:rate=40,dur=1.5", seed=11,
            batch_frac=0.4, slo_ms=500.0, timeout_s=10.0,
        )
    finally:
        stub.stop()
    plan = schedule("flat:rate=40,dur=1.5", seed=11, batch_frac=0.4)
    assert rec["offered"] == len(plan)
    assert rec["failed_requests"] == 0
    assert rec["client_overflow"] == 0
    assert rec["metric"] == "serve_open_loop_slo_ok_frac"
    cls = rec["classes"]
    assert set(cls) == {"batch", "interactive"}
    for c in cls.values():
        assert c["offered"] == c["ok"] + c["shed"] + c["failed"]
        assert c["shed"] == 0
    assert (cls["batch"]["offered"]
            == sum(1 for c in plan.classes if c == "batch"))
    # a healthy stub answers instantly: the SLO fraction is perfect
    assert rec["value"] == 1.0
    assert rec["classes"]["interactive"]["p99_ms"] is not None
    assert rec["lateness_p99_ms"] is not None
    assert rec["duration_s"] == 1.5


def test_open_loadgen_session_mode_appends_history_on_success():
    from sparknet_tpu.serve.loadgen import run_open_loadgen

    stub = _Stub()
    try:
        rec = run_open_loadgen(
            stub.host, stub.port, (1,),
            script="flat:rate=30,dur=1", seed=3,
            sessions=4, session_zipf=1.2, session_steps=1,
            slo_ms=500.0, timeout_s=10.0,
        )
    finally:
        stub.stop()
    assert rec["session_failed_requests"] == 0
    assert rec["failed_requests"] == 0
    assert rec["sessions"]["count"] == 4
    assert 1 <= rec["sessions"]["distinct"] <= 4
    assert stub.gen_sessions, "no /generate traffic reached the stub"
    assert rec["classes"]["interactive"]["ok"] == rec["offered"]


def test_open_loadgen_batch_class_is_sessionless_generate():
    """Session-mode tiers (char-rnn) serve only ``/generate`` — the
    batch class must ride it sessionless, never ``/classify``."""
    from sparknet_tpu.serve.loadgen import run_open_loadgen

    stub = _Stub()
    try:
        rec = run_open_loadgen(
            stub.host, stub.port, (1,),
            script="flat:rate=30,dur=1", seed=5,
            batch_frac=0.5, sessions=4, session_steps=1,
            slo_ms=500.0, timeout_s=10.0,
        )
    finally:
        stub.stop()
    assert rec["failed_requests"] == 0
    assert rec["session_failed_requests"] == 0
    b = rec["classes"]["batch"]
    assert b["offered"] > 0 and b["ok"] == b["offered"]
    assert not stub.served, "batch leaked onto /classify"
    # batch = sessionless generate; interactive steps carry session ids
    assert any(s is None for s in stub.gen_sessions)
    assert any(s is not None for s in stub.gen_sessions)
