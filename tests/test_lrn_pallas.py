"""Fused Pallas LRN vs the jnp reduce_window oracle (fwd + grads).

The jnp path in nets/layers.py is torch-verified (test_layers); the
kernel must match it bitwise-closely in f32, including through
jax.grad, before it may replace it on TPU."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sparknet_tpu.nets import layers as L
from sparknet_tpu.ops.lrn import lrn_nhwc
from sparknet_tpu.proto.caffe_pb import LayerParameter
from sparknet_tpu.proto.textformat import parse


def _oracle(x, size, alpha, beta, k):
    lp = LayerParameter.from_message(parse(
        f'name: "n" type: "LRN" lrn_param {{ local_size: {size} '
        f"alpha: {alpha} beta: {beta} k: {k} }}"
    ))
    (y,), _ = L.LRN.apply(lp, {}, None, [x], None)
    return y


CASES = [
    # (shape, size, alpha, beta, k)
    ((2, 5, 5, 96), 5, 1e-4, 0.75, 1.0),   # AlexNet norm1 geometry
    ((2, 4, 4, 256), 5, 1e-4, 0.75, 1.0),  # AlexNet norm2 channels
    ((1, 3, 3, 64), 5, 1e-4, 0.75, 2.0),   # GoogLeNet-style k=2
    ((2, 3, 3, 32), 3, 5e-5, 0.5, 1.0),    # dyadic beta=0.5
    ((1, 2, 2, 16), 4, 1e-4, 0.9, 1.0),    # even window + general beta
]


@pytest.mark.parametrize("shape,size,alpha,beta,k", CASES)
def test_forward_matches_oracle(shape, size, alpha, beta, k):
    x = jnp.asarray(
        np.random.default_rng(0).normal(0, 2, shape), jnp.float32
    )
    y_ref = _oracle(x, size, alpha, beta, k)
    y = lrn_nhwc(
        x, size=size, alpha=alpha, beta=beta, k=k, interpret=True
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=2e-6)


@pytest.mark.parametrize("shape,size,alpha,beta,k", CASES)
def test_grad_matches_oracle(shape, size, alpha, beta, k):
    x = jnp.asarray(
        np.random.default_rng(1).normal(0, 2, shape), jnp.float32
    )
    g = jnp.asarray(np.random.default_rng(2).normal(0, 1, shape), jnp.float32)

    def loss_ref(x):
        return jnp.sum(_oracle(x, size, alpha, beta, k) * g)

    def loss_ker(x):
        return jnp.sum(
            lrn_nhwc(x, size=size, alpha=alpha, beta=beta, k=k,
                     interpret=True) * g
        )

    dx_ref = jax.grad(loss_ref)(x)
    dx = jax.grad(loss_ker)(x)
    np.testing.assert_allclose(
        np.asarray(dx), np.asarray(dx_ref), atol=3e-6
    )


def test_bf16_io_keeps_f32_internals():
    x = jnp.asarray(
        np.random.default_rng(3).normal(0, 2, (2, 4, 4, 96)), jnp.bfloat16
    )
    y = lrn_nhwc(x, size=5, alpha=1e-4, beta=0.75, k=1.0, interpret=True)
    assert y.dtype == jnp.bfloat16
    y_ref = _oracle(x, 5, 1e-4, 0.75, 1.0)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(y_ref, np.float32), atol=2e-2
    )


def test_row_padding_roundtrip():
    """N*H*W not a block multiple: pad rows are sliced back off."""
    x = jnp.asarray(
        np.random.default_rng(4).normal(0, 1, (3, 7, 5, 32)), jnp.float32
    )
    y = lrn_nhwc(x, size=5, alpha=1e-4, beta=0.75, k=1.0, interpret=True)
    y_ref = _oracle(x, 5, 1e-4, 0.75, 1.0)
    assert y.shape == x.shape
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=2e-6)
