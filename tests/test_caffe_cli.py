"""``caffe`` CLI twin: train/test/time over a toolchain-built LMDB."""

import os

import numpy as np
import pytest

from sparknet_tpu.data.caffe_layers import encode_datum
from sparknet_tpu.data.lmdb_io import write_lmdb
from sparknet_tpu.tools import caffe as caffe_cli


@pytest.fixture()
def workspace(tmp_path):
    rng = np.random.default_rng(0)
    for db, n in (("train_lmdb", 64), ("test_lmdb", 32)):
        imgs = rng.integers(0, 256, (n, 16, 16, 3), dtype=np.uint8)
        labels = rng.integers(0, 4, n)
        os.makedirs(tmp_path / db)
        write_lmdb(
            str(tmp_path / db),
            [
                (f"{i:08d}".encode(), encode_datum(imgs[i], int(labels[i])))
                for i in range(n)
            ],
        )
    net = tmp_path / "net.prototxt"
    net.write_text(f"""
name: "cli"
layer {{ name: "d" type: "Data" top: "data" top: "label"
        include {{ phase: TRAIN }}
        data_param {{ source: "{tmp_path}/train_lmdb" batch_size: 8 }} }}
layer {{ name: "d" type: "Data" top: "data" top: "label"
        include {{ phase: TEST }}
        data_param {{ source: "{tmp_path}/test_lmdb" batch_size: 8 }} }}
layer {{ name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param {{ num_output: 4
          weight_filler {{ type: "gaussian" std: 0.01 }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip1" bottom: "label" top: "loss" }}
layer {{ name: "accuracy" type: "Accuracy" bottom: "ip1" bottom: "label" top: "accuracy"
        include {{ phase: TEST }} }}
""")
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{net}"
base_lr: 0.001
momentum: 0.9
lr_policy: "fixed"
display: 2
max_iter: 4
test_interval: 4
test_iter: 2
""")
    return tmp_path


def test_caffe_train_and_time(workspace):
    result = caffe_cli.main(
        ["train", f"--solver={workspace}/solver.prototxt"]
    )
    assert "accuracy" in result
    out = caffe_cli.main(
        ["time", f"--solver={workspace}/solver.prototxt", "--iters", "3"]
    )
    assert out["train_step_ms"] > 0


def test_caffe_test_subcommand(workspace):
    metrics = caffe_cli.main(
        ["test", f"--model={workspace}/net.prototxt", "--iterations=3"]
    )
    assert "accuracy" in metrics and 0.0 <= metrics["accuracy"] <= 1.0


def test_caffe_usage_error():
    with pytest.raises(SystemExit):
        caffe_cli.main(["bogus"])


@pytest.fixture()
def gray_workspace(tmp_path):
    """MNIST-LeNet-shaped setup: grayscale 1-channel LMDB."""
    rng = np.random.default_rng(3)
    for db, n in (("train_lmdb", 32), ("test_lmdb", 16)):
        imgs = rng.integers(0, 256, (n, 12, 12, 1), dtype=np.uint8)
        labels = rng.integers(0, 3, n)
        os.makedirs(tmp_path / db)
        write_lmdb(
            str(tmp_path / db),
            [
                (f"{i:08d}".encode(), encode_datum(imgs[i], int(labels[i])))
                for i in range(n)
            ],
        )
    net = tmp_path / "net.prototxt"
    net.write_text(f"""
name: "gray"
layer {{ name: "d" type: "Data" top: "data" top: "label"
        include {{ phase: TRAIN }}
        transform_param {{ crop_size: 8 }}
        data_param {{ source: "{tmp_path}/train_lmdb" batch_size: 8 }} }}
layer {{ name: "d" type: "Data" top: "data" top: "label"
        include {{ phase: TEST }}
        transform_param {{ crop_size: 8 }}
        data_param {{ source: "{tmp_path}/test_lmdb" batch_size: 8 }} }}
layer {{ name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param {{ num_output: 4 kernel_size: 3
          weight_filler {{ type: "gaussian" std: 0.1 }} }} }}
layer {{ name: "ip1" type: "InnerProduct" bottom: "conv1" top: "ip1"
        inner_product_param {{ num_output: 3
          weight_filler {{ type: "gaussian" std: 0.01 }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip1" bottom: "label" top: "loss" }}
layer {{ name: "accuracy" type: "Accuracy" bottom: "ip1" bottom: "label" top: "accuracy"
        include {{ phase: TEST }} }}
""")
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{net}"
base_lr: 0.001
momentum: 0.9
lr_policy: "fixed"
max_iter: 2
test_interval: 2
test_iter: 1
""")
    return tmp_path


def test_caffe_grayscale_lmdb(gray_workspace):
    """Non-RGB sources must flow through with their true channel count
    (regression: input shapes once hardcoded 3 channels, breaking any
    grayscale net even with a crop)."""
    result = caffe_cli.main(
        ["train", f"--solver={gray_workspace}/solver.prototxt"]
    )
    assert "accuracy" in result
    metrics = caffe_cli.main(
        ["test", f"--model={gray_workspace}/net.prototxt", "--iterations=2"]
    )
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_caffe_cli_accepts_gpu_and_iterations_flags(workspace):
    """Published caffe command lines (--gpu=0, time --iterations=N)
    must run unchanged."""
    out = caffe_cli.main(
        ["time", f"--solver={workspace}/solver.prototxt",
         "--iterations=2", "--gpu=0"]
    )
    assert out["train_step_ms"] > 0
    result = caffe_cli.main(
        ["train", f"--solver={workspace}/solver.prototxt", "--gpu", "all"]
    )
    assert "accuracy" in result
    metrics = caffe_cli.main(
        ["test", f"--model={workspace}/net.prototxt", "--iterations=1",
         "--gpu=0"]
    )
    assert "accuracy" in metrics
