"""Native data runtime (C++ via ctypes) vs the pure-Python path."""

import numpy as np
import pytest

from sparknet_tpu import native
from sparknet_tpu.data.cifar import _decode_binary
from sparknet_tpu.data.preprocess import Transformer

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def test_cifar_decode_matches_python():
    rng = np.random.default_rng(0)
    raw = bytes(rng.integers(0, 256, 3073 * 7).astype(np.uint8))
    ni, nl = native.cifar_decode(raw)
    pi, pl = _decode_binary(raw)
    np.testing.assert_array_equal(ni, pi)
    np.testing.assert_array_equal(nl, pl)


def test_transform_center_crop_matches_python():
    """Deterministic settings (TEST phase): native == Transformer."""
    rng = np.random.default_rng(1)
    images = rng.integers(0, 256, (6, 32, 32, 3)).astype(np.uint8)
    mean = rng.normal(size=(32, 32, 3)).astype(np.float32)
    t = Transformer(scale=0.5, mean_image=mean, crop_size=28, train=False)
    ref = t(images, np.random.default_rng(0))
    out = native.transform_batch(
        images, crop=28, train=False, mean_image=mean, scale=0.5
    )
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-5)


def test_transform_mean_channel_and_threads():
    rng = np.random.default_rng(2)
    images = rng.integers(0, 256, (16, 8, 8, 3)).astype(np.uint8)
    mc = np.array([104.0, 117.0, 123.0], np.float32)
    a = native.transform_batch(images, mean_channel=mc, num_threads=1)
    b = native.transform_batch(images, mean_channel=mc, num_threads=8)
    np.testing.assert_array_equal(a, b)  # thread count can't change output
    np.testing.assert_allclose(
        a, images.astype(np.float32) - mc, rtol=1e-6
    )


def test_transform_train_crop_in_bounds_and_seed_deterministic():
    rng = np.random.default_rng(3)
    images = rng.integers(0, 256, (32, 16, 16, 3)).astype(np.uint8)
    a = native.transform_batch(images, crop=8, train=True, mirror=True, seed=7)
    b = native.transform_batch(images, crop=8, train=True, mirror=True, seed=7)
    c = native.transform_batch(images, crop=8, train=True, mirror=True, seed=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)  # different seed, different crops
    assert a.shape == (32, 8, 8, 3)


def test_loader_epoch_coverage_and_determinism():
    """One epoch visits each sample at most once (Feistel shuffle is a
    permutation); two loaders with the same seed produce identical
    streams regardless of thread count."""
    n = 64
    rng = np.random.default_rng(4)
    images = rng.integers(0, 256, (n, 8, 8, 3)).astype(np.uint8)
    labels = np.arange(n, dtype=np.int32)  # label == sample id

    def stream(threads):
        ld = native.NativeLoader(
            images, labels, batch_size=8, train=False, seed=5,
            num_threads=threads,
        )
        try:
            return [next(ld) for _ in range(16)]  # 2 epochs
        finally:
            ld.close()

    s1, s2 = stream(1), (stream(4))
    for b1, b2 in zip(s1, s2):
        np.testing.assert_array_equal(b1["label"], b2["label"])
        np.testing.assert_array_equal(b1["data"], b2["data"])
    # epoch 0 = batches 0..7: every sample exactly once
    seen = np.concatenate([b["label"] for b in s1[:8]])
    assert sorted(seen.tolist()) == list(range(n))
    # epoch 1 differs in order from epoch 0
    seen2 = np.concatenate([b["label"] for b in s1[8:]])
    assert sorted(seen2.tolist()) == list(range(n))
    assert seen.tolist() != seen2.tolist()


def test_loader_transform_matches_native_transform():
    """Loader batches equal sn_transform_batch on the same permuted rows
    (data path consistency), including mean subtraction."""
    n = 32
    rng = np.random.default_rng(6)
    images = rng.integers(0, 256, (n, 12, 12, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)
    mc = np.array([10.0, 20.0, 30.0], np.float32)
    ld = native.NativeLoader(
        images, labels, batch_size=4, crop=8, train=False,
        mean_channel=mc, scale=0.25, seed=9, num_threads=2,
    )
    try:
        batch = next(ld)
    finally:
        ld.close()
    # reconstruct: which source rows were batch 0? labels identify them
    # only statistically; instead just verify value semantics on one row
    # by matching against all candidate source rows
    cand = native.transform_batch(
        images, crop=8, train=False, mean_channel=mc, scale=0.25
    )
    for row in batch["data"]:
        assert any(
            np.allclose(row, cand[j], atol=1e-5) for j in range(n)
        )


def test_loader_rejects_batch_larger_than_dataset():
    images = np.zeros((4, 8, 8, 3), np.uint8)
    labels = np.zeros((4,), np.int32)
    with pytest.raises(ValueError):
        native.NativeLoader(images, labels, batch_size=8)


def test_transform_both_means_and_scalar_mean_value():
    """Both mean_image and mean_channel subtract (preprocess.py parity);
    a single mean_value broadcasts to all channels."""
    rng = np.random.default_rng(7)
    images = rng.integers(0, 256, (3, 8, 8, 3)).astype(np.uint8)
    mean_img = rng.normal(size=(8, 8, 3)).astype(np.float32)
    mc1 = np.array([50.0], np.float32)  # scalar mean_value
    t = Transformer(scale=2.0, mean_image=mean_img, mean_values=mc1,
                    train=False)
    ref = t(images, np.random.default_rng(0))
    out = native.transform_batch(
        images, train=False, mean_image=mean_img, mean_channel=mc1, scale=2.0
    )
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)


def test_crop_larger_than_image_raises():
    images = np.zeros((2, 8, 8, 3), np.uint8)
    with pytest.raises(ValueError):
        native.transform_batch(images, crop=16)
    with pytest.raises(ValueError):
        native.NativeLoader(images, np.zeros(2, np.int32), 1, crop=16)
