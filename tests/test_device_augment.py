"""Device-side augmentation: the TPU-first input-pipeline redesign.

The host path (``Transformer.__call__``) and the device path
(``Transformer.plan`` + ``Transformer.device_fn`` inside the jitted
step) must be bit-identical given the same per-batch RNG — the lineage
property that makes ``--device-augment`` a pure performance choice, not
a different training run (reference preprocesses on executors,
SURVEY.md §2; mount empty)."""

import numpy as np
import jax
import jax.numpy as jnp

from sparknet_tpu.data.preprocess import Transformer
from sparknet_tpu.data.rdd import ShardedDataset
from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.solver.trainer import Solver


def _images(n=8, h=40, w=40, c=3, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (n, h, w, c)
    ).astype(np.uint8)


def _device_out(tf: Transformer, images: np.ndarray, seed: int) -> np.ndarray:
    plan = tf.plan(len(images), images.shape[1:3], np.random.default_rng(seed))
    batch = {"data": jnp.asarray(images), "label": jnp.zeros(len(images))}
    batch.update({k: jnp.asarray(v) for k, v in plan.items()})
    out = jax.jit(tf.device_fn())(batch)
    assert not any(k.startswith("aug_") for k in out), "plan keys must pop"
    return np.asarray(out["data"])


def test_train_crop_mirror_mean_scale_matches_host():
    images = _images()
    tf = Transformer(
        scale=0.5, mean_values=[104.0, 117.0, 123.0], crop_size=32,
        mirror=True, train=True,
    )
    host = tf(images, np.random.default_rng(7))
    dev = _device_out(tf, images, 7)
    assert dev.dtype == np.float32
    np.testing.assert_array_equal(host, dev)


def test_mean_image_subtracted_in_the_crop_window():
    images = _images(seed=1)
    mean = np.random.default_rng(2).normal(120, 10, (40, 40, 3)).astype(
        np.float32
    )
    tf = Transformer(mean_image=mean, crop_size=24, mirror=True, train=True)
    host = tf(images, np.random.default_rng(11))
    dev = _device_out(tf, images, 11)
    np.testing.assert_array_equal(host, dev)


def test_eval_center_crop_matches_host():
    images = _images(seed=3)
    tf = Transformer(
        mean_values=[100.0, 110.0, 120.0], crop_size=32, mirror=True,
        train=False,
    )
    host = tf(images, np.random.default_rng(0))
    dev = _device_out(tf, images, 0)
    np.testing.assert_array_equal(host, dev)


def test_no_crop_no_mirror_is_just_cast_mean_scale():
    images = _images(seed=4)
    tf = Transformer(scale=2.0, mean_values=[10.0, 20.0, 30.0], train=True)
    host = tf(images, np.random.default_rng(0))
    dev = _device_out(tf, images, 0)
    np.testing.assert_array_equal(host, dev)


NET = """
name: "tiny"
layer { name: "d" type: "Input" top: "data" top: "label" }
layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 } }
layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" }
layer { name: "ip" type: "InnerProduct" bottom: "conv" top: "ip"
  inner_product_param { num_output: 5 } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label"
  top: "loss" }
"""

SOLVER = """
base_lr: 0.05 momentum: 0.9 lr_policy: 'fixed' max_iter: 10 display: 0
"""


def _train(feed, batch_transform, iters=3, bs=8, solver_text=SOLVER):
    sp = caffe_pb.load_solver(solver_text, is_path=False)
    net = caffe_pb.load_net(NET, is_path=False)
    solver = Solver(
        sp, {"data": (bs, 8, 8, 3), "label": (bs,)}, net_param=net, seed=3,
        batch_transform=batch_transform,
    )
    solver.step(feed, iters)
    return solver.params


def test_device_augment_under_iter_size_micro_batching():
    """Caffe ``iter_size`` gradient accumulation stacks micro-batches
    on a leading axis; the device transform must vmap over it (the
    make_train_step branch) and still match the host path exactly."""
    from sparknet_tpu.apps.imagenet_app import make_device_feed, make_feed

    rng = np.random.default_rng(12)
    data = rng.integers(0, 256, (64, 12, 12, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, 64).astype(np.int32)
    ds = ShardedDataset.from_arrays(
        {"data": data, "label": labels}, num_partitions=4
    )
    tf = Transformer(
        mean_values=[100.0, 110.0, 120.0], crop_size=8, mirror=True,
        train=True,
    )
    accum = SOLVER + " iter_size: 2"
    # 2 iters x iter_size 2 = 4 micro-batches through the vmap branch
    p_host = _train(
        make_feed(ds, tf, 4, seed=6), None, iters=2, bs=4,
        solver_text=accum,
    )
    p_dev = _train(
        make_device_feed(ds, tf, 4, seed=6), tf.device_fn(), iters=2,
        bs=4, solver_text=accum,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        p_host, p_dev,
    )


def test_solver_device_augment_equals_host_path():
    """Training through --device-augment is the SAME run as through the
    host feed: identical params after identical batches+plan RNG.
    Feeds come from the real app helpers so the test exercises the
    shipped pipeline, not a re-implementation."""
    from sparknet_tpu.apps.imagenet_app import make_device_feed, make_feed

    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, (64, 12, 12, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, 64).astype(np.int32)
    ds = ShardedDataset.from_arrays(
        {"data": data, "label": labels}, num_partitions=4
    )
    tf = Transformer(
        mean_values=[100.0, 110.0, 120.0], crop_size=8, mirror=True,
        train=True,
    )

    p_host = _train(make_feed(ds, tf, 8, seed=5), None)
    p_dev = _train(make_device_feed(ds, tf, 8, seed=5), tf.device_fn())
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        p_host, p_dev,
    )
