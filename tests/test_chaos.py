"""chaos/ subsystem + the self-healing contracts it exercises.

The acceptance bar (ISSUE 3): fault plans are deterministic and
zero-cost when disabled; a killed pipeline worker is respawned and the
run's batch stream (and trained weights) stay bit-identical with the
recovery counter at exactly one; a torn snapshot falls back to the
previous one; a retry storm against a flapping server ends with zero
hung or silently-dropped requests; expired requests are shed before
compute and surface as a degraded /healthz.  All CPU-only and fast —
tier-1, no ``slow`` marker.
"""

import glob
import multiprocessing
import os
import threading
import time

import numpy as np
import pytest

from sparknet_tpu import chaos
from sparknet_tpu.chaos.plan import FAULT_POINTS, FaultPlan
from sparknet_tpu.data.pipeline import SHM_PREFIX, ParallelBatchPipeline
from sparknet_tpu.data.rdd import ShardedDataset
from sparknet_tpu.serve.batcher import DeadlineExceeded, MicroBatcher
from sparknet_tpu.serve.metrics import Counter, ServeMetrics
from sparknet_tpu.serve.server import Client, InferenceServer
from sparknet_tpu.solver import snapshot

_HAVE_FORK = "fork" in multiprocessing.get_all_start_methods()
fork_only = pytest.mark.skipif(
    not _HAVE_FORK, reason="pipeline workers require the fork start method"
)


@pytest.fixture(autouse=True)
def _chaos_isolation():
    """No plan (or fire/recovery counts) may leak between tests."""
    chaos.clear()
    yield
    chaos.clear()


def _assert_no_pipeline_leaks():
    stray = [
        p for p in multiprocessing.active_children()
        if p.name.startswith(SHM_PREFIX)
    ]
    assert not stray, f"leaked pipeline workers: {stray}"
    if os.path.isdir("/dev/shm"):
        segs = glob.glob(f"/dev/shm/{SHM_PREFIX}_*")
        assert not segs, f"leaked shm segments: {segs}"


# ------------------------------------------------------------- fault plans
def test_spec_grammar_and_validation():
    p = FaultPlan(
        "pipeline.worker_crash@batch=37:worker=1,"
        "serve.engine_stall@p=0.25:delay_ms=80,"
        "snapshot.partial_write@index=1:frac=0.25",
        seed=7,
    )
    assert p.points() == [
        "pipeline.worker_crash", "serve.engine_stall",
        "snapshot.partial_write",
    ]
    rule = p.match("pipeline.worker_crash", batch=37, worker=1)
    assert rule is not None and rule.match == {"batch": 37, "worker": 1}
    tear = p.match("snapshot.partial_write", index=1)
    assert tear is not None and tear.params["frac"] == 0.25

    with pytest.raises(ValueError, match="unknown fault point"):
        FaultPlan("bogus.point")
    with pytest.raises(ValueError, match="key=value"):
        FaultPlan("serve.conn_drop@nonsense")
    with pytest.raises(ValueError, match="must be a number"):
        FaultPlan("serve.conn_drop@request=abc")
    with pytest.raises(ValueError, match=r"p=2\.0"):
        FaultPlan("serve.conn_drop@p=2.0")
    with pytest.raises(ValueError, match="names no fault points"):
        FaultPlan("  ,  ")
    # every registered point parses bare
    for point in FAULT_POINTS:
        assert FaultPlan(point).points() == [point]


def test_exact_coordinate_match_and_schedule_predicates():
    p = FaultPlan("pipeline.worker_crash@batch=3:worker=1")
    assert not p.fires("pipeline.worker_crash", batch=3, worker=0)
    assert not p.fires("pipeline.worker_crash", batch=2, worker=1)
    assert p.fires("pipeline.worker_crash", batch=3, worker=1)
    assert not p.fires("pipeline.slow_batch", batch=3, worker=1)

    every = FaultPlan("serve.conn_drop@every=3:after=3")
    hits = [i for i in range(12) if every.fires("serve.conn_drop", request=i)]
    assert hits == [3, 6, 9]

    capped = FaultPlan("serve.conn_drop@times=2")
    hits = [i for i in range(6) if capped.fires("serve.conn_drop", request=i)]
    assert hits == [0, 1]  # budget spent after two fires


def test_probabilistic_plans_are_seed_deterministic():
    def decisions(seed):
        p = FaultPlan("serve.engine_stall@p=0.4", seed=seed)
        return [p.fires("serve.engine_stall", batch=i) for i in range(64)]

    a, b = decisions(11), decisions(11)
    assert a == b  # same seed + spec -> same fault sequence
    assert any(a) and not all(a)
    assert decisions(12) != a  # a different seed moves the faults


def test_disabled_chaos_is_a_noop_fast_path(monkeypatch):
    monkeypatch.delenv("SPARKNET_CHAOS", raising=False)
    chaos.clear()
    assert chaos.get_plan() is None and not chaos.active()
    # hot-path call sites cache the plan once: disabled means the guard
    # object is literally None (a single `is None` test per batch)
    b = MicroBatcher(_EchoEngine(), max_latency_us=0)
    assert b._chaos is None
    b.drain()
    srv = InferenceServer(_EchoEngine(), port=0).start()
    assert srv._chaos is None
    srv.stop()
    assert chaos.METRICS.snapshot() == {"fires": {}, "recoveries": {}}


def test_install_from_flag_wins_and_env_is_lazy(monkeypatch):
    monkeypatch.setenv("SPARKNET_CHAOS", "serve.conn_drop@every=2")
    chaos.clear()
    env_plan = chaos.get_plan()
    assert env_plan is not None and env_plan.points() == ["serve.conn_drop"]
    flag_plan = chaos.install_from("serve.engine_stall@batch=0")
    assert flag_plan.points() == ["serve.engine_stall"]
    assert chaos.get_plan() is flag_plan  # explicit install wins over env


# ---------------------------------------------------------------- pipeline
def _ds(n=96, parts=4):
    rng = np.random.default_rng(0)
    return ShardedDataset.from_arrays(
        {
            "data": rng.normal(size=(n, 8, 8, 3)).astype(np.float32),
            "label": np.arange(n, dtype=np.int32),
        },
        parts,
    )


def _aug(batch, r):
    return {
        "data": batch["data"]
        + r.normal(size=batch["data"].shape).astype(np.float32),
        "label": batch["label"],
    }


def _assert_same_stream(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


@fork_only
def test_worker_crash_respawns_and_stream_is_bit_identical():
    ds = _ds()
    serial = list(
        ds.batches(8, shuffle=True, seed=3, epochs=2, transform=_aug)
    )
    chaos.install("pipeline.worker_crash@batch=6")
    with ParallelBatchPipeline(
        ds, 8, workers=2, shuffle=True, seed=3, epochs=2, transform=_aug
    ) as pipe:
        got = list(pipe)
        respawns = pipe.metrics.worker_respawns
        snap = pipe.metrics.snapshot()
    _assert_same_stream(serial, got)
    assert respawns == 1  # exactly one recovery, observable
    assert snap["worker_respawns"] == 1
    assert chaos.METRICS.recovery_count("pipeline.worker_respawn") == 1
    _assert_no_pipeline_leaks()


@fork_only
def test_worker_crash_past_respawn_budget_fails_at_serial_position():
    ds = _ds(n=48, parts=2)
    chaos.install("pipeline.worker_crash@batch=4")
    pipe = ParallelBatchPipeline(
        ds, 8, workers=2, shuffle=False, seed=0, epochs=1, transform=_aug,
        max_respawns=0,
    )
    with pytest.raises(RuntimeError, match="respawns already spent"):
        list(pipe)
    _assert_no_pipeline_leaks()


@fork_only
def test_slow_batch_fault_changes_timing_not_content():
    ds = _ds(n=48, parts=2)
    serial = list(
        ds.batches(8, shuffle=False, seed=0, epochs=1, transform=_aug)
    )
    chaos.install("pipeline.slow_batch@every=2:delay_ms=30")
    with ParallelBatchPipeline(
        ds, 8, workers=2, shuffle=False, seed=0, epochs=1, transform=_aug
    ) as pipe:
        got = list(pipe)
        respawns = pipe.metrics.worker_respawns
    _assert_same_stream(serial, got)
    assert respawns == 0  # slow is not dead
    _assert_no_pipeline_leaks()


# --------------------------------------------------------------- snapshots
def test_npz_snapshot_carries_manifest_and_detects_torn_file(tmp_path):
    import json

    path = str(tmp_path / "st.solverstate.npz")
    snapshot.save_state(
        path, tree={"w": np.arange(12, dtype=np.float32)}, it=3
    )
    with np.load(path) as z:
        meta = json.loads(bytes(z["__solverstate__"].tobytes()).decode())
    assert "arrays" in meta and meta["arrays"]  # the verify manifest
    assert snapshot.load_state(path)["it"] == 3
    assert not glob.glob(str(tmp_path / "*.tmp"))  # staged write renamed

    size = os.path.getsize(path)
    with open(path, "rb+") as fh:
        fh.truncate(size // 2)
    with pytest.raises(snapshot.SnapshotError, match="torn or unreadable"):
        snapshot.load_state(path)


def test_partial_write_chaos_then_fallback_restore(tmp_path):
    import jax

    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "tiny"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""
    sp_txt = 'base_lr: 0.1\nlr_policy: "fixed"\nmomentum: 0.9\nmax_iter: 8\n'

    def make_solver():
        sp = caffe_pb.load_solver(sp_txt, is_path=False)
        sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
        return Solver(sp, {"data": (8, 6), "label": (8,)})

    rng = np.random.default_rng(5)
    batches = [
        {
            "data": rng.normal(size=(8, 6)).astype(np.float32),
            "label": rng.integers(0, 3, 8).astype(np.int32),
        }
        for _ in range(4)
    ]
    prefix = str(tmp_path / "run")
    chaos.install("snapshot.partial_write@iter=4")

    s1 = make_solver()
    s1.step(iter(batches[:2]), 2)
    s1.save(f"{prefix}_iter_2.solverstate.npz")  # intact
    s1.step(iter(batches[2:]), 2)
    s1.save(f"{prefix}_iter_4.solverstate.npz")  # chaos tears this one
    assert chaos.METRICS.snapshot()["fires"]["snapshot.partial_write"] == 1

    torn = f"{prefix}_iter_4.solverstate.npz"
    with pytest.raises(snapshot.SnapshotError):
        snapshot.load_state(torn)

    s2 = make_solver()
    restored = snapshot.restore_with_fallback(s2, prefix, torn)
    assert restored == f"{prefix}_iter_2.solverstate.npz"
    assert s2.iter == 2
    assert chaos.METRICS.recovery_count("snapshot.fallback_restore") == 1
    # the fallback state is the real iter-2 state, not garbage
    for layer, leaves in jax.device_get(s2.params).items():
        for name, v in leaves.items():
            np.testing.assert_array_equal(
                v, np.asarray(snapshot.load_state(restored)["params"][layer][name])
            )
    # nothing under the prefix restorable -> the error surfaces
    with open(f"{prefix}_iter_2.solverstate.npz", "rb+") as fh:
        fh.truncate(10)
    with pytest.raises(snapshot.SnapshotError):
        snapshot.restore_with_fallback(make_solver(), prefix, torn)


def test_prune_snapshots_keep_last_k(tmp_path):
    prefix = str(tmp_path / "run")
    for it in (2, 4, 6, 8, 10):
        open(f"{prefix}_iter_{it}.solverstate.npz", "wb").close()
        open(f"{prefix}_iter_{it}.npz", "wb").close()  # weights twin
    removed = snapshot.prune_snapshots(prefix, keep=2)
    left = sorted(os.path.basename(p) for p in glob.glob(f"{prefix}*"))
    assert left == [
        "run_iter_10.npz", "run_iter_10.solverstate.npz",
        "run_iter_8.npz", "run_iter_8.solverstate.npz",
    ]
    assert len(removed) == 6
    assert snapshot.prune_snapshots(prefix, keep=0) == []  # 0 disables


# ----------------------------------------------------------------- serving
class _EchoEngine:
    """Duck-typed engine: identity infer + an argsort postprocess —
    enough for the HTTP surface without compiling a net."""

    buckets = (8,)
    output = "prob"
    metrics = None

    def __init__(self):
        self.calls = 0

    def infer(self, rows):
        self.calls += 1
        return np.asarray(rows, np.float32)

    def postprocess(self, out, top_k):
        idx = np.argsort(-out, axis=-1)[:, :top_k]
        probs = np.take_along_axis(out, idx, axis=-1)
        return idx, probs


class _BlockingEngine(_EchoEngine):
    """Engine that blocks inside infer until released."""

    def __init__(self):
        super().__init__()
        self.release = threading.Event()
        self.started = threading.Event()

    def infer(self, rows):
        self.started.set()
        assert self.release.wait(10)
        return super().infer(rows)


def test_client_retry_storm_against_flapping_server():
    """Every other /classify connection is dropped cold; retrying
    clients must end with every request answered — zero hung, zero
    silently dropped — and the recoveries counted."""
    chaos.install("serve.conn_drop@every=2")
    eng = _EchoEngine()
    srv = InferenceServer(
        eng, port=0, model_name="echo",
        batcher=MicroBatcher(eng, max_latency_us=0),
    ).start()
    try:
        c = Client(
            srv.host, srv.port, timeout=5,
            retries=3, backoff_s=0.01, max_backoff_s=0.05,
        )
        rows = np.eye(5, dtype=np.float32)[:1]
        n = 12
        for _ in range(n):
            st, resp = c.classify(rows, top_k=2)
            assert st == 200
            assert resp["indices"][0][0] == 0  # identity engine: argmax
        snap = chaos.METRICS.snapshot()
        assert snap["fires"]["serve.conn_drop"] == n
        assert snap["recoveries"]["serve.client_retry"] == n
        st, health = c.healthz()
        assert st == 200 and health["status"] == "ok"  # flaky != degraded
    finally:
        srv.stop()


def test_client_gives_up_after_retry_budget():
    chaos.install("serve.conn_drop@every=1")  # always drop
    eng = _EchoEngine()
    srv = InferenceServer(
        eng, port=0, batcher=MicroBatcher(eng, max_latency_us=0)
    ).start()
    try:
        c = Client(
            srv.host, srv.port, timeout=2,
            retries=1, backoff_s=0.01, max_backoff_s=0.02,
        )
        with pytest.raises(OSError):
            c.classify(np.zeros((1, 4), np.float32))
    finally:
        srv.stop()


def test_engine_stall_sheds_expired_requests_before_compute():
    """serve.engine_stall + a 50 ms deadline: the stalled flush must
    shed the expired request without calling the engine, count it, and
    degrade /healthz."""
    chaos.install("serve.engine_stall@batch=0:delay_ms=120")
    m = ServeMetrics()
    eng = _EchoEngine()
    b = MicroBatcher(
        eng, max_batch=1, max_latency_us=0, deadline_s=0.05, metrics=m,
    )
    fut = b.submit(np.zeros((1, 4), np.float32))
    with pytest.raises(DeadlineExceeded, match="expired"):
        fut.result(timeout=10)
    b.drain()
    assert eng.calls == 0  # shed BEFORE compute
    snap = m.snapshot()
    assert snap["shed"] == 1 and snap["health"] == "degraded"
    assert m.health() == "degraded"
    assert chaos.METRICS.snapshot()["fires"]["serve.engine_stall"] == 1

    # the degraded state is visible on the HTTP surface
    srv = InferenceServer(
        _EchoEngine(), metrics=m, port=0,
        batcher=MicroBatcher(_EchoEngine(), max_latency_us=0),
    ).start()
    try:
        st, health = srv.client().healthz()
        assert st == 200
        assert health["status"] == "degraded" and health["shed"] == 1
    finally:
        srv.stop()


def test_server_timeout_cancels_inflight_request_and_batcher_drops_it():
    """Two requests against a wedged engine: both handlers 504, and the
    queued one must be dropped by the batcher (counted as cancelled)
    instead of computed for nobody."""
    m = ServeMetrics()
    eng = _BlockingEngine()
    srv = InferenceServer(
        eng, metrics=m, port=0, request_timeout_s=0.4,
        batcher=MicroBatcher(eng, max_batch=1, max_latency_us=0, metrics=m),
    ).start()
    try:
        c = Client(srv.host, srv.port, timeout=10, retries=0)
        results = []

        def call():
            results.append(c.classify(np.zeros((1, 4), np.float32)))

        t1 = threading.Thread(target=call)
        t1.start()
        assert eng.started.wait(10)  # engine wedged on request 1
        t2 = threading.Thread(target=call)
        t2.start()
        t1.join(10)
        t2.join(10)
        assert [st for st, _ in results] == [504, 504]
        eng.release.set()  # unwedge; the queued request must be dropped
        deadline = time.perf_counter() + 10
        while m.cancelled < 1 and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert m.cancelled == 1
        assert eng.calls == 1  # only the in-flight one ever computed
        assert m.health() == "degraded"
    finally:
        srv.stop()


def test_drain_raises_when_worker_is_wedged():
    eng = _BlockingEngine()
    b = MicroBatcher(eng, max_batch=1, max_latency_us=0)
    b.submit(np.zeros((1, 3), np.float32))
    assert eng.started.wait(10)
    with pytest.raises(RuntimeError, match="did not stop"):
        b.drain(timeout=0.2)
    eng.release.set()  # let the worker finish so the thread exits
    b._worker.join(10)


# ------------------------------------------------------------ CLI e2e
@fork_only
def test_caffe_train_with_worker_crash_is_bit_identical(tmp_path, capsys):
    """The acceptance run: ``caffe train`` with
    SPARKNET_CHAOS-style injection of one pipeline worker crash
    completes, final weights are bit-identical to the fault-free run,
    and the recovery counter reads exactly one respawn."""
    from sparknet_tpu.tools import caffe as caffe_cli

    net_txt = """
name: "tiny"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 10
          weight_filler { type: "gaussian" std: 0.05 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""

    def run(tag, chaos_spec):
        d = tmp_path / tag
        d.mkdir()
        (d / "net.prototxt").write_text(net_txt)
        (d / "solver.prototxt").write_text(
            'net: "net.prototxt"\nbase_lr: 0.05\nlr_policy: "fixed"\n'
            'momentum: 0.9\nmax_iter: 6\nsnapshot: 6\n'
            f'snapshot_prefix: "{d}/snap"\ndisplay: 0\n'
        )
        argv = [
            "train", f"--solver={d}/solver.prototxt", "--synthetic",
            "--synthetic-n=64", "--batch-size=8", "--seed=3",
            "--data-workers=2", "--native-loader=off",
        ]
        if chaos_spec:
            argv.append(f"--chaos={chaos_spec}")
        caffe_cli.main(argv)
        with np.load(f"{d}/snap_iter_6.npz") as z:
            weights = {k: z[k].copy() for k in z.files}
        return weights

    chaotic = run("chaos", "pipeline.worker_crash@batch=3")
    assert chaos.METRICS.recovery_count("pipeline.worker_respawn") == 1
    out = capsys.readouterr().out
    assert '"pipeline.worker_respawn": 1' in out  # the printed chaos line
    chaos.clear()
    clean = run("clean", None)
    assert sorted(chaotic) == sorted(clean)
    for k in clean:
        np.testing.assert_array_equal(chaotic[k], clean[k], err_msg=k)
    _assert_no_pipeline_leaks()
