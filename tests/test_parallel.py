"""Distribution layer tests on the 8-device virtual CPU mesh.

Key invariants (mirroring the reference's algorithmic contract,
SURVEY.md §1 core algorithm):
- sync DP over a sharded global batch == single-device step on the same
  batch (the all-reduce is exact, not approximate);
- τ=1 local SGD == sync DP (averaging weights after one step with
  momentum starting at 0 is identical to averaging gradients);
- τ>1 local SGD still trains (loss decreases) and advances iter by τ.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.parallel import ParallelSolver, make_mesh
from sparknet_tpu.solver.trainer import Solver

TINY_NET = """
name: "tiny"
layer { name: "d" type: "Input" top: "data" top: "label" }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss" }
"""

SOLVER_TXT = "base_lr: 0.1 momentum: 0.9 lr_policy: 'fixed' weight_decay: 0.001"


def tiny_net():
    return caffe_pb.load_net(TINY_NET, is_path=False)


def tiny_solver():
    return caffe_pb.load_solver(SOLVER_TXT, is_path=False)


def batch(seed, n=16):
    rng = np.random.default_rng(seed)
    return {
        "data": jnp.asarray(rng.normal(size=(n, 8)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 4, size=(n,)), jnp.int32),
    }


SHAPES = {"data": (16, 8), "label": (16,)}


def test_make_mesh_shapes():
    m = make_mesh()
    assert m.shape["dp"] == 8
    m = make_mesh({"dp": 2, "tp": -1})
    assert m.shape == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        make_mesh({"dp": 3})


def test_sync_dp_matches_single_device():
    net = tiny_net()
    single = Solver(tiny_solver(), SHAPES, net_param=net, seed=7)
    par = ParallelSolver(
        tiny_solver(), SHAPES, net_param=net, seed=7, mesh=make_mesh(), mode="sync"
    )
    feed = [batch(i) for i in range(3)]
    single.step(iter(list(feed)), 3)
    par.step(iter(list(feed)), 3)
    for layer in single.params:
        for name in single.params[layer]:
            np.testing.assert_allclose(
                np.asarray(single.params[layer][name]),
                np.asarray(par.params[layer][name]),
                rtol=2e-5,
                atol=1e-6,
                err_msg=f"{layer}/{name}",
            )


def test_local_sgd_tau1_matches_sync():
    net = tiny_net()
    mesh = make_mesh()
    sync = ParallelSolver(
        tiny_solver(), SHAPES, net_param=net, seed=7, mesh=mesh, mode="sync"
    )
    local = ParallelSolver(
        tiny_solver(), SHAPES, net_param=net, seed=7, mesh=mesh, mode="local", tau=1
    )
    feed = [batch(i) for i in range(2)]
    sync.step(iter(list(feed)), 2)
    local.step(iter(list(feed)), 2)
    # τ=1: averaging post-step weights == averaging gradients, except the
    # momentum buffers stay per-worker; with 2 steps they have begun to
    # diverge at O(lr^2) — compare loosely but meaningfully.
    for layer in sync.params:
        for name in sync.params[layer]:
            np.testing.assert_allclose(
                np.asarray(sync.params[layer][name]),
                np.asarray(local.params[layer][name]),
                rtol=1e-3,
                atol=1e-4,
                err_msg=f"{layer}/{name}",
            )


def test_local_sgd_tau4_trains():
    net = tiny_net()
    s = ParallelSolver(
        tiny_solver(),
        SHAPES,
        net_param=net,
        seed=0,
        mesh=make_mesh(),
        mode="local",
        tau=4,
    )
    fixed = batch(0)

    def feed():
        while True:
            yield fixed

    m0 = s.step(feed(), 4)
    assert s.iter == 4
    m1 = s.step(feed(), 40)
    assert float(m1["loss"]) < float(m0["loss"])
    assert float(m1["loss"]) < 0.2


def test_local_sgd_metrics_replicated_and_batch_split():
    """Each worker must see a distinct batch shard: train on data whose
    label depends on the shard, and check the model fits all shards
    (impossible if every device saw the same slice)."""
    net = tiny_net()
    mesh = make_mesh()
    s = ParallelSolver(
        tiny_solver(), SHAPES, net_param=net, seed=1, mesh=mesh, mode="local", tau=2
    )
    rng = np.random.default_rng(3)
    data = rng.normal(size=(16, 8)).astype(np.float32)
    labels = (np.arange(16) // 4 % 4).astype(np.int32)  # varies across shards
    b = {"data": jnp.asarray(data), "label": jnp.asarray(labels)}

    def feed():
        while True:
            yield b

    s.step(feed(), 60)
    ev = s._eval_step(s.params, s.state, b)
    assert float(ev["loss"]) < 0.3


def test_local_sgd_partial_round_respects_n():
    net = tiny_net()
    s = ParallelSolver(
        tiny_solver(), SHAPES, net_param=net, seed=0,
        mesh=make_mesh(), mode="local", tau=4,
    )
    consumed = []

    def feed():
        i = 0
        while True:
            consumed.append(i)
            yield batch(i)
            i += 1

    s.step(feed(), 6)  # 4 + 2: second round is a partial tau=2 round
    assert s.iter == 6
    assert len(consumed) == 6


def test_iter_size_parallel_modes():
    """iter_size=2 must accumulate (not crash / not halve the batch) in
    both modes; sync-vs-local τ=1 must agree like the plain case."""
    net = tiny_net()
    mesh = make_mesh()
    sp_txt = SOLVER_TXT + " iter_size: 2"
    shapes = {"data": (8, 8), "label": (8,)}
    halves = [
        {"data": batch(i)["data"][:8], "label": batch(i)["label"][:8]}
        for i in range(4)
    ]
    sync = ParallelSolver(
        caffe_pb.load_solver(sp_txt, is_path=False), shapes,
        net_param=net, seed=7, mesh=mesh, mode="sync",
    )
    local = ParallelSolver(
        caffe_pb.load_solver(sp_txt, is_path=False), shapes,
        net_param=net, seed=7, mesh=mesh, mode="local", tau=1,
    )
    sync.step(iter(list(halves)), 2)
    local.step(iter(list(halves)), 2)
    for layer in sync.params:
        for name in sync.params[layer]:
            np.testing.assert_allclose(
                np.asarray(sync.params[layer][name]),
                np.asarray(local.params[layer][name]),
                rtol=1e-3,
                atol=1e-4,
                err_msg=f"{layer}/{name}",
            )


def test_sync_dp_cifar_quick_smoke():
    """The flagship prototxt compiles and trains under the 8-way mesh."""
    from pathlib import Path

    zoo = Path(__file__).resolve().parents[1] / "sparknet_tpu" / "models" / "prototxt"
    sp = caffe_pb.load_solver(str(zoo / "cifar10_quick_solver.prototxt"))
    shapes = {"data": (16, 32, 32, 3), "label": (16,)}
    s = ParallelSolver(sp, shapes, solver_dir=str(zoo), mesh=make_mesh(), mode="sync")
    rng = np.random.default_rng(0)
    b = {
        "data": jnp.asarray(rng.normal(size=(16, 32, 32, 3)), jnp.float32),
        "label": jnp.asarray(np.arange(16) % 10, jnp.int32),
    }

    def feed():
        while True:
            yield b

    m = s.step(feed(), 2)
    assert np.isfinite(float(m["loss"]))
