"""Unified sharding compile path (parallel/partition.py) on the
8-device virtual CPU mesh.

Pins the ISSUE 10 contract:
- rule table semantics: first-match-wins ordering, explicit replicated
  fallback for unmatched leaves, strict rejection of non-divisible
  dims, scalar leaves never partitioned;
- BITWISE weight equality between the legacy hand-built dp shardings
  and the rule-table-built ones over 5 training steps (the refactor
  changes zero numerics when the shardings agree);
- a NEW dp×tp layout needs only a table entry — an arch trains under
  it with zero parallel/ code changes and matches single-device math
  to reduction-order accuracy;
- per-leaf specs round-trip through snapshot save/restore, and a
  resume under a different layout relayouts with one warning;
- the serve fingerprint is layout-keyed so compile caches never alias.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from sparknet_tpu.parallel import ParallelSolver, make_mesh, partition
from sparknet_tpu.parallel.partition import (
    Layout,
    Rule,
    layout_from_json,
    layout_to_json,
    match_spec,
    parse_layout,
    spec_from_str,
    spec_to_str,
    spec_tree,
)
from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.solver.trainer import Solver

from .test_parallel import SHAPES, TINY_NET, SOLVER_TXT, batch, tiny_net, tiny_solver


def feed_of(b):
    def gen():
        while True:
            yield b
    return gen()


# ---------------------------------------------------------------------------
# rule-table semantics
# ---------------------------------------------------------------------------

def test_first_match_wins_ordering():
    mesh = make_mesh({"dp": 2, "tp": 4}, jax.devices()[:8])
    leaf = jnp.zeros((8, 8))
    rules = (
        Rule(r"ip1/weight", (None, "tp")),
        Rule(r"weight", ("tp", None)),  # broader rule AFTER the specific
    )
    assert match_spec(rules, "ip1/weight", leaf, mesh) == P(None, "tp")
    assert match_spec(rules, "ip2/weight", leaf, mesh) == P("tp")
    # reversed order: the broad rule shadows the specific one
    assert match_spec(rules[::-1], "ip1/weight", leaf, mesh) == P("tp")


def test_unmatched_leaf_falls_back_replicated():
    mesh = make_mesh({"dp": 8}, jax.devices()[:8])
    rules = (Rule(r"weight$", ("dp",)),)
    assert match_spec(rules, "ip1/bias", jnp.zeros((8,)), mesh) == P()
    # scalars never partition, even when a rule matches
    assert match_spec(rules, "scale/weight", jnp.zeros(()), mesh) == P()


def test_rule_axes_absent_from_mesh_degrade_to_replicated():
    """One ruleset serves every layout: axes the mesh lacks become
    None, so 'tp' rules are harmless on a pure-dp mesh."""
    mesh = make_mesh({"dp": 8}, jax.devices()[:8])
    rules = (Rule(r"weight$", (None, "tp")),)
    assert match_spec(rules, "ip1/weight", jnp.zeros((8, 8)), mesh) == P()


def test_trailing_align_shards_last_dim():
    mesh = make_mesh({"dp": 2, "tp": 4}, jax.devices()[:8])
    rules = (Rule(r"weight$", ("tp",), align="trailing"),)
    conv = jnp.zeros((5, 5, 3, 32))
    ip = jnp.zeros((64, 8))
    assert match_spec(rules, "conv1/weight", conv, mesh) == P(
        None, None, None, "tp"
    )
    assert match_spec(rules, "ip1/weight", ip, mesh) == P(None, "tp")


def test_strict_rejects_nondivisible_dims():
    mesh = make_mesh({"dp": 2, "tp": 4}, jax.devices()[:8])
    tree = {"ip": {"weight": jnp.zeros((8, 10))}}  # 10 % 4 != 0
    rules = (Rule(r"weight$", (None, "tp")),)
    with pytest.raises(ValueError, match="not\\s+divisible"):
        spec_tree(tree, rules, mesh, validate="strict")
    # validate=off accepts the same table
    specs = spec_tree(tree, rules, mesh, validate="off")
    assert specs["ip"]["weight"] == P(None, "tp")


def test_rank_overflow_rejected():
    mesh = make_mesh({"dp": 8}, jax.devices()[:8])
    rules = (Rule(r"bias$", (None, "dp")),)
    with pytest.raises(ValueError, match="rank"):
        match_spec(rules, "ip/bias", jnp.zeros((8,)), mesh)


def test_spec_string_round_trip():
    for spec in (P(), P("dp"), P(None, "tp"), P(("dp", "tp"), None), P("tp", None)):
        assert spec_from_str(spec_to_str(spec)) == spec


def test_layout_json_round_trip():
    lay = parse_layout("dp=2,tp=4", rules="bert", name="mine")
    back = layout_from_json(layout_to_json(lay))
    assert back.axes == lay.axes
    assert back.rules == lay.rules
    assert partition.layout_fingerprint(back) == partition.layout_fingerprint(lay)
    # a different rule table is a different fingerprint
    other = parse_layout("dp=2,tp=4", rules="tp")
    assert partition.layout_fingerprint(other) != partition.layout_fingerprint(lay)


# ---------------------------------------------------------------------------
# the compiled path
# ---------------------------------------------------------------------------

def test_bitwise_legacy_dp_equals_unified_dp():
    """5 training steps with hand-built dp shardings (the pre-refactor
    make_dp_train_step spec construction, inlined here as the oracle)
    vs the rule-table path — identical shardings must give identical
    executables, pinned BITWISE on the trained weights."""
    from sparknet_tpu.solver.trainer import make_train_step
    from sparknet_tpu.solver.caffe_solver import init_opt_state

    net_param = tiny_net()
    sp = tiny_solver()
    mesh = make_mesh()
    from sparknet_tpu.nets.xlanet import XLANet

    net = XLANet(net_param, "TRAIN", SHAPES)
    params, state = net.init(jax.random.PRNGKey(3))
    opt = init_opt_state(sp, params)
    # host copies per arm: on CPU device_put can alias rather than
    # copy, and both arms donate — a shared buffer would be deleted
    # out from under the second arm
    params, state, opt = (
        jax.device_get(params), jax.device_get(state), jax.device_get(opt)
    )
    b = batch(0)
    repl = NamedSharding(mesh, P())
    bsh = NamedSharding(mesh, P("dp"))

    # legacy: the old hand-rolled implicit-dp jit
    legacy = jax.jit(
        make_train_step(net, sp),
        in_shardings=(repl, repl, repl, bsh, repl, repl),
        out_shardings=(repl, repl, repl, repl),
        donate_argnums=(0, 1, 2),
    )
    p1, s1, o1 = (
        jax.device_put(params, repl), jax.device_put(state, repl),
        jax.device_put(opt, repl),
    )
    for it in range(5):
        p1, s1, o1, _ = legacy(
            p1, s1, o1, jax.device_put(b, bsh),
            jnp.asarray(it, jnp.int32), jax.random.PRNGKey(7),
        )

    # unified: the same shardings from the (empty) rule table
    lay = Layout(axes=(("dp", 8),), rules=(), name="dp")
    plan = partition.make_plan(lay, params, state, sp, mesh=mesh)
    step = partition.make_sharded_train_step(net, sp, plan)
    p2 = partition.place(params, plan.params_sh)
    s2 = partition.place(state, plan.state_sh)
    o2 = partition.place(opt, plan.opt_sh)
    for it in range(5):
        p2, s2, o2, _ = step(
            p2, s2, o2, jax.device_put(b, plan.batch_train_sh),
            jnp.asarray(it, jnp.int32), jax.random.PRNGKey(7),
        )
    for (ka, a), (kb, c) in zip(
        partition.tree_paths(p1), partition.tree_paths(p2)
    ):
        assert ka == kb
        assert (np.asarray(a) == np.asarray(c)).all(), ka


def test_new_layout_is_a_table_entry():
    """The acceptance pin: a dp×tp layout over the tiny net needs ONLY
    a rule-table entry (no step builder, no parallel/ code) and
    matches single-device training to reduction-order accuracy."""
    sp = tiny_solver()
    lay = parse_layout("dp=2,tp=2", rules="tp")
    par = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7, layout=lay
    )
    rep = par.layout_report()
    assert rep["path"] == "unified"
    assert rep["sharded"] >= 2  # both IP weights (and biases) shard
    single = Solver(sp, SHAPES, net_param=tiny_net(), seed=7)
    b = batch(1)
    par.step(feed_of(b), 5)
    single.step(feed_of(b), 5)
    for (ka, a), (kb, c) in zip(
        partition.tree_paths(jax.device_get(par.params)),
        partition.tree_paths(jax.device_get(single.params)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(c), rtol=1e-5, atol=1e-6, err_msg=ka
        )
    # and the params really are distributed per the table
    w_sh = par.params["ip1"]["weight"].sharding
    assert w_sh.spec == P(None, "tp"), w_sh


def test_unified_eval_step_shares_the_path():
    lay = parse_layout("dp=2,tp=2", rules="tp")
    par = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7, layout=lay
    )
    single = Solver(tiny_solver(), SHAPES, net_param=tiny_net(), seed=7)
    b = batch(2)
    m_par = par.test(feed_of(b), test_iter=2)
    m_single = single.test(feed_of(b), test_iter=2)
    for k in m_single:
        np.testing.assert_allclose(m_par[k], m_single[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


def test_local_mode_rejects_model_parallel_layout():
    with pytest.raises(ValueError, match="dp-only"):
        ParallelSolver(
            tiny_solver(), SHAPES, net_param=tiny_net(), seed=0,
            layout=parse_layout("dp=2,tp=2", rules="tp"), mode="local",
        )


def test_local_mode_over_dp_only_layout():
    """τ-local SGD rides a dp-shaped layout unchanged: same machinery,
    mesh built from the table."""
    s = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=0,
        layout=parse_layout("dp=8"), mode="local", tau=2,
    )
    m = s.step(feed_of(batch(3)), 4)
    assert np.isfinite(float(m["loss"]))
    assert s.iter == 4
    assert s.layout_report()["path"] == "legacy-local"


# ---------------------------------------------------------------------------
# snapshot round-trip + relayout-on-resume
# ---------------------------------------------------------------------------

def test_specs_round_trip_through_snapshot(tmp_path, capsys):
    lay = parse_layout("dp=8", rules="replicated", name="dp8")
    a = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7, layout=lay
    )
    a.step(feed_of(batch(4)), 3)
    snap = str(tmp_path / "iter3.solverstate.npz")
    a.save(snap)

    # same layout back: specs match, NO relayout warning
    b1 = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7, layout=lay
    )
    b1.restore(snap)
    from sparknet_tpu.solver import snapshot as snap_mod

    st = snap_mod.load_state(snap)
    env = st["env"]
    saved_specs = json.loads(str(env["param_specs"]))
    assert saved_specs == b1._plan.specs
    assert json.loads(str(env["layout"]))["name"] == "dp8"

    # different layout: leaves land per the RUN's table + one warning
    import io, contextlib, sys as _sys

    lay2 = parse_layout("dp=2,tp=2", rules="tp")
    b2 = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7, layout=lay2
    )
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        b2.restore(snap)
    assert "relayout on resume" in err.getvalue()
    assert b2.params["ip1"]["weight"].sharding.spec == P(None, "tp")
    # weights bitwise-equal to the snapshot (placement never mutates)
    for (k, x), (k2, y) in zip(
        partition.tree_paths(jax.device_get(a.params)),
        partition.tree_paths(jax.device_get(b2.params)),
    ):
        assert (np.asarray(x) == np.asarray(y)).all(), k
    # and training continues through the new layout's compiled path
    m = b2.step(feed_of(batch(4)), 1)
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# serve-side: fingerprint + guards
# ---------------------------------------------------------------------------

def test_net_fingerprint_is_layout_keyed():
    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.serve.compile_cache import net_fingerprint

    net = XLANet(tiny_net(), "TEST", SHAPES)
    params, state = net.init(jax.random.PRNGKey(0))
    base = net_fingerprint(net, params, state, jnp.float32)
    lay = parse_layout("dp=2,tp=2", rules="tp")
    keyed = net_fingerprint(net, params, state, jnp.float32, layout=lay)
    other = net_fingerprint(
        net, params, state, jnp.float32,
        layout=parse_layout("dp=4", rules="replicated"),
    )
    assert len({base, keyed, other}) == 3


def test_engine_serves_through_layout_shardings():
    """A multi-device replica compiles through the same sharding trees
    training uses and answers identically to a single-device engine."""
    from sparknet_tpu.nets.xlanet import XLANet
    from sparknet_tpu.serve.engine import InferenceEngine

    net = XLANet(tiny_net(), "TEST", SHAPES)
    params, state = net.init(jax.random.PRNGKey(0))
    plain = InferenceEngine(net, params, state, buckets=(4, 8),
                            output="ip2")
    lay = parse_layout("dp=2,tp=2", rules="tp")
    sharded = InferenceEngine(net, params, state, buckets=(4, 8),
                              output="ip2", layout=lay)
    assert plain.fingerprint != sharded.fingerprint
    rows = np.asarray(
        np.random.default_rng(0).normal(size=(6, 8)), np.float32
    )
    out_plain = plain.infer({"data": rows})
    out_sharded = sharded.infer({"data": rows})
    np.testing.assert_allclose(out_sharded, out_plain, rtol=1e-5,
                               atol=1e-6)
    assert sharded.params["ip1"]["weight"].sharding.spec == P(None, "tp")


def test_fence_once_respects_timeline_fence():
    """The compiled-step fence guard: with a fencing timeline active,
    fence_once must NOT add a second block_until_ready to the timed
    region (it returns the tree untouched)."""
    from sparknet_tpu.telemetry import timeline as _ttl

    x = jnp.arange(4.0)
    tl = _ttl.Timeline(fence=True)
    _ttl.set_current(tl)
    try:
        got = partition.fence_once(x)
        assert got is x  # untouched — no second fence
    finally:
        _ttl.set_current(None)
    got = partition.fence_once(x)  # no timeline: this IS the fence
    assert got is not None


def test_ensure_virtual_devices_is_idempotent_and_loud():
    import warnings as _w

    # backend is initialized in the test process: asking for more
    # devices than exist must WARN, not silently proceed
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        ok = partition.ensure_virtual_devices(len(jax.devices()) + 1)
    assert not ok
    assert any("already initialized" in str(r.message) for r in rec)
    # asking for what we have succeeds silently
    assert partition.ensure_virtual_devices(len(jax.devices()))
