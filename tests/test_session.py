"""Session-aware serving (ISSUE 13): the decode stepper, the
per-session state cache, engine.generate, session-affinity routing and
the satellites (loadgen skew mode, dash panel, bench_diff gates).

The expensive chaos e2e (subprocess tier, SIGKILL of the session
holder) lives in scripts/session_smoke.py (check.sh); these tests pin
the same semantics fast with in-process servers and a toy char-level
decoder small enough that the step compiles in well under a second."""

import json
import threading
import time

import numpy as np
import pytest
import jax

from sparknet_tpu.nets.xlanet import XLANet
from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.serve import session as session_mod
from sparknet_tpu.serve.engine import InferenceEngine
from sparknet_tpu.serve.session import (
    DISABLED,
    DecodeStepper,
    SessionCache,
)

VOCAB = 12

TOY_CHAR = """
name: "toy_char"
input: "data"
input_shape { dim: 6 dim: 1 }
input: "cont"
input_shape { dim: 6 dim: 1 }
layer { name: "embed" type: "Embed" bottom: "data" top: "emb"
        embed_param { num_output: 4 input_dim: 12 bias_term: false
          weight_filler { type: "uniform" min: -0.3 max: 0.3 } } }
layer { name: "lstm" type: "LSTM" bottom: "emb" bottom: "cont" top: "hid"
        recurrent_param { num_output: 6
          weight_filler { type: "uniform" min: -0.3 max: 0.3 }
          bias_filler { type: "constant" } } }
layer { name: "ip" type: "InnerProduct" bottom: "hid" top: "logits"
        inner_product_param { num_output: 12 axis: 2
          weight_filler { type: "gaussian" std: 0.2 } } }
layer { name: "prob" type: "Softmax" bottom: "logits" top: "prob"
        softmax_param { axis: 2 } }
"""

TOY_RNN = TOY_CHAR.replace('type: "LSTM"', 'type: "RNN"')


def char_engine(seed=3, **kw):
    net = XLANet(caffe_pb.load_net(TOY_CHAR, is_path=False), "TEST")
    params, state = net.init(jax.random.PRNGKey(seed))
    return InferenceEngine(net, params, state, **kw).warmup()


# ------------------------------------------------------------- stepper
def _seq_vs_step(proto):
    net = XLANet(caffe_pb.load_net(proto, is_path=False), "TEST")
    params, state = net.init(jax.random.PRNGKey(0))
    stepper = DecodeStepper(net, "prob")
    T = 6
    toks = np.arange(T) % VOCAB
    cont = np.ones((T, 1), np.float32)
    cont[0] = 0
    blobs, _ = net.apply(
        params, state,
        {"data": jax.numpy.asarray(toks[:, None], jax.numpy.int32),
         "cont": jax.numpy.asarray(cont)},
        train=False, rng=None,
    )
    seq = np.asarray(blobs["prob"])
    step = jax.jit(stepper.step_fn)
    carry = stepper.init_carry(1)
    outs = []
    for t in toks:
        out, carry = step(
            params, state, carry,
            jax.numpy.asarray([t], jax.numpy.int32),
        )
        outs.append(np.asarray(out))
    return seq, np.stack(outs)


@pytest.mark.parametrize("proto", [TOY_CHAR, TOY_RNN],
                         ids=["lstm", "rnn"])
def test_stepper_matches_sequence(proto):
    """The single-token step replays the sequence net's own math: per-
    step outputs match the lax.scan path (ulp-level — XLA fuses the
    scan body differently; the serving bit-identity bar is hit-vs-cold
    through ONE executable, pinned below)."""
    seq, stepped = _seq_vs_step(proto)
    assert np.allclose(seq, stepped, rtol=1e-5, atol=1e-6)


def test_stepper_rejects_unsupported_nets():
    from tests.test_serving_tier import TOY_DEPLOY

    net = XLANet(caffe_pb.load_net(TOY_DEPLOY, is_path=False), "TEST")
    assert not DecodeStepper.supports(net)
    with pytest.raises(ValueError, match="no recurrent"):
        DecodeStepper(net, "prob")
    # a recurrent net with a step-unsafe layer (Flatten mixes the time
    # axis into the row) is rejected with the offending layer named
    bad = TOY_CHAR.replace(
        'layer { name: "ip" type: "InnerProduct" bottom: "hid" top: "logits"\n'
        '        inner_product_param { num_output: 12 axis: 2\n'
        '          weight_filler { type: "gaussian" std: 0.2 } } }',
        'layer { name: "flat" type: "Flatten" bottom: "hid" '
        'top: "logits" }',
    )
    assert 'Flatten' in bad  # the replace actually happened
    netp = caffe_pb.load_net(bad, is_path=False)
    with pytest.raises(ValueError, match="flat"):
        DecodeStepper(XLANet(netp, "TEST"), "prob")


def test_inner_product_axis2_matches_einsum():
    """The layers.py satellite: IP axis=2 contracts the trailing dim
    per (T, N) position — pinned against the plain einsum."""
    from sparknet_tpu.nets.layers import ApplyCtx, InnerProduct

    lp = caffe_pb.load_net(TOY_CHAR, is_path=False).layers[2]
    rng = np.random.default_rng(0)
    x = jax.numpy.asarray(
        rng.normal(size=(5, 2, 6)).astype(np.float32)
    )
    params = InnerProduct.init(lp, jax.random.PRNGKey(1), [(5, 2, 6)])
    (y,), _ = InnerProduct.apply(
        lp, params, None, [x],
        ApplyCtx(train=False, rng=None),
    )
    want = np.einsum("tnh,hv->tnv", np.asarray(x),
                     np.asarray(params["weight"]))
    assert y.shape == (5, 2, 12)
    assert np.allclose(np.asarray(y), want, rtol=1e-5, atol=1e-6)
    assert InnerProduct.infer(lp, [(5, 2, 6)]) == [(5, 2, 12)]


# ----------------------------------------------------- engine.generate
def test_generate_hit_vs_cold_bit_identical():
    """THE session bar: the same full prefix answered from the cache
    (hit) and recomputed from scratch (cold) must be bitwise equal —
    both paths run the one compiled step executable."""
    eng = char_engine()
    prefix = [1, 2, 3, 4, 5, 6, 7]
    r0 = eng.generate(prefix, session="a", steps=0)
    assert r0["cache_state"] == "cold"
    assert r0["steps_run"] == len(prefix)
    hit = eng.generate(prefix + [8], session="a", steps=2)
    assert hit["cache_state"] == "hit"
    assert hit["steps_run"] == 3  # 1 new + 2 generated, never O(prefix)
    cold = eng.generate(prefix + [8], steps=2)
    assert cold["cache_state"] == "cold"
    assert hit["probs"] == cold["probs"]
    assert hit["indices"] == cold["indices"]
    assert hit["tokens"] == cold["tokens"]


def test_generate_prefix_mismatch_rebuilds():
    """Reusing a session id with a DIFFERENT history must rebuild from
    the request's prefix (cache_state=rebuilt), answering exactly like
    a fresh cold request — never from the stale carry."""
    eng = char_engine()
    eng.generate([1, 2, 3], session="s")
    r = eng.generate([9, 8, 7], session="s")
    assert r["cache_state"] == "rebuilt"
    cold = eng.generate([9, 8, 7])
    assert r["probs"] == cold["probs"]
    assert eng.session_cache.snapshot()["rebuilt"] == 1


def test_hot_swap_invalidates_sessions():
    """Gen-tag invalidation: after a weight hot-swap, cached session
    state must be dropped (stale_gen) and the answer recomputed under
    the NEW weights — bit-equal to a fresh engine on those weights."""
    eng = char_engine(seed=3)
    other = char_engine(seed=11)
    prefix = [1, 2, 3, 4]
    eng.generate(prefix, session="s")
    gen = eng.swap(
        jax.device_get(other.params), jax.device_get(other.state)
    )
    r = eng.generate(prefix, session="s")
    assert r["cache_state"] == "stale_gen" and r["gen"] == gen
    want = other.generate(prefix)
    assert r["probs"] == want["probs"], "stale-gen state leaked"
    assert eng.session_cache.snapshot()["stale_gen"] == 1
    # and the rebuilt state at the new gen hits afterwards
    assert eng.generate(prefix + [5], session="s")["cache_state"] == "hit"


def test_session_cache_lru_bound(monkeypatch):
    """LRU-by-hit under the byte budget: the hot (recently hit)
    session survives, cold ones evict, resident bytes stay bounded."""
    cache = SessionCache(max_mb=2e-3)  # ~2 KB
    carry = {"lstm": (np.zeros((1, 6), np.float32),) * 2}
    toks = np.arange(4, dtype=np.int32)
    out = np.zeros((1, 12), np.float32)
    per = session_mod._tree_bytes(carry) + toks.nbytes + out.nbytes
    fits = cache.max_bytes // per
    assert fits >= 2
    cache.put("fp", "hot", 0, toks, carry, out)
    for i in range(fits * 3):
        # keep "hot" recently hit while colds pour in
        got, st = cache.take("fp", "hot", 0, toks)
        assert st == "hit"
        cache.put("fp", "hot", 0, toks, got.carry, got.last_out)
        cache.put("fp", f"cold{i}", 0, toks, carry, out)
    snap = cache.snapshot()
    assert snap["resident_bytes"] <= cache.max_bytes
    assert snap["evictions"] > 0
    got, st = cache.take("fp", "hot", 0, toks)
    assert st == "hit", "the hot session was evicted before cold ones"


def test_session_cache_disabled_zero_footprint(monkeypatch):
    """SPARKNET_SESSION_CACHE=0: the engine shares the no-op singleton
    — generate works (always cold-replays), nothing is stored, and
    non-recurrent engines use the same object."""
    monkeypatch.setenv("SPARKNET_SESSION_CACHE", "0")
    eng = char_engine()
    assert eng.session_cache is DISABLED
    r1 = eng.generate([1, 2, 3], session="s")
    r2 = eng.generate([1, 2, 3], session="s")
    assert r1["cache_state"] == r2["cache_state"] == "disabled"
    assert r1["probs"] == r2["probs"]
    assert DISABLED.snapshot() == {"enabled": False, "entries": 0}
    monkeypatch.delenv("SPARKNET_SESSION_CACHE")
    from tests.test_serving_tier import toy_net

    net, params, state = toy_net()
    assert InferenceEngine(net, params, state).session_cache is DISABLED


def test_generate_validation():
    eng = char_engine()
    with pytest.raises(ValueError, match="out of range"):
        eng.generate([99])
    with pytest.raises(ValueError, match="empty"):
        eng.generate([])
    with pytest.raises(ValueError, match="steps"):
        eng.generate([1], steps=-1)
    from tests.test_serving_tier import toy_net

    net, params, state = toy_net()
    with pytest.raises(ValueError, match="no recurrent"):
        InferenceEngine(net, params, state).generate([1])


# ------------------------------------------------ batcher submit_call
class _StubEngine:
    buckets = (8,)

    def infer_tagged(self, rows):
        return rows * 2.0, 0

    def bucket_for(self, n):
        return 8


def test_batcher_submit_call_fifo_and_shed():
    """Callable requests share the single worker with rows requests:
    results land in order, and an expired call is shed before running
    (DeadlineExceeded) exactly like rows."""
    from sparknet_tpu.serve.batcher import DeadlineExceeded, MicroBatcher

    order = []
    b = MicroBatcher(_StubEngine(), max_latency_us=100)
    futs = []
    for i in range(3):
        futs.append(b.submit(np.full((1, 2), float(i))))
        futs.append(b.submit_call(lambda i=i: order.append(i) or i))
    rows_out = [f.result(10) for f in futs[::2]]
    call_out = [f.result(10) for f in futs[1::2]]
    assert call_out == [0, 1, 2] and order == [0, 1, 2]
    assert [float(r[0][0]) for r in rows_out] == [0.0, 2.0, 4.0]
    # deadline shed: the shed check runs at flush time — park the
    # worker on a slow call first so the short-deadline call expires
    # in the queue behind it, then is dropped before running
    ran = []
    slow = b.submit_call(lambda: time.sleep(0.4))
    time.sleep(0.1)  # let the worker pick up the slow call alone
    shed = b.submit_call(lambda: ran.append(1), deadline_s=0.01)
    slow.result(10)
    with pytest.raises(DeadlineExceeded):
        shed.result(10)
    assert not ran
    b.drain()


# --------------------------------------------------- HTTP + router e2e
@pytest.fixture(scope="module")
def char_tier():
    """Two real char-rnn replicas (in-process servers) behind a
    Router — the affinity/migration fixture."""
    from sparknet_tpu.serve.router import Router
    from sparknet_tpu.serve.server import InferenceServer

    servers = [
        InferenceServer(char_engine(seed=3), port=0).start()
        for _ in range(2)
    ]
    router = Router(
        [(s.host, s.port) for s in servers],
        model_name="char", health_interval_s=0.1,
    )
    assert router.wait_healthy(timeout_s=30)
    router.start()
    yield servers, router
    router.stop()
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass


def test_server_generate_route(char_tier):
    """Single-replica surface: cold -> hit over the wire, session
    counters on /healthz, 400 on garbage."""
    servers, _ = char_tier
    c = servers[0].client()
    st, r1 = c.generate([1, 2, 3], session="route", steps=1)
    assert st == 200 and r1["cache_state"] == "cold"
    assert r1["session"] == "route" and r1["quant"] == "f32"
    st, r2 = c.generate([1, 2, 3] + r1["tokens"], session="route")
    assert st == 200 and r2["cache_state"] == "hit"
    st, hz = c.healthz()
    sc = hz["session_cache"]
    assert sc["enabled"] and sc["hits"] >= 1 and sc["entries"] >= 1
    st, err = c.generate([1000], session="route")
    assert st == 400 and "out of range" in err["error"]
    import http.client as hc

    conn = hc.HTTPConnection(servers[0].host, servers[0].port)
    conn.request("POST", "/generate", b"{}",
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 400
    conn.close()


def test_router_affinity_sticks_then_migrates(char_tier):
    """Affinity: every step of a session lands on the replica holding
    its state (hits, despite least-outstanding ties).  Ejecting the
    holder migrates the session: the answer is rebuilt cold on the
    peer, marked migrated, counted, and bit-equal to the cold path."""
    servers, router = char_tier
    c = router.client()
    sid = "aff-e2e"
    st, r = c.generate([5, 6, 7], session=sid, steps=1)
    assert st == 200 and r["cache_state"] == "cold"
    hist = [5, 6, 7] + r["tokens"]
    for _ in range(3):
        st, r = c.generate(hist, session=sid, steps=1)
        assert st == 200 and r["cache_state"] == "hit", r
        hist += r["tokens"]
    holders = [
        i for i, s in enumerate(servers)
        if s.engine.session_cache.snapshot()["entries"] > 0
    ]
    assert len(holders) == 1, "affinity scattered one session"
    before = router.metrics.snapshot()["session_migrations"]
    # eject the holder (stop its HTTP server: conn-refused -> retry)
    servers[holders[0]].stop()
    try:
        st, r = c.generate(hist, session=sid, steps=1)
        assert st == 200, r
        assert r.get("migrated") is True and r["cache_state"] == "cold"
        assert (
            router.metrics.snapshot()["session_migrations"] == before + 1
        )
        hist += r["tokens"]
        st, cold = c.generate(hist, steps=0)
        st2, again = c.generate(hist, session=sid, steps=0)
        assert cold["probs"] == again["probs"], "migrated state wrong"
    finally:
        # revive a server on the dead slot so the module fixture's
        # other tests see two healthy replicas again
        from sparknet_tpu.serve.server import InferenceServer

        servers[holders[0]] = InferenceServer(
            char_engine(seed=3), port=0
        ).start()
        with router._lock:
            rep = router.replicas[holders[0]]
            rep.host = servers[holders[0]].host
            rep.port = servers[holders[0]].port
        router.wait_healthy(timeout_s=30)


def test_loadgen_session_mode(char_tier):
    """Hot-session skew mode: Zipf weights are deterministic and
    normalized, the record carries per-state counts + hit rate +
    session_failed_requests, and zero requests fail."""
    from sparknet_tpu.serve.loadgen import run_http_loadgen, zipf_weights

    w = zipf_weights(8, 1.2)
    assert np.isclose(w.sum(), 1.0) and (np.diff(w) < 0).all()
    assert np.allclose(zipf_weights(8, 1.2), w)
    assert np.allclose(zipf_weights(4, 0.0), 0.25)
    _, router = char_tier
    rec = run_http_loadgen(
        router.host, router.port, (), n_requests=24, concurrency=2,
        sessions=4, session_zipf=1.2, seed=5,
    )
    assert rec["failed_requests"] == 0
    assert rec["session_failed_requests"] == 0
    s = rec["sessions"]
    assert s["count"] == 4 and s["zipf"] == 1.2
    assert s["states"].get("hit", 0) > 0
    assert 0 < s["hit_rate"] <= 1
    assert sum(n for _, n in s["hottest"]) <= 24


def test_dash_session_panel(char_tier):
    """The /dash session panel renders on both tiers: replica dash
    from the registry source, router dash from the aggregated replica
    scrapes + a sessions column in the replica table."""
    import urllib.request

    servers, router = char_tier
    c = router.client()
    c.generate([1, 2], session="dash", steps=1)
    page = urllib.request.urlopen(
        f"http://{servers[0].host}:{servers[0].port}/dash"
    ).read().decode()
    assert "Sessions" in page and "stale gen" in page
    # router view: wait one health sweep so replica session_cache
    # blocks arrive, then the tier page aggregates them
    deadline = time.time() + 10
    while time.time() < deadline:
        snap = router.snapshot()
        if any(
            (r.get("session_cache") or {}).get("entries")
            for r in snap["replicas"]
        ):
            break
        time.sleep(0.2)
    page = urllib.request.urlopen(
        f"http://{router.host}:{router.port}/dash"
    ).read().decode()
    assert "Sessions" in page and "<th>sessions</th>" in page


# ------------------------------------------------------ bench_diff gate
def test_bench_diff_session_gates(tmp_path):
    """session_serving records gate ABSOLUTELY: cached_speedup >= 5x,
    session_failed_requests == 0, hit-vs-cold bitwise equality."""
    import sys

    sys.path.insert(0, "scripts")
    try:
        import bench_diff
    finally:
        sys.path.pop(0)

    def rec(speedup, failed, bit=True):
        return {
            "metric": "session_serving_cached_speedup",
            "value": speedup,
            "cached_speedup": speedup,
            "bit_identical": bit,
            "session_failed_requests": failed,
            "tier": {"migrations": 1},
        }

    def run(old, new):
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(new))
        return bench_diff.main([str(a), str(b)])

    assert run(rec(8.0, 0), rec(9.0, 0)) == 0
    assert run(rec(8.0, 0), rec(3.0, 0)) == 1      # below the 5x floor
    assert run(rec(8.0, 0), rec(9.0, 2)) == 1      # failed requests
    assert run(rec(8.0, 0), rec(9.0, 0, bit=False)) == 1
