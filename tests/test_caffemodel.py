"""Caffe weight interchange: wire format against google.protobuf, and
layout transposition against NCHW math (torch oracle)."""

import numpy as np
import pytest
import jax.numpy as jnp

from google.protobuf import descriptor_pb2

from sparknet_tpu.proto import caffe_pb, caffemodel, wire
from sparknet_tpu.nets.xlanet import XLANet

T = descriptor_pb2.FieldDescriptorProto


def _get_classes():
    """Dynamic caffe.proto subset via the real protobuf runtime — the
    encoding oracle for our hand-rolled wire reader/writer."""
    from google.protobuf import descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    pool.Add(_build_fdp())
    if hasattr(message_factory, "GetMessageClassesForFiles"):
        classes = message_factory.GetMessageClassesForFiles(
            ["caffe_oracle.proto"], pool
        )
        return {k.split(".")[-1]: v for k, v in classes.items()}
    factory = message_factory.MessageFactory(pool)
    names = ["BlobShape", "BlobProto", "LayerParameter", "NetParameter"]
    return {
        n: factory.GetPrototype(pool.FindMessageTypeByName(f"caffeoracle.{n}"))
        for n in names
    }


def _build_fdp():
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "caffe_oracle.proto"
    fdp.package = "caffeoracle"
    bs = fdp.message_type.add()
    bs.name = "BlobShape"
    f = bs.field.add()
    f.name, f.number, f.type, f.label = "dim", 1, T.TYPE_INT64, T.LABEL_REPEATED
    f.options.packed = True
    bp = fdp.message_type.add()
    bp.name = "BlobProto"
    for name, num in (("num", 1), ("channels", 2), ("height", 3), ("width", 4)):
        f = bp.field.add()
        f.name, f.number, f.type, f.label = name, num, T.TYPE_INT32, T.LABEL_OPTIONAL
    f = bp.field.add()
    f.name, f.number, f.type, f.label = "data", 5, T.TYPE_FLOAT, T.LABEL_REPEATED
    f.options.packed = True
    f = bp.field.add()
    f.name, f.number, f.type, f.label = "shape", 7, T.TYPE_MESSAGE, T.LABEL_OPTIONAL
    f.type_name = ".caffeoracle.BlobShape"
    lp = fdp.message_type.add()
    lp.name = "LayerParameter"
    f = lp.field.add()
    f.name, f.number, f.type, f.label = "name", 1, T.TYPE_STRING, T.LABEL_OPTIONAL
    f = lp.field.add()
    f.name, f.number, f.type, f.label = "type", 2, T.TYPE_STRING, T.LABEL_OPTIONAL
    f = lp.field.add()
    f.name, f.number, f.type, f.label = "blobs", 7, T.TYPE_MESSAGE, T.LABEL_REPEATED
    f.type_name = ".caffeoracle.BlobProto"
    np_ = fdp.message_type.add()
    np_.name = "NetParameter"
    f = np_.field.add()
    f.name, f.number, f.type, f.label = "name", 1, T.TYPE_STRING, T.LABEL_OPTIONAL
    f = np_.field.add()
    f.name, f.number, f.type, f.label = "layer", 100, T.TYPE_MESSAGE, T.LABEL_REPEATED
    f.type_name = ".caffeoracle.LayerParameter"
    return fdp


def _oracle_model(conv_w, conv_b, ip_w, ip_b):
    """Serialize a NetParameter with the real protobuf runtime."""
    C = _get_classes()
    net = C["NetParameter"]()
    net.name = "oracle"
    l1 = net.layer.add()
    l1.name, l1.type = "conv1", "Convolution"
    b = l1.blobs.add()
    b.shape.dim.extend(conv_w.shape)
    b.data.extend(conv_w.reshape(-1).tolist())
    b = l1.blobs.add()
    b.shape.dim.extend(conv_b.shape)
    b.data.extend(conv_b.tolist())
    l2 = net.layer.add()
    l2.name, l2.type = "ip1", "InnerProduct"
    b = l2.blobs.add()
    b.shape.dim.extend(ip_w.shape)
    b.data.extend(ip_w.reshape(-1).tolist())
    b = l2.blobs.add()
    b.shape.dim.extend(ip_b.shape)
    b.data.extend(ip_b.tolist())
    return net.SerializeToString()


NET_TXT = """
name: "tiny"
layer { name: "d" type: "Input" top: "data" top: "label" }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "c1"
        convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 } }
layer { name: "relu1" type: "ReLU" bottom: "c1" top: "c1" }
layer { name: "ip1" type: "InnerProduct" bottom: "c1" top: "ip1"
        inner_product_param { num_output: 5 } }
"""


def _make_net():
    npm = caffe_pb.load_net(NET_TXT, is_path=False)
    shapes = {"data": (2, 6, 6, 3), "label": (2,)}
    return XLANet(npm, "TRAIN", shapes)


def _rand_weights(seed=0):
    rng = np.random.default_rng(seed)
    conv_w = rng.normal(size=(4, 3, 3, 3)).astype(np.float32)  # OIHW
    conv_b = rng.normal(size=(4,)).astype(np.float32)
    ip_w = rng.normal(size=(5, 4 * 6 * 6)).astype(np.float32)  # (out, CHW)
    ip_b = rng.normal(size=(5,)).astype(np.float32)
    return conv_w, conv_b, ip_w, ip_b


def test_wire_decodes_protobuf_encoding():
    conv_w, conv_b, ip_w, ip_b = _rand_weights()
    payload = _oracle_model(conv_w, conv_b, ip_w, ip_b)
    name, blobs = caffemodel.load_caffemodel(payload)
    assert name == "oracle"
    np.testing.assert_array_equal(blobs["conv1"][0], conv_w)
    np.testing.assert_array_equal(blobs["conv1"][1], conv_b)
    np.testing.assert_array_equal(blobs["ip1"][0], ip_w)
    np.testing.assert_array_equal(blobs["ip1"][1], ip_b)


def test_import_matches_nchw_math():
    """Imported weights must reproduce Caffe's NCHW forward bit-for-bit
    (torch conv/linear as the NCHW oracle) — VERDICT missing #4."""
    import torch
    import torch.nn.functional as F

    conv_w, conv_b, ip_w, ip_b = _rand_weights()
    payload = _oracle_model(conv_w, conv_b, ip_w, ip_b)
    net = _make_net()
    imported, _ = caffemodel.import_caffemodel(payload, net)
    params = {
        k: {n: jnp.asarray(a) for n, a in v.items()}
        for k, v in imported.items()
    }

    rng = np.random.default_rng(1)
    x_nchw = rng.normal(size=(2, 3, 6, 6)).astype(np.float32)
    # caffe forward in torch: conv(pad1) -> relu -> flatten CHW -> linear
    tx = torch.from_numpy(x_nchw)
    ty = F.relu(F.conv2d(tx, torch.from_numpy(conv_w),
                         torch.from_numpy(conv_b), padding=1))
    t_out = (ty.flatten(1) @ torch.from_numpy(ip_w).T
             + torch.from_numpy(ip_b)).numpy()

    batch = {
        "data": jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1))),
        "label": jnp.zeros((2,), jnp.int32),
    }
    blobs, _ = net.apply(params, {}, batch, train=False, rng=None)
    np.testing.assert_allclose(
        np.asarray(blobs["ip1"]), t_out, rtol=1e-4, atol=1e-5
    )


def test_export_round_trips(tmp_path):
    conv_w, conv_b, ip_w, ip_b = _rand_weights(3)
    payload = _oracle_model(conv_w, conv_b, ip_w, ip_b)
    net = _make_net()
    imported, _ = caffemodel.import_caffemodel(payload, net)
    out = str(tmp_path / "rt.caffemodel")
    caffemodel.export_caffemodel(out, net, imported)
    # the real protobuf runtime must parse our writer's output
    C = _get_classes()
    msg = C["NetParameter"]()
    msg.ParseFromString(open(out, "rb").read())
    got = {l.name: l for l in msg.layer}
    w = np.asarray(got["conv1"].blobs[0].data, np.float32).reshape(
        tuple(got["conv1"].blobs[0].shape.dim)
    )
    np.testing.assert_allclose(w, conv_w, rtol=1e-6)
    w = np.asarray(got["ip1"].blobs[0].data, np.float32).reshape(
        tuple(got["ip1"].blobs[0].shape.dim)
    )
    np.testing.assert_allclose(w, ip_w, rtol=1e-6)


def test_binaryproto_mean(tmp_path):
    C = _get_classes()
    mean_chw = np.arange(3 * 4 * 5, dtype=np.float32).reshape(3, 4, 5)
    b = C["BlobProto"]()
    b.channels, b.height, b.width = 3, 4, 5
    b.num = 1
    b.data.extend(mean_chw.reshape(-1).tolist())
    out = caffemodel.load_binaryproto_mean(b.SerializeToString())
    np.testing.assert_array_equal(out, np.transpose(mean_chw, (1, 2, 0)))


def test_solver_export_import_round_trip(tmp_path):
    """Solver.export_weights -> Solver.load_weights reproduces params
    exactly (the .caffemodel interchange at the app level)."""
    from sparknet_tpu.solver.trainer import Solver

    sp = caffe_pb.load_solver(
        "base_lr: 0.01 lr_policy: 'fixed' max_iter: 10", is_path=False
    )
    shapes = {"data": (2, 6, 6, 3), "label": (2,)}
    npm = caffe_pb.load_net(NET_TXT, is_path=False)
    s1 = Solver(sp, shapes, net_param=npm, seed=1)
    path = str(tmp_path / "w.caffemodel")
    s1.export_weights(path)

    s2 = Solver(sp, shapes, net_param=npm, seed=2)  # different init
    s2.load_weights(path)
    for layer, ps in s1.params.items():
        for name, arr in ps.items():
            np.testing.assert_allclose(
                np.asarray(s2.params[layer][name]), np.asarray(arr),
                rtol=1e-6, err_msg=f"{layer}.{name}",
            )


def test_legacy_v1_layers_field():
    """V1 nets store weights in NetParameter.layers (field 2)."""
    conv_w = np.ones((2, 3, 1, 1), np.float32)
    blob = (
        caffemodel.wire.encode_packed_floats(5, conv_w.reshape(-1))
        + wire.encode_bytes_field(
            7, b"".join(wire.encode_varint_field(1, d) for d in conv_w.shape)
        )
    )
    v1_layer = (
        wire.encode_string_field(4, "convA")
        + wire.encode_bytes_field(6, blob)
    )
    net = wire.encode_string_field(1, "v1net") + wire.encode_bytes_field(
        2, v1_layer
    )
    name, blobs = caffemodel.load_caffemodel(net)
    assert name == "v1net"
    np.testing.assert_array_equal(blobs["convA"][0], conv_w)


def test_prelu_bias_embed_interchange(tmp_path):
    """Single-blob layers with non-'weight' param names (PReLU slope,
    Bias bias) and Embed round-trip through .caffemodel import/export
    into the params XLANet actually reads."""
    net_txt = """
name: "pb"
layer { name: "data" type: "Input" top: "data" }
layer { name: "ids" type: "Input" top: "ids" }
layer { name: "act" type: "PReLU" bottom: "data" top: "act" }
layer { name: "sh" type: "Bias" bottom: "act" top: "sh" }
layer { name: "emb" type: "Embed" bottom: "ids" top: "emb"
        embed_param { num_output: 3 input_dim: 5
          weight_filler { type: "gaussian" std: 1.0 } } }
"""
    npm = caffe_pb.load_net(net_txt, is_path=False)
    net = XLANet(npm, "TRAIN", {"data": (2, 4), "ids": (2,)})
    import jax

    params, state = net.init(jax.random.PRNGKey(0))
    # give recognisable values, export, then re-import
    params = {k: {n: jnp.asarray(np.arange(v.size, dtype=np.float32).reshape(v.shape) + i)
                  for i, (n, v) in enumerate(sorted(p.items()))}
              for k, p in params.items()}
    out = str(tmp_path / "pb.caffemodel")
    caffemodel.export_caffemodel(out, net, params)
    imported, _ = caffemodel.import_caffemodel(open(out, "rb").read(), net)
    assert set(imported["act"]) == {"slope"}
    assert set(imported["sh"]) == {"bias"}
    np.testing.assert_allclose(
        imported["act"]["slope"], np.asarray(params["act"]["slope"]).reshape(-1)
    )
    np.testing.assert_allclose(
        imported["sh"]["bias"], np.asarray(params["sh"]["bias"]).reshape(-1)
    )
    # Embed keeps its (input_dim, num_output) table through the generic path
    got = caffemodel.merge_into(jax.device_get(net.init(jax.random.PRNGKey(1))[0]), imported)
    assert got["emb"]["weight"].shape == (5, 3)
    np.testing.assert_allclose(
        got["emb"]["weight"], np.asarray(params["emb"]["weight"]), rtol=1e-6
    )


def test_lstm_caffemodel_layout_round_trip(tmp_path):
    """Recurrent blobs are (out, in) in Caffe; import must transpose to
    our (in, out) and export must invert it — verified by writing a
    Caffe-layout model by hand, importing, and re-exporting."""
    import jax

    net_txt = """
name: "seq"
layer { name: "x" type: "Input" top: "x" }
layer { name: "lstm" type: "LSTM" bottom: "x" top: "lstm"
        recurrent_param { num_output: 3
          weight_filler { type: "xavier" } } }
"""
    npm = caffe_pb.load_net(net_txt, is_path=False)
    net = XLANet(npm, "TRAIN", {"x": (4, 2, 5)})
    rng = np.random.default_rng(9)
    w_xc = rng.normal(size=(12, 5)).astype(np.float32)   # Caffe (4H, in)
    b = rng.normal(size=(12,)).astype(np.float32)
    w_hc = rng.normal(size=(12, 3)).astype(np.float32)   # Caffe (4H, H)
    layer_msg = wire.encode_string_field(1, "lstm") + wire.encode_string_field(
        2, "LSTM"
    )
    for arr in (w_xc, b, w_hc):
        blob = caffemodel.wire.encode_packed_floats(5, arr.reshape(-1)) + \
            wire.encode_bytes_field(
                7,
                b"".join(wire.encode_varint_field(1, d) for d in arr.shape),
            )
        layer_msg += wire.encode_bytes_field(7, blob)
    payload = wire.encode_bytes_field(100, layer_msg)
    imported, _ = caffemodel.import_caffemodel(payload, net)
    np.testing.assert_allclose(imported["lstm"]["weight"], w_xc.T)
    np.testing.assert_allclose(imported["lstm"]["hidden_weight"], w_hc.T)
    np.testing.assert_allclose(imported["lstm"]["bias"], b)
    # shapes now match the net's own params
    init_params, _ = net.init(jax.random.PRNGKey(0))
    for k, v in imported["lstm"].items():
        assert v.shape == tuple(init_params["lstm"][k].shape), k

    out = str(tmp_path / "seq.caffemodel")
    caffemodel.export_caffemodel(
        out, net, {"lstm": {k: jnp.asarray(v) for k, v in imported["lstm"].items()}}
    )
    _, blobs = caffemodel.load_caffemodel(open(out, "rb").read())
    np.testing.assert_allclose(blobs["lstm"][0], w_xc, rtol=1e-6)
    np.testing.assert_allclose(blobs["lstm"][2], w_hc, rtol=1e-6)


def test_load_weights_comma_list(tmp_path):
    """caffe binary semantics: --weights a.caffemodel,b.caffemodel
    overlays in order, later files winning on overlapping layers."""
    import jax

    from sparknet_tpu.proto.caffe_pb import SolverParameter
    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "two"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ipA" type: "InnerProduct" bottom: "data" top: "ipA"
        inner_product_param { num_output: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "ipB" type: "InnerProduct" bottom: "ipA" top: "ipB"
        inner_product_param { num_output: 2
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ipB" bottom: "label" top: "loss" }
"""
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", max_iter=1)
    sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
    solver = Solver(sp, {"data": (2, 4), "label": (2,)})
    from sparknet_tpu.proto import caffemodel as cm

    # file 1: sets both layers; file 2: overrides only ipB
    p1 = {"ipA": {"weight": np.full((4, 3), 1.0, np.float32)},
          "ipB": {"weight": np.full((3, 2), 2.0, np.float32)}}
    p2 = {"ipB": {"weight": np.full((3, 2), 9.0, np.float32)}}
    f1, f2 = str(tmp_path / "a.caffemodel"), str(tmp_path / "b.caffemodel")
    cm.export_caffemodel(f1, solver.train_net, p1)
    cm.export_caffemodel(f2, solver.train_net, p2)
    solver.load_weights(f"{f1},{f2}")
    got = jax.device_get(solver.params)
    np.testing.assert_allclose(got["ipA"]["weight"], 1.0)
    np.testing.assert_allclose(got["ipB"]["weight"], 9.0)  # later wins
