"""Checkpoint/resume: Caffe ``.solverstate`` parity (SURVEY.md §5).

The contract: save at iteration k, restore into a FRESH solver, feed the
same batches — every parameter, optimizer slot, and metric must be
bit-identical to the uninterrupted run.
"""

from pathlib import Path

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.solver import snapshot
from sparknet_tpu.solver.trainer import Solver
from sparknet_tpu.parallel import ParallelSolver, make_mesh

REPO = Path(__file__).resolve().parents[1]
ZOO = REPO / "sparknet_tpu" / "models" / "prototxt"


def test_save_state_round_trip(tmp_path):
    tree = {
        "a": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
        "b": [np.ones(2, np.int32), (np.zeros(1), None)],
        "c": {"nested": {"deep": np.float64(3.5)}},
    }
    path = str(tmp_path / "st.npz")
    snapshot.save_state(path, tree=tree, it=42, scalar=1.5, name="x")
    out = snapshot.load_state(path)
    assert out["it"] == 42 and out["scalar"] == 1.5 and out["name"] == "x"
    np.testing.assert_array_equal(out["tree"]["a"]["w"], tree["a"]["w"])
    np.testing.assert_array_equal(out["tree"]["b"][0], tree["b"][0])
    assert isinstance(out["tree"]["b"][1], tuple)
    assert out["tree"]["b"][1][1] is None
    np.testing.assert_array_equal(
        out["tree"]["c"]["nested"]["deep"], tree["c"]["nested"]["deep"]
    )


def _batches(n, bs=8, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "data": jnp.asarray(rng.normal(size=(bs, 32, 32, 3)), jnp.float32),
            "label": jnp.asarray(rng.integers(0, 10, bs), jnp.int32),
        }
        for _ in range(n)
    ]


def _make_cifar_solver(parallel=None, tau=1, bs=8):
    sp = caffe_pb.load_solver(str(ZOO / "cifar10_quick_solver.prototxt"))
    sp.base_lr = 0.01
    shapes = {"data": (bs, 32, 32, 3), "label": (bs,)}
    if parallel is None:
        return Solver(sp, shapes, solver_dir=str(REPO))
    return ParallelSolver(
        sp, shapes, solver_dir=str(REPO),
        mesh=make_mesh({"dp": 2}, jax.devices()[:2]), mode=parallel, tau=tau,
    )


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (pa, xa), (pb, xb) in zip(la, lb):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb),
                                      err_msg=str(pa))


@pytest.mark.parametrize("mode,tau", [(None, 1), ("sync", 1), ("local", 2)])
def test_resume_is_bit_identical(tmp_path, mode, tau):
    batches = _batches(8, seed=5)
    path = str(tmp_path / "ck.solverstate.npz")

    # uninterrupted run: 4 + 4
    s1 = _make_cifar_solver(mode, tau)
    s1.step(iter(batches[:4]), 4)
    s1.save(path)
    s1.step(iter(batches[4:]), 4)

    # fresh solver, restored mid-run, fed the same tail
    s2 = _make_cifar_solver(mode, tau)
    s2.restore(path)
    assert s2.iter == 4
    s2.step(iter(batches[4:]), 4)

    assert s2.iter == s1.iter
    _assert_trees_equal(s1.params, s2.params)
    _assert_trees_equal(s1.opt_state, s2.opt_state)
    _assert_trees_equal(s1.state, s2.state)
    np.testing.assert_array_equal(np.asarray(s1.rng), np.asarray(s2.rng))


def test_latest_solverstate(tmp_path):
    prefix = str(tmp_path / "run")
    assert snapshot.latest_solverstate(prefix) is None
    for it in (2, 10, 6):
        open(f"{prefix}_iter_{it}.solverstate.npz", "wb").close()
    open(f"{prefix}_iter_99.npz", "wb").close()  # weights-only: ignored
    assert snapshot.latest_solverstate(prefix) == (
        f"{prefix}_iter_10.solverstate.npz"
    )


@pytest.mark.slow
def test_cifar_app_restore_cli(tmp_path):
    """The CifarApp --restore flag end-to-end: snapshot at iter 2, resume
    to 4, matching the uninterrupted params exactly."""
    from sparknet_tpu.apps import cifar_app

    prefix = str(tmp_path / "snap")
    common = [
        "--synthetic", "--synthetic-n", "1000", "--batch-size", "8",
        "--seed", "7",
    ]

    def run(extra):
        import sys

        solver_txt = tmp_path / "solver.prototxt"
        base = (ZOO / "cifar10_quick_solver.prototxt").read_text()
        base += f"\nsnapshot: 2\nsnapshot_prefix: \"{prefix}\"\n"
        solver_txt.write_text(base)
        return cifar_app.main(
            ["--solver", str(solver_txt), "--max-iter", "4"] + common + extra
        )

    run([])  # writes snap_iter_2.solverstate.npz and snap_iter_4...
    import sparknet_tpu.nets.weights as W

    p_full = W.load_npz(f"{prefix}_iter_4.npz")
    # wipe the iter-4 artifacts, resume from iter 2
    run(["--restore", f"{prefix}_iter_2.solverstate.npz"])
    p_resumed = W.load_npz(f"{prefix}_iter_4.npz")
    _assert_trees_equal(p_full, p_resumed)

    # --auto-resume picks the newest remaining solverstate (iter 2 after
    # the iter-4 one "is lost in the preemption") and re-reaches iter 4
    import os

    os.remove(f"{prefix}_iter_4.npz")
    os.remove(f"{prefix}_iter_4.solverstate.npz")
    run(["--auto-resume"])
    p_auto = W.load_npz(f"{prefix}_iter_4.npz")
    _assert_trees_equal(p_full, p_auto)


def test_orbax_solverstate_round_trip(tmp_path):
    """--snapshot-format orbax: save/restore through the Orbax backend
    is bit-identical to continuing the uninterrupted run, exactly like
    the npz path."""
    import os

    pytest.importorskip("orbax.checkpoint")
    import jax
    import jax.numpy as jnp

    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver import snapshot
    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "ob"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""

    def make():
        sp = caffe_pb.load_solver(
            "base_lr: 0.1\nlr_policy: \"fixed\"\nmomentum: 0.9\n"
            "max_iter: 10\nsolver_type: ADAM\n",
            is_path=False,
        )
        sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
        return Solver(sp, {"data": (4, 6), "label": (4,)})

    def feed():
        rng = np.random.default_rng(3)
        while True:
            yield {
                "data": rng.normal(size=(4, 6)).astype(np.float32),
                "label": rng.integers(0, 3, 4).astype(np.int32),
            }

    a = make()
    fa = feed()
    a.step(fa, 4)
    path = str(tmp_path / f"ob_iter_4{snapshot.ORBAX_SUFFIX}")
    a.save(path)
    assert os.path.isdir(path)  # orbax checkpoints are directories
    a.step(fa, 4)  # uninterrupted continuation

    b = make()
    fb = feed()
    b.restore(path, fb)
    assert b.iter == 4
    b.step(fb, 4)
    for layer in a.params:
        for name in a.params[layer]:
            np.testing.assert_array_equal(
                np.asarray(a.params[layer][name]),
                np.asarray(b.params[layer][name]),
            )
    # auto-resume finds the orbax checkpoint too
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        assert snapshot.latest_solverstate("ob") == f"ob_iter_4{snapshot.ORBAX_SUFFIX}"
    finally:
        os.chdir(cwd)


@pytest.mark.slow  # two subprocess training runs (~25s warm)
def test_sigterm_preemption_snapshot_and_resume(tmp_path):
    """Preemption grace end-to-end (SURVEY.md §5 failure handling): a
    real SIGTERM against the CifarApp process must finish the in-flight
    iteration, write a solverstate, and exit 0; a relaunch with
    --auto-resume must pick that snapshot up and run to completion."""
    import glob
    import os
    import re
    import signal
    import subprocess
    import sys
    import time as _time

    prefix = str(tmp_path / "pre")
    solver_txt = tmp_path / "solver.prototxt"
    base = (ZOO / "cifar10_quick_solver.prototxt").read_text()
    base += f'\nsnapshot_prefix: "{prefix}"\n'
    solver_txt.write_text(base)
    base_cmd = [
        sys.executable, "-m", "sparknet_tpu.apps.cifar_app",
        "--solver", str(solver_txt), "--synthetic", "--synthetic-n", "1000",
        "--batch-size", "8", "--seed", "3",
    ]
    cmd = base_cmd + ["--max-iter", "5000"]
    env = dict(os.environ)
    # force the subprocess onto CPU: repo-only PYTHONPATH (the axon
    # tunnel plugin on the default path hangs jax.devices()) + explicit
    # platform pin
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"  # readline() must see lines promptly
    import threading

    proc = subprocess.Popen(
        cmd, env=env, cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # a reader thread drains stdout so the main thread can enforce the
    # deadline even if the subprocess wedges before printing anything
    lines = []
    started = threading.Event()

    def _drain():
        for line in proc.stdout:
            lines.append(line)
            if "Test net output" in line:
                started.set()

    reader = threading.Thread(target=_drain, daemon=True)
    reader.start()
    try:
        assert started.wait(timeout=300), "".join(lines)
        _time.sleep(5)  # let a few training iterations run
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            raise AssertionError(
                "SIGTERM did not stop the app:\n" + "".join(lines)
            )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        reader.join(timeout=30)
    full = "".join(lines)
    assert proc.returncode == 0, full
    assert "SIGTERM: preempted at iteration" in full, full
    states = glob.glob(f"{prefix}_iter_*.solverstate.npz")
    assert states, full
    it = max(
        int(re.search(r"_iter_(\d+)\.solverstate", s).group(1))
        for s in states
    )

    # relaunch with --auto-resume: must restore and finish cleanly
    out2 = subprocess.run(
        base_cmd + ["--max-iter", str(it + 2), "--auto-resume"],
        env=env, cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=300,
    )
    assert out2.returncode == 0, out2.stdout
    assert "Restoring previous solver status" in out2.stdout, out2.stdout
    assert "Optimization Done" in out2.stdout, out2.stdout


def test_preemption_grace_noop_off_main_thread():
    """Embedded use: installing a signal handler off the main thread is
    illegal; the context manager must no-op cleanly, not raise."""
    import threading

    from sparknet_tpu.solver.preempt import preemption_grace

    class Dummy:
        stop_requested = False

    results = {}

    def run():
        try:
            with preemption_grace(Dummy()):
                results["entered"] = True
        except Exception as e:  # pragma: no cover
            results["error"] = e

    # daemon: if the context ever wedges, the join timeout must report
    # the failure instead of blocking interpreter exit forever
    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=30)
    assert results.get("entered") is True
    assert "error" not in results, results
