"""MoE FFN with expert parallelism: sharded == single-device oracle."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from sparknet_tpu.parallel import comm
from sparknet_tpu.parallel.mesh import make_mesh
from sparknet_tpu.parallel.moe import init_moe_params, moe_ffn, moe_pspecs


def setup(t=64, h=16, f=32, e=8, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(t, h)), jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(seed), h, f, e)
    return x, params


def test_moe_routes_and_shapes():
    x, params = setup()
    out, aux = moe_ffn(x, params, capacity_factor=2.0)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    # aux loss near 1.0 for near-uniform routing at init
    assert 0.5 < float(aux) < 4.0


def test_moe_capacity_drop_zero_rows():
    """capacity_factor tiny -> most tokens dropped -> zero expert output."""
    x, params = setup(t=64, e=2)
    out, _ = moe_ffn(x, params, capacity_factor=0.05)  # cap = 2 per expert
    zeros = np.sum(np.abs(np.asarray(out)).max(-1) == 0.0)
    assert zeros >= 64 - 2 * 2 * 2  # at most 2*cap kept per expert


def test_moe_ep_matches_single_device():
    """ep=4 sharded forward + grads == unsharded."""
    x, params = setup(t=64, h=16, f=32, e=8)
    mesh = make_mesh({"ep": 4}, jax.devices()[:4])
    pspecs = moe_pspecs()

    def loss_single(params, x):
        out, aux = moe_ffn(x, params, capacity_factor=2.0)
        return jnp.sum(jnp.sin(out)) + 0.01 * aux

    def loss_ep(params, x):
        def inner(params, x):
            out, aux = moe_ffn(x, params, ep_axis="ep", capacity_factor=2.0)
            return jnp.sum(jnp.sin(out)) + 0.01 * aux

        return comm.shard_map(
            inner, mesh=mesh, in_specs=(pspecs, P()), out_specs=P(),
        )(params, x)

    l0 = float(jax.jit(loss_single)(params, x))
    l1 = float(jax.jit(loss_ep)(params, x))
    np.testing.assert_allclose(l1, l0, rtol=1e-5)

    g0 = jax.grad(loss_single)(params, x)
    g1 = jax.grad(loss_ep)(params, x)
    for name in g0:
        np.testing.assert_allclose(
            np.asarray(g1[name]), np.asarray(g0[name]),
            rtol=1e-4, atol=1e-5, err_msg=name,
        )


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_sort_dispatch_matches_dense(top_k):
    """The O(T·h) sort path must be numerically identical to the dense
    one-hot path — forward and gradients — for both routing modes."""
    x, params = setup(t=64, h=16, f=32, e=8)

    def loss(params, x, impl):
        out, aux = moe_ffn(
            x, params, capacity_factor=1.25, top_k=top_k, dispatch=impl,
            z_loss_weight=1e-3,
        )
        return jnp.sum(jnp.sin(out)) + 0.01 * aux

    ld = float(jax.jit(partial(loss, impl="dense"))(params, x))
    ls = float(jax.jit(partial(loss, impl="sort"))(params, x))
    np.testing.assert_allclose(ls, ld, rtol=1e-5)
    gd = jax.grad(loss)(params, x, "dense")
    gs = jax.grad(loss)(params, x, "sort")
    for name in gd:
        np.testing.assert_allclose(
            np.asarray(gs[name]), np.asarray(gd[name]),
            rtol=1e-4, atol=1e-6, err_msg=name,
        )


@pytest.mark.slow
@pytest.mark.parametrize("dispatch", ["dense", "sort"])
def test_moe_top2_ep_matches_single_device(dispatch):
    """top-2 + z-loss under ep=4 shard_map == unsharded, both dispatches."""
    x, params = setup(t=64, h=16, f=32, e=8)
    mesh = make_mesh({"ep": 4}, jax.devices()[:4])
    kw = dict(
        capacity_factor=2.0, top_k=2, z_loss_weight=1e-3, dispatch=dispatch
    )

    def loss_single(params, x):
        out, aux = moe_ffn(x, params, **kw)
        return jnp.sum(jnp.sin(out)) + 0.01 * aux

    def loss_ep(params, x):
        def inner(params, x):
            out, aux = moe_ffn(x, params, ep_axis="ep", **kw)
            return jnp.sum(jnp.sin(out)) + 0.01 * aux

        return comm.shard_map(
            inner, mesh=mesh, in_specs=(moe_pspecs(), P()), out_specs=P(),
        )(params, x)

    l0 = float(jax.jit(loss_single)(params, x))
    l1 = float(jax.jit(loss_ep)(params, x))
    np.testing.assert_allclose(l1, l0, rtol=1e-5)
    g0 = jax.grad(loss_single)(params, x)
    g1 = jax.grad(loss_ep)(params, x)
    for name in g0:
        np.testing.assert_allclose(
            np.asarray(g1[name]), np.asarray(g0[name]),
            rtol=1e-4, atol=1e-5, err_msg=name,
        )


def test_moe_top2_gates_renormalised():
    """top-2 output ~= gate-weighted mix: with capacity ample, every
    token gets contributions from both its experts and the gates sum
    to 1, so scaling x scales out through the experts only."""
    x, params = setup(t=32, h=16, f=32, e=4)
    out1, _ = moe_ffn(x, params, capacity_factor=4.0, top_k=2)
    # Each token's row should be nonzero (no drops at cf=4)
    assert np.all(np.abs(np.asarray(out1)).max(-1) > 0)


def test_moe_rejects_bad_dispatch():
    x, params = setup()
    with pytest.raises(ValueError):
        moe_ffn(x, params, dispatch="hash")


def test_moe_rejects_indivisible_experts():
    x, params = setup(e=6)
    mesh = make_mesh({"ep": 4}, jax.devices()[:4])
    with pytest.raises(ValueError):
        comm.shard_map(
            lambda p, x: moe_ffn(x, p, ep_axis="ep")[0],
            mesh=mesh, in_specs=(moe_pspecs(), P()), out_specs=P(),
        )(params, x)
