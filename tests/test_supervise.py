"""supervise/ — the Spark-driver-equivalent relaunch loop (ISSUE 4).

The acceptance bar: a chaos-killed training child is relaunched with
``--auto-resume`` and the final weights are bit-identical to an
uninterrupted run; a permanently flapping child exhausts the restart
budget, exits nonzero, and leaves a complete machine-readable failure
report; elastic degrade drops a repeatedly-blamed rank and scales back
up after a healthy degraded generation; ``--supervise`` off adds
nothing to the train path.  All CPU-only, plain subprocesses, no
``jax.shard_map`` anywhere.
"""

import json
import os
import signal
import sys
import tempfile

import numpy as np
import pytest

from sparknet_tpu import chaos
from sparknet_tpu.supervise import records
from sparknet_tpu.supervise.metrics import METRICS
from sparknet_tpu.supervise.policy import (
    Config,
    ElasticState,
    RestartPolicy,
    classify_exit,
)
from sparknet_tpu.supervise.supervisor import (
    REPORT_NAME,
    Supervisor,
    strip_flag,
)

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_supervise_child.py")

NET_TXT = """
name: "tiny"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 10
          weight_filler { type: "gaussian" std: 0.05 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""


@pytest.fixture(autouse=True)
def _isolation(monkeypatch):
    """No chaos plan, supervisor metrics, or supervision env may leak
    between tests (or in from the outer environment)."""
    for var in (
        "SPARKNET_SUPERVISE", "SPARKNET_SUPERVISE_DIR",
        "SPARKNET_SUPERVISE_GEN", "SPARKNET_ELASTIC_RESUME",
        "SPARKNET_RUN_DIR",
    ):
        monkeypatch.delenv(var, raising=False)
    chaos.clear()
    METRICS.reset()
    yield
    chaos.clear()
    METRICS.reset()


def _cfg(**kw):
    base = dict(
        max_restarts=3, backoff_s=0.01, max_backoff_s=0.02,
        flap_limit=20, flap_window_s=300.0, degrade_after=2,
        healthy_s=0.5, kill_grace_s=5.0,
    )
    base.update(kw)
    return Config(**base)


# ---------------------------------------------------------------- records
def test_failure_records_are_gated_and_round_trip(tmp_path, monkeypatch):
    # unsupervised: every writer is a no-op
    assert records.write_failure_record(
        process_id=0, kind="x", reason="y"
    ) is None
    monkeypatch.setenv(records.RECORD_DIR_ENV, str(tmp_path))
    monkeypatch.setenv(records.GENERATION_ENV, "2")
    path = records.write_failure_record(
        process_id=1, kind="test", reason="because", exit_code=5
    )
    assert path and os.path.exists(path)
    (rec,) = records.read_failure_records(str(tmp_path))
    assert rec["process_id"] == 1 and rec["generation"] == 2
    assert rec["kind"] == "test" and rec["exit_code"] == 5
    # generation filter
    assert records.read_failure_records(str(tmp_path), generation=3) == []
    # crash records skip clean SystemExit but keep real errors
    assert records.write_crash_record(SystemExit(0)) is None
    assert records.write_crash_record(RuntimeError("boom")) is not None


def test_progress_plumbing_names_last_completed_iteration():
    class FakeSolver:
        iter = 17

    s = FakeSolver()
    records.publish_progress(s)
    assert records.last_completed_iteration() == 17
    del s  # weakref: a dead solver is not progress
    assert records.last_completed_iteration() is None


# ----------------------------------------------------------------- policy
def test_classify_exit_taxonomy():
    assert classify_exit(0) == "clean"
    assert classify_exit(43) == "peer_failure"  # multihost.EXIT_PEER_FAILURE
    assert classify_exit(-signal.SIGKILL) == "signal"
    assert classify_exit(3) == "error"


def test_restart_policy_budget_backoff_and_flap():
    p = RestartPolicy(_cfg(max_restarts=2, backoff_s=1.0, max_backoff_s=3.0))
    p.note_failure(0.0)
    verdict, sleep1, _ = p.decide()
    assert verdict == "restart" and 0.5 <= sleep1 <= 1.0
    p.note_failure(1.0)
    verdict, sleep2, _ = p.decide()
    assert verdict == "restart" and 1.0 <= sleep2 <= 2.0
    p.note_failure(2.0)
    verdict, _, why = p.decide()
    assert verdict == "give_up" and "budget" in why
    # a healthy run resets the budget (per-incident semantics): the
    # next incident restarts again, from the base backoff rung
    p.note_healthy_run()
    p.note_failure(3.0)
    verdict, sleep4, _ = p.decide()
    assert verdict == "restart" and 0.5 <= sleep4 <= 1.0
    flappy = RestartPolicy(_cfg(max_restarts=100, flap_limit=3))
    for t in (0.0, 1.0):
        flappy.note_failure(t)
        assert flappy.decide()[0] == "restart"
    flappy.note_failure(2.0)
    verdict, _, why = flappy.decide()
    assert verdict == "give_up" and "flapping" in why


def test_elastic_state_degrades_and_scales_up():
    e = ElasticState(_cfg(degrade_after=2), full_width=3)
    assert e.next_width(3, blamed=1, was_healthy=False) == (3, None)
    assert e.next_width(3, blamed=1, was_healthy=False) == (2, "degrade")
    # a healthy degraded generation earns full width back
    assert e.next_width(2, blamed=0, was_healthy=True) == (3, "scale_up")
    # blame must be CONSECUTIVE on the same rank
    e2 = ElasticState(_cfg(degrade_after=2), full_width=2)
    assert e2.next_width(2, blamed=1, was_healthy=False) == (2, None)
    assert e2.next_width(2, blamed=0, was_healthy=False) == (2, None)
    assert e2.next_width(2, blamed=0, was_healthy=False) == (1, "degrade")


# ------------------------------------------------------------- supervisor
def test_flapping_child_exhausts_budget_and_leaves_full_report(tmp_path):
    """Acceptance: a permanently failing child exits nonzero through
    the supervisor and the report is complete and machine-readable."""
    sup = Supervisor(
        [sys.executable, "-c", "import sys; sys.exit(3)"],
        run_dir=str(tmp_path), config=_cfg(max_restarts=2),
        auto_resume=False,
    )
    code = sup.run()
    assert code == 3
    with open(tmp_path / REPORT_NAME) as fh:
        report = json.load(fh)
    assert report["final_status"] == "gave_up"
    gens = report["generations"]
    assert len(gens) == 3  # initial + 2 restarts, all classified
    for g in gens:
        assert g["exits"][0]["class"] == "error"
        # the child never writes a record; the supervisor synthesizes
        assert g["records"] and g["records"][0]["kind"].startswith(
            "synthesized."
        )
    assert report["metrics"]["restarts"] == 2
    assert report["metrics"]["records_synthesized"] == 3
    assert METRICS.count("give_ups") == 1


def test_sigkilled_child_is_classified_as_signal(tmp_path):
    env = dict(os.environ, TEST_CHILD_PLAN="sigkill,ok")
    sup = Supervisor(
        [sys.executable, CHILD], run_dir=str(tmp_path), config=_cfg(),
        auto_resume=False, env=env,
    )
    assert sup.run() == 0
    with open(tmp_path / REPORT_NAME) as fh:
        report = json.load(fh)
    first = report["generations"][0]
    assert first["exits"][0]["class"] == "signal"
    assert first["exits"][0]["returncode"] == -signal.SIGKILL
    (rec,) = first["records"]
    assert rec["kind"] == "synthesized.signal"
    assert "signal 9" in rec["reason"]


def test_elastic_degrade_then_scale_up(tmp_path):
    """Failures attributed to rank 1 twice -> relaunch one narrower
    (with SPARKNET_ELASTIC_RESUME exported); a healthy degraded
    generation earns the width back."""
    env = dict(
        os.environ,
        TEST_CHILD_PLAN="crash1,crash1,healthy-crash,ok",
        TEST_CHILD_HEALTHY_S="0.6",
    )
    sup = Supervisor(
        [sys.executable, CHILD], num_procs=2, run_dir=str(tmp_path),
        config=_cfg(), auto_resume=False, env=env,
    )
    assert sup.run() == 0
    with open(tmp_path / REPORT_NAME) as fh:
        report = json.load(fh)
    gens = report["generations"]
    assert [g["width"] for g in gens] == [2, 2, 1, 2]
    assert [g["action"] for g in gens] == [None, None, "degrade", "scale_up"]
    assert [g.get("blamed_rank") for g in gens[:3]] == [1, 1, 0]
    # the degraded child saw the elastic-resume contract
    assert sup._base_env["SPARKNET_ELASTIC_RESUME"] == "0"  # back at full
    assert METRICS.count("degraded_relaunches") == 1
    assert METRICS.count("scale_ups") == 1
    assert METRICS.count("restarts") == 3


def test_verify_resume_walks_past_torn_snapshot(tmp_path):
    """supervisor.resume_torn chaos: the newest solverstate is torn
    between crash and relaunch; the pre-relaunch verify must count it
    and land on the older intact snapshot."""
    from sparknet_tpu.solver import snapshot

    prefix = str(tmp_path / "run")
    for it in (2, 4):
        snapshot.save_state(
            f"{prefix}_iter_{it}.solverstate.npz",
            tree={"w": np.arange(6, dtype=np.float32) + it}, it=it,
        )
    chaos.install("supervisor.resume_torn@index=0")
    sup = Supervisor(
        [sys.executable, "-c", "pass"], run_dir=str(tmp_path),
        snapshot_prefix=prefix, config=_cfg(), auto_resume=False,
    )
    resume = sup._verify_resume(0)
    assert resume is not None
    it, path = resume
    assert it == 2 and path.endswith("_iter_2.solverstate.npz")
    # the newest really was torn by the chaos point
    with pytest.raises(snapshot.SnapshotError):
        snapshot.load_state(f"{prefix}_iter_4.solverstate.npz")
    assert METRICS.count("torn_snapshots") == 1
    assert METRICS.count("verified_resumes") == 1
    assert chaos.METRICS.snapshot()["fires"]["supervisor.resume_torn"] == 1


def test_strip_flag_both_spellings():
    assert strip_flag(["a", "--chaos", "x", "b"], "--chaos", True) == ["a", "b"]
    assert strip_flag(["a", "--chaos=x", "b"], "--chaos", True) == ["a", "b"]
    assert strip_flag(["--supervise", "b"], "--supervise") == ["b"]
    assert strip_flag(["b"], "--supervise") == ["b"]


def test_relaunch_disarms_chaos_and_appends_auto_resume():
    sup = Supervisor(
        ["prog", "--chaos=supervisor.child_crash@after=4", "--x"],
        num_procs=1,
    )
    assert sup._child_argv(0) == [
        "prog", "--chaos=supervisor.child_crash@after=4", "--x"
    ]
    assert sup._child_argv(1) == ["prog", "--x", "--auto-resume"]
    env = sup._child_env(1, 1, None)
    assert env["SPARKNET_CHAOS"] == ""
    assert env["SPARKNET_SUPERVISE"] == "0"  # children never recurse
    assert env[records.GENERATION_ENV] == "1"


def test_elastic_weights_only_restore_reinits_opt_state(tmp_path):
    """The degraded relaunch's restore contract: params/iter/rng come
    back, optimizer slots re-initialize (the snapshot's slots may be
    laid out for a dp width that no longer exists)."""
    import jax

    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.trainer import Solver

    sp_txt = (
        'base_lr: 0.1\nlr_policy: "fixed"\nmomentum: 0.9\nmax_iter: 8\n'
    )

    def make_solver():
        sp = caffe_pb.load_solver(sp_txt, is_path=False)
        sp.net_param = caffe_pb.load_net(NET_TXT, is_path=False)
        return Solver(sp, {"data": (8, 6), "label": (8,)})

    rng = np.random.default_rng(0)
    batches = [
        {
            "data": rng.normal(size=(8, 6)).astype(np.float32),
            "label": rng.integers(0, 10, 8).astype(np.int32),
        }
        for _ in range(2)
    ]
    s1 = make_solver()
    s1.step(iter(batches), 2)
    path = str(tmp_path / "st.solverstate.npz")
    s1.save(path)

    s2 = make_solver()
    s2.restore(path, weights_only=True)
    assert s2.iter == 2
    for layer, leaves in jax.device_get(s1.params).items():
        for name, v in leaves.items():
            np.testing.assert_array_equal(
                v, np.asarray(jax.device_get(s2.params)[layer][name])
            )
    # momentum slots are fresh zeros, not the snapshot's
    mom1 = jax.device_get(s1.opt_state)["momentum"]["ip"]["weight"]
    mom2 = jax.device_get(s2.opt_state)["momentum"]["ip"]["weight"]
    assert np.any(mom1 != 0)
    assert not np.any(mom2 != 0)


# ------------------------------------------------------------ CLI e2e
def _write_job(d, max_iter=8, snapshot=4):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "net.prototxt"), "w") as fh:
        fh.write(NET_TXT)
    with open(os.path.join(d, "solver.prototxt"), "w") as fh:
        fh.write(
            'net: "net.prototxt"\nbase_lr: 0.05\nlr_policy: "fixed"\n'
            f'momentum: 0.9\nmax_iter: {max_iter}\nsnapshot: {snapshot}\n'
            f'snapshot_prefix: "{d}/snap"\ndisplay: 0\n'
        )
    return [
        f"--solver={d}/solver.prototxt", "--synthetic", "--synthetic-n=64",
        "--batch-size=8", "--seed=3", "--data-workers=0",
        "--native-loader=off",
    ]


def test_supervised_chaos_kill_resumes_bit_identical(tmp_path, monkeypatch,
                                                     capfd):
    """THE acceptance run: ``caffe train --supervise`` with a
    supervisor.child_crash injection.  The child snapshots at iter 4,
    hard-exits at the next boundary, the supervisor verifies the
    snapshot and relaunches with --auto-resume (chaos disarmed), and
    the final weights are bit-identical to an uninterrupted run."""
    from sparknet_tpu.tools import caffe as caffe_cli

    monkeypatch.setenv("SPARKNET_SUPERVISE_RESTARTS", "3")
    monkeypatch.setenv("SPARKNET_SUPERVISE_BACKOFF", "0.05")
    monkeypatch.setenv("SPARKNET_SUPERVISE_BACKOFF_CAP", "0.1")

    d1 = str(tmp_path / "sup")
    caffe_cli.main(
        ["train", "--supervise",
         "--chaos=supervisor.child_crash@after=4"] + _write_job(d1)
    )
    out = capfd.readouterr().out
    assert '"restarts": 1' in out and '"verified_resumes": 1' in out
    assert "supervisor:" in out  # the one JSON metrics line

    # the machine-readable trail: report + the child's own crash record
    with open(os.path.join(d1, REPORT_NAME)) as fh:
        report = json.load(fh)
    assert report["final_status"] == "done"
    assert len(report["generations"]) == 2
    assert report["generations"][1]["action"] is None  # same width back
    assert report["generations"][0]["resume"]["iter"] == 4
    (rec,) = records.read_failure_records(d1)
    assert rec["kind"] == "chaos.child_crash"
    assert rec["last_completed_iteration"] == 4

    d2 = str(tmp_path / "clean")
    caffe_cli.main(["train"] + _write_job(d2))

    with np.load(f"{d1}/snap_iter_8.npz") as z:
        supervised = {k: z[k].copy() for k in z.files}
    with np.load(f"{d2}/snap_iter_8.npz") as z:
        clean = {k: z[k].copy() for k in z.files}
    assert sorted(supervised) == sorted(clean)
    for k in clean:
        np.testing.assert_array_equal(supervised[k], clean[k], err_msg=k)


def test_unsupervised_train_path_has_zero_supervision_footprint(
    tmp_path, capfd
):
    """--supervise off: no child processes, no failure records, no
    supervisor line, no report — the train path is the PR-3-era one."""
    from sparknet_tpu.tools import caffe as caffe_cli

    d = str(tmp_path / "plain")
    caffe_cli.main(["train"] + _write_job(d, max_iter=2, snapshot=2))
    out = capfd.readouterr().out
    assert "supervisor" not in out
    assert not os.path.exists(os.path.join(d, "failures"))
    assert not os.path.exists(os.path.join(d, REPORT_NAME))
    assert METRICS.snapshot() == {}


def test_sparknet_supervise_console_entry_resolves():
    from sparknet_tpu.supervise import supervisor as mod

    assert callable(mod.main)
    with pytest.raises(SystemExit):  # no command -> usage error
        mod.main([])
