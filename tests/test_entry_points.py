"""Driver-facing entry points must never regress (VERDICT r1/r2 #1).

MULTICHIP_r01/r02 both failed because ``dryrun_multichip`` assumed the
driver environment provided 8 devices. These tests import and execute
the exact artifacts the driver runs — ``__graft_entry__.entry``,
``__graft_entry__.dryrun_multichip`` and ``bench.main`` — so any
regression fails CI before it can cost a round.
"""

import json
import os
import sys

import numpy as np
import pytest
import jax

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def test_console_entry_points_resolve():
    """Every [project.scripts] target in pyproject.toml must import and
    expose its callable — ``serve`` and friends ship as console
    commands, and a typo'd target only fails at install time otherwise."""
    import importlib
    import re

    with open(os.path.join(_ROOT, "pyproject.toml")) as fh:
        text = fh.read()
    section = re.search(
        r"\[project\.scripts\]\n(.*?)(\n\[|\Z)", text, re.S
    ).group(1)
    targets = dict(re.findall(r'([\w-]+)\s*=\s*"([\w.:]+)"', section))
    assert "serve" in targets and "cifar-app" in targets
    for name, target in targets.items():
        mod_name, _, attr = target.partition(":")
        mod = importlib.import_module(mod_name)
        assert callable(getattr(mod, attr)), f"{name} -> {target}"


def test_entry_compiles_and_runs():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    loss = jax.jit(fn)(*args)
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_dryrun_multichip_all_axes():
    # conftest already forced the 8-device CPU mesh; _ensure_devices must
    # detect that and no-op. In the driver's process (1 axon device) it
    # must instead force the virtual mesh itself.
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_dryrun_ensure_devices_is_idempotent():
    import __graft_entry__ as ge

    ge._ensure_devices(8)
    assert len(jax.devices()) >= 8


def _run_bench(capsys):
    import bench

    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(line)


def test_bench_alexnet_emits_json(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_BATCH", "4")
    monkeypatch.setenv("BENCH_ITERS", "2")
    rec = _run_bench(capsys)
    assert rec["metric"] == "alexnet_train_images_per_sec_per_chip"
    assert rec["value"] > 0 and "error" not in rec
    assert rec["platform"] == "cpu"
    assert rec["tflops"] > 0


@pytest.mark.slow
def test_bench_alexnet_input_pipeline_mode(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_BATCH", "4")
    monkeypatch.setenv("BENCH_ITERS", "1")
    monkeypatch.setenv("BENCH_INPUT_PIPELINE", "1")
    rec = _run_bench(capsys)
    assert rec["value"] > 0 and rec["input_pipeline"] == "1"


@pytest.mark.slow
def test_bench_alexnet_native_pipeline_mode(monkeypatch, capsys):
    from sparknet_tpu import native

    if not native.available():
        import pytest

        pytest.skip("native library unavailable")
    monkeypatch.setenv("BENCH_BATCH", "4")
    monkeypatch.setenv("BENCH_ITERS", "1")
    monkeypatch.setenv("BENCH_INPUT_PIPELINE", "native")
    rec = _run_bench(capsys)
    assert rec["value"] > 0 and rec["input_pipeline"] == "native"


@pytest.mark.slow
def test_bench_e2e_subrecord_on_accelerator_path(monkeypatch):
    """Accelerator runs append an input_pipeline sub-record (host-fed
    loop vs compute-only). That branch is platform-gated off on CPU, so
    cover its record assembly by faking the platform — otherwise its
    first execution ever is an unattended tpu_measure.sh window, where
    the defensive except would silently downgrade a bug to an error
    field."""
    import bench

    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_ITERS", "1")
    monkeypatch.delenv("BENCH_PROFILE", raising=False)
    monkeypatch.delenv("BENCH_INPUT_PIPELINE", raising=False)
    rec = bench.bench_imagenet("fake-accel", "alexnet")
    ip = rec["input_pipeline"]
    assert ip["mode"] == "python+prefetch", ip
    assert ip["img_per_sec"] > 0 and ip["iters"] >= 4
    assert ip["vs_compute_only"] > 0


@pytest.mark.slow
def test_bench_bert_emits_json(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_MODEL", "bert")
    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_SEQ", "64")
    monkeypatch.setenv("BENCH_ITERS", "1")
    rec = _run_bench(capsys)
    assert rec["metric"] == "bert_base_mlm_tokens_per_sec_per_chip"
    assert rec["value"] > 0 and "error" not in rec


@pytest.mark.slow
def test_bench_resnet50_emits_json(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_MODEL", "resnet50")
    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_ITERS", "1")
    rec = _run_bench(capsys)
    assert rec["metric"] == "resnet50_train_images_per_sec_per_chip"
    assert rec["value"] > 0 and "error" not in rec
    assert rec["vs_baseline"] is None  # the K40 anchor is AlexNet-only


def test_bench_oom_retry_halves_batch(monkeypatch):
    """An unattended hardware window must survive a too-big default
    batch: RESOURCE_EXHAUSTED during warmup halves the batch and
    retries, recording the original in oom_retry_from_batch."""
    import bench
    from sparknet_tpu.solver import trainer

    real_step = trainer.Solver.step

    def fake_step(self, batches, n=1, log_fn=None):
        if self.train_net.blob_shapes["data"][0] >= 4:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory (fake)")
        return real_step(self, batches, n, log_fn)

    monkeypatch.setattr(trainer.Solver, "step", fake_step)
    monkeypatch.setenv("BENCH_BATCH", "4")
    monkeypatch.setenv("BENCH_ITERS", "1")
    rec = bench.bench_imagenet("cpu")
    assert rec["batch_size"] == 2, rec
    assert rec["oom_retry_from_batch"] == 4, rec
    assert rec["value"] > 0


def test_bench_remat_mode_emits_tagged_json(monkeypatch, capsys):
    """BENCH_REMAT=1 is staged for unattended TPU windows; the path
    (remat solver build + remat-tagged record) must be CI-exercised
    before it first runs on hardware."""
    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_ITERS", "1")
    monkeypatch.setenv("BENCH_REMAT", "1")
    rec = _run_bench(capsys)
    assert rec["value"] > 0 and "error" not in rec
    assert rec["remat"] is True
