"""telemetry/ subsystem: registry, span tracer, timeline, exporters.

The acceptance bar (ISSUE 5): span nesting survives threads, a merged
multi-process trace validates against the Chrome trace-event schema,
registry label cardinality is bounded, the disabled tracer is an
allocation-free singleton, Prometheus text serves a counter + gauge +
histogram from the live HTTP server, and a CPU ``caffe train --trace``
e2e prints a step-time breakdown attributing ≥90% of measured loop
wall time.  All CPU-only and fast — tier-1, no ``slow`` marker.
"""

import gc
import json
import multiprocessing
import os
import re
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.telemetry import exporter, timeline, trace
from sparknet_tpu.telemetry.registry import (
    REGISTRY,
    Counter,
    Gauge,
    LatencyHistogram,
    NamedCounters,
    Registry,
)

_HAVE_FORK = "fork" in multiprocessing.get_all_start_methods()
fork_only = pytest.mark.skipif(
    not _HAVE_FORK, reason="sidecar merge exercises forked children"
)


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """No tracer state, current timeline, or owner-pid env may leak
    between tests."""
    yield
    trace.disable()
    timeline.set_current(None)
    os.environ.pop(trace.OWNER_PID_ENV, None)
    os.environ.pop(trace.TRACE_ENV, None)


# ---------------------------------------------------------------- registry
def test_registry_primitives_and_labels():
    r = Registry()
    c = r.counter("events", kind="fire")
    c.inc(2)
    assert r.counter("events", kind="fire") is c  # same labels -> same series
    assert r.counter("events", kind="recover") is not c
    g = r.gauge("depth")
    g.set(3)
    g.add(-1)
    h = r.histogram("latency")
    h.observe(0.02)
    snap = r.snapshot()
    assert snap["metrics"]["events"]["kind=fire"] == 2
    assert snap["metrics"]["depth"][""] == {"value": 2, "max": 3}
    assert snap["metrics"]["latency"][""]["count"] == 1
    json.dumps(snap)  # the whole tree must stay JSON-able


def test_registry_type_conflicts_raise():
    r = Registry()
    r.counter("x")
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("x")


def test_registry_label_cardinality_is_bounded():
    r = Registry(max_series=4)
    for i in range(10):
        r.counter("hot", request=i).inc()
    fam = r.families()["hot"]
    # 4 real series + the one shared overflow series
    assert len(fam["series"]) == 5
    assert r.dropped_series.snapshot() == 6
    # every overflow inc landed on the same shared series
    from sparknet_tpu.telemetry.registry import OVERFLOW_KEY

    assert fam["series"][OVERFLOW_KEY].snapshot() == 6
    # the overflow spill is visible in snapshots (and Prometheus)
    assert r.snapshot()["dropped_series"] == 6


def test_registry_sources_are_weak_and_newest_wins():
    r = Registry()

    class Src:
        def __init__(self, tag):
            self.tag = tag

        def snapshot(self):
            return {"tag": self.tag}

    a = Src("a")
    r.register_source("sub", a)
    assert r.snapshot()["sub"] == {"tag": "a"}
    b = Src("b")
    r.register_source("sub", b)  # newest registration wins
    assert r.snapshot()["sub"] == {"tag": "b"}
    del a, b
    gc.collect()
    assert "sub" not in r.snapshot()  # weakly held: dead sources drop out


def test_named_counters_shared_shape():
    nc = NamedCounters()
    nc.inc("restarts")
    nc.inc("restarts", 2)
    assert nc.count("restarts") == 3
    assert nc.count("missing") == 0
    assert nc.snapshot() == {"restarts": 3}
    nc.reset()
    assert nc.snapshot() == {}


# ------------------------------------------------------------------ tracer
def test_disabled_mode_is_an_allocation_free_singleton():
    assert not trace.enabled()
    s1 = trace.span("a", key=1)
    s2 = trace.span("b")
    assert s1 is s2  # ONE shared no-op object — nothing allocated
    with s1:
        pass
    assert trace.events() == []

    calls = []

    @trace.traced("decorated")
    def fn(x):
        calls.append(x)
        return x + 1

    assert fn(1) == 2 and calls == [1]
    assert trace.events() == []
    # record() is also a no-op while disabled
    trace.record("x", 0, 1.0)
    assert trace.events() == []


def test_span_nesting_across_threads():
    trace.enable()
    try:
        with trace.span("outer", cat="t"):
            with trace.span("inner", cat="t"):
                time.sleep(0.002)

        def worker():
            with trace.span("thread_outer"):
                with trace.span("thread_inner"):
                    time.sleep(0.002)

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        evs = {(e["name"], e["tid"]): e for e in trace.events()}
        main_tid = threading.get_ident()
        outer = evs[("outer", main_tid)]
        inner = evs[("inner", main_tid)]
        # nesting: the inner span's interval is contained in the outer's
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
        # thread-awareness: worker spans carry their own tids
        tids = {
            e["tid"] for e in trace.events() if e["name"] == "thread_inner"
        }
        assert len(tids) == 2 and main_tid not in tids
        for tid in tids:
            t_out = evs[("thread_outer", tid)]
            t_in = evs[("thread_inner", tid)]
            assert t_out["ts"] <= t_in["ts"]
            assert t_in["dur"] <= t_out["dur"] + 1
    finally:
        trace.disable()


def test_ring_buffer_is_bounded():
    trace.enable(capacity=8)
    try:
        for i in range(50):
            with trace.span(f"s{i}"):
                pass
        evs = trace.events()
        assert len(evs) == 8
        assert evs[-1]["name"] == "s49"  # newest kept, oldest evicted
    finally:
        trace.disable()


def _validate_chrome_trace(doc):
    """The trace-event schema subset Perfetto requires: a traceEvents
    list of events with name/ph/pid/tid, complete events carrying
    numeric ts+dur, metadata events carrying args."""
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    assert doc["traceEvents"], "empty trace"
    for e in doc["traceEvents"]:
        assert isinstance(e["name"], str)
        assert e["ph"] in ("X", "M")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        else:
            assert "name" in e["args"]


def _sidecar_child(path):
    # runs in a forked child: the at-fork hook cleared inherited spans
    # and demoted us to sidecar; our spans land in a part file
    with trace.span("child_work", cat="test"):
        time.sleep(0.002)
    out = trace.flush_sidecar()
    os._exit(0 if out and os.path.exists(out) else 17)


@fork_only
def test_multiprocess_merge_validates_against_trace_event_schema(tmp_path):
    path = str(tmp_path / "merged.json")
    trace.enable(path)
    try:
        with trace.span("parent_work", cat="test"):
            ctx = multiprocessing.get_context("fork")
            procs = [
                ctx.Process(target=_sidecar_child, args=(path,))
                for _ in range(2)
            ]
            for p in procs:
                p.start()
            for p in procs:
                p.join(10)
            assert all(p.exitcode == 0 for p in procs)
        written = trace.write()
        assert written == path
    finally:
        trace.disable()
    doc = json.load(open(path))
    _validate_chrome_trace(doc)
    by_pid = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            by_pid.setdefault(e["pid"], []).append(e["name"])
    # merged by pid: the parent plus BOTH sidecar children
    assert len(by_pid) == 3
    assert sum("child_work" in names for names in by_pid.values()) == 2
    # part files are consumed by the merge
    assert not list(tmp_path.glob("merged.json.part-*"))


def test_fork_hook_drops_inherited_spans(tmp_path):
    if not _HAVE_FORK:
        pytest.skip("fork start method unavailable")
    path = str(tmp_path / "t.json")
    trace.enable(path)
    try:
        with trace.span("parent_only"):
            pass

        def child():
            # inherited buffer was cleared: only OUR span may appear
            names = [e["name"] for e in trace.events()]
            ok = "parent_only" not in names
            with trace.span("child_span"):
                pass
            out = trace.flush_sidecar()
            os._exit(0 if (ok and out) else 23)

        p = multiprocessing.get_context("fork").Process(target=child)
        p.start()
        p.join(10)
        assert p.exitcode == 0
        part = json.load(open(trace.part_path(path, p.pid)))
        names = [e["name"] for e in part if e["ph"] == "X"]
        assert names == ["child_span"]
    finally:
        trace.disable()


# ---------------------------------------------------------------- timeline
def test_timeline_nested_phases_attribute_exclusively():
    tl = timeline.Timeline()
    tl.start()
    with tl.phase("device_put"):
        with tl.phase("multihost_sync"):
            time.sleep(0.02)
        time.sleep(0.01)
    tl.stop()
    snap = tl.snapshot()
    phases = snap["phases"]
    # the inner phase owns its time; the outer keeps only its exclusive
    # share — so the table can never double-count
    assert phases["multihost_sync"]["total_s"] >= 0.018
    assert phases["device_put"]["total_s"] < 0.02
    assert snap["attributed_s"] <= snap["wall_s"] + 1e-6
    assert snap["attributed_frac"] > 0.9
    table = tl.table()
    assert "device_put" in table and "multihost_sync" in table
    assert re.search(r"attributed \d+(\.\d+)?% of", table)


def test_timeline_threads_do_not_cross_nest():
    tl = timeline.Timeline()
    tl.start()

    def worker():
        with tl.phase("input_wait"):
            time.sleep(0.01)

    t = threading.Thread(target=worker)
    with tl.phase("compiled_step"):
        t.start()
        t.join()
    tl.stop()
    phases = tl.snapshot()["phases"]
    # the worker's phase ran on its own stack: compiled_step keeps its
    # full duration (no cross-thread child subtraction)
    assert phases["compiled_step"]["total_s"] >= 0.009
    assert phases["input_wait"]["total_s"] >= 0.009


def test_null_timeline_is_inert():
    n = timeline.NULL
    assert not n.enabled and not n.fence
    p1 = n.phase("a")
    assert p1 is n.phase("b")  # shared no-op context manager
    with p1:
        pass
    assert n.snapshot() == {} and n.table() == ""
    timeline.set_current(None)
    assert timeline.current() is timeline.NULL
    with timeline.current_phase("multihost_sync"):
        pass  # no-op without an active timeline


# --------------------------------------------------------------- exporter
def test_prometheus_rendering_counter_gauge_histogram():
    r = Registry()
    r.counter("fires", point="pipeline").inc(3)
    r.gauge("depth").set(7)
    r.histogram("wait").observe(0.005)
    text = exporter.render_prometheus(registry=r)
    assert "# TYPE sparknet_fires_total counter" in text
    assert 'sparknet_fires_total{point="pipeline"} 3' in text
    assert "# TYPE sparknet_depth gauge" in text
    assert "sparknet_depth 7" in text
    assert "# TYPE sparknet_wait histogram" in text
    assert 'sparknet_wait_bucket{le="+Inf"} 1' in text
    assert "sparknet_wait_count 1" in text
    # cumulative: every bucket count is <= the next
    counts = [
        int(m.group(1))
        for m in re.finditer(r'sparknet_wait_bucket\{le="[^"]+"\} (\d+)', text)
    ]
    assert counts == sorted(counts)


def test_prometheus_rendering_of_serve_metrics():
    from sparknet_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics((4,))
    m.record_request(0.01, rows=2)
    m.record_batch(4, rows=2, padded_rows=2, device_s=0.004)
    m.set_queue_depth(3)
    text = exporter.render_prometheus(serve_metrics=m)
    assert "# TYPE sparknet_serve_requests_total counter" in text
    assert "sparknet_serve_requests_total 1" in text
    assert "# TYPE sparknet_serve_queue_depth gauge" in text
    assert (
        "# TYPE sparknet_serve_request_latency_seconds histogram" in text
    )
    assert "sparknet_serve_request_latency_seconds_count 1" in text
    assert 'sparknet_serve_batches_total{bucket="4"} 1' in text
    assert "sparknet_serve_healthy 1" in text


def test_periodic_flush_emits_and_stops():
    lines = []
    stop = exporter.maybe_start_periodic(emit=lines.append, interval_s=0.03)
    time.sleep(0.11)
    stop()
    n = len(lines)
    assert n >= 2  # ticks + the final line at stop
    for line in lines:
        assert line.startswith("telemetry: ")
        json.loads(line[len("telemetry: "):])
    time.sleep(0.08)
    assert len(lines) == n  # stopped means stopped


def test_periodic_flush_default_off(monkeypatch):
    monkeypatch.delenv(exporter.PERIODIC_ENV, raising=False)
    lines = []
    stop = exporter.maybe_start_periodic(emit=lines.append)
    time.sleep(0.03)
    stop()
    assert lines == []
    monkeypatch.setenv(exporter.PERIODIC_ENV, "nonsense")
    with pytest.raises(ValueError, match="must be a number"):
        exporter.periodic_interval()


# ------------------------------------------------------------- HTTP server
class _StubEngine:
    """Minimal engine contract for the HTTP layer (buckets + infer +
    postprocess); keeps the route tests off the XLA compile path."""

    buckets = (4,)
    output = "prob"
    metrics = None

    def infer(self, rows):
        rows = np.asarray(rows, np.float32)
        return rows.reshape(len(rows), -1)[:, :3]

    def postprocess(self, out, top_k):
        idx = np.argsort(-out, axis=-1)[:, :top_k]
        return idx, np.take_along_axis(out, idx, axis=-1)


def test_server_serves_prometheus_and_json_metrics():
    import http.client

    from sparknet_tpu.serve.metrics import ServeMetrics
    from sparknet_tpu.serve.server import InferenceServer

    m = ServeMetrics((4,))
    srv = InferenceServer(
        _StubEngine(), metrics=m, port=0, model_name="stub"
    ).start()
    try:
        c = srv.client()
        st, _ = c.classify(np.ones((2, 3)), top_k=2)
        assert st == 200
        # the JSON snapshot moved to /metrics.json; Client.metrics()
        # follows it and keeps its dict shape
        st, met = c.metrics()
        assert st == 200 and met["requests"] == 1

        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=10)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        conn.close()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        # the acceptance bar: at least one counter, gauge and histogram
        assert "# TYPE sparknet_serve_requests_total counter" in body
        assert "sparknet_serve_requests_total 1" in body
        assert "# TYPE sparknet_serve_queue_depth gauge" in body
        assert (
            "# TYPE sparknet_serve_request_latency_seconds histogram"
            in body
        )
    finally:
        srv.stop()


# ------------------------------------------------------------------- e2e
_TINY_NET = """
name: "tiny"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 10
          weight_filler { type: "gaussian" std: 0.05 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""


@fork_only
def test_caffe_train_trace_e2e_attributes_wall_time(tmp_path, capsys):
    """The acceptance run: CPU ``caffe train --trace OUT.json`` emits
    valid Chrome trace JSON (workers' sidecars merged in by pid) and
    prints a step-time breakdown attributing ≥90% of loop wall time."""
    from sparknet_tpu.tools import caffe as caffe_cli

    (tmp_path / "net.prototxt").write_text(_TINY_NET)
    (tmp_path / "solver.prototxt").write_text(
        'net: "net.prototxt"\nbase_lr: 0.05\nlr_policy: "fixed"\n'
        'momentum: 0.9\nmax_iter: 6\nsnapshot: 6\n'
        f'snapshot_prefix: "{tmp_path}/snap"\ndisplay: 0\n'
    )
    out_json = tmp_path / "trace.json"
    caffe_cli.main([
        "train", f"--solver={tmp_path}/solver.prototxt", "--synthetic",
        "--synthetic-n=64", "--batch-size=8", "--seed=3",
        "--data-workers=2", "--native-loader=off",
        f"--trace={out_json}",
    ])
    out = capsys.readouterr().out
    # the breakdown table and its attribution line
    assert "telemetry: step-time breakdown" in out
    mt = re.search(r"attributed (\d+(?:\.\d+)?)% of ([\d.]+)s", out)
    assert mt, out
    assert float(mt.group(1)) >= 90.0, out
    for phase in ("input_wait", "compiled_step", "snapshot"):
        assert re.search(rf"{phase}\s+\d", out), out
    # valid, merged Chrome trace: the 2 pipeline workers' sidecars rode
    # in by pid alongside the trainer's spans
    doc = json.load(open(out_json))
    _validate_chrome_trace(doc)
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(pids) >= 3, pids
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"compiled_step", "input_wait", "pipeline.produce"} <= names
    # the run's own cleanup restored tracer state (finish_run)
    assert not trace.enabled()
    assert os.environ.get(trace.TRACE_ENV) in (None, "")


def test_trace_flag_does_not_change_results(tmp_path):
    """--trace observes; it must not perturb the batch stream or the
    trained weights (fencing changes timing only)."""
    from sparknet_tpu.tools import caffe as caffe_cli

    def run(tag, traced):
        d = tmp_path / tag
        d.mkdir()
        (d / "net.prototxt").write_text(_TINY_NET)
        (d / "solver.prototxt").write_text(
            'net: "net.prototxt"\nbase_lr: 0.05\nlr_policy: "fixed"\n'
            'momentum: 0.9\nmax_iter: 4\nsnapshot: 4\n'
            f'snapshot_prefix: "{d}/snap"\ndisplay: 0\n'
        )
        argv = [
            "train", f"--solver={d}/solver.prototxt", "--synthetic",
            "--synthetic-n=64", "--batch-size=8", "--seed=5",
            "--data-workers=0", "--native-loader=off",
        ]
        if traced:
            argv.append(f"--trace={d}/trace.json")
        caffe_cli.main(argv)
        with np.load(f"{d}/snap_iter_4.npz") as z:
            return {k: z[k].copy() for k in z.files}

    traced = run("traced", True)
    clean = run("clean", False)
    assert sorted(traced) == sorted(clean)
    for k in clean:
        np.testing.assert_array_equal(traced[k], clean[k], err_msg=k)
