"""Sequence parallelism (ring + Ulysses) on the 8-device CPU mesh:
sharded results must match single-device full attention, forward and
backward, and the SP BERT train step must train."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from sparknet_tpu.ops.attention import mha_reference
from sparknet_tpu.parallel.mesh import make_mesh
from sparknet_tpu.parallel.sequence import (
    make_sp_train_step,
    ring_attention,
    ulysses_attention,
)

SP = 4


def sp_mesh(n=SP):
    return make_mesh({"sp": n}, jax.devices()[:n])


def rand(rng, shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


def _run_sp(fn, mesh, q, k, v, mask):
    # comm.shard_map: the version-compat spelling (jax.shard_map where
    # it exists, jax.experimental fallback otherwise) the parallel
    # modes themselves route through
    from sparknet_tpu.parallel import comm

    mapped = comm.shard_map(
        lambda q_, k_, v_, m_: fn(q_, k_, v_, kv_mask=m_),
        mesh=mesh,
        in_specs=(P(None, None, "sp"), P(None, None, "sp"),
                  P(None, None, "sp"), P(None, "sp")),
        out_specs=P(None, None, "sp"),
    )
    return mapped(q, k, v, mask)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp_attention_matches_full(impl, causal):
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 4, 64, 8  # h=4 divides sp=4 for ulysses
    q, k, v = rand(rng, (b, h, s, d)), rand(rng, (b, h, s, d)), rand(rng, (b, h, s, d))
    mask = np.ones((b, s), np.int32)
    mask[0, 50:] = 0
    mask_j = jnp.asarray(mask)
    fn = ring_attention if impl == "ring" else ulysses_attention
    out = _run_sp(
        partial(fn, axis_name="sp", causal=causal), sp_mesh(), q, k, v, mask_j
    )
    ref = mha_reference(q, k, v, causal=causal, kv_mask=mask_j)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sp_attention_grads_match_full(impl):
    rng = np.random.default_rng(1)
    b, h, s, d = 1, 4, 32, 8
    q, k, v = rand(rng, (b, h, s, d)), rand(rng, (b, h, s, d)), rand(rng, (b, h, s, d))
    mask_j = jnp.ones((b, s), jnp.int32)
    fn = ring_attention if impl == "ring" else ulysses_attention
    mesh = sp_mesh()

    def loss_sp(q, k, v):
        out = _run_sp(partial(fn, axis_name="sp", causal=True),
                      mesh, q, k, v, mask_j)
        return jnp.sum(jnp.sin(out))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=True)))

    gs = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gs, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} ({impl})")


def test_ring_attention_long_seq_many_shards():
    """8-way ring on a longer sequence (the long-context configuration)."""
    rng = np.random.default_rng(2)
    b, h, s, d = 1, 2, 512, 16
    q, k, v = rand(rng, (b, h, s, d)), rand(rng, (b, h, s, d)), rand(rng, (b, h, s, d))
    mask_j = jnp.ones((b, s), jnp.int32)
    out = _run_sp(
        partial(ring_attention, axis_name="sp", causal=True),
        sp_mesh(8), q, k, v, mask_j,
    )
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sp_bert_train_step_runs_and_learns():
    from sparknet_tpu.data.text import mlm_dataset, mlm_feed_tokens
    from sparknet_tpu.models.bert import BertConfig, BertMLM
    from sparknet_tpu.proto.caffe_pb import SolverParameter
    from sparknet_tpu.solver.caffe_solver import init_opt_state

    mesh = make_mesh({"dp": 2, "sp": 4}, jax.devices()[:8])
    cfg = BertConfig.bert_tiny(vocab_size=64)
    b, s = 4, 64
    model = BertMLM(
        cfg, {"input_ids": (b, s), "mlm_positions": (b, 8)},
        attention_impl="ring",
    )
    params, _ = model.init(jax.random.PRNGKey(0))
    sp = SolverParameter(
        base_lr=3e-3, lr_policy="fixed", solver_type="ADAMW",
        momentum=0.9, weight_decay=0.01, max_iter=100,
    )
    opt_state = init_opt_state(sp, params)
    step = make_sp_train_step(model, sp, mesh)

    ds, vsize = mlm_dataset(vocab_size=64, n_tokens=8192, seq_len=s)
    feed = mlm_feed_tokens(ds, b, vsize, seed=0)
    # one FIXED batch: memorisation decreases loss deterministically,
    # where a 12-step run over a random stream is threshold-flaky
    batch = {k_: jnp.asarray(v_) for k_, v_ in next(feed).items()}
    losses = []
    rng = jax.random.PRNGKey(1)
    for it in range(12):
        rng, srng = jax.random.split(rng)
        params, opt_state, m = step(
            params, opt_state, batch, jnp.asarray(it, jnp.int32), srng
        )
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


def test_ulysses_rejects_indivisible_heads():
    rng = np.random.default_rng(3)
    q = rand(rng, (1, 3, 32, 8))  # 3 heads, sp=4
    mask_j = jnp.ones((1, 32), jnp.int32)
    with pytest.raises(ValueError):
        _run_sp(partial(ulysses_attention, axis_name="sp"),
                sp_mesh(), q, q, q, mask_j)


@pytest.mark.slow  # interpret-mode flash kernels at lane-aligned shapes
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_full_and_grads(causal):
    """The flash-per-block ring engine (TPU default for lane-aligned
    shards; Pallas interpret mode here): forward AND gradients must
    match single-device full attention exactly — the custom VJP re-runs
    the ring with the GLOBAL merged lse per block and rotates dk/dv
    accumulators home with their kv shards."""
    from sparknet_tpu.parallel.sequence import _ring_einsum

    rng = np.random.default_rng(7)
    b, h, s, d = 2, 2, 512, 64  # s_loc = 128: lane-aligned
    q = rand(rng, (b, h, s, d))
    k = rand(rng, (b, h, s, d))
    v = rand(rng, (b, h, s, d))
    mask = np.ones((b, s), np.int32)
    mask[0, 400:] = 0
    mask_j = jnp.asarray(mask)
    mesh = sp_mesh()
    fn = partial(ring_attention, axis_name="sp", causal=causal,
                 impl="flash", interpret=True)

    def run(fn_, q_, k_, v_):
        return _run_sp(fn_, mesh, q_, k_, v_, mask_j)

    out = run(fn, q, k, v)
    ref = mha_reference(q, k, v, causal=causal, kv_mask=mask_j)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_sp(q_, k_, v_):
        return jnp.sum(jnp.sin(run(fn, q_, k_, v_)))

    def loss_ref(q_, k_, v_):
        return jnp.sum(jnp.sin(
            mha_reference(q_, k_, v_, causal=causal, kv_mask=mask_j)))

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_sp, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-5, atol=3e-5,
            err_msg=f"d{name}",
        )

    # and the two ring engines agree with each other
    fn_e = partial(_ring_einsum, axis_name="sp", causal=causal)
    out_e = run(fn_e, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_e),
                               rtol=2e-5, atol=2e-5)
