"""Storage-fault hardening (ISSUE 19): deterministic disk-fault
injection through ``utils/safeio`` and the per-writer degradation
policies (docs/ROBUSTNESS.md "Storage faults").

The contract under test: a disk that says no (ENOSPC / EIO, injected
via the ``io.*`` chaos points) never tears a published file, never
takes down a serving or training process, and every degradation a
writer takes is a counted policy — skipped snapshots, paused tees,
evicted cache segments, disabled compile caches.
"""

import errno
import glob
import json
import os
import time

import numpy as np
import pytest

from sparknet_tpu import chaos
from sparknet_tpu.chaos.plan import FAULT_POINTS, FaultPlan
from sparknet_tpu.solver import snapshot
from sparknet_tpu.telemetry import anomaly
from sparknet_tpu.telemetry.registry import REGISTRY
from sparknet_tpu.utils import safeio

IO_POINTS = ("io.enospc", "io.eio", "io.slow_write", "io.enospc_storm")


@pytest.fixture(autouse=True)
def _isolation():
    """No chaos plan, io-site sequence, storm window, or detector state
    may leak between tests."""
    chaos.clear()
    safeio.reset()
    anomaly.reset_detectors()
    yield
    chaos.clear()
    safeio.reset()
    anomaly.reset_detectors()


def _fault_count(site, kind):
    snap = REGISTRY.snapshot().get("metrics", {}).get("io_faults") or {}
    return snap.get(f"errno={kind},site={site}", 0)


# ------------------------------------------------------------- grammar
def test_io_fault_points_registered_and_parse_bare():
    for point in IO_POINTS:
        assert point in FAULT_POINTS
        assert FaultPlan(point).points() == [point]


def test_site_is_a_string_coordinate():
    p = FaultPlan("io.enospc@site=tee:index=0")
    assert p.match("io.enospc", site="cache", index=0) is None
    rule = p.match("io.enospc", site="tee", index=0)
    assert rule is not None and rule.match["site"] == "tee"
    # site values are bare tags, not paths/globs
    with pytest.raises(ValueError):
        FaultPlan("io.enospc@site=../evil")
    with pytest.raises(ValueError):
        FaultPlan("io.enospc@site=")


def test_storm_and_slow_write_params_parse():
    p = FaultPlan(
        "io.enospc_storm@times=1:clear_after_s=3,"
        "io.slow_write@site=records:delay_ms=7"
    )
    storm = p.match("io.enospc_storm", site="snapshot", index=0)
    assert storm is not None and storm.params["clear_after_s"] == 3
    slow = p.match("io.slow_write", site="records", index=0)
    assert slow is not None and slow.params["delay_ms"] == 7


# ------------------------------------------------------------- safeio
def test_atomic_write_enospc_keeps_old_bytes_and_counts(tmp_path):
    path = str(tmp_path / "doc.json")
    before = _fault_count("records", "enospc")
    # the per-site write sequence is the chaos index (counted only
    # while a plan is installed): index=1 hits exactly the SECOND
    # records write
    chaos.install("io.enospc@site=records:index=1")
    safeio.atomic_write_json(path, {"v": 1}, site="records", fsync=False)
    with pytest.raises(OSError) as ei:
        safeio.atomic_write_json(path, {"v": 2}, site="records",
                                 fsync=False)
    assert ei.value.errno == errno.ENOSPC
    with open(path) as fh:
        assert json.load(fh) == {"v": 1}  # old bytes, never torn
    assert not glob.glob(str(tmp_path / "*.tmp*"))  # staging cleaned
    assert _fault_count("records", "enospc") == before + 1
    # a different site is untouched by the site-targeted rule
    other = str(tmp_path / "other.json")
    safeio.atomic_write_json(other, {"ok": 1}, site="flight", fsync=False)
    assert os.path.exists(other)


def test_slow_write_injects_latency_not_failure(tmp_path):
    chaos.install("io.slow_write@site=flight:delay_ms=80:times=1")
    path = str(tmp_path / "slow.json")
    t0 = time.monotonic()
    safeio.atomic_write_json(path, {"v": 1}, site="flight", fsync=False)
    assert time.monotonic() - t0 >= 0.08
    with open(path) as fh:
        assert json.load(fh) == {"v": 1}


def test_enospc_storm_is_volume_wide_and_clears(tmp_path):
    chaos.install("io.enospc_storm@times=1:clear_after_s=0.2")
    with pytest.raises(OSError):  # the match opens the storm window
        safeio.check_faults("snapshot")
    assert safeio.storm_active()
    with pytest.raises(OSError):  # ...which blocks EVERY site
        safeio.check_faults("tee")
    with pytest.raises(OSError):
        safeio.check_faults("cache")
    time.sleep(0.25)
    safeio.check_faults("records")  # storm expired: writes flow again
    assert not safeio.storm_active()
    assert chaos.METRICS.recovery_count("io.storm_cleared") == 1


def test_preflight_floor_refuses_early(tmp_path, monkeypatch):
    # an absurd floor (1 PB) always trips: the write is refused BEFORE
    # any bytes are staged
    monkeypatch.setenv("SPARKNET_DISK_MIN_FREE_MB", str(1 << 30))
    path = str(tmp_path / "doc.json")
    with pytest.raises(OSError) as ei:
        safeio.atomic_write_json(path, {"v": 1}, site="records",
                                 fsync=False)
    assert ei.value.errno == errno.ENOSPC
    assert not os.path.exists(path)
    assert not glob.glob(str(tmp_path / "*.tmp*"))


def test_best_effort_writer_never_raises(tmp_path):
    chaos.install("io.eio@site=flight")
    path = str(tmp_path / "fl.json")
    assert not safeio.best_effort_write_json(
        path, {"v": 1}, site="flight", fsync=False
    )
    assert not os.path.exists(path)
    chaos.clear()
    assert safeio.best_effort_write_json(
        path, {"v": 1}, site="flight", fsync=False
    )
    with open(path) as fh:
        assert json.load(fh) == {"v": 1}


# --------------------------------------------------------- disk pressure
def test_disk_pressure_detector_fires_below_watermark():
    seen = []
    det = anomaly.DiskPressureDetector(
        watermark_mb=10, refire_s=100.0, emit=seen.append
    )
    assert det.observe(64 << 20) is None  # headroom: quiet
    ev = det.observe(5 << 20)
    assert ev is not None and ev["kind"] == "disk_pressure"
    assert ev["severity"] == "serious"
    assert det.observe(5 << 20) is None  # rate-limited while fresh
    assert det.observe(64 << 20) is None  # recovery re-arms...
    assert det.observe(5 << 20) is not None  # ...the next breach
    assert len(seen) == 2
    assert any(a["kind"] == "disk_pressure" for a in anomaly.active())


# -------------------------------------------------------------- snapshot
def test_enospc_snapshot_skip_parity_and_fallback(tmp_path):
    import jax

    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "tiny"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""
    sp_txt = 'base_lr: 0.1\nlr_policy: "fixed"\nmomentum: 0.9\nmax_iter: 8\n'

    def make_solver():
        sp = caffe_pb.load_solver(sp_txt, is_path=False)
        sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
        return Solver(sp, {"data": (8, 6), "label": (8,)})

    def assert_trees_equal(a, b):
        la = jax.tree_util.tree_leaves_with_path(a)
        lb = jax.tree_util.tree_leaves_with_path(b)
        assert len(la) == len(lb)
        for (pa, xa), (pb, xb) in zip(la, lb):
            assert pa == pb
            np.testing.assert_array_equal(
                np.asarray(xa), np.asarray(xb), err_msg=str(pa)
            )

    rng = np.random.default_rng(5)
    batches = [
        {
            "data": rng.normal(size=(8, 6)).astype(np.float32),
            "label": rng.integers(0, 3, 8).astype(np.int32),
        }
        for _ in range(4)
    ]
    prefix = str(tmp_path / "run")

    # reference run: the mid-run snapshot lands normally
    s1 = make_solver()
    s1.step(iter(batches[:2]), 2)
    assert s1.save_or_skip(f"{prefix}_iter_2.solverstate.npz", prefix)
    s1.step(iter(batches[2:]), 2)

    # degraded run: the same snapshot hits a full disk and is skipped —
    # chain intact (nothing new, nothing torn), training continues
    chaos.install("io.enospc@site=snapshot:every=1")
    s2 = make_solver()
    s2.step(iter(batches[:2]), 2)
    other = str(tmp_path / "deg")
    skipped = REGISTRY.snapshot()["metrics"].get(
        "snapshot_skipped", {}
    ).get("errno=enospc", 0)
    assert not s2.save_or_skip(f"{other}_iter_2.solverstate.npz", other)
    assert not os.path.exists(f"{other}_iter_2.solverstate.npz")
    assert not glob.glob(str(tmp_path / "*.tmp*"))
    assert REGISTRY.snapshot()["metrics"]["snapshot_skipped"][
        "errno=enospc"
    ] == skipped + 1
    s2.step(iter(batches[2:]), 2)
    chaos.clear()
    safeio.reset()

    # a skipped snapshot never perturbs the training trajectory
    assert s2.iter == s1.iter
    assert_trees_equal(s1.params, s2.params)
    assert_trees_equal(s1.opt_state, s2.opt_state)

    # a torn newest snapshot falls back to the intact chain, bit-exact
    torn = f"{prefix}_iter_4.solverstate.npz"
    with open(torn, "wb") as fh:
        fh.write(b"not a solverstate")
    s3 = make_solver()
    restored = snapshot.restore_with_fallback(s3, prefix, torn)
    assert restored == f"{prefix}_iter_2.solverstate.npz"
    assert s3.iter == 2
    s3.step(iter(batches[2:]), 2)
    assert_trees_equal(s1.params, s3.params)


def test_save_state_or_skip_prunes_then_retries(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARKNET_SNAPSHOT_KEEP", "2")
    prefix = str(tmp_path / "run")
    tree = {"w": np.arange(8, dtype=np.float32)}
    for it in (2, 4):
        snapshot.save_state(
            f"{prefix}_iter_{it}.solverstate.npz", tree=tree, it=it
        )
    # the first snapshot write fails ENOSPC; the policy prunes the
    # chain one deeper than keep (2 -> 1) and the retry lands
    chaos.install("io.enospc@site=snapshot:index=0")
    ok = snapshot.save_state_or_skip(
        f"{prefix}_iter_6.solverstate.npz", prefix=prefix, tree=tree, it=6
    )
    assert ok
    assert os.path.exists(f"{prefix}_iter_6.solverstate.npz")
    assert not os.path.exists(f"{prefix}_iter_2.solverstate.npz")  # pruned
    assert os.path.exists(f"{prefix}_iter_4.solverstate.npz")
    assert chaos.METRICS.recovery_count("snapshot.enospc_prune") == 1


# ------------------------------------------------------------------ tee
def test_tee_pauses_on_enospc_and_resumes(tmp_path):
    from sparknet_tpu.deploy.tee import TeeWriter

    chaos.install("io.enospc@site=tee:times=1")
    tee = TeeWriter(str(tmp_path), interval_s=60.0, shard_records=4)
    try:
        for i in range(4):
            assert tee.offer({
                "data": np.full(4, i, np.float32),
                "label": np.int32(i),
            })
        tee.flush()  # shard creation hits the injected ENOSPC
        st = tee.stats()
        assert st["io_paused"] and st["dropped"] == 1 and st["shards"] == 0
        assert _fault_count("tee", "enospc") >= 1
        time.sleep(0.3)  # the 0.25 s first backoff elapses
        tee.flush()  # space is back: the drain seals the survivors
    finally:
        tee.stop()
    st = tee.stats()
    assert st["shards"] == 1 and st["written"] == 3
    assert not st["io_paused"]
    assert chaos.METRICS.recovery_count("deploy.tee_resume") == 1
    # the published log is readable and carries exactly the survivors
    from sparknet_tpu.data import records as rec

    ds = rec.PackedDataset(str(tmp_path))
    assert ds.num_records == 3
    # no bare staging file survives; quarantines are allowed
    assert not glob.glob(str(tmp_path / "*.writing"))


def test_tee_retention_evicts_only_below_consumed_floor(
    tmp_path, monkeypatch
):
    from sparknet_tpu.data import records as rec
    from sparknet_tpu.deploy.tee import CONSUMED_NAME, TeeWriter

    monkeypatch.setenv("SPARKNET_DEPLOY_LOG_MB", "0.002")  # ~2 KB budget
    tee = TeeWriter(str(tmp_path), interval_s=60.0, shard_records=4)
    try:
        def seal_batch(tag):
            for i in range(4):
                tee.offer({
                    "data": np.full(256, tag * 10 + i, np.float32),
                    "label": np.int32(i),
                })
            tee.flush()

        seal_batch(0)
        seal_batch(1)
        # over budget but the trainer has consumed nothing: the log
        # must NOT shed records a resume still needs
        assert tee.stats()["evicted"] == 0
        # trainer publishes its durable floor: the first shard's 4
        # records are consumed, the second shard's are not
        with open(os.path.join(str(tmp_path), CONSUMED_NAME), "w") as fh:
            json.dump({"records": 4}, fh)
        seal_batch(2)
        st = tee.stats()
        assert st["shards"] == 3 and st["evicted"] == 1
    finally:
        tee.stop()
    # the evicted shard keeps its manifest entry (positions never
    # move) but its FILE is gone; later shards are untouched
    with open(os.path.join(str(tmp_path), rec.MANIFEST_NAME)) as fh:
        m = json.load(fh)
    shards = m["shards"]
    assert len(shards) == 3
    assert shards[0].get("evicted") is True
    assert not os.path.exists(os.path.join(str(tmp_path), shards[0]["file"]))
    for s in shards[1:]:
        assert not s.get("evicted")
        assert os.path.exists(os.path.join(str(tmp_path), s["file"]))
    # record positions past the evicted span are unchanged: the second
    # shard still holds records 4..7 with their original payloads
    ds = rec.PackedDataset(str(tmp_path))
    assert ds.num_records == 12
    r = rec.PackedShardReader(
        os.path.join(str(tmp_path), shards[1]["file"])
    )
    try:
        np.testing.assert_array_equal(
            r.record(0)["data"], np.full(256, 10, np.float32)
        )
    finally:
        r.close()


# ---------------------------------------------------------------- cache
def test_shm_cache_enospc_evicts_and_retries_then_disables(tmp_path):
    from sparknet_tpu.data.cache import ShmBatchCache

    ns = f"iofault-{os.getpid()}"
    cache = ShmBatchCache(ns, registry_dir=str(tmp_path), max_bytes=1 << 20)
    try:
        batch = {"x": np.arange(16, dtype=np.float32)}
        # one injected ENOSPC: the put sheds unpinned entries and the
        # single retry lands — callers never notice
        assert cache.put("k0", batch)
        chaos.install("io.enospc@site=cache:times=1")
        assert cache.put("k1", batch)
        assert cache.get("k1") is not None
        assert not cache._io_disabled
        assert _fault_count("cache", "enospc") >= 1
        # a persistent fault (two in a row) disables puts for the run
        chaos.install("io.enospc@site=cache:every=1")
        assert not cache.put("k2", batch)
        assert cache._io_disabled
        chaos.clear()
        assert not cache.put("k3", batch)  # still off: counted skip
        # the emergency shed emptied the namespace before the failed
        # retry: every get is now a clean miss (decode fallback), not
        # an error — a dead cache costs time, never correctness
        assert cache.get("k0") is None
        assert cache.metrics.snapshot()["put_skipped"] >= 2
    finally:
        cache.clear()


# -------------------------------------------------------- compile cache
def test_compile_cache_disables_for_the_run(tmp_path):
    from sparknet_tpu.serve import compile_cache

    chaos.install("io.eio@site=compile_cache:times=1")
    try:
        assert compile_cache.enable_persistent_cache(
            str(tmp_path / "cc"), "deadbeef"
        ) is None
        assert compile_cache.io_disabled()
        chaos.clear()
        # still disabled for the rest of the process — no flapping
        assert compile_cache.enable_persistent_cache(
            str(tmp_path / "cc"), "deadbeef"
        ) is None
        assert _fault_count("compile_cache", "eio") >= 1
    finally:
        compile_cache._reset_io_disabled()


# ----------------------------------------------------------- supervisor
def test_crash_record_classifies_io_errno(tmp_path, monkeypatch):
    from sparknet_tpu.supervise import records as srec

    monkeypatch.setenv("SPARKNET_SUPERVISE_DIR", str(tmp_path))
    try:
        try:
            raise OSError(errno.ENOSPC, "disk full")
        except OSError as inner:
            try:
                raise RuntimeError("snapshot failed") from inner
            except RuntimeError as outer:
                path = srec.write_crash_record(outer)
    finally:
        monkeypatch.delenv("SPARKNET_SUPERVISE_DIR")
    assert path is not None
    with open(path) as fh:
        record = json.load(fh)
    assert record["io_errno"] == "enospc"
    # non-io crashes carry no classification
    assert srec._io_classification(ValueError("nope")) is None
    assert srec._io_classification(OSError(errno.EIO, "bad media")) == "eio"
