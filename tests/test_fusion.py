"""Dispatch fusion (ISSUE 12): the fused train step — rng split +
iteration counter folded into the compiled program — must be BITWISE
identical to the legacy three-dispatch loop, survive restore, stay
out of the parallel solvers' way, and the trace-driven audit
(scripts/fusion_audit.py) must find the gaps that ground it."""

import json
import os
import subprocess
import sys

import numpy as np
import jax

from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.solver.trainer import Solver

REPO = os.path.join(os.path.dirname(__file__), "..")
AUDIT = os.path.join(REPO, "scripts", "fusion_audit.py")

TINY_NET = """
name: "tiny"
layer { name: "d" type: "Input" top: "data" top: "label" }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss" }
"""
SOLVER_TXT = "base_lr: 0.1 momentum: 0.9 lr_policy: 'fixed' weight_decay: 0.001"
SHAPES = {"data": (8, 8), "label": (8,)}


def make_solver(seed=7):
    return Solver(
        caffe_pb.load_solver(SOLVER_TXT, is_path=False), SHAPES,
        net_param=caffe_pb.load_net(TINY_NET, is_path=False), seed=seed,
    )


def feed():
    rng = np.random.default_rng(11)
    while True:
        yield {
            "data": rng.normal(size=(8, 8)).astype(np.float32),
            "label": rng.integers(0, 4, size=(8,)).astype(np.int32),
        }


def leaves(params):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        jax.device_get(params)
    )]


def test_fused_step_bitwise_equals_legacy():
    """jax.random.split is the same deterministic function inside and
    outside jit: folding it (and the counter) into the step changes
    dispatch count, never the rng stream or the weights."""
    legacy = make_solver()
    legacy._fuse_host = False
    fused = make_solver()
    fused._fuse_host = True
    legacy.step(feed(), 6)
    fused.step(feed(), 6)
    assert legacy.iter == fused.iter == 6
    for a, b in zip(leaves(legacy.params), leaves(fused.params)):
        np.testing.assert_array_equal(a, b)
    # the rng key itself advanced identically
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(legacy.rng)),
        np.asarray(jax.device_get(fused.rng)),
    )


def test_fused_step_is_the_default_and_env_disables(monkeypatch):
    assert make_solver()._fuse_host is True
    monkeypatch.setenv("SPARKNET_FUSED_STEP", "0")
    assert make_solver()._fuse_host is False


def test_fused_resume_reseeds_device_counter(tmp_path):
    """restore() must invalidate the on-device iteration counter, so
    an interrupted fused run resumes bit-identically to the
    uninterrupted one (LR schedules read the counter)."""
    base = make_solver()
    base._fuse_host = True
    base.step(feed(), 8)

    first = make_solver()
    first._fuse_host = True
    f = feed()
    first.step(f, 4)
    path = str(tmp_path / "mid_iter_4.solverstate.npz")
    first.save(path)

    resumed = make_solver()
    resumed._fuse_host = True
    resumed.step(feed(), 2)  # park the counter somewhere wrong
    resumed.restore(path)
    assert resumed._it_dev is None
    resumed.align_feed(g := feed())
    resumed.step(g, 4)
    assert resumed.iter == 8
    for a, b in zip(leaves(base.params), leaves(resumed.params)):
        np.testing.assert_array_equal(a, b)


def test_parallel_solver_opts_out_of_fusion():
    from sparknet_tpu.parallel import ParallelSolver, make_mesh

    par = ParallelSolver(
        caffe_pb.load_solver(SOLVER_TXT, is_path=False), SHAPES,
        net_param=caffe_pb.load_net(TINY_NET, is_path=False), seed=7,
        mesh=make_mesh(), mode="sync",
    )
    assert par._fuse_host is False


# ------------------------------------------------------------ fusion audit
def synth_trace(gap_us=0.0, iters=5, put_us=50.0):
    """A timeline-shaped Chrome trace: input_wait -> device_put ->
    compiled_step per iteration, with ``gap_us`` of unattributed host
    time inserted before each compiled_step."""
    evs = []
    ts = 1000.0
    for _ in range(iters):
        for name, dur in (
            ("input_wait", 100.0),
            ("device_put", put_us),
            ("compiled_step", 800.0),
        ):
            if name == "compiled_step":
                ts += gap_us
            evs.append({"name": name, "ph": "X", "ts": ts, "dur": dur,
                        "pid": 1, "tid": 1, "cat": "timeline"})
            ts += dur
    return {"traceEvents": evs}


def run_audit(tmp_path, doc, *args):
    p = tmp_path / "trace.json"
    p.write_text(json.dumps(doc))
    return subprocess.run(
        [sys.executable, AUDIT, str(p), *args],
        capture_output=True, text=True, timeout=120,
    )


def test_audit_finds_dispatch_gap(tmp_path):
    r = run_audit(tmp_path, synth_trace(gap_us=300.0), "--json",
                  "--informational")
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    kinds = [f["kind"] for f in rec["findings"]]
    assert "dispatch_gap" in kinds
    assert rec["iterations"] == 5
    # the gap aggregates on the transition where it was inserted
    top = next(iter(rec["transitions"]))
    assert top == "device_put -> compiled_step"
    # gating mode: findings exit 1 without --informational
    assert run_audit(tmp_path, synth_trace(gap_us=300.0)).returncode == 1


def test_audit_clean_trace_has_no_findings(tmp_path):
    r = run_audit(tmp_path, synth_trace(gap_us=0.0), "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    gating = [f for f in rec["findings"]
              if not f.get("informational")]
    assert gating == []


def test_audit_flags_device_put_stalls(tmp_path):
    doc = synth_trace(gap_us=0.0, put_us=900.0)
    r = run_audit(tmp_path, doc, "--json", "--informational")
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert "device_put_stall" in [f["kind"] for f in rec["findings"]]


def test_audit_reads_a_real_solver_trace(tmp_path):
    """End to end: a traced legacy run's capture parses, attributes
    the timeline phases, and counts the iterations."""
    from sparknet_tpu.telemetry import timeline as ttl
    from sparknet_tpu.telemetry import trace as tr

    path = str(tmp_path / "real.json")
    s = make_solver()
    s._fuse_host = False
    tr.enable(path)
    try:
        tl = ttl.Timeline(fence=True)
        s.timeline = tl
        tl.start()
        s.step(feed(), 5)
        tl.stop()
        tr.write(path)
    finally:
        tr.disable()
    r = subprocess.run(
        [sys.executable, AUDIT, path, "--json", "--informational"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["iterations"] == 5
    assert "compiled_step" in rec["phases"]
    assert "perf_counter" not in open(AUDIT).read()
