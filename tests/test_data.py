import numpy as np

from sparknet_tpu.data.rdd import ShardedDataset
from sparknet_tpu.data.preprocess import Transformer
from sparknet_tpu.data.cifar import synthetic_cifar10, cifar10_dataset, _decode_binary
from sparknet_tpu.proto.textformat import parse


def test_sharded_dataset_partitions_and_shard():
    data = {"x": np.arange(100), "y": np.arange(100) * 2}
    ds = ShardedDataset.from_arrays(data, 8)
    assert ds.num_partitions == 8
    # all elements present exactly once across partitions
    seen = np.concatenate([ds.collect_partition(i)["x"] for i in range(8)])
    assert sorted(seen.tolist()) == list(range(100))
    # host sharding is disjoint and complete
    s0 = ds.shard(0, 2)
    s1 = ds.shard(1, 2)
    a = np.concatenate([s0.collect_partition(i)["x"] for i in range(s0.num_partitions)])
    b = np.concatenate([s1.collect_partition(i)["x"] for i in range(s1.num_partitions)])
    assert sorted(np.concatenate([a, b]).tolist()) == list(range(100))
    assert set(a.tolist()).isdisjoint(b.tolist())


def test_batches_deterministic_and_complete():
    data = {"x": np.arange(64)}
    ds = ShardedDataset.from_arrays(data, 4)
    b1 = [b["x"].copy() for b in ds.batches(8, seed=5, epochs=1)]
    b2 = [b["x"].copy() for b in ds.batches(8, seed=5, epochs=1)]
    assert len(b1) == 8
    np.testing.assert_array_equal(np.concatenate(b1), np.concatenate(b2))
    assert sorted(np.concatenate(b1).tolist()) == list(range(64))


def test_map_partitions_lazy_lineage():
    calls = []
    ds = ShardedDataset([lambda: calls.append(1) or np.arange(4)])
    ds2 = ds.map_partitions(lambda p: p * 10)
    assert calls == []  # lazy until collected
    np.testing.assert_array_equal(ds2.collect_partition(0), [0, 10, 20, 30])
    # lineage recompute: collecting again re-runs the source
    ds2.collect_partition(0)
    assert len(calls) == 2


def test_transformer_caffe_semantics():
    m = parse('scale: 0.5 crop_size: 4 mirror: true mean_value: 10')
    t = Transformer.from_message(m, train=False)
    x = np.full((2, 8, 8, 3), 20, np.uint8)
    rng = np.random.default_rng(0)
    y = t(x, rng)
    assert y.shape == (2, 4, 4, 3)
    np.testing.assert_allclose(y, (20 - 10) * 0.5)

    # train-mode random crop stays in bounds and is deterministic per rng
    t2 = Transformer.from_message(m, train=True)
    y2 = t2(np.arange(2 * 8 * 8 * 3, dtype=np.uint8).reshape(2, 8, 8, 3),
            np.random.default_rng(1))
    assert y2.shape == (2, 4, 4, 3)


def test_cifar_binary_decode_and_synthetic():
    # build a fake caffe-format record
    img_chw = np.arange(3072, dtype=np.uint8)
    rec = np.concatenate([[7], img_chw]).astype(np.uint8).tobytes()
    images, labels = _decode_binary(rec)
    assert labels.tolist() == [7]
    assert images.shape == (1, 32, 32, 3)
    # CHW -> HWC: channel plane c at (y,x) = img_chw[c*1024 + y*32 + x]
    assert images[0, 1, 2, 2] == img_chw[2 * 1024 + 1 * 32 + 2]

    ims, lbs = synthetic_cifar10(100, seed=3)
    ims2, _ = synthetic_cifar10(100, seed=3)
    np.testing.assert_array_equal(ims, ims2)
    assert ims.shape == (100, 32, 32, 3) and lbs.min() >= 0 and lbs.max() <= 9

    ds, mean = cifar10_dataset(None, train=True, synthetic_n=200)
    assert mean.shape == (32, 32, 3)
    batch = next(ds.batches(16, epochs=1))
    assert batch["data"].shape == (16, 32, 32, 3)


def test_prefetch_to_device_preserves_sequence_and_errors():
    """The device-prefetch wrapper must be order-preserving (bitwise
    determinism) and relay source-iterator exceptions."""
    import numpy as np

    from sparknet_tpu.data.prefetch import prefetch_to_device

    src = [{"data": np.full((2, 2), i, np.float32), "label": np.array([i])}
           for i in range(7)]
    got = list(prefetch_to_device(iter(src), size=3))
    assert len(got) == 7
    for i, b in enumerate(got):
        np.testing.assert_array_equal(np.asarray(b["data"]), src[i]["data"])

    # size=0 disables the thread but still places on device
    got0 = list(prefetch_to_device(iter(src[:2]), size=0))
    assert len(got0) == 2

    def boom():
        yield src[0]
        raise RuntimeError("feed died")

    it = prefetch_to_device(boom(), size=2)
    next(it)
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="feed died"):
        next(it)


def test_prefetch_training_is_bit_identical():
    """Training through the prefetch wrapper must produce bitwise the
    same weights as the raw feed (same batch order, same math)."""
    import numpy as np
    import jax

    from sparknet_tpu.data.prefetch import prefetch_to_device
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.trainer import Solver

    net_txt = """
name: "pf"
layer { name: "data" type: "Input" top: "data" }
layer { name: "label" type: "Input" top: "label" }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param { num_output: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
"""
    sp_txt = "base_lr: 0.1\nlr_policy: \"fixed\"\nmomentum: 0.9\nmax_iter: 5\n"

    def feed():
        rng = np.random.default_rng(7)
        while True:
            yield {
                "data": rng.normal(size=(4, 6)).astype(np.float32),
                "label": rng.integers(0, 3, 4).astype(np.int32),
            }

    results = []
    for wrap in (False, True):
        sp = caffe_pb.load_solver(sp_txt, is_path=False)
        sp.net_param = caffe_pb.load_net(net_txt, is_path=False)
        solver = Solver(sp, {"data": (4, 6), "label": (4,)})
        f = prefetch_to_device(feed(), size=2) if wrap else feed()
        solver.step(f, 5)
        results.append(jax.device_get(solver.params))
    a, b = results
    for layer in a:
        for name in a[layer]:
            np.testing.assert_array_equal(a[layer][name], b[layer][name])


def test_prefetch_close_shutdown_contract():
    """Pins _put_checked's shutdown contract: closing the consumer
    stops the worker thread (it gives up its blocked put instead of
    hanging on the full queue forever), and no batch loss is observable
    before the close — everything yielded is the exact source prefix."""
    import threading
    import time

    from sparknet_tpu.data.prefetch import prefetch_to_device

    src = [{"data": np.full((4,), i, np.float32)} for i in range(50)]
    before = set(threading.enumerate())
    it = prefetch_to_device(iter(src), size=3)
    got = [next(it) for _ in range(5)]
    # the worker is now parked in _put_checked on the full queue
    it.close()
    for i, b in enumerate(got):
        np.testing.assert_array_equal(np.asarray(b["data"]), src[i]["data"])
    # worker exits promptly after close (the 0.1 s put timeout polls the
    # stop event); staged-but-undelivered batches are dropped silently,
    # which is exactly the contract: loss is only ever post-close
    deadline = time.time() + 5
    extra = []
    while time.time() < deadline:
        extra = [
            t for t in threading.enumerate()
            if t not in before and t.is_alive()
        ]
        if not extra:
            break
        time.sleep(0.01)
    assert not extra, f"prefetch worker leaked past close: {extra}"


def test_batch_iterator_skip_matches_consumed():
    """skip(n) must position the feed exactly where n next() calls
    would, including the per-batch transform RNG (resume contract)."""
    import numpy as np

    from sparknet_tpu.data.rdd import ShardedDataset

    rng = np.random.default_rng(0)
    ds = ShardedDataset.from_arrays(
        {"data": rng.normal(size=(40, 3)).astype(np.float32),
         "label": np.arange(40, dtype=np.int32)},
        num_partitions=4,
    )

    def aug(batch, r):
        return {
            "data": batch["data"] + r.normal(size=batch["data"].shape),
            "label": batch["label"],
        }

    a = ds.batches(8, shuffle=True, seed=3, transform=aug)
    for _ in range(5):  # crosses an epoch boundary (5 batches/epoch)
        next(a)
    want = next(a)

    b = ds.batches(8, shuffle=True, seed=3, transform=aug)
    b.skip(5)
    got = next(b)
    np.testing.assert_array_equal(got["label"], want["label"])
    np.testing.assert_allclose(got["data"], want["data"], rtol=1e-6)
