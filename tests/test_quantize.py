"""Quantized inference (serve/quantize.py, ISSUE 12): per-channel
scale capture from real snapshots, int8 pack/unpack bit-stability
across processes, f32-vs-int8 top-1 agreement, fingerprint uniqueness
across (arch, layout, precision), the engine/server/router quant
surfaces, and the bench_diff gates."""

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest
import jax

from sparknet_tpu.nets.xlanet import XLANet
from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.serve import quantize
from sparknet_tpu.serve.compile_cache import net_fingerprint
from sparknet_tpu.serve.engine import InferenceEngine
from sparknet_tpu.solver import snapshot as snap

REPO = os.path.join(os.path.dirname(__file__), "..")

TOY_DEPLOY = """
name: "toy"
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 4 kernel_size: 3 pad: 1
          weight_filler { type: "gaussian" std: 0.2 } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
        pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
        inner_product_param { num_output: 5
          weight_filler { type: "gaussian" std: 0.2 } } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""

TOY2_DEPLOY = TOY_DEPLOY.replace("num_output: 5", "num_output: 6")


def toy_net(text=TOY_DEPLOY, seed=7):
    net = XLANet(caffe_pb.load_net(text, is_path=False), "TEST")
    params, state = net.init(jax.random.PRNGKey(seed))
    return net, params, state


def toy_engine(quant=None, buckets=(4, 8), seed=7, warm=True):
    net, params, state = toy_net(seed=seed)
    eng = InferenceEngine(net, params, state, buckets=buckets,
                          quant=quant)
    return eng.warmup() if warm else eng


def toy_rows(n, seed=0):
    return (
        np.random.default_rng(seed).normal(size=(n, 8, 8, 3))
        .astype(np.float32)
    )


# ------------------------------------------------------------ scale capture
def test_weight_scale_is_per_output_channel_absmax():
    net, params, state = toy_net()
    scales = quantize.capture_scales(net, params)
    assert set(scales) == {"conv1", "ip1"}
    w = np.asarray(params["conv1"]["weight"])  # HWIO
    want = np.abs(w).reshape(-1, w.shape[-1]).max(0) / 127.0
    np.testing.assert_allclose(scales["conv1"], want, rtol=1e-6)
    assert scales["conv1"].shape == (4,)
    assert scales["ip1"].shape == (5,)


def test_quantize_dequantize_error_bounded_by_half_scale():
    net, params, state = toy_net()
    q = quantize.quantize_tree(net, params)
    assert np.asarray(q["conv1"]["weight"]).dtype == np.int8
    deq = quantize.dequantize_tree(q)
    for lname in ("conv1", "ip1"):
        w = np.asarray(params[lname]["weight"])
        err = np.abs(np.asarray(deq[lname]["weight"]) - w)
        step = np.asarray(q[lname][quantize.SCALE_KEY])
        assert (err <= step / 2 + 1e-7).all(), lname
        # biases ride through untouched
        np.testing.assert_array_equal(
            np.asarray(deq[lname]["bias"]),
            np.asarray(params[lname]["bias"]),
        )


def test_scale_capture_from_verified_snapshot(tmp_path):
    """The hot-swap capture path: scales come from the newest
    manifest-INTACT solverstate — a torn newer file is skipped."""
    net, params, state = toy_net()
    prefix = str(tmp_path / "w")
    good = f"{prefix}_iter_10.solverstate.npz"
    snap.save_state(good, params=jax.device_get(params),
                    state=jax.device_get(state))
    # a torn newer snapshot must be skipped, not quantized
    torn = f"{prefix}_iter_20.solverstate.npz"
    with open(good, "rb") as fh:
        raw = fh.read()
    with open(torn, "wb") as fh:
        fh.write(raw[: len(raw) // 2])
    qparams, qstate, it = quantize.quantize_snapshot(net, prefix)
    assert it == 10
    want = quantize.quantize_tree(net, jax.device_get(params))
    np.testing.assert_array_equal(
        np.asarray(qparams["conv1"]["weight"]),
        np.asarray(want["conv1"]["weight"]),
    )


def test_int8_pack_bit_stable_across_processes(tmp_path):
    """The packed tree round-trips the snapshot format bit-exactly in
    a DIFFERENT process (no float re-derivation on load)."""
    net, params, state = toy_net()
    q = quantize.quantize_tree(net, params)
    path = str(tmp_path / "q_iter_1.solverstate.npz")
    snap.save_state(path, params=jax.device_get(q))

    def digest(tree):
        h = hashlib.sha256()
        for kp, leaf in sorted(
            jax.tree_util.tree_flatten_with_path(tree)[0],
            key=lambda t: jax.tree_util.keystr(t[0]),
        ):
            a = np.asarray(leaf)
            h.update(jax.tree_util.keystr(kp).encode())
            h.update(str(a.dtype).encode())
            h.update(a.tobytes())
        return h.hexdigest()

    child = subprocess.run(
        [sys.executable, "-c", (
            "import sys, hashlib, numpy as np, jax\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from sparknet_tpu.solver import snapshot as snap\n"
            "st = snap.load_state(sys.argv[1])\n"
            "h = hashlib.sha256()\n"
            "for kp, leaf in sorted("
            "jax.tree_util.tree_flatten_with_path(st['params'])[0],"
            "key=lambda t: jax.tree_util.keystr(t[0])):\n"
            "    a = np.asarray(leaf)\n"
            "    h.update(jax.tree_util.keystr(kp).encode())\n"
            "    h.update(str(a.dtype).encode())\n"
            "    h.update(a.tobytes())\n"
            "print(h.hexdigest())\n"
        ), path],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert child.returncode == 0, child.stderr
    assert child.stdout.strip() == digest(q)


# --------------------------------------------------------------- agreement
def test_int8_and_bf16_top1_agreement():
    f32 = toy_engine()
    int8 = toy_engine(quant="int8")
    bf16 = toy_engine(quant="bf16")
    rows = toy_rows(128)
    ref, _ = f32.topk(rows, 1)
    for eng in (int8, bf16):
        idx, _ = eng.topk(rows, 1)
        agree = float((idx[:, 0] == ref[:, 0]).mean())
        assert agree >= 0.995, (eng.quant, agree)


def test_int8_padded_rows_bit_identical():
    """Per-ROW activation scales: a request's outputs can't depend on
    the engine's zero padding or bucket co-riders (the serving
    row-independence contract, held for int8 like f32)."""
    eng = toy_engine(quant="int8", buckets=(4,))
    rows = toy_rows(4, seed=3)
    full = np.asarray(eng.infer(rows))
    part = np.asarray(eng.infer(rows[:2]))  # padded 2 -> 4
    np.testing.assert_array_equal(part, full[:2])


# ------------------------------------------------------------- fingerprints
def test_fingerprint_unique_across_arch_layout_precision():
    from sparknet_tpu.parallel import partition

    net, params, state = toy_net()
    net2, params2, state2 = toy_net(TOY2_DEPLOY)
    q = quantize.quantize_tree(net, params)
    lay = partition.parse_layout("dp=1", rules="tp")
    fps = {
        "f32": net_fingerprint(net, params, state, "float32"),
        "bf16": net_fingerprint(
            net, quantize.bf16_tree(params), state, "bfloat16",
            quant="bf16",
        ),
        "int8": net_fingerprint(net, q, state, "float32", quant="int8"),
        "arch2": net_fingerprint(net2, params2, state2, "float32"),
        "layout": net_fingerprint(
            net, params, state, "float32", layout=lay
        ),
    }
    assert len(set(fps.values())) == len(fps), fps


def test_engine_quant_modes_never_share_executable_keys():
    f32 = toy_engine(warm=False)
    int8 = toy_engine(quant="int8", warm=False)
    bf16 = toy_engine(quant="bf16", warm=False)
    assert len({f32.fingerprint, int8.fingerprint, bf16.fingerprint}) == 3
    # the in-memory executable cache key leads with the fingerprint
    f32._executable(4)
    int8._executable(4)
    keys = set(f32._cache) | set(int8._cache)
    assert len(keys) == 2


def test_quant_mode_validation():
    with pytest.raises(ValueError, match="quant mode"):
        toy_engine(quant="fp4", warm=False)
    from sparknet_tpu.parallel import partition

    net, params, state = toy_net()
    with pytest.raises(ValueError, match="layout"):
        InferenceEngine(
            net, params, state, buckets=(4,), quant="int8",
            layout=partition.parse_layout("dp=1", rules="tp"),
        )


# ----------------------------------------------------------------- hot swap
def test_int8_hot_swap_recaptures_scales(tmp_path):
    """swap_from_file on an int8 engine: scales re-captured from the
    verified snapshot (outputs track the new weights), generation
    bumps, and the merge base is the retained f32 reference — not the
    quantized tree."""
    eng = toy_engine(quant="int8", buckets=(4,))
    rows = toy_rows(4, seed=5)
    out0 = np.asarray(eng.infer(rows))

    # scaled-up weights -> different scales, different outputs
    net, params, state = toy_net()
    scaled = jax.tree_util.tree_map(
        lambda a: np.asarray(a) * 2.0, jax.device_get(params)
    )
    w = str(tmp_path / "w_iter_20.solverstate.npz")
    snap.save_state(w, params=scaled, state=jax.device_get(state))
    gen = eng.swap_from_file(w)
    assert gen == 1 and eng.quant == "int8"
    assert np.asarray(eng.params["conv1"]["weight"]).dtype == np.int8
    out1 = np.asarray(eng.infer(rows))
    assert not np.array_equal(out0, out1)
    # swapping the SAME file again is bit-stable (scale capture is
    # deterministic) and keeps bumping the generation
    gen2 = eng.swap_from_file(w)
    assert gen2 == 2
    np.testing.assert_array_equal(out1, np.asarray(eng.infer(rows)))


# ------------------------------------------------------- HTTP quant surface
def test_server_exposes_quant_on_healthz_and_classify():
    from sparknet_tpu.serve.server import InferenceServer

    eng = toy_engine(quant="int8", buckets=(4,))
    server = InferenceServer(eng, port=0).start()
    try:
        client = server.client(timeout=30)
        st, hz = client.healthz()
        assert st == 200 and hz["quant"] == "int8"
        st, resp = client.classify(toy_rows(2), top_k=2)
        assert st == 200 and resp["quant"] == "int8"
        assert "gen" in resp
    finally:
        server.stop()


def test_router_quant_ab_splits_and_records(tmp_path):
    """A 2-replica f32+int8 tier under quant_ab=0.5: the Bresenham
    draw splits a burst exactly in half, both variants answer, and
    the replica table carries the precision column's source field."""
    from sparknet_tpu.serve.loadgen import run_http_loadgen
    from sparknet_tpu.serve.router import Router
    from sparknet_tpu.serve.server import InferenceServer

    servers = [
        InferenceServer(toy_engine(buckets=(4,)), port=0).start(),
        InferenceServer(
            toy_engine(quant="int8", buckets=(4,)), port=0
        ).start(),
    ]
    router = Router(
        [(s.host, s.port) for s in servers], quant_ab=0.5
    )
    try:
        assert router.wait_healthy(timeout_s=30)
        router.start()
        lg = run_http_loadgen(
            router.host, router.port, (8, 8, 3),
            n_requests=40, sizes=(1, 2), concurrency=1,
        )
        assert lg["failed_requests"] == 0
        assert lg["served_quants"] == ["f32", "int8"]
        hz = router.healthz()
        assert hz["quant_ab"] == 0.5
        assert hz["quants"] == ["f32", "int8"]
        answered = {
            r["quant"]: r["forwarded"] for r in hz["replicas"]
        }
        assert answered == {"f32": 20, "int8": 20}, answered
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_router_quant_ab_falls_back_when_variant_down():
    """Variant preference never beats availability: with the int8
    replica dead, quant-preferring requests still answer on f32."""
    from sparknet_tpu.serve.router import Router
    from sparknet_tpu.serve.server import Client, InferenceServer

    f32_server = InferenceServer(toy_engine(buckets=(4,)), port=0).start()
    int8_server = InferenceServer(
        toy_engine(quant="int8", buckets=(4,)), port=0
    ).start()
    router = Router(
        [(f32_server.host, f32_server.port),
         (int8_server.host, int8_server.port)],
        quant_ab=1.0,  # EVERY request prefers the quant variant
        eject_after=1,
    )
    try:
        assert router.wait_healthy(timeout_s=30)
        router.start()
        int8_server.stop()
        client = Client(router.host, router.port, timeout=30, retries=4)
        oks = 0
        for _ in range(6):
            st, resp = client.classify(toy_rows(1), top_k=1)
            if st == 200:
                oks += 1
                assert resp["quant"] == "f32"
        assert oks == 6
    finally:
        router.stop()
        f32_server.stop()


def test_dash_replica_table_has_precision_column():
    from sparknet_tpu.telemetry import dash

    page = dash.render_html(
        {},
        router={
            "replicas_healthy": 1,
            "replicas_total": 1,
            "generations": [0],
            "router": {},
            "replicas": [{
                "index": 0, "healthy": True, "addr": "x:1",
                "outstanding": 0, "generation": 0, "quant": "int8",
                "forwarded": 3, "latency": {},
            }],
        },
    )
    assert "<th>precision</th>" in page
    assert "<td>int8</td>" in page


# ------------------------------------------------------------ bench_diff gates
def _diff(tmp_path, old, new, *args):
    o = tmp_path / "old.json"
    n = tmp_path / "new.json"
    o.write_text(json.dumps(old))
    n.write_text(json.dumps(new))
    return subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "bench_diff.py"),
         str(o), str(n), *args],
        capture_output=True, text=True, timeout=120,
    )


def test_bench_diff_gates_quant_fields(tmp_path):
    base = {
        "metric": "quant_serving_int8_speedup", "value": 2.0,
        "int8_speedup": 2.0, "bf16_speedup": 1.3,
        "int8_disagree_pct": 0.1, "bf16_disagree_pct": 0.0,
        "int8_weight_compression": 3.9,
        "fingerprints_distinct": True,
        "speedup_gate": "gated",
    }
    ok = _diff(tmp_path, base, base)
    assert ok.returncode == 0, ok.stdout + ok.stderr

    # accuracy bar is absolute
    bad = dict(base, int8_disagree_pct=0.8)
    r = _diff(tmp_path, base, bad)
    assert r.returncode == 1 and "int8_disagree_pct" in r.stdout

    # aliasing fingerprints always regress
    bad = dict(base, fingerprints_distinct=False)
    assert _diff(tmp_path, base, bad).returncode == 1

    # speed floors gate accelerator records...
    bad = dict(base, int8_speedup=1.1, value=1.1)
    r = _diff(tmp_path, base, bad, "--throughput-pct", "99")
    assert r.returncode == 1 and "1.5" in r.stdout
    # ...but a cpu-labeled record is informational for speed
    cpu = dict(base, int8_speedup=0.2, value=0.2, bf16_speedup=0.9,
               speedup_gate="informational-on-cpu")
    r = _diff(tmp_path, cpu, cpu)
    assert r.returncode == 0 and "cpu-informational" in r.stdout

    # the memory-side floor holds everywhere
    bad = dict(base, int8_weight_compression=1.2)
    assert _diff(tmp_path, base, bad).returncode == 1


def test_bench_diff_gates_fusion_speedup(tmp_path):
    base = {
        "metric": "fusion_step_ms_fused", "value": 0.5,
        "step_ms_legacy": 1.0, "step_ms_fused": 0.5,
        "fusion_speedup": 2.0,
    }
    assert _diff(tmp_path, base, base).returncode == 0
    bad = dict(base, fusion_speedup=0.97, step_ms_fused=1.03, value=1.03)
    r = _diff(tmp_path, base, bad, "--throughput-pct", "999")
    assert r.returncode == 1 and "fusion_speedup" in r.stdout
