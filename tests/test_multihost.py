"""Multi-host layer (SURVEY.md §1 Deployment): 2-process CPU simulation
must produce the exact global-batch semantics of a single process."""

import os
import socket
import subprocess
import sys

import numpy as np
import jax
import pytest

from tests import _multihost_worker as worker
from sparknet_tpu.nets import weights as W
from sparknet_tpu.parallel import make_mesh, multihost


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_helpers_single_process():
    assert multihost.initialize() is False  # no coordinator -> no-op
    assert multihost.is_primary()
    assert multihost.process_count() == 1
    ds_like = type("DS", (), {"shard": lambda *a: pytest.fail("sharded")})()
    assert multihost.host_shard(ds_like) is ds_like  # identity at 1 proc


def _spawn_cluster(out, mode="sync", extra_env=None):
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["SPARKNET_HEARTBEAT_PORT"] = str(_free_port())
    env.update(extra_env or {})
    return [
        subprocess.Popen(
            [sys.executable, worker.__file__, coord, str(i), out, mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for i in (0, 1)
    ]


def _run_cluster(out, mode="sync"):
    procs = _spawn_cluster(out, mode)
    logs = [p.communicate(timeout=600)[0].decode() for p in procs]
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    return logs


def test_two_processes_match_single_process(tmp_path):
    out = str(tmp_path / "mh_params.npz")
    logs = _run_cluster(out, "sync")
    assert os.path.exists(out), logs[0]

    # single-process reference over the SAME global batches
    solver = worker.build_solver(make_mesh({"dp": 4}, jax.devices()[:4]))
    solver.step(iter(worker.global_batches()), worker.N_STEPS)
    ref = jax.device_get(solver.params)
    got = W.load_npz(out)
    for layer, ps in ref.items():
        for name, arr in ps.items():
            np.testing.assert_allclose(
                got[layer][name], np.asarray(arr), rtol=2e-5, atol=1e-6,
                err_msg=f"{layer}.{name}",
            )


def test_end_broadcast_reaches_reconnecting_worker():
    """The end-broadcast race (ISSUE 4 satellite): a worker that was
    mid-reconnect when process 0's close() broadcast went out must
    still receive ``end`` from the lingering server — not misread the
    situation as a dead peer.  Drives :class:`_Heartbeat` directly (no
    jax.distributed, no cluster)."""
    import struct
    import threading
    import time

    from sparknet_tpu.parallel.multihost import _Heartbeat, _recv_exactly

    port = _free_port()
    hb = _Heartbeat("127.0.0.1", port, 0, 2, interval=1.0, timeout=10.0)
    try:
        # worker 1 joins, pings once, then drops its connection — the
        # "mid-reconnect when the broadcast went out" state
        c = socket.create_connection(("127.0.0.1", port), timeout=5)
        c.sendall(struct.pack("!i", 1))
        assert _recv_exactly(c, 3) == b"ok\n"
        c.close()
        closer = threading.Thread(target=hb.close)
        closer.start()
        deadline = time.monotonic() + 5
        while not hb._ending and time.monotonic() < deadline:
            time.sleep(0.005)
        # reconnect during the linger: the ack slot must carry "end"
        c2 = socket.create_connection(("127.0.0.1", port), timeout=5)
        c2.sendall(struct.pack("!i", 1))
        assert _recv_exactly(c2, 3) == b"end"
        # the graceful bye releases the linger early
        c2.sendall(struct.pack("!i", -2))
        _recv_exactly(c2, 3)
        c2.close()
        closer.join(10)
        assert not closer.is_alive()
    finally:
        hb._stop.set()


def test_worker_rejoins_fabric_after_graceful_bye():
    """Rejoin grace (ISSUE 4): after a worker's graceful bye, a new
    incarnation (per-host supervisor relaunch) pinging with the same id
    re-enters the monitored set instead of running unwatched."""
    import struct

    from sparknet_tpu.parallel.multihost import _Heartbeat, _recv_exactly

    port = _free_port()
    hb = _Heartbeat("127.0.0.1", port, 0, 2, interval=0.2, timeout=5.0)
    try:
        c = socket.create_connection(("127.0.0.1", port), timeout=5)
        c.sendall(struct.pack("!i", 1))
        assert _recv_exactly(c, 3) == b"ok\n"
        c.sendall(struct.pack("!i", -2))  # graceful bye
        _recv_exactly(c, 3)
        c.close()
        with hb._lock:
            assert 1 not in hb._expected
        c2 = socket.create_connection(("127.0.0.1", port), timeout=5)
        c2.sendall(struct.pack("!i", 1))
        assert _recv_exactly(c2, 3) == b"ok\n"
        with hb._lock:
            assert 1 in hb._expected  # monitored again
        c2.sendall(struct.pack("!i", -2))
        _recv_exactly(c2, 3)
        c2.close()
        hb.close()
    finally:
        hb._stop.set()


@pytest.mark.slow
def test_dead_peer_fails_the_job_fast(tmp_path):
    """Live failure detection (SURVEY.md §5): worker 1 dies hard
    mid-run; process 0 — blocked in a collective that will never
    complete — must exit non-zero within the heartbeat timeout instead
    of hanging until the job is killed externally."""
    import time

    from sparknet_tpu.parallel.multihost import EXIT_PEER_FAILURE

    procs = _spawn_cluster(
        str(tmp_path / "dead"), "droppeer",
        extra_env={"SPARKNET_HEARTBEAT_TIMEOUT": "4"},
    )
    t0 = time.monotonic()
    logs = [p.communicate(timeout=300)[0].decode() for p in procs]
    elapsed = time.monotonic() - t0
    assert procs[1].returncode == 7, logs[1]  # the simulated death
    # process 0: killed by the heartbeat monitor (or by JAX's own
    # distributed-runtime error if that fires first) — never 0, and
    # fast (bound dominated by startup/compile, not by any hang)
    assert procs[0].returncode not in (0, None), logs[0]
    assert elapsed < 240, f"took {elapsed:.0f}s — detection too slow"
    if procs[0].returncode == EXIT_PEER_FAILURE:
        assert "no heartbeat" in logs[0]


@pytest.mark.slow
def test_local_mode_collective_snapshot(tmp_path):
    """τ-local SGD across 2 processes: optimizer slots are dp-sharded
    across hosts; a snapshot must gather them collectively and still
    restore into a single-process solver."""
    from sparknet_tpu.solver import snapshot as snap

    out = str(tmp_path / "mh_local")
    _run_cluster(out, "local")
    path = out + ".solverstate.npz"
    assert os.path.exists(path)
    st = snap.load_state(path)
    assert st["it"] == worker.N_STEPS
    # local-mode slots carry the per-dp-slice leading axis (dp=4)
    mom = st["opt_state"]["momentum"]["conv1"]["weight"]
    assert mom.shape[0] == 4
    # and the gathered state restores into a fresh single-process solver
    solver = worker.build_solver(
        make_mesh({"dp": 4}, jax.devices()[:4]), mode="local", tau=2
    )
    solver.restore(path)
    assert solver.iter == worker.N_STEPS
