"""Caffe `Python` layer escape hatch: traceable callable registry.

The reference's Caffe engine loads python_param.module/.layer and runs
host-side setup/forward/backward (SURVEY.md §2 Caffe engine; mount
empty, no file:line). The TPU-native twin registers a *traceable*
callable instead, fused into the jitted step — these tests pin the
contract: bare-callable dispatch with eval_shape inference, the full
infer/init/apply protocol, module-qualified lookup with bare fallback,
gradient flow through the custom layer, and the unregistered error.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu.proto import caffe_pb
from sparknet_tpu.nets import layers as L
from sparknet_tpu.nets.xlanet import XLANet


def _net(text):
    return caffe_pb.load_net(text, is_path=False)


@pytest.fixture(autouse=True)
def _clean_registry():
    saved = dict(L.PYTHON_LAYER_REGISTRY)
    L.PYTHON_LAYER_REGISTRY.clear()
    yield
    L.PYTHON_LAYER_REGISTRY.clear()
    L.PYTHON_LAYER_REGISTRY.update(saved)


NET_TXT = """
name: "pynet"
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 2 dim: 8 } } }
layer { name: "py" type: "Python" bottom: "data" top: "py"
        python_param { module: "my_layers" layer: "DoubleShift"
                       param_str: "3.5" } }
layer { name: "ip" type: "InnerProduct" bottom: "py" top: "ip"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
"""


def test_bare_callable_end_to_end():
    L.register_python_layer(
        "my_layers.DoubleShift",
        lambda inputs, param_str: [2.0 * inputs[0] + float(param_str)],
    )
    net = XLANet(_net(NET_TXT), "TRAIN", {"data": (2, 8)})
    assert net.blob_shapes["py"] == (2, 8)  # eval_shape inference
    params, state = net.init(jax.random.PRNGKey(0))
    assert params.get("py", {}) == {}  # stateless: no params
    x = np.linspace(-1, 1, 16).reshape(2, 8).astype(np.float32)
    blobs, _ = net.apply(params, state, {"data": jnp.asarray(x)},
                         train=False, rng=None)
    w = np.asarray(params["ip"]["weight"])
    np.testing.assert_allclose(
        np.asarray(blobs["ip"]), (2.0 * x + 3.5) @ w, rtol=1e-5, atol=1e-5
    )


def test_bare_callable_is_differentiable():
    L.register_python_layer(
        "my_layers.DoubleShift",
        lambda inputs, param_str: [2.0 * inputs[0] + float(param_str)],
    )
    net = XLANet(_net(NET_TXT), "TRAIN", {"data": (2, 8)})
    params, state = net.init(jax.random.PRNGKey(0))
    x = jnp.ones((2, 8), jnp.float32)

    def loss(p):
        blobs, _ = net.apply(p, state, {"data": x}, train=False, rng=None)
        return jnp.sum(blobs["ip"] ** 2)

    g = jax.jit(jax.grad(loss))(params)  # autodiff replaces backward()
    assert float(jnp.sum(jnp.abs(g["ip"]["weight"]))) > 0.0


def test_bare_name_fallback_when_module_not_registered():
    L.register_python_layer(
        "DoubleShift",  # module-agnostic fallback key
        lambda inputs, param_str: [inputs[0] + float(param_str)],
    )
    net = XLANet(_net(NET_TXT), "TRAIN", {"data": (2, 8)})
    params, state = net.init(jax.random.PRNGKey(0))
    x = np.ones((2, 8), np.float32)
    blobs, _ = net.apply(params, state, {"data": jnp.asarray(x)},
                         train=False, rng=None)
    np.testing.assert_allclose(np.asarray(blobs["py"]), x + 3.5, rtol=1e-6)


def test_full_protocol_impl_with_params():
    class Gain:
        @staticmethod
        def infer(lp, in_shapes):
            return [in_shapes[0]]

        @staticmethod
        def init(lp, rng, in_shapes):
            return {"gain": jnp.full((in_shapes[0][-1],), 2.0)}

        @staticmethod
        def apply(lp, params, state, inputs, ctx):
            return [inputs[0] * params["gain"]], None

    L.register_python_layer("my_layers.DoubleShift", Gain)
    net = XLANet(_net(NET_TXT), "TRAIN", {"data": (2, 8)})
    params, state = net.init(jax.random.PRNGKey(0))
    assert params["py"]["gain"].shape == (8,)
    x = np.full((2, 8), 3.0, np.float32)
    blobs, _ = net.apply(params, state, {"data": jnp.asarray(x)},
                         train=False, rng=None)
    np.testing.assert_allclose(np.asarray(blobs["py"]), x * 2.0, rtol=1e-6)


def test_unregistered_python_layer_raises():
    with pytest.raises(KeyError, match="register_python_layer"):
        XLANet(_net(NET_TXT), "TRAIN", {"data": (2, 8)})


def test_decorator_registration():
    @L.register_python_layer("my_layers.DoubleShift")
    def double_shift(inputs, param_str):
        return [2.0 * inputs[0] + float(param_str)]

    assert L.PYTHON_LAYER_REGISTRY["my_layers.DoubleShift"] is double_shift
    import sparknet_tpu

    assert sparknet_tpu.register_python_layer is L.register_python_layer
