"""Worker process for the multi-host CPU simulation test.

Run as:  python _multihost_worker.py <coordinator> <process_id> <out.npz>

Each of the 2 worker processes owns 2 virtual CPU devices; together
they form one global 4-device dp mesh. Both feed only their host-local
rows of the same deterministic global batches; process 0 saves the
resulting params. The parent test compares against a single-process run
over the identical global batches.
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ZOO = os.path.join(REPO, "sparknet_tpu", "models", "prototxt")

GLOBAL_BS = 8
N_STEPS = 3


def global_batches():
    rng = np.random.default_rng(5)
    return [
        {
            "data": rng.normal(size=(GLOBAL_BS, 32, 32, 3)).astype(np.float32),
            "label": rng.integers(0, 10, GLOBAL_BS).astype(np.int32),
        }
        for _ in range(N_STEPS)
    ]


def build_solver(mesh, mode="sync", tau=1):
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.parallel import ParallelSolver

    sp = caffe_pb.load_solver(os.path.join(ZOO, "cifar10_quick_solver.prototxt"))
    sp.base_lr = 0.01
    shapes = {"data": (GLOBAL_BS, 32, 32, 3), "label": (GLOBAL_BS,)}
    return ParallelSolver(
        sp, shapes, solver_dir=REPO, mesh=mesh, mode=mode, tau=tau
    )


def main():
    coord, pid, out = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "sync"
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
    sys.path.insert(0, REPO)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from sparknet_tpu.parallel import make_mesh, multihost

    assert multihost.initialize(coord, 2, pid)
    assert jax.device_count() == 4 and jax.local_device_count() == 2

    solver = build_solver(
        make_mesh({"dp": 4}), mode="sync" if mode == "droppeer" else mode,
        tau=2 if mode == "local" else 1,
    )
    lo, hi = pid * GLOBAL_BS // 2, (pid + 1) * GLOBAL_BS // 2

    if mode == "droppeer":
        # liveness test: worker 1 dies hard after one step; process 0
        # keeps stepping, blocks in the next collective, and must be
        # killed by the heartbeat monitor (EXIT_PEER_FAILURE) instead
        # of hanging forever
        def feed():
            while True:
                for b in global_batches():
                    yield {k: v[lo:hi] for k, v in b.items()}

        m = solver.step(feed(), 1)
        assert np.isfinite(float(m["loss"]))
        if pid == 1:
            print("worker 1: simulating host death", flush=True)
            os._exit(7)
        solver.step(feed(), 10_000)  # expected: killed by the watchdog
        print("worker 0: UNEXPECTEDLY completed", flush=True)
        return

    def feed():
        for b in global_batches():
            yield {k: v[lo:hi] for k, v in b.items()}  # host-local rows

    m = solver.step(feed(), N_STEPS)
    assert np.isfinite(float(m["loss"]))
    if mode == "local":
        # collective snapshot: gathers the dp-sharded optimizer slots
        # across hosts; every process calls, process 0 writes
        solver.save(out + ".solverstate.npz")
    elif multihost.is_primary():
        from sparknet_tpu.nets import weights as W

        W.save_npz(out, jax.device_get(solver.params))
    multihost.stop_heartbeat()  # graceful leave, like the apps
    print(f"worker {pid}: done, loss={float(m['loss']):.6f}")


if __name__ == "__main__":
    main()
