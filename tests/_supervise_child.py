"""Dummy supervised child for supervisor policy tests (jax-free).

Run by tests/test_supervise.py under ``supervise.Supervisor`` to
exercise classification, attribution, elastic degrade and scale-up
without paying a JAX backend init per generation.

Behavior is driven by the environment:

- ``SPARKNET_SUPERVISE_GEN`` (set by the supervisor): generation index.
- ``TEST_CHILD_PLAN``: comma-separated per-generation actions:

  - ``crash<N>``      — rank N writes a failure record and exits 5;
    other ranks exit 0 after a short sleep.
  - ``healthy-crash`` — sleep ``TEST_CHILD_HEALTHY_S`` (default 0.6),
    then rank 0 writes a record and exits 5.
  - ``sigkill``       — rank 0 SIGKILLs itself (no record — the
    supervisor must synthesize one).
  - ``ok``            — exit 0.

  Generations past the end of the plan default to ``ok``.
"""

import os
import signal
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from sparknet_tpu.supervise import records  # noqa: E402


def main():
    gen = int(os.environ.get(records.GENERATION_ENV, "0") or 0)
    rank = int(os.environ.get("SPARKNET_PROCESS_ID", "0") or 0)
    plan = [p for p in os.environ.get("TEST_CHILD_PLAN", "").split(",") if p]
    action = plan[gen] if gen < len(plan) else "ok"

    if action == "ok":
        return 0
    if action == "sigkill":
        if rank == 0:
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(0.2)
        return 0
    if action == "healthy-crash":
        time.sleep(float(os.environ.get("TEST_CHILD_HEALTHY_S", "0.6")))
        if rank == 0:
            records.write_failure_record(
                process_id=rank, kind="test.crash",
                reason=f"planned healthy-crash in generation {gen}",
                exit_code=5,
            )
            return 5
        return 0
    if action.startswith("crash"):
        bad = int(action[len("crash"):] or 0)
        if rank == bad:
            records.write_failure_record(
                process_id=rank, kind="test.crash",
                reason=f"planned crash of rank {bad} in generation {gen}",
                exit_code=5,
            )
            return 5
        time.sleep(0.2)
        return 0
    raise SystemExit(f"unknown TEST_CHILD_PLAN action {action!r}")


if __name__ == "__main__":
    sys.exit(main())
