"""Live elastic resharding (parallel/reshard.py) on the 8-device
virtual CPU mesh.

Pins the ISSUE 14 contract:
- a mid-run ``dp=4`` -> ``dp=2,tp=2`` migration preserves params, BN
  state and optimizer slots BITWISE (``device_put`` is data movement,
  never arithmetic) and swaps the compiled step without a restart;
- continuing after the reshard is bitwise-equal to replaying the same
  iterations in a fresh layout-B job restored from the reshard-point
  snapshot (identical shardings -> identical executables), and
  ulp-close to a job that ran in layout B from the start (PR 10's
  cross-partitioning bar);
- snapshots taken after the reshard carry the NEW layout + specs, so
  an ``--auto-resume`` cannot silently relayout backwards;
- a second reshard to a layout seen earlier this run hits the
  per-layout step cache — the SAME jitted callable, no recompile;
- τ-local SGD / bucketed comm / layout-less solvers are rejected with
  a pointer, not a deep XLA error;
- the tau controller raises a ``layout`` advisory when a job stays
  sync-bound at tau_max (single-process only);
- the supervisor's degrade path rewrites ``--layout`` to the best
  table entry for the surviving mesh.
"""

import contextlib
import io
import json
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from sparknet_tpu.parallel import ParallelSolver, partition
from sparknet_tpu.parallel.partition import parse_layout
from sparknet_tpu.parallel.reshard import (
    RequestWatcher,
    ReshardError,
    degrade_layout,
    reshard,
)
from sparknet_tpu.proto import caffe_pb

from .test_parallel import SHAPES, TINY_NET, batch, tiny_net, tiny_solver

# a variant with BatchNorm so the net-state tree is non-trivial: the
# migration must carry running stats, not just params
BN_NET = """
name: "tiny_bn"
layer { name: "d" type: "Input" top: "data" top: "label" }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "xavier" } } }
layer { name: "bn1" type: "BatchNorm" bottom: "ip1" top: "bn1" }
layer { name: "relu1" type: "ReLU" bottom: "bn1" top: "bn1" }
layer { name: "ip2" type: "InnerProduct" bottom: "bn1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss" }
"""


def bn_net():
    return caffe_pb.load_net(BN_NET, is_path=False)


def feed_of(b):
    def gen():
        while True:
            yield b
    return gen()


def host_tree(tree):
    # np.array copies: on CPU device_get may alias device buffers that
    # later steps DONATE — a view would read freed memory
    return jax.tree_util.tree_map(lambda x: np.array(x), jax.device_get(tree))


def assert_tree_bitwise(a, b, what=""):
    for (ka, x), (kb, y) in zip(partition.tree_paths(a), partition.tree_paths(b)):
        assert ka == kb
        assert (np.asarray(x) == np.asarray(y)).all(), f"{what}:{ka}"


def dp4_solver(net_fn=tiny_net, **kw):
    return ParallelSolver(
        tiny_solver(), SHAPES, net_param=net_fn(), seed=7,
        layout=parse_layout("dp=4", rules="tp"), **kw
    )


# ---------------------------------------------------------------------------
# the migration itself
# ---------------------------------------------------------------------------

def test_reshard_bitwise_preserves_params_bn_state_and_opt_slots():
    s = dp4_solver(net_fn=bn_net)
    s.step(feed_of(batch(0)), 3)  # BN stats + momentum slots are live
    params0 = host_tree(s.params)
    state0 = host_tree(s.state)
    opt0 = host_tree(s.opt_state)
    assert any(np.asarray(x).any() for x in jax.tree_util.tree_leaves(state0))

    rec = s.reshard("dp=2,tp=2")

    assert_tree_bitwise(params0, host_tree(s.params), "params")
    assert_tree_bitwise(state0, host_tree(s.state), "state")
    assert_tree_bitwise(opt0, host_tree(s.opt_state), "opt")
    # the params really moved to the new table's placement
    assert s.params["ip1"]["weight"].sharding.spec == P(None, "tp")
    assert s.mesh.shape == {"dp": 2, "tp": 2}
    assert s.layout_report()["mesh"] == {"dp": 2, "tp": 2}
    assert rec["from"] == "dp=4" and rec["to"] == "dp=2,tp=2"
    assert rec["cache"] == "miss"
    assert rec["leaves_moved"] >= 1 and rec["bytes_relaid"] > 0
    assert rec["relayout_ms"] >= 0.0
    # training continues through the swapped step, in place
    m = s.step(feed_of(batch(0)), 2)
    assert np.isfinite(float(m["loss"]))


def test_reshard_records_new_layout_env_for_snapshots(tmp_path):
    """ISSUE 14 satellite: snapshots after an in-place reshard must
    carry the NEW layout + per-leaf specs — else a later --auto-resume
    silently relayouts backwards to layout A."""
    s = dp4_solver()
    s.step(feed_of(batch(1)), 2)
    s.reshard("dp=2,tp=2")
    assert json.loads(s.env_meta["layout"])["axes"] == [["dp", 2], ["tp", 2]]
    assert json.loads(s.env_meta["param_specs"]) == s._plan.specs
    s.step(feed_of(batch(1)), 1)
    snap = str(tmp_path / "post_iter_3.solverstate.npz")
    s.save(snap)

    # resume in the resharded layout: specs match, NO relayout warning
    b = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7,
        layout=parse_layout("dp=2,tp=2", rules="tp"),
    )
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        b.restore(snap)
    assert "relayout" not in err.getvalue()
    assert_tree_bitwise(host_tree(s.params), host_tree(b.params), "resume")


def test_reshard_then_continue_equals_replay_and_scratch(tmp_path):
    """Continue-training equivalence: bitwise vs a fresh layout-B job
    restored from the reshard-point snapshot (same shardings -> same
    executable), allclose vs a job started in layout B from scratch
    (cross-partitioning is reduction-order/ulp, PR 10's bar)."""
    b0 = batch(2)
    a = dp4_solver()
    a.step(feed_of(b0), 2)
    snap = str(tmp_path / "a_iter_2.solverstate.npz")
    a.save(snap)
    a.reshard("dp=2,tp=2")
    a.step(feed_of(b0), 3)

    replay = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7,
        layout=parse_layout("dp=2,tp=2", rules="tp"),
    )
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        replay.restore(snap)  # relayout-on-resume, warned
    assert "relayout on resume" in err.getvalue()
    replay.step(feed_of(b0), 3)
    assert_tree_bitwise(host_tree(a.params), host_tree(replay.params), "replay")

    scratch = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=7,
        layout=parse_layout("dp=2,tp=2", rules="tp"),
    )
    scratch.step(feed_of(b0), 5)
    for (k, x), (_, y) in zip(
        partition.tree_paths(host_tree(a.params)),
        partition.tree_paths(host_tree(scratch.params)),
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6, err_msg=k
        )


def test_second_reshard_to_seen_layout_hits_step_cache():
    s = dp4_solver()
    step_a = s._train_step
    s.step(feed_of(batch(3)), 1)
    rec1 = s.reshard("dp=2,tp=2")
    assert rec1["cache"] == "miss"
    step_b, eval_b = s._train_step, s._eval_step
    assert step_b is not step_a

    rec2 = s.reshard("dp=4")  # back to the starting layout: seeded hit
    assert rec2["cache"] == "hit"
    assert s._train_step is step_a

    rec3 = s.reshard("dp=2,tp=2")  # seen this run: the SAME callable,
    assert rec3["cache"] == "hit"  # so no retrace and no recompile
    assert s._train_step is step_b and s._eval_step is eval_b
    m = s.step(feed_of(batch(3)), 1)
    assert np.isfinite(float(m["loss"]))


def test_reshard_timeline_phase_and_registry_counter():
    from sparknet_tpu.telemetry import timeline as _ttl
    from sparknet_tpu.telemetry.registry import REGISTRY

    s = dp4_solver()
    tl = _ttl.Timeline(fence=True)
    s.timeline = tl
    tl.start()
    labels = {"from": "dp=4", "to": "dp=2,tp=2", "reason": "explicit"}
    before = REGISTRY.counter("reshard_events", **labels).snapshot()
    rec = s.reshard("dp=2,tp=2")
    assert tl.phase_seconds().get("reshard", 0.0) > 0.0
    assert "reshard" in _ttl.PHASES
    after = REGISTRY.counter("reshard_events", **labels).snapshot()
    assert after == before + 1
    assert rec["relayout_ms"] >= 0.0


# ---------------------------------------------------------------------------
# rejections: the comm path stays dp-only
# ---------------------------------------------------------------------------

def test_reshard_rejects_local_sgd_with_pointer():
    s = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=0,
        layout=parse_layout("dp=8"), mode="local", tau=2,
    )
    with pytest.raises(ReshardError, match="sync"):
        reshard(s, "dp=2,tp=2")
    # --tau auto rides the same local-SGD path: same rejection
    s2 = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=0,
        layout=parse_layout("dp=8"), mode="local", tau="auto",
    )
    with pytest.raises(ReshardError, match="shard_map|sync"):
        reshard(s2, "dp=4")


def test_reshard_rejects_bucketed_sync_and_layoutless():
    from sparknet_tpu.parallel import CommConfig, make_mesh

    s = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=0,
        layout=parse_layout("dp=8"),
        comm_config=CommConfig(mode="bucketed"),
    )
    with pytest.raises(ReshardError, match="grad-compress|bucketed"):
        reshard(s, "dp=4")
    s2 = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=0,
        mesh=make_mesh(), mode="sync",
    )
    with pytest.raises(ReshardError, match="layout"):
        reshard(s2, "dp=4")


def test_reshard_rejects_indivisible_batch_and_stays_usable():
    s = dp4_solver()
    with pytest.raises(ReshardError, match="not divisible"):
        s.reshard("dp=3")  # 16 % 3
    # rejected BEFORE any state moved: the solver still runs layout A
    assert s.mesh.shape == {"dp": 4}
    m = s.step(feed_of(batch(4)), 1)
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# triggers
# ---------------------------------------------------------------------------

def test_tau_controller_layout_advisory_at_tau_max():
    from sparknet_tpu.parallel.tau_controller import TauController
    from sparknet_tpu.telemetry import anomaly

    anomaly.clear()
    try:
        tc = TauController(tau=4, tau_min=1, tau_max=4, widen_share=0.25)
        assert tc.layout_advisory_rounds == 2
        # sync-bound rounds at tau_max: τ cannot widen, advisory fires
        # after the streak (advisories=[] == the single-process hook)
        tc.observe_round(round_s=1.0, sync_s=0.6, loss=1.0, advisories=[])
        assert not anomaly.active("layout")
        tc.observe_round(round_s=1.0, sync_s=0.6, loss=1.0, advisories=[])
        (adv,) = anomaly.active("layout")
        assert "reshard" in adv["suggestion"]
        assert tc.decisions[-1]["layout_advisory"] is True
        assert tc.snapshot()["layout_advisories"] == 1
        # a non-sync-bound round resets the streak
        tc.observe_round(round_s=1.0, sync_s=0.0, loss=1.0, advisories=[])
        assert tc._syncbound_at_max == 0
    finally:
        anomaly.clear()


def test_tau_controller_layout_advisory_multihost_gated():
    from sparknet_tpu.parallel.tau_controller import TauController
    from sparknet_tpu.telemetry import anomaly

    anomaly.clear()
    try:
        tc = TauController(tau=4, tau_min=1, tau_max=4, widen_share=0.25)
        for _ in range(4):  # advisories=None == the multi-host caller
            tc.observe_round(round_s=1.0, sync_s=0.6, loss=1.0,
                             advisories=None)
        assert not anomaly.active("layout")
        assert not any(d.get("layout_advisory") for d in tc.decisions)
    finally:
        anomaly.clear()


def test_degrade_layout_best_table_entry():
    # bare dp degrades like the old width-1 path
    assert degrade_layout("dp=4", 4, 3) == "dp=3"
    # model axes survive while they divide the surviving budget
    assert degrade_layout("dp=2,tp=4", 8, 4) == "dp=1,tp=4"
    assert degrade_layout("dp=4,tp=2", 8, 6) == "dp=3,tp=2"
    # ... and halve away when they don't
    assert degrade_layout("dp=2,tp=2", 4, 3) == "dp=3"
    assert degrade_layout("dp=4,tp=2", 8, 7) == "dp=7"
    # scale-up restores the declared layout; -1 resolves at mesh build
    assert degrade_layout("dp=2,tp=2", 4, 4) == "dp=2,tp=2"
    assert degrade_layout("dp=-1", 4, 3) == "dp=-1"


def test_supervisor_degrade_rewrites_layout_flag():
    from sparknet_tpu.supervise.supervisor import (
        Supervisor, flag_value, set_flag_value,
    )

    argv = ["python", "-m", "x", "--layout=dp=2,tp=2", "--synthetic"]
    sup = Supervisor(argv, num_procs=4, run_dir=".")
    assert sup._orig_layout == "dp=2,tp=2"
    entry = {}
    sup._apply_elastic_layout(3, entry)
    assert flag_value(sup.argv, "--layout") == "dp=3"
    assert entry["relayout"] == {"from": "dp=2,tp=2", "to": "dp=3"}
    # scale-up back to full width restores the original declaration
    entry2 = {}
    sup._apply_elastic_layout(4, entry2)
    assert flag_value(sup.argv, "--layout") == "dp=2,tp=2"
    # a job without --layout is untouched (the old width-1 behavior)
    sup2 = Supervisor(["python", "-m", "x"], num_procs=4, run_dir=".")
    e = {}
    sup2._apply_elastic_layout(3, e)
    assert "relayout" not in e
    # both flag spellings rewrite
    assert set_flag_value(["--layout", "dp=4"], "--layout", "dp=3") == [
        "--layout", "dp=3",
    ]


def test_request_watcher_fires_at_iter_boundary(tmp_path):
    req_path = str(tmp_path / "reshard_request.json")
    with open(req_path, "w") as fh:
        json.dump([{"layout": "dp=2,tp=2", "at_iter": 2}], fh)
    s = dp4_solver()
    lines = []
    w = RequestWatcher(s, req_path, log=lines.append)
    targets = [100]
    w.add_targets(targets, 0)
    assert 2 in targets  # the boundary joins the loop's chunk targets
    assert w.poll() == []  # iter 0 < at_iter 2: not yet
    s.step(feed_of(batch(5)), 2)
    (rec,) = w.poll()
    assert rec["to"] == "dp=2,tp=2" and rec["at_iter"] == 2
    assert s.mesh.shape == {"dp": 2, "tp": 2}
    assert any(l.startswith("reshard: ") for l in lines)
    assert any("relayout (live reshard)" in l for l in lines)
    # consumed: polling again is a no-op
    assert w.poll() == []
    # the outcome landed in the request log for the requester
    with open(req_path + ".log") as fh:
        logged = [json.loads(l) for l in fh]
    assert logged[-1]["to"] == "dp=2,tp=2"


def test_request_watcher_bad_requests_do_not_kill_the_loop(tmp_path):
    req_path = str(tmp_path / "req.json")
    with open(req_path, "w") as fh:
        fh.write("{ torn json")
    s = dp4_solver()
    lines = []
    w = RequestWatcher(s, req_path, log=lines.append)
    assert w.poll() == []  # unreadable: warned, retried next poll
    assert any("unreadable" in l for l in lines)
    with open(req_path, "w") as fh:
        json.dump({"layout": "dp=3"}, fh)  # indivisible batch
    assert w.poll() == []
    assert any("reshard request failed" in l for l in lines)
    assert s.mesh.shape == {"dp": 4}  # untouched, still training
    with open(req_path + ".log") as fh:
        assert "error" in json.loads(fh.readlines()[-1])


def test_request_watcher_create_gates_on_reshardable(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARKNET_RESHARD_REQUEST", str(tmp_path / "r.json"))
    lines = []
    s = ParallelSolver(
        tiny_solver(), SHAPES, net_param=tiny_net(), seed=0,
        layout=parse_layout("dp=8"), mode="local", tau=2,
    )
    assert RequestWatcher.create(s, log=lines.append) is None
    assert any("cannot reshard" in l for l in lines)
    s2 = dp4_solver()
    assert RequestWatcher.create(s2, log=lines.append) is not None
    monkeypatch.delenv("SPARKNET_RESHARD_REQUEST")
    assert RequestWatcher.create(s2, log=lines.append) is None
