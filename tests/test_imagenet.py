"""ImageNet data layer + ImageNetApp (the reference's second
entrypoint, SURVEY.md §2)."""

import io
import os
import tarfile

import numpy as np
import pytest

from sparknet_tpu.data.imagenet import (
    imagenet_dataset,
    synthetic_imagenet,
)


def test_synthetic_imagenet_deterministic():
    a, la = synthetic_imagenet(64, seed=0, size=64, classes=10)
    b, lb = synthetic_imagenet(64, seed=0, size=64, classes=10)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(la, lb)
    assert a.shape == (64, 64, 64, 3) and a.dtype == np.uint8
    assert la.min() >= 0 and la.max() < 10


def test_dataset_fallback_synthetic(tmp_path):
    ds = imagenet_dataset(None, train=True, synthetic_n=64, synthetic_classes=5)
    batch = next(ds.batches(8, epochs=1))
    assert batch["data"].shape == (8, 256, 256, 3)
    assert batch["label"].dtype == np.int32


def _png_bytes(arr):
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def test_folder_layout(tmp_path):
    rng = np.random.default_rng(0)
    for wnid in ("n01440764", "n01443537"):
        d = tmp_path / "train" / wnid
        d.mkdir(parents=True)
        for i in range(3):
            img = rng.integers(0, 255, (32, 48, 3)).astype(np.uint8)
            (d / f"{wnid}_{i}.png").write_bytes(_png_bytes(img))
    ds = imagenet_dataset(str(tmp_path), train=True)
    part = ds.collect_partition(0)
    assert part["data"].shape == (6, 256, 256, 3)  # resized
    # labels follow sorted-wnid indexing
    assert sorted(np.unique(part["label"]).tolist()) == [0, 1]


def test_tar_shard_layout(tmp_path):
    rng = np.random.default_rng(1)
    path = tmp_path / "shard-000.tar"
    with tarfile.open(path, "w") as tf:
        for wnid, k in (("n02084071", 2), ("n02121808", 1)):
            for i in range(k):
                raw = _png_bytes(rng.integers(0, 255, (20, 20, 3)).astype(np.uint8))
                info = tarfile.TarInfo(f"{wnid}_{i}.png")
                info.size = len(raw)
                tf.addfile(info, io.BytesIO(raw))
    ds = imagenet_dataset(str(tmp_path), train=True)
    assert ds.num_partitions == 1
    part = ds.collect_partition(0)
    assert part["data"].shape == (3, 256, 256, 3)
    assert sorted(part["label"].tolist()) == [0, 0, 1]


def test_npz_shard_layout(tmp_path):
    ims = np.zeros((10, 256, 256, 3), np.uint8)
    lbs = np.arange(10, dtype=np.int32)
    np.savez(tmp_path / "imagenet-train-000.npz", data=ims, label=lbs)
    ds = imagenet_dataset(str(tmp_path), train=True)
    part = ds.collect_partition(0)
    assert part["data"].shape == (10, 256, 256, 3)
    np.testing.assert_array_equal(part["label"], lbs)
    # val split must not pick up train shards
    ds_val = imagenet_dataset(str(tmp_path), train=False, synthetic_n=64)
    assert ds_val.collect_partition(0)["data"].shape[0] != 10


def test_imagenet_app_alexnet_synthetic_step():
    """End-to-end: build ImageNetApp (AlexNet) on synthetic data and run
    two train iterations."""
    from sparknet_tpu.apps import imagenet_app

    solver, train_feed, test_feed = imagenet_app.build(
        imagenet_app.make_args(
            synthetic=True,
            synthetic_n=32,
            synthetic_classes=10,
            batch_size=4,
            max_iter=2,
        )
    )
    m = solver.step(train_feed, 2)
    assert np.isfinite(float(m["loss"]))


def test_imagenet_app_device_augment_step():
    """--device-augment: uint8 + aug plan in, augmentation inside the
    jitted step; same build/step surface as the host path."""
    from sparknet_tpu.apps import imagenet_app

    solver, train_feed, _ = imagenet_app.build(
        imagenet_app.make_args(
            synthetic=True,
            synthetic_n=32,
            synthetic_classes=10,
            batch_size=4,
            max_iter=2,
            device_augment=True,
        )
    )
    batch = next(train_feed)
    assert batch["data"].dtype == np.uint8  # pixels ship raw
    assert "aug_oy" in batch and "aug_flip" in batch
    m = solver.step(train_feed, 2)
    assert np.isfinite(float(m["loss"]))
    with pytest.raises(ValueError):
        imagenet_app.build(
            imagenet_app.make_args(
                synthetic=True, batch_size=4, device_augment=True,
                parallel="sync",
            )
        )
    with pytest.raises(ValueError):  # explicit native loader conflicts
        imagenet_app.build(
            imagenet_app.make_args(
                synthetic=True, batch_size=4, device_augment=True,
                native_loader="on",
            )
        )


@pytest.mark.slow
def test_imagenet_app_parallel_local_tau():
    """τ-local-SGD over the 8-device CPU mesh through the app path."""
    from sparknet_tpu.apps import imagenet_app

    solver, train_feed, _ = imagenet_app.build(
        imagenet_app.make_args(
            synthetic=True,
            synthetic_n=64,
            synthetic_classes=10,
            batch_size=8,
            max_iter=4,
            parallel="local",
            tau=2,
        )
    )
    m = solver.step(train_feed, 4)
    assert np.isfinite(float(m["loss"]))
    assert solver.iter == 4
