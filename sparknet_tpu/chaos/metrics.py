"""Chaos observability: healing must be visible, not silent.

One process-global :class:`ChaosMetrics` registry counts every fault
*fire* (per fault point) and every *recovery* (per recovery action —
``pipeline.worker_respawn``, ``serve.client_retry``,
``snapshot.fallback_restore``), reusing the serving stack's
:class:`~sparknet_tpu.serve.metrics.Counter` primitive and its
one-JSON-line dump discipline.  The apps print the line at the end of a
chaos-enabled run; tests assert exact recovery counts against it.

Note: fires at fault points that live inside *forked worker processes*
(the pipeline points) are counted in the worker's copy of this registry
and die with it — the parent-side **recovery** counters are the
observable record of what happened, which is exactly what the tests
and the acceptance criteria assert on.
"""

from __future__ import annotations

import json
import threading
from typing import Dict

from ..serve.metrics import Counter


class ChaosMetrics:
    """Fires per fault point + recoveries per recovery action."""

    def __init__(self):
        self._lock = threading.Lock()
        self.fires: Dict[str, Counter] = {}
        self.recoveries: Dict[str, Counter] = {}

    def _get(self, table: Dict[str, Counter], name: str) -> Counter:
        with self._lock:
            c = table.get(name)
            if c is None:
                c = table[name] = Counter()
            return c

    def record_fire(self, point: str) -> None:
        self._get(self.fires, point).inc()

    def record_recovery(self, name: str) -> None:
        self._get(self.recoveries, name).inc()

    def recovery_count(self, name: str) -> int:
        with self._lock:
            c = self.recoveries.get(name)
        return c.snapshot() if c is not None else 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "fires": {k: c.snapshot() for k, c in self.fires.items()},
                "recoveries": {
                    k: c.snapshot() for k, c in self.recoveries.items()
                },
            }

    def json_line(self) -> str:
        return json.dumps(self.snapshot())

    def reset(self) -> None:
        with self._lock:
            self.fires.clear()
            self.recoveries.clear()


METRICS = ChaosMetrics()
