"""Chaos observability: healing must be visible, not silent.

One process-global :class:`ChaosMetrics` registry counts every fault
*fire* (per fault point) and every *recovery* (per recovery action —
``pipeline.worker_respawn``, ``serve.client_retry``,
``snapshot.fallback_restore``), built on the telemetry registry's
:class:`~sparknet_tpu.telemetry.registry.NamedCounters` (the shared
name->Counter table this module and ``supervise/metrics.py`` used to
re-implement separately) and its one-JSON-line dump discipline.  The
apps print the line at the end of a chaos-enabled run; tests assert
exact recovery counts against it; ``telemetry.REGISTRY.snapshot()``
carries the same dicts under the ``"chaos"`` source.

Note: fires at fault points that live inside *forked worker processes*
(the pipeline points) are counted in the worker's copy of this registry
and die with it — the parent-side **recovery** counters are the
observable record of what happened, which is exactly what the tests
and the acceptance criteria assert on.
"""

from __future__ import annotations

import json

from ..telemetry.registry import REGISTRY, NamedCounters


class ChaosMetrics:
    """Fires per fault point + recoveries per recovery action."""

    def __init__(self):
        self.fires = NamedCounters()
        self.recoveries = NamedCounters()

    def record_fire(self, point: str) -> None:
        self.fires.inc(point)

    def record_recovery(self, name: str) -> None:
        self.recoveries.inc(name)

    def recovery_count(self, name: str) -> int:
        return self.recoveries.count(name)

    def snapshot(self) -> dict:
        return {
            "fires": self.fires.snapshot(),
            "recoveries": self.recoveries.snapshot(),
        }

    def json_line(self) -> str:
        return json.dumps(self.snapshot())

    def reset(self) -> None:
        self.fires.reset()
        self.recoveries.reset()


METRICS = ChaosMetrics()
REGISTRY.register_source("chaos", METRICS)
