"""Fault plans: the chaos subsystem's deterministic "when to fail".

A :class:`FaultPlan` is parsed from a spec string (``--chaos`` /
``SPARKNET_CHAOS``) of comma-separated clauses::

    point[@cond[:cond...]]

where ``point`` names a registered fault point (:data:`FAULT_POINTS`)
and each ``cond`` is ``key=value``.  Keys fall into three groups:

- **coordinates** (``batch=37``, ``worker=1``, ``request=12``,
  ``iter=500``, ``tick=3``, ``index=0``): exact-match predicates
  against the coordinates the injection site passes.  A clause fires
  only when every coordinate it names matches.  ``site=snapshot`` is
  the one *string* coordinate — the writer tag the ``io.*`` points
  (utils/safeio.py) target.
- **schedule predicates**: ``p=0.25`` (seeded Bernoulli per index),
  ``every=2`` (index % every == 0), ``after=10`` (index >= after),
  ``times=3`` (at most N fires per process), ``seed=7`` (per-clause
  override of the plan seed).  The "index" these use is the site's
  primary sequence coordinate — the first of ``batch``, ``request``,
  ``iter``, ``tick``, ``index`` present in the call.
- **parameters** (``delay_ms=50``, ``exit_code=3``, ``frac=0.5``):
  carried on the matched rule for the site to interpret; never
  predicates.

Determinism: probabilistic decisions draw from
``np.random.default_rng((seed, crc32(point), index))`` — the same
seed + spec + coordinate stream reproduces the same fault sequence on
every run and every host, which is what makes chaos tests assertable.

Examples::

    pipeline.worker_crash@batch=37:worker=1
    serve.conn_drop@every=2,serve.engine_stall@p=0.1:delay_ms=80
    snapshot.partial_write@index=1:frac=0.5
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional

import numpy as np

# The registry: every injectable fault point in the system. Sites pass
# the coordinates listed here; the spec parser rejects unknown points
# so a typo fails at launch, not silently never-fires.
FAULT_POINTS: Dict[str, str] = {
    "pipeline.worker_crash": (
        "input-pipeline worker hard-exits (os._exit) before producing a "
        "batch; coords: batch (global index), worker (rank); params: "
        "exit_code"
    ),
    "pipeline.slow_batch": (
        "input-pipeline worker stalls before producing a batch; coords: "
        "batch, worker; params: delay_ms (default 50)"
    ),
    "data.torn_shard": (
        "packed shard reader sees a CRC-torn record: skipped with "
        "counter, replaced by the nearest healthy record of the batch, "
        "and the batch is excluded from the decoded-batch cache; "
        "coords: shard (shard index), index (record index)"
    ),
    "serve.conn_drop": (
        "HTTP server drops a /classify connection with no response; "
        "coords: request (per-server POST index)"
    ),
    "serve.engine_stall": (
        "micro-batcher stalls before an engine call; coords: batch "
        "(per-batcher flush index); params: delay_ms (default 50)"
    ),
    "serve.replica_kill": (
        "serving router SIGKILLs an engine-replica child; its "
        "in-flight requests retry on a peer, the pool respawns it; "
        "coords: tick (router health-loop tick), worker (replica "
        "index)"
    ),
    "snapshot.partial_write": (
        "solverstate write publishes a torn (truncated) file; coords: "
        "index (per-process save count), iter (parsed from the path); "
        "params: frac (default 0.5)"
    ),
    "multihost.peer_silence": (
        "heartbeat client goes silent (peer appears dead to the "
        "fabric); coords: worker (process id), tick (ping count)"
    ),
    "supervisor.child_crash": (
        "supervised training child hard-exits (os._exit, after writing "
        "its failure record) at a train-loop boundary; coords: iter "
        "(solver iteration at the boundary); params: exit_code "
        "(default 9)"
    ),
    "supervisor.resume_torn": (
        "supervisor tears the newest solverstate before a relaunch, "
        "forcing the verified-resume fallback chain; coords: index "
        "(restart count); params: frac (default 0.5)"
    ),
    "deploy.poison_snapshot": (
        "deploy gate's candidate solverstate is corrupted (truncated) "
        "BEFORE evaluation — the gate must quarantine it with a fail "
        "verdict, never serve it; coords: index (per-process gate "
        "evaluation count), iter (parsed from the candidate path); "
        "params: frac (default 0.5)"
    ),
    "deploy.regressed_weights": (
        "engine hot-swap scales one weight leaf AFTER the gate saw "
        "clean bytes (silent post-gate regression) — the deploy watch "
        "window must detect the agreement drop and auto-roll-back; "
        "coords: index (per-process swap_from_file count); params: "
        "frac (scale factor, default 8.0)"
    ),
    "io.enospc": (
        "a writer's atomic publish (utils/safeio.py) fails with ENOSPC "
        "(disk full) before any byte lands; coords: site (writer tag: "
        "snapshot/tee/cache/compile_cache/records/flight/ledger), "
        "index (per-site write count)"
    ),
    "io.eio": (
        "a writer's atomic publish fails with EIO (media error); "
        "coords: site (writer tag), index (per-site write count)"
    ),
    "io.slow_write": (
        "a writer's atomic publish stalls before writing (degraded "
        "disk); coords: site (writer tag), index (per-site write "
        "count); params: delay_ms (default 50)"
    ),
    "io.enospc_storm": (
        "volume-wide disk-full window: the matched write AND every "
        "subsequent write at every site fails ENOSPC until the storm "
        "clears; coords: site (writer tag), index (per-site write "
        "count); params: clear_after_s (default 2)"
    ),
}

# which coordinate serves as the schedule index, in priority order
_INDEX_COORDS = ("batch", "request", "iter", "tick", "index")
_SCHEDULE_KEYS = {"p", "every", "after", "times", "seed"}
_PARAM_KEYS = {"delay_ms", "exit_code", "frac", "clear_after_s"}
# coordinates whose values are identifiers, not sequence numbers (the
# io.* writer-site tags)
_STR_COORDS = {"site"}


def _parse_value(point: str, key: str, raw: str):
    if key in _STR_COORDS:
        if not raw or not raw.replace("_", "").isalnum():
            raise ValueError(
                f"chaos spec: {point}@{key}={raw!r} — value must be a "
                f"writer site tag (identifier)"
            )
        return raw
    try:
        return int(raw)
    except ValueError:
        try:
            return float(raw)
        except ValueError:
            raise ValueError(
                f"chaos spec: {point}@{key}={raw!r} — value must be a "
                f"number"
            ) from None


class Rule:
    """One parsed clause: predicates + parameters + a fire budget."""

    __slots__ = ("point", "match", "p", "every", "after", "times", "seed",
                 "params", "fired")

    def __init__(self, point: str, conds: Dict[str, float]):
        self.point = point
        self.match: Dict[str, object] = {}
        self.p: Optional[float] = None
        self.every: Optional[int] = None
        self.after: Optional[int] = None
        self.times: Optional[int] = None
        self.seed: Optional[int] = None
        self.params: Dict[str, float] = {}
        self.fired = 0
        for k, v in conds.items():
            if k in _PARAM_KEYS:
                self.params[k] = v
            elif k == "p":
                if not 0.0 < float(v) <= 1.0:
                    raise ValueError(
                        f"chaos spec: {point}@p={v} — p must be in (0, 1]"
                    )
                self.p = float(v)
            elif k == "every":
                if int(v) < 1:
                    raise ValueError(f"chaos spec: {point}@every={v} < 1")
                self.every = int(v)
            elif k == "after":
                self.after = int(v)
            elif k == "times":
                if int(v) < 1:
                    raise ValueError(f"chaos spec: {point}@times={v} < 1")
                self.times = int(v)
            elif k == "seed":
                self.seed = int(v)
            else:
                # anything else is an exact coordinate match (site tags
                # stay strings; every other coordinate is an integer)
                self.match[k] = v if isinstance(v, str) else int(v)

    def _index(self, coords: Dict[str, int]) -> Optional[int]:
        for k in _INDEX_COORDS:
            if k in coords:
                return int(coords[k])
        return None

    def decide(self, plan_seed: int, coords: Dict[str, int]) -> bool:
        """Does this rule fire at these coordinates?  Mutates the fire
        budget on a hit (caller holds the plan lock)."""
        if self.times is not None and self.fired >= self.times:
            return False
        for k, want in self.match.items():
            if coords.get(k) != want:
                return False
        idx = self._index(coords)
        if self.after is not None and (idx is None or idx < self.after):
            return False
        if self.every is not None and (idx is None or idx % self.every):
            return False
        if self.p is not None:
            seed = self.seed if self.seed is not None else plan_seed
            draw = np.random.default_rng(
                (seed, zlib.crc32(self.point.encode()), idx or 0)
            ).random()
            if draw >= self.p:
                return False
        self.fired += 1
        return True


class FaultPlan:
    """A parsed chaos spec: ordered rules grouped by fault point.

    ``match(point, **coords)`` returns the first rule that fires (and
    records the fire in the chaos metrics), or None.  Sites that only
    need a boolean use ``fires(...)``.  Call sites are expected to hold
    a *cached* plan reference (or None) so the disabled path is a
    single ``is None`` check — the zero-hot-path-cost contract.
    """

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._by_point: Dict[str, List[Rule]] = {}
        for clause in filter(None, (c.strip() for c in spec.split(","))):
            point, _, tail = clause.partition("@")
            point = point.strip()
            if point not in FAULT_POINTS:
                known = ", ".join(sorted(FAULT_POINTS))
                raise ValueError(
                    f"chaos spec: unknown fault point {point!r} "
                    f"(known: {known})"
                )
            conds: Dict[str, float] = {}
            if tail:
                for cond in tail.split(":"):
                    key, eq, raw = cond.partition("=")
                    if not eq or not key.strip():
                        raise ValueError(
                            f"chaos spec: bad condition {cond!r} in "
                            f"{clause!r} (want key=value)"
                        )
                    conds[key.strip()] = _parse_value(
                        point, key.strip(), raw.strip()
                    )
            self._by_point.setdefault(point, []).append(Rule(point, conds))
        if not self._by_point:
            raise ValueError(f"chaos spec {spec!r} names no fault points")

    def points(self):
        return sorted(self._by_point)

    def match(self, point: str, **coords) -> Optional[Rule]:
        rules = self._by_point.get(point)
        if not rules:
            return None
        with self._lock:
            for rule in rules:
                if rule.decide(self.seed, coords):
                    from .metrics import METRICS

                    METRICS.record_fire(point)
                    return rule
        return None

    def fires(self, point: str, **coords) -> bool:
        return self.match(point, **coords) is not None

    def __repr__(self) -> str:
        return f"FaultPlan({self.spec!r}, seed={self.seed})"
