"""Chaos: deterministic fault injection + the self-healing contract.

SparkNet's pitch leans on Spark re-running a dead executor's partition;
the TensorFlow paper makes the same point for non-Spark stacks —
recovery is checkpointing + restart discipline, and it must be
*testable*.  This package is the testable half: a registry of named
fault points (:data:`~sparknet_tpu.chaos.plan.FAULT_POINTS`) driven by
a seeded, sequence-indexed :class:`~sparknet_tpu.chaos.plan.FaultPlan`
parsed from ``--chaos`` / ``SPARKNET_CHAOS``::

    SPARKNET_CHAOS=pipeline.worker_crash@batch=37:worker=1 \\
        python -m sparknet_tpu.tools.caffe train --solver=... \\
        --data-workers=2

The surfaces the faults exercise heal instead of aborting:

- a dead pipeline worker is respawned and the lost batches re-produced
  bit-identically (``data/pipeline.py``);
- ``serve.Client`` retries 503s/connection drops with capped backoff,
  the micro-batcher sheds expired requests before compute
  (``serve/``);
- solverstate writes are atomic + verified, restore falls back to the
  previous snapshot on a torn file (``solver/snapshot.py``).

Disabled (no spec installed, env unset) the whole subsystem compiles
down to ``get_plan() is None`` — call sites cache that None and pay a
single attribute test on the hot path.  Every recovery increments the
process-global :data:`METRICS` registry so healing is observable.

See docs/ROBUSTNESS.md for the fault-point catalog, the spec grammar
and the recovery semantics/budgets.
"""

from __future__ import annotations

import os
from typing import Optional

from .metrics import METRICS, ChaosMetrics
from .plan import FAULT_POINTS, FaultPlan, Rule

__all__ = [
    "FAULT_POINTS",
    "FaultPlan",
    "METRICS",
    "ChaosMetrics",
    "Rule",
    "active",
    "clear",
    "get_plan",
    "install",
    "install_from",
    "record_recovery",
]

_plan: Optional[FaultPlan] = None
_installed = False  # an explicit install() wins over the env var
_env_spec: Optional[str] = None


def _env_seed() -> int:
    return int(os.environ.get("SPARKNET_CHAOS_SEED", "0") or 0)


def install(spec: Optional[str], seed: Optional[int] = None) -> Optional[FaultPlan]:
    """Install a fault plan for this process (CLI ``--chaos`` wiring and
    tests).  ``spec`` of None/"" disables chaos regardless of the env.
    Forked children (pipeline workers) inherit the installed plan."""
    global _plan, _installed
    _installed = True
    _plan = (
        FaultPlan(spec, seed=_env_seed() if seed is None else seed)
        if spec
        else None
    )
    return _plan


def install_from(flag: Optional[str]) -> Optional[FaultPlan]:
    """App-side wiring: an explicit ``--chaos`` flag wins; otherwise
    ``SPARKNET_CHAOS`` (resolved lazily by :func:`get_plan`)."""
    if flag:
        return install(flag)
    return get_plan()


def get_plan() -> Optional[FaultPlan]:
    """The active plan, or None when chaos is disabled.  Without an
    explicit :func:`install`, ``SPARKNET_CHAOS`` is parsed on demand
    (re-parsed only when the env value changes, so a CLI subprocess
    needs zero wiring).  Call sites cache the result at construction
    time — the disabled hot path is one ``is None`` test."""
    global _plan, _env_spec
    if _installed:
        return _plan
    spec = os.environ.get("SPARKNET_CHAOS", "").strip()
    if not spec:
        _plan, _env_spec = None, None
        return None
    if _plan is None or _env_spec != spec:
        _env_spec = spec
        _plan = FaultPlan(spec, seed=_env_seed())
    return _plan


def active() -> bool:
    return get_plan() is not None


def clear() -> None:
    """Drop any installed/env-resolved plan and zero the metrics
    (test isolation)."""
    global _plan, _installed, _env_spec
    _plan = None
    _installed = False
    _env_spec = None
    METRICS.reset()


def record_recovery(name: str) -> None:
    """Count one recovery action (see :mod:`sparknet_tpu.chaos.metrics`).
    Recorded unconditionally — recoveries from real faults (not just
    injected ones) are equally worth surfacing."""
    METRICS.record_recovery(name)
