"""``.caffemodel`` / ``.binaryproto`` weight interchange.

The reference snapshots and ships weights as binary ``NetParameter``
protos — the Caffe zoo's published ``bvlc_alexnet.caffemodel`` etc.
(SURVEY.md §2 prototxt model zoo; mount empty, no file:line).  This
module reads and writes that format against the framework's
WeightCollection, handling the layout transposition:

- Convolution: Caffe OIHW  ->  ours HWIO (``nets/layers.py:13``)
- InnerProduct: Caffe (out, in) with in flattened CHW  ->  ours
  (in, out) with in flattened HWC; the row permutation is derived from
  the net's blob shapes, so flatten bit-compat holds end-to-end.
- BatchNorm: Caffe's unnormalized sum blobs (+ scale factor) ->
  normalized running mean/var in the state pytree.

Field numbers follow caffe.proto (BVLC master): NetParameter.name=1,
.layer=100 (V2), .layers=2 (V1); LayerParameter.name=1/.type=2/
.blobs=7; V1LayerParameter.name=4/.blobs=6; BlobProto.shape=7/.data=5/
.num..width=1..4; BlobShape.dim=1.  Verified against google.protobuf
dynamic messages in tests/test_caffemodel.py.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from . import wire
from ..nets.layers import LAYER_IMPLS

WeightBlobs = Dict[str, List[np.ndarray]]  # layer name -> caffe-layout blobs


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------

def read_blob(buf: bytes) -> np.ndarray:
    """BlobProto -> array in Caffe's native dim order."""
    f = wire.decode(buf)
    data = wire.repeated_floats(f, 5)
    if not data and 8 in f:  # double_data
        import struct

        out: List[float] = []
        for raw in f[8]:
            if isinstance(raw, bytes):
                out.extend(struct.unpack(f"<{len(raw) // 8}d", raw))
            else:
                out.append(struct.unpack("<d", struct.pack("<Q", raw))[0])
        data = out
    if 7 in f:  # BlobShape
        shape = wire.repeated_ints(wire.decode(f[7][-1]), 1)
    else:  # legacy num/channels/height/width
        shape = [int(wire.first(f, i, 1)) for i in (1, 2, 3, 4)]
        while len(shape) > 1 and shape[0] == 1:
            shape = shape[1:]
    arr = np.asarray(data, np.float32)
    return arr.reshape(shape) if shape else arr


def load_caffemodel(path_or_bytes) -> Tuple[str, WeightBlobs]:
    """Parse a binary NetParameter -> (net name, layer blobs)."""
    buf = (
        path_or_bytes
        if isinstance(path_or_bytes, (bytes, bytearray))
        else open(path_or_bytes, "rb").read()
    )
    f = wire.decode(bytes(buf))
    name = wire.first(f, 1, b"").decode()
    blobs: WeightBlobs = {}
    for raw in f.get(100, []):  # LayerParameter (V2)
        lf = wire.decode(raw)
        lname = wire.first(lf, 1, b"").decode()
        lb = [read_blob(b) for b in lf.get(7, [])]
        if lb:
            blobs[lname] = lb
    for raw in f.get(2, []):  # V1LayerParameter
        lf = wire.decode(raw)
        lname = wire.first(lf, 4, b"").decode()
        lb = [read_blob(b) for b in lf.get(6, [])]
        if lb:
            blobs.setdefault(lname, lb)
    return name, blobs


def load_binaryproto_mean(path_or_bytes) -> np.ndarray:
    """``mean_file`` BlobProto -> (H, W, C) float32 NHWC mean image."""
    buf = (
        path_or_bytes
        if isinstance(path_or_bytes, (bytes, bytearray))
        else open(path_or_bytes, "rb").read()
    )
    arr = read_blob(bytes(buf))
    if arr.ndim == 3:  # (C, H, W) -> (H, W, C)
        return np.transpose(arr, (1, 2, 0))
    if arr.ndim == 4:  # (1, C, H, W)
        return np.transpose(arr[0], (1, 2, 0))
    return arr


# ---------------------------------------------------------------------------
# Layout conversion against a compiled net
# ---------------------------------------------------------------------------

def _ip_rows_chw_to_hwc(w: np.ndarray, bottom_shape) -> np.ndarray:
    """(out, in) rows flattened CHW -> flattened HWC, when the bottom
    blob is 4D; identity otherwise."""
    if len(bottom_shape) != 4:
        return w
    _, h, wd, c = bottom_shape
    if w.shape[1] != c * h * wd:
        raise ValueError(
            f"IP weight in-dim {w.shape[1]} != bottom {c}*{h}*{wd}"
        )
    return (
        w.reshape(w.shape[0], c, h, wd).transpose(0, 2, 3, 1)
        .reshape(w.shape[0], h * wd * c)
    )


def import_caffemodel(path_or_bytes, net) -> Tuple[Dict, Dict]:
    """-> (params, state) matching ``XLANet.init``'s structure, filled
    from a .caffemodel where layer names match; unmatched layers keep
    no entry (caller merges over freshly-initialised values)."""
    _, blobs = load_caffemodel(path_or_bytes)
    params: Dict[str, Dict[str, np.ndarray]] = {}
    state: Dict[str, Dict[str, np.ndarray]] = {}
    for lp in net.layers:
        lb = blobs.get(lp.name)
        if not lb:
            continue
        t = lp.type
        if t in ("Convolution", "Deconvolution"):
            w = lb[0]
            entry = {"weight": np.transpose(w, (2, 3, 1, 0))}  # OIHW->HWIO
            if len(lb) > 1:
                entry["bias"] = lb[1].reshape(-1)
            params[lp.name] = entry
        elif t == "InnerProduct":
            w = _ip_rows_chw_to_hwc(lb[0], net.blob_shapes[lp.bottom[0]])
            entry = {"weight": np.ascontiguousarray(w.T)}  # (in, out)
            if len(lb) > 1:
                entry["bias"] = lb[1].reshape(-1)
            params[lp.name] = entry
        elif t == "BatchNorm":
            scale = float(lb[2].reshape(-1)[0]) if len(lb) > 2 else 1.0
            scale = 1.0 / scale if scale != 0 else 0.0
            state[lp.name] = {
                "mean": lb[0].reshape(-1) * scale,
                "var": lb[1].reshape(-1) * scale,
            }
        elif t == "Scale":
            entry = {"weight": lb[0].reshape(-1)}
            if len(lb) > 1:
                entry["bias"] = lb[1].reshape(-1)
            params[lp.name] = entry
        elif t in ("LSTM", "RNN"):
            # Caffe recurrent blobs are (out, in) matrices; ours are
            # (in, out) so matmuls run untransposed in the hot loop
            order = LAYER_IMPLS[t].PARAM_ORDER
            params[lp.name] = {
                name: (lb[i].T if lb[i].ndim == 2 else lb[i].reshape(-1))
                for i, name in enumerate(order)
                if i < len(lb)
            }
        else:
            # generic path: blob i maps to the layer's i-th declared
            # param name (PReLU: slope; Bias: bias; default
            # weight/bias) — the same PARAM_ORDER contract export uses,
            # so the two sides can never disagree. Legacy 4-D vector
            # blobs like (1,1,1,C) flatten to the 1-D param shape.
            order = getattr(
                LAYER_IMPLS.get(t), "PARAM_ORDER", ("weight", "bias")
            )
            entry = {}
            for i, name in enumerate(order):
                if i >= len(lb):
                    break
                arr = lb[i]
                if arr.ndim > 1 and arr.size == arr.shape[-1]:
                    arr = arr.reshape(-1)
                entry[name] = arr
            if entry:
                params[lp.name] = entry
    return params, state


def merge_into(params, imported) -> Dict:
    """Overlay imported arrays (host numpy) onto an initialised
    WeightCollection, preserving entries the model file lacks."""
    out = {k: dict(v) for k, v in params.items()}
    for layer, entry in imported.items():
        if layer not in out:
            out[layer] = {}
        for name, arr in entry.items():
            out[layer][name] = np.asarray(arr, np.float32)
    return out


# ---------------------------------------------------------------------------
# Writing
# ---------------------------------------------------------------------------

def _encode_blob(arr: np.ndarray) -> bytes:
    shape_msg = b"".join(
        wire.encode_varint_field(1, int(d)) for d in arr.shape
    )
    return (
        wire.encode_packed_floats(5, arr.reshape(-1))
        + wire.encode_bytes_field(7, shape_msg)
    )


def export_caffemodel(path: str, net, params, state=None) -> None:
    """Write params (+BN state) as a binary NetParameter, inverting the
    import transpositions so Caffe reads native layouts."""
    out = [wire.encode_string_field(1, getattr(net.net, "name", "") or "")]
    state = state or {}
    for lp in net.layers:
        entry = params.get(lp.name, {})
        st = state.get(lp.name, {})
        blobs: List[np.ndarray] = []
        t = lp.type
        if t in ("Convolution", "Deconvolution") and "weight" in entry:
            blobs.append(
                np.transpose(np.asarray(entry["weight"]), (3, 2, 0, 1))
            )  # HWIO->OIHW
            if "bias" in entry:
                blobs.append(np.asarray(entry["bias"]))
        elif t == "InnerProduct" and "weight" in entry:
            w = np.asarray(entry["weight"]).T  # (out, in) rows HWC
            bshape = net.blob_shapes[lp.bottom[0]]
            if len(bshape) == 4:
                _, h, wd, c = bshape
                w = (
                    w.reshape(w.shape[0], h, wd, c).transpose(0, 3, 1, 2)
                    .reshape(w.shape[0], c * h * wd)
                )  # rows back to CHW
            blobs.append(w)
            if "bias" in entry:
                blobs.append(np.asarray(entry["bias"]))
        elif t == "BatchNorm" and st:
            blobs.extend(
                [np.asarray(st["mean"]), np.asarray(st["var"]),
                 np.asarray([1.0], np.float32)]
            )
        elif t in ("LSTM", "RNN") and entry:
            # invert the import transpose: (in, out) -> Caffe (out, in)
            order = LAYER_IMPLS[t].PARAM_ORDER
            for name in order:
                if name in entry:
                    arr = np.asarray(entry[name])
                    blobs.append(arr.T if arr.ndim == 2 else arr)
        elif entry:
            # blob order = the layer's declared param order (PReLU's
            # single blob is "slope", Bias's is "bias")
            order = getattr(
                LAYER_IMPLS.get(t), "PARAM_ORDER", ("weight", "bias")
            )
            blobs.extend(
                np.asarray(entry[name]) for name in order if name in entry
            )
        if not blobs:
            continue
        layer_msg = (
            wire.encode_string_field(1, lp.name)
            + wire.encode_string_field(2, lp.type)
            + b"".join(
                wire.encode_bytes_field(7, _encode_blob(b)) for b in blobs
            )
        )
        out.append(wire.encode_bytes_field(100, layer_msg))
    with open(path, "wb") as fh:
        fh.write(b"".join(out))
