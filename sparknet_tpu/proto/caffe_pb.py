"""Typed views over parsed Caffe prototxt messages.

The reference's native engine consumes ``NetParameter`` /
``SolverParameter`` protobufs (SURVEY.md §1 — Caffe prototxt configs per
BASELINE.json; reference mount empty, so semantics here follow the
published Caffe schema rather than file:line cites). These dataclasses
are the IR handed to :mod:`sparknet_tpu.nets.xlanet`.

Only the fields the model zoo actually uses are surfaced; everything
else remains reachable through ``.raw`` (the untyped parse tree).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional

from .textformat import Message, parse, parse_file

__all__ = [
    "Filler",
    "ParamSpec",
    "LayerParameter",
    "NetParameter",
    "SolverParameter",
    "load_net",
    "load_solver",
]

# Caffe V1 layer-type enum -> V2 string type (upgrade path, as Caffe's
# upgrade_proto does; lets us read older zoo prototxts unchanged).
_V1_TYPES = {
    "ACCURACY": "Accuracy",
    "BNLL": "BNLL",
    "CONCAT": "Concat",
    "CONVOLUTION": "Convolution",
    "DATA": "Data",
    "DROPOUT": "Dropout",
    "ELTWISE": "Eltwise",
    "FLATTEN": "Flatten",
    "IM2COL": "Im2col",
    "INNER_PRODUCT": "InnerProduct",
    "LRN": "LRN",
    "POOLING": "Pooling",
    "POWER": "Power",
    "RELU": "ReLU",
    "SIGMOID": "Sigmoid",
    "SIGMOID_CROSS_ENTROPY_LOSS": "SigmoidCrossEntropyLoss",
    "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "SPLIT": "Split",
    "TANH": "TanH",
    "EUCLIDEAN_LOSS": "EuclideanLoss",
    "MEMORY_DATA": "MemoryData",
    "HDF5_DATA": "HDF5Data",
    "IMAGE_DATA": "ImageData",
}


@dataclass
class Filler:
    """Caffe weight filler spec (constant/gaussian/xavier/msra/uniform)."""

    type: str = "constant"
    value: float = 0.0
    mean: float = 0.0
    std: float = 1.0
    min: float = 0.0
    max: float = 1.0
    # xavier / msra variance normalisation: FAN_IN (default) | FAN_OUT | AVERAGE
    variance_norm: str = "FAN_IN"
    sparse: int = -1

    @classmethod
    def from_message(cls, m: Optional[Message]) -> "Filler":
        if m is None:
            return cls()
        return cls(
            type=str(m.get("type", "constant")),
            value=float(m.get("value", 0.0)),
            mean=float(m.get("mean", 0.0)),
            std=float(m.get("std", 1.0)),
            min=float(m.get("min", 0.0)),
            max=float(m.get("max", 1.0)),
            variance_norm=str(m.get("variance_norm", "FAN_IN")),
            sparse=int(m.get("sparse", -1)),
        )


@dataclass
class ParamSpec:
    """Per-parameter learning-rate / decay multipliers (``param {}``)."""

    name: str = ""
    lr_mult: float = 1.0
    decay_mult: float = 1.0

    @classmethod
    def from_message(cls, m: Any) -> "ParamSpec":
        if isinstance(m, Message):
            return cls(
                name=str(m.get("name", "")),
                lr_mult=float(m.get("lr_mult", 1.0)),
                decay_mult=float(m.get("decay_mult", 1.0)),
            )
        # V1 nets wrote bare repeated floats: `blobs_lr: 1` style handled
        # by LayerParameter.from_message; a bare scalar here is a name.
        return cls(name=str(m))


@dataclass
class LayerParameter:
    name: str
    type: str
    bottom: List[str]
    top: List[str]
    phase: Optional[str]  # None = both phases; else "TRAIN" / "TEST"
    params: List[ParamSpec]
    loss_weight: List[float]
    raw: Message

    # ---- typed sub-message access ---------------------------------------
    def sub(self, field_name: str) -> Optional[Message]:
        v = self.raw.get(field_name)
        return v if isinstance(v, Message) else None

    @property
    def convolution_param(self) -> Optional[Message]:
        return self.sub("convolution_param")

    @property
    def pooling_param(self) -> Optional[Message]:
        return self.sub("pooling_param")

    @property
    def inner_product_param(self) -> Optional[Message]:
        return self.sub("inner_product_param")

    @property
    def lrn_param(self) -> Optional[Message]:
        return self.sub("lrn_param")

    @property
    def dropout_param(self) -> Optional[Message]:
        return self.sub("dropout_param")

    @property
    def batch_norm_param(self) -> Optional[Message]:
        return self.sub("batch_norm_param")

    @property
    def scale_param(self) -> Optional[Message]:
        return self.sub("scale_param")

    @property
    def eltwise_param(self) -> Optional[Message]:
        return self.sub("eltwise_param")

    @property
    def concat_param(self) -> Optional[Message]:
        return self.sub("concat_param")

    @property
    def transform_param(self) -> Optional[Message]:
        return self.sub("transform_param")

    @classmethod
    def from_message(cls, m: Message) -> "LayerParameter":
        typ = str(m.get("type", ""))
        typ = _V1_TYPES.get(typ, typ)
        phase = None
        inc = m.get("include")
        if isinstance(inc, Message) and inc.has("phase"):
            phase = str(inc.get("phase"))
        exc = m.get("exclude")
        if phase is None and isinstance(exc, Message) and exc.has("phase"):
            phase = "TEST" if str(exc.get("phase")) == "TRAIN" else "TRAIN"
        params = [ParamSpec.from_message(p) for p in m.get_all("param")]
        # V1 style multipliers
        blobs_lr = [float(x) for x in m.get_all("blobs_lr")]
        if blobs_lr and not params:
            decays = [float(x) for x in m.get_all("weight_decay")]
            params = [
                ParamSpec(lr_mult=lr, decay_mult=decays[i] if i < len(decays) else 1.0)
                for i, lr in enumerate(blobs_lr)
            ]
        return cls(
            name=str(m.get("name", "")),
            type=typ,
            bottom=[str(b) for b in m.get_all("bottom")],
            top=[str(t) for t in m.get_all("top")],
            phase=phase,
            params=params,
            loss_weight=[float(w) for w in m.get_all("loss_weight")],
            raw=m,
        )

    def active_in(self, phase: str) -> bool:
        return self.phase is None or self.phase == phase


@dataclass
class NetParameter:
    name: str
    layers: List[LayerParameter]
    # deploy-net style external inputs: name -> shape (list of ints)
    inputs: List[str] = field(default_factory=list)
    input_shapes: List[List[int]] = field(default_factory=list)
    raw: Optional[Message] = None

    @classmethod
    def from_message(cls, m: Message) -> "NetParameter":
        layer_msgs = m.get_all("layer") or m.get_all("layers")
        layers = [LayerParameter.from_message(lm) for lm in layer_msgs]
        inputs = [str(i) for i in m.get_all("input")]
        shapes: List[List[int]] = []
        for s in m.get_all("input_shape"):
            shapes.append([int(d) for d in s.get_all("dim")])
        dims = [int(d) for d in m.get_all("input_dim")]
        if dims and not shapes:
            shapes = [dims[i : i + 4] for i in range(0, len(dims), 4)]
        return cls(
            name=str(m.get("name", "")),
            layers=layers,
            inputs=inputs,
            input_shapes=shapes,
            raw=m,
        )

    def layers_for_phase(self, phase: str) -> List[LayerParameter]:
        return [l for l in self.layers if l.active_in(phase)]


@dataclass
class SolverParameter:
    net: Optional[str] = None
    train_net: Optional[str] = None
    test_net: List[str] = field(default_factory=list)
    net_param: Optional[NetParameter] = None
    test_iter: List[int] = field(default_factory=list)
    test_interval: int = 0
    base_lr: float = 0.01
    lr_policy: str = "fixed"
    gamma: float = 0.1
    power: float = 0.75
    stepsize: int = 100000
    stepvalue: List[int] = field(default_factory=list)
    max_iter: int = 0
    momentum: float = 0.0
    momentum2: float = 0.999  # Adam
    rms_decay: float = 0.99
    delta: float = 1e-8
    weight_decay: float = 0.0
    regularization_type: str = "L2"
    clip_gradients: float = -1.0
    iter_size: int = 1
    display: int = 0
    snapshot: int = 0
    snapshot_prefix: str = ""
    solver_mode: str = "GPU"
    solver_type: str = "SGD"
    random_seed: int = -1
    # Caffe: run the TEST nets once before training starts
    test_initialization: bool = True
    # Caffe: display the loss averaged over the last N iterations
    average_loss: int = 1
    warmup_iter: int = 0  # extension: linear LR warmup (not in Caffe)
    raw: Optional[Message] = None

    @classmethod
    def from_message(cls, m: Message) -> "SolverParameter":
        return cls(
            net=m.get("net"),
            train_net=m.get("train_net"),
            test_net=[str(t) for t in m.get_all("test_net")],
            net_param=(
                NetParameter.from_message(m.get("net_param"))
                if isinstance(m.get("net_param"), Message)
                else None
            ),
            test_iter=[int(t) for t in m.get_all("test_iter")],
            test_interval=int(m.get("test_interval", 0)),
            base_lr=float(m.get("base_lr", 0.01)),
            lr_policy=str(m.get("lr_policy", "fixed")),
            gamma=float(m.get("gamma", 0.1)),
            power=float(m.get("power", 0.75)),
            stepsize=int(m.get("stepsize", 100000)),
            stepvalue=[int(s) for s in m.get_all("stepvalue")],
            max_iter=int(m.get("max_iter", 0)),
            momentum=float(m.get("momentum", 0.0)),
            momentum2=float(m.get("momentum2", 0.999)),
            rms_decay=float(m.get("rms_decay", 0.99)),
            delta=float(m.get("delta", 1e-8)),
            weight_decay=float(m.get("weight_decay", 0.0)),
            regularization_type=str(m.get("regularization_type", "L2")),
            clip_gradients=float(m.get("clip_gradients", -1.0)),
            iter_size=int(m.get("iter_size", 1)),
            display=int(m.get("display", 0)),
            snapshot=int(m.get("snapshot", 0)),
            snapshot_prefix=str(m.get("snapshot_prefix", "")),
            solver_mode=str(m.get("solver_mode", "GPU")),
            solver_type=str(m.get("type", m.get("solver_type", "SGD"))),
            random_seed=int(m.get("random_seed", -1)),
            test_initialization=bool(m.get("test_initialization", True)),
            average_loss=int(m.get("average_loss", 1)),
            warmup_iter=int(m.get("warmup_iter", 0)),
            raw=m,
        )


def load_net(path_or_text: str, *, is_path: bool = True) -> NetParameter:
    m = parse_file(path_or_text) if is_path else parse(path_or_text)
    return NetParameter.from_message(m)


def load_solver(path_or_text: str, *, is_path: bool = True) -> SolverParameter:
    m = parse_file(path_or_text) if is_path else parse(path_or_text)
    return SolverParameter.from_message(m)
