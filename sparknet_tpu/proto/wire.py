"""Minimal protobuf *binary* wire-format reader/writer.

The text-format front end (textformat.py) covers prototxt configs; this
module covers Caffe's binary artifacts — ``.caffemodel`` weights,
``.binaryproto`` mean blobs, and LMDB ``Datum`` records (SURVEY.md §2
prototxt model zoo / data loaders; mount empty, no file:line).

Schema-free: ``decode`` yields ``{field_number: [raw values]}`` where a
raw value is an int (varint/fixed), bytes (length-delimited), or a
nested dict decoded on demand by the caller. Callers apply Caffe's
field numbering (see caffemodel.py / caffe_datum.py). ``encode``
mirrors it for writing.  Cross-checked against google.protobuf in
tests/test_caffemodel.py.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Tuple, Union

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2
WIRE_FIXED32 = 5

FieldMap = Dict[int, List[Any]]


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def write_varint(value: int) -> bytes:
    if value < 0:
        value &= (1 << 64) - 1  # two's complement, 64-bit
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, raw_value) over a message buffer."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == WIRE_VARINT:
            val, pos = read_varint(buf, pos)
        elif wt == WIRE_FIXED64:
            val = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == WIRE_BYTES:
            ln, pos = read_varint(buf, pos)
            val = buf[pos : pos + ln]
            if len(val) != ln:
                raise ValueError("truncated length-delimited field")
            pos += ln
        elif wt == WIRE_FIXED32:
            val = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def decode(buf: bytes) -> FieldMap:
    out: FieldMap = {}
    for field, _, val in iter_fields(buf):
        out.setdefault(field, []).append(val)
    return out


def packed_floats(raw: Union[bytes, List[Any]]) -> List[float]:
    """repeated float: packed bytes or a list of fixed32 ints."""
    if isinstance(raw, bytes):
        return list(struct.unpack(f"<{len(raw) // 4}f", raw))
    return [struct.unpack("<f", struct.pack("<I", v))[0] for v in raw]


def repeated_floats(fields: FieldMap, num: int) -> List[float]:
    """Gather a repeated float field that may be packed, unpacked, or
    split across multiple packed chunks."""
    out: List[float] = []
    for raw in fields.get(num, []):
        if isinstance(raw, bytes):
            out.extend(packed_floats(raw))
        else:
            out.append(struct.unpack("<f", struct.pack("<I", raw))[0])
    return out


def repeated_ints(fields: FieldMap, num: int) -> List[int]:
    """Repeated int64/int32 field, packed or not."""
    out: List[int] = []
    for raw in fields.get(num, []):
        if isinstance(raw, bytes):
            pos = 0
            while pos < len(raw):
                v, pos = read_varint(raw, pos)
                out.append(v)
        else:
            out.append(raw)
    return out


def first(fields: FieldMap, num: int, default: Any = None) -> Any:
    vals = fields.get(num)
    return vals[-1] if vals else default  # last-wins, proto semantics


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------

def tag(field: int, wt: int) -> bytes:
    return write_varint((field << 3) | wt)


def encode_varint_field(field: int, value: int) -> bytes:
    return tag(field, WIRE_VARINT) + write_varint(value)


def encode_bytes_field(field: int, value: bytes) -> bytes:
    return tag(field, WIRE_BYTES) + write_varint(len(value)) + value


def encode_string_field(field: int, value: str) -> bytes:
    return encode_bytes_field(field, value.encode())


def encode_packed_floats(field: int, values) -> bytes:
    import numpy as np

    payload = np.asarray(values, "<f4").tobytes()
    return encode_bytes_field(field, payload)


def encode_float_field(field: int, value: float) -> bytes:
    return tag(field, WIRE_FIXED32) + struct.pack("<f", value)
