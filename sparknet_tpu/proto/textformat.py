"""Protobuf text-format parser (schema-free).

SparkNet's reference feeds Caffe ``NetParameter``/``SolverParameter``
prototxt files to its native solver (see SURVEY.md §1: prototxt model zoo
``cifar10_quick``, ``bvlc_alexnet``, ``bvlc_googlenet``; the reference
mount was empty so no file:line citation is possible — BASELINE.json
names the prototxt configs directly). We parse the text format ourselves
so the front end has zero dependency on compiled Caffe protos.

The grammar we support is the complete protobuf text format as used by
Caffe model zoo files:

    message  := (field)*
    field    := ident ':' value | ident '{' message '}' | ident ':' '[' value (',' value)* ']'
    value    := scalar | '{' message '}'
    scalar   := number | 'true' | 'false' | ident (enum) | quoted-string+

Repeated fields accumulate in order; singular-field reads are last-wins
(protobuf semantics). Adjacent string literals concatenate. Bracket
lists expand to repeated values. Comments (``#`` to end of line) are
stripped. The result is a :class:`Message`: an ordered multimap with
convenience accessors, from which ``caffe_pb`` builds typed views.
"""

from __future__ import annotations

import re
from typing import Any, Iterator, List, Tuple

__all__ = ["Message", "parse", "parse_file", "ParseError"]


class ParseError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|\#[^\n]*)                       # whitespace / comment
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<punct>[{}\[\]:,;])
  | (?P<atom>[^\s{}\[\]:,;"'\#]+)
    """,
    re.VERBOSE,
)


def _tokenize(text: str) -> Iterator[Tuple[str, str]]:
    pos = 0
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise ParseError(f"bad character at offset {pos}: {text[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        yield kind, m.group()


class Message:
    """Ordered multimap of field name -> values (scalars or Messages)."""

    __slots__ = ("fields",)

    def __init__(self) -> None:
        self.fields: List[Tuple[str, Any]] = []

    # -- construction -----------------------------------------------------
    def add(self, name: str, value: Any) -> None:
        self.fields.append((name, value))

    # -- access -----------------------------------------------------------
    def get_all(self, name: str) -> List[Any]:
        return [v for k, v in self.fields if k == name]

    def get(self, name: str, default: Any = None) -> Any:
        """Singular-field access: protobuf text-format is last-wins."""
        out = default
        for k, v in self.fields:
            if k == name:
                out = v
        return out

    def get_first(self, name: str, default: Any = None) -> Any:
        for k, v in self.fields:
            if k == name:
                return v
        return default

    def has(self, name: str) -> bool:
        return any(k == name for k, _ in self.fields)

    def keys(self) -> List[str]:
        seen, out = set(), []
        for k, _ in self.fields:
            if k not in seen:
                seen.add(k)
                out.append(k)
        return out

    def __contains__(self, name: str) -> bool:
        return self.has(name)

    def __repr__(self) -> str:
        return f"Message({self.fields!r})"

    def to_dict(self) -> dict:
        """Lossy dict view (repeated fields become lists)."""
        grouped: dict = {}
        for k, v in self.fields:
            grouped.setdefault(k, []).append(
                v.to_dict() if isinstance(v, Message) else v
            )
        return {k: (vs[0] if len(vs) == 1 else vs) for k, vs in grouped.items()}


_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "a": "\a", "b": "\b",
    "f": "\f", "v": "\v", "\\": "\\", "'": "'", '"': '"', "?": "?",
}


def _unescape(body: str) -> str:
    """Protobuf string escapes, unicode-safe (no latin-1 round-trip)."""
    out: List[str] = []
    i, n = 0, len(body)
    while i < n:
        c = body[i]
        if c != "\\" or i + 1 >= n:
            out.append(c)
            i += 1
            continue
        e = body[i + 1]
        if e in _ESCAPES:
            out.append(_ESCAPES[e])
            i += 2
        elif e == "x" and i + 2 < n:
            j = i + 2
            while j < n and j < i + 4 and body[j] in "0123456789abcdefABCDEF":
                j += 1
            out.append(chr(int(body[i + 2 : j], 16)))
            i = j
        elif e == "u" and i + 5 < n:
            out.append(chr(int(body[i + 2 : i + 6], 16)))
            i += 6
        elif e.isdigit():
            j = i + 1
            while j < n and j < i + 4 and body[j] in "01234567":
                j += 1
            out.append(chr(int(body[i + 1 : j], 8)))
            i = j
        else:
            out.append(e)
            i += 2
    return "".join(out)


def _coerce_scalar(tok_kind: str, tok: str) -> Any:
    if tok_kind == "string":
        return _unescape(tok[1:-1])
    if tok == "true":
        return True
    if tok == "false":
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok  # enum identifier, e.g. MAX, LMDB, TRAIN


def parse(text: str) -> Message:
    tokens = list(_tokenize(text))
    msg, pos = _parse_message(tokens, 0, top=True)
    if pos != len(tokens):
        raise ParseError(f"trailing tokens at {pos}: {tokens[pos:pos+5]}")
    return msg


def _parse_message(tokens: List[Tuple[str, str]], pos: int, top: bool = False) -> Tuple[Message, int]:
    msg = Message()
    n = len(tokens)
    while pos < n:
        kind, tok = tokens[pos]
        if tok == "}":
            if top:
                raise ParseError("unexpected '}' at top level")
            return msg, pos
        if kind != "atom":
            raise ParseError(f"expected field name, got {tok!r}")
        name = tok
        pos += 1
        if pos >= n:
            raise ParseError(f"unexpected EOF after field name {name!r}")
        kind, tok = tokens[pos]
        if tok == ":":
            pos += 1
            if pos >= n:
                raise ParseError(f"unexpected EOF after '{name}:'")
            kind, tok = tokens[pos]
            if tok == "{":
                sub, pos = _parse_braced(tokens, pos)
                msg.add(name, sub)
            elif tok == "[":
                pos = _parse_list(tokens, pos, msg, name)
            else:
                val, pos = _parse_scalar(tokens, pos, name)
                msg.add(name, val)
        elif tok == "{":
            sub, pos = _parse_braced(tokens, pos)
            msg.add(name, sub)
        else:
            raise ParseError(f"expected ':' or '{{' after {name!r}, got {tok!r}")
        # optional separators
        while pos < n and tokens[pos][1] in (",", ";"):
            pos += 1
    if not top:
        raise ParseError("unexpected EOF inside message")
    return msg, pos


def _parse_scalar(tokens: List[Tuple[str, str]], pos: int, name: str) -> Tuple[Any, int]:
    kind, tok = tokens[pos]
    if kind not in ("string", "atom"):
        raise ParseError(f"expected scalar after '{name}:', got {tok!r}")
    val = _coerce_scalar(kind, tok)
    pos += 1
    # adjacent string literals concatenate, like C
    while kind == "string" and pos < len(tokens) and tokens[pos][0] == "string":
        val += _coerce_scalar("string", tokens[pos][1])
        pos += 1
    return val, pos


def _parse_list(tokens: List[Tuple[str, str]], pos: int, msg: Message, name: str) -> int:
    """``field: [v1, v2, ...]`` — each element adds as a repeated value."""
    assert tokens[pos][1] == "["
    pos += 1
    n = len(tokens)
    while pos < n and tokens[pos][1] != "]":
        if tokens[pos][1] == "{":
            sub, pos = _parse_braced(tokens, pos)
            msg.add(name, sub)
        else:
            val, pos = _parse_scalar(tokens, pos, name)
            msg.add(name, val)
        if pos < n and tokens[pos][1] == ",":
            pos += 1
    if pos >= n:
        raise ParseError(f"missing closing ']' for {name!r}")
    return pos + 1


def _parse_braced(tokens: List[Tuple[str, str]], pos: int) -> Tuple[Message, int]:
    assert tokens[pos][1] == "{"
    sub, pos = _parse_message(tokens, pos + 1)
    if pos >= len(tokens) or tokens[pos][1] != "}":
        raise ParseError("missing closing '}'")
    return sub, pos + 1


def parse_file(path: str) -> Message:
    with open(path, "r") as f:
        return parse(f.read())
