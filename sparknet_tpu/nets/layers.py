"""Caffe layer semantics on XLA — the TPU-native layer library.

The reference executes layers inside native Caffe (SURVEY.md §1-2: Caffe
vendored as native engine; mount empty so semantics follow the published
Caffe layer catalogue, not file:line cites). We re-implement the layer
*contract* — shapes, math, fillers, phase behavior — as pure functions
on ``jax.numpy``, designed for the TPU:

- **NHWC layout** (channels-last) everywhere, the layout XLA tiles best
  onto the MXU; Caffe's NCHW axis arguments are remapped (axis 1 ->
  last). Flatten order therefore differs from Caffe NCHW flatten; this
  matters only for bit-compat weight import, not for training parity.
- Convolution weights are stored **HWIO**, matmul weights **(in, out)**
  — both directly MXU-friendly, no transposes in the hot path.
- All shape arithmetic (ceil-mode pooling, Caffe's average-pool divisor
  that counts padding) is precomputed with numpy at trace time, so the
  compiled graph contains only static-shaped ``lax`` ops.

Each layer type registers three pure functions:
``infer`` (shape inference), ``init`` (param fillers), ``apply``.
BatchNorm additionally carries running stats through the ``state``
pytree (Caffe keeps them in blobs; a functional state pytree is the JAX
equivalent).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..proto.caffe_pb import Filler, LayerParameter
from ..ops.matmul import mxu_dot

Shape = Tuple[int, ...]

# Layer types that declare net inputs rather than computing anything.
DATA_LAYER_TYPES = {
    "Data",
    "Input",
    "MemoryData",
    "ImageData",
    "HDF5Data",
    "DummyData",
    "AnnotatedData",
    "WindowData",
}

LOSS_LAYER_TYPES = {
    "SoftmaxWithLoss",
    "SigmoidCrossEntropyLoss",
    "EuclideanLoss",
    "HingeLoss",
    "ContrastiveLoss",
    "MultinomialLogisticLoss",
    "InfogainLoss",
}


@dataclass
class ApplyCtx:
    train: bool
    rng: Optional[jax.Array]
    compute_dtype: Any = jnp.float32


# ---------------------------------------------------------------------------
# helpers


def _ints(param, name: str, default: int, count: int = 2) -> Tuple[int, ...]:
    """Caffe repeated-or-scalar spatial params (kernel_size/stride/pad)."""
    if param is None:
        return (default,) * count
    vals = [int(v) for v in param.get_all(name)]
    h = param.get(name + "_h")
    w = param.get(name + "_w")
    if h is not None or w is not None:
        return (int(h if h is not None else default), int(w if w is not None else default))
    if not vals:
        return (default,) * count
    if len(vals) == 1:
        return (vals[0],) * count
    return tuple(vals[:count])


def caffe_axis(axis: int, ndim: int) -> int:
    """Map a Caffe NCHW-axis argument onto our NHWC layout."""
    if axis < 0:
        axis += ndim
    if ndim == 4:
        return {0: 0, 1: 3, 2: 1, 3: 2}[axis]
    return axis


def fill(filler: Filler, rng: jax.Array, shape: Shape, fan_in: int, fan_out: int) -> jax.Array:
    t = filler.type
    if t == "constant":
        return jnp.full(shape, filler.value, jnp.float32)
    if t == "gaussian":
        return filler.mean + filler.std * jax.random.normal(rng, shape, jnp.float32)
    if t == "uniform":
        return jax.random.uniform(rng, shape, jnp.float32, filler.min, filler.max)
    if t in ("xavier", "msra"):
        if filler.variance_norm == "FAN_OUT":
            fan = fan_out
        elif filler.variance_norm == "AVERAGE":
            fan = (fan_in + fan_out) / 2.0
        else:
            fan = fan_in
        if t == "xavier":
            scale = math.sqrt(3.0 / fan)
            return jax.random.uniform(rng, shape, jnp.float32, -scale, scale)
        std = math.sqrt(2.0 / fan)
        return std * jax.random.normal(rng, shape, jnp.float32)
    if t == "bilinear":
        # upsampling deconv init; rarely used — approximate with msra
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(rng, shape, jnp.float32)
    raise NotImplementedError(f"filler type {t!r}")


def nchw_view(shape) -> List[int]:
    """The NCHW view of an NHWC 4D shape; non-4D shapes already carry
    NCHW-order axes (see the Reshape policy below)."""
    if len(shape) == 4:
        n, h, w, c = shape
        return [n, c, h, w]
    return list(shape)


def _spatial_geom(p):
    """convolution_param's kernel/stride/pad/dilation (shared by
    Convolution/Deconvolution via _conv_geom and by Im2col, which has
    no num_output)."""
    return (
        _ints(p, "kernel_size", 0), _ints(p, "stride", 1),
        _ints(p, "pad", 0), _ints(p, "dilation", 1),
    )


def _conv_geom(lp: LayerParameter):
    p = lp.convolution_param
    if p is None:
        raise ValueError(f"layer {lp.name}: missing convolution_param")
    (kh, kw), (sh, sw), (ph, pw), (dh, dw) = _spatial_geom(p)
    group = int(p.get("group", 1))
    cout = int(p.get("num_output"))
    bias = bool(p.get("bias_term", True))
    return (kh, kw), (sh, sw), (ph, pw), (dh, dw), group, cout, bias


def _conv_out(h: int, k: int, s: int, p: int, d: int) -> int:
    keff = d * (k - 1) + 1
    return (h + 2 * p - keff) // s + 1


def _pool_out(h: int, k: int, s: int, p: int) -> int:
    """Caffe ceil-mode pooling output size with the start-inside clamp."""
    out = int(math.ceil((h + 2 * p - k) / s)) + 1
    if p > 0 and (out - 1) * s >= h + p:
        out -= 1
    return out


# ---------------------------------------------------------------------------
# layer implementations. Each is a namespace of pure functions.


class Convolution:
    @staticmethod
    def infer(lp: LayerParameter, in_shapes: List[Shape]) -> List[Shape]:
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), group, cout, _ = _conv_geom(lp)
        n, h, w, c = in_shapes[0]
        return [(n, _conv_out(h, kh, sh, ph, dh), _conv_out(w, kw, sw, pw, dw), cout)]

    @staticmethod
    def init(lp: LayerParameter, rng: jax.Array, in_shapes: List[Shape]) -> Dict[str, jax.Array]:
        (kh, kw), _, _, _, group, cout, bias = _conv_geom(lp)
        cin = in_shapes[0][3]
        assert cin % group == 0 and cout % group == 0, (
            f"{lp.name}: group={group} must divide cin={cin}, cout={cout}"
        )
        p = lp.convolution_param
        wf = Filler.from_message(p.get("weight_filler"))
        k1, k2 = jax.random.split(rng)
        fan_in = kh * kw * (cin // group)
        fan_out = kh * kw * (cout // group)
        params = {"weight": fill(wf, k1, (kh, kw, cin // group, cout), fan_in, fan_out)}
        if bias:
            bf = Filler.from_message(p.get("bias_filler"))
            params["bias"] = fill(bf, k2, (cout,), fan_in, fan_out)
        return params

    @staticmethod
    def apply(lp, params, state, inputs, ctx: ApplyCtx):
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), group, cout, bias = _conv_geom(lp)
        x = inputs[0].astype(ctx.compute_dtype)
        w = params["weight"].astype(ctx.compute_dtype)
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            rhs_dilation=(dh, dw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=group,
            # no preferred_element_type: the MXU already accumulates
            # bf16 products in f32 internally, and an explicit f32
            # output breaks the conv transpose rule under mixed dtypes.
        )
        if bias and "bias" in params:
            y = y + params["bias"].astype(y.dtype)
        return [y], None


class Deconvolution:
    @staticmethod
    def infer(lp, in_shapes):
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), group, cout, _ = _conv_geom(lp)
        n, h, w, c = in_shapes[0]
        oh = sh * (h - 1) + (dh * (kh - 1) + 1) - 2 * ph
        ow = sw * (w - 1) + (dw * (kw - 1) + 1) - 2 * pw
        return [(n, oh, ow, cout)]

    @staticmethod
    def init(lp, rng, in_shapes):
        return Convolution.init(lp, rng, in_shapes)

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        # Transposed conv as an lhs-dilated conv (supports groups, which
        # lax.conv_transpose does not expose): dilate the input by the
        # stride, spatially flip the kernel, pad by keff-1-p.
        (kh, kw), (sh, sw), (ph, pw), (dh, dw), group, cout, bias = _conv_geom(lp)
        x = inputs[0].astype(ctx.compute_dtype)
        w = params["weight"].astype(ctx.compute_dtype)
        w = jnp.flip(w, (0, 1))
        keff_h = dh * (kh - 1) + 1
        keff_w = dw * (kw - 1) + 1
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=(1, 1),
            padding=((keff_h - 1 - ph, keff_h - 1 - ph), (keff_w - 1 - pw, keff_w - 1 - pw)),
            lhs_dilation=(sh, sw),
            rhs_dilation=(dh, dw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=group,
        )
        if bias and "bias" in params:
            y = y + params["bias"].astype(y.dtype)
        return [y], None


class Pooling:
    @staticmethod
    def _geom(lp, in_shape):
        p = lp.pooling_param
        n, h, w, c = in_shape
        if p is not None and bool(p.get("global_pooling", False)):
            kh, kw = h, w
            sh = sw = 1
            ph = pw = 0
        else:
            kh, kw = _ints(p, "kernel_size", 0)
            sh, sw = _ints(p, "stride", 1)
            ph, pw = _ints(p, "pad", 0)
        mode = str(p.get("pool", "MAX")) if p is not None else "MAX"
        return (kh, kw), (sh, sw), (ph, pw), mode

    @staticmethod
    def infer(lp, in_shapes):
        (kh, kw), (sh, sw), (ph, pw), _ = Pooling._geom(lp, in_shapes[0])
        n, h, w, c = in_shapes[0]
        return [(n, _pool_out(h, kh, sh, ph), _pool_out(w, kw, sw, pw), c)]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        n, h, w, c = x.shape
        (kh, kw), (sh, sw), (ph, pw), mode = Pooling._geom(lp, x.shape)
        oh = _pool_out(h, kh, sh, ph)
        ow = _pool_out(w, kw, sw, pw)
        # ceil mode may need extra low-side... no: extra high-side padding
        extra_h = max(0, (oh - 1) * sh + kh - (h + 2 * ph))
        extra_w = max(0, (ow - 1) * sw + kw - (w + 2 * pw))
        pad_h = (ph, ph + extra_h)
        pad_w = (pw, pw + extra_w)
        if mode == "MAX":
            y = lax.reduce_window(
                x,
                -jnp.inf,
                lax.max,
                (1, kh, kw, 1),
                (1, sh, sw, 1),
                ((0, 0), pad_h, pad_w, (0, 0)),
            )
            return [y.astype(x.dtype)], None
        if mode == "AVE":
            s = lax.reduce_window(
                x.astype(jnp.float32),
                0.0,
                lax.add,
                (1, kh, kw, 1),
                (1, sh, sw, 1),
                ((0, 0), pad_h, pad_w, (0, 0)),
            )
            # Caffe divisor: window clipped to the *padded* region — padding
            # counts toward the denominator. Static per-position constant.
            hs = np.arange(oh) * sh - ph
            he = np.minimum(hs + kh, h + ph)
            hs = np.maximum(hs, -ph)
            ws_ = np.arange(ow) * sw - pw
            we = np.minimum(ws_ + kw, w + pw)
            ws_ = np.maximum(ws_, -pw)
            div = (he - hs)[:, None] * (we - ws_)[None, :]
            y = s / jnp.asarray(div[None, :, :, None], jnp.float32)
            return [y.astype(x.dtype)], None
        raise NotImplementedError(f"pool mode {mode}")


class SPP:
    """Spatial pyramid pooling (He et al.): pyramid level i pools into
    a 2^i x 2^i grid (Caffe geometry: kernel = ceil(dim/bins), pad
    centers the remainder), each level flattens in NCHW order and the
    levels concatenate — a fixed-length descriptor from any input
    resolution."""

    @staticmethod
    def _geom(lp):
        p = lp.sub("spp_param")
        if p is None or p.get("pyramid_height") is None:
            raise ValueError(
                f"layer {lp.name!r}: SPP requires "
                f"spp_param {{ pyramid_height: N }}"
            )
        return int(p.get("pyramid_height")), str(p.get("pool", "MAX"))

    @staticmethod
    def _level(dim: int, bins: int):
        k = -(-dim // bins)  # ceil
        remainder = k * bins - dim
        pad = (remainder + 1) // 2
        return k, pad

    @staticmethod
    def infer(lp, in_shapes):
        height, _ = SPP._geom(lp)
        n, h, w, c = in_shapes[0]
        top_bins = 2 ** (height - 1)
        if top_bins > min(h, w):
            # Caffe CHECKs this at setup; without it the padded MAX
            # windows cover only -inf and the loss goes NaN silently
            raise ValueError(
                f"layer {lp.name!r}: pyramid level {height - 1} needs "
                f"{top_bins} bins per side but the input is {h}x{w}"
            )
        total = sum((2 ** i) ** 2 for i in range(height))
        return [(n, c * total)]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        height, mode = SPP._geom(lp)
        x = inputs[0]
        n, h, w, c = x.shape
        SPP.infer(lp, [x.shape])  # re-check bins vs dims (direct callers)
        pieces = []
        for i in range(height):
            bins = 2 ** i
            kh, ph = SPP._level(h, bins)
            kw, pw = SPP._level(w, bins)
            if mode == "MAX":
                init_v = -jnp.inf
                op = lax.max
            elif mode == "AVE":
                init_v = 0.0
                op = lax.add
            else:
                raise NotImplementedError(f"spp pool mode {mode}")
            y = lax.reduce_window(
                x.astype(jnp.float32), init_v, op,
                window_dimensions=(1, kh, kw, 1),
                window_strides=(1, kh, kw, 1),
                padding=((0, 0), (ph, kh * bins - h - ph),
                         (pw, kw * bins - w - pw), (0, 0)),
            )
            if mode == "AVE":
                y = y / (kh * kw)  # Caffe divides by the full window
            # flatten in NCHW order so the descriptor layout matches
            pieces.append(
                jnp.transpose(y, (0, 3, 1, 2)).reshape(n, -1)
            )
        out = jnp.concatenate(pieces, axis=1)
        return [out.astype(x.dtype)], None


class InnerProduct:
    @staticmethod
    def _geom(lp):
        p = lp.inner_product_param
        return int(p.get("num_output")), bool(p.get("bias_term", True)), int(p.get("axis", 1))

    @staticmethod
    def _axis(lp, ndim: int) -> int:
        # Caffe semantics: dims before `axis` are preserved (batch-like),
        # dims from `axis` on are flattened into the contraction
        ax = InnerProduct._geom(lp)[2]
        ax = ax if ax >= 0 else ndim + ax
        if not 1 <= ax < ndim:
            raise ValueError(
                f"layer {lp.name!r}: inner_product axis={ax} out of "
                f"range for a {ndim}-d bottom"
            )
        return ax

    @staticmethod
    def infer(lp, in_shapes):
        cout, _, _ = InnerProduct._geom(lp)
        ax = InnerProduct._axis(lp, len(in_shapes[0]))
        return [tuple(in_shapes[0][:ax]) + (cout,)]

    @staticmethod
    def init(lp, rng, in_shapes):
        cout, bias, _ = InnerProduct._geom(lp)
        ax = InnerProduct._axis(lp, len(in_shapes[0]))
        cin = int(np.prod(in_shapes[0][ax:]))
        p = lp.inner_product_param
        wf = Filler.from_message(p.get("weight_filler"))
        bf = Filler.from_message(p.get("bias_filler"))
        k1, k2 = jax.random.split(rng)
        params = {"weight": fill(wf, k1, (cin, cout), cin, cout)}
        if bias:
            params["bias"] = fill(bf, k2, (cout,), cin, cout)
        return params

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        cout, bias, _ = InnerProduct._geom(lp)
        x = inputs[0]
        ax = InnerProduct._axis(lp, x.ndim)
        lead = x.shape[:ax]
        x2 = x.reshape(int(np.prod(lead)), -1).astype(ctx.compute_dtype)
        w = params["weight"].astype(ctx.compute_dtype)
        # mxu_dot: f32 accumulation forward AND compute-dtype
        # backward dots (the default transpose rule would run the
        # backward at f32 MXU rate — see ops/matmul.py)
        y = mxu_dot(x2, w)
        if bias and "bias" in params:
            y = y + params["bias"]
        return [y.astype(ctx.compute_dtype).reshape(lead + (cout,))], None


class ReLU:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        p = lp.sub("relu_param")
        slope = float(p.get("negative_slope", 0.0)) if p else 0.0
        if slope:
            return [jnp.where(x > 0, x, slope * x)], None
        return [jax.nn.relu(x)], None


class _Elementwise:
    fn = staticmethod(lambda x: x)

    @classmethod
    def infer(cls, lp, in_shapes):
        return [in_shapes[0]]

    @classmethod
    def init(cls, lp, rng, in_shapes):
        return {}

    @classmethod
    def apply(cls, lp, params, state, inputs, ctx):
        return [cls.fn(inputs[0])], None


class Sigmoid(_Elementwise):
    fn = staticmethod(jax.nn.sigmoid)


class TanH(_Elementwise):
    fn = staticmethod(jnp.tanh)


class AbsVal(_Elementwise):
    fn = staticmethod(jnp.abs)


class BNLL(_Elementwise):
    # log(1 + exp(x)), computed stably
    fn = staticmethod(jax.nn.softplus)


class ELU:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        p = lp.sub("elu_param")
        alpha = float(p.get("alpha", 1.0)) if p else 1.0
        return [jax.nn.elu(inputs[0], alpha)], None


class Power:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        p = lp.sub("power_param")
        power = float(p.get("power", 1.0)) if p else 1.0
        scale = float(p.get("scale", 1.0)) if p else 1.0
        shift = float(p.get("shift", 0.0)) if p else 0.0
        y = scale * inputs[0] + shift
        if power != 1.0:
            y = jnp.power(y, power)
        return [y], None


class Exp:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        p = lp.sub("exp_param")
        base = float(p.get("base", -1.0)) if p else -1.0
        scale = float(p.get("scale", 1.0)) if p else 1.0
        shift = float(p.get("shift", 0.0)) if p else 0.0
        y = scale * inputs[0] + shift
        return [jnp.exp(y) if base <= 0 else jnp.power(base, y)], None


class Log:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        p = lp.sub("log_param")
        base = float(p.get("base", -1.0)) if p else -1.0
        scale = float(p.get("scale", 1.0)) if p else 1.0
        shift = float(p.get("shift", 0.0)) if p else 0.0
        y = jnp.log(scale * inputs[0] + shift)
        if base > 0:
            y = y / math.log(base)
        return [y], None


class LRN:
    """Local response normalization (AlexNet/GoogLeNet). ACROSS_CHANNELS
    runs the window over the channel axis — last in NHWC, so the rolling
    sum is a reduce_window over a minor axis, which XLA vectorizes well.
    """

    @staticmethod
    def _geom(lp):
        p = lp.lrn_param
        size = int(p.get("local_size", 5)) if p else 5
        alpha = float(p.get("alpha", 1.0)) if p else 1.0
        beta = float(p.get("beta", 0.75)) if p else 0.75
        k = float(p.get("k", 1.0)) if p else 1.0
        region = str(p.get("norm_region", "ACROSS_CHANNELS")) if p else "ACROSS_CHANNELS"
        return size, alpha, beta, k, region

    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        size, alpha, beta, k, region = LRN._geom(lp)
        x = inputs[0]
        if (
            region == "ACROSS_CHANNELS"
            and x.ndim == 4
            and x.shape[-1] <= 512  # (C,C) f32 band must fit VMEM
            # alongside the double-buffered row tiles (1 MB at C=512)
            and jax.default_backend() == "tpu"
            and os.environ.get("SPARKNET_LRN_PALLAS", "0") not in ("", "0")
        ):
            # fused one-pass kernel (ops/lrn.py). OFF by default: the
            # round-5 on-chip A/B measured it 2x SLOWER end to end
            # (86 vs 43 ms AlexNet bs512 step) — mid-network XLA
            # assigns the neighbouring convs exotic layouts (e.g.
            # batch-minor {0,3,2,1}) and a pallas_call pins row-major
            # operands, so every LRN pays conv-sized relayout copies
            # both ways that dwarf the temp-chain saving. Kept
            # reachable for standalone/row-major contexts.
            from ..ops.lrn import lrn_nhwc

            return [
                lrn_nhwc(x, size=size, alpha=alpha, beta=beta, k=k)
            ], None
        # The squared/windowed temps follow the net's compute dtype:
        # under bf16 the conv activations feeding this are already
        # bf16-rounded, and keeping LRN's conv-sized temp chain at f32
        # doubles its HBM bytes for ~3 extra digits in d that the
        # surrounding net can't use. On-chip (v5e, AlexNet bs512) the
        # bf16 temp chain is worth 5 ms/step: 42.7 -> 37.6 ms, MFU
        # 0.234 -> 0.266 (RESULTS.md "Round-5 A/B"). f32 nets are
        # untouched (x is f32); SPARKNET_LRN_F32=1 restores f32 temps
        # under bf16 for an apples-to-apples numerics comparison.
        out_dtype = x.dtype
        if os.environ.get("SPARKNET_LRN_F32", "0") not in ("", "0"):
            x = x.astype(jnp.float32)
        sq = jnp.square(x)
        half = size // 2
        if region == "ACROSS_CHANNELS":
            window = (1, 1, 1, size)
            padding = ((0, 0), (0, 0), (0, 0), (half, size - 1 - half))
            scale = alpha / size
        else:  # WITHIN_CHANNEL: avg over the size*size spatial window, k fixed 1
            window = (1, size, size, 1)
            padding = ((0, 0), (half, size - 1 - half), (half, size - 1 - half), (0, 0))
            scale = alpha / (size * size)
            k = 1.0
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1), padding)
        d = k + scale * ssum
        # x * d^-beta. Round-4 rewrote the pow into rsqrt/sqrt chains on
        # VPU-transcendental theory; the round-5 on-chip A/B (v5e,
        # AlexNet bs512, 50 timed iters — RESULTS.md "Round-5 A/B")
        # measured the chain ~2.5 ms/step SLOWER — LRN is HBM-bound,
        # and the longer chain plus its VJP materialises more conv-sized
        # temps than it saves in transcendentals. A single pow (and its
        # single-temp VJP) wins; SPARKNET_LRN_CHAIN=1 keeps the chain
        # reachable for re-measurement on other topologies.
        chain = os.environ.get("SPARKNET_LRN_CHAIN", "0") not in ("", "0")
        if chain and beta == 0.75:
            t = jnp.sqrt(lax.rsqrt(d))  # d^(-1/4)
            inv = t * t * t
        elif chain and beta == 0.5:
            inv = lax.rsqrt(d)
        elif chain and beta == 1.0:
            inv = 1.0 / d
        else:
            inv = jnp.power(d, -beta)
        return [(x * inv).astype(out_dtype)], None


class Dropout:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        p = lp.dropout_param
        ratio = float(p.get("dropout_ratio", 0.5)) if p else 0.5
        if not ctx.train or ratio <= 0.0:
            return [x], None
        keep = 1.0 - ratio
        mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)], None


class BatchNorm:
    """Caffe BatchNorm: normalization only (pair with Scale for affine).

    Caffe stores unnormalized sums + a scale factor in blobs; we keep
    normalized running mean/var in the state pytree with EMA updates
    (equivalent fixed point; SURVEY.md notes no file:line available).
    """

    @staticmethod
    def _geom(lp):
        p = lp.batch_norm_param
        use_global = p.get("use_global_stats") if p else None
        mavf = float(p.get("moving_average_fraction", 0.999)) if p else 0.999
        eps = float(p.get("eps", 1e-5)) if p else 1e-5
        return use_global, mavf, eps

    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def init_state(lp, in_shapes):
        c = in_shapes[0][-1]
        return {
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32),
        }

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        use_global, mavf, eps = BatchNorm._geom(lp)
        x = inputs[0]
        xf = x.astype(jnp.float32)
        axes = tuple(range(x.ndim - 1))  # all but channel
        if use_global is None:
            use_global = not ctx.train
        if use_global:
            mean, var = state["mean"], state["var"]
            new_state = state
        else:
            mean = jnp.mean(xf, axes)
            var = jnp.var(xf, axes)
            new_state = {
                "mean": mavf * state["mean"] + (1 - mavf) * mean,
                "var": mavf * state["var"] + (1 - mavf) * var,
            }
        # note: a compute-dtype normalize pass was probed on-chip in
        # round 5 and measured no faster (141 vs 143 ms ResNet-50
        # bs256 step) — unlike LRN's temp chain, XLA already fuses
        # these converts, so the f32 math here is free
        y = (xf - mean) * lax.rsqrt(var + eps)
        return [y.astype(x.dtype)], new_state


class Scale:
    """Per-channel (axis) scale, optional bias: the affine half of BN."""

    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        if len(in_shapes) == 2:  # scale comes from the second bottom
            return {}
        p = lp.scale_param
        bias = bool(p.get("bias_term", False)) if p else False
        c = in_shapes[0][-1]
        wf = Filler.from_message(p.get("filler")) if p and p.get("filler") else Filler(type="constant", value=1.0)
        bf = Filler.from_message(p.get("bias_filler")) if p and p.get("bias_filler") else Filler(type="constant", value=0.0)
        k1, k2 = jax.random.split(rng)
        params = {"weight": fill(wf, k1, (c,), c, c)}
        if bias:
            params["bias"] = fill(bf, k2, (c,), c, c)
        return params

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        if len(inputs) == 2:  # two-bottom form: second input is the scale
            y = inputs[0] * inputs[1]
        else:
            y = inputs[0] * params["weight"]
        if "bias" in params:
            y = y + params["bias"]
        return [y], None


class Bias:
    PARAM_ORDER = ("bias",)  # single learned blob

    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        if len(in_shapes) == 2:
            return {}
        c = in_shapes[0][-1]
        return {"bias": jnp.zeros((c,), jnp.float32)}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        b = inputs[1] if len(inputs) == 2 else params["bias"]
        return [inputs[0] + b], None


class Eltwise:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        p = lp.eltwise_param
        op = str(p.get("operation", "SUM")) if p else "SUM"
        if op == "SUM":
            coeffs = [float(c) for c in p.get_all("coeff")] if p else []
            if coeffs:
                if len(coeffs) != len(inputs):
                    raise ValueError(
                        f"layer {lp.name!r}: {len(coeffs)} eltwise coeffs "
                        f"for {len(inputs)} bottoms"
                    )
                y = sum(c * x for c, x in zip(coeffs, inputs))
            else:
                y = sum(inputs[1:], inputs[0])
        elif op == "PROD":
            y = inputs[0]
            for x in inputs[1:]:
                y = y * x
        elif op == "MAX":
            y = inputs[0]
            for x in inputs[1:]:
                y = jnp.maximum(y, x)
        else:
            raise NotImplementedError(f"eltwise op {op}")
        return [y], None


class Concat:
    @staticmethod
    def _axis(lp, ndim):
        p = lp.concat_param
        ax = int(p.get("axis", p.get("concat_dim", 1))) if p else 1
        return caffe_axis(ax, ndim)

    @staticmethod
    def infer(lp, in_shapes):
        ax = Concat._axis(lp, len(in_shapes[0]))
        out = list(in_shapes[0])
        out[ax] = sum(s[ax] for s in in_shapes)
        return [tuple(out)]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        return [jnp.concatenate(inputs, Concat._axis(lp, inputs[0].ndim))], None


class Slice:
    @staticmethod
    def _geom(lp, in_shape):
        p = lp.sub("slice_param")
        ax = int(p.get("axis", p.get("slice_dim", 1))) if p else 1
        ax = caffe_axis(ax, len(in_shape))
        points = [int(x) for x in p.get_all("slice_point")] if p else []
        return ax, points

    @staticmethod
    def infer(lp, in_shapes):
        ax, points = Slice._geom(lp, in_shapes[0])
        total = in_shapes[0][ax]
        if not points:
            n = len(lp.top)
            points = [total // n * i for i in range(1, n)]
        bounds = [0] + points + [total]
        outs = []
        for i in range(len(bounds) - 1):
            s = list(in_shapes[0])
            s[ax] = bounds[i + 1] - bounds[i]
            outs.append(tuple(s))
        return outs

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        ax, points = Slice._geom(lp, x.shape)
        if not points:
            n = len(lp.top)
            points = [x.shape[ax] // n * i for i in range(1, n)]
        return list(jnp.split(x, points, axis=ax)), None


class Split:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]] * max(1, len(lp.top))

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        return [inputs[0]] * max(1, len(lp.top)), None


class Flatten:
    @staticmethod
    def infer(lp, in_shapes):
        s = in_shapes[0]
        return [(s[0], int(np.prod(s[1:])))]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        return [x.reshape(x.shape[0], -1)], None


class Reshape:
    """Caffe reshape semantics operate on the NCHW view; we transpose a
    4D NHWC input to NCHW, reshape, and transpose back when the result
    is again 4D (non-4D results keep NCHW-order axes, like Caffe)."""

    @staticmethod
    def _nchw_shape(lp, in_shape_nchw):
        p = lp.sub("reshape_param")
        dims = [int(d) for d in p.get("shape").get_all("dim")]
        out = []
        for i, d in enumerate(dims):
            if d == 0:
                out.append(in_shape_nchw[i])
            else:
                out.append(d)
        if -1 in out:
            known = int(np.prod([d for d in out if d != -1]))
            total = int(np.prod(in_shape_nchw))
            out[out.index(-1)] = total // known
        return tuple(out)

    @staticmethod
    def _shapes(lp, in_shape):
        if len(in_shape) == 4:
            n, h, w, c = in_shape
            nchw_in = (n, c, h, w)
        else:
            nchw_in = tuple(in_shape)
        nchw_out = Reshape._nchw_shape(lp, nchw_in)
        if len(nchw_out) == 4:
            n, c, h, w = nchw_out
            return nchw_out, (n, h, w, c)
        return nchw_out, nchw_out

    @staticmethod
    def infer(lp, in_shapes):
        return [Reshape._shapes(lp, in_shapes[0])[1]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        nchw_out, out = Reshape._shapes(lp, x.shape)
        if x.ndim == 4:
            x = jnp.transpose(x, (0, 3, 1, 2))
        y = x.reshape(nchw_out)
        if len(nchw_out) == 4:
            y = jnp.transpose(y, (0, 2, 3, 1))
        return [y], None


class Softmax:
    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        p = lp.sub("softmax_param")
        ax = caffe_axis(int(p.get("axis", 1)) if p else 1, x.ndim)
        return [jax.nn.softmax(x.astype(jnp.float32), axis=ax).astype(x.dtype)], None


class SoftmaxWithLoss:
    @staticmethod
    def infer(lp, in_shapes):
        return [()]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        logits, labels = inputs[0], inputs[1]
        logits = logits.astype(jnp.float32)
        if logits.ndim > 2:
            ax = caffe_axis(1, logits.ndim)
            logits = jnp.moveaxis(logits, ax, -1).reshape(-1, logits.shape[ax])
            labels = labels.reshape(-1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, labels.astype(jnp.int32)[:, None], axis=-1
        )[:, 0]
        p = lp.sub("loss_param")
        ignore = p.get("ignore_label") if p else None
        if ignore is not None:
            valid = labels != int(ignore)
            loss = jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(
                jnp.sum(valid), 1
            )
        else:
            loss = jnp.mean(nll)
        return [loss], None


class SigmoidCrossEntropyLoss:
    @staticmethod
    def infer(lp, in_shapes):
        return [()]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x, t = inputs[0].astype(jnp.float32), inputs[1].astype(jnp.float32)
        # stable: max(x,0) - x*t + log(1+exp(-|x|)); Caffe normalizes by N
        loss = jnp.sum(
            jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
        ) / x.shape[0]
        return [loss], None


class EuclideanLoss:
    @staticmethod
    def infer(lp, in_shapes):
        return [()]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        a, b = inputs[0].astype(jnp.float32), inputs[1].astype(jnp.float32)
        return [jnp.sum(jnp.square(a - b)) / (2.0 * a.shape[0])], None


class Accuracy:
    @staticmethod
    def infer(lp, in_shapes):
        return [()] * max(1, len(lp.top))

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        logits, labels = inputs[0], inputs[1].astype(jnp.int32)
        p = lp.sub("accuracy_param")
        top_k = int(p.get("top_k", 1)) if p else 1
        ignore = p.get("ignore_label") if p else None
        if top_k == 1:
            correct = jnp.argmax(logits, -1) == labels
        else:
            _, idx = lax.top_k(logits, top_k)
            correct = jnp.any(idx == labels[:, None], axis=-1)
        if ignore is not None:
            valid = labels != int(ignore)
            acc = jnp.sum(
                jnp.where(valid, correct, False).astype(jnp.float32)
            ) / jnp.maximum(jnp.sum(valid), 1)
        else:
            acc = jnp.mean(correct.astype(jnp.float32))
        outs = [acc] * max(1, len(lp.top))
        return outs, None


class PReLU:
    """Learnable leaky slope, per channel (Caffe NCHW channel -> our
    trailing axis) or shared (``channel_shared``); filler default 0.25."""

    PARAM_ORDER = ("slope",)  # prototxt param{} spec 0 is the slope blob

    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        p = lp.sub("prelu_param")
        shared = bool(p.get("channel_shared", False)) if p else False
        c = 1 if shared else int(in_shapes[0][-1])
        fm = p.get("filler") if p else None
        filler = (
            Filler.from_message(fm)
            if fm is not None
            else Filler(type="constant", value=0.25)
        )
        return {"slope": fill(filler, rng, (c,), c, c)}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        a = params["slope"].astype(x.dtype)
        return [jnp.where(x > 0, x, a * x)], None


class Threshold(_Elementwise):
    @classmethod
    def apply(cls, lp, params, state, inputs, ctx):
        p = lp.sub("threshold_param")
        t = float(p.get("threshold", 0.0)) if p else 0.0
        x = inputs[0]
        return [(x > t).astype(x.dtype)], None


class Tile:
    @staticmethod
    def _geom(lp, ndim):
        p = lp.sub("tile_param")
        axis = caffe_axis(int(p.get("axis", 1)) if p else 1, ndim)
        tiles = int(p.get("tiles")) if p else 1
        return axis, tiles

    @staticmethod
    def infer(lp, in_shapes):
        s = list(in_shapes[0])
        axis, tiles = Tile._geom(lp, len(s))
        s[axis] *= tiles
        return [tuple(s)]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        axis, tiles = Tile._geom(lp, x.ndim)
        reps = [1] * x.ndim
        reps[axis] = tiles
        return [jnp.tile(x, reps)], None


class MVN:
    """Mean-variance normalization per sample: over H,W per channel, or
    over C,H,W when ``across_channels``."""

    @staticmethod
    def infer(lp, in_shapes):
        return [in_shapes[0]]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        p = lp.sub("mvn_param")
        across = bool(p.get("across_channels", False)) if p else False
        norm_var = bool(p.get("normalize_variance", True)) if p else True
        eps = float(p.get("eps", 1e-9)) if p else 1e-9
        x = inputs[0].astype(jnp.float32)
        axes = tuple(range(1, x.ndim)) if across else tuple(range(1, x.ndim - 1))
        mu = jnp.mean(x, axes, keepdims=True)
        y = x - mu
        if norm_var:
            # Caffe divides by sqrt(E[(x-mu)^2]) + eps (eps OUTSIDE)
            y = y / (jnp.sqrt(jnp.mean(jnp.square(y), axes, keepdims=True)) + eps)
        return [y.astype(inputs[0].dtype)], None


class ArgMax:
    """Per-sample top-k indices (float blob, like Caffe); ``axis`` keeps
    dims and disallows out_max_val pairs, axis-less flattens the sample."""

    @staticmethod
    def _geom(lp):
        p = lp.sub("argmax_param")
        top_k = int(p.get("top_k", 1)) if p else 1
        out_max = bool(p.get("out_max_val", False)) if p else False
        axis = p.get("axis") if p else None
        return top_k, out_max, (None if axis is None else int(axis))

    @staticmethod
    def infer(lp, in_shapes):
        top_k, out_max, axis = ArgMax._geom(lp)
        s = in_shapes[0]
        if axis is not None:
            out = list(s)
            out[caffe_axis(axis, len(s))] = top_k
            return [tuple(out)]
        return [(s[0], 2 if out_max else 1, top_k)]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        top_k, out_max, axis = ArgMax._geom(lp)
        x = inputs[0].astype(jnp.float32)
        if axis is not None:
            ax = caffe_axis(axis, x.ndim)
            xm = jnp.moveaxis(x, ax, -1)
            vals, idx = lax.top_k(xm, top_k)
            # with an axis, Caffe emits the top-k VALUES when
            # out_max_val is set (indices otherwise) — never pairs
            y = vals if out_max else idx.astype(jnp.float32)
            return [jnp.moveaxis(y, -1, ax)], None
        flat = x.reshape(x.shape[0], -1)
        vals, idx = lax.top_k(flat, top_k)
        idx = idx.astype(jnp.float32)[:, None, :]
        if out_max:
            return [jnp.concatenate([idx, vals[:, None, :]], axis=1)], None
        return [idx], None


class Embed:
    """Lookup table: integer indices -> (…, num_output) rows."""

    @staticmethod
    def _geom(lp):
        p = lp.sub("embed_param")
        return (
            int(p.get("num_output")),
            int(p.get("input_dim")),
            # caffe.proto EmbedParameter: bias_term [default = true]
            bool(p.get("bias_term", True)),
        )

    @staticmethod
    def infer(lp, in_shapes):
        cout, _, _ = Embed._geom(lp)
        return [tuple(in_shapes[0]) + (cout,)]

    @staticmethod
    def init(lp, rng, in_shapes):
        cout, vocab, bias = Embed._geom(lp)
        p = lp.sub("embed_param")
        wf = Filler.from_message(p.get("weight_filler"))
        k1, k2 = jax.random.split(rng)
        params = {"weight": fill(wf, k1, (vocab, cout), vocab, cout)}
        if bias:
            bf = Filler.from_message(p.get("bias_filler"))
            params["bias"] = fill(bf, k2, (cout,), vocab, cout)
        return params

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        idx = inputs[0].astype(jnp.int32)
        y = params["weight"][idx]
        if "bias" in params:
            y = y + params["bias"]
        return [y.astype(ctx.compute_dtype)], None


class Reduction:
    """Reduce every axis from ``axis`` to the end of the NCHW view
    (Caffe flattens the tail); non-4D outputs keep NCHW-order axes,
    matching the Reshape policy above."""

    @staticmethod
    def _geom(lp):
        p = lp.sub("reduction_param")
        op = str(p.get("operation", "SUM")) if p else "SUM"
        axis = int(p.get("axis", 0)) if p else 0
        coeff = float(p.get("coeff", 1.0)) if p else 1.0
        return op, axis, coeff

    @staticmethod
    def infer(lp, in_shapes):
        _, axis, _ = Reduction._geom(lp)
        nchw = nchw_view(in_shapes[0])
        axis = axis % len(nchw) if axis else 0
        return [tuple(nchw[:axis])]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        op, axis, coeff = Reduction._geom(lp)
        x = inputs[0].astype(jnp.float32)
        if x.ndim == 4:
            x = jnp.transpose(x, (0, 3, 1, 2))
        axis = axis % x.ndim if axis else 0
        axes = tuple(range(axis, x.ndim))
        if op == "SUM":
            y = jnp.sum(x, axes)
        elif op == "ASUM":
            y = jnp.sum(jnp.abs(x), axes)
        elif op == "SUMSQ":
            y = jnp.sum(jnp.square(x), axes)
        elif op == "MEAN":
            y = jnp.mean(x, axes)
        else:
            raise NotImplementedError(f"reduction op {op}")
        return [(coeff * y).astype(inputs[0].dtype)], None


class Crop:
    """Crop bottom[0] to bottom[1]'s size from ``axis`` (NCHW view)
    onward at the given offsets, like the FCN skip-connection crops."""

    @staticmethod
    def _geom(lp, ndim):
        p = lp.sub("crop_param")
        axis = int(p.get("axis", 2)) if p else 2
        offsets = [int(o) for o in p.get_all("offset")] if p else []
        return axis % ndim, offsets

    @staticmethod
    def infer(lp, in_shapes):
        a = nchw_view(in_shapes[0])
        b = nchw_view(in_shapes[1])
        if len(a) != len(b):
            # Caffe's CropLayer CHECKs num_axes equality
            raise ValueError(
                f"layer {lp.name!r}: crop bottoms must have equal rank, "
                f"got {len(a)} vs {len(b)}"
            )
        axis, _ = Crop._geom(lp, len(a))
        out = a[:axis] + b[axis:]
        if len(out) == 4:
            n, c, h, w = out
            return [(n, h, w, c)]
        return [tuple(out)]

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0]
        ref_nchw = nchw_view(inputs[1].shape)
        if len(ref_nchw) != x.ndim:
            raise ValueError(
                f"layer {lp.name!r}: crop bottoms must have equal rank, "
                f"got {x.ndim} vs {len(ref_nchw)}"
            )
        x_nchw4 = x.ndim == 4
        if x_nchw4:
            x = jnp.transpose(x, (0, 3, 1, 2))
        axis, offsets = Crop._geom(lp, x.ndim)
        n_cropped = x.ndim - axis
        if len(offsets) not in (0, 1, n_cropped):
            # Caffe's CropLayer CHECKs exactly 1 or n offsets
            raise ValueError(
                f"layer {lp.name!r}: crop needs 1 or {n_cropped} offsets, "
                f"got {len(offsets)}"
            )
        starts = [0] * x.ndim
        sizes = list(x.shape)
        for i in range(axis, x.ndim):
            j = i - axis
            off = offsets[j] if len(offsets) == n_cropped else (
                offsets[0] if offsets else 0
            )
            starts[i] = off
            sizes[i] = ref_nchw[i]
        y = lax.slice(
            x, starts, [s + z for s, z in zip(starts, sizes)]
        )
        if x_nchw4:
            y = jnp.transpose(y, (0, 2, 3, 1))
        return [y], None

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}


class Silence:
    """Consumes its bottoms, produces nothing (suppresses unused-blob
    plumbing in prototxts)."""

    @staticmethod
    def infer(lp, in_shapes):
        return []

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        return [], None


class LSTM:
    """Caffe's LSTMLayer: time-major input x (T, N, ...) plus sequence
    -continuation markers cont (T, N) (0 at sequence starts resets the
    state, so packed batches of variable-length sequences train
    correctly). One ``lax.scan`` over T — the TPU-native unrolling;
    gate order i, f, o, g matches Caffe's blob layout, and the blobs
    are [W_xc (in,4H), b (4H), W_hc (H,4H)] via PARAM_ORDER."""

    PARAM_ORDER = ("weight", "bias", "hidden_weight")

    @staticmethod
    def _geom(lp):
        p = lp.sub("recurrent_param")
        h = int(p.get("num_output"))
        if p.get("expose_hidden"):
            raise NotImplementedError(
                f"layer {lp.name!r}: recurrent expose_hidden unsupported"
            )
        return h, p

    @staticmethod
    def infer(lp, in_shapes):
        h, _ = LSTM._geom(lp)
        t, n = in_shapes[0][:2]
        return [(t, n, h)]

    @staticmethod
    def init(lp, rng, in_shapes):
        h, p = LSTM._geom(lp)
        cin = int(np.prod(in_shapes[0][2:]))
        wf = Filler.from_message(p.get("weight_filler"))
        bf = Filler.from_message(p.get("bias_filler"))
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "weight": fill(wf, k1, (cin, 4 * h), cin, 4 * h),
            "bias": fill(bf, k2, (4 * h,), cin, 4 * h),
            "hidden_weight": fill(wf, k3, (h, 4 * h), h, 4 * h),
        }

    @staticmethod
    def _cont(inputs, t, n, dtype):
        if len(inputs) > 1:
            return inputs[1].astype(dtype).reshape(t, n)
        # no cont bottom: one unbroken sequence per batch row (first
        # step still starts from the zero state)
        return jnp.ones((t, n), dtype).at[0].set(0.0)

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        hs, _ = LSTM._geom(lp)
        x = inputs[0]
        t, n = x.shape[:2]
        cdt = ctx.compute_dtype
        x = x.reshape(t, n, -1).astype(cdt)
        cont = LSTM._cont(inputs, t, n, jnp.float32)
        wx = params["weight"].astype(cdt)
        wh = params["hidden_weight"].astype(cdt)
        b = params["bias"]
        # input contribution for every step in one batched matmul
        gx = mxu_dot(x, wx) + b  # (T, N, 4H) f32

        def step(carry, inp):
            h_prev, c_prev = carry
            gxt, ct = inp
            h_in = (h_prev * ct[:, None]).astype(cdt)
            gates = gxt + mxu_dot(h_in, wh)
            i, f, o, g = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            o = jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c = ct[:, None] * (f * c_prev) + i * g
            h = o * jnp.tanh(c)
            return (h, c), h

        zeros = jnp.zeros((n, hs), jnp.float32)
        _, hseq = lax.scan(step, (zeros, zeros), (gx, cont))
        return [hseq.astype(cdt)], None


class RNN(LSTM):
    """Caffe's RNNLayer: h_t = tanh(W_xh x_t + b_h + W_hh h_{t-1}),
    o_t = tanh(W_ho h_t + b_o); blobs [W_xh, b_h, W_hh, W_ho, b_o]."""

    PARAM_ORDER = (
        "weight", "bias", "hidden_weight", "out_weight", "out_bias"
    )

    @staticmethod
    def init(lp, rng, in_shapes):
        h, p = LSTM._geom(lp)
        cin = int(np.prod(in_shapes[0][2:]))
        wf = Filler.from_message(p.get("weight_filler"))
        bf = Filler.from_message(p.get("bias_filler"))
        ks = jax.random.split(rng, 5)
        return {
            "weight": fill(wf, ks[0], (cin, h), cin, h),
            "bias": fill(bf, ks[1], (h,), cin, h),
            "hidden_weight": fill(wf, ks[2], (h, h), h, h),
            "out_weight": fill(wf, ks[3], (h, h), h, h),
            "out_bias": fill(bf, ks[4], (h,), h, h),
        }

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        hs, _ = LSTM._geom(lp)
        x = inputs[0]
        t, n = x.shape[:2]
        cdt = ctx.compute_dtype
        x = x.reshape(t, n, -1).astype(cdt)
        cont = LSTM._cont(inputs, t, n, jnp.float32)
        wx = params["weight"].astype(cdt)
        wh = params["hidden_weight"].astype(cdt)
        wo = params["out_weight"].astype(cdt)
        gx = mxu_dot(x, wx) + params["bias"]

        def step(h_prev, inp):
            gxt, ct = inp
            h_in = (h_prev * ct[:, None]).astype(cdt)
            h = jnp.tanh(gxt + mxu_dot(h_in, wh))
            o = jnp.tanh(mxu_dot(h.astype(cdt), wo) + params["out_bias"])
            return h, o

        zeros = jnp.zeros((n, hs), jnp.float32)
        _, oseq = lax.scan(step, zeros, (gx, cont))
        return [oseq.astype(cdt)], None


class MultinomialLogisticLoss:
    """NLL over already-softmaxed probabilities (Caffe pairs it with an
    explicit Softmax layer; SoftmaxWithLoss is the fused form)."""

    @staticmethod
    def infer(lp, in_shapes):
        return [()]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        probs = inputs[0].astype(jnp.float32)
        labels = inputs[1].astype(jnp.int32).reshape(-1)
        p = jnp.take_along_axis(
            probs.reshape(labels.shape[0], -1), labels[:, None], axis=-1
        )[:, 0]
        # Caffe clamps at kLOG_THRESHOLD=1e-20
        return [-jnp.mean(jnp.log(jnp.maximum(p, 1e-20)))], None


class InfogainLoss:
    """NLL weighted by an infogain matrix H (bottom[2] or
    ``infogain_loss_param.source`` .binaryproto); H=I reduces to
    MultinomialLogisticLoss."""

    @staticmethod
    def infer(lp, in_shapes):
        return [()]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def _matrix(lp, inputs, n_classes):
        if len(inputs) == 3:
            return inputs[2].astype(jnp.float32).reshape(n_classes, n_classes)
        p = lp.sub("infogain_loss_param")
        src = str(p.get("source")) if p and p.get("source") else None
        if src is None:
            raise ValueError(
                f"layer {lp.name!r}: InfogainLoss needs a third bottom or "
                f"infogain_loss_param.source"
            )
        from ..proto.caffemodel import load_binaryproto_mean

        h = load_binaryproto_mean(src)
        return jnp.asarray(h, jnp.float32).reshape(n_classes, n_classes)

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        probs = inputs[0].astype(jnp.float32)
        labels = inputs[1].astype(jnp.int32).reshape(-1)
        probs = probs.reshape(labels.shape[0], -1)
        h = InfogainLoss._matrix(lp, inputs, probs.shape[-1])
        logp = jnp.log(jnp.maximum(probs, 1e-20))
        # loss_i = -sum_j H[label_i, j] * log p_ij
        rows = h[labels]  # (N, C)
        return [-jnp.mean(jnp.sum(rows * logp, axis=-1))], None


class HingeLoss:
    """One-vs-all hinge over (N, C) scores: t=+1 at the label, -1
    elsewhere; L1 or squared (L2) norm, averaged over N."""

    @staticmethod
    def infer(lp, in_shapes):
        return [()]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        x = inputs[0].astype(jnp.float32)
        labels = inputs[1].astype(jnp.int32).reshape(-1)
        t = 2.0 * jax.nn.one_hot(labels, x.shape[-1]) - 1.0
        m = jnp.maximum(0.0, 1.0 - t * x)
        p = lp.sub("hinge_loss_param")
        norm = str(p.get("norm", "L1")) if p else "L1"
        if norm == "L2":
            m = jnp.square(m)
        return [jnp.sum(m) / x.shape[0]], None


class ContrastiveLoss:
    """Siamese pairs: y=1 similar pulls d^2, y=0 dissimilar pushes to
    ``margin``; legacy_version uses Caffe's original margin-d^2 form."""

    @staticmethod
    def infer(lp, in_shapes):
        return [()]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        a = inputs[0].astype(jnp.float32).reshape(inputs[0].shape[0], -1)
        b = inputs[1].astype(jnp.float32).reshape(inputs[1].shape[0], -1)
        y = inputs[2].astype(jnp.float32).reshape(-1)
        p = lp.sub("contrastive_loss_param")
        margin = float(p.get("margin", 1.0)) if p else 1.0
        legacy = bool(p.get("legacy_version", False)) if p else False
        d2 = jnp.sum(jnp.square(a - b), -1)
        if legacy:
            dissim = jnp.maximum(margin - d2, 0.0)
        else:
            dissim = jnp.square(jnp.maximum(margin - jnp.sqrt(d2 + 1e-12), 0.0))
        loss = jnp.sum(y * d2 + (1.0 - y) * dissim) / (2.0 * a.shape[0])
        return [loss], None


class BatchReindex:
    """Caffe BatchReindexLayer: top = bottom[0][bottom[1]] along the
    batch axis (gather; autodiff gives the scatter-add backward)."""

    @staticmethod
    def infer(lp, in_shapes):
        if len(in_shapes[1]) != 1:
            raise ValueError(
                f"layer {lp.name!r}: BatchReindex wants a rank-1 index "
                f"blob (Caffe's contract), got shape {in_shapes[1]}"
            )
        return [(in_shapes[1][0],) + tuple(in_shapes[0][1:])]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        idx = inputs[1].reshape(-1).astype(jnp.int32)
        # mode="clip": an out-of-range index (Caffe CHECK-fails at
        # runtime; untraceable under jit) clamps to the batch edge
        # instead of jnp.take's default fill-with-NaN, which would
        # silently poison training
        return [jnp.take(inputs[0], idx, axis=0, mode="clip")], None


class Parameter:
    """Caffe ParameterLayer: exposes a learnable blob as a top.
    ``parameter_param { shape { dim ... } }``; Caffe initialises the
    blob to zeros (values normally arrive via .caffemodel loading),
    and so do we."""

    @staticmethod
    def _shape(lp) -> Shape:
        p = lp.sub("parameter_param")
        shp = p.get("shape") if p else None
        if shp is None:
            raise ValueError(
                f"layer {lp.name!r}: Parameter needs parameter_param.shape"
            )
        return tuple(int(d) for d in shp.get_all("dim"))

    @staticmethod
    def infer(lp, in_shapes):
        return [Parameter._shape(lp)]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {"weight": jnp.zeros(Parameter._shape(lp), jnp.float32)}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        return [params["weight"].astype(ctx.compute_dtype)], None


class Im2col:
    """Caffe Im2colLayer: explicit patch extraction. NCHW Caffe emits
    (N, C*kh*kw, Ho, Wo) with c-major column order; the NHWC twin emits
    (N, Ho, Wo, C*kh*kw) with the SAME c-major feature order, so
    column contents match Caffe's exactly (only the axis placement
    follows this library's NHWC policy)."""

    @staticmethod
    def _geom(lp):
        p = lp.convolution_param
        if p is None:
            raise ValueError(f"layer {lp.name}: missing convolution_param")
        return _spatial_geom(p)

    @staticmethod
    def infer(lp, in_shapes):
        (kh, kw), (sh, sw), (ph, pw), (dh, dw) = Im2col._geom(lp)
        n, h, w, c = in_shapes[0]
        return [(
            n, _conv_out(h, kh, sh, ph, dh), _conv_out(w, kw, sw, pw, dw),
            c * kh * kw,
        )]

    @staticmethod
    def init(lp, rng, in_shapes):
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        (kh, kw), (sh, sw), (ph, pw), (dh, dw) = Im2col._geom(lp)
        x = inputs[0]
        # conv_general_dilated_patches orders the output features
        # c-major (source channel, then filter h, then filter w) — the
        # exact Caffe column order
        out = jax.lax.conv_general_dilated_patches(
            x.astype(ctx.compute_dtype),
            filter_shape=(kh, kw),
            window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            rhs_dilation=(dh, dw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return [out], None


# ---------------------------------------------------------------------------
# Caffe `Python` layer escape hatch.
#
# Caffe's Python layer loads a user class (python_param.module/.layer)
# and calls its setup/forward/backward on host tensors. A host callback
# per layer would serialize the TPU pipeline, so the TPU-native contract
# is a *traceable* callable registry instead: the user registers a pure
# JAX function (or a full infer/init/apply impl) under "module.layer",
# and it is traced and fused into the jitted step like any built-in
# layer — autodiff replaces the hand-written backward.

PYTHON_LAYER_REGISTRY: Dict[str, Any] = {}


def register_python_layer(name: str, impl: Any = None):
    """Register a ``Python``-layer implementation (also a decorator).

    ``impl`` is either a bare traceable callable
    ``fn(inputs: list[Array], param_str: str) -> list[Array]`` —
    stateless, shapes inferred with ``jax.eval_shape`` over *float32*
    avals (a callable that demands integer inputs, e.g. index-taking
    on a label bottom, will fail at net-build time; give it the full
    protocol with an explicit ``infer`` instead) — or an object with
    the full built-in layer protocol (``infer(lp, in_shapes)``,
    ``init(lp, rng, in_shapes)``, ``apply(lp, params, state, inputs,
    ctx)``) for layers that need params, state, integer-typed inputs,
    or phase behavior.
    ``name`` should match the prototxt's ``python_param`` as
    ``"<module>.<layer>"``; a bare ``"<layer>"`` key acts as a
    module-agnostic fallback.
    """
    if impl is None:
        return lambda f: register_python_layer(name, f)
    PYTHON_LAYER_REGISTRY[name] = impl
    return impl


class PythonLayer:
    """Dispatch for Caffe ``Python`` layers via the callable registry."""

    @staticmethod
    def _impl(lp) -> Tuple[Any, str]:
        p = lp.sub("python_param")
        module = str(p.get("module", "")) if p else ""
        layer = str(p.get("layer", "")) if p else ""
        param_str = str(p.get("param_str", "")) if p else ""
        for key in ((f"{module}.{layer}",) if module else ()) + (layer,):
            if key in PYTHON_LAYER_REGISTRY:
                return PYTHON_LAYER_REGISTRY[key], param_str
        raise KeyError(
            f"Python layer {lp.name!r} wants {module + '.' if module else ''}"
            f"{layer} but nothing is registered under that name — call "
            f"sparknet_tpu.register_python_layer({(module + '.' + layer) if module else layer!r}, fn) "
            f"with a traceable callable before building the net"
        )

    @staticmethod
    def infer(lp, in_shapes):
        impl, param_str = PythonLayer._impl(lp)
        if hasattr(impl, "infer"):
            return impl.infer(lp, in_shapes)
        outs = jax.eval_shape(
            lambda *xs: impl(list(xs), param_str),
            *[jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes],
        )
        return [tuple(o.shape) for o in outs]

    @staticmethod
    def init(lp, rng, in_shapes):
        impl, _ = PythonLayer._impl(lp)
        if hasattr(impl, "init"):
            return impl.init(lp, rng, in_shapes)
        return {}

    @staticmethod
    def apply(lp, params, state, inputs, ctx):
        impl, param_str = PythonLayer._impl(lp)
        if hasattr(impl, "apply"):
            return impl.apply(lp, params, state, inputs, ctx)
        return list(impl(list(inputs), param_str)), None


LAYER_IMPLS = {
    "Convolution": Convolution,
    "Deconvolution": Deconvolution,
    "Pooling": Pooling,
    "InnerProduct": InnerProduct,
    "ReLU": ReLU,
    "Sigmoid": Sigmoid,
    "TanH": TanH,
    "AbsVal": AbsVal,
    "BNLL": BNLL,
    "ELU": ELU,
    "Power": Power,
    "Exp": Exp,
    "Log": Log,
    "LRN": LRN,
    "Dropout": Dropout,
    "BatchNorm": BatchNorm,
    "Scale": Scale,
    "Bias": Bias,
    "Eltwise": Eltwise,
    "Concat": Concat,
    "Slice": Slice,
    "Split": Split,
    "Flatten": Flatten,
    "Reshape": Reshape,
    "Softmax": Softmax,
    "SoftmaxWithLoss": SoftmaxWithLoss,
    "SigmoidCrossEntropyLoss": SigmoidCrossEntropyLoss,
    "EuclideanLoss": EuclideanLoss,
    "Accuracy": Accuracy,
    "PReLU": PReLU,
    "Threshold": Threshold,
    "Tile": Tile,
    "MVN": MVN,
    "ArgMax": ArgMax,
    "Embed": Embed,
    "Reduction": Reduction,
    "Crop": Crop,
    "Silence": Silence,
    "HingeLoss": HingeLoss,
    "ContrastiveLoss": ContrastiveLoss,
    "MultinomialLogisticLoss": MultinomialLogisticLoss,
    "InfogainLoss": InfogainLoss,
    "LSTM": LSTM,
    "RNN": RNN,
    "SPP": SPP,
    "Python": PythonLayer,
    "BatchReindex": BatchReindex,
    "Parameter": Parameter,
    "Im2col": Im2col,
}
