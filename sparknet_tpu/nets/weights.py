"""WeightCollection — the framework's weight container.

The reference's ``WeightCollection`` is a Scala name->layer->Array[Float]
map used for driver-side broadcast / elementwise averaging (SURVEY.md §2;
mount empty, no file:line). Here it is simply a nested dict pytree
``{layer_name: {param_name: jnp.ndarray}}`` — which makes it directly
jit-traceable, shardable with ``jax.sharding``, and usable as the leaves
of ``jax.grad``. The elementwise algebra the reference implements by hand
(add / scale for parameter averaging) falls out of ``jax.tree_util``.
"""

from __future__ import annotations

import io
import os
from typing import Any, Dict

import jax
import numpy as np

WeightCollection = Dict[str, Dict[str, Any]]  # layer -> param name -> array


def tree_add(a: WeightCollection, b: WeightCollection) -> WeightCollection:
    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def tree_scale(a: WeightCollection, s: float) -> WeightCollection:
    return jax.tree_util.tree_map(lambda x: x * s, a)


def tree_mean(collections) -> WeightCollection:
    """Elementwise average of N weight collections (the reference's
    driver-side ``reduce(sum)/numWorkers`` step)."""
    collections = list(collections)
    out = collections[0]
    for c in collections[1:]:
        out = tree_add(out, c)
    return tree_scale(out, 1.0 / len(collections))


def num_params(w: WeightCollection) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(w))


def _flatten(w: WeightCollection) -> Dict[str, np.ndarray]:
    return {
        f"{layer}::{name}": np.asarray(arr)
        for layer, ps in w.items()
        for name, arr in ps.items()
    }


def _unflatten(z) -> WeightCollection:
    out: WeightCollection = {}
    for key in z.files:
        layer, name = key.split("::", 1)
        out.setdefault(layer, {})[name] = z[key]
    return out


def save_npz(path: str, w: WeightCollection) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    np.savez(path, **_flatten(w))


def load_npz(path: str) -> WeightCollection:
    with np.load(path) as z:
        return _unflatten(z)


def to_bytes(w: WeightCollection) -> bytes:
    """Serialize for broadcast over a wire (the reference ships weights
    via Spark broadcast; we expose the same capability for the driver)."""
    buf = io.BytesIO()
    np.savez(buf, **_flatten(w))
    return buf.getvalue()


def from_bytes(data: bytes) -> WeightCollection:
    with np.load(io.BytesIO(data)) as z:
        return _unflatten(z)
