"""XLANet: a Caffe ``NetParameter`` compiled to pure JAX functions.

This is the TPU-native replacement for the reference's ``CaffeNet``
(Scala wrapper over a native Caffe solver via JavaCPP — SURVEY.md §1-2;
reference mount empty, no file:line). Where ``CaffeNet`` owns a mutable
native net and copies weights across the JNI boundary, ``XLANet`` is a
*compiler*: it walks the layer DAG once at construction (static shape
inference, numpy-only), and exposes

- ``init(rng) -> (WeightCollection, state)`` — filler-initialised params
- ``apply(params, state, batch, train, rng) -> (blobs, new_state)``
- ``loss_and_metrics(blobs)`` — weighted loss-layer sum + metric tops

all pure, all jit/pjit/grad-compatible. The whole forward+backward is
one XLA program; there is no per-layer dispatch at run time and no
host<->device weight copying (the JNI cost center in the reference).

Layout is NHWC (see layers.py). Batches are dicts of blob name ->
array, e.g. ``{"data": (N,H,W,C) float, "label": (N,) int}``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..proto.caffe_pb import NetParameter
from .layers import (
    ApplyCtx,
    DATA_LAYER_TYPES,
    LAYER_IMPLS,
    LOSS_LAYER_TYPES,
    Shape,
)
from .weights import WeightCollection


class XLANet:
    def __init__(
        self,
        net: NetParameter,
        phase: str = "TRAIN",
        input_shapes: Optional[Dict[str, Shape]] = None,
        compute_dtype: Any = jnp.float32,
        remat: bool = False,
    ):
        """``remat``: wrap each layer's apply in ``jax.checkpoint`` so
        only layer-boundary blobs survive the forward pass — intra-layer
        intermediates (BN normalization, LRN chains, dropout masks)
        recompute during backward. The HBM-for-FLOPs trade for deep
        BN-heavy nets (ResNet-50) at large batch; dropout recompute is
        exact (masks are PRNG-keyed, not saved)."""
        self.net = net
        self.phase = phase
        self.compute_dtype = compute_dtype
        self.remat = remat
        self.layers = [
            l for l in net.layers_for_phase(phase) if l.type not in ("Silence",)
        ]
        input_shapes = dict(input_shapes or {})
        # deploy-style declared inputs (NetParameter.input / input_shape,
        # given in Caffe NCHW order -> converted to NHWC here)
        for name, shape in zip(net.inputs, net.input_shapes):
            if name not in input_shapes:
                if len(shape) == 4:
                    n, c, h, w = shape
                    input_shapes[name] = (n, h, w, c)
                else:
                    input_shapes[name] = tuple(shape)

        self.input_names: List[str] = list(net.inputs)
        self.blob_shapes: Dict[str, Shape] = dict(input_shapes)
        self._infer_shapes(input_shapes)

    # ------------------------------------------------------------------
    def _infer_shapes(self, input_shapes: Dict[str, Shape]) -> None:
        for lp in self.layers:
            if lp.type in DATA_LAYER_TYPES:
                for top in lp.top:
                    if top not in self.blob_shapes:
                        if top not in input_shapes:
                            raise ValueError(
                                f"data layer {lp.name!r} top {top!r}: shape not "
                                f"provided via input_shapes"
                            )
                        self.blob_shapes[top] = tuple(input_shapes[top])
                    if top not in self.input_names:
                        self.input_names.append(top)
                continue
            impl = LAYER_IMPLS.get(lp.type)
            if impl is None:
                raise NotImplementedError(
                    f"layer {lp.name!r}: type {lp.type!r} not implemented"
                )
            in_shapes = [self.blob_shapes[b] for b in lp.bottom]
            out_shapes = impl.infer(lp, in_shapes)
            for top, s in zip(lp.top, out_shapes):
                self.blob_shapes[top] = tuple(s)

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array) -> Tuple[WeightCollection, Dict[str, Any]]:
        params: WeightCollection = {}
        state: Dict[str, Any] = {}
        for i, lp in enumerate(self.layers):
            if lp.type in DATA_LAYER_TYPES:
                continue
            impl = LAYER_IMPLS[lp.type]
            in_shapes = [self.blob_shapes[b] for b in lp.bottom]
            p = impl.init(lp, jax.random.fold_in(rng, i), in_shapes)
            if p:
                params[lp.name] = p
            if hasattr(impl, "init_state"):
                st = impl.init_state(lp, in_shapes)
                if st:
                    state[lp.name] = st
        return params, state

    # ------------------------------------------------------------------
    def apply(
        self,
        params: WeightCollection,
        state: Dict[str, Any],
        batch: Dict[str, jax.Array],
        *,
        train: Optional[bool] = None,
        rng: Optional[jax.Array] = None,
    ) -> Tuple[Dict[str, jax.Array], Dict[str, Any]]:
        train = (self.phase == "TRAIN") if train is None else train
        blobs: Dict[str, jax.Array] = dict(batch)
        new_state: Dict[str, Any] = dict(state)
        for i, lp in enumerate(self.layers):
            if lp.type in DATA_LAYER_TYPES:
                continue
            impl = LAYER_IMPLS[lp.type]
            layer_rng = jax.random.fold_in(rng, i) if rng is not None else None
            inputs = [blobs[b] for b in lp.bottom]

            def run_layer(p, st_in, inputs_, rng_, lp=lp, impl=impl):
                ctx = ApplyCtx(
                    train=train, rng=rng_,
                    compute_dtype=self.compute_dtype,
                )
                return impl.apply(lp, p, st_in, inputs_, ctx)

            if self.remat and train:
                run_layer = jax.checkpoint(run_layer)
            outputs, st = run_layer(
                params.get(lp.name, {}), state.get(lp.name), inputs,
                layer_rng,
            )
            for top, out in zip(lp.top, outputs):
                blobs[top] = out
            if st is not None:
                new_state[lp.name] = st
        return blobs, new_state

    # ------------------------------------------------------------------
    def loss_and_metrics(
        self, blobs: Dict[str, jax.Array]
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Weighted sum of loss tops (Caffe: loss layers default weight 1,
        everything else 0) plus scalar metric tops (loss / accuracy)."""
        total = jnp.asarray(0.0, jnp.float32)
        metrics: Dict[str, jax.Array] = {}
        for lp in self.layers:
            is_loss = lp.type in LOSS_LAYER_TYPES
            for ti, top in enumerate(lp.top):
                w = lp.loss_weight[ti] if ti < len(lp.loss_weight) else (1.0 if is_loss else 0.0)
                if w:
                    total = total + w * jnp.sum(blobs[top].astype(jnp.float32))
                if is_loss or lp.type == "Accuracy":
                    metrics[top] = blobs[top]
        return total, metrics

    # ------------------------------------------------------------------
    def dummy_batch(self, dtype=jnp.float32) -> Dict[str, jax.Array]:
        """Zeros batch matching the net's declared inputs (for tracing)."""
        out = {}
        for name in self.input_names:
            s = self.blob_shapes[name]
            if name == "label":
                out[name] = jnp.zeros(s, jnp.int32)
            else:
                out[name] = jnp.zeros(s, dtype)
        return out

    def param_specs(self) -> Dict[str, Dict[str, Tuple[float, float]]]:
        """Per-param (lr_mult, decay_mult) from the prototxt ``param {}``
        entries — consumed by the solver. Spec index i maps to the
        layer's i-th blob in ITS declared order (Caffe's blob order):
        weight-then-bias for most layers, but e.g. PReLU's single blob
        is the slope — layer impls override via ``PARAM_ORDER``."""
        specs: Dict[str, Dict[str, Tuple[float, float]]] = {}
        for lp in self.layers:
            if lp.type in DATA_LAYER_TYPES:
                continue
            impl = LAYER_IMPLS.get(lp.type)
            order = getattr(impl, "PARAM_ORDER", ("weight", "bias"))
            sp: Dict[str, Tuple[float, float]] = {}
            for idx, pname in enumerate(order):
                spec = lp.params[idx] if idx < len(lp.params) else None
                sp[pname] = (
                    spec.lr_mult if spec else 1.0,
                    spec.decay_mult if spec else 1.0,
                )
            specs[lp.name] = sp
        return specs
