"""Device mesh construction — the TPU-native cluster abstraction.

The reference's "cluster" is Spark's driver + executor set, sized by
``spark-submit --num-executors`` (SURVEY.md §1-2; reference mount empty,
no file:line).  The TPU-native equivalent is a ``jax.sharding.Mesh``:
a named, n-dimensional arrangement of chips over which ``pjit`` /
``shard_map`` place computation, and over whose axes XLA collectives
(psum / all_gather / ppermute) ride the ICI links.

Axis-name conventions used across the framework:

- ``"dp"``  — data parallelism (batch axis). SparkNet's only axis.
- ``"tp"``  — tensor/model parallelism (hidden-dim sharding).
- ``"sp"``  — sequence/context parallelism (ring attention).
- ``"pp"``  — pipeline stages.

A 1-D ``{"dp": N}`` mesh reproduces the reference's topology; the other
axes are the capabilities the reference never had but a TPU pod gives
for free.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
TP_AXIS = "tp"
SP_AXIS = "sp"
PP_AXIS = "pp"


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh.

    ``axes`` maps axis name -> size, in major-to-minor order; one axis may
    be ``-1`` ("use all remaining devices").  Default: all devices on a
    single ``"dp"`` axis — the reference's pure-data-parallel topology.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes) if axes else {DP_AXIS: n}
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if known == 0 or n % known:
            raise ValueError(f"cannot infer -1 axis: {n} devices / {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"mesh {dict(zip(axes, sizes))} needs {total} devices, have {n}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DP_AXIS) -> NamedSharding:
    """Shard the leading (batch) axis over ``axis``."""
    return NamedSharding(mesh, P(axis))


def shard_batch(batch, mesh: Mesh, axis: str = DP_AXIS):
    """Place a host batch onto the mesh, batch-axis sharded (the
    reference's RDD-partition -> executor placement, but via ICI-aware
    device_put instead of TCP shuffle)."""
    s = batch_sharding(mesh, axis)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), batch)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree across the mesh (the reference's driver
    ``broadcast(WeightCollection)``, minus the serialization)."""
    s = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)
