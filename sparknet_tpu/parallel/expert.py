"""Expert parallelism for the BERT-MoE family.

No reference counterpart (SURVEY.md §2: data parallelism only; EP is a
task-spec obligation). The expert stacks of every MoE layer shard on
their leading (expert) dim over an ``"ep"`` mesh axis; tokens stay
replicated across ``ep`` (each rank routes the full local batch) and
``lax.all_to_all`` inside :func:`~sparknet_tpu.parallel.moe.moe_ffn`
carries each expert's token groups to its owner.  Composes with ``dp``:
batch rows shard over ``dp``, expert weights over ``ep``, and gradient
reduction follows each leaf's replication — dp for expert shards,
dp×ep for everything else.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..solver.caffe_solver import make_update_fn, mults_for_params
from . import comm
from .moe import moe_pspecs


def bert_moe_pspecs(model, ep_axis: str = "ep") -> Dict[str, Dict[str, P]]:
    """PartitionSpec tree for a MoE ``BertMLM``: expert stacks sharded
    on ``ep``, all other params replicated."""
    rep = P()
    moe = moe_pspecs(ep_axis)
    specs: Dict[str, Dict[str, P]] = {
        "embeddings": {
            "word": rep, "position": rep, "token_type": rep,
            "ln_scale": rep, "ln_bias": rep,
        },
        "mlm_head": {
            "dense_w": rep, "dense_b": rep, "ln_scale": rep,
            "ln_bias": rep, "output_bias": rep,
        },
    }
    for li in range(model.cfg.num_layers):
        specs[f"layer_{li:02d}"] = {
            "q_w": rep, "q_b": rep, "k_w": rep, "k_b": rep,
            "v_w": rep, "v_b": rep, "out_w": rep, "out_b": rep,
            "attn_ln_scale": rep, "attn_ln_bias": rep,
            "ffn_ln_scale": rep, "ffn_ln_bias": rep,
            **moe,
        }
    return specs


def make_ep_train_step(
    model,
    sp,
    mesh,
    dp_axis: Optional[str] = "dp",
    ep_axis: str = "ep",
):
    """Jitted ``step(params, opt_state, batch, it, rng)`` over a
    dp×ep mesh with token-level MLM loss (+ router aux loss).

    ``model`` must be built with ``ep_axis=ep_axis`` and a MoE config
    whose expert count divides the mesh's ep size. ``batch`` is the
    token-level layout of
    :func:`sparknet_tpu.data.text.mlm_feed_tokens`.
    """
    cfg = model.cfg
    nep = mesh.shape[ep_axis]
    if cfg.moe_num_experts <= 0:
        raise ValueError("make_ep_train_step needs a MoE config")
    if cfg.moe_num_experts % nep:
        raise ValueError(
            f"ep={nep} must divide moe_num_experts ({cfg.moe_num_experts})"
        )
    if model.ep_axis != ep_axis:
        raise ValueError(
            f"model.ep_axis ({model.ep_axis!r}) != ep_axis ({ep_axis!r}): "
            "build the model with BertMLM(..., ep_axis=ep_axis)"
        )
    pspecs = bert_moe_pspecs(model, ep_axis)
    ndp = mesh.shape[dp_axis] if dp_axis else 1

    def local_step(params, opt_state, batch, it, rng):
        # dropout: identical across ep ranks (tokens are replicated
        # there — divergent masks would desynchronise routing inputs),
        # distinct across dp shards
        if dp_axis:
            rng = jax.random.fold_in(rng, lax.axis_index(dp_axis))

        def loss_fn(p):
            nll, w, corr, aux = model.token_loss_sums_with_aux(
                p, {}, batch, train=True, rng=rng
            )
            w_tot = lax.psum(w, dp_axis) if dp_axis else w
            # aux is already pmean'd over ep inside moe_ffn; /ndp makes
            # the dp-psum'd gradients carry its dp-mean
            loss_local = (
                nll / jnp.maximum(w_tot, 1.0)
                + cfg.moe_aux_weight * aux / ndp
            )
            return loss_local, (nll, w_tot, corr, aux)

        grads, (nll, w_tot, corr, aux) = jax.grad(loss_fn, has_aux=True)(params)
        # tokens are REPLICATED over ep: every ep rank computes the same
        # local loss, and the all_to_all transpose accumulates one
        # cotangent copy per rank into each expert shard — so expert
        # leaves come back scaled by nep; normalise them
        grads = {
            layer: {
                name: g / nep if ep_axis in pspecs[layer][name] else g
                for name, g in entry.items()
            }
            for layer, entry in grads.items()
        }
        if dp_axis:
            # replicated leaves see identical grads on every ep rank (no
            # ep reduction needed); every leaf still reduces over dp
            grads = jax.tree_util.tree_map(
                lambda g: lax.psum(g, dp_axis), grads
            )
        lr_m, dec_m = mults_for_params(params, model.param_specs())
        update = make_update_fn(sp, lr_m, dec_m)
        params, opt_state = update(params, grads, opt_state, it)
        nll_tot = lax.psum(nll, dp_axis) if dp_axis else nll
        corr_tot = lax.psum(corr, dp_axis) if dp_axis else corr
        aux_mean = lax.pmean(aux, dp_axis) if dp_axis else aux
        denom = jnp.maximum(w_tot, 1.0)
        return params, opt_state, {
            "loss": nll_tot / denom + cfg.moe_aux_weight * aux_mean,
            "mlm_acc": corr_tot / denom,
        }

    rows = P(dp_axis)  # replicated over ep
    batch_spec = {
        "input_ids": rows,
        "token_type_ids": rows,
        "attention_mask": rows,
        "position_ids": rows,
        "mlm_labels": rows,
        "mlm_weights": rows,
    }
    compiled = {}

    def stepper(params, opt_state, batch, it, rng):
        key = tuple(sorted(opt_state))
        if key not in compiled:
            ospec = {k: pspecs for k in opt_state}
            compiled[key] = comm.jit_manual(
                comm.shard_map(
                    local_step,
                    mesh=mesh,
                    in_specs=(pspecs, ospec, batch_spec, P(), P()),
                    out_specs=(pspecs, ospec, P()),
                ),
                donate_argnums=(0, 1),
            )
        return compiled[key](params, opt_state, batch, it, rng)

    return stepper
