"""Multi-host bring-up: the framework's deployment layer.

The reference deploys as a Spark application: a driver JVM schedules
executor JVMs across an EC2/Mesos cluster, each owning one GPU, with
Spark's TCP fabric carrying weights (SURVEY.md §1 "Deployment", §2
"EC2/cluster scripts"; mount empty, no file:line).  The TPU-native
equivalent is JAX's multi-controller model: one identical Python
process per host, ``jax.distributed.initialize`` wiring them into a
single global device mesh, and ICI/DCN carrying the collectives that
replace Spark's shuffle.  There is no driver — every process runs the
same SPMD program; process 0 merely owns logging and snapshots.

Launch (one command per host, see docs/MULTIHOST.md):

    SPARKNET_COORDINATOR=host0:1234 SPARKNET_NUM_PROCESSES=4 \\
    SPARKNET_PROCESS_ID=<i> python -m sparknet_tpu.apps.imagenet_app \\
        --multihost ...

Data: each host feeds only its shard (``host_shard``), and
``jax.make_array_from_process_local_data`` assembles the host-local
rows into one globally-sharded batch — the same global-batch semantics
as the reference's RDD partitioning, minus the driver round-trip.
"""

from __future__ import annotations

import os
import socket
import struct
import sys
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

from ..telemetry import aggregate as _aggregate
from ..telemetry import timeline as _timeline
from ..telemetry import trace as _trace

# Exit code for "a peer host stopped responding": the launcher (or
# scripts/launch_multihost.sh + --auto-resume) treats any non-zero exit
# as restart-the-job. Distinct from ordinary crashes to aid triage.
EXIT_PEER_FAILURE = 43

# Telemetry piggyback (telemetry/aggregate.py): after an acked ping a
# worker may send one *stats frame* — this sentinel int32, then
# ``!ii`` (rank, payload length), then the JSON payload — acked in the
# same 3-byte slot.  INT32_MIN can never collide with a ping (pid >= 0)
# or a graceful bye (-1 - pid, pids far below 2**31 - 1).
_STATS_TAG = -(2 ** 31)

_heartbeat: Optional["_Heartbeat"] = None


def _recv_exactly(conn: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or return what arrived before EOF.
    TCP is a byte stream — a single recv may legally return a fragment
    of a ping/ack, which must not be misread as peer-closed."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return buf
        buf += chunk
    return buf


class _Heartbeat:
    """Out-of-band liveness fabric (SURVEY.md §5 failure handling).

    Spark detects a dead executor via its driver<->executor heartbeats
    and re-runs the lost partition. A JAX SPMD job has no driver and a
    dead peer leaves every other host blocked *inside* a collective —
    no exception, no timeout — so detection must live outside the
    compute path entirely. This is a star of plain TCP pings through
    process 0 (coordinator port + 1): workers ping every ``interval``
    seconds; process 0 acks and tracks last-seen per worker. Whoever
    observes silence longer than ``timeout`` prints a diagnostic and
    hard-exits ``EXIT_PEER_FAILURE`` (``os._exit`` — the main thread
    may be stuck in a collective and can't be unwound). A worker death
    fails process 0; process 0's death fails every worker; a worker
    noticing its own isolation fails transitively through 0.

    Non-goal (by design): the star cannot see a partition that cuts
    two non-zero workers off from *each other* while both still reach
    process 0.  That case only matters if workers talked directly —
    they don't; all collectives go through the global mesh, and a mesh
    partition wedges a collective, which stalls pings to/through 0 and
    is detected.  Pairwise partition detection is therefore explicitly
    out of scope; the fabric promises fail-fast on dead/isolated-from-0
    hosts only.

    Clean shutdown: ``close()`` on process 0 broadcasts a 3-byte
    ``end`` to every connected worker before closing the server, so a
    worker with tail work (local-mode τ tail, slow snapshot write)
    disarms its watchdog instead of misreading the silence as process
    0 dying and exiting ``EXIT_PEER_FAILURE``.

    Recovery is restart-level, exactly like the reference's driver
    rescheduling a lost executor's work: relaunch the job and
    ``--auto-resume`` resumes from the newest collective snapshot.
    """

    def __init__(self, host: str, port: int, pid: int, nprocs: int,
                 interval: float, timeout: float):
        self.host, self.port = host, port
        self.pid, self.nprocs = pid, nprocs
        self.interval, self.timeout = interval, timeout
        self._stop = threading.Event()
        self._threads = []
        self._server = None
        self._disarmed = False  # set when process 0 announced clean end
        self._ending = False  # process 0: close() underway, answer "end"
        self._silent = False  # chaos multihost.peer_silence engaged
        # telemetry piggyback: rank 0 merges stats frames into the
        # cluster aggregator; workers publish one frame per acked ping
        # (SPARKNET_CLUSTER_TELEMETRY=0 turns the piggyback off)
        self._publisher = None
        if pid == 0:
            self._last_seen = {}
            self._expected = set(range(1, nprocs))
            self._conns = set()  # live worker conns, for the end broadcast
            self._lock = threading.Lock()
            if _aggregate.enabled():
                _aggregate.init_aggregator()
            self._server = socket.create_server(
                ("", port), backlog=nprocs, reuse_port=False
            )
            self._spawn(self._accept_loop)
            self._spawn(self._monitor_loop)
        else:
            if _aggregate.enabled():
                self._publisher = _aggregate.RankPublisher(pid)
            self._spawn(self._client_loop)

    def _spawn(self, fn):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        self._threads.append(t)

    def _die(self, why: str) -> None:
        if self._stop.is_set() or self._disarmed:
            return
        from ..supervise import records

        it = records.last_completed_iteration()
        progress = (
            f" (last completed iteration: {it})" if it is not None else ""
        )
        print(
            f"[sparknet multihost] process {self.pid}: {why}{progress} — "
            f"exiting {EXIT_PEER_FAILURE} so the launcher can restart the "
            f"job (--auto-resume recovers from the newest snapshot)",
            file=sys.stderr, flush=True,
        )
        # supervised runs get a machine-readable record (who died, why,
        # progress) for attribution; a no-op otherwise. Never raises —
        # this is a dying path.
        records.write_failure_record(
            process_id=self.pid, kind="peer_failure", reason=why,
            exit_code=EXIT_PEER_FAILURE,
        )
        os._exit(EXIT_PEER_FAILURE)

    # -- process 0: server + monitor -----------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # closed
            self._spawn(lambda c=conn: self._serve_one(c))

    def _serve_one(self, conn: socket.socket):
        with self._lock:
            self._conns.add(conn)
        try:
            with conn:
                conn.settimeout(self.timeout)
                while not self._stop.is_set():
                    try:
                        raw = _recv_exactly(conn, 4)
                        if len(raw) < 4:
                            return  # peer closed; monitor ages it out
                        (peer,) = struct.unpack("!i", raw)
                        if peer == _STATS_TAG:
                            # telemetry piggyback: bounded JSON payload
                            # merged into the cluster aggregator; any
                            # framing violation drops the connection
                            # (liveness is the pings' job, not this)
                            hdr = _recv_exactly(conn, 8)
                            if len(hdr) < 8:
                                return
                            rank, length = struct.unpack("!ii", hdr)
                            if not 0 <= length <= _aggregate.MAX_PAYLOAD_BYTES:
                                return
                            payload = _recv_exactly(conn, length)
                            if len(payload) < length:
                                return
                            _aggregate.ingest(payload, fallback_rank=rank)
                            conn.sendall(
                                b"end" if self._ending else b"ok\n"
                            )
                            continue
                        if peer < 0:  # graceful bye: stop expecting -1-peer
                            with self._lock:
                                self._expected.discard(-1 - peer)
                                self._last_seen.pop(-1 - peer, None)
                            conn.sendall(b"ok\n")
                            return
                        with self._lock:
                            self._last_seen[peer] = time.monotonic()
                            # rejoin grace: a worker relaunched by a
                            # per-host supervisor re-enters the fabric on
                            # its first ping even after its predecessor
                            # said a graceful bye — otherwise the new
                            # incarnation's death would go unmonitored
                            if not self._ending:
                                self._expected.add(peer)
                        # during close()'s linger, every ping is answered
                        # "end" so workers that were mid-reconnect when the
                        # broadcast went out still learn of the clean finish
                        conn.sendall(b"end" if self._ending else b"ok\n")
                    except socket.timeout:
                        return
                    except OSError:
                        return
        finally:
            with self._lock:
                self._conns.discard(conn)

    def _join_grace(self) -> float:
        """How long a worker gets to make first contact.  Default covers
        jax.distributed.initialize stragglers; supervised relaunches can
        widen it (SPARKNET_HEARTBEAT_JOIN_GRACE) when children re-enter
        staggered — e.g. restoring a big snapshot before the first ping."""
        raw = os.environ.get("SPARKNET_HEARTBEAT_JOIN_GRACE", "")
        try:
            v = float(raw) if raw else 0.0
        except ValueError:
            v = 0.0
        return v if v > 0 else max(3 * self.timeout, 30.0)

    def _monitor_loop(self):
        # workers must check in once within the join grace (they connect
        # right after jax.distributed.initialize returns, which already
        # required every process to be alive)
        grace_until = time.monotonic() + self._join_grace()
        while not self._stop.is_set():
            time.sleep(self.interval)
            # fold rank 0's own telemetry into the cluster aggregate at
            # the same cadence the workers publish at (no-op when the
            # piggyback is disabled)
            _aggregate.self_ingest()
            now = time.monotonic()
            with self._lock:
                seen = dict(self._last_seen)
                expected = set(self._expected)
            missing = expected - set(seen)
            if missing and now > grace_until:
                self._die(f"worker(s) {sorted(missing)} never joined the "
                          f"heartbeat fabric")
            stale = [
                p for p, t in seen.items()
                if p in expected and now - t > self.timeout
            ]
            if stale:
                self._die(f"no heartbeat from worker(s) {sorted(stale)} "
                          f"for {self.timeout:.0f}s (host dead or wedged)")

    # -- workers: ping/ack client --------------------------------------

    def _client_loop(self):
        # one unified freshness clock: transient failures (including
        # process 0 finishing and closing the server a beat before this
        # worker stops) retry-with-reconnect until `timeout` elapses
        # since the last good ack; only persistent silence kills
        last_ok = time.monotonic()
        joined = False
        conn = None
        ping = struct.pack("!i", self.pid)
        from .. import chaos as _chaos

        plan = _chaos.get_plan()
        tick = 0
        while not self._stop.is_set():
            if plan is not None and not self._silent and plan.fires(
                "multihost.peer_silence", worker=self.pid, tick=tick
            ):
                # simulate a dead/isolated peer: stop pinging but stay
                # alive, and disarm the local watchdog so detection is
                # process 0's job — the fabric must fail the whole job
                # (EXIT_PEER_FAILURE) and the launcher's relaunch +
                # --auto-resume is the restart-level recovery
                self._silent = True
                self._disarmed = True
            tick += 1
            if self._silent:
                self._stop.wait(self.interval)
                continue
            if conn is None:
                try:
                    conn = socket.create_connection(
                        (self.host, self.port),
                        timeout=max(self.interval, 1.0),
                    )
                    conn.settimeout(self.timeout)
                except OSError:
                    conn = None
            if conn is not None:
                try:
                    # one ping, then (telemetry piggyback) at most one
                    # stats frame — each acked in the same 3-byte slot,
                    # so the end-broadcast semantics hold for both
                    msgs = [ping]
                    if self._publisher is not None:
                        try:
                            payload = self._publisher.payload()
                        except Exception:
                            payload = None  # stats must not kill liveness
                        if payload:
                            msgs.append(
                                struct.pack(
                                    "!iii", _STATS_TAG, self.pid,
                                    len(payload),
                                ) + payload
                            )
                    ack = b""
                    for msg in msgs:
                        conn.sendall(msg)
                        ack = _recv_exactly(conn, 3)
                        if ack != b"ok\n":
                            break
                    if ack == b"end":
                        # process 0 finished cleanly: disarm the
                        # watchdog so tail work here (τ tail, slow
                        # snapshot write) is not misread as 0 dying;
                        # answer with the graceful bye so 0's linger
                        # can finish as soon as everyone has heard
                        self._disarmed = True
                        try:
                            conn.sendall(struct.pack("!i", -1 - self.pid))
                            _recv_exactly(conn, 3)
                        except OSError:
                            pass
                        try:
                            conn.close()
                        except OSError:
                            pass
                        return
                    if ack == b"ok\n":
                        last_ok = time.monotonic()
                        joined = True
                    else:
                        # short read / unknown token = broken connection
                        raise OSError("server closed")
                except OSError:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    conn = None
            limit = self.timeout if joined else self._join_grace()
            if time.monotonic() - last_ok > limit:
                self._die(
                    f"no heartbeat ack from process 0 for {limit:.0f}s "
                    f"(host dead or wedged)"
                )
            self._stop.wait(self.interval)
        # graceful leave: tell process 0 to stop expecting this worker
        if conn is not None:
            try:
                conn.sendall(struct.pack("!i", -1 - self.pid))
                # _recv_exactly, same as the end-ack path: the 3-byte
                # bye ack can legally arrive fragmented, and a raw
                # recv(3) short-read would be misread as server-closed
                _recv_exactly(conn, 3)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        if self.pid == 0 and self._server is not None:
            # announce clean end so workers with tail work disarm their
            # watchdog instead of exiting EXIT_PEER_FAILURE (the "end"
            # rides the 3-byte ack slot of each worker's next ping)
            self._ending = True
            with self._lock:
                conns = list(self._conns)
            for c in conns:
                try:
                    c.sendall(b"end")
                except OSError:
                    pass
            # linger one full ping period so a worker that was
            # mid-reconnect when the broadcast went out can reconnect,
            # ping, and get "end" too — otherwise it would misread the
            # vanished server as process 0 dying. Must cover at least
            # one interval (workers ping that often); ends early once
            # every expected worker has said its graceful bye, which is
            # the normal case, so the full wait is only paid for
            # workers that are genuinely gone.
            deadline = time.monotonic() + self.interval + 0.5
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._expected:
                        break
                time.sleep(0.05)
        self._stop.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)  # let a worker deliver its bye


def start_heartbeat(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    interval: Optional[float] = None,
    timeout: Optional[float] = None,
) -> Optional[_Heartbeat]:
    """Start the liveness fabric (idempotent). ``SPARKNET_HEARTBEAT=0``
    disables; ``SPARKNET_HEARTBEAT_TIMEOUT`` (seconds, default 15)
    tunes how quickly a dead host fails the job;
    ``SPARKNET_HEARTBEAT_PORT`` overrides coordinator-port+1."""
    global _heartbeat
    if _heartbeat is not None or num_processes <= 1:
        return _heartbeat
    if os.environ.get("SPARKNET_HEARTBEAT", "1") in ("0", ""):
        return None
    host, _, port_s = coordinator_address.rpartition(":")
    if "SPARKNET_HEARTBEAT_PORT" in os.environ:
        port = int(os.environ["SPARKNET_HEARTBEAT_PORT"])
    else:
        try:
            port = int(port_s) + 1
        except ValueError:
            raise ValueError(
                f"cannot derive a heartbeat port from coordinator "
                f"address {coordinator_address!r} (expected host:port); "
                f"set SPARKNET_HEARTBEAT_PORT explicitly or "
                f"SPARKNET_HEARTBEAT=0 to disable the liveness fabric"
            ) from None
    timeout = timeout or float(
        os.environ.get("SPARKNET_HEARTBEAT_TIMEOUT", "15")
    )
    interval = interval or max(0.2, timeout / 5.0)
    try:
        _heartbeat = _Heartbeat(
            host or "127.0.0.1", port, process_id, num_processes,
            interval, timeout,
        )
    except OSError as e:
        raise OSError(
            f"heartbeat fabric could not bind port {port} "
            f"(coordinator port + 1 may collide with another listener): "
            f"{e}; set SPARKNET_HEARTBEAT_PORT to a free port or "
            f"SPARKNET_HEARTBEAT=0 to disable"
        ) from e
    return _heartbeat


def stop_heartbeat() -> None:
    global _heartbeat
    if _heartbeat is not None:
        _heartbeat.close()
        _heartbeat = None


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host cluster; returns True if distributed mode is
    active.  Arguments fall back to ``SPARKNET_COORDINATOR`` /
    ``SPARKNET_NUM_PROCESSES`` / ``SPARKNET_PROCESS_ID`` env vars (and
    then to JAX's own cluster auto-detection).  A single-process launch
    (no coordinator configured) is a no-op.

    Once the cluster is up, a peer-liveness heartbeat fabric starts
    (see :class:`_Heartbeat`): a dead host fails the whole job within
    ``SPARKNET_HEARTBEAT_TIMEOUT`` seconds instead of leaving every
    other host blocked in a collective."""
    coordinator_address = coordinator_address or os.environ.get(
        "SPARKNET_COORDINATOR"
    )
    if num_processes is None and "SPARKNET_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["SPARKNET_NUM_PROCESSES"])
    if process_id is None and "SPARKNET_PROCESS_ID" in os.environ:
        process_id = int(os.environ["SPARKNET_PROCESS_ID"])
    if coordinator_address is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    start_heartbeat(
        coordinator_address, jax.process_count(), jax.process_index()
    )
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """Process 0 owns logging and snapshot writes (the reference's
    driver-side responsibilities)."""
    return jax.process_index() == 0


def host_shard(ds):
    """This host's partitions of a ShardedDataset (deterministic
    ``i % num_hosts`` assignment — rdd.py's sharding arithmetic)."""
    if jax.process_count() == 1:
        return ds
    return ds.shard(jax.process_index(), jax.process_count())


def put_global(batch: Any, sharding: jax.sharding.NamedSharding) -> Any:
    """Assemble per-host local rows into one globally-sharded array
    pytree.  Each process passes its own rows; process order defines
    global order along the sharded axis.

    This is the host-path cross-host rendezvous (every process must
    arrive before the global array exists), so the active timeline
    attributes it as ``multihost_sync`` — nested inside the solver's
    ``device_put`` phase, which then reports only its exclusive H2D
    time — and the tracer records a span per call.  The comm layer's
    byte accounting (``comm_bytes{path=host_assembly}``) counts this
    process's contribution, so the registry answers "barrier wait vs
    bytes moved" next to the ``grad_allreduce`` estimates."""
    with _trace.span("multihost.put_global", cat="multihost"), \
            _timeline.current_phase("multihost_sync"):
        nbytes = 0

        def assemble(x):
            nonlocal nbytes
            x = np.asarray(x)
            nbytes += x.nbytes
            return jax.make_array_from_process_local_data(sharding, x)

        out = jax.tree_util.tree_map(assemble, batch)
        from ..telemetry import REGISTRY

        REGISTRY.counter("comm_bytes", path="host_assembly").inc(nbytes)
        return out
