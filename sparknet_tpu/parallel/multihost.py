"""Multi-host bring-up: the framework's deployment layer.

The reference deploys as a Spark application: a driver JVM schedules
executor JVMs across an EC2/Mesos cluster, each owning one GPU, with
Spark's TCP fabric carrying weights (SURVEY.md §1 "Deployment", §2
"EC2/cluster scripts"; mount empty, no file:line).  The TPU-native
equivalent is JAX's multi-controller model: one identical Python
process per host, ``jax.distributed.initialize`` wiring them into a
single global device mesh, and ICI/DCN carrying the collectives that
replace Spark's shuffle.  There is no driver — every process runs the
same SPMD program; process 0 merely owns logging and snapshots.

Launch (one command per host, see docs/MULTIHOST.md):

    SPARKNET_COORDINATOR=host0:1234 SPARKNET_NUM_PROCESSES=4 \\
    SPARKNET_PROCESS_ID=<i> python -m sparknet_tpu.apps.imagenet_app \\
        --multihost ...

Data: each host feeds only its shard (``host_shard``), and
``jax.make_array_from_process_local_data`` assembles the host-local
rows into one globally-sharded batch — the same global-batch semantics
as the reference's RDD partitioning, minus the driver round-trip.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host cluster; returns True if distributed mode is
    active.  Arguments fall back to ``SPARKNET_COORDINATOR`` /
    ``SPARKNET_NUM_PROCESSES`` / ``SPARKNET_PROCESS_ID`` env vars (and
    then to JAX's own cluster auto-detection).  A single-process launch
    (no coordinator configured) is a no-op."""
    coordinator_address = coordinator_address or os.environ.get(
        "SPARKNET_COORDINATOR"
    )
    if num_processes is None and "SPARKNET_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["SPARKNET_NUM_PROCESSES"])
    if process_id is None and "SPARKNET_PROCESS_ID" in os.environ:
        process_id = int(os.environ["SPARKNET_PROCESS_ID"])
    if coordinator_address is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """Process 0 owns logging and snapshot writes (the reference's
    driver-side responsibilities)."""
    return jax.process_index() == 0


def host_shard(ds):
    """This host's partitions of a ShardedDataset (deterministic
    ``i % num_hosts`` assignment — rdd.py's sharding arithmetic)."""
    if jax.process_count() == 1:
        return ds
    return ds.shard(jax.process_index(), jax.process_count())


def put_global(batch: Any, sharding: jax.sharding.NamedSharding) -> Any:
    """Assemble per-host local rows into one globally-sharded array
    pytree.  Each process passes its own rows; process order defines
    global order along the sharded axis."""
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        batch,
    )
