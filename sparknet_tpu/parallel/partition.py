"""Unified sharding compile path: one mesh + regex partition rules.

The strategy zoo this module replaces grew one hand-built ``shard_map``
step builder per parallelism flavour (dp/tp/pp/sp/ep/local-SGD), each
with its own manual collectives.  Following the declarative
dataflow-partitioning design of the TensorFlow paper (PAPERS.md,
arXiv:1605.08695) and the mesh/``NamedSharding`` idiom in SNIPPETS.md
[1]/[3], the unified path expresses a parallel layout as DATA, not
code:

- **Layout** = a mesh shape (``dp``/``tp``/``pp``/``ep`` axes over
  :func:`~sparknet_tpu.parallel.mesh.make_mesh`) plus an ORDERED table
  of regex rules mapping param-tree paths -> ``PartitionSpec``.  First
  match wins; an unmatched leaf gets the explicit replicated fallback;
  ``validate="strict"`` rejects specs whose mesh axes do not divide
  the dims they shard.
- The rule table compiles into per-leaf :class:`NamedSharding` trees
  for params, optimizer slots and the batch, and
  :func:`make_sharded_train_step` jits the ONE generic train step
  (:func:`~sparknet_tpu.solver.trainer.make_train_step`) with
  ``in_shardings``/``out_shardings`` from those trees and
  ``donate_argnums`` on weights + opt state.  The XLA GSPMD
  partitioner inserts (and overlaps) every collective — no
  ``shard_map``, no hand-written ``pmean``/``all_gather``.

Any dp×tp×ep combination is a table entry, not a new trainer: rules
may name axes the current layout does not have (they resolve to
replicated on that dim), so one ruleset serves ``dp=8``, ``dp=2,tp=4``
and ``dp=2,ep=4`` alike.  Numerics: GSPMD partitioning is
semantics-preserving — a sharded step matches the single-device step
to reduction-order (ulp-level) accuracy, and is BITWISE identical to
any hand-built jit with the same shardings (tests/test_partition.py
pins both).

Serialization (``spec_to_str``/``layout_to_json``) lets snapshots
carry per-leaf specs for relayout-on-resume, and
:func:`layout_fingerprint` extends the serve tier's
``net_fingerprint`` so compile caches never alias across layouts.
See docs/PARALLELISM.md.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import warnings
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DP_AXIS, make_mesh

# Mesh axis vocabulary of the framework (mesh.py conventions).  Layouts
# may use any subset; rules may reference any of them and degrade to
# replicated when the layout lacks the axis.
AXES = ("dp", "tp", "pp", "sp", "ep")


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    """One partition rule: ``re.search(pattern, leaf_path)`` against
    the ``/``-joined tree path; ``spec`` entries are mesh-axis names,
    ``None``, or tuples of axis names — exactly ``PartitionSpec``'s
    grammar.  ``align`` anchors a spec shorter than the leaf's rank:
    ``"leading"`` pads ``None`` on the right (PartitionSpec's own
    convention), ``"trailing"`` pads on the left — so one
    ``("tp",) @ trailing`` rule shards the output dim of both a 2-D
    InnerProduct weight and a 4-D conv filter."""

    pattern: str
    spec: Tuple[Any, ...]
    align: str = "leading"

    def __post_init__(self):
        re.compile(self.pattern)  # fail at table-build time, not match time
        if self.align not in ("leading", "trailing"):
            raise ValueError(
                f"rule {self.pattern!r}: align must be leading|trailing, "
                f"got {self.align!r}"
            )
        if not isinstance(self.spec, tuple):
            object.__setattr__(self, "spec", tuple(self.spec))


# Named rule tables.  "tp" covers the prototxt/XLANet families (every
# learned blob is output-dim-trailing); "bert" covers the BertMLM
# family by parameter name (Megatron column/row split + expert stacks).
RULESETS: Dict[str, Tuple[Rule, ...]] = {
    "replicated": (),
    "tp": (
        Rule(r"(^|/)weight$", ("tp",), align="trailing"),
        Rule(r"(^|/)bias$", ("tp",), align="trailing"),
    ),
    "bert": (
        Rule(r"/(q_w|k_w|v_w|ffn_in_w)$", (None, "tp")),
        Rule(r"/(q_b|k_b|v_b|ffn_in_b)$", ("tp",)),
        Rule(r"/(out_w|ffn_out_w)$", ("tp", None)),
        Rule(r"/(w_in|b_in|w_out|b_out)$", ("ep",)),
    ),
}


@dataclasses.dataclass(frozen=True)
class Layout:
    """A parallel layout: ordered mesh axes + the partition rule table.

    ``axes``: ``((name, size), ...)`` major-to-minor; one size may be
    ``-1`` ("all remaining devices", resolved at mesh build).
    ``rules``: ordered :class:`Rule` tuple (first match wins) or a
    :data:`RULESETS` name.  ``validate``: ``"strict"`` (reject specs
    that don't divide the dims they shard) or ``"off"``."""

    axes: Tuple[Tuple[str, int], ...] = ((DP_AXIS, -1),)
    rules: Tuple[Rule, ...] = ()
    name: str = "custom"
    validate: str = "strict"
    batch_axis: str = DP_AXIS

    def __post_init__(self):
        if isinstance(self.rules, str):
            object.__setattr__(self, "rules", RULESETS[self.rules])
        object.__setattr__(
            self, "axes", tuple((str(a), int(s)) for a, s in self.axes)
        )
        if self.validate not in ("strict", "off"):
            raise ValueError(
                f"validate must be strict|off, got {self.validate!r}"
            )
        names = [a for a, _ in self.axes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate mesh axes in {names}")

    def axes_dict(self) -> Dict[str, int]:
        return dict(self.axes)

    def mesh(self, devices=None) -> Mesh:
        axes = self.axes_dict()
        sizes = list(axes.values())
        if devices is None and -1 not in sizes:
            need = 1
            for s in sizes:
                need *= s
            devices = jax.devices()[:need]  # fully-sized layout: take
            # the first N devices rather than demanding an exact count
        return make_mesh(axes, devices)


def parse_axes(spec: str) -> Dict[str, int]:
    """``"dp=2,tp=4"`` -> ``{"dp": 2, "tp": 4}`` (one size may be -1)."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"layout axis {part!r}: want name=size (e.g. dp=2,tp=4)"
            )
        k, _, v = part.partition("=")
        try:
            out[k.strip()] = int(v)
        except ValueError:
            raise ValueError(f"layout axis {part!r}: size must be an int")
    if not out:
        raise ValueError(f"empty layout spec {spec!r}")
    return out


def parse_layout(
    axes: str, rules="replicated", name: Optional[str] = None, **kw
) -> Layout:
    """Build a :class:`Layout` from the apps' flag syntax:
    ``parse_layout("dp=2,tp=2", rules="tp")``."""
    ax = tuple(parse_axes(axes).items())
    rules_t = RULESETS[rules] if isinstance(rules, str) else tuple(rules)
    return Layout(
        axes=ax,
        rules=rules_t,
        name=name or (rules if isinstance(rules, str) else "custom"),
        **kw,
    )


# --------------------------------------------------------------------------
# path naming + rule matching
# --------------------------------------------------------------------------

def _path_str(path) -> str:
    """``/``-joined tree path: dict keys and sequence indices, without
    jax.keystr's bracket noise — ``conv1/weight``, ``m/layer_00/q_w``."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:  # pragma: no cover - future key types
            parts.append(str(p))
    return "/".join(parts)


def tree_paths(tree) -> Tuple[Tuple[str, Any], ...]:
    """Flattened ``(path_str, leaf)`` pairs in tree order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return tuple((_path_str(path), leaf) for path, leaf in flat)


def _entry_axes(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(str(a) for a in entry)
    return (str(entry),)


def _filter_entry(entry, mesh_axes) -> Any:
    """Drop axis names the mesh does not have (rule written for a
    bigger layout) — the dim degrades to replicated there."""
    axes = tuple(a for a in _entry_axes(entry) if a in mesh_axes)
    if not axes:
        return None
    return axes[0] if len(axes) == 1 else axes


def match_spec(
    rules: Sequence[Rule],
    path: str,
    leaf,
    mesh: Optional[Mesh] = None,
) -> P:
    """First-match-wins spec for one leaf; replicated fallback.  Scalar
    (0-d / single-element) leaves are never partitioned (SNIPPETS.md
    [1] discipline).  When ``mesh`` is given, rule axes the mesh lacks
    resolve to ``None``."""
    ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))
    size = getattr(leaf, "size", None)
    if ndim == 0 or size == 1:
        return P()
    mesh_axes = tuple(mesh.shape) if mesh is not None else None
    for rule in rules:
        if re.search(rule.pattern, path) is None:
            continue
        spec = tuple(rule.spec)
        if len(spec) > ndim:
            raise ValueError(
                f"partition rule {rule.pattern!r} has {len(spec)} spec "
                f"entries but {path!r} is rank {ndim}"
            )
        pad = (None,) * (ndim - len(spec))
        spec = pad + spec if rule.align == "trailing" else spec + pad
        if mesh_axes is not None:
            spec = tuple(_filter_entry(e, mesh_axes) for e in spec)
        # trim trailing Nones: P(None, "tp") == P(None, "tp", None)
        while spec and spec[-1] is None:
            spec = spec[:-1]
        return P(*spec)
    return P()  # explicit replicated fallback


def validate_spec(path: str, leaf, spec: P, mesh: Mesh) -> None:
    """Strict mode: every sharded dim must be divisible by the product
    of its mesh axes (XLA would pad silently otherwise, which changes
    memory math and hides layout bugs)."""
    for dim, entry in enumerate(spec):
        factor = 1
        for axis in _entry_axes(entry):
            if axis not in mesh.shape:
                raise ValueError(
                    f"{path}: spec {spec} names mesh axis {axis!r} but the "
                    f"mesh has {tuple(mesh.shape)}"
                )
            factor *= mesh.shape[axis]
        if factor > 1 and leaf.shape[dim] % factor:
            raise ValueError(
                f"{path}: dim {dim} of shape {tuple(leaf.shape)} is not "
                f"divisible by mesh axes {entry!r} (= {factor}); fix the "
                f"rule table or use validate='off'"
            )


def spec_tree(tree, rules: Sequence[Rule], mesh: Mesh, validate: str = "strict"):
    """Same-structure pytree of ``PartitionSpec`` from the rule table."""
    def one(path, leaf):
        spec = match_spec(rules, _path_str(path), leaf, mesh)
        if validate == "strict":
            validate_spec(_path_str(path), leaf, spec, mesh)
        return spec

    return jax.tree_util.tree_map_with_path(one, tree)


def sharding_tree(tree, rules: Sequence[Rule], mesh: Mesh,
                  validate: str = "strict"):
    """Per-leaf :class:`NamedSharding` tree for ``tree``."""
    specs = spec_tree(tree, rules, mesh, validate)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)


# --------------------------------------------------------------------------
# spec serialization (snapshot relayout-on-resume)
# --------------------------------------------------------------------------

def spec_to_str(spec: P) -> str:
    """``P(None, ("dp","tp"))`` -> ``"None,(dp+tp)"`` — stable, eval-free."""
    parts = []
    for entry in spec:
        axes = _entry_axes(entry)
        if not axes:
            parts.append("None")
        elif len(axes) == 1:
            parts.append(axes[0])
        else:
            parts.append("(" + "+".join(axes) + ")")
    return ",".join(parts)


def spec_from_str(s: str) -> P:
    if not s:
        return P()
    entries = []
    for part in s.split(","):
        part = part.strip()
        if part in ("None", ""):
            entries.append(None)
        elif part.startswith("(") and part.endswith(")"):
            entries.append(tuple(part[1:-1].split("+")))
        else:
            entries.append(part)
    return P(*entries)


def specs_record(tree, rules: Sequence[Rule], mesh: Mesh) -> Dict[str, str]:
    """``{leaf_path: spec_str}`` for every leaf — what snapshots carry
    so a resume can detect (and warn about) a relayout."""
    specs = spec_tree(tree, rules, mesh, validate="off")
    flat, _ = jax.tree_util.tree_flatten_with_path(specs)
    return {
        _path_str(path): spec_to_str(spec)
        for path, spec in flat
    }


def layout_to_json(layout: Layout) -> str:
    return json.dumps(
        {
            "name": layout.name,
            "axes": list(layout.axes),
            "rules": [
                [r.pattern, [list(e) if isinstance(e, tuple) else e
                             for e in r.spec], r.align]
                for r in layout.rules
            ],
            "batch_axis": layout.batch_axis,
        },
        sort_keys=True,
    )


def layout_from_json(doc: str) -> Layout:
    d = json.loads(doc)
    return Layout(
        axes=tuple((a, s) for a, s in d["axes"]),
        rules=tuple(
            Rule(p, tuple(tuple(e) if isinstance(e, list) else e
                          for e in spec), align)
            for p, spec, align in d["rules"]
        ),
        name=d.get("name", "custom"),
        batch_axis=d.get("batch_axis", DP_AXIS),
    )


def layout_fingerprint(layout: Layout) -> str:
    """16-hex content hash of the layout — folded into the serve
    tier's ``net_fingerprint`` so compile caches never alias across
    layouts of the same arch."""
    return hashlib.sha256(layout_to_json(layout).encode()).hexdigest()[:16]


# --------------------------------------------------------------------------
# the compiled-step plan
# --------------------------------------------------------------------------

class Plan:
    """The rule table compiled against concrete trees: per-leaf
    ``NamedSharding`` for params/state, per-slot trees for the
    optimizer state, and the batch shardings — everything
    :func:`make_sharded_train_step` needs, reusable by the solver for
    placement and by the serve engine for inference."""

    def __init__(self, layout: Layout, mesh: Mesh, params, state,
                 opt_keys: Sequence[str] = ()):
        self.layout = layout
        self.mesh = mesh
        for axis, size in layout.axes:
            if size != -1 and mesh.shape.get(axis) != size:
                raise ValueError(
                    f"layout axis {axis}={size} vs mesh "
                    f"{dict(mesh.shape)} — build the mesh from "
                    f"layout.mesh() or pass a matching one"
                )
        self.replicated = NamedSharding(mesh, P())
        self.params_sh = sharding_tree(
            params, layout.rules, mesh, layout.validate
        )
        # net state (BN stats etc.): replicated unless a rule targets it
        self.state_sh = sharding_tree(
            state, layout.rules, mesh, layout.validate
        )
        # solver slots mirror the param tree leaf-for-leaf
        self.opt_sh = {k: self.params_sh for k in opt_keys}
        dp = layout.batch_axis
        self.dp_axis = dp if dp in mesh.shape else None
        self.batch_eval_sh = NamedSharding(mesh, P(dp) if dp in mesh.shape else P())
        self.batch_train_sh = self.batch_eval_sh
        self.specs = specs_record(params, layout.rules, mesh)

    def with_iter_size(self, iter_size: int) -> "Plan":
        """Gradient accumulation stacks micro-batches on a leading
        axis; the batch axis to shard is then axis 1."""
        if iter_size > 1:
            dp = self.layout.batch_axis
            self.batch_train_sh = NamedSharding(
                self.mesh, P(None, dp) if dp in self.mesh.shape else P()
            )
        return self

    # ---- reporting ----------------------------------------------------
    def counts(self) -> Dict[str, int]:
        flat = jax.tree_util.tree_leaves(
            self.params_sh, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
        sharded = sum(1 for s in flat if s.spec != P())
        return {
            "param_leaves": len(flat),
            "sharded": sharded,
            "replicated": len(flat) - sharded,
        }

    def report(self) -> Dict[str, Any]:
        out = {
            "name": self.layout.name,
            "mesh": dict(self.mesh.shape),
            "rules": len(self.layout.rules),
            "fingerprint": layout_fingerprint(self.layout),
        }
        out.update(self.counts())
        return out


def make_plan(
    layout: Layout,
    params,
    state,
    sp=None,
    mesh: Optional[Mesh] = None,
    devices=None,
    iter_size: Optional[int] = None,
) -> Plan:
    """Resolve a layout against concrete trees (and a solver's slot
    keys) into a :class:`Plan`."""
    from ..solver.caffe_solver import opt_state_keys

    mesh = mesh if mesh is not None else layout.mesh(devices)
    keys = opt_state_keys(sp) if sp is not None else ()
    plan = Plan(layout, mesh, params, state, keys)
    if iter_size is None and sp is not None:
        iter_size = sp.iter_size
    return plan.with_iter_size(iter_size or 1)


# --------------------------------------------------------------------------
# the ONE sharded compile path
# --------------------------------------------------------------------------

def jit_sharded_step(fn, in_shardings, out_shardings, donate_argnums=()):
    """The single jit wrapper every sharded program goes through —
    train, eval and the dp wrappers in data_parallel.py all compile
    here, so compiler options and donation policy cannot drift."""
    from ..solver.trainer import step_compile_kw

    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=donate_argnums,
        **step_compile_kw(),
    )


def make_sharded_train_step(net, sp, plan: Plan, donate: bool = True):
    """``step(params, state, opt_state, batch, it, rng)`` jitted with
    the plan's shardings: params/opt donated, batch dp-sharded, every
    collective inserted by the XLA partitioner.  Works for any object
    satisfying the net protocol (XLANet or a model like BertMLM)."""
    from ..solver.trainer import make_train_step

    repl = plan.replicated
    return jit_sharded_step(
        make_train_step(net, sp),
        in_shardings=(
            plan.params_sh, plan.state_sh, plan.opt_sh,
            plan.batch_train_sh, repl, repl,
        ),
        out_shardings=(plan.params_sh, plan.state_sh, plan.opt_sh, repl),
        donate_argnums=(0, 1, 2) if donate else (),
    )


def make_sharded_eval_step(net, plan: Plan):
    """TEST-phase step over the same sharding trees — serve and eval
    compile through the identical path as training."""
    from ..solver.trainer import make_eval_step

    return jit_sharded_step(
        make_eval_step(net),
        in_shardings=(plan.params_sh, plan.state_sh, plan.batch_eval_sh),
        out_shardings=plan.replicated,
    )


def place(tree, shardings):
    """Device-put a host tree onto its sharding tree (or one broadcast
    sharding) — the layout-aware replacement for ``mesh.replicate``."""
    if isinstance(shardings, (NamedSharding,)):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, shardings), tree
        )
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), tree, shardings
    )


# --------------------------------------------------------------------------
# virtual-mesh + fence guards (test/bench plumbing)
# --------------------------------------------------------------------------

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def ensure_virtual_devices(n: int) -> bool:
    """``honor_platform_env``-style guard for the virtual-CPU mesh:
    make ``XLA_FLAGS=--xla_force_host_platform_device_count=n``
    effective when the backend is not yet initialized, and a LOUD
    no-op (warning, return False) when it is — instead of the silent
    1-device mesh that makes every divisibility check downstream fail
    confusingly.  Returns True when n devices are (or will be)
    available."""
    flags = os.environ.get("XLA_FLAGS", "")
    have = re.search(_FORCE_FLAG + r"=(\d+)", flags)
    backend_up = False
    try:  # detect init WITHOUT triggering it
        from jax._src import xla_bridge as _xb

        backend_up = bool(getattr(_xb, "_backends", None))
    except Exception:  # pragma: no cover - jax internals moved
        pass
    if backend_up:
        ok = len(jax.devices()) >= n
        if not ok:
            warnings.warn(
                f"ensure_virtual_devices({n}): jax backend already "
                f"initialized with {len(jax.devices())} device(s) — set "
                f"XLA_FLAGS={_FORCE_FLAG}={n} before the first device "
                "touch",
                RuntimeWarning,
                stacklevel=2,
            )
        return ok
    if have and int(have.group(1)) >= n:
        return True
    if have:
        flags = re.sub(_FORCE_FLAG + r"=\d+", f"{_FORCE_FLAG}={n}", flags)
    else:
        flags = (flags + f" {_FORCE_FLAG}={n}").strip()
    os.environ["XLA_FLAGS"] = flags
    return True


def fence_once(tree):
    """``block_until_ready`` UNLESS the active telemetry timeline
    already fences the compiled step — the solver's ``compiled_step``
    phase bracket blocks on the step's outputs, so fencing again here
    would put a second device sync inside the timed region and charge
    it to the wrong phase.  Bench arms and smoke scripts use this as
    their one fence."""
    from ..telemetry import timeline as _tl

    if getattr(_tl.current(), "fence", False):
        return tree
    return jax.block_until_ready(tree)


# --------------------------------------------------------------------------
# relayout-on-resume support
# --------------------------------------------------------------------------

def relayout_warning(saved_specs_json: str, current: Dict[str, str],
                     saved_layout: str = "", current_layout: str = "",
                     event: str = "resume") -> str:
    """One aggregated message for a relayout — name the count and the
    two layouts, not a leaf-per-line wall.  The same migration runs on
    two paths, and the wording names which: ``event="resume"`` (a
    snapshot restored under a different layout) or ``event="reshard"``
    (a live in-place migration, parallel/reshard.py)."""
    try:
        saved = json.loads(saved_specs_json)
    except (TypeError, json.JSONDecodeError):
        saved = {}
    changed = [
        k for k in current
        if k in saved and saved[k] != current[k]
    ] + [k for k in current if k not in saved]
    head, src, dst = (
        ("relayout on resume", "snapshot", "run")
        if event == "resume"
        else ("relayout (live reshard)", "old", "new")
    )
    return (
        f"{head}: {len(changed)} of {len(current)} leaves "
        f"re-partitioned ({src} layout {saved_layout or 'unknown'} -> "
        f"{dst} layout {current_layout or 'unknown'}); weights are placed "
        "per the new rule table bitwise-unchanged — numerics of further "
        "training match to reduction order (the same in-place migration "
        "on either path; docs/PARALLELISM.md \"Live resharding\")"
    )
