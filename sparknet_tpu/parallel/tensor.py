"""Tensor (model) parallelism for the BERT family — Megatron-style.

No reference counterpart (SURVEY.md §2: data parallelism only). Layer
weights shard over a ``"tp"`` mesh axis: qkv and ffn_in are
column-parallel (output-dim sharded — each rank owns a contiguous block
of heads / ffn neurons), out and ffn_out are row-parallel (input-dim
sharded, partial products ``psum``-reduced inside
:meth:`BertMLM.encode`). Embeddings, LayerNorms and the MLM head stay
replicated — they are a small fraction of parameters and keeping them
replicated avoids a vocab-sharded softmax.

The train step composes with the other axes: batch rows shard over
``dp``, sequence over ``sp`` (ring attention on the local heads), and
gradients reduce over exactly the axes each parameter is *replicated*
on — sharded leaves reduce over dp/sp only.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..solver.caffe_solver import make_update_fn, mults_for_params
from . import comm


def bert_param_pspecs(model, tp_axis: str = "tp") -> Dict[str, Dict[str, P]]:
    """PartitionSpec tree matching ``BertMLM`` params: column-parallel
    qkv/ffn_in, row-parallel out/ffn_out, everything else replicated."""
    col_w = P(None, tp_axis)
    col_b = P(tp_axis)
    row_w = P(tp_axis, None)
    rep = P()
    specs: Dict[str, Dict[str, P]] = {
        "embeddings": {
            "word": rep, "position": rep, "token_type": rep,
            "ln_scale": rep, "ln_bias": rep,
        },
        "mlm_head": {
            "dense_w": rep, "dense_b": rep, "ln_scale": rep,
            "ln_bias": rep, "output_bias": rep,
        },
    }
    for li in range(model.cfg.num_layers):
        specs[f"layer_{li:02d}"] = {
            "q_w": col_w, "q_b": col_b,
            "k_w": col_w, "k_b": col_b,
            "v_w": col_w, "v_b": col_b,
            "out_w": row_w, "out_b": rep,
            "attn_ln_scale": rep, "attn_ln_bias": rep,
            "ffn_in_w": col_w, "ffn_in_b": col_b,
            "ffn_out_w": row_w, "ffn_out_b": rep,
            "ffn_ln_scale": rep, "ffn_ln_bias": rep,
        }
    return specs


def _grad_reduce(grads, data_axes):
    """Gradients reduce over the data axes only. No tp reduction is
    needed anywhere: sharded leaves own their shard's grad outright, and
    replicated leaves already see the full gradient on every tp rank
    because the model's ``_tp_copy`` (Megatron "f") psums the
    column-parallel input cotangents in backward."""
    if not data_axes:
        return grads
    return jax.tree_util.tree_map(lambda g: lax.psum(g, data_axes), grads)


def make_tp_train_step(
    model,
    sp,
    mesh,
    dp_axis: Optional[str] = "dp",
    tp_axis: str = "tp",
    sp_axis: Optional[str] = None,
):
    """Jitted ``step(params, opt_state, batch, it, rng)`` over a
    dp×tp(×sp) mesh with token-level MLM loss.

    ``model`` must be built with ``tp_axis=tp_axis`` (and, when
    ``sp_axis`` is given, ``attention_impl="ring"`` — ulysses shards
    heads and composes poorly with head-sharding tp). ``batch`` is the
    token-level layout of
    :func:`sparknet_tpu.data.text.mlm_feed_tokens`.
    """
    ntp = mesh.shape[tp_axis]
    cfg = model.cfg
    if cfg.num_heads % ntp or cfg.intermediate_size % ntp:
        raise ValueError(
            f"tp={ntp} must divide num_heads ({cfg.num_heads}) and "
            f"intermediate_size ({cfg.intermediate_size})"
        )
    # a model without the matching tp hook would silently skip the
    # row-parallel psum and train on partial activations
    if model.tp_axis != tp_axis:
        raise ValueError(
            f"model.tp_axis ({model.tp_axis!r}) != tp_axis ({tp_axis!r}): "
            "build the model with BertMLM(..., tp_axis=tp_axis)"
        )
    if sp_axis is not None and model.attention_impl != "ring":
        raise ValueError(
            "sp_axis with tensor parallelism requires attention_impl="
            f"'ring' (got {model.attention_impl!r}); ulysses re-shards "
            "heads and conflicts with tp head sharding"
        )
    pspecs = bert_param_pspecs(model, tp_axis)
    data_axes = tuple(a for a in (dp_axis, sp_axis) if a is not None)

    def local_step(params, opt_state, batch, it, rng):
        # dropout: identical across tp ranks (activations are
        # replicated there), distinct across data axes
        for a in data_axes:
            rng = jax.random.fold_in(rng, lax.axis_index(a))

        def loss_fn(p):
            nll, w, corr = model.token_loss_sums(
                p, {}, batch, train=True, rng=rng
            )
            w_tot = lax.psum(w, data_axes) if data_axes else w
            loss_local = nll / jnp.maximum(w_tot, 1.0)
            return loss_local, (nll, w_tot, corr)

        grads, (nll, w_tot, corr) = jax.grad(loss_fn, has_aux=True)(params)
        grads = _grad_reduce(grads, data_axes)
        lr_m, dec_m = mults_for_params(params, model.param_specs())
        update = make_update_fn(sp, lr_m, dec_m)
        params, opt_state = update(params, grads, opt_state, it)
        nll_tot = lax.psum(nll, data_axes) if data_axes else nll
        corr_tot = lax.psum(corr, data_axes) if data_axes else corr
        denom = jnp.maximum(w_tot, 1.0)
        return params, opt_state, {
            "loss": nll_tot / denom, "mlm_acc": corr_tot / denom,
        }

    batch_axes = P(dp_axis, sp_axis)
    batch_spec = {
        "input_ids": batch_axes,
        "token_type_ids": batch_axes,
        "attention_mask": batch_axes,
        "position_ids": batch_axes,
        "mlm_labels": batch_axes,
        "mlm_weights": batch_axes,
    }
    # opt_state's outer keys depend on the solver type ("m"/"v" for
    # AdamW, "momentum" for SGD, ...), so its spec tree is resolved at
    # first call and the shard_map cached per key set
    compiled = {}

    def stepper(params, opt_state, batch, it, rng):
        key = tuple(sorted(opt_state))
        if key not in compiled:
            ospec = {k: pspecs for k in opt_state}
            compiled[key] = comm.jit_manual(
                comm.shard_map(
                    local_step,
                    mesh=mesh,
                    in_specs=(pspecs, ospec, batch_spec, P(), P()),
                    out_specs=(pspecs, ospec, P()),
                ),
                donate_argnums=(0, 1),
            )
        return compiled[key](params, opt_state, batch, it, rng)

    return stepper