"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference is data-parallel only (SURVEY.md §2: "no TP/PP/SP/EP/CP" —
long-context parallelism is a task-spec obligation, designed TPU-native
here rather than ported). Both strategies run *inside* ``shard_map``
over an ``"sp"`` mesh axis, with sequence-sharded q/k/v ``(B, H, S/n,
D)`` per device:

- :func:`ring_attention` — k/v shards rotate around the ring via
  ``lax.ppermute`` (ICI neighbor exchange) while each device folds every
  incoming block into a running online-softmax accumulator ``(o, m, l)``
  — flash attention's recurrence at shard granularity, so no device ever
  materialises more than one ``(S/n, S/n)`` logit block. Memory is
  O(S/n), communication is the bandwidth-optimal ring.
- :func:`ulysses_attention` — ``lax.all_to_all`` re-shards sequence ->
  heads, runs *full-sequence* attention locally on H/n heads (the Pallas
  flash kernel on TPU), then re-shards back. Cheaper compute plumbing
  when H divides the axis and S fits per-device; ring wins at extreme S.

Both differentiate through the collectives (``ppermute``/``all_to_all``
have transpose rules), so the same code path trains.
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF, attention


def _block_logits(q, k, scale, kv_mask_blk, causal, q_off, kv_off):
    """(B,H,Sq,Sk) masked logits for one ring block."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    sq, sk = q.shape[2], k.shape[2]
    valid = jnp.ones((1, 1, sq, sk), bool)
    if causal:
        qi = jnp.arange(sq)[:, None] + q_off
        ki = jnp.arange(sk)[None, :] + kv_off
        valid = valid & (ki <= qi)[None, None]
    if kv_mask_blk is not None:
        valid = valid & kv_mask_blk[:, None, None, :].astype(bool)
    return jnp.where(valid, s, NEG_INF)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    impl: Optional[str] = None,
    interpret: bool = False,
) -> jax.Array:
    """Ring attention over sequence shards. Call inside ``shard_map``.

    q/k/v: local shards (B, H, S_local, D); kv_mask: local (B, S_local),
    True/1 = valid key. Returns the local output shard (B, H, S_local, D).

    Two block engines, same ring:

    - **flash** (TPU default for lane-aligned shards): each ring step
      runs the streamed Pallas flash kernel on (q_local × kv shard) with
      per-step q/kv offsets, merging the per-block ``(out, lse)`` pairs
      with logaddexp weights; backward re-runs the ring calling the
      flash backward kernels per block with the GLOBAL merged lse
      (exact), accumulating dk/dv in carries that rotate with their kv
      shard so every contribution lands home. HBM per step is O(S_local
      * D) — the (S_local, S_local) logit block never materialises.
    - **einsum** (fallback/oracle): materialises one f32 logit block per
      step with an explicit online-softmax merge.

    ``impl`` forces "flash"/"einsum" (env ``SPARKNET_RING_IMPL``
    overrides the default); ``interpret`` runs the flash kernels in
    Pallas interpret mode (CPU tests).

    Attention-probability dropout drops entries of the *unnormalised*
    online-softmax numerator p per ring step (keyed by the source shard
    so the mask is well-defined per (query, key) pair); the denominator
    keeps the undropped sum, matching the reference path's
    ``p/sum(p)``-then-drop semantics in expectation.
    """
    b, h, s_loc, d = q.shape
    if impl is None:
        impl = os.environ.get("SPARKNET_RING_IMPL") or None
    if impl is None:
        from ..ops.attention import pltpu

        impl = (
            "flash"
            if (
                jax.default_backend() == "tpu"
                and pltpu is not None
                and s_loc % 128 == 0
            )
            else "einsum"
        )
    if impl not in ("flash", "einsum"):
        raise ValueError(
            f"ring impl {impl!r}: want 'flash' or 'einsum' "
            f"(check SPARKNET_RING_IMPL)"
        )
    if impl == "flash":
        scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
        mask = (
            jnp.ones((b, s_loc), jnp.int8)
            if kv_mask is None
            else kv_mask.astype(jnp.int8)
        )
        if dropout_rate > 0.0 and dropout_rng is not None:
            from ..ops.attention import seed_from_rng

            seed = seed_from_rng(dropout_rng)
        else:
            dropout_rate = 0.0
            seed = jnp.asarray(0, jnp.int32)
        return _ring_flash(
            q, k, v, mask, seed, axis_name, causal, float(scale_v),
            float(dropout_rate), interpret,
        )
    return _ring_einsum(
        q, k, v, axis_name=axis_name, causal=causal, kv_mask=kv_mask,
        scale=scale, dropout_rate=dropout_rate, dropout_rng=dropout_rng,
    )


def _ring_flash_steps(q, k, v, kv_mask, seed, axis_name, causal, scale,
                      dropout_rate, interpret):
    """Forward ring: one flash-fwd kernel call per kv shard, partials
    merged by logaddexp weights. Returns (out f32, merged lse)."""
    from ..ops.attention import flash_block_fwd

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    q_off = idx * s_loc
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        o, lse_acc, k_cur, v_cur, mask_cur, src = carry
        o_s, lse_s = flash_block_fwd(
            q, k_cur, v_cur, mask_cur,
            q_offset=q_off, kv_offset=src * s_loc,
            # decorrelate masks per (q shard, kv shard) — the kernel's
            # own PRNG only sees block-local coordinates
            seed=seed + src * jnp.int32(-1640531527)
            + idx * jnp.int32(40503),
            causal=causal, scale=scale, interpret=interpret,
            dropout_rate=dropout_rate,
        )
        # NEG_INF is finite (-1e30), so dead rows merge NaN-free: their
        # weights underflow to 0 and their o stays 0
        lse_new = jnp.logaddexp(lse_acc, lse_s)
        w1 = jnp.exp(lse_acc - lse_new)
        w2 = jnp.exp(lse_s - lse_new)
        o = o * w1[..., None] + o_s.astype(jnp.float32) * w2[..., None]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        return (o, lse_new, k_nxt, v_nxt, mask_nxt, (src - 1) % n), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    (o, lse, *_), _ = lax.scan(
        step, (o0, lse0, k, v, kv_mask, idx), None, length=n
    )
    return o, lse


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _ring_flash(q, k, v, kv_mask, seed, axis_name, causal, scale,
                dropout_rate, interpret):
    o, _ = _ring_flash_steps(
        q, k, v, kv_mask, seed, axis_name, causal, scale, dropout_rate,
        interpret,
    )
    return o.astype(q.dtype)


def _ring_flash_fwd(q, k, v, kv_mask, seed, axis_name, causal, scale,
                    dropout_rate, interpret):
    o, lse = _ring_flash_steps(
        q, k, v, kv_mask, seed, axis_name, causal, scale, dropout_rate,
        interpret,
    )
    out = o.astype(q.dtype)
    return out, (q, k, v, kv_mask, seed, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, dropout_rate, interpret,
                    res, do):
    from ..ops.attention import flash_block_bwd

    q, k, v, kv_mask, seed, out, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    q_off = idx * s_loc
    perm = [(i, (i + 1) % n) for i in range(n)]
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (B, H, S_local)

    def step(carry, _):
        dq, dk_acc, dv_acc, k_cur, v_cur, mask_cur, src = carry
        dq_s, dk_s, dv_s = flash_block_bwd(
            q, k_cur, v_cur, mask_cur, do, lse, delta,
            q_offset=q_off, kv_offset=src * s_loc,
            seed=seed + src * jnp.int32(-1640531527)
            + idx * jnp.int32(40503),
            causal=causal, scale=scale, interpret=interpret,
            dropout_rate=dropout_rate,
        )
        dq = dq + dq_s.astype(jnp.float32)
        # dk/dv accumulators travel WITH their kv shard: add this
        # device's contribution, then rotate both together — after the
        # full circle every shard is home with its total gradient
        dk_nxt = lax.ppermute(
            dk_acc + dk_s.astype(jnp.float32), axis_name, perm
        )
        dv_nxt = lax.ppermute(
            dv_acc + dv_s.astype(jnp.float32), axis_name, perm
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        return (
            dq, dk_nxt, dv_nxt, k_nxt, v_nxt, mask_nxt, (src - 1) % n
        ), None

    z = jnp.zeros((b, h, s_loc, d), jnp.float32)
    (dq, dk, dv, *_), _ = lax.scan(
        step, (z, z, z, k, v, kv_mask, idx), None, length=n
    )
    return (
        dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
        None, None,
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _ring_einsum(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    q_off = idx * s_loc
    if kv_mask is None:
        kv_mask = jnp.ones((b, s_loc), jnp.int32)
    dropping = dropout_rate > 0.0 and dropout_rng is not None

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        o, m, l, k_cur, v_cur, mask_cur, src = carry
        kv_off = src * s_loc
        s_blk = _block_logits(
            q, k_cur, scale, mask_cur, causal, q_off, kv_off
        )
        m_blk = jnp.max(s_blk, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # guard: rows with nothing valid yet keep exp(NEG_INF-NEG_INF)
        # from turning into 1
        p = jnp.where(
            s_blk <= NEG_INF * 0.5, 0.0, jnp.exp(s_blk - m_new[..., None])
        )
        alpha = jnp.where(
            m <= NEG_INF * 0.5, 0.0, jnp.exp(m - m_new)
        )
        l = alpha * l + jnp.sum(p, axis=-1)
        p_v = p
        if dropping:
            # mask keyed by (q shard, kv shard origin), independent of
            # ring scheduling; numerator-only so l stays the softmax sum
            blk_rng = jax.random.fold_in(
                jax.random.fold_in(dropout_rng, idx), src
            )
            keep = jax.random.bernoulli(blk_rng, 1.0 - dropout_rate, p.shape)
            p_v = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p_v, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        src = (src - 1) % n
        return (o, m_new, l, k_nxt, v_nxt, mask_nxt, src), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    (o, m, l, *_), _ = lax.scan(
        step, (o0, m0, l0, k, v, kv_mask, idx), None, length=n
    )
    dead = m <= NEG_INF * 0.5
    out = jnp.where(
        dead[..., None], 0.0, o / jnp.maximum(l, 1e-30)[..., None]
    )
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    force: Optional[str] = None,
) -> jax.Array:
    """Ulysses SP: all-to-all seq->heads, local full-seq attention
    (flash on TPU), all-to-all heads->seq. Call inside ``shard_map``.

    Heads must be divisible by the axis size. Attention dropout is
    delegated to the local attention dispatcher (each rank holds
    distinct heads, so per-rank rng decorrelation is handled by folding
    in the axis index).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if h % n:
        raise ValueError(f"ulysses: heads ({h}) not divisible by axis ({n})")
    # (B, H, S/n, D) -> (B, H/n, S, D)
    a2a = partial(
        lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    mask_g = (
        lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
        if kv_mask is not None
        else None
    )
    if dropout_rng is not None:
        dropout_rng = jax.random.fold_in(dropout_rng, idx)
    ctx = attention(
        qg, kg, vg, causal=causal, kv_mask=mask_g, scale=scale,
        dropout_rate=dropout_rate, dropout_rng=dropout_rng, force=force,
    )
    # (B, H/n, S, D) -> (B, H, S/n, D)
    return lax.all_to_all(
        ctx, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


# ---------------------------------------------------------------------------
# Sequence-parallel BERT training step
# ---------------------------------------------------------------------------

def make_sp_train_step(model, sp, mesh, dp_axis: str = "dp", sp_axis: str = "sp"):
    """Jitted ``step(params, opt_state, batch, it, rng) -> (params,
    opt_state, metrics)`` training a token-loss BERT over a 2-D
    ``(dp, sp)`` mesh: batch rows sharded over ``dp``, sequence sharded
    over ``sp`` (ring/ulysses attention inside the model), params
    replicated, gradient all-reduce over both axes.

    ``model`` must be a BertMLM built with ``attention_impl`` in
    {"ring", "ulysses"}; ``batch`` blobs are (B, S) token-level arrays
    (``mlm_labels``/``mlm_weights`` per token, plus ``position_ids``).
    """
    from jax.sharding import PartitionSpec as P

    from ..solver.caffe_solver import make_update_fn, mults_for_params

    if model.attention_impl not in ("ring", "ulysses"):
        raise ValueError(
            "make_sp_train_step needs a model built with attention_impl="
            f"'ring' or 'ulysses' (got {model.attention_impl!r}) — plain "
            "attention would silently attend within each shard only"
        )
    if model.sp_axis != sp_axis:
        raise ValueError(
            f"model.sp_axis ({model.sp_axis!r}) != sp_axis ({sp_axis!r})"
        )

    def local_step(params, opt_state, batch, it, rng):
        # decorrelate dropout across mesh positions
        rng = jax.random.fold_in(rng, lax.axis_index(dp_axis))
        rng = jax.random.fold_in(rng, lax.axis_index(sp_axis))

        def loss_fn(p):
            nll, w, corr = model.token_loss_sums(
                p, {}, batch, train=True, rng=rng
            )
            w_tot = lax.psum(w, (dp_axis, sp_axis))
            loss_local = nll / jnp.maximum(w_tot, 1.0)
            return loss_local, (nll, w_tot, corr)

        grads, (nll, w_tot, corr) = jax.grad(loss_fn, has_aux=True)(params)
        grads = lax.psum(grads, (dp_axis, sp_axis))
        lr_m, dec_m = mults_for_params(params, model.param_specs())
        update = make_update_fn(sp, lr_m, dec_m)
        params, opt_state = update(params, grads, opt_state, it)
        loss = lax.psum(nll, (dp_axis, sp_axis)) / jnp.maximum(w_tot, 1.0)
        acc = lax.psum(corr, (dp_axis, sp_axis)) / jnp.maximum(w_tot, 1.0)
        return params, opt_state, {"loss": loss, "mlm_acc": acc}

    batch_spec = {
        "input_ids": P(dp_axis, sp_axis),
        "token_type_ids": P(dp_axis, sp_axis),
        "attention_mask": P(dp_axis, sp_axis),
        "position_ids": P(dp_axis, sp_axis),
        "mlm_labels": P(dp_axis, sp_axis),
        "mlm_weights": P(dp_axis, sp_axis),
    }
    from . import comm

    step = comm.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, P(), P()),
        out_specs=(P(), P(), P()),
    )
    return comm.jit_manual(step, donate_argnums=(0, 1))
