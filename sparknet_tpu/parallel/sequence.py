"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference is data-parallel only (SURVEY.md §2: "no TP/PP/SP/EP/CP" —
long-context parallelism is a task-spec obligation, designed TPU-native
here rather than ported). Both strategies run *inside* ``shard_map``
over an ``"sp"`` mesh axis, with sequence-sharded q/k/v ``(B, H, S/n,
D)`` per device:

- :func:`ring_attention` — k/v shards rotate around the ring via
  ``lax.ppermute`` (ICI neighbor exchange) while each device folds every
  incoming block into a running online-softmax accumulator ``(o, m, l)``
  — flash attention's recurrence at shard granularity, so no device ever
  materialises more than one ``(S/n, S/n)`` logit block. Memory is
  O(S/n), communication is the bandwidth-optimal ring.
- :func:`ulysses_attention` — ``lax.all_to_all`` re-shards sequence ->
  heads, runs *full-sequence* attention locally on H/n heads (the Pallas
  flash kernel on TPU), then re-shards back. Cheaper compute plumbing
  when H divides the axis and S fits per-device; ring wins at extreme S.

Both differentiate through the collectives (``ppermute``/``all_to_all``
have transpose rules), so the same code path trains.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF, attention


def _block_logits(q, k, scale, kv_mask_blk, causal, q_off, kv_off):
    """(B,H,Sq,Sk) masked logits for one ring block."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    sq, sk = q.shape[2], k.shape[2]
    valid = jnp.ones((1, 1, sq, sk), bool)
    if causal:
        qi = jnp.arange(sq)[:, None] + q_off
        ki = jnp.arange(sk)[None, :] + kv_off
        valid = valid & (ki <= qi)[None, None]
    if kv_mask_blk is not None:
        valid = valid & kv_mask_blk[:, None, None, :].astype(bool)
    return jnp.where(valid, s, NEG_INF)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Ring attention over sequence shards. Call inside ``shard_map``.

    q/k/v: local shards (B, H, S_local, D); kv_mask: local (B, S_local),
    True/1 = valid key. Returns the local output shard (B, H, S_local, D).

    Attention-probability dropout drops entries of the *unnormalised*
    online-softmax numerator p per ring step (keyed by the source shard
    so the mask is well-defined per (query, key) pair); the denominator
    keeps the undropped sum, matching the reference path's
    ``p/sum(p)``-then-drop semantics in expectation.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    q_off = idx * s_loc
    if kv_mask is None:
        kv_mask = jnp.ones((b, s_loc), jnp.int32)
    dropping = dropout_rate > 0.0 and dropout_rng is not None

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        o, m, l, k_cur, v_cur, mask_cur, src = carry
        kv_off = src * s_loc
        s_blk = _block_logits(
            q, k_cur, scale, mask_cur, causal, q_off, kv_off
        )
        m_blk = jnp.max(s_blk, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # guard: rows with nothing valid yet keep exp(NEG_INF-NEG_INF)
        # from turning into 1
        p = jnp.where(
            s_blk <= NEG_INF * 0.5, 0.0, jnp.exp(s_blk - m_new[..., None])
        )
        alpha = jnp.where(
            m <= NEG_INF * 0.5, 0.0, jnp.exp(m - m_new)
        )
        l = alpha * l + jnp.sum(p, axis=-1)
        p_v = p
        if dropping:
            # mask keyed by (q shard, kv shard origin), independent of
            # ring scheduling; numerator-only so l stays the softmax sum
            blk_rng = jax.random.fold_in(
                jax.random.fold_in(dropout_rng, idx), src
            )
            keep = jax.random.bernoulli(blk_rng, 1.0 - dropout_rate, p.shape)
            p_v = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p_v, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        src = (src - 1) % n
        return (o, m_new, l, k_nxt, v_nxt, mask_nxt, src), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    (o, m, l, *_), _ = lax.scan(
        step, (o0, m0, l0, k, v, kv_mask, idx), None, length=n
    )
    dead = m <= NEG_INF * 0.5
    out = jnp.where(
        dead[..., None], 0.0, o / jnp.maximum(l, 1e-30)[..., None]
    )
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    force: Optional[str] = None,
) -> jax.Array:
    """Ulysses SP: all-to-all seq->heads, local full-seq attention
    (flash on TPU), all-to-all heads->seq. Call inside ``shard_map``.

    Heads must be divisible by the axis size. Attention dropout is
    delegated to the local attention dispatcher (each rank holds
    distinct heads, so per-rank rng decorrelation is handled by folding
    in the axis index).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if h % n:
        raise ValueError(f"ulysses: heads ({h}) not divisible by axis ({n})")
    # (B, H, S/n, D) -> (B, H/n, S, D)
    a2a = partial(
        lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    mask_g = (
        lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
        if kv_mask is not None
        else None
    )
    if dropout_rng is not None:
        dropout_rng = jax.random.fold_in(dropout_rng, idx)
    ctx = attention(
        qg, kg, vg, causal=causal, kv_mask=mask_g, scale=scale,
        dropout_rate=dropout_rate, dropout_rng=dropout_rng, force=force,
    )
    # (B, H/n, S, D) -> (B, H, S/n, D)
    return lax.all_to_all(
        ctx, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


# ---------------------------------------------------------------------------
# Sequence-parallel BERT training step
# ---------------------------------------------------------------------------

def make_sp_train_step(model, sp, mesh, dp_axis: str = "dp", sp_axis: str = "sp"):
    """Jitted ``step(params, opt_state, batch, it, rng) -> (params,
    opt_state, metrics)`` training a token-loss BERT over a 2-D
    ``(dp, sp)`` mesh: batch rows sharded over ``dp``, sequence sharded
    over ``sp`` (ring/ulysses attention inside the model), params
    replicated, gradient all-reduce over both axes.

    ``model`` must be a BertMLM built with ``attention_impl`` in
    {"ring", "ulysses"}; ``batch`` blobs are (B, S) token-level arrays
    (``mlm_labels``/``mlm_weights`` per token, plus ``position_ids``).
    """
    from jax.sharding import PartitionSpec as P

    from ..solver.caffe_solver import make_update_fn, mults_for_params

    if model.attention_impl not in ("ring", "ulysses"):
        raise ValueError(
            "make_sp_train_step needs a model built with attention_impl="
            f"'ring' or 'ulysses' (got {model.attention_impl!r}) — plain "
            "attention would silently attend within each shard only"
        )
    if model.sp_axis != sp_axis:
        raise ValueError(
            f"model.sp_axis ({model.sp_axis!r}) != sp_axis ({sp_axis!r})"
        )

    def local_step(params, opt_state, batch, it, rng):
        # decorrelate dropout across mesh positions
        rng = jax.random.fold_in(rng, lax.axis_index(dp_axis))
        rng = jax.random.fold_in(rng, lax.axis_index(sp_axis))

        def loss_fn(p):
            nll, w, corr = model.token_loss_sums(
                p, {}, batch, train=True, rng=rng
            )
            w_tot = lax.psum(w, (dp_axis, sp_axis))
            loss_local = nll / jnp.maximum(w_tot, 1.0)
            return loss_local, (nll, w_tot, corr)

        grads, (nll, w_tot, corr) = jax.grad(loss_fn, has_aux=True)(params)
        grads = lax.psum(grads, (dp_axis, sp_axis))
        lr_m, dec_m = mults_for_params(params, model.param_specs())
        update = make_update_fn(sp, lr_m, dec_m)
        params, opt_state = update(params, grads, opt_state, it)
        loss = lax.psum(nll, (dp_axis, sp_axis)) / jnp.maximum(w_tot, 1.0)
        acc = lax.psum(corr, (dp_axis, sp_axis)) / jnp.maximum(w_tot, 1.0)
        return params, opt_state, {"loss": loss, "mlm_acc": acc}

    batch_spec = {
        "input_ids": P(dp_axis, sp_axis),
        "token_type_ids": P(dp_axis, sp_axis),
        "attention_mask": P(dp_axis, sp_axis),
        "position_ids": P(dp_axis, sp_axis),
        "mlm_labels": P(dp_axis, sp_axis),
        "mlm_weights": P(dp_axis, sp_axis),
    }
    step = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(0, 1))
