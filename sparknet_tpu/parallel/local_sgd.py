"""τ-local SGD with periodic parameter averaging — SparkNet's algorithm.

The reference's central contribution (SparkNet paper, arXiv:1511.06051;
SURVEY.md §1 "core algorithm"; mount empty, no file:line): each worker
runs τ *independent* SGD steps on its own data shard, then the driver
averages the weights — trading gradient staleness for a τ× reduction in
communication rounds.  There, one round is JNI weight copy -> Spark
treeReduce over TCP -> broadcast.  Here the whole round is ONE compiled
XLA program under ``shard_map``: each device runs its τ steps as a
``lax.scan`` (no host involvement between steps), then a single
``lax.pmean`` over the ``dp`` axis averages the weights across ICI.
Per-worker solver state (momentum etc.) persists across rounds without
averaging, matching the reference where each executor keeps its native
Caffe solver alive between syncs.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nets.xlanet import XLANet
from ..proto.caffe_pb import SolverParameter
from ..solver.caffe_solver import init_opt_state, make_update_fn, mults_for_params
from ..solver.trainer import accumulate_grads, make_grad_fn, step_compile_kw
from .mesh import DP_AXIS


def init_local_opt_state(sp: SolverParameter, params: Any, num_workers: int):
    """Per-worker solver state: leading axis = dp mesh size (each worker's
    momentum lives on its own device, like each executor's native solver)."""
    single = init_opt_state(sp, params)
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (num_workers,) + x.shape), single
    )


def make_local_sgd_round(
    net: XLANet,
    sp: SolverParameter,
    mesh: Mesh,
    tau: int,
    dp_axis: str = DP_AXIS,
    donate: bool = True,
) -> Callable:
    """Build the jitted round function

    ``round(params, state, opt_state, batches, it, rng)
        -> (params, state, opt_state, metrics)``

    - ``params``/``state``: replicated in, replicated (averaged) out —
      like the reference, worker nets are averaged wholesale at sync
      (state, e.g. BN running stats, is averaged alongside weights).
    - ``opt_state``: from :func:`init_local_opt_state` — leading axis is
      the worker axis, sharded over ``dp``; persists un-averaged.
    - ``batches``: pytree with leaves shaped ``[tau, global_bs, ...]``
      (or ``[tau, iter_size, global_bs, ...]`` when ``sp.iter_size > 1``);
      the global batch axis is sharded over ``dp`` so each worker scans
      over its own ``[tau, local_bs, ...]`` shard.
    - ``it``: int32 global iteration at round start (advances by tau).
    """
    grad_fn = make_grad_fn(net)
    specs = net.param_specs()

    def per_worker(params, state, opt_state, batches, it, rng):
        # params/state arrive replicated but immediately diverge per
        # worker (local updates): mark them device-varying for shard_map's
        # replication typing so the scan carry has a stable type.
        vary = lambda t: jax.tree_util.tree_map(
            lambda x: lax.pcast(x, dp_axis, to="varying"), t
        )
        params, state = vary(params), vary(state)
        # inside shard_map: opt_state leading worker-axis is local size 1
        opt_local = jax.tree_util.tree_map(lambda x: x[0], opt_state)
        lr_m, dec_m = mults_for_params(params, specs)
        update = make_update_fn(sp, lr_m, dec_m)
        widx = lax.axis_index(dp_axis)
        wrng = jax.random.fold_in(rng, widx)

        def grads_of(p, st, micro, step_rng):
            """One iteration's gradient; Caffe iter_size accumulation
            when the extra micro-batch axis is present."""
            if sp.iter_size > 1:
                return accumulate_grads(grad_fn, p, st, micro, step_rng)
            return grad_fn(p, st, micro, step_rng)

        def body(carry, micro):
            p, st, opt, i = carry
            g, st2, metrics = grads_of(p, st, micro, jax.random.fold_in(wrng, i))
            p2, opt2 = update(p, g, opt, it + i)
            return (p2, st2, opt2, i + 1), metrics

        (p, st, opt_local, _), mstack = lax.scan(
            body, (params, state, opt_local, 0), batches, length=tau
        )
        # SparkNet's sync: elementwise average of worker weights — one
        # ICI all-reduce instead of a driver TCP round-trip.
        p = lax.pmean(p, dp_axis)
        st = lax.pmean(st, dp_axis)  # BN running stats etc.
        metrics = lax.pmean(
            jax.tree_util.tree_map(lambda m: jnp.mean(m, 0), mstack), dp_axis
        )
        opt_out = jax.tree_util.tree_map(lambda x: x[None], opt_local)
        return p, st, opt_out, metrics

    batch_spec = (
        P(None, None, dp_axis) if sp.iter_size > 1 else P(None, dp_axis)
    )
    fn = jax.shard_map(
        per_worker,
        mesh=mesh,
        in_specs=(P(), P(), P(dp_axis), batch_spec, P(), P()),
        out_specs=(P(), P(), P(dp_axis), P()),
    )
    return jax.jit(
        fn, donate_argnums=(0, 1, 2) if donate else (), **step_compile_kw()
    )


def stack_round_batches(batch_list):
    """Stack tau host batches into the ``[tau, global_bs, ...]`` layout.

    Stacks on the host (numpy): the caller's device_put then shards the
    result straight onto the mesh, instead of committing the full round
    batch to device 0 first and re-transferring.
    """
    import numpy as np

    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *batch_list
    )


def round_batch_sharding(
    mesh: Mesh, dp_axis: str = DP_AXIS, iter_size: int = 1
) -> NamedSharding:
    if iter_size > 1:
        return NamedSharding(mesh, P(None, None, dp_axis))
    return NamedSharding(mesh, P(None, dp_axis))
