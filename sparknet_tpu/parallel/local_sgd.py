"""τ-local SGD with periodic parameter averaging — SparkNet's algorithm.

The reference's central contribution (SparkNet paper, arXiv:1511.06051;
SURVEY.md §1 "core algorithm"; mount empty, no file:line): each worker
runs τ *independent* SGD steps on its own data shard, then the driver
averages the weights — trading gradient staleness for a τ× reduction in
communication rounds.  There, one round is JNI weight copy -> Spark
treeReduce over TCP -> broadcast.  Here a round is at most TWO compiled
XLA programs under ``shard_map``: each device runs its τ steps as a
``lax.scan`` (no host involvement between steps), then the round-end
weight average runs through :mod:`.comm` — bucketed, optionally
compressed (bf16/int8 + error feedback), and dispatched as its own
program so the timeline can attribute the *exposed* reduction time to
the ``grad_allreduce`` phase (``SPARKNET_COMM=monolithic`` restores the
old single-program round with one fused ``lax.pmean``, the A/B
baseline).  Per-worker solver state (momentum etc.) persists across
rounds without averaging, matching the reference where each executor
keeps its native Caffe solver alive between syncs.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nets.xlanet import XLANet
from ..proto.caffe_pb import SolverParameter
from ..solver.caffe_solver import init_opt_state, make_update_fn, mults_for_params
from ..solver.trainer import accumulate_grads, make_grad_fn, step_compile_kw
from . import comm
from .mesh import DP_AXIS

# opt-state key carrying the error-feedback residual stack (leading
# worker axis, like the solver slots); present only when --grad-compress
# is lossy, so lossless opt state stays bit-compatible with pre-comm
# snapshots
RESIDUAL_KEY = "comm_residual"


def init_local_opt_state(sp: SolverParameter, params: Any, num_workers: int):
    """Per-worker solver state: leading axis = dp mesh size (each worker's
    momentum lives on its own device, like each executor's native solver)."""
    single = init_opt_state(sp, params)
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (num_workers,) + x.shape), single
    )


def init_local_residual(params: Any, num_workers: int):
    """Per-worker error-feedback residuals (each worker quantizes its
    own delta, so each carries its own error), zeros at start."""
    single = comm.init_residual(params)
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (num_workers,) + x.shape), single
    )


def _scan_tau_steps(net, sp, tau, dp_axis):
    """The shared per-worker τ-step scan body: params/state arrive
    replicated, diverge locally; returns the un-averaged end-of-round
    worker values plus τ-mean metrics (pmean'd)."""
    grad_fn = make_grad_fn(net)
    specs = net.param_specs()

    def scan(params, state, opt_state, batches, it, rng):
        # params/state arrive replicated but immediately diverge per
        # worker (local updates): mark them device-varying for shard_map's
        # replication typing so the scan carry has a stable type.
        params = comm.pcast_varying(params, dp_axis)
        state = comm.pcast_varying(state, dp_axis)
        # inside shard_map: opt_state leading worker-axis is local size 1
        opt_local = jax.tree_util.tree_map(lambda x: x[0], opt_state)
        lr_m, dec_m = mults_for_params(params, specs)
        update = make_update_fn(sp, lr_m, dec_m)
        widx = lax.axis_index(dp_axis)
        wrng = jax.random.fold_in(rng, widx)

        def grads_of(p, st, micro, step_rng):
            """One iteration's gradient; Caffe iter_size accumulation
            when the extra micro-batch axis is present."""
            if sp.iter_size > 1:
                return accumulate_grads(grad_fn, p, st, micro, step_rng)
            return grad_fn(p, st, micro, step_rng)

        def body(carry, micro):
            p, st, opt, i = carry
            g, st2, metrics = grads_of(p, st, micro, jax.random.fold_in(wrng, i))
            p2, opt2 = update(p, g, opt, it + i)
            return (p2, st2, opt2, i + 1), metrics

        (p, st, opt_local, _), mstack = lax.scan(
            body, (params, state, opt_local, 0), batches, length=tau
        )
        metrics = lax.pmean(
            jax.tree_util.tree_map(lambda m: jnp.mean(m, 0), mstack), dp_axis
        )
        return p, st, opt_local, metrics

    return scan


def _batch_spec(sp: SolverParameter, dp_axis: str):
    return P(None, None, dp_axis) if sp.iter_size > 1 else P(None, dp_axis)


def make_local_sgd_round(
    net: XLANet,
    sp: SolverParameter,
    mesh: Mesh,
    tau: int,
    dp_axis: str = DP_AXIS,
    donate: bool = True,
) -> Callable:
    """The MONOLITHIC single-dispatch round (the pre-comm baseline and
    the ``SPARKNET_COMM=monolithic`` A/B arm):

    ``round(params, state, opt_state, batches, it, rng)
        -> (params, state, opt_state, metrics)``

    - ``params``/``state``: replicated in, replicated (averaged) out —
      like the reference, worker nets are averaged wholesale at sync
      (state, e.g. BN running stats, is averaged alongside weights).
    - ``opt_state``: from :func:`init_local_opt_state` — leading axis is
      the worker axis, sharded over ``dp``; persists un-averaged.
    - ``batches``: pytree with leaves shaped ``[tau, global_bs, ...]``
      (or ``[tau, iter_size, global_bs, ...]`` when ``sp.iter_size > 1``);
      the global batch axis is sharded over ``dp`` so each worker scans
      over its own ``[tau, local_bs, ...]`` shard.
    - ``it``: int32 global iteration at round start (advances by tau).
    """
    scan = _scan_tau_steps(net, sp, tau, dp_axis)

    def per_worker(params, state, opt_state, batches, it, rng):
        p, st, opt_local, metrics = scan(
            params, state, opt_state, batches, it, rng
        )
        # SparkNet's sync: elementwise average of worker weights — one
        # ICI all-reduce instead of a driver TCP round-trip.
        p = lax.pmean(p, dp_axis)
        st = lax.pmean(st, dp_axis)  # BN running stats etc.
        opt_out = jax.tree_util.tree_map(lambda x: x[None], opt_local)
        return p, st, opt_out, metrics

    fn = comm.shard_map(
        per_worker,
        mesh=mesh,
        in_specs=(P(), P(), P(dp_axis), _batch_spec(sp, dp_axis), P(), P()),
        out_specs=(P(), P(), P(dp_axis), P()),
    )
    return comm.jit_manual(
        fn, donate_argnums=(0, 1, 2) if donate else (), **step_compile_kw()
    )


def make_local_scan(
    net: XLANet,
    sp: SolverParameter,
    mesh: Mesh,
    tau: int,
    dp_axis: str = DP_AXIS,
    donate: bool = True,
) -> Callable:
    """The bucketed round's FIRST program: the τ-step scan only, no
    averaging.

    ``scan(params, state, opt_state, batches, it, rng) ->
        (params, p_stack, st_stack, opt_state, metrics)``

    ``p_stack``/``st_stack`` carry each worker's un-averaged end-of-
    round values (leading worker axis, dp-sharded, same layout as
    ``opt_state``); ``params`` passes the round-start weights through
    untouched — the reduce program's reference point for compressed
    delta reduction (and a live buffer: the inputs are donated)."""
    scan = _scan_tau_steps(net, sp, tau, dp_axis)

    def per_worker(params, state, opt_state, batches, it, rng):
        p, st, opt_local, metrics = scan(
            params, state, opt_state, batches, it, rng
        )
        lift = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        return params, lift(p), lift(st), lift(opt_local), metrics

    fn = comm.shard_map(
        per_worker,
        mesh=mesh,
        in_specs=(P(), P(), P(dp_axis), _batch_spec(sp, dp_axis), P(), P()),
        out_specs=(P(), P(dp_axis), P(dp_axis), P(dp_axis), P()),
    )
    return comm.jit_manual(
        fn, donate_argnums=(0, 1, 2) if donate else (), **step_compile_kw()
    )


def make_round_reduce(
    mesh: Mesh,
    config: comm.CommConfig,
    dp_axis: str = DP_AXIS,
    donate: bool = True,
) -> Callable:
    """The bucketed round's SECOND program: SparkNet's weight average
    through the comm layer.

    ``reduce(p_start, p_stack, st_stack, residual_stack) ->
        (params, state, residual_stack)``

    Lossless (``compress="none"``): bucketed ``pmean`` of the worker
    weights directly — bitwise-identical to the monolithic round's
    average (tests/test_comm.py pins it).  Lossy (bf16/int8): each
    worker reduces its round DELTA (``p_end - p_start``) with error
    feedback — the residual rides ``opt_state["comm_residual"]`` and
    re-injects this round's quantization error into the next round.
    Tau-independent: one compile serves every round length."""
    ndp = mesh.shape[dp_axis]

    def per_worker(p_start, p_stack, st_stack, residual):
        drop = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
        lift = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        p_end, st_end = drop(p_stack), drop(st_stack)
        st, _ = comm.reduce_bucketed(
            st_end, dp_axis, ndp, comm.CommConfig(bucket_mb=config.bucket_mb)
        )
        if not config.wants_residual:
            p, _ = comm.reduce_bucketed(p_end, dp_axis, ndp, config)
            return p, st, residual
        delta = jax.tree_util.tree_map(lambda e, s: e - s, p_end, p_start)
        red, new_res = comm.reduce_bucketed(
            delta, dp_axis, ndp, config, residual=drop(residual)
        )
        p = jax.tree_util.tree_map(lambda s, d: s + d, p_start, red)
        return p, st, lift(new_res)

    fn = comm.shard_map(
        per_worker,
        mesh=mesh,
        in_specs=(P(), P(dp_axis), P(dp_axis), P(dp_axis)),
        out_specs=(P(), P(), P(dp_axis)),
    )
    return comm.jit_manual(
        fn, donate_argnums=(0, 1, 2, 3) if donate else (), **step_compile_kw()
    )


# --------------------------------------------------------------------------
# host-side round batch staging
# --------------------------------------------------------------------------

def stack_round_batches(batch_list, buffer: Optional["RoundBuffer"] = None):
    """Stack tau host batches into the ``[tau, global_bs, ...]`` layout.

    Stacks on the host (numpy): the caller's device_put then shards the
    result straight onto the mesh, instead of committing the full round
    batch to device 0 first and re-transferring.  With a
    :class:`RoundBuffer` the destination is a preallocated rotating
    buffer instead of a fresh ``np.stack`` allocation per round."""
    if buffer is not None:
        out = buffer.stack(batch_list)
        if out is not None:
            return out
    import numpy as np

    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *batch_list
    )


class RoundBuffer:
    """Preallocated host staging for :func:`stack_round_batches`.

    ``np.stack`` allocates (and the allocator churns) a fresh
    ``[tau, ...]`` round batch every round; this keeps a small rotation
    of destination buffers per ``(key, n, shape, dtype)`` and copies
    into the next one.  Depth 3: a buffer is only rewritten three
    rounds later, past any plausible async-dispatch runahead — round
    N+1's program consumes round N's output params, so device execution
    serializes per round and the host can run at most the dispatch
    queue ahead (the CPU backend may alias a host buffer zero-copy,
    which is why "reuse immediately" would be wrong).

    Saved allocations are counted in the telemetry registry
    (``round_buffer{event=reuse|alloc}``) and surface through
    ``PipelineMetrics`` snapshots."""

    DEPTH = 3

    def __init__(self):
        self._bufs: Dict[tuple, list] = {}
        self._next: Dict[tuple, int] = {}

    def stack(self, batch_list):
        import numpy as np

        first = batch_list[0]
        if not isinstance(first, dict) or not all(
            isinstance(b, dict) and b.keys() == first.keys()
            for b in batch_list
        ):
            return None  # exotic pytree: fall back to np.stack
        from ..telemetry import REGISTRY

        out = {}
        n = len(batch_list)
        for k in first:
            rows = [np.asarray(b[k]) for b in batch_list]
            key = (k, n, rows[0].shape, rows[0].dtype.str)
            ring = self._bufs.get(key)
            if ring is None:
                ring = self._bufs[key] = []
            slot = self._next.get(key, 0)
            if len(ring) < self.DEPTH:
                ring.append(
                    np.empty((n,) + rows[0].shape, dtype=rows[0].dtype)
                )
                buf = ring[-1]
                self._next[key] = len(ring) % self.DEPTH
                REGISTRY.counter("round_buffer", event="alloc").inc()
            else:
                buf = ring[slot]
                self._next[key] = (slot + 1) % self.DEPTH
                REGISTRY.counter("round_buffer", event="reuse").inc()
            for t, r in enumerate(rows):
                if r.shape != rows[0].shape or r.dtype != rows[0].dtype:
                    return None  # ragged round: let np.stack raise/handle
                buf[t] = r
            out[k] = buf
        return out


def round_batch_sharding(
    mesh: Mesh, dp_axis: str = DP_AXIS, iter_size: int = 1
) -> NamedSharding:
    if iter_size > 1:
        return NamedSharding(mesh, P(None, None, dp_axis))
    return NamedSharding(mesh, P(None, dp_axis))
