"""ParallelSolver: the multi-chip training driver.

Plays the role of the reference's Spark driver program (SURVEY.md §1-3:
broadcast -> mapPartitions(train) -> reduce/average; mount empty, no
file:line), with the driver logic compiled away: placement is a mesh
sharding, broadcast is replication, and the average is an in-program
collective.  Two modes:

- ``mode="sync"``  — one global batch per iteration, gradient
  all-reduce inside the step (modern synchronous DP; the better
  default on a TPU pod where ICI makes sync cheap).
- ``mode="local"`` — SparkNet's τ-local SGD: each mesh ``dp`` slice
  runs τ independent steps, then weights are averaged.  The τ knob
  reproduces the paper's communication/staleness tradeoff — and with
  ``tau="auto"`` becomes a telemetry-driven control loop
  (:mod:`.tau_controller`).

Communication in both modes routes through :mod:`.comm` (bucketed
reduction, optional bf16/int8 compression with error-feedback
residuals in opt state); ``SPARKNET_COMM=monolithic`` restores the
pre-bucketing fused all-reduce as the A/B baseline.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp

from ..proto import caffe_pb
from ..solver.trainer import Solver
from . import comm as comm_mod
from .data_parallel import make_dp_eval_step, make_dp_train_step
from .local_sgd import (
    RESIDUAL_KEY,
    RoundBuffer,
    init_local_opt_state,
    init_local_residual,
    make_local_scan,
    make_local_sgd_round,
    make_round_reduce,
    round_batch_sharding,
    stack_round_batches,
)
from ..telemetry import anomaly as _anomaly
from .mesh import DP_AXIS, batch_sharding, make_mesh, replicate
from .tau_controller import TauController, parse_tau
from . import multihost
from . import partition as partition_mod


class ParallelSolver(Solver):
    def __init__(
        self,
        solver: caffe_pb.SolverParameter,
        input_shapes: Dict[str, Tuple[int, ...]],
        *,
        mesh: Optional[jax.sharding.Mesh] = None,
        mode: str = "sync",
        tau=1,
        dp_axis: str = DP_AXIS,
        comm_config: Optional[comm_mod.CommConfig] = None,
        layout: Optional[Any] = None,
        layout_rules: str = "auto",
        **kw: Any,
    ):
        """``layout``: a :class:`~sparknet_tpu.parallel.partition.Layout`
        (or a ``"dp=2,tp=2"`` axes string resolved against
        ``layout_rules`` — ``"auto"`` picks the ``"bert"`` ruleset for
        model-protocol nets and ``"tp"`` for prototxt nets).  With a
        layout, sync training compiles through the unified
        rule-table/NamedSharding path (parallel/partition.py): any
        dp×tp×ep combination is a table entry, no new step builder.
        ``mode="local"`` (τ-local SGD) and bucketed/compressed sync
        comm remain dp-only and accept only dp-shaped layouts."""
        if kw.get("batch_transform") is not None:
            # the parallel modes build their own train steps below,
            # which would silently drop the transform — reject, per the
            # base Solver's can't-believe-it-took-effect policy
            raise ValueError(
                "batch_transform (device-side augmentation) is not "
                "supported by ParallelSolver — use the base Solver"
            )
        if isinstance(layout, str):
            rules = layout_rules
            if rules == "auto":
                rules = "bert" if kw.get("model") is not None else "tp"
            layout = partition_mod.parse_layout(layout, rules=rules)
        self.layout: Optional[partition_mod.Layout] = layout
        self._plan: Optional[partition_mod.Plan] = None
        super().__init__(solver, input_shapes, **kw)
        # the parallel step builders below own their dispatch shape
        # (sharded batches, explicit reduce programs): the base
        # solver's fused host-dispatch wrapper must never shadow them
        self._fuse_host = False
        if mesh is None:
            mesh = layout.mesh() if layout is not None else make_mesh()
        self.mesh = mesh
        self.mode = mode
        self.comm = (
            comm_config if comm_config is not None
            else comm_mod.resolve_config()
        )
        # recorded into the solverstate (Solver.save env_meta): resuming
        # under a different wire format warns through the existing
        # env-drift machinery, on top of the residual reconciliation
        self.env_meta["grad_compress"] = self.comm.compress
        tau0, tau_auto = parse_tau(tau)
        self.tau = int(tau0)
        self.tau_controller: Optional[TauController] = None
        if tau_auto:
            if mode == "sync":
                raise ValueError(
                    "tau='auto' drives local-SGD round length — it "
                    "needs mode='local' (--parallel local)"
                )
            self.tau_controller = TauController(tau=self.tau)
            self.tau = self.tau_controller.tau
        if mode != "sync" and self.tau > 1:
            # local-SGD materialises only per-round tau-means, so the
            # display window is in ROUNDS: ceil(average_loss / tau)
            # rounds ≈ the last average_loss iterations
            from collections import deque

            n_rounds = -(-max(1, solver.average_loss) // self.tau)
            self._loss_window = deque(maxlen=n_rounds)
        self.dp_axis = dp_axis
        ndp = self.mesh.shape.get(dp_axis, 1)
        for which, xnet in (("train", self.train_net), ("test", self.test_net)):
            for name in xnet.input_names:
                bs = xnet.blob_shapes[name][0]
                if bs % ndp:
                    raise ValueError(
                        f"{which} input {name!r}: batch {bs} not divisible "
                        f"by dp={ndp}"
                    )
        if self.layout is not None:
            non_dp = [
                f"{a}={s}" for a, s in self.mesh.shape.items()
                if a != dp_axis and s > 1
            ]
            if non_dp and mode == "local":
                raise ValueError(
                    "mode='local' (τ-local SGD averaging) is dp-only; "
                    f"layout has non-trivial axes {non_dp} — use "
                    "mode='sync' for model-parallel layouts"
                )
            if non_dp and (
                self.comm.for_sync() == "bucketed" if mode == "sync" else False
            ):
                raise ValueError(
                    "bucketed/compressed sync comm is an explicit dp "
                    f"shard_map program; layout axes {non_dp} need the "
                    "unified path — drop --grad-compress / "
                    "SPARKNET_COMM=bucketed"
                )
            if mode == "sync" and self.comm.for_sync() != "bucketed":
                self._plan = partition_mod.make_plan(
                    self.layout, self.params, self.state, solver,
                    mesh=self.mesh,
                )
            # snapshots carry the layout + per-leaf specs so a resume
            # under a different layout warns and relayouts explicitly;
            # a live reshard re-records both (reshard.py) so snapshots
            # taken after the migration carry the NEW layout
            self._record_layout_env()
        if self._plan is not None:
            self.params = partition_mod.place(
                self.params, self._plan.params_sh
            )
            self.state = partition_mod.place(self.state, self._plan.state_sh)
        else:
            self.params = replicate(self.params, self.mesh)
            self.state = replicate(self.state, self.mesh)
        # multi-host: each process feeds its local rows; _put_batch
        # assembles them into globally-sharded arrays
        self._multihost = jax.process_count() > 1
        if self._plan is not None:
            self._eval_sharding = self._plan.batch_eval_sh
            self._train_sharding = self._plan.batch_train_sh
        else:
            self._eval_sharding = batch_sharding(self.mesh, dp_axis)
            if solver.iter_size > 1:
                self._train_sharding = jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec(None, dp_axis)
                )
            else:
                self._train_sharding = self._eval_sharding
        if mode == "sync":
            if self._plan is not None:
                self.opt_state = partition_mod.place(
                    self.opt_state, self._plan.opt_sh
                )
                self._train_step = partition_mod.make_sharded_train_step(
                    self.train_net, solver, self._plan
                )
                self._eval_step = partition_mod.make_sharded_eval_step(
                    self.test_net, self._plan
                )
            else:
                self.opt_state = replicate(self.opt_state, self.mesh)
                if (
                    self.comm.for_sync() == "bucketed"
                    and self.comm.wants_residual
                ):
                    self.opt_state[RESIDUAL_KEY] = jax.device_put(
                        init_local_residual(self.params, ndp),
                        self._dp_sharding(),
                    )
                self._train_step = make_dp_train_step(
                    self.train_net, solver, self.mesh, dp_axis,
                    config=self.comm,
                )
                self._eval_step = make_dp_eval_step(
                    self.test_net, self.mesh, dp_axis
                )
            comm_mod.count_reduction(self.comm, self.params, "sync_grads")
        elif mode == "local":
            if self.tau < 1:
                raise ValueError(f"tau must be >= 1, got {self.tau}")
            opt_state = init_local_opt_state(solver, self.params, ndp)
            if (
                self.comm.for_local() == "bucketed"
                and self.comm.wants_residual
            ):
                opt_state[RESIDUAL_KEY] = init_local_residual(
                    self.params, ndp
                )
            self.opt_state = jax.device_put(opt_state, self._dp_sharding())
            # round fns keyed by effective tau: the last round of a
            # step(n) with n % tau != 0 runs a shorter compiled round
            # rather than overshooting n.  Bucketed rounds split into a
            # per-tau scan and ONE tau-independent reduce program.
            self._rounds: Dict[int, Any] = {}
            self._reduce_fn = (
                make_round_reduce(self.mesh, self.comm, dp_axis)
                if self.comm.for_local() == "bucketed" else None
            )
            self._round_buffer = RoundBuffer()
            self._batch_sharding = round_batch_sharding(
                self.mesh, dp_axis, solver.iter_size
            )
            self._eval_step = make_dp_eval_step(self.test_net, self.mesh, dp_axis)
            comm_mod.count_reduction(self.comm, self.params, "round_average")
        else:
            raise ValueError(f"mode {mode!r} (want 'sync' or 'local')")
        if self.tau_controller is not None and not self.timeline.enabled:
            # the controller's widen signal IS the timeline's sync share
            # — auto-tau implies attribution even without --trace
            from ..telemetry import timeline as _ttl

            self.timeline = _ttl.Timeline(fence=True)
            _ttl.set_current(self.timeline)
            self.timeline.start()

    # ------------------------------------------------------------------
    def _record_layout_env(self) -> None:
        """(Re)write the snapshot env's layout + per-leaf specs from the
        solver's CURRENT layout — called at construction and again by
        every live reshard, so a snapshot always resumes into the
        layout the job was actually running."""
        if self.layout is None:
            return
        import json as _json

        self.env_meta["layout"] = partition_mod.layout_to_json(self.layout)
        specs = (
            self._plan.specs if self._plan is not None
            else partition_mod.specs_record(
                self.params, self.layout.rules, self.mesh
            )
        )
        self.env_meta["param_specs"] = _json.dumps(specs, sort_keys=True)

    def reshard(self, new_layout, *, reason: str = "explicit"):
        """Migrate this running solver to ``new_layout`` in place —
        see :func:`sparknet_tpu.parallel.reshard.reshard`."""
        from . import reshard as reshard_mod

        return reshard_mod.reshard(self, new_layout, reason=reason)

    def _dp_sharding(self):
        return jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(self.dp_axis)
        )

    def layout_report(self) -> Optional[Dict[str, Any]]:
        """Machine-readable layout record for the apps' ``layout:``
        line: mesh shape, rule count, sharded/replicated leaf counts
        and the layout fingerprint (None without a layout)."""
        if self.layout is None:
            return None
        if self._plan is not None:
            out = self._plan.report()
            out["path"] = "unified"
            return out
        out = {
            "name": self.layout.name,
            "mesh": dict(self.mesh.shape),
            "rules": len(self.layout.rules),
            "fingerprint": partition_mod.layout_fingerprint(self.layout),
            "path": f"legacy-{self.mode}",
        }
        return out

    def _env_drift_message(self, key, saved, cur) -> str:
        if key == "param_specs":
            return ""  # the layout key carries the aggregated notice
        if key == "layout":
            import json as _json

            saved_name = "unknown"
            try:
                d = _json.loads(saved)
                saved_name = f"{d.get('name')}:{dict(d.get('axes') or [])}"
            except (TypeError, ValueError):
                pass
            cur_specs = (
                self._plan.specs if self._plan is not None
                else partition_mod.specs_record(
                    self.params, self.layout.rules, self.mesh
                )
            )
            saved_specs = str(
                (getattr(self, "_restored_env", None) or {}).get(
                    "param_specs", ""
                )
            )
            return partition_mod.relayout_warning(
                saved_specs,
                cur_specs,
                saved_layout=saved_name,
                current_layout=(
                    f"{self.layout.name}:{dict(self.mesh.shape)}"
                ),
            )
        return super()._env_drift_message(key, saved, cur)

    def scan_steps(self, batch, n: int):
        """Not supported: the base implementation scans the
        SINGLE-DEVICE train step, which would silently bypass this
        solver's dp/local-SGD program (and local mode's per-worker
        opt_state layout). Local-SGD rounds are already one compiled
        scan over tau steps — bench parallel modes through step()."""
        raise NotImplementedError(
            "ParallelSolver.scan_steps: use step(); local-SGD rounds "
            "already run as one compiled scan over tau iterations"
        )

    # ------------------------------------------------------------------
    def _put_batch(self, batch, train: bool = True):
        """sync mode: jit's in_shardings place single-host batches; with
        multiple processes each host contributes only its local rows, so
        the global array must be assembled explicitly."""
        if not self._multihost:
            return batch
        sharding = self._train_sharding if train else self._eval_sharding
        return multihost.put_global(batch, sharding)

    def _wants_residual(self) -> bool:
        active = (
            self.comm.for_local() if self.mode == "local"
            else self.comm.for_sync()
        )
        return active == "bucketed" and self.comm.wants_residual

    def _reconcile_residual(self, opt_state):
        """Snapshot <-> config drift: a pre-comm (or --grad-compress
        none) snapshot restored into a lossy run gets fresh zero
        residuals; a lossy snapshot restored into a lossless run drops
        them.  Either way the restore proceeds with a warning instead
        of a KeyError deep inside the compiled step."""
        wants, has = self._wants_residual(), RESIDUAL_KEY in opt_state
        if wants and not has:
            if jax.process_index() == 0:
                print(
                    "WARNING: snapshot carries no error-feedback "
                    "residuals (taken without --grad-compress?) — "
                    "starting compression from zero residuals",
                    file=sys.stderr, flush=True,
                )
            ndp = self.mesh.shape[self.dp_axis]
            opt_state = dict(opt_state)
            opt_state[RESIDUAL_KEY] = init_local_residual(self.params, ndp)
        elif has and not wants:
            if jax.process_index() == 0:
                print(
                    "WARNING: dropping the snapshot's error-feedback "
                    "residuals (--grad-compress is off in this run)",
                    file=sys.stderr, flush=True,
                )
            opt_state = {
                k: v for k, v in opt_state.items() if k != RESIDUAL_KEY
            }
        return opt_state

    def _place_restored(self, params, state, opt_state):
        if self._plan is not None:
            # relayout-on-resume: leaves land wherever the RUN's rule
            # table puts them, whatever the snapshot's layout was (the
            # env-drift hook prints the aggregated warning)
            if opt_state:
                opt_state = self._reconcile_residual(opt_state)
            return (
                partition_mod.place(params, self._plan.params_sh),
                partition_mod.place(state, self._plan.state_sh),
                partition_mod.place(opt_state, self._plan.opt_sh)
                if opt_state else opt_state,
            )
        params = replicate(params, self.mesh)
        state = replicate(state, self.mesh)
        if opt_state:
            opt_state = self._reconcile_residual(opt_state)
        if self.mode == "sync":
            resid = None
            if RESIDUAL_KEY in opt_state:
                opt_state = dict(opt_state)
                resid = opt_state.pop(RESIDUAL_KEY)
            opt_state = replicate(opt_state, self.mesh)
            if resid is not None:
                opt_state[RESIDUAL_KEY] = jax.device_put(
                    resid, self._dp_sharding()
                )
        else:  # local: per-dp-slice optimizer slots, sharded on dp
            opt_state = jax.device_put(opt_state, self._dp_sharding())
        return params, state, opt_state

    def _reinit_opt_state(self):
        """Elastic weights-only resume: a snapshot taken at a different
        dp width carries incompatible slot layouts (local mode's
        per-dp-slice leading axis) — rebuild fresh slots in THIS
        solver's layout instead."""
        from ..solver.caffe_solver import init_opt_state

        ndp = self.mesh.shape.get(self.dp_axis, 1)
        if self._plan is not None:
            return partition_mod.place(
                init_opt_state(self.sp, self.params), self._plan.opt_sh
            )
        if self.mode == "sync":
            opt = replicate(init_opt_state(self.sp, self.params), self.mesh)
            if self._wants_residual():
                opt[RESIDUAL_KEY] = jax.device_put(
                    init_local_residual(self.params, ndp),
                    self._dp_sharding(),
                )
            return opt
        opt = init_local_opt_state(self.sp, self.params, ndp)
        if self._wants_residual():
            opt[RESIDUAL_KEY] = init_local_residual(self.params, ndp)
        return jax.device_put(opt, self._dp_sharding())

    def _round_fn(self, tau: int):
        """Per-tau compiled round program: the monolithic one-dispatch
        round, or (bucketed) the scan half of the two-program round."""
        if tau not in self._rounds:
            if self._reduce_fn is not None:
                self._rounds[tau] = make_local_scan(
                    self.train_net, self.sp, self.mesh, tau, self.dp_axis
                )
            else:
                self._rounds[tau] = make_local_sgd_round(
                    self.train_net, self.sp, self.mesh, tau, self.dp_axis
                )
        return self._rounds[tau]

    def _next_iteration_batch(self, batches):
        """One iteration's worth of host batches (iter_size micro-batches
        stacked on a leading axis when accumulating, Caffe-style)."""
        if self.sp.iter_size > 1:
            # NO round buffer here: the tau outer stacks copy these
            # inner stacks only at round end, so inner reuse within a
            # round (tau > buffer depth) would alias live data
            return stack_round_batches(
                [next(batches) for _ in range(self.sp.iter_size)]
            )
        return next(batches)

    def _split_residual(self, opt_state):
        if RESIDUAL_KEY not in opt_state:
            return opt_state, {}
        return (
            {k: v for k, v in opt_state.items() if k != RESIDUAL_KEY},
            opt_state[RESIDUAL_KEY],
        )

    def comm_report(self) -> Dict[str, Any]:
        """Machine-readable communication record for bench records and
        run reports: the active config, the bucket plan over THIS
        model's params, and the tau controller's decision log when one
        is driving."""
        leaves = jax.tree_util.tree_leaves(self.params)
        plan = comm_mod.plan_buckets(leaves, self.comm.bucket_bytes)
        mode = (
            self.comm.for_local() if self.mode == "local"
            else self.comm.for_sync()
        )
        out = {
            "mode": mode,
            "compress": self.comm.compress,
            "bucket_mb": self.comm.bucket_mb,
            "buckets": comm_mod.bucket_histogram(plan, leaves),
            "wire_bytes_per_reduction": comm_mod.wire_bytes(
                plan if mode == "bucketed"
                else ((tuple(range(len(leaves))),) if leaves else ()),
                leaves, self.comm.compress,
            ),
        }
        if self.tau_controller is not None:
            out["tau_controller"] = self.tau_controller.snapshot()
        return out

    def step(self, batches: Iterator[Dict[str, Any]], n: int = 1, log_fn=None):
        if self.mode == "sync":
            return super().step(batches, n, log_fn)
        metrics: Dict[str, Any] = {}
        end = self.iter + n
        tl = self.timeline  # same phase brackets as Solver.step: one
        # local-SGD round = tau iterations in one compiled dispatch;
        # bucketed comm adds the round-end reduce as its own dispatch,
        # bracketed grad_allreduce so the EXPOSED reduction time reads
        # off the table separately from multihost_sync's barrier time
        controller = self.tau_controller
        while self.iter < end:
            if self.stop_requested:
                break
            tau = min(self.tau, end - self.iter)
            with tl.phase("input_wait"):
                stacked = stack_round_batches(
                    [self._next_iteration_batch(batches) for _ in range(tau)],
                    buffer=self._round_buffer,
                )
            with tl.phase("device_put"):
                if self._multihost:
                    stacked = multihost.put_global(
                        stacked, self._batch_sharding
                    )
                else:
                    stacked = jax.device_put(stacked, self._batch_sharding)
            phases0 = tl.phase_seconds() if controller is not None else None
            wall0 = tl.wall_s if controller is not None else 0.0
            self.rng, step_rng = jax.random.split(self.rng)
            prev = self.iter
            it_arr = jnp.asarray(self.iter, jnp.int32)
            if self._reduce_fn is not None:
                opt_solver, resid = self._split_residual(self.opt_state)
                with tl.phase("compiled_step"):
                    p_start, p_stack, st_stack, opt_out, metrics = (
                        self._round_fn(tau)(
                            self.params, self.state, opt_solver,
                            stacked, it_arr, step_rng,
                        )
                    )
                    if tl.fence:
                        jax.block_until_ready(metrics)
                with tl.phase("grad_allreduce"):
                    self.params, self.state, resid = self._reduce_fn(
                        p_start, p_stack, st_stack, resid
                    )
                    if tl.fence:
                        jax.block_until_ready(self.params)
                self.opt_state = (
                    {**opt_out, RESIDUAL_KEY: resid}
                    if self._wants_residual() else opt_out
                )
            else:
                with tl.phase("compiled_step"):
                    self.params, self.state, self.opt_state, metrics = (
                        self._round_fn(tau)(
                            self.params,
                            self.state,
                            self.opt_state,
                            stacked,
                            it_arr,
                            step_rng,
                        )
                    )
                    if tl.fence:
                        jax.block_until_ready(metrics)
            self.iter += tau
            if controller is not None:
                # host sync per round — the controller's price, only
                # paid under --tau auto (the loss is about to be fetched
                # for display smoothing anyway on display rounds)
                phases1 = tl.phase_seconds()
                sync_s = sum(
                    phases1.get(k, 0.0) - (phases0 or {}).get(k, 0.0)
                    for k in ("grad_allreduce", "multihost_sync")
                )
                # anomaly advisory hook: only consumed single-process —
                # straggler advisories live on rank 0's board, and a
                # multi-host run needs every rank to pick the same τ
                # (consuming rank-0-only signal would diverge them)
                advisories = (
                    _anomaly.active("straggler")
                    if multihost.process_count() == 1 else None
                )
                self.tau = controller.observe_round(
                    round_s=max(tl.wall_s - wall0, 1e-9),
                    sync_s=sync_s,
                    loss=float(metrics.get("loss", 0.0)),
                    advisories=advisories,
                )
            d = self.sp.display
            if log_fn and d:
                # round metrics are already tau-means; the window holds
                # ceil(average_loss/tau) rounds (sized in __init__), so
                # the display covers ≈ the last average_loss iterations
                self._push_loss(metrics)
                if (self.iter // d) > (prev // d):
                    log_fn(self.iter, self._smoothed(metrics))
        return metrics
