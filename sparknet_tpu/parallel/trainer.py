"""ParallelSolver: the multi-chip training driver.

Plays the role of the reference's Spark driver program (SURVEY.md §1-3:
broadcast -> mapPartitions(train) -> reduce/average; mount empty, no
file:line), with the driver logic compiled away: placement is a mesh
sharding, broadcast is replication, and the average is an in-program
collective.  Two modes:

- ``mode="sync"``  — one global batch per iteration, gradient
  all-reduce inside the step (modern synchronous DP; the better
  default on a TPU pod where ICI makes sync cheap).
- ``mode="local"`` — SparkNet's τ-local SGD: each mesh ``dp`` slice
  runs τ independent steps, then weights are averaged.  The τ knob
  reproduces the paper's communication/staleness tradeoff.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp

from ..proto import caffe_pb
from ..solver.trainer import Solver
from .data_parallel import make_dp_eval_step, make_dp_train_step
from .local_sgd import (
    init_local_opt_state,
    make_local_sgd_round,
    round_batch_sharding,
    stack_round_batches,
)
from .mesh import DP_AXIS, batch_sharding, make_mesh, replicate
from . import multihost


class ParallelSolver(Solver):
    def __init__(
        self,
        solver: caffe_pb.SolverParameter,
        input_shapes: Dict[str, Tuple[int, ...]],
        *,
        mesh: Optional[jax.sharding.Mesh] = None,
        mode: str = "sync",
        tau: int = 1,
        dp_axis: str = DP_AXIS,
        **kw: Any,
    ):
        if kw.get("batch_transform") is not None:
            # the parallel modes build their own train steps below,
            # which would silently drop the transform — reject, per the
            # base Solver's can't-believe-it-took-effect policy
            raise ValueError(
                "batch_transform (device-side augmentation) is not "
                "supported by ParallelSolver — use the base Solver"
            )
        super().__init__(solver, input_shapes, **kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.mode = mode
        self.tau = int(tau)
        if mode != "sync" and self.tau > 1:
            # local-SGD materialises only per-round tau-means, so the
            # display window is in ROUNDS: ceil(average_loss / tau)
            # rounds ≈ the last average_loss iterations
            from collections import deque

            n_rounds = -(-max(1, solver.average_loss) // self.tau)
            self._loss_window = deque(maxlen=n_rounds)
        self.dp_axis = dp_axis
        ndp = self.mesh.shape[dp_axis]
        for which, xnet in (("train", self.train_net), ("test", self.test_net)):
            for name in xnet.input_names:
                bs = xnet.blob_shapes[name][0]
                if bs % ndp:
                    raise ValueError(
                        f"{which} input {name!r}: batch {bs} not divisible "
                        f"by dp={ndp}"
                    )
        self.params = replicate(self.params, self.mesh)
        self.state = replicate(self.state, self.mesh)
        # multi-host: each process feeds its local rows; _put_batch
        # assembles them into globally-sharded arrays
        self._multihost = jax.process_count() > 1
        self._eval_sharding = batch_sharding(self.mesh, dp_axis)
        if solver.iter_size > 1:
            self._train_sharding = jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(None, dp_axis)
            )
        else:
            self._train_sharding = self._eval_sharding
        if mode == "sync":
            self.opt_state = replicate(self.opt_state, self.mesh)
            self._train_step = make_dp_train_step(
                self.train_net, solver, self.mesh, dp_axis
            )
            self._eval_step = make_dp_eval_step(self.test_net, self.mesh, dp_axis)
        elif mode == "local":
            if self.tau < 1:
                raise ValueError(f"tau must be >= 1, got {self.tau}")
            self.opt_state = jax.device_put(
                init_local_opt_state(solver, self.params, ndp),
                jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec(dp_axis)
                ),
            )
            # round fns keyed by effective tau: the last round of a
            # step(n) with n % tau != 0 runs a shorter compiled round
            # rather than overshooting n.
            self._rounds: Dict[int, Any] = {}
            self._batch_sharding = round_batch_sharding(
                self.mesh, dp_axis, solver.iter_size
            )
            self._eval_step = make_dp_eval_step(self.test_net, self.mesh, dp_axis)
        else:
            raise ValueError(f"mode {mode!r} (want 'sync' or 'local')")

    # ------------------------------------------------------------------
    def scan_steps(self, batch, n: int):
        """Not supported: the base implementation scans the
        SINGLE-DEVICE train step, which would silently bypass this
        solver's dp/local-SGD program (and local mode's per-worker
        opt_state layout). Local-SGD rounds are already one compiled
        scan over tau steps — bench parallel modes through step()."""
        raise NotImplementedError(
            "ParallelSolver.scan_steps: use step(); local-SGD rounds "
            "already run as one compiled scan over tau iterations"
        )

    # ------------------------------------------------------------------
    def _put_batch(self, batch, train: bool = True):
        """sync mode: jit's in_shardings place single-host batches; with
        multiple processes each host contributes only its local rows, so
        the global array must be assembled explicitly."""
        if not self._multihost:
            return batch
        sharding = self._train_sharding if train else self._eval_sharding
        return multihost.put_global(batch, sharding)

    def _place_restored(self, params, state, opt_state):
        params = replicate(params, self.mesh)
        state = replicate(state, self.mesh)
        if self.mode == "sync":
            opt_state = replicate(opt_state, self.mesh)
        else:  # local: per-dp-slice optimizer slots, sharded on dp
            opt_state = jax.device_put(
                opt_state,
                jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec(self.dp_axis)
                ),
            )
        return params, state, opt_state

    def _reinit_opt_state(self):
        """Elastic weights-only resume: a snapshot taken at a different
        dp width carries incompatible slot layouts (local mode's
        per-dp-slice leading axis) — rebuild fresh slots in THIS
        solver's layout instead."""
        from ..solver.caffe_solver import init_opt_state

        if self.mode == "sync":
            return replicate(init_opt_state(self.sp, self.params), self.mesh)
        ndp = self.mesh.shape[self.dp_axis]
        return jax.device_put(
            init_local_opt_state(self.sp, self.params, ndp),
            jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(self.dp_axis)
            ),
        )

    def _round_fn(self, tau: int):
        if tau not in self._rounds:
            self._rounds[tau] = make_local_sgd_round(
                self.train_net, self.sp, self.mesh, tau, self.dp_axis
            )
        return self._rounds[tau]

    def _next_iteration_batch(self, batches):
        """One iteration's worth of host batches (iter_size micro-batches
        stacked on a leading axis when accumulating, Caffe-style)."""
        if self.sp.iter_size > 1:
            return stack_round_batches(
                [next(batches) for _ in range(self.sp.iter_size)]
            )
        return next(batches)

    def step(self, batches: Iterator[Dict[str, Any]], n: int = 1, log_fn=None):
        if self.mode == "sync":
            return super().step(batches, n, log_fn)
        metrics: Dict[str, Any] = {}
        end = self.iter + n
        tl = self.timeline  # same phase brackets as Solver.step: one
        # local-SGD round = tau iterations in one compiled dispatch, so
        # compiled_step here covers the whole round incl. the τ-sync
        # weight average (the on-device communication the paper's τ
        # analysis amortizes); put_global attributes multihost_sync
        while self.iter < end:
            if self.stop_requested:
                break
            tau = min(self.tau, end - self.iter)
            with tl.phase("input_wait"):
                stacked = stack_round_batches(
                    [self._next_iteration_batch(batches) for _ in range(tau)]
                )
            with tl.phase("device_put"):
                if self._multihost:
                    stacked = multihost.put_global(
                        stacked, self._batch_sharding
                    )
                else:
                    stacked = jax.device_put(stacked, self._batch_sharding)
            with tl.phase("compiled_step"):
                self.rng, step_rng = jax.random.split(self.rng)
                prev = self.iter
                self.params, self.state, self.opt_state, metrics = (
                    self._round_fn(tau)(
                        self.params,
                        self.state,
                        self.opt_state,
                        stacked,
                        jnp.asarray(self.iter, jnp.int32),
                        step_rng,
                    )
                )
                if tl.fence:
                    jax.block_until_ready(metrics)
            self.iter += tau
            d = self.sp.display
            if log_fn and d:
                # round metrics are already tau-means; the window holds
                # ceil(average_loss/tau) rounds (sized in __init__), so
                # the display covers ≈ the last average_loss iterations
                self._push_loss(metrics)
                if (self.iter // d) > (prev // d):
                    log_fn(self.iter, self._smoothed(metrics))
        return metrics
