"""Pipeline parallelism: GPipe-style microbatch pipelining over "pp".

No reference counterpart (SURVEY.md §2: data parallelism only). The
encoder layer stack shards over the ``"pp"`` mesh axis — rank ``r`` owns
layers ``[r*L/npp, (r+1)*L/npp)`` as *stacked* arrays (leading layer
axis, ``lax.scan`` inside the stage: one compiled layer body regardless
of depth). Microbatches march through stages with a neighbor
``ppermute`` per tick — the classic ``n_micro + npp - 1`` tick schedule
with bubble ticks at the ends. Embeddings and the MLM head are
replicated (computed on every rank; only stage 0's embedding output and
the last stage's loss carry gradients, so the pp-psum of grads is exact,
not double-counted).

Autodiff runs through the whole schedule: ``ppermute`` transposes to the
inverse permutation, giving the reverse-order backward pipeline for
free.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..solver.caffe_solver import make_update_fn, mults_for_params
from . import comm


def stack_layer_params(params: Dict[str, Dict[str, jax.Array]], num_layers: int):
    """Split BertMLM params into (stacked_layers, rest): the per-layer
    dicts become one dict of arrays with a leading layer axis."""
    layer_keys = [f"layer_{li:02d}" for li in range(num_layers)]
    names = params[layer_keys[0]].keys()
    stacked = {
        n: jnp.stack([params[k][n] for k in layer_keys]) for n in names
    }
    rest = {k: v for k, v in params.items() if k not in layer_keys}
    return stacked, rest


def unstack_layer_params(stacked, rest, num_layers: int):
    out = dict(rest)
    for li in range(num_layers):
        out[f"layer_{li:02d}"] = {n: v[li] for n, v in stacked.items()}
    return out


# expert stacks: leading layer axis shards over pp, the (now second)
# expert axis over ep
_EXPERT_NAMES = frozenset({"w_in", "b_in", "w_out", "b_out"})


def bert_pp_pspecs(model, pp_axis: str = "pp", ep_axis=None):
    """(stacked_spec, rest_spec): layer stack sharded on its leading
    axis over pp, everything else replicated. For a MoE config the
    layer dict holds expert stacks instead of dense FFN weights; with
    ``ep_axis`` those additionally shard their expert dim."""
    if getattr(model.cfg, "moe_num_experts", 0) > 0:
        ffn_names = ["router_w", "w_in", "b_in", "w_out", "b_out"]
    else:
        ffn_names = ["ffn_in_w", "ffn_in_b", "ffn_out_w", "ffn_out_b"]
    names = [
        "q_w", "q_b", "k_w", "k_b", "v_w", "v_b", "out_w", "out_b",
        "attn_ln_scale", "attn_ln_bias", *ffn_names,
        "ffn_ln_scale", "ffn_ln_bias",
    ]
    stacked_spec = {
        n: (
            P(pp_axis, ep_axis)
            if ep_axis and n in _EXPERT_NAMES
            else P(pp_axis)
        )
        for n in names
    }
    rest_spec = {
        "embeddings": {
            "word": P(), "position": P(), "token_type": P(),
            "ln_scale": P(), "ln_bias": P(),
        },
        "mlm_head": {
            "dense_w": P(), "dense_b": P(), "ln_scale": P(),
            "ln_bias": P(), "output_bias": P(),
        },
    }
    return stacked_spec, rest_spec


def _stage_apply(model, stacked_local, x, kv_mask, rng, train, stage, l_loc,
                 micro_idx):
    """Scan this rank's layers over x; returns (y, moe_aux_sum). rng
    folds in the *global* layer index (decorrelates across stages) and
    the microbatch index (decorrelates dropout across microbatches,
    matching the unpipelined baseline where every batch row draws
    independent mask values)."""

    def body(carry, layer_params):
        x, li, aux = carry
        lrng = None
        if rng is not None:
            lrng = jax.random.fold_in(
                jax.random.fold_in(rng, stage * l_loc + li), micro_idx
            )
        y, a = model.layer_apply_with_aux(
            layer_params, x, kv_mask, lrng, train
        )
        return (y, li + 1, aux + a), None

    (y, _, aux), _ = lax.scan(
        body, (x, 0, jnp.asarray(0.0, jnp.float32)), stacked_local
    )
    return y, aux


def make_pp_train_step(
    model,
    sp,
    mesh,
    n_micro: int,
    dp_axis: Optional[str] = None,
    pp_axis: str = "pp",
    ep_axis: Optional[str] = None,
):
    """Jitted ``step(params, opt_state, batch, it, rng)`` with the layer
    stack pipelined over ``pp`` (optionally composed with ``dp`` and,
    for MoE configs, ``ep``).

    ``params``/``opt_state`` use the *stacked* layout:
    ``{"layers": stacked, "rest": rest}`` from
    :func:`stack_layer_params`. ``batch`` is token-level
    (:func:`sparknet_tpu.data.text.mlm_feed_tokens`); its leading batch
    dim must divide ``n_micro`` (× dp).

    MoE composition: each stage scans its stacked expert layers; the
    router aux loss is accumulated per (stage, live microbatch) through
    the tick scan — the pipelined objective adds
    ``moe_aux_weight * mean_over_microbatches(sum_over_layers(aux))``,
    the microbatch-granular analogue of the unpipelined loss. With
    ``ep_axis`` the expert stacks shard their expert dim and tokens
    reach their expert's owner via the ``all_to_all`` inside
    :func:`~sparknet_tpu.parallel.moe.moe_ffn`, exactly as in
    :func:`~sparknet_tpu.parallel.expert.make_ep_train_step`.
    """
    cfg = model.cfg
    moe = getattr(cfg, "moe_num_experts", 0) > 0
    if ep_axis and not moe:
        raise ValueError("ep_axis given but the config has no MoE experts")
    if moe and model.ep_axis != ep_axis:
        raise ValueError(
            f"model.ep_axis ({model.ep_axis!r}) != ep_axis ({ep_axis!r}): "
            "build the model with BertMLM(..., ep_axis=ep_axis)"
        )
    nep = mesh.shape[ep_axis] if ep_axis else 1
    if moe and cfg.moe_num_experts % nep:
        raise ValueError(
            f"ep={nep} must divide moe_num_experts ({cfg.moe_num_experts})"
        )
    npp = mesh.shape[pp_axis]
    L = model.cfg.num_layers
    if L % npp:
        raise ValueError(f"pp={npp} must divide num_layers ({L})")
    l_loc = L // npp
    ndp = mesh.shape[dp_axis] if dp_axis else 1
    data_axes = (dp_axis,) if dp_axis else ()
    stacked_spec, rest_spec = bert_pp_pspecs(
        model, pp_axis, ep_axis if moe else None
    )
    pspec = {"layers": stacked_spec, "rest": rest_spec}

    # layer lr/decay multipliers, stacked layout: identical per layer
    l_specs = model.param_specs()["layer_00"]
    mult_tree = {
        "layers": {n: l_specs[n][0] for n in stacked_spec},
        "rest": {
            k: {n: s[0] for n, s in model.param_specs()[k].items()}
            for k in ("embeddings", "mlm_head")
        },
    }
    decay_tree = {
        "layers": {n: l_specs[n][1] for n in stacked_spec},
        "rest": {
            k: {n: s[1] for n, s in model.param_specs()[k].items()}
            for k in ("embeddings", "mlm_head")
        },
    }

    def local_step(params, opt_state, batch, it, rng):
        stage = lax.axis_index(pp_axis)
        if dp_axis:
            rng = jax.random.fold_in(rng, lax.axis_index(dp_axis))
        is_first = stage == 0
        is_last = stage == npp - 1
        perm = [(i, i + 1) for i in range(npp - 1)]

        def loss_fn(p):
            stacked, rest = p["layers"], p["rest"]
            x0, kv_mask, rng2 = model.embed(
                rest, batch, train=True, rng=rng
            )
            b = x0.shape[0]
            if b % n_micro:
                raise ValueError(f"batch {b} not divisible by {n_micro} micro")
            mb = b // n_micro
            s, h = x0.shape[1], x0.shape[2]
            x_micro = x0.reshape(n_micro, mb, s, h)
            mask_micro = kv_mask.reshape(n_micro, mb, s)
            ticks = n_micro + npp - 1

            def tick(carry, t):
                recv, outs, aux_acc = carry
                mi_in = jnp.clip(t, 0, n_micro - 1)
                inject = jnp.where(
                    is_first,
                    x_micro[mi_in].astype(jnp.float32),
                    recv.astype(jnp.float32),
                ).astype(x0.dtype)
                # each tick, stage s processes microbatch t - s; mask
                # for that microbatch (clamped during bubbles)
                mi_here = jnp.clip(t - stage, 0, n_micro - 1)
                y, aux = _stage_apply(
                    model, stacked, inject, mask_micro[mi_here], rng2,
                    True, stage, l_loc, mi_here,
                )
                # bubble ticks process clamped garbage whose outputs are
                # never consumed — their aux must not be either
                live_tick = jnp.logical_and(t >= stage, t - stage < n_micro)
                aux_acc = aux_acc + jnp.where(live_tick, aux, 0.0)
                recv_next = lax.ppermute(y, pp_axis, perm)
                # last stage emits microbatch t - (npp - 1)
                mi_out = t - (npp - 1)
                outs = jnp.where(
                    jnp.logical_and(is_last, mi_out >= 0)[..., None],
                    lax.dynamic_update_index_in_dim(
                        outs, y, jnp.clip(mi_out, 0, n_micro - 1), 0
                    ),
                    outs,
                )
                return (recv_next, outs, aux_acc), None

            outs0 = jnp.zeros((n_micro, mb, s, h), x0.dtype)
            recv0 = jnp.zeros((mb, s, h), x0.dtype)
            aux0 = jnp.asarray(0.0, jnp.float32)
            (_, outs, aux_acc), _ = lax.scan(
                tick, (recv0, outs0, aux0), jnp.arange(ticks)
            )
            xf = outs.reshape(b, s, h)
            nll, w, corr = model.token_loss_from_hidden(
                rest, xf, batch["mlm_labels"], batch["mlm_weights"]
            )
            # only the last stage's head output is real
            live = is_last.astype(jnp.float32)
            nll, corr = nll * live, corr * live
            w_tot = lax.psum(
                batch["mlm_weights"].astype(jnp.float32).sum(), data_axes
            ) if data_axes else batch["mlm_weights"].astype(jnp.float32).sum()
            # this stage's aux (already ep-pmean'd inside moe_ffn), mean
            # over microbatches; /ndp so the dp-psum of grads carries
            # the dp-mean (cf. make_ep_train_step)
            aux_mean = aux_acc / n_micro
            loss_local = nll / jnp.maximum(w_tot, 1.0)
            if moe:
                loss_local = (
                    loss_local + cfg.moe_aux_weight * aux_mean / ndp
                )
            return loss_local, (nll, w_tot, corr, aux_mean)

        grads, (nll, w_tot, corr, aux_mean) = jax.grad(
            loss_fn, has_aux=True
        )(params)
        if moe and ep_axis:
            # tokens are replicated over ep: the all_to_all transpose
            # accumulates one cotangent copy per ep rank into each
            # expert shard — normalise them (cf. make_ep_train_step);
            # non-expert leaves see identical grads on every ep rank
            grads = {
                "layers": {
                    n: g / nep if n in _EXPERT_NAMES else g
                    for n, g in grads["layers"].items()
                },
                "rest": grads["rest"],
            }
        # pp reduction: replicated leaves ("rest") have grads only on the
        # stage that used them (embed on 0 unless... actually embed runs
        # on every rank but only stage 0's output enters the pipeline, so
        # cotangents vanish elsewhere) -> psum over pp completes them.
        # stacked layers are pp-sharded: psum over data axes only.
        grads = {
            "layers": jax.tree_util.tree_map(
                (lambda g: lax.psum(g, data_axes)) if data_axes else (lambda g: g),
                grads["layers"],
            ),
            "rest": jax.tree_util.tree_map(
                lambda g: lax.psum(g, data_axes + (pp_axis,)),
                grads["rest"],
            ),
        }
        update = make_update_fn(sp, mult_tree, decay_tree)
        params, opt_state = update(params, grads, opt_state, it)
        red = lambda z: lax.psum(z, data_axes + (pp_axis,))
        denom = jnp.maximum(w_tot, 1.0)
        metrics = {"loss": red(nll) / denom, "mlm_acc": red(corr) / denom}
        if moe:
            # stages hold disjoint layers: psum over pp completes the
            # layer sum; dp shards see different tokens: mean
            aux_all = lax.psum(aux_mean, pp_axis)
            if data_axes:
                aux_all = lax.pmean(aux_all, data_axes)
            metrics["loss"] = metrics["loss"] + cfg.moe_aux_weight * aux_all
            metrics["moe_aux"] = aux_all
        return params, opt_state, metrics

    batch_axes = P(dp_axis) if dp_axis else P()
    batch_spec = {
        k: batch_axes
        for k in (
            "input_ids", "token_type_ids", "attention_mask",
            "position_ids", "mlm_labels", "mlm_weights",
        )
    }
    compiled = {}

    def stepper(params, opt_state, batch, it, rng):
        key = tuple(sorted(opt_state))
        if key not in compiled:
            ospec = {k: pspec for k in opt_state}
            compiled[key] = comm.jit_manual(
                comm.shard_map(
                    local_step,
                    mesh=mesh,
                    in_specs=(pspec, ospec, batch_spec, P(), P()),
                    out_specs=(pspec, ospec, P()),
                ),
                donate_argnums=(0, 1),
            )
        return compiled[key](params, opt_state, batch, it, rng)

    return stepper