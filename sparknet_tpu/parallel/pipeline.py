"""Pipeline parallelism: GPipe-style microbatch pipelining over "pp".

No reference counterpart (SURVEY.md §2: data parallelism only). The
encoder layer stack shards over the ``"pp"`` mesh axis — rank ``r`` owns
layers ``[r*L/npp, (r+1)*L/npp)`` as *stacked* arrays (leading layer
axis, ``lax.scan`` inside the stage: one compiled layer body regardless
of depth). Microbatches march through stages with a neighbor
``ppermute`` per tick — the classic ``n_micro + npp - 1`` tick schedule
with bubble ticks at the ends. Embeddings and the MLM head are
replicated (computed on every rank; only stage 0's embedding output and
the last stage's loss carry gradients, so the pp-psum of grads is exact,
not double-counted).

Autodiff runs through the whole schedule: ``ppermute`` transposes to the
inverse permutation, giving the reverse-order backward pipeline for
free.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..solver.caffe_solver import make_update_fn, mults_for_params


def stack_layer_params(params: Dict[str, Dict[str, jax.Array]], num_layers: int):
    """Split BertMLM params into (stacked_layers, rest): the per-layer
    dicts become one dict of arrays with a leading layer axis."""
    layer_keys = [f"layer_{li:02d}" for li in range(num_layers)]
    names = params[layer_keys[0]].keys()
    stacked = {
        n: jnp.stack([params[k][n] for k in layer_keys]) for n in names
    }
    rest = {k: v for k, v in params.items() if k not in layer_keys}
    return stacked, rest


def unstack_layer_params(stacked, rest, num_layers: int):
    out = dict(rest)
    for li in range(num_layers):
        out[f"layer_{li:02d}"] = {n: v[li] for n, v in stacked.items()}
    return out


def bert_pp_pspecs(model, pp_axis: str = "pp"):
    """(stacked_spec, rest_spec): layer stack sharded on its leading
    axis over pp, everything else replicated."""
    names = [
        "q_w", "q_b", "k_w", "k_b", "v_w", "v_b", "out_w", "out_b",
        "attn_ln_scale", "attn_ln_bias", "ffn_in_w", "ffn_in_b",
        "ffn_out_w", "ffn_out_b", "ffn_ln_scale", "ffn_ln_bias",
    ]
    stacked_spec = {n: P(pp_axis) for n in names}
    rest_spec = {
        "embeddings": {
            "word": P(), "position": P(), "token_type": P(),
            "ln_scale": P(), "ln_bias": P(),
        },
        "mlm_head": {
            "dense_w": P(), "dense_b": P(), "ln_scale": P(),
            "ln_bias": P(), "output_bias": P(),
        },
    }
    return stacked_spec, rest_spec


def _stage_apply(model, stacked_local, x, kv_mask, rng, train, stage, l_loc,
                 micro_idx):
    """Scan this rank's layers over x. rng folds in the *global* layer
    index (decorrelates across stages) and the microbatch index
    (decorrelates dropout across microbatches, matching the unpipelined
    baseline where every batch row draws independent mask values)."""

    def body(carry, layer_params):
        x, li = carry
        lrng = None
        if rng is not None:
            lrng = jax.random.fold_in(
                jax.random.fold_in(rng, stage * l_loc + li), micro_idx
            )
        y = model.layer_apply(layer_params, x, kv_mask, rng=lrng, train=train)
        return (y, li + 1), None

    (y, _), _ = lax.scan(body, (x, 0), stacked_local)
    return y


def make_pp_train_step(
    model,
    sp,
    mesh,
    n_micro: int,
    dp_axis: Optional[str] = None,
    pp_axis: str = "pp",
):
    """Jitted ``step(params, opt_state, batch, it, rng)`` with the layer
    stack pipelined over ``pp`` (optionally composed with ``dp``).

    ``params``/``opt_state`` use the *stacked* layout:
    ``{"layers": stacked, "rest": rest}`` from
    :func:`stack_layer_params`. ``batch`` is token-level
    (:func:`sparknet_tpu.data.text.mlm_feed_tokens`); its leading batch
    dim must divide ``n_micro`` (× dp).
    """
    if getattr(getattr(model, "cfg", None), "moe_num_experts", 0) > 0:
        raise NotImplementedError(
            "pipeline parallelism is not wired to the MoE FFN path (the "
            "stage pspecs and layer scan assume dense FFN params, and the "
            "router aux loss would be dropped)"
        )
    npp = mesh.shape[pp_axis]
    L = model.cfg.num_layers
    if L % npp:
        raise ValueError(f"pp={npp} must divide num_layers ({L})")
    l_loc = L // npp
    data_axes = (dp_axis,) if dp_axis else ()
    stacked_spec, rest_spec = bert_pp_pspecs(model, pp_axis)
    pspec = {"layers": stacked_spec, "rest": rest_spec}

    # layer lr/decay multipliers, stacked layout: identical per layer
    l_specs = model.param_specs()["layer_00"]
    mult_tree = {
        "layers": {n: l_specs[n][0] for n in stacked_spec},
        "rest": {
            k: {n: s[0] for n, s in model.param_specs()[k].items()}
            for k in ("embeddings", "mlm_head")
        },
    }
    decay_tree = {
        "layers": {n: l_specs[n][1] for n in stacked_spec},
        "rest": {
            k: {n: s[1] for n, s in model.param_specs()[k].items()}
            for k in ("embeddings", "mlm_head")
        },
    }

    def local_step(params, opt_state, batch, it, rng):
        stage = lax.axis_index(pp_axis)
        if dp_axis:
            rng = jax.random.fold_in(rng, lax.axis_index(dp_axis))
        is_first = stage == 0
        is_last = stage == npp - 1
        perm = [(i, i + 1) for i in range(npp - 1)]

        def loss_fn(p):
            stacked, rest = p["layers"], p["rest"]
            x0, kv_mask, rng2 = model.embed(
                rest, batch, train=True, rng=rng
            )
            b = x0.shape[0]
            if b % n_micro:
                raise ValueError(f"batch {b} not divisible by {n_micro} micro")
            mb = b // n_micro
            s, h = x0.shape[1], x0.shape[2]
            x_micro = x0.reshape(n_micro, mb, s, h)
            mask_micro = kv_mask.reshape(n_micro, mb, s)
            ticks = n_micro + npp - 1

            def tick(carry, t):
                recv, outs = carry
                mi_in = jnp.clip(t, 0, n_micro - 1)
                inject = jnp.where(
                    is_first,
                    x_micro[mi_in].astype(jnp.float32),
                    recv.astype(jnp.float32),
                ).astype(x0.dtype)
                # each tick, stage s processes microbatch t - s; mask
                # for that microbatch (clamped during bubbles)
                mi_here = jnp.clip(t - stage, 0, n_micro - 1)
                y = _stage_apply(
                    model, stacked, inject, mask_micro[mi_here], rng2,
                    True, stage, l_loc, mi_here,
                )
                recv_next = lax.ppermute(y, pp_axis, perm)
                # last stage emits microbatch t - (npp - 1)
                mi_out = t - (npp - 1)
                outs = jnp.where(
                    jnp.logical_and(is_last, mi_out >= 0)[..., None],
                    lax.dynamic_update_index_in_dim(
                        outs, y, jnp.clip(mi_out, 0, n_micro - 1), 0
                    ),
                    outs,
                )
                return (recv_next, outs), None

            outs0 = jnp.zeros((n_micro, mb, s, h), x0.dtype)
            recv0 = jnp.zeros((mb, s, h), x0.dtype)
            (_, outs), _ = lax.scan(
                tick, (recv0, outs0), jnp.arange(ticks)
            )
            xf = outs.reshape(b, s, h)
            nll, w, corr = model.token_loss_from_hidden(
                rest, xf, batch["mlm_labels"], batch["mlm_weights"]
            )
            # only the last stage's head output is real
            live = is_last.astype(jnp.float32)
            nll, corr = nll * live, corr * live
            w_tot = lax.psum(
                batch["mlm_weights"].astype(jnp.float32).sum(), data_axes
            ) if data_axes else batch["mlm_weights"].astype(jnp.float32).sum()
            loss_local = nll / jnp.maximum(w_tot, 1.0)
            return loss_local, (nll, w_tot, corr)

        grads, (nll, w_tot, corr) = jax.grad(loss_fn, has_aux=True)(params)
        # pp reduction: replicated leaves ("rest") have grads only on the
        # stage that used them (embed on 0 unless... actually embed runs
        # on every rank but only stage 0's output enters the pipeline, so
        # cotangents vanish elsewhere) -> psum over pp completes them.
        # stacked layers are pp-sharded: psum over data axes only.
        grads = {
            "layers": jax.tree_util.tree_map(
                (lambda g: lax.psum(g, data_axes)) if data_axes else (lambda g: g),
                grads["layers"],
            ),
            "rest": jax.tree_util.tree_map(
                lambda g: lax.psum(g, data_axes + (pp_axis,)),
                grads["rest"],
            ),
        }
        update = make_update_fn(sp, mult_tree, decay_tree)
        params, opt_state = update(params, grads, opt_state, it)
        red = lambda z: lax.psum(z, data_axes + (pp_axis,))
        denom = jnp.maximum(w_tot, 1.0)
        return params, opt_state, {
            "loss": red(nll) / denom, "mlm_acc": red(corr) / denom,
        }

    batch_axes = P(dp_axis) if dp_axis else P()
    batch_spec = {
        k: batch_axes
        for k in (
            "input_ids", "token_type_ids", "attention_mask",
            "position_ids", "mlm_labels", "mlm_weights",
        )
    }
    compiled = {}

    def stepper(params, opt_state, batch, it, rng):
        key = tuple(sorted(opt_state))
        if key not in compiled:
            ospec = {k: pspec for k in opt_state}
            compiled[key] = jax.jit(
                jax.shard_map(
                    local_step,
                    mesh=mesh,
                    in_specs=(pspec, ospec, batch_spec, P(), P()),
                    out_specs=(pspec, ospec, P()),
                    check_vma=False,
                ),
                donate_argnums=(0, 1),
            )
        return compiled[key](params, opt_state, batch, it, rng)

    return stepper