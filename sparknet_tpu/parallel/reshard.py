"""Live elastic resharding — migrate a running job between layouts.

PR 10 made a parallel layout pure data (an ordered regex rule table
compiled into per-leaf ``NamedSharding`` trees), and snapshots already
relayout-on-resume — but changing layout still cost a full process
restart (backend re-init, model rebuild, recompile).  This module is
the finishing move, the TensorFlow paper's dynamic re-placement of a
running dataflow (PAPERS.md, arXiv:1605.08695) applied to our
one-compiled-step world: :func:`reshard` recomputes the rule-table
trees for the new mesh, ``jax.device_put``\\ s the live params / BN
state / optimizer slots across (pure data movement — BITWISE
preserving, the same trick as relayout-on-resume, now without the
restart), re-jits through
:func:`~sparknet_tpu.parallel.partition.make_sharded_train_step`, and
atomically swaps the solver's compiled step the way the serve tier's
hot-swap exchanges weight pointers.

Compile-cache warmth: steps are cached per layout inside the solver,
keyed by the serve tier's ``net_fingerprint`` (which already folds the
layout fingerprint in) — resharding back to a layout seen earlier this
run reuses the SAME jitted callable, so no retrace, no recompile, and
when jax's persistent compilation cache is configured the on-disk
entries can never alias across layouts either.

Triggers (docs/PARALLELISM.md "Live resharding"):

- **explicit** — a request file (``SPARKNET_RESHARD_REQUEST``, or
  ``reshard_request.json`` in the supervisor's run dir for supervised
  children) polled by the training loop at chunk boundaries, mirroring
  how ``--auto-resume`` is driven today: the operator (or the
  supervisor) writes ``{"layout": "dp=2,tp=2", "at_iter": 200}`` and
  the job migrates in place at that boundary
  (:class:`RequestWatcher`);
- **degrade** — the supervisor's rank-blame path generalizes from
  "dp width−1" to :func:`degrade_layout`: the best rule-table entry
  for the surviving mesh (model-parallel axes preserved while they
  divide the surviving device budget);
- **advisory** — the tau controller raises a ``layout`` advisory when
  a local-SGD job stays sync-bound at ``SPARKNET_TAU_MAX`` (τ can't
  widen further; a different table entry is the remaining lever).

What stays restart-only: τ-local SGD and bucketed/compressed sync comm
(explicit dp-only ``shard_map`` programs), and multi-host width changes
(the supervisor relaunch path owns those — a live migration would need
every process to repartition its addressable shards in lockstep).

Imports are lazy throughout: the supervisor consumes
:func:`degrade_layout` without paying a jax import.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

RESHARD_PHASE = "reshard"
REQUEST_ENV = "SPARKNET_RESHARD_REQUEST"
REQUEST_NAME = "reshard_request.json"


class ReshardError(ValueError):
    """A live reshard this solver/layout combination cannot perform —
    the message names the restart-path alternative."""


# ---------------------------------------------------------------------------
# the migration
# ---------------------------------------------------------------------------

def _axes_str(layout) -> str:
    return ",".join(f"{a}={s}" for a, s in layout.axes)


def _check_reshardable(solver) -> None:
    from .trainer import ParallelSolver

    if not isinstance(solver, ParallelSolver) or solver.layout is None:
        raise ReshardError(
            "live resharding needs a ParallelSolver with a --layout "
            "(the unified rule-table path, docs/PARALLELISM.md); this "
            "solver has no layout to migrate from"
        )
    if solver.mode != "sync":
        raise ReshardError(
            "live resharding is sync-mode only: τ-local SGD (--parallel "
            "local, --tau auto included) runs explicit dp-only shard_map "
            "round programs that cannot be re-partitioned in place — "
            "snapshot and restart with --parallel sync --layout ..., or "
            "let relayout-on-resume migrate the snapshot"
        )
    if solver._plan is None:
        raise ReshardError(
            "live resharding needs the unified compile path; bucketed/"
            "compressed sync comm (--grad-compress / SPARKNET_COMM="
            "bucketed) is an explicit dp shard_map program — drop it to "
            "reshard live"
        )
    import jax

    if jax.process_count() > 1:
        raise ReshardError(
            "live resharding is single-process only: a multi-host width "
            "change must go through the supervisor's degrade/relaunch "
            "path (every process repartitions on relaunch; docs/"
            "MULTIHOST.md)"
        )


def resolve_layout(solver, new_layout):
    """An axes string (``"dp=2,tp=2"``) inherits the running layout's
    rule table / validation / batch axis — the table IS the policy, the
    mesh shape is what changes; a full :class:`Layout` passes through."""
    from . import partition

    if isinstance(new_layout, partition.Layout):
        return new_layout
    base = solver.layout
    return partition.Layout(
        axes=tuple(partition.parse_axes(str(new_layout)).items()),
        rules=base.rules,
        name=base.name,
        validate=base.validate,
        batch_axis=base.batch_axis,
    )


def _fingerprint(solver, layout) -> str:
    from ..serve.compile_cache import net_fingerprint

    return net_fingerprint(
        solver.train_net, solver.params, solver.state,
        getattr(solver.train_net, "compute_dtype", None), layout=layout,
    )


def _moved(old_specs: Dict[str, str], new_specs: Dict[str, str], tree):
    """(count, bytes) of the leaves whose partition spec changed — the
    data the migration actually relays (spec-identical leaves keep
    their placement; ``device_put`` is free to alias them)."""
    from . import partition

    flat = dict(partition.tree_paths(tree))
    moved = [k for k, s in new_specs.items() if old_specs.get(k) != s]
    nbytes = sum(
        flat[k].size * flat[k].dtype.itemsize for k in moved if k in flat
    )
    return len(moved), int(nbytes)


def reshard(solver, new_layout, *, reason: str = "explicit") -> Dict[str, Any]:
    """Migrate a running :class:`ParallelSolver` to ``new_layout`` in
    place: recompute the rule-table trees for the new mesh, ``device_put``
    params / net state / optimizer slots across (bitwise-preserving),
    and atomically swap the compiled train/eval steps.  Returns the
    machine-readable migration record (the ``reshard:`` line's payload).

    The per-layout step cache keeps reshards back to layouts seen
    earlier this run compile-free (``record["cache"] == "hit"``).
    """
    import jax

    from . import partition
    from ..telemetry.registry import REGISTRY
    from ..telemetry import timeline as _ttl

    _check_reshardable(solver)
    layout = resolve_layout(solver, new_layout)
    old_layout, old_plan = solver.layout, solver._plan
    fp = _fingerprint(solver, layout)

    cache = getattr(solver, "_reshard_cache", None)
    if cache is None:
        cache = solver._reshard_cache = {}
    # seed with the running layout so A -> B -> A is a hit on the way back
    cache.setdefault(_fingerprint(solver, old_layout), {
        "layout": old_layout, "plan": old_plan,
        "train_step": solver._train_step, "eval_step": solver._eval_step,
    })

    entry = cache.get(fp)
    cache_hit = entry is not None
    if entry is None:
        mesh = layout.mesh()
        plan = partition.make_plan(
            layout, solver.params, solver.state, solver.sp, mesh=mesh
        )
        ndp = mesh.shape.get(layout.batch_axis, 1)
        for which, xnet in (
            ("train", solver.train_net), ("test", solver.test_net)
        ):
            for name in xnet.input_names:
                bs = xnet.blob_shapes[name][0]
                if bs % ndp:
                    raise ReshardError(
                        f"{which} input {name!r}: batch {bs} not divisible "
                        f"by {layout.batch_axis}={ndp} in the requested "
                        f"layout {_axes_str(layout)}"
                    )
        entry = cache[fp] = {
            "layout": layout,
            "plan": plan,
            "train_step": partition.make_sharded_train_step(
                solver.train_net, solver.sp, plan
            ),
            "eval_step": partition.make_sharded_eval_step(
                solver.test_net, plan
            ),
        }
    layout, plan = entry["layout"], entry["plan"]

    # migration timing rides the telemetry timeline (one `reshard`
    # phase); an uninstrumented solver gets a private fenced timeline
    # so the record still carries an honest cost without ad-hoc clocks
    tl = solver.timeline if solver.timeline.enabled else _ttl.Timeline(
        fence=True
    )
    before_s = tl.phase_seconds().get(RESHARD_PHASE, 0.0)
    with tl.phase(RESHARD_PHASE):
        params = partition.place(solver.params, plan.params_sh)
        state = partition.place(solver.state, plan.state_sh)
        opt_state = (
            partition.place(solver.opt_state, plan.opt_sh)
            if solver.opt_state else solver.opt_state
        )
        # fence inside the phase: the migration cost is the data
        # movement, not whenever the next step happens to block
        jax.block_until_ready((params, state, opt_state))
    cost_s = tl.phase_seconds().get(RESHARD_PHASE, 0.0) - before_s

    leaves, nbytes = _moved(old_plan.specs, plan.specs, params)
    n_slots = len(plan.opt_sh)
    state_specs_old = partition.specs_record(
        state, old_layout.rules, old_plan.mesh
    )
    state_specs_new = partition.specs_record(state, layout.rules, plan.mesh)
    st_leaves, st_bytes = _moved(state_specs_old, state_specs_new, state)

    # ---- the atomic swap: every reference flips after the new trees
    # exist, so a failure above leaves the solver running under layout A
    solver.params, solver.state, solver.opt_state = params, state, opt_state
    solver._train_step = entry["train_step"]
    solver._eval_step = entry["eval_step"]
    solver._plan = plan
    solver.layout = layout
    solver.mesh = plan.mesh
    solver._eval_sharding = plan.batch_eval_sh
    solver._train_sharding = plan.batch_train_sh
    # snapshots taken from here on must carry the NEW layout + specs,
    # or a later --auto-resume would silently relayout backwards
    solver._record_layout_env()

    record = {
        "from": _axes_str(old_layout),
        "to": _axes_str(layout),
        "from_mesh": dict(old_plan.mesh.shape),
        "to_mesh": dict(plan.mesh.shape),
        "reason": reason,
        "relayout_ms": round(cost_s * 1e3, 3),
        "leaves_moved": leaves * (1 + n_slots) + st_leaves,
        "bytes_relaid": nbytes * (1 + n_slots) + st_bytes,
        "cache": "hit" if cache_hit else "miss",
        "fingerprint": fp,
    }
    REGISTRY.counter("reshard_events", **{
        "from": record["from"], "to": record["to"], "reason": reason,
    }).inc()
    return record


# ---------------------------------------------------------------------------
# degrade: the best table entry for a surviving mesh (supervisor path)
# ---------------------------------------------------------------------------

def degrade_layout(spec: str, full_width: int, new_width: int) -> str:
    """The supervisor's elastic generalization: given the job's
    declared layout axes and a width change (process count
    ``full_width`` -> ``new_width``), return the best table entry for
    the surviving mesh — model-parallel axes are preserved while their
    product divides the surviving device budget, halving the largest
    one until it fits, and the batch ("dp") axis absorbs the rest.
    Pure stdlib (the supervisor must stay importable without jax).

    ``degrade_layout("dp=4", 4, 3) == "dp=3"``;
    ``degrade_layout("dp=2,tp=4", 8, 4) == "dp=1,tp=4"`` (the tp block
    survives); scale-up back to ``full_width`` restores the original.
    """
    axes: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    if new_width >= full_width or any(s < 0 for s in axes.values()):
        # scale-up restores the declared layout; a -1 axis already
        # means "all remaining devices" and resolves at mesh build
        return ",".join(f"{a}={s}" for a, s in axes.items())
    total = 1
    for s in axes.values():
        total *= s
    budget = max(1, total * new_width // full_width)
    model = {a: s for a, s in axes.items() if a != "dp" and s > 1}
    while model:
        prod = 1
        for s in model.values():
            prod *= s
        if budget % prod == 0 and prod <= budget:
            break
        widest = max(model, key=lambda a: model[a])
        model[widest] //= 2
        if model[widest] <= 1:
            del model[widest]
    prod = 1
    for s in model.values():
        prod *= s
    out = {"dp": max(1, budget // prod)}
    out.update(model)
    # keep the declared axis order where it survives
    ordered = [a for a in axes if a in out] + [
        a for a in out if a not in axes
    ]
    return ",".join(f"{a}={out[a]}" for a in ordered)


# ---------------------------------------------------------------------------
# the explicit control surface: a request file polled by the train loop
# ---------------------------------------------------------------------------

def request_path() -> Optional[str]:
    """Where the training loop looks for reshard requests:
    ``SPARKNET_RESHARD_REQUEST`` names the file explicitly; a
    supervised child (``SPARKNET_SUPERVISE_DIR``) watches
    ``reshard_request.json`` in its run dir — the supervisor-side half
    of the control surface."""
    explicit = os.environ.get(REQUEST_ENV, "").strip()
    if explicit:
        return explicit
    run_dir = os.environ.get("SPARKNET_SUPERVISE_DIR", "").strip()
    if run_dir:
        return os.path.join(run_dir, REQUEST_NAME)
    return None


class RequestWatcher:
    """Polls the request file at training-chunk boundaries and fires
    :func:`reshard` in place.  A request is one JSON object (or a list
    of them): ``{"layout": "dp=2,tp=2", "at_iter": 200}`` — ``at_iter``
    (optional) delays the migration to that iteration boundary and
    joins the loop's chunk targets so the boundary actually lands
    there.  Consumed requests append their migration record (or error)
    to ``<path>.log`` as JSON lines, so the requester can read the
    outcome without scraping stdout."""

    def __init__(self, solver, path: str, log=print):
        self.solver = solver
        self.path = path
        self.log = log
        self._mtime: Optional[float] = None
        self._requests: List[Dict[str, Any]] = []
        self._done: set = set()
        self._warned_bad = False

    @classmethod
    def create(cls, solver, log=print) -> Optional["RequestWatcher"]:
        """The train loop's constructor: None (zero per-iteration cost)
        unless a request path is configured AND this solver can
        reshard.  An explicit ``SPARKNET_RESHARD_REQUEST`` on a solver
        that cannot reshard warns once instead of silently ignoring the
        surface."""
        path = request_path()
        if not path:
            return None
        try:
            _check_reshardable(solver)
        except ReshardError as e:
            if os.environ.get(REQUEST_ENV, "").strip():
                log(f"WARNING: {REQUEST_ENV} is set but this run cannot "
                    f"reshard live: {e}")
            return None
        return cls(solver, path, log=log)

    # -- request file ----------------------------------------------------
    def _key(self, req: Dict[str, Any]) -> str:
        return json.dumps(req, sort_keys=True)

    def _load(self) -> None:
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            self._requests = []
            return
        if mtime == self._mtime:
            return
        self._mtime = mtime
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            # a torn half-written request file is retried on the next
            # poll (the writer may still be mid-rename); warn once
            if not self._warned_bad:
                self._warned_bad = True
                self.log(f"WARNING: unreadable reshard request "
                         f"{self.path}: {e}")
            self._mtime = None
            return
        self._warned_bad = False
        reqs = doc if isinstance(doc, list) else [doc]
        self._requests = [r for r in reqs if isinstance(r, dict)]

    def _pending(self) -> List[Dict[str, Any]]:
        self._load()
        return [r for r in self._requests if self._key(r) not in self._done]

    # -- train-loop hooks ------------------------------------------------
    def add_targets(self, targets: List[int], cur_iter: int) -> None:
        """Make requested ``at_iter`` boundaries chunk targets, so the
        loop stops exactly there instead of at the next test/snapshot
        cadence."""
        for r in self._pending():
            at = int(r.get("at_iter", 0) or 0)
            if at > cur_iter:
                targets.append(at)

    def poll(self) -> List[Dict[str, Any]]:
        """Fire every pending request whose boundary has arrived;
        returns the migration records."""
        out: List[Dict[str, Any]] = []
        for req in self._pending():
            if int(req.get("at_iter", 0) or 0) > self.solver.iter:
                continue
            self._done.add(self._key(req))
            record = self._fire(req)
            if record is not None:
                out.append(record)
        return out

    def _fire(self, req: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        from . import partition

        target = req.get("layout")
        old_specs = dict(self.solver._plan.specs)
        old_name = _axes_str(self.solver.layout)
        try:
            if not target:
                raise ReshardError(
                    f"reshard request without a 'layout' key: {req}"
                )
            record = reshard(
                self.solver, str(target),
                reason=str(req.get("reason", "request")),
            )
        except (ReshardError, ValueError) as e:
            record = {"error": str(e), "request": req}
            self.log(f"WARNING: reshard request failed: {e}")
        else:
            record["at_iter"] = self.solver.iter
            self.log(f"reshard: {json.dumps(record)}")
            # the aggregated relayout notice, worded for the live path
            self.log(partition.relayout_warning(
                json.dumps(old_specs), self.solver._plan.specs,
                saved_layout=old_name,
                current_layout=_axes_str(self.solver.layout),
                event="reshard",
            ))
        try:
            with open(self.path + ".log", "a") as fh:
                fh.write(json.dumps(record) + "\n")
        except OSError:
            pass
        return None if "error" in record else record
