"""Mixture-of-Experts FFN with expert parallelism over an "ep" axis.

No reference counterpart (SURVEY.md §2: data parallelism only; EP is a
task-spec obligation). Switch-Transformer-style top-1 routing with a
fixed per-expert capacity, expressed as dense one-hot dispatch/combine
einsums — static shapes, MXU-friendly, no sorting/segment ops that
would defeat XLA on TPU.

Under ``shard_map`` over ``ep``, the expert weight stacks shard on
their leading (expert) axis and tokens travel to their expert's owner
via ``lax.all_to_all`` — the TPU analogue of the all-to-all dispatch in
GShard/Switch. Without an axis (``ep_axis=None``) the same code runs
single-device, which doubles as the test oracle.

Capacity-dropped tokens contribute zero from the expert path (the
caller's residual connection carries them through unchanged) — Switch
semantics.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax


def init_moe_params(
    rng: jax.Array,
    hidden: int,
    ffn: int,
    num_experts: int,
    std: float = 0.02,
) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(rng, 3)
    trunc = lambda k, shape: (
        jax.random.truncated_normal(k, -2.0, 2.0, shape, jnp.float32) * std
    )
    return {
        "router_w": trunc(k1, (hidden, num_experts)),
        "w_in": trunc(k2, (num_experts, hidden, ffn)),
        "b_in": jnp.zeros((num_experts, ffn), jnp.float32),
        "w_out": trunc(k3, (num_experts, ffn, hidden)),
        "b_out": jnp.zeros((num_experts, hidden), jnp.float32),
    }


def moe_pspecs(ep_axis: str = "ep"):
    from jax.sharding import PartitionSpec as P

    return {
        "router_w": P(),
        "w_in": P(ep_axis),
        "b_in": P(ep_axis),
        "w_out": P(ep_axis),
        "b_out": P(ep_axis),
    }


def moe_ffn(
    x: jax.Array,
    params: Dict[str, jax.Array],
    *,
    ep_axis: Optional[str] = None,
    capacity_factor: float = 1.25,
    compute_dtype=jnp.float32,
):
    """Top-1 MoE FFN. x: (..., T, h) flattened to tokens internally.

    Returns (out, aux) where ``out`` has x's shape (zero rows for
    capacity-dropped tokens — add the residual outside) and ``aux`` is
    the Switch load-balancing loss (scalar; add to the training loss
    with a small coefficient, e.g. 0.01).
    """
    orig_shape = x.shape
    h = orig_shape[-1]
    xt = x.reshape(-1, h)  # (T, h)
    t = xt.shape[0]
    e_total = params["router_w"].shape[-1]
    nep = lax.psum(1, ep_axis) if ep_axis is not None else 1
    if e_total % nep:
        raise ValueError(f"experts ({e_total}) not divisible by ep ({nep})")

    logits = jnp.dot(
        xt.astype(jnp.float32), params["router_w"],
        preferred_element_type=jnp.float32,
    )  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    cap = max(1, int(math.ceil(t / e_total * capacity_factor)))
    onehot = jax.nn.one_hot(expert, e_total, dtype=jnp.float32)  # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # (T, E), -1 elsewhere
    pos_tok = jnp.sum(pos * onehot, axis=-1)  # (T,)
    keep = (pos_tok < cap) & (pos_tok >= 0)
    # dispatch tensor (T, E, C)
    pos_oh = jax.nn.one_hot(
        jnp.where(keep, pos_tok, cap).astype(jnp.int32), cap, dtype=jnp.float32
    )  # (T, C); overflow rows land outside the one-hot range -> zeros
    dispatch = onehot[:, :, None] * pos_oh[:, None, :]  # (T, E, C)
    combine = dispatch * gate[:, None, None]

    # Switch aux loss: E * sum_e (fraction tokens to e) * (mean prob e)
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    if ep_axis is not None:
        frac = lax.pmean(frac, ep_axis)
        mean_prob = lax.pmean(mean_prob, ep_axis)
    aux = e_total * jnp.sum(frac * mean_prob)

    expert_in = jnp.einsum(
        "tec,th->ech", dispatch, xt.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # (E, C, h)
    if ep_axis is not None:
        # route token groups to the experts' owners: (E, C, h) ->
        # (E/n, n*C, h); the local expert dim now matches w_in's shard
        expert_in = lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True
        )
    cdt = compute_dtype
    y = jax.nn.gelu(
        jnp.einsum(
            "ech,ehf->ecf", expert_in.astype(cdt),
            params["w_in"].astype(cdt),
            preferred_element_type=jnp.float32,
        )
        + params["b_in"][:, None, :],
        approximate=True,
    )
    y = (
        jnp.einsum(
            "ecf,efh->ech", y.astype(cdt), params["w_out"].astype(cdt),
            preferred_element_type=jnp.float32,
        )
        + params["b_out"][:, None, :]
    )
    if ep_axis is not None:
        y = lax.all_to_all(
            y, ep_axis, split_axis=1, concat_axis=0, tiled=True
        )  # back to (E, C, h) token-owner layout
    out = jnp.einsum(
        "tec,ech->th", combine, y.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(orig_shape).astype(x.dtype), aux