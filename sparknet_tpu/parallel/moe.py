"""Mixture-of-Experts FFN with expert parallelism over an "ep" axis.

No reference counterpart (SURVEY.md §2: data parallelism only; EP is a
task-spec obligation). Switch/GShard-style routing with a fixed
per-expert capacity:

- ``top_k=1`` — Switch semantics: gate is the chosen expert's raw
  router probability.
- ``top_k>=2`` — GShard semantics: gates renormalised over the chosen
  experts; first choices win capacity over second choices.
- ``z_loss_weight`` — router z-loss (mean logsumexp² of the router
  logits) folded into the aux scalar, stabilising router magnitudes.

Two dispatch implementations, numerically identical:

- ``dispatch="dense"`` — one-hot dispatch/combine einsums, O(T·E·C)
  memory. Static shapes, MXU-friendly; best at small T·E.
- ``dispatch="sort"`` — argsort tokens by expert, position-in-expert
  via searchsorted, scatter/gather into the (E, C, h) buffer. O(T·h)
  memory; the only viable layout at realistic T and E.

Under ``shard_map`` over ``ep``, the expert weight stacks shard on
their leading (expert) axis and tokens travel to their expert's owner
via ``lax.all_to_all`` — the TPU analogue of the all-to-all dispatch in
GShard/Switch. Without an axis (``ep_axis=None``) the same code runs
single-device, which doubles as the test oracle.

Capacity-dropped tokens contribute zero from the expert path (the
caller's residual connection carries them through unchanged) — Switch
semantics.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.matmul import mxu_bmm


def init_moe_params(
    rng: jax.Array,
    hidden: int,
    ffn: int,
    num_experts: int,
    std: float = 0.02,
) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(rng, 3)
    trunc = lambda k, shape: (
        jax.random.truncated_normal(k, -2.0, 2.0, shape, jnp.float32) * std
    )
    return {
        "router_w": trunc(k1, (hidden, num_experts)),
        "w_in": trunc(k2, (num_experts, hidden, ffn)),
        "b_in": jnp.zeros((num_experts, ffn), jnp.float32),
        "w_out": trunc(k3, (num_experts, ffn, hidden)),
        "b_out": jnp.zeros((num_experts, hidden), jnp.float32),
    }


def moe_pspecs(ep_axis: str = "ep"):
    from jax.sharding import PartitionSpec as P

    return {
        "router_w": P(),
        "w_in": P(ep_axis),
        "b_in": P(ep_axis),
        "w_out": P(ep_axis),
        "b_out": P(ep_axis),
    }


def _route(logits: jax.Array, top_k: int) -> Tuple[jax.Array, jax.Array]:
    """(gates, experts), both (T, K).  Switch gate for k=1, GShard
    renormalised gates for k>=2."""
    probs = jax.nn.softmax(logits, axis=-1)
    top_probs, top_idx = lax.top_k(probs, top_k)
    if top_k == 1:
        gates = top_probs
    else:
        gates = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)
    return gates, top_idx


def _dense_dispatch(xt, expert_s, gate_s, e_total, cap, top_k):
    """One-hot (S, E, C) dispatch/combine tensors; S = T*top_k slots in
    choice-major order (all first choices before all second choices, so
    first choices win capacity)."""
    t = xt.shape[0]
    onehot = jax.nn.one_hot(expert_s, e_total, dtype=jnp.float32)  # (S, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0
    pos_slot = jnp.sum(pos * onehot, axis=-1)  # (S,)
    keep = (pos_slot < cap) & (pos_slot >= 0)
    pos_oh = jax.nn.one_hot(
        jnp.where(keep, pos_slot, cap).astype(jnp.int32), cap,
        dtype=jnp.float32,
    )  # (S, C); dropped slots land outside the one-hot range -> zeros
    dispatch = onehot[:, :, None] * pos_oh[:, None, :]  # (S, E, C)
    combine = dispatch * gate_s[:, None, None]
    xs = jnp.tile(xt, (top_k, 1))  # slot s holds token s % T
    expert_in = jnp.einsum(
        "sec,sh->ech", dispatch, xs.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # (E, C, h)

    def combine_fn(y):  # y: (E, C, h) -> (T, h)
        out_slots = jnp.einsum(
            "sec,ech->sh", combine, y.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return jnp.sum(out_slots.reshape(top_k, t, -1), axis=0)

    return expert_in, combine_fn


def _sort_dispatch(xt, expert_s, gate_s, e_total, cap, top_k):
    """Sort-based dispatch: O(S log S) routing + O(E*C*h) buffer instead
    of the dense O(S*E*C) tensors.  Same slot priority as the dense
    path (stable sort over choice-major slots)."""
    t, h = xt.shape
    s = t * top_k
    order = jnp.argsort(expert_s, stable=True)  # (S,) slot ids by expert
    sorted_e = expert_s[order]
    starts = jnp.searchsorted(sorted_e, jnp.arange(e_total), side="left")
    pos_sorted = jnp.arange(s) - starts[sorted_e]  # position within expert
    keep = pos_sorted < cap
    dest = jnp.where(keep, sorted_e * cap + pos_sorted, e_total * cap)
    tok_sorted = order % t  # slot -> owning token (choice-major layout)
    buf = jnp.zeros((e_total * cap + 1, h), jnp.float32)
    buf = buf.at[dest].add(xt[tok_sorted].astype(jnp.float32))
    expert_in = buf[:-1].reshape(e_total, cap, h)

    def combine_fn(y):  # y: (E, C, h) -> (T, h)
        y_flat = jnp.concatenate(
            [y.reshape(e_total * cap, h), jnp.zeros((1, h), y.dtype)]
        )
        out_slots = y_flat[dest].astype(jnp.float32) * gate_s[order][:, None]
        return (
            jnp.zeros((t, h), jnp.float32).at[tok_sorted].add(out_slots)
        )

    return expert_in, combine_fn


def moe_ffn(
    x: jax.Array,
    params: Dict[str, jax.Array],
    *,
    ep_axis: Optional[str] = None,
    capacity_factor: float = 1.25,
    top_k: int = 1,
    z_loss_weight: float = 0.0,
    dispatch: str = "dense",
    compute_dtype=jnp.float32,
):
    """MoE FFN. x: (..., T, h) flattened to tokens internally.

    Returns (out, aux) where ``out`` has x's shape (zero rows for
    capacity-dropped tokens — add the residual outside) and ``aux`` is
    the Switch load-balancing loss plus ``z_loss_weight`` times the
    router z-loss (scalar; add to the training loss with a small
    coefficient, e.g. 0.01).
    """
    if dispatch not in ("dense", "sort"):
        raise ValueError(f"dispatch {dispatch!r} (want 'dense' or 'sort')")
    orig_shape = x.shape
    h = orig_shape[-1]
    xt = x.reshape(-1, h)  # (T, h)
    t = xt.shape[0]
    e_total = params["router_w"].shape[-1]
    nep = lax.psum(1, ep_axis) if ep_axis is not None else 1
    if e_total % nep:
        raise ValueError(f"experts ({e_total}) not divisible by ep ({nep})")
    if top_k > e_total:
        raise ValueError(f"top_k ({top_k}) > experts ({e_total})")

    logits = jnp.dot(
        xt.astype(jnp.float32), params["router_w"],
        preferred_element_type=jnp.float32,
    )  # (T, E)
    gates, top_idx = _route(logits, top_k)
    # choice-major slots: all first choices, then all second choices
    expert_s = top_idx.T.reshape(-1)  # (S,)
    gate_s = gates.T.reshape(-1)

    cap = max(1, int(math.ceil(t * top_k / e_total * capacity_factor)))
    dispatch_fn = _dense_dispatch if dispatch == "dense" else _sort_dispatch
    expert_in, combine_fn = dispatch_fn(
        xt, expert_s, gate_s, e_total, cap, top_k
    )

    # Switch aux loss over first-choice assignment:
    # E * sum_e (fraction tokens to e) * (mean prob e)
    probs = jax.nn.softmax(logits, axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(top_idx[:, 0], e_total, dtype=jnp.float32), axis=0
    )
    mean_prob = jnp.mean(probs, axis=0)
    z = jax.scipy.special.logsumexp(logits, axis=-1)
    z_loss = jnp.mean(jnp.square(z))
    if ep_axis is not None:
        frac = lax.pmean(frac, ep_axis)
        mean_prob = lax.pmean(mean_prob, ep_axis)
        z_loss = lax.pmean(z_loss, ep_axis)
    aux = e_total * jnp.sum(frac * mean_prob) + z_loss_weight * z_loss

    if ep_axis is not None:
        # route token groups to the experts' owners: (E, C, h) ->
        # (E/n, n*C, h); the local expert dim now matches w_in's shard
        expert_in = lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True
        )
    cdt = compute_dtype
    # mxu_bmm: per-expert (E, C, h) @ (E, h, f) at bf16 MXU rate in
    # both directions with f32 accumulation (see ops/matmul.py) — these
    # are the largest matmuls in an expert-parallel step
    y = jax.nn.gelu(
        mxu_bmm(expert_in.astype(cdt), params["w_in"].astype(cdt))
        + params["b_in"][:, None, :],
        approximate=True,
    )
    y = (
        mxu_bmm(y.astype(cdt), params["w_out"].astype(cdt))
        + params["b_out"][:, None, :]
    )
    if ep_axis is not None:
        y = lax.all_to_all(
            y, ep_axis, split_axis=1, concat_axis=0, tiled=True
        )  # back to (E, C, h) token-owner layout
    out = combine_fn(y)
    return out.reshape(orig_shape).astype(x.dtype), aux
