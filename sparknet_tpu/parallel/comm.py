"""Communication-efficiency layer: bucketed collectives + compression.

SparkNet's contribution (PAPER.md) is trading gradient staleness for a
τ-fold cut in communication *rounds*; FireCaffe (PAPERS.md,
arXiv:1511.00175) attacks the cost of each round itself — reduction
trees, overlap with backward work, fewer bytes on the wire.  This
module is the one home of that second lever:

- **Bucketing.**  :func:`plan_buckets` groups a gradient/weight pytree
  into size-bounded buckets in *reverse* flatten order (output-side
  layers first — the order backward produces gradients), so the
  reduction becomes several medium-sized collectives instead of one
  monolithic all-reduce or thousands of per-leaf ones.
- **Overlap.**  :func:`overlap_reduce_on_backward` attaches each
  bucket's ``pmean`` to the *backward pass itself* (a per-bucket
  ``custom_vjp`` identity whose cotangent rule reduces): a bucket's
  all-reduce is issued the moment its layers' gradients exist, so XLA's
  scheduler can overlap it with the remaining backward work.
- **Compression.**  :func:`reduce_bucketed` optionally casts each
  bucket to bf16 or quantizes it to int8 (shared per-bucket scale from
  a ``pmax``) before the reduce, with **error-feedback residuals**: the
  quantization error is returned to the caller, persisted in opt state,
  and re-injected into the next round's payload instead of being lost.

Everything here runs *inside* the compiled step (under ``shard_map``);
the host-side knobs are ``SPARKNET_COMM`` (``bucketed``/``monolithic``),
``SPARKNET_GRAD_COMPRESS`` (``none``/``bf16``/``int8``, also the apps'
``--grad-compress``) and ``SPARKNET_COMM_BUCKET_MB``.  See
docs/COMMUNICATION.md.

This module also owns the jax compat shims for the manual-sharding API
(``shard_map`` moved from ``jax.experimental`` to ``jax.``;
``lax.pcast`` is newer still): the parallel modes route through them so
one source runs on every jax this framework meets.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

COMM_ENV = "SPARKNET_COMM"
COMPRESS_ENV = "SPARKNET_GRAD_COMPRESS"
BUCKET_MB_ENV = "SPARKNET_COMM_BUCKET_MB"

COMM_MODES = ("auto", "bucketed", "monolithic")
COMPRESS_MODES = ("none", "bf16", "int8")

# int8 payloads are accumulated in int16 on the wire: with the shared
# per-bucket scale each element is in [-127, 127], so up to 256 workers
# sum without overflow (a dp axis wider than that would need int32).
_INT8_ACC_DTYPE = jnp.int16
_INT8_MAX_WORKERS = 256


# --------------------------------------------------------------------------
# jax compat: the manual-sharding API across jax versions
# --------------------------------------------------------------------------

def shard_map(f: Callable, *, mesh, in_specs, out_specs) -> Callable:
    """``jax.shard_map`` when it exists, else the ``jax.experimental``
    spelling — with replication/vma checking off in both (the comm
    programs mix invariant params with per-bucket collectives through a
    ``custom_vjp``, which the checkers cannot see through)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        for kw in ({"check_vma": False}, {"check_rep": False}, {}):
            try:
                return sm(
                    f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **kw,
                )
            except TypeError:
                continue
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def jit_manual(fn: Callable, **jit_kw) -> Callable:
    """``jax.jit`` for manual-sharding (shard_map) programs.

    On current jax this IS ``jax.jit``.  On the old-API fallback these
    programs must never land in the persistent compilation cache: that
    jaxlib segfaults DESERIALIZING cached executables carrying
    manual-collective thunks (same serialization bug family
    tests/conftest.py works around via the min-compile-time floor —
    these programs compile in whole seconds, so the floor can't exclude
    them).  Neither the cache-dir config nor the enable flag can be
    toggled per program (``is_cache_used`` latches once per process),
    but ``_cache_write`` consults the min-compile-time config LIVE — so
    the wrapper raises it past any real compile around every call.
    Never written means never read back, and a cache MISS is harmless;
    the in-memory jit cache still applies, so only the first call per
    shape pays a real compile."""
    jfn = jax.jit(fn, **jit_kw)
    if getattr(jax, "shard_map", None) is not None:
        return jfn

    knob = "jax_persistent_cache_min_compile_time_secs"

    def call(*a, **k):
        prev = getattr(jax.config, knob, None)
        if prev is None:
            return jfn(*a, **k)
        jax.config.update(knob, 1e9)
        try:
            return jfn(*a, **k)
        finally:
            jax.config.update(knob, prev)

    return call


def pcast_varying(tree: Any, axis_name: str) -> Any:
    """Mark a replicated tree device-varying for shard_map's typing
    (newer jax); a no-op where ``lax.pcast`` does not exist (older jax
    has no varying type to satisfy)."""
    pc = getattr(lax, "pcast", None)
    if pc is None:
        return tree
    return jax.tree_util.tree_map(
        lambda x: pc(x, axis_name, to="varying"), tree
    )


# --------------------------------------------------------------------------
# config
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CommConfig:
    """Resolved communication settings for one solver.

    ``mode="monolithic"`` is the pre-bucketing behavior (one fused
    all-reduce of the whole tree) and the A/B baseline; ``"bucketed"``
    routes through :func:`plan_buckets`/:func:`reduce_bucketed`.
    ``"auto"`` (the default) resolves per training mode — see
    :meth:`for_local` / :meth:`for_sync`.  ``compress`` only applies to
    bucketed reductions."""

    mode: str = "auto"
    compress: str = "none"
    bucket_mb: float = 4.0

    def __post_init__(self):
        if self.mode not in COMM_MODES:
            raise ValueError(
                f"comm mode {self.mode!r} (want {'|'.join(COMM_MODES)})"
            )
        if self.compress not in COMPRESS_MODES:
            raise ValueError(
                f"grad compression {self.compress!r} "
                f"(want {'|'.join(COMPRESS_MODES)})"
            )
        if self.compress != "none" and self.mode == "monolithic":
            raise ValueError(
                "grad compression requires the bucketed comm path "
                f"({COMM_ENV}=bucketed); monolithic has no place to "
                "quantize"
            )
        if self.bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {self.bucket_mb}")

    @property
    def bucket_bytes(self) -> int:
        return int(self.bucket_mb * 1e6)

    def for_local(self) -> str:
        """τ-local SGD rounds default to the bucketed path: the
        lossless bucketed average is bitwise-identical to the
        monolithic one (pinned by test), so bucketing is pure upside
        there."""
        return "bucketed" if self.mode == "auto" else self.mode

    def for_sync(self) -> str:
        """Sync DP defaults to the implicit path (XLA places one fused
        all-reduce from the shardings — the long-standing behavior)
        unless compression forces the explicit bucketed program, or the
        caller asked for it."""
        if self.mode == "auto":
            return "bucketed" if self.compress != "none" else "monolithic"
        return self.mode

    @property
    def wants_residual(self) -> bool:
        """Lossy compression carries an error-feedback residual in opt
        state; ``none`` must leave the opt-state layout untouched so
        pre-change snapshots stay bit-compatible."""
        return self.compress in ("bf16", "int8")


def resolve_config(
    compress: Optional[str] = None,
    mode: Optional[str] = None,
    bucket_mb: Optional[float] = None,
) -> CommConfig:
    """Explicit args win; the environment fills the rest
    (``SPARKNET_COMM`` / ``SPARKNET_GRAD_COMPRESS`` /
    ``SPARKNET_COMM_BUCKET_MB``)."""
    mode = mode or os.environ.get(COMM_ENV, "").strip() or "auto"
    compress = compress or os.environ.get(COMPRESS_ENV, "").strip() or "none"
    if bucket_mb is None:
        raw = os.environ.get(BUCKET_MB_ENV, "").strip()
        try:
            bucket_mb = float(raw) if raw else 4.0
        except ValueError:
            raise ValueError(
                f"{BUCKET_MB_ENV} must be a float MB count, got {raw!r}"
            ) from None
    return CommConfig(mode=mode, compress=compress, bucket_mb=bucket_mb)


# --------------------------------------------------------------------------
# bucket planning
# --------------------------------------------------------------------------

def plan_buckets(
    leaves: Sequence[Any], bucket_bytes: int
) -> Tuple[Tuple[int, ...], ...]:
    """Greedy size-bounded grouping of flattened leaves, in REVERSE
    flatten order.

    Backward produces gradients output-side-first, so reverse flatten
    order (the param tree flattens input→output) approximates the order
    buckets become ready — the first bucket's reduce can be issued
    while earlier layers are still differentiating.  A leaf larger than
    the bound gets its own bucket; dtypes never mix inside a bucket
    (the payload is one concatenated buffer)."""
    plan: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i in reversed(range(len(leaves))):
        leaf = leaves[i]
        nbytes = int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        if cur and (
            cur_bytes + nbytes > bucket_bytes
            or jnp.dtype(leaf.dtype) != cur_dtype
        ):
            plan.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = jnp.dtype(leaf.dtype)
    if cur:
        plan.append(tuple(cur))
    return tuple(plan)


def bucket_histogram(
    plan: Sequence[Sequence[int]], leaves: Sequence[Any]
) -> dict:
    """Bucket-size distribution for bench records: how well the bound
    packs this model's tree."""
    sizes = [
        sum(int(leaves[i].size) * jnp.dtype(leaves[i].dtype).itemsize
            for i in bucket)
        for bucket in plan
    ]
    if not sizes:
        return {"buckets": 0}
    return {
        "buckets": len(sizes),
        "leaves": sum(len(b) for b in plan),
        "min_bytes": min(sizes),
        "max_bytes": max(sizes),
        "mean_bytes": int(sum(sizes) / len(sizes)),
        "total_bytes": sum(sizes),
        "bytes": sizes,
    }


def wire_bytes(
    plan: Sequence[Sequence[int]],
    leaves: Sequence[Any],
    compress: str = "none",
) -> int:
    """Estimated payload bytes ONE worker contributes to one reduction
    (per ring hop; multiply by the topology factor for totals):
    ``none`` moves the native dtype, ``bf16`` two bytes/element,
    ``int8`` the int16 accumulation type plus a float32 scale per
    bucket.  An estimate of the algorithm's traffic, not a measurement
    of XLA's wire format."""
    total = 0
    for bucket in plan:
        n = sum(int(leaves[i].size) for i in bucket)
        if compress == "bf16":
            total += 2 * n
        elif compress == "int8":
            total += jnp.dtype(_INT8_ACC_DTYPE).itemsize * n + 4
        else:
            total += sum(
                int(leaves[i].size) * jnp.dtype(leaves[i].dtype).itemsize
                for i in bucket
            )
    return total


# --------------------------------------------------------------------------
# bucket payload packing
# --------------------------------------------------------------------------

def _concat_bucket(leaves: Sequence[Any], bucket: Sequence[int]):
    if len(bucket) == 1:
        return leaves[bucket[0]].reshape(-1)
    return jnp.concatenate([leaves[i].reshape(-1) for i in bucket])


def _split_bucket(flat, leaves: Sequence[Any], bucket: Sequence[int], out):
    off = 0
    for i in bucket:
        n = int(leaves[i].size)
        out[i] = flat[off:off + n].reshape(leaves[i].shape)
        off += n


# --------------------------------------------------------------------------
# in-step reduction (call inside shard_map)
# --------------------------------------------------------------------------

def _reduce_payload(flat, axis_name: str, compress: str, axis_size: int):
    """One bucket's mean-reduce over ``axis_name`` with the configured
    wire format; returns ``(reduced_f32like, dequantized_local)`` where
    the second term is what THIS worker's peers received from it (for
    the error-feedback residual; equals ``flat`` when lossless)."""
    if compress == "bf16":
        # bf16 on the wire, float32 accumulation: reducing IN bf16
        # would add summation error the error-feedback residual cannot
        # see (it only measures local quantization), leaving a
        # persistent bias — with a wide accumulator EF converges
        q = flat.astype(jnp.bfloat16)
        red = lax.pmean(q.astype(flat.dtype), axis_name)
        return red, q.astype(flat.dtype)
    if compress == "int8":
        if axis_size > _INT8_MAX_WORKERS:
            raise ValueError(
                f"int8 gradient compression accumulates in int16 and "
                f"supports at most {_INT8_MAX_WORKERS} workers, got "
                f"{axis_size}"
            )
        # shared scale: every worker quantizes against the same bound,
        # so the summed int payloads dequantize with one multiply
        absmax = lax.pmax(jnp.max(jnp.abs(flat)), axis_name)
        scale = jnp.maximum(absmax, 1e-30) / 127.0
        q = jnp.clip(jnp.round(flat / scale), -127.0, 127.0)
        acc = lax.psum(q.astype(_INT8_ACC_DTYPE), axis_name)
        red = (acc.astype(flat.dtype) * scale) / float(axis_size)
        return red, q.astype(flat.dtype) * scale
    return lax.pmean(flat, axis_name), flat


def reduce_bucketed(
    tree: Any,
    axis_name: str,
    axis_size: int,
    config: CommConfig,
    residual: Optional[Any] = None,
):
    """Mean-reduce a pytree over ``axis_name``, bucket by bucket, with
    the configured compression.  Call inside ``shard_map``.

    Returns ``(reduced_tree, new_residual)``.  With a lossy ``compress``
    the caller passes last round's residual tree (zeros to start): the
    payload becomes ``value + residual`` and the new residual is the
    part quantization dropped — re-injected next round, so compression
    error accumulates to zero instead of biasing training.  With
    ``compress="none"`` the residual is passed through untouched
    (``None`` in, ``None`` out) and the math is exactly the per-leaf
    ``pmean`` it replaces, one concatenated buffer at a time."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree, residual
    plan = plan_buckets(leaves, config.bucket_bytes)
    out: List[Any] = [None] * len(leaves)
    if not config.wants_residual:
        for bucket in plan:
            flat = _concat_bucket(leaves, bucket)
            red, _ = _reduce_payload(flat, axis_name, "none", axis_size)
            _split_bucket(red, leaves, bucket, out)
        return jax.tree_util.tree_unflatten(treedef, out), residual
    res_leaves = jax.tree_util.tree_leaves(residual)
    if len(res_leaves) != len(leaves):
        raise ValueError(
            f"error-feedback residual has {len(res_leaves)} leaves, "
            f"tree has {len(leaves)} — opt state out of sync with "
            f"--grad-compress (see docs/COMMUNICATION.md)"
        )
    new_res: List[Any] = [None] * len(leaves)
    for bucket in plan:
        flat = _concat_bucket(leaves, bucket)
        res = _concat_bucket(res_leaves, bucket).astype(flat.dtype)
        payload = flat + res
        red, sent = _reduce_payload(
            payload, axis_name, config.compress, axis_size
        )
        _split_bucket(red, leaves, bucket, out)
        # residuals stay float32 regardless of the payload dtype, so
        # the opt-state layout (and jit signature) is round-stable
        _split_bucket(
            (payload - sent).astype(jnp.float32), leaves, bucket, new_res
        )
    return (
        jax.tree_util.tree_unflatten(treedef, out),
        jax.tree_util.tree_unflatten(treedef, new_res),
    )


def init_residual(tree: Any) -> Any:
    """Zero error-feedback residuals shaped like ``tree`` (one per
    communicated leaf), float32 — quantization error is small and must
    accumulate without itself rounding away."""
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, jnp.float32), tree
    )


# --------------------------------------------------------------------------
# overlapped in-backward reduction (sync DP)
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _pmean_on_backward(axis_name: str, leaves: Tuple[Any, ...]):
    """Identity forward; the backward rule mean-reduces the bucket's
    cotangents over ``axis_name`` as ONE concatenated buffer.  Because
    autodiff emits a bucket's rule the moment its last cotangent
    exists, each bucket's all-reduce enters the program mid-backward —
    the overlap FireCaffe gets from interleaving reduction trees with
    remaining backprop work."""
    return leaves


def _pmean_on_backward_fwd(axis_name, leaves):
    return leaves, None


def _pmean_on_backward_bwd(axis_name, _, g):
    g = tuple(g)
    bucket = tuple(range(len(g)))
    flat = _concat_bucket(g, bucket)
    red = lax.pmean(flat, axis_name)
    out: List[Any] = [None] * len(g)
    _split_bucket(red, g, bucket, out)
    return (tuple(out),)


_pmean_on_backward.defvjp(_pmean_on_backward_fwd, _pmean_on_backward_bwd)


def overlap_reduce_on_backward(
    params: Any, axis_name: str, config: CommConfig
) -> Any:
    """Wrap a params pytree so its gradients come back bucket-mean-
    reduced over ``axis_name``, each bucket's collective issued inside
    the backward pass.  Use on the loss function's input params, inside
    ``shard_map``; lossless only (lossy compression needs the residual
    state that :func:`reduce_bucketed` threads)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        return params
    plan = plan_buckets(leaves, config.bucket_bytes)
    out = list(leaves)
    for bucket in plan:
        synced = _pmean_on_backward(
            axis_name, tuple(leaves[i] for i in bucket)
        )
        for j, i in enumerate(bucket):
            out[i] = synced[j]
    return jax.tree_util.tree_unflatten(treedef, out)


# --------------------------------------------------------------------------
# host-side accounting
# --------------------------------------------------------------------------

def count_reduction(config: CommConfig, tree: Any, path: str) -> int:
    """Record one reduction's estimated traffic in the telemetry
    registry (``comm_bytes{path=...}`` counter + a bucket gauge);
    returns the byte estimate.  Host-side, once per compiled-program
    build or round — never in the per-step hot path."""
    from ..telemetry import REGISTRY

    leaves = jax.tree_util.tree_leaves(tree)
    if config.mode == "bucketed":
        plan = plan_buckets(leaves, config.bucket_bytes)
    else:
        plan = (tuple(range(len(leaves))),) if leaves else ()
    est = wire_bytes(plan, leaves, config.compress)
    REGISTRY.counter("comm_bytes", path=path).inc(est)
    REGISTRY.gauge("comm_buckets", path=path).set(len(plan))
    return est
