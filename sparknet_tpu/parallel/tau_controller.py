"""Adaptive τ for local SGD — the paper's knob, closed-loop.

SparkNet (PAPER.md) leaves τ a hand-set constant and derives the
tradeoff analytically: more local steps amortize communication but add
staleness.  PR 5's telemetry made both sides of that tradeoff
measurable per round — the ``multihost_sync``/``grad_allreduce`` share
of step time on one side, the loss trajectory on the other — so τ can
be a control loop instead of a guess (``--tau auto`` on the apps):

- **Widen** (τ ← 2τ, up to ``tau_max``) when the round is *sync-bound*:
  the communication phases exceed ``widen_share`` of round wall time.
  More local steps per sync directly shrink that share.
- **Narrow** (τ ← τ/2, down to ``tau_min``) when the loss *diverges*
  between sync points: a round's τ-mean loss rising more than
  ``narrow_divergence`` above its smoothed trajectory means staleness
  is eating the communication win — sync more often.

τ moves by doubling/halving only, so a run compiles at most
``log2(tau_max/tau_min)`` distinct round programs (the round fns are
cached per τ).  Every decision lands in the telemetry registry
(``tau_controller`` gauges) and the decision log, which the apps write
as a machine-readable run record next to the snapshots
(``<prefix>_tau_controller.json``, same discipline as
``supervisor_report.json``).  Unit-testable from synthetic telemetry
snapshots — no mesh required (tests/test_comm.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None


class TauController:
    """Host-side τ control loop for :class:`ParallelSolver` local mode.

    Call :meth:`observe_round` once per completed round with that
    round's wall seconds, its communication-phase seconds (exposed
    ``grad_allreduce`` + ``multihost_sync``, from the timeline), and
    the round's mean loss; it returns the τ to use for the NEXT round.
    """

    def __init__(
        self,
        tau: int = 8,
        tau_min: Optional[int] = None,
        tau_max: Optional[int] = None,
        widen_share: float = 0.25,
        narrow_divergence: float = 0.10,
        loss_smoothing: float = 0.7,
        cooldown_rounds: int = 2,
    ):
        self.tau_min = tau_min if tau_min is not None else _env_int(
            "SPARKNET_TAU_MIN", 1
        )
        self.tau_max = tau_max if tau_max is not None else _env_int(
            "SPARKNET_TAU_MAX", 64
        )
        if not (1 <= self.tau_min <= self.tau_max):
            raise ValueError(
                f"need 1 <= tau_min <= tau_max, got "
                f"[{self.tau_min}, {self.tau_max}]"
            )
        self.tau = int(min(max(tau, self.tau_min), self.tau_max))
        self.widen_share = widen_share
        self.narrow_divergence = narrow_divergence
        self.loss_smoothing = loss_smoothing
        # decisions need a few rounds of signal at the NEW tau before
        # moving again — without a cooldown one noisy loss round can
        # saw the controller between two values forever
        self.cooldown_rounds = max(0, cooldown_rounds)
        self._cooldown = 0
        self._loss_ema: Optional[float] = None
        self._round = 0
        # layout advisory (ISSUE 14): a job that stays sync-bound at
        # tau_max has exhausted the τ lever — after this many
        # consecutive such rounds the controller raises a `layout`
        # advisory pointing at live resharding (parallel/reshard.py)
        self.layout_advisory_rounds = max(
            1, _env_int("SPARKNET_TAU_LAYOUT_ADVISORY_ROUNDS", 2)
        )
        self._syncbound_at_max = 0
        self.decisions: List[Dict[str, Any]] = []
        from ..telemetry import REGISTRY

        self._g_tau = REGISTRY.gauge("tau_controller", signal="tau")
        self._g_share = REGISTRY.gauge(
            "tau_controller", signal="sync_share_pct"
        )
        self._g_div = REGISTRY.gauge(
            "tau_controller", signal="divergence_pct"
        )
        self._g_tau.set(self.tau)

    # ------------------------------------------------------------------
    def observe_round(
        self, *, round_s: float, sync_s: float, loss: float,
        advisories=None,
    ) -> int:
        """Digest one round's telemetry; returns the next round's τ.

        ``advisories`` is the anomaly board's consumable hook
        (``telemetry.anomaly.active("straggler")``): while a straggler
        advisory is live, every sync waits on the slow rank, so the
        widen threshold halves — amortizing the straggler's barrier
        cost is exactly what more local steps buy (FireCaffe's
        slowest-participant observation, closed-loop)."""
        self._round += 1
        share = (sync_s / round_s) if round_s > 0 else 0.0
        straggler = any(
            a.get("kind") == "straggler" for a in (advisories or ())
        )
        widen_share = self.widen_share * (0.5 if straggler else 1.0)
        if self._loss_ema is None:
            self._loss_ema = loss
        divergence = (
            (loss - self._loss_ema) / max(abs(self._loss_ema), 1e-12)
        )
        prev_tau, action, why = self.tau, "hold", ""
        if self._cooldown > 0:
            self._cooldown -= 1
            why = "cooldown"
        elif divergence > self.narrow_divergence and self.tau > self.tau_min:
            # staleness is winning: halve toward fresher syncs
            self.tau = max(self.tau_min, self.tau // 2)
            action, why = "narrow", (
                f"divergence {divergence:.1%} > {self.narrow_divergence:.0%}"
            )
            self._cooldown = self.cooldown_rounds
        elif share > widen_share and self.tau < self.tau_max:
            # sync-bound: double the local work each round amortizes
            self.tau = min(self.tau_max, self.tau * 2)
            action, why = "widen", (
                f"sync share {share:.1%} > {widen_share:.0%}"
                + (" (straggler advisory active)" if straggler else "")
            )
            self._cooldown = self.cooldown_rounds
        # sync-bound with τ pinned at tau_max: widening is no longer an
        # option, so the remaining lever is the LAYOUT.  After
        # `layout_advisory_rounds` consecutive such rounds, raise a
        # `layout` advisory (same board as straggler) naming live
        # resharding.  Single-process only — the caller passes
        # ``advisories=None`` under multi-host (τ and any layout move
        # must stay rank-identical, same caveat as straggler
        # consumption), which also gates the raise.
        layout_advisory = False
        if share > widen_share and self.tau >= self.tau_max:
            self._syncbound_at_max += 1
            if (
                self._syncbound_at_max >= self.layout_advisory_rounds
                and advisories is not None
            ):
                layout_advisory = True
                from ..telemetry import anomaly as _anomaly

                _anomaly.fire(
                    "layout",
                    key="tau_max",
                    tau=self.tau,
                    sync_share=round(share, 4),
                    rounds=self._syncbound_at_max,
                    suggestion=(
                        "sync-bound at SPARKNET_TAU_MAX — τ cannot "
                        "widen further; consider a live reshard to a "
                        "different layout table entry "
                        "(parallel/reshard.py, docs/PARALLELISM.md)"
                    ),
                )
        elif share <= widen_share:
            self._syncbound_at_max = 0
        # EMA after the divergence test: the test compares THIS round
        # against the trajectory before it
        self._loss_ema = (
            self.loss_smoothing * self._loss_ema
            + (1.0 - self.loss_smoothing) * loss
        )
        self._g_tau.set(self.tau)
        self._g_share.set(round(100.0 * share, 2))
        self._g_div.set(round(100.0 * divergence, 2))
        decision = {
            "round": self._round,
            "tau": prev_tau,
            "next_tau": self.tau,
            "action": action,
            "reason": why,
            "sync_share": round(share, 4),
            "divergence": round(divergence, 4),
            "round_s": round(round_s, 5),
            "sync_s": round(sync_s, 5),
            "loss": round(float(loss), 6),
        }
        if straggler:
            decision["straggler_advisory"] = True
        if layout_advisory:
            decision["layout_advisory"] = True
        self.decisions.append(decision)
        return self.tau

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The machine-readable run record (the ``tau:`` log line and
        ``<prefix>_tau_controller.json``)."""
        taus = [d["next_tau"] for d in self.decisions]
        return {
            "tau": self.tau,
            "tau_min": self.tau_min,
            "tau_max": self.tau_max,
            "rounds": self._round,
            "widened": sum(1 for d in self.decisions if d["action"] == "widen"),
            "narrowed": sum(
                1 for d in self.decisions if d["action"] == "narrow"
            ),
            "layout_advisories": sum(
                1 for d in self.decisions if d.get("layout_advisory")
            ),
            "tau_trajectory": taus,
            "decisions": self.decisions,
        }

    def json_line(self) -> str:
        return json.dumps(self.snapshot())

    def write_report(self, snapshot_prefix: str) -> Optional[str]:
        """Persist the decision record next to the run's snapshots
        (``<prefix>_tau_controller.json``); returns the path, or None
        when there is no prefix to anchor it to."""
        if not snapshot_prefix:
            return None
        path = f"{snapshot_prefix}_tau_controller.json"
        # best-effort (safeio): the decision record is observability,
        # not state — a full disk must not fail the training run
        from ..utils import safeio

        if not safeio.best_effort_write_json(
            path, self.snapshot(), site="records", fsync=False
        ):
            return None
        return path


def parse_tau(value) -> tuple:
    """App-side ``--tau`` parsing: an int, or ``auto`` for the
    controller.  Returns ``(tau_int_or_initial, auto: bool)``."""
    if isinstance(value, int):
        return value, False
    s = str(value).strip().lower()
    if s == "auto":
        return _env_int("SPARKNET_TAU_INITIAL", 8), True
    try:
        return int(s), False
    except ValueError:
        raise ValueError(
            f"--tau must be an integer or 'auto', got {value!r}"
        ) from None
