"""Distribution layer: mesh construction, synchronous data parallelism,
SparkNet's τ-local SGD, and (see sibling modules) sequence/tensor
parallelism — all expressed as jax.sharding + collectives over ICI."""

from .mesh import (
    DP_AXIS,
    PP_AXIS,
    SP_AXIS,
    TP_AXIS,
    batch_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
)
from .comm import CommConfig, resolve_config
from .data_parallel import (
    make_bucketed_dp_train_step,
    make_dp_eval_step,
    make_dp_train_step,
)
from .local_sgd import (
    RoundBuffer,
    init_local_opt_state,
    make_local_scan,
    make_local_sgd_round,
    make_round_reduce,
    round_batch_sharding,
    stack_round_batches,
)
from .partition import (
    Layout,
    Rule,
    RULESETS,
    layout_from_json,
    layout_to_json,
    make_plan,
    make_sharded_eval_step,
    make_sharded_train_step,
    parse_layout,
)
from .tau_controller import TauController
from .trainer import ParallelSolver
from . import comm, multihost, partition
from . import reshard

__all__ = [
    "comm",
    "multihost",
    "partition",
    "reshard",
    "Layout",
    "Rule",
    "RULESETS",
    "layout_from_json",
    "layout_to_json",
    "make_plan",
    "make_sharded_eval_step",
    "make_sharded_train_step",
    "parse_layout",
    "DP_AXIS",
    "PP_AXIS",
    "SP_AXIS",
    "TP_AXIS",
    "CommConfig",
    "ParallelSolver",
    "RoundBuffer",
    "TauController",
    "batch_sharding",
    "init_local_opt_state",
    "make_bucketed_dp_train_step",
    "make_dp_eval_step",
    "make_dp_train_step",
    "make_local_scan",
    "make_local_sgd_round",
    "make_round_reduce",
    "make_mesh",
    "replicate",
    "replicated",
    "resolve_config",
    "round_batch_sharding",
    "shard_batch",
    "stack_round_batches",
]
