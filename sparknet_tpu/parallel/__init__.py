"""Distribution layer: mesh construction, synchronous data parallelism,
SparkNet's τ-local SGD, and (see sibling modules) sequence/tensor
parallelism — all expressed as jax.sharding + collectives over ICI."""

from .mesh import (
    DP_AXIS,
    PP_AXIS,
    SP_AXIS,
    TP_AXIS,
    batch_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
)
from .data_parallel import make_dp_eval_step, make_dp_train_step
from .local_sgd import (
    init_local_opt_state,
    make_local_sgd_round,
    round_batch_sharding,
    stack_round_batches,
)
from .trainer import ParallelSolver
from . import multihost

__all__ = [
    "multihost",
    "DP_AXIS",
    "PP_AXIS",
    "SP_AXIS",
    "TP_AXIS",
    "ParallelSolver",
    "batch_sharding",
    "init_local_opt_state",
    "make_dp_eval_step",
    "make_dp_train_step",
    "make_local_sgd_round",
    "make_mesh",
    "replicate",
    "replicated",
    "round_batch_sharding",
    "shard_batch",
    "stack_round_batches",
]
